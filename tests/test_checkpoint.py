"""Checkpoint/restart round-trip: a run interrupted at a host sync and
resumed from the .npz must finish bit-identical to an uninterrupted run
(the subsystem the reference lacks, SURVEY.md §5). PR 4 durability edges:
per-field CRC32 + schema version, live->.prev rotation, torn-.tmp crash
safety, corrupt-primary fallback, and the restart-under-telemetry arity
contract."""

import os

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import checkpoint as ckpt
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter, read_parameter


def _param(te):
    return Parameter(
        name="dcavity", imax=32, jmax=32, re=10.0, te=te, tau=0.5,
        itermax=100, eps=1e-3, omg=1.8, gamma=0.9, tpu_dtype="float64",
    )


def test_roundtrip_bitwise(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    # interrupted: checkpoint at EVERY host sync, stop partway by using a
    # shorter te, then restore into a fresh solver and continue to te
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    second = NS2DSolver(_param(te=0.5))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))
    np.testing.assert_array_equal(np.asarray(ref.v), np.asarray(second.v))


def test_shape_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    other = NS2DSolver(
        Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.1,
                  tpu_dtype="float64")
    )
    with pytest.raises(ValueError, match="checkpoint grid"):
        ckpt.load_checkpoint(path, other)


def test_par_keys_parsed(tmp_path):
    par = tmp_path / "r.par"
    par.write_text(
        "name dcavity\ntpu_checkpoint ck.npz\ntpu_ckpt_every 3\n"
        "tpu_restart old.npz\n"
    )
    p = read_parameter(str(par))
    assert p.tpu_checkpoint == "ck.npz"
    assert p.tpu_ckpt_every == 3
    assert p.tpu_restart == "old.npz"


def test_roundtrip_distributed(tmp_path):
    """Dist solvers carry stacked extended blocks; save/restore on the same
    mesh must continue bit-identical, and a mesh mismatch must be refused."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    def p3(te):
        return Parameter(
            name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=te,
            tau=0.5, itermax=50, eps=1e-3, omg=1.7, gamma=0.9,
            tpu_dtype="float64",
        )

    path = str(tmp_path / "ck3d.npz")
    dims = (2, 2, 2)
    ref = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ref.run(progress=False)

    first = NS3DDistSolver(p3(0.08), CartComm(ndims=3, dims=dims))
    first.run(progress=False)
    ckpt.save_checkpoint(path, first)

    second = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)
    assert ref.nt == second.nt
    for a, b in zip(ref.collect(), second.collect()):
        np.testing.assert_array_equal(a, b)

    other = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=(1, 2, 4)))
    with pytest.raises(ValueError, match="mesh"):
        ckpt.load_checkpoint(path, other)


# ---------------------------------------------------------------------------
# PR 4: durability edges (rotation, torn writes, corruption, fallback)
# ---------------------------------------------------------------------------

# the `faults` arming fixture lives in tests/conftest.py

def _two_generations(tmp_path):
    """One solver, two saves: gen1 rotated to .prev, gen2 live. Returns
    (path, solver, t_gen1, t_gen2)."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    t1 = s.t
    ckpt.save_checkpoint(path, s)
    s.t = t1 + 7.0  # distinguishable second generation
    ckpt.save_checkpoint(path, s)
    assert os.path.exists(path + ".prev")
    return path, s, t1, s.t


def test_rotation_keeps_previous_generation(tmp_path):
    path, _s, t1, t2 = _two_generations(tmp_path)
    a = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, a)
    assert a.t == t2
    b = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", b)
    assert b.t == t1


def test_torn_tmp_never_corrupts_live(tmp_path, faults):
    """An injected crash mid-np.savez leaves a torn .tmp; the atomic-rename
    protocol keeps the live file (and .prev) byte-valid and loadable."""
    path, s, t1, t2 = _two_generations(tmp_path)
    faults("ckpt_torn@write1")
    with pytest.raises(fi.CheckpointWriteCrash, match="torn"):
        ckpt.save_checkpoint(path, s)
    assert os.path.exists(path + ".tmp")  # the torn artifact
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # live file: still gen2, CRC-clean
    assert fresh.t == t2
    prev = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", prev)
    assert prev.t == t1


def test_corrupt_primary_falls_back_to_prev(tmp_path, faults):
    """An injected post-write corruption of the primary is rejected (CRC /
    zip integrity) and load falls back to the .prev generation."""
    path, s, t1, _t2 = _two_generations(tmp_path)
    faults("ckpt_corrupt@write1")
    ckpt.save_checkpoint(path, s)  # gen3 written then corrupted in place
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_checkpoint(path, fresh)
    # .prev is now gen2 (rotated by the gen3 write)
    assert fresh.t == s.t


def test_corrupt_without_prev_raises_clearly(tmp_path):
    """Corruption-at-rest with no previous generation: a clear structured
    error naming the file, not a confusing numpy traceback."""
    path = str(tmp_path / "only.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    fi.corrupt_file(path)
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError, match="torn or corrupt"):
        ckpt.load_checkpoint(path, other)


def test_crc_rejects_payload_bitflip(tmp_path):
    """A checkpoint whose zip container still reads but whose field bytes
    changed fails the per-field CRC32 (defense beyond the container's own
    integrity): rebuild the .npz with one flipped u value."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    ckpt.save_checkpoint(path, s)
    with np.load(path) as z:
        data = {k: z[k].copy() for k in z.files}
    data["u"].flat[5] += 1.0  # payload flip, container re-written validly
    with open(path, "wb") as fh:
        np.savez(fh, **data)
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError, match="CRC32"):
        ckpt.load_checkpoint(path, other, fallback=False)


def test_mesh_mismatch_single_vs_dist(tmp_path):
    """A dist-written checkpoint refuses to load into a single-device
    solver (stacked extended blocks are mesh-dependent) — with the message
    naming tpu_mesh, and NO .prev fallback (config error, not rot)."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    path = str(tmp_path / "ck.npz")
    d = NS2DDistSolver(_param(te=0.05), CartComm(ndims=2, dims=(2, 2)))
    d.run(progress=False)
    ckpt.save_checkpoint(path, d)
    single = NS2DSolver(_param(te=0.05))
    with pytest.raises(ValueError, match="tpu_mesh"):
        ckpt.load_checkpoint(path, single)


def test_restart_under_telemetry(tmp_path, monkeypatch):
    """Satellite (PR 4): a restart of a telemetry-enabled run rebuilds its
    chunk state via initial_state(), so the first post-restart chunk has
    the metrics arity — and the resumed run finishes bit-identical to an
    uninterrupted telemetry run, with ckpt save/load records and a
    continuous chunk trajectory in the flight record."""
    import json

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "a.jsonl"))
    tm.reset()
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    path = str(tmp_path / "ck.npz")
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "b.jsonl"))
    tm.reset()
    second = NS2DSolver(_param(te=0.5))
    assert second._metrics and len(second.initial_state()) == 6
    ckpt.load_checkpoint(path, second)
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))

    recs = [json.loads(ln) for ln in open(tmp_path / "b.jsonl") if ln.strip()]
    loads = [r for r in recs if r["kind"] == "ckpt" and r["event"] == "load"]
    assert len(loads) == 1 and loads[0]["nt"] == first.nt
    chunks = [r for r in recs if r["kind"] == "chunk"]
    # the post-restart trajectory starts where the checkpoint left off
    assert chunks[0]["nt"] > first.nt and chunks[-1]["nt"] == second.nt
    assert sum(c["steps"] for c in chunks) == second.nt - first.nt
    tm.reset()


def test_nonfinite_state_refused(tmp_path):
    """A diverged state is a CRC-valid checkpoint — saving it would rotate
    the last GOOD generation away, and a later restart/rollback would
    resume from garbage. save_checkpoint must refuse and leave the
    existing generations untouched."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    good_t = s.t
    ckpt.save_checkpoint(path, s)
    s.t = float("nan")
    with pytest.warns(UserWarning, match="non-finite"):
        ckpt.save_checkpoint(path, s)
    s.t = good_t
    s.u = s.u.at[3, 3].set(float("inf"))  # finite t, poisoned field
    with pytest.warns(UserWarning, match="non-finite"):
        ckpt.save_checkpoint(path, s)
    assert not os.path.exists(path + ".prev")  # no rotation happened
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # live file: still the good state
    assert fresh.t == good_t
    assert np.isfinite(np.asarray(fresh.u)).all()


def test_torn_primary_not_rotated_over_prev(tmp_path):
    """A torn (non-zip) primary must never rotate over the .prev
    generation — .prev may be the only good state left. It is parked at
    .bad and the new write lands as the fresh primary."""
    path, s, t1, t2 = _two_generations(tmp_path)
    with open(path, "wb") as fh:
        fh.write(b"garbage, definitely not a zip")
    with pytest.warns(UserWarning, match="torn"):
        ckpt.save_checkpoint(path, s)  # gen3 write over the torn primary
    assert os.path.exists(path + ".bad")  # the torn file, parked
    b = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", b)
    assert b.t == t1  # .prev untouched: still gen1
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # new primary: the gen3 state
    assert fresh.t == s.t


def test_both_generations_corrupt_one_structured_error(tmp_path):
    """Primary and .prev both corrupt: ONE CheckpointCorruptError naming
    both (a ValueError subclass — cli.py's restart handler catches it),
    never a raw BadZipFile escaping with a traceback."""
    path, s, _t1, _t2 = _two_generations(tmp_path)
    fi.corrupt_file(path)
    fi.corrupt_file(path + ".prev")
    other = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="and so is the previous generation"):
            ckpt.load_checkpoint(path, other)


# ---------------------------------------------------------------------------
# PR 10: elastic checkpoints — manifest + shards, restore on ANY mesh
# ---------------------------------------------------------------------------

def _dist2(te, dims):
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    return NS2DDistSolver(_param(te=te), CartComm(ndims=2, dims=dims))


def test_elastic_restore_matrix_bitwise(tmp_path):
    """The acceptance matrix: save on the full virtual 8-device mesh,
    restore onto 4 / 2 / transposed / single-device solvers — global
    fields bitwise equal after the NamedSharding reshard (the
    8->4->1 chip shrink)."""
    path = str(tmp_path / "ck.elastic")
    src = _dist2(0.1, (2, 4))
    src.run(progress=False)
    ckpt.save_elastic(path, src)
    ref = src.global_fields()

    for dims in ((2, 2), (4, 2), (1, 2), (2, 1)):
        tgt = _dist2(0.1, dims)
        ckpt.load_elastic(path, tgt)
        assert tgt.t == src.t and tgt.nt == src.nt
        got = tgt.global_fields()
        for f in ("u", "v", "p"):
            np.testing.assert_array_equal(got[f], ref[f], err_msg=str(dims))

    single = NS2DSolver(_param(te=0.1))
    ckpt.load_elastic(path, single)
    for f in ("u", "v", "p"):
        np.testing.assert_array_equal(np.asarray(getattr(single, f)),
                                      ref[f])


def test_elastic_single_to_dist_and_restart_continuation(tmp_path):
    """Single-device elastic save restores onto a mesh (the scale-UP
    direction), and a single->single elastic restart continues BITWISE
    (the full ghost ring rides the global layout)."""
    path = str(tmp_path / "ck.elastic")
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False)
    ckpt.save_elastic(path, first)

    onto_mesh = _dist2(0.2, (2, 2))
    ckpt.load_elastic(path, onto_mesh)
    got = onto_mesh.global_fields()
    for f in ("u", "v", "p"):
        np.testing.assert_array_equal(got[f], np.asarray(getattr(first, f)))

    second = NS2DSolver(_param(te=0.5))
    ckpt.load_elastic(path, second)
    second.run(progress=False)
    assert second.nt == ref.nt
    np.testing.assert_array_equal(np.asarray(second.p), np.asarray(ref.p))
    np.testing.assert_array_equal(np.asarray(second.u), np.asarray(ref.u))


def test_elastic_3d_roundtrip_across_meshes(tmp_path):
    """The 3-D family through the same N-D helpers: (2,2,2) -> (1,2,2)
    and single-device, bitwise."""
    from pampi_tpu.models.ns3d import NS3DSolver
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    def p3(te):
        return Parameter(
            name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=te,
            tau=0.5, itermax=50, eps=1e-3, omg=1.7, gamma=0.9,
            tpu_dtype="float64",
        )

    path = str(tmp_path / "ck3.elastic")
    src = NS3DDistSolver(p3(0.08), CartComm(ndims=3, dims=(2, 2, 2)))
    src.run(progress=False)
    ckpt.save_elastic(path, src)
    ref = src.global_fields()

    tgt = NS3DDistSolver(p3(0.08), CartComm(ndims=3, dims=(1, 2, 2)))
    ckpt.load_elastic(path, tgt)
    for f in ("u", "v", "w", "p"):
        np.testing.assert_array_equal(tgt.global_fields()[f], ref[f])

    single = NS3DSolver(p3(0.08))
    ckpt.load_elastic(path, single)
    for f in ("u", "v", "w", "p"):
        np.testing.assert_array_equal(np.asarray(getattr(single, f)),
                                      ref[f])


def _two_elastic_generations(tmp_path):
    path = str(tmp_path / "ck.elastic")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    t1 = s.t
    ckpt.save_elastic(path, s)
    s.t = t1 + 7.0
    ckpt.save_elastic(path, s)
    assert os.path.exists(path + ".prev")
    return path, s, t1, s.t


def test_elastic_rotation_and_generation_named_shards(tmp_path):
    """Two saves: manifest rotated to .prev, each generation pointing at
    its OWN generation-named shard files (no cross-generation sharing —
    the crash-window safety of the scheme)."""
    import json

    path, _s, t1, t2 = _two_elastic_generations(tmp_path)
    live = json.load(open(path))
    prev = json.load(open(path + ".prev"))
    assert live["generation"] == 2 and prev["generation"] == 1
    assert live["shards"][0]["file"] != prev["shards"][0]["file"]
    a = NS2DSolver(_param(te=0.1))
    ckpt.load_elastic(path, a)
    assert a.t == t2
    b = NS2DSolver(_param(te=0.1))
    ckpt.load_elastic(path + ".prev", b)
    assert b.t == t1


def test_elastic_torn_manifest_falls_back_to_prev(tmp_path):
    path, _s, t1, _t2 = _two_elastic_generations(tmp_path)
    with open(path, "w") as fh:
        fh.write('{"format": "pampi-elastic-ckpt", "tru')  # torn JSON
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_elastic(path, fresh)
    assert fresh.t == t1


def test_elastic_missing_shard_rejected_then_falls_back(tmp_path):
    import json

    path, _s, t1, _t2 = _two_elastic_generations(tmp_path)
    shard = json.load(open(path))["shards"][0]["file"]
    os.remove(str(tmp_path / shard))
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_elastic(path, fresh)
    assert fresh.t == t1
    # without a fallback generation the rejection is structured + loud
    os.remove(path + ".prev")
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError, match="torn or corrupt"):
        ckpt.load_elastic(path, other)


def test_elastic_mixed_generation_refused(tmp_path):
    """A shard whose embedded generation differs from the manifest's is
    the crash-window / mangled-backup signature: REFUSED, never silently
    combined — and the error names both generations."""
    import json

    path, _s, t1, _t2 = _two_elastic_generations(tmp_path)
    man = json.load(open(path))
    man["generation"] = 7  # manifest claims a generation no shard has
    # keep shard names as-is: the EMBEDDED generation is the authority
    with open(path, "w") as fh:
        json.dump(man, fh)
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_elastic(path, fresh)  # .prev set still loads
    assert fresh.t == t1
    os.remove(path + ".prev")
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError,
                       match="mixed-generation"):
        ckpt.load_elastic(path, other, fallback=False)


def test_elastic_shard_crc_rejects_bitflip(tmp_path, faults):
    """ckpt_corrupt@write<N> now exercises the elastic shard write too:
    the corrupted shard fails its CRC and load falls back."""
    path, s, t1, _t2 = _two_elastic_generations(tmp_path)
    faults("ckpt_corrupt@write1")
    ckpt.save_elastic(path, s)  # gen3 shard written then corrupted
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_elastic(path, fresh)
    assert fresh.t == s.t  # .prev is gen2 (same state, rotated)


def test_elastic_torn_shard_write_never_commits(tmp_path, faults):
    """ckpt_torn@write<N> on an elastic save: the crash lands before the
    manifest commit, so the OLD generation set stays live and loadable."""
    path, s, t1, t2 = _two_elastic_generations(tmp_path)
    faults("ckpt_torn@write1")
    with pytest.raises(fi.CheckpointWriteCrash, match="torn"):
        ckpt.save_elastic(path, s)
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_elastic(path, fresh)  # live manifest: still gen2, intact
    assert fresh.t == t2


def test_elastic_shape_mismatch_is_config_error_no_fallback(tmp_path):
    path, _s, _t1, _t2 = _two_elastic_generations(tmp_path)
    other = NS2DSolver(
        Parameter(name="dcavity", imax=8, jmax=8, re=10.0, te=0.1,
                  tpu_dtype="float64"))
    with pytest.raises(ValueError, match="global shape"):
        ckpt.load_elastic(path, other)  # .prev exists but must NOT mask it


def test_elastic_nonfinite_state_refused(tmp_path):
    path = str(tmp_path / "ck.elastic")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    ckpt.save_elastic(path, s)
    s.t = float("nan")
    with pytest.warns(UserWarning, match="non-finite"):
        ckpt.save_elastic(path, s)
    assert not os.path.exists(path + ".prev")  # no rotation happened


def test_load_any_sniffs_both_formats(tmp_path):
    legacy, elastic = str(tmp_path / "a.npz"), str(tmp_path / "b.el")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    ckpt.save_checkpoint(legacy, s)
    ckpt.save_elastic(elastic, s)
    for path in (legacy, elastic):
        fresh = NS2DSolver(_param(te=0.1))
        ckpt.load_any(path, fresh)
        assert fresh.t == s.t and fresh.nt == s.nt
        np.testing.assert_array_equal(np.asarray(fresh.u), np.asarray(s.u))


def test_fleet_elastic_restore_shrinks_the_mesh(tmp_path):
    """The autoscaling hook: an 8-chip elastic checkpoint restored by
    the FleetScheduler onto 4 (and 1) of the same virtual devices —
    fields bitwise, solver ready to drive."""
    import jax

    from pampi_tpu.fleet.scheduler import FleetScheduler

    path = str(tmp_path / "ck.elastic")
    src = _dist2(0.1, (2, 4))
    src.run(progress=False)
    ckpt.save_elastic(path, src)
    ref = src.global_fields()

    sched = FleetScheduler()
    shrunk = sched.elastic_restore(path, _param(te=0.2), "ns2d",
                                   devices=jax.devices()[:4])
    assert shrunk.comm.size == 4
    got = shrunk.global_fields()
    for f in ("u", "v", "p"):
        np.testing.assert_array_equal(got[f], ref[f])

    one = sched.elastic_restore(path, _param(te=0.2), "ns2d",
                                devices=jax.devices()[:1])
    assert not hasattr(one, "comm")  # single-device solver
    np.testing.assert_array_equal(np.asarray(one.p), ref["p"])
    one.run(progress=False)  # drives on from the restored state
    assert one.t > 0.1


def test_ckpt_fsck_tool_verdicts(tmp_path):
    """tools/ckpt_fsck.py: healthy elastic + legacy sets verify (rc 0);
    a corrupted shard flips the verdict (rc 1) and the report names the
    failing field/file."""
    import subprocess
    import sys as _sys

    path, s, _t1, _t2 = _two_elastic_generations(tmp_path)
    legacy = str(tmp_path / "l.npz")
    ckpt.save_checkpoint(legacy, s)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    r = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "ckpt_fsck.py"),
         path, legacy], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "verdict  ok" in r.stdout and "generation 2" in r.stdout

    import json

    shard = json.load(open(path))["shards"][0]["file"]
    fi.corrupt_file(str(tmp_path / shard))
    r = subprocess.run(
        [_sys.executable, os.path.join(repo, "tools", "ckpt_fsck.py"),
         path], capture_output=True, text=True)
    assert r.returncode == 1
    assert "CORRUPT" in r.stdout


def test_ckpt_fsck_survivors_check(tmp_path):
    """PR 12: `--survivors N` is the shrink-resume pre-flight — a
    healthy elastic set WITH a fault ledger passes; a set written
    without an armed coordinator (no ledger) fails naming the amnesia
    risk; the mesh-locked legacy format is refused outright."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "ckpt_fsck.py")
    s = NS2DSolver(_param(te=0.05))
    s.run(progress=False)

    with_ledger = str(tmp_path / "led.elastic")
    ckpt.save_elastic(with_ledger, s,
                      ledger={"budget_spent": 0, "epoch": 0})
    r = subprocess.run([_sys.executable, tool, "--survivors", "1",
                        with_ledger], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "survivors 1: ok" in r.stdout

    bare = str(tmp_path / "bare.elastic")
    ckpt.save_elastic(bare, s)
    r = subprocess.run([_sys.executable, tool, "--survivors", "2", bare],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "no fault ledger" in r.stdout
    # ...and without the flag the same set still verifies clean
    r = subprocess.run([_sys.executable, tool, bare],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    legacy = str(tmp_path / "l.npz")
    ckpt.save_checkpoint(legacy, s)
    r = subprocess.run([_sys.executable, tool, "--survivors", "1",
                        legacy], capture_output=True, text=True)
    assert r.returncode == 1
    assert "mesh-locked" in r.stdout


def test_ring_recovery_cold_tier_reads_elastic(tmp_path):
    """Review regression: the divergence rollback's COLD tier must read
    whichever format tpu_checkpoint writes — with the ring exhausted and
    an elastic manifest on disk, attempt() restores from it (load_any
    sniffs) instead of degrading to 'no checkpoint'."""
    from pampi_tpu.models._driver import RingRecovery

    path = str(tmp_path / "ck.elastic")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    good_t, good_nt = s.t, s.nt
    ckpt.save_elastic(path, s)
    s.t, s.nt = float("nan"), good_nt + 5  # diverged in-memory state
    rec = RingRecovery(s, "ns2d", time_index=3, ring=2, ckpt_path=path)
    rolled = rec.attempt()  # ring empty -> cold tier
    assert rolled is not None
    state, _fn = rolled
    assert float(state[3]) == good_t and int(state[4]) == good_nt
    assert np.isfinite(np.asarray(s.u)).all()
