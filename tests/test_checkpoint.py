"""Checkpoint/restart round-trip: a run interrupted at a host sync and
resumed from the .npz must finish bit-identical to an uninterrupted run
(the subsystem the reference lacks, SURVEY.md §5)."""

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import checkpoint as ckpt
from pampi_tpu.utils.params import Parameter, read_parameter


def _param(te):
    return Parameter(
        name="dcavity", imax=32, jmax=32, re=10.0, te=te, tau=0.5,
        itermax=100, eps=1e-3, omg=1.8, gamma=0.9, tpu_dtype="float64",
    )


def test_roundtrip_bitwise(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    # interrupted: checkpoint at EVERY host sync, stop partway by using a
    # shorter te, then restore into a fresh solver and continue to te
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    second = NS2DSolver(_param(te=0.5))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))
    np.testing.assert_array_equal(np.asarray(ref.v), np.asarray(second.v))


def test_shape_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    other = NS2DSolver(
        Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.1,
                  tpu_dtype="float64")
    )
    with pytest.raises(ValueError, match="checkpoint grid"):
        ckpt.load_checkpoint(path, other)


def test_par_keys_parsed(tmp_path):
    par = tmp_path / "r.par"
    par.write_text(
        "name dcavity\ntpu_checkpoint ck.npz\ntpu_ckpt_every 3\n"
        "tpu_restart old.npz\n"
    )
    p = read_parameter(str(par))
    assert p.tpu_checkpoint == "ck.npz"
    assert p.tpu_ckpt_every == 3
    assert p.tpu_restart == "old.npz"
