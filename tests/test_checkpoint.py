"""Checkpoint/restart round-trip: a run interrupted at a host sync and
resumed from the .npz must finish bit-identical to an uninterrupted run
(the subsystem the reference lacks, SURVEY.md §5). PR 4 durability edges:
per-field CRC32 + schema version, live->.prev rotation, torn-.tmp crash
safety, corrupt-primary fallback, and the restart-under-telemetry arity
contract."""

import os

import numpy as np
import pytest

from pampi_tpu.models.ns2d import NS2DSolver
from pampi_tpu.utils import checkpoint as ckpt
from pampi_tpu.utils import faultinject as fi
from pampi_tpu.utils import telemetry as tm
from pampi_tpu.utils.params import Parameter, read_parameter


def _param(te):
    return Parameter(
        name="dcavity", imax=32, jmax=32, re=10.0, te=te, tau=0.5,
        itermax=100, eps=1e-3, omg=1.8, gamma=0.9, tpu_dtype="float64",
    )


def test_roundtrip_bitwise(tmp_path):
    path = str(tmp_path / "ck.npz")

    # uninterrupted run
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    # interrupted: checkpoint at EVERY host sync, stop partway by using a
    # shorter te, then restore into a fresh solver and continue to te
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    second = NS2DSolver(_param(te=0.5))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))
    np.testing.assert_array_equal(np.asarray(ref.v), np.asarray(second.v))


def test_shape_mismatch_rejected(tmp_path):
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    other = NS2DSolver(
        Parameter(name="dcavity", imax=16, jmax=16, re=10.0, te=0.1,
                  tpu_dtype="float64")
    )
    with pytest.raises(ValueError, match="checkpoint grid"):
        ckpt.load_checkpoint(path, other)


def test_par_keys_parsed(tmp_path):
    par = tmp_path / "r.par"
    par.write_text(
        "name dcavity\ntpu_checkpoint ck.npz\ntpu_ckpt_every 3\n"
        "tpu_restart old.npz\n"
    )
    p = read_parameter(str(par))
    assert p.tpu_checkpoint == "ck.npz"
    assert p.tpu_ckpt_every == 3
    assert p.tpu_restart == "old.npz"


def test_roundtrip_distributed(tmp_path):
    """Dist solvers carry stacked extended blocks; save/restore on the same
    mesh must continue bit-identical, and a mesh mismatch must be refused."""
    from pampi_tpu.models.ns3d_dist import NS3DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    def p3(te):
        return Parameter(
            name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=te,
            tau=0.5, itermax=50, eps=1e-3, omg=1.7, gamma=0.9,
            tpu_dtype="float64",
        )

    path = str(tmp_path / "ck3d.npz")
    dims = (2, 2, 2)
    ref = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ref.run(progress=False)

    first = NS3DDistSolver(p3(0.08), CartComm(ndims=3, dims=dims))
    first.run(progress=False)
    ckpt.save_checkpoint(path, first)

    second = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=dims))
    ckpt.load_checkpoint(path, second)
    assert second.t == first.t and second.nt == first.nt
    second.run(progress=False)
    assert ref.nt == second.nt
    for a, b in zip(ref.collect(), second.collect()):
        np.testing.assert_array_equal(a, b)

    other = NS3DDistSolver(p3(0.2), CartComm(ndims=3, dims=(1, 2, 4)))
    with pytest.raises(ValueError, match="mesh"):
        ckpt.load_checkpoint(path, other)


# ---------------------------------------------------------------------------
# PR 4: durability edges (rotation, torn writes, corruption, fallback)
# ---------------------------------------------------------------------------

# the `faults` arming fixture lives in tests/conftest.py

def _two_generations(tmp_path):
    """One solver, two saves: gen1 rotated to .prev, gen2 live. Returns
    (path, solver, t_gen1, t_gen2)."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    t1 = s.t
    ckpt.save_checkpoint(path, s)
    s.t = t1 + 7.0  # distinguishable second generation
    ckpt.save_checkpoint(path, s)
    assert os.path.exists(path + ".prev")
    return path, s, t1, s.t


def test_rotation_keeps_previous_generation(tmp_path):
    path, _s, t1, t2 = _two_generations(tmp_path)
    a = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, a)
    assert a.t == t2
    b = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", b)
    assert b.t == t1


def test_torn_tmp_never_corrupts_live(tmp_path, faults):
    """An injected crash mid-np.savez leaves a torn .tmp; the atomic-rename
    protocol keeps the live file (and .prev) byte-valid and loadable."""
    path, s, t1, t2 = _two_generations(tmp_path)
    faults("ckpt_torn@write1")
    with pytest.raises(fi.CheckpointWriteCrash, match="torn"):
        ckpt.save_checkpoint(path, s)
    assert os.path.exists(path + ".tmp")  # the torn artifact
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # live file: still gen2, CRC-clean
    assert fresh.t == t2
    prev = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", prev)
    assert prev.t == t1


def test_corrupt_primary_falls_back_to_prev(tmp_path, faults):
    """An injected post-write corruption of the primary is rejected (CRC /
    zip integrity) and load falls back to the .prev generation."""
    path, s, t1, _t2 = _two_generations(tmp_path)
    faults("ckpt_corrupt@write1")
    ckpt.save_checkpoint(path, s)  # gen3 written then corrupted in place
    fresh = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        ckpt.load_checkpoint(path, fresh)
    # .prev is now gen2 (rotated by the gen3 write)
    assert fresh.t == s.t


def test_corrupt_without_prev_raises_clearly(tmp_path):
    """Corruption-at-rest with no previous generation: a clear structured
    error naming the file, not a confusing numpy traceback."""
    path = str(tmp_path / "only.npz")
    s = NS2DSolver(_param(te=0.1))
    ckpt.save_checkpoint(path, s)
    fi.corrupt_file(path)
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError, match="torn or corrupt"):
        ckpt.load_checkpoint(path, other)


def test_crc_rejects_payload_bitflip(tmp_path):
    """A checkpoint whose zip container still reads but whose field bytes
    changed fails the per-field CRC32 (defense beyond the container's own
    integrity): rebuild the .npz with one flipped u value."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    ckpt.save_checkpoint(path, s)
    with np.load(path) as z:
        data = {k: z[k].copy() for k in z.files}
    data["u"].flat[5] += 1.0  # payload flip, container re-written validly
    with open(path, "wb") as fh:
        np.savez(fh, **data)
    other = NS2DSolver(_param(te=0.1))
    with pytest.raises(ckpt.CheckpointCorruptError, match="CRC32"):
        ckpt.load_checkpoint(path, other, fallback=False)


def test_mesh_mismatch_single_vs_dist(tmp_path):
    """A dist-written checkpoint refuses to load into a single-device
    solver (stacked extended blocks are mesh-dependent) — with the message
    naming tpu_mesh, and NO .prev fallback (config error, not rot)."""
    from pampi_tpu.models.ns2d_dist import NS2DDistSolver
    from pampi_tpu.parallel.comm import CartComm

    path = str(tmp_path / "ck.npz")
    d = NS2DDistSolver(_param(te=0.05), CartComm(ndims=2, dims=(2, 2)))
    d.run(progress=False)
    ckpt.save_checkpoint(path, d)
    single = NS2DSolver(_param(te=0.05))
    with pytest.raises(ValueError, match="tpu_mesh"):
        ckpt.load_checkpoint(path, single)


def test_restart_under_telemetry(tmp_path, monkeypatch):
    """Satellite (PR 4): a restart of a telemetry-enabled run rebuilds its
    chunk state via initial_state(), so the first post-restart chunk has
    the metrics arity — and the resumed run finishes bit-identical to an
    uninterrupted telemetry run, with ckpt save/load records and a
    continuous chunk trajectory in the flight record."""
    import json

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "a.jsonl"))
    tm.reset()
    ref = NS2DSolver(_param(te=0.5))
    ref.run(progress=False)

    path = str(tmp_path / "ck.npz")
    first = NS2DSolver(_param(te=0.2))
    first.run(progress=False, on_sync=ckpt.periodic_writer(path, every=1))
    ckpt.save_checkpoint(path, first)

    monkeypatch.setenv("PAMPI_TELEMETRY", str(tmp_path / "b.jsonl"))
    tm.reset()
    second = NS2DSolver(_param(te=0.5))
    assert second._metrics and len(second.initial_state()) == 6
    ckpt.load_checkpoint(path, second)
    second.run(progress=False)

    assert ref.nt == second.nt
    np.testing.assert_array_equal(np.asarray(ref.p), np.asarray(second.p))
    np.testing.assert_array_equal(np.asarray(ref.u), np.asarray(second.u))

    recs = [json.loads(ln) for ln in open(tmp_path / "b.jsonl") if ln.strip()]
    loads = [r for r in recs if r["kind"] == "ckpt" and r["event"] == "load"]
    assert len(loads) == 1 and loads[0]["nt"] == first.nt
    chunks = [r for r in recs if r["kind"] == "chunk"]
    # the post-restart trajectory starts where the checkpoint left off
    assert chunks[0]["nt"] > first.nt and chunks[-1]["nt"] == second.nt
    assert sum(c["steps"] for c in chunks) == second.nt - first.nt
    tm.reset()


def test_nonfinite_state_refused(tmp_path):
    """A diverged state is a CRC-valid checkpoint — saving it would rotate
    the last GOOD generation away, and a later restart/rollback would
    resume from garbage. save_checkpoint must refuse and leave the
    existing generations untouched."""
    path = str(tmp_path / "ck.npz")
    s = NS2DSolver(_param(te=0.1))
    s.run(progress=False)
    good_t = s.t
    ckpt.save_checkpoint(path, s)
    s.t = float("nan")
    with pytest.warns(UserWarning, match="non-finite"):
        ckpt.save_checkpoint(path, s)
    s.t = good_t
    s.u = s.u.at[3, 3].set(float("inf"))  # finite t, poisoned field
    with pytest.warns(UserWarning, match="non-finite"):
        ckpt.save_checkpoint(path, s)
    assert not os.path.exists(path + ".prev")  # no rotation happened
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # live file: still the good state
    assert fresh.t == good_t
    assert np.isfinite(np.asarray(fresh.u)).all()


def test_torn_primary_not_rotated_over_prev(tmp_path):
    """A torn (non-zip) primary must never rotate over the .prev
    generation — .prev may be the only good state left. It is parked at
    .bad and the new write lands as the fresh primary."""
    path, s, t1, t2 = _two_generations(tmp_path)
    with open(path, "wb") as fh:
        fh.write(b"garbage, definitely not a zip")
    with pytest.warns(UserWarning, match="torn"):
        ckpt.save_checkpoint(path, s)  # gen3 write over the torn primary
    assert os.path.exists(path + ".bad")  # the torn file, parked
    b = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path + ".prev", b)
    assert b.t == t1  # .prev untouched: still gen1
    fresh = NS2DSolver(_param(te=0.1))
    ckpt.load_checkpoint(path, fresh)  # new primary: the gen3 state
    assert fresh.t == s.t


def test_both_generations_corrupt_one_structured_error(tmp_path):
    """Primary and .prev both corrupt: ONE CheckpointCorruptError naming
    both (a ValueError subclass — cli.py's restart handler catches it),
    never a raw BadZipFile escaping with a traceback."""
    path, s, _t1, _t2 = _two_generations(tmp_path)
    fi.corrupt_file(path)
    fi.corrupt_file(path + ".prev")
    other = NS2DSolver(_param(te=0.1))
    with pytest.warns(UserWarning, match="falling back"):
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="and so is the previous generation"):
            ckpt.load_checkpoint(path, other)
