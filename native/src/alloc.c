/* Aligned host allocation (capability parity with the reference's
 * allocate.c posix_memalign wrapper, /root/reference/assignment-4/src/
 * allocate.c:11-37 — same contract: aligned or die loudly). */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>

#include "pampi.h"

void *pampi_allocate(size_t alignment, size_t bytes) {
    void *p = NULL;
    int rc = posix_memalign(&p, alignment, bytes);
    if (rc != 0 || p == NULL) {
        fprintf(stderr, "pampi_allocate: %zu bytes @%zu failed: %s\n", bytes,
                alignment, rc == EINVAL ? "bad alignment" : "out of memory");
        exit(EXIT_FAILURE);
    }
    return p;
}

void pampi_deallocate(void *p) { free(p); }
