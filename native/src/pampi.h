/* PAMPI-TPU native runtime layer.
 *
 * Host-side plumbing for the TPU framework, mirroring the capability of the
 * reference's C runtime toolbox (/root/reference: allocate.{h,c},
 * parameter.{h,c}, vtkWriter.{h,c}, the .dat writers in solver.c, and the L6
 * driver main.c) with a fresh, table-driven design. The compute path is
 * JAX/XLA/Pallas (Python); this layer provides:
 *   - the .par parser + config echo (same grammar: '#' comments, first two
 *     whitespace tokens, prefix-matched keys, defaults for every key),
 *   - aligned host allocation,
 *   - fast buffered writers for .dat / legacy-VTK output (byte-compatible
 *     with the Python writers in pampi_tpu/utils/{datio,vtkio}.py),
 *   - the exe shim that validates a config natively and hands the run to
 *     the JAX process (see shim_main.c).
 */
#ifndef PAMPI_H
#define PAMPI_H

#include <stddef.h>
#include <stdio.h>

/* ---- aligned allocation (parity: allocate.h) ---- */
void *pampi_allocate(size_t alignment, size_t bytes); /* exits on failure */
void pampi_deallocate(void *p);

/* ---- .par configuration (parity: parameter.h) ---- */
typedef struct {
    double xlength, ylength, zlength;
    long imax, jmax, kmax;
    long itermax;
    double eps, omg, rho;
    double re, tau, gamma, dt, te;
    double gx, gy, gz;
    char name[128];
    long bcLeft, bcRight, bcBottom, bcTop, bcFront, bcBack;
    double u_init, v_init, w_init, p_init;
    char obstacles[256]; /* ';'-separated "x0,y0,x1,y1" rects; "" = none */
    char tpu_mesh[64];
    char tpu_dtype[32];
    unsigned seen; /* bitmask over PAMPI_SEEN_* below */
} PampiParam;

enum {
    PAMPI_SEEN_KMAX = 1u << 0,
    PAMPI_SEEN_ZLENGTH = 1u << 1,
    PAMPI_SEEN_BCFRONT = 1u << 2,
    PAMPI_SEEN_BCBACK = 1u << 3,
};

void pampi_param_init(PampiParam *p);
/* returns 0 on success, -1 if the file cannot be opened/parsed */
int pampi_param_read(PampiParam *p, const char *path);
int pampi_param_is3d(const PampiParam *p);
void pampi_param_print(const PampiParam *p, FILE *out);

/* ---- .dat writers (parity: assignment-4 writeResult / assignment-5
 *      writeResult; byte-compatible with pampi_tpu/utils/datio.py) ---- */
int pampi_write_matrix(const char *path, const double *a, long rows, long cols);
int pampi_write_pressure(const char *path, const double *p, long rows,
                         long cols, double dx, double dy);
int pampi_write_velocity(const char *path, const double *u, const double *v,
                         long rows, long cols, double dx, double dy);

/* ---- legacy-VTK STRUCTURED_POINTS writer (parity: vtkWriter.h;
 *      byte-compatible with pampi_tpu/utils/vtkio.py) ---- */
typedef struct PampiVtk PampiVtk;
PampiVtk *pampi_vtk_open(const char *path, const char *title, long imax,
                         long jmax, long kmax, double dx, double dy, double dz,
                         int binary);
int pampi_vtk_scalar(PampiVtk *w, const char *name, const double *s, long n);
int pampi_vtk_vector(PampiVtk *w, const char *name, const double *u,
                     const double *v, const double *wv, long n);
int pampi_vtk_close(PampiVtk *w);

#endif /* PAMPI_H */
