/* Table-driven .par parser + config echo.
 *
 * Grammar parity with the reference's parameter.c (/root/reference/
 * assignment-6/src/parameter.c:31-93): '#' starts a comment, the first two
 * whitespace-separated tokens are key and value, keys are matched by PREFIX
 * (a token `imaxFoo` still sets `imax`), unknown keys are ignored, every key
 * has a default. The echo format matches printParameter (:95-126) and the
 * Python twin pampi_tpu/utils/params.py `print_parameter`.
 *
 * Design is deliberately different from the reference's PARSE_* macro
 * ladder: one descriptor table drives parsing, so adding a key is one line.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pampi.h"

typedef enum { T_DBL, T_LONG, T_STR } FieldType;

typedef struct {
    const char *key;
    FieldType type;
    size_t off;
    size_t strcap;  /* for T_STR */
    unsigned seenbit; /* 0 if untracked */
} FieldDesc;

#define F_DBL(k, m) {#k, T_DBL, offsetof(PampiParam, m), 0, 0}
#define F_LONG(k, m, bit) {#k, T_LONG, offsetof(PampiParam, m), 0, bit}
#define F_STR(k, m) {#k, T_STR, offsetof(PampiParam, m), sizeof(((PampiParam *)0)->m), 0}

static const FieldDesc FIELDS[] = {
    F_DBL(xlength, xlength),
    F_DBL(ylength, ylength),
    {"zlength", T_DBL, offsetof(PampiParam, zlength), 0, PAMPI_SEEN_ZLENGTH},
    F_LONG(imax, imax, 0),
    F_LONG(jmax, jmax, 0),
    F_LONG(kmax, kmax, PAMPI_SEEN_KMAX),
    F_LONG(itermax, itermax, 0),
    F_DBL(eps, eps),
    F_DBL(omg, omg),
    F_DBL(rho, rho),
    F_DBL(re, re),
    F_DBL(tau, tau),
    F_DBL(gamma, gamma),
    F_DBL(dt, dt),
    F_DBL(te, te),
    F_DBL(gx, gx),
    F_DBL(gy, gy),
    F_DBL(gz, gz),
    F_STR(name, name),
    F_LONG(bcLeft, bcLeft, 0),
    F_LONG(bcRight, bcRight, 0),
    F_LONG(bcBottom, bcBottom, 0),
    F_LONG(bcTop, bcTop, 0),
    F_LONG(bcFront, bcFront, PAMPI_SEEN_BCFRONT),
    F_LONG(bcBack, bcBack, PAMPI_SEEN_BCBACK),
    F_DBL(u_init, u_init),
    F_DBL(v_init, v_init),
    F_DBL(w_init, w_init),
    F_DBL(p_init, p_init),
    F_STR(obstacles, obstacles),
    F_STR(tpu_mesh, tpu_mesh),
    F_STR(tpu_dtype, tpu_dtype),
};
enum { NFIELDS = sizeof(FIELDS) / sizeof(FIELDS[0]) };

void pampi_param_init(PampiParam *p) {
    memset(p, 0, sizeof(*p));
    p->xlength = p->ylength = p->zlength = 1.0;
    p->imax = p->jmax = 100;
    p->kmax = 50;
    p->itermax = 1000;
    p->eps = 0.0001;
    p->omg = 1.7;
    p->rho = 0.99;
    p->re = 100.0;
    p->tau = 0.5;
    p->gamma = 0.9;
    p->dt = 0.02;
    p->te = 10.0;
    snprintf(p->name, sizeof(p->name), "poisson");
    p->bcLeft = p->bcRight = p->bcBottom = p->bcTop = 1;
    p->bcFront = p->bcBack = 1;
    snprintf(p->tpu_mesh, sizeof(p->tpu_mesh), "auto");
    snprintf(p->tpu_dtype, sizeof(p->tpu_dtype), "float64");
}

/* returns 0, or -1 on a malformed numeric value (parity: params.py
 * read_parameter exits with "bad value ... for parameter ...") */
static int assign(PampiParam *p, const FieldDesc *f, const char *val) {
    char *base = (char *)p;
    char *end = NULL;
    switch (f->type) {
    case T_DBL:
        *(double *)(base + f->off) = strtod(val, &end);
        break;
    case T_LONG:
        *(long *)(base + f->off) = strtol(val, &end, 10);
        break;
    case T_STR:
        snprintf(base + f->off, f->strcap, "%s", val);
        break;
    }
    if (end && (end == val || *end != '\0')) {
        fprintf(stderr, "bad value '%s' for parameter %s\n", val, f->key);
        return -1;
    }
    p->seen |= f->seenbit;
    return 0;
}

int pampi_param_read(PampiParam *p, const char *path) {
    FILE *fh = fopen(path, "r");
    if (!fh) {
        fprintf(stderr, "Could not open parameter file: %s\n", path);
        return -1;
    }
    char line[1024];
    while (fgets(line, sizeof(line), fh)) {
        char *hash = strchr(line, '#');
        if (hash)
            *hash = '\0';
        char *save = NULL;
        char *tok = strtok_r(line, " \t\r\n", &save);
        char *val = tok ? strtok_r(NULL, " \t\r\n", &save) : NULL;
        if (!tok || !val)
            continue;
        /* reference semantics: every key that prefixes the token matches */
        for (int i = 0; i < NFIELDS; i++)
            if (strncmp(tok, FIELDS[i].key, strlen(FIELDS[i].key)) == 0)
                if (assign(p, &FIELDS[i], val) != 0) {
                    fclose(fh);
                    return -1;
                }
    }
    fclose(fh);
    return 0;
}

int pampi_param_is3d(const PampiParam *p) {
    size_t n = strlen(p->name);
    if (n >= 2 && strcmp(p->name + n - 2, "3d") == 0)
        return 1;
    return (p->seen & (PAMPI_SEEN_KMAX | PAMPI_SEEN_ZLENGTH |
                       PAMPI_SEEN_BCFRONT | PAMPI_SEEN_BCBACK)) != 0;
}

void pampi_param_print(const PampiParam *p, FILE *out) {
    int d3 = pampi_param_is3d(p);
    fprintf(out, "Parameters for %s\n", p->name);
    if (d3)
        fprintf(out,
                "Boundary conditions Left:%ld Right:%ld Bottom:%ld Top:%ld "
                "Front:%ld Back:%ld\n",
                p->bcLeft, p->bcRight, p->bcBottom, p->bcTop, p->bcFront,
                p->bcBack);
    else
        fprintf(out,
                "Boundary conditions Left:%ld Right:%ld Bottom:%ld Top:%ld\n",
                p->bcLeft, p->bcRight, p->bcBottom, p->bcTop);
    fprintf(out, "\tReynolds number: %.2f\n", p->re);
    if (d3)
        fprintf(out, "\tInit arrays: U:%.2f V:%.2f W:%.2f P:%.2f\n", p->u_init,
                p->v_init, p->w_init, p->p_init);
    else
        fprintf(out, "\tInit arrays: U:%.2f V:%.2f P:%.2f\n", p->u_init,
                p->v_init, p->p_init);
    fprintf(out, "Geometry data:\n");
    if (d3) {
        fprintf(out, "\tDomain box size (x, y, z): %.2f, %.2f, %.2f\n",
                p->xlength, p->ylength, p->zlength);
        fprintf(out, "\tCells (x, y, z): %ld, %ld, %ld\n", p->imax, p->jmax,
                p->kmax);
    } else {
        fprintf(out, "\tDomain box size (x, y): %.2f, %.2f\n", p->xlength,
                p->ylength);
        fprintf(out, "\tCells (x, y): %ld, %ld\n", p->imax, p->jmax);
    }
    fprintf(out, "Timestep parameters:\n");
    fprintf(out, "\tDefault stepsize: %.2f, Final time %.2f\n", p->dt, p->te);
    fprintf(out, "\tTau factor: %.2f\n", p->tau);
    fprintf(out, "Iterative solver parameters:\n");
    fprintf(out, "\tMax iterations: %ld\n", p->itermax);
    fprintf(out, "\tepsilon (stopping tolerance) : %f\n", p->eps);
    fprintf(out, "\tgamma factor: %f\n", p->gamma);
    fprintf(out, "\tomega (SOR relaxation): %f\n", p->omg);
}
