/* Buffered .dat and legacy-VTK writers.
 *
 * Byte-compatible with the Python writers (pampi_tpu/utils/datio.py,
 * vtkio.py), which themselves carry format parity with the reference's
 * output layer (assignment-4/src/solver.c writeResult, assignment-5
 * writeResult, assignment-6/src/vtkWriter.c). Used from Python via ctypes
 * (pampi_tpu/utils/native.py) to take the per-value printf loop out of the
 * interpreter for large fields.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pampi.h"

#define IOBUF (1 << 20)

static FILE *open_buffered(const char *path, char **buf) {
    FILE *fh = fopen(path, "wb");
    if (!fh)
        return NULL;
    *buf = malloc(IOBUF);
    if (*buf)
        setvbuf(fh, *buf, _IOFBF, IOBUF);
    return fh;
}

/* close + error check: a short write (ENOSPC, quota) must NOT look like
 * success to the Python caller */
static int close_checked(FILE *fh, char *buf) {
    int bad = ferror(fh);
    int rc = fclose(fh);
    free(buf);
    return (bad || rc != 0) ? -1 : 0;
}

int pampi_write_matrix(const char *path, const double *a, long rows,
                       long cols) {
    char *buf = NULL;
    FILE *fh = open_buffered(path, &buf);
    if (!fh)
        return -1;
    for (long j = 0; j < rows; j++) {
        for (long i = 0; i < cols; i++)
            fprintf(fh, "%f ", a[j * cols + i]);
        fputc('\n', fh);
    }
    return close_checked(fh, buf);
}

int pampi_write_pressure(const char *path, const double *p, long rows,
                         long cols, double dx, double dy) {
    char *buf = NULL;
    FILE *fh = open_buffered(path, &buf);
    if (!fh)
        return -1;
    long jmax = rows - 2, imax = cols - 2;
    for (long j = 1; j <= jmax; j++) {
        double y = (j - 0.5) * dy;
        for (long i = 1; i <= imax; i++)
            fprintf(fh, "%.2f %.2f %f\n", (i - 0.5) * dx, y, p[j * cols + i]);
        fputc('\n', fh);
    }
    return close_checked(fh, buf);
}

int pampi_write_velocity(const char *path, const double *u, const double *v,
                         long rows, long cols, double dx, double dy) {
    char *buf = NULL;
    FILE *fh = open_buffered(path, &buf);
    if (!fh)
        return -1;
    long jmax = rows - 2, imax = cols - 2;
    for (long j = 1; j <= jmax; j++) {
        double y = dy * (j - 0.5);
        for (long i = 1; i <= imax; i++) {
            double uc = (u[j * cols + i] + u[j * cols + i - 1]) / 2.0;
            double vc = (v[j * cols + i] + v[(j - 1) * cols + i]) / 2.0;
            double ln = __builtin_sqrt(uc * uc + vc * vc);
            fprintf(fh, "%.2f %.2f %f %f %f\n", dx * (i - 0.5), y, uc, vc, ln);
        }
    }
    return close_checked(fh, buf);
}

/* ---- VTK ---- */

struct PampiVtk {
    FILE *fh;
    char *buf;
    int binary;
};

PampiVtk *pampi_vtk_open(const char *path, const char *title, long imax,
                         long jmax, long kmax, double dx, double dy, double dz,
                         int binary) {
    PampiVtk *w = malloc(sizeof(*w));
    if (!w)
        return NULL;
    w->binary = binary;
    w->fh = open_buffered(path, &w->buf);
    if (!w->fh) {
        free(w);
        return NULL;
    }
    fprintf(w->fh, "# vtk DataFile Version 3.0\n");
    fprintf(w->fh, "%s\n", title);
    fprintf(w->fh, "%s\n", binary ? "BINARY" : "ASCII");
    fprintf(w->fh, "DATASET STRUCTURED_POINTS\n");
    fprintf(w->fh, "DIMENSIONS %ld %ld %ld\n", imax, jmax, kmax);
    fprintf(w->fh, "ORIGIN %f %f %f\n", dx * 0.5, dy * 0.5, dz * 0.5);
    fprintf(w->fh, "SPACING %f %f %f\n", dx, dy, dz);
    fprintf(w->fh, "POINT_DATA %ld\n", imax * jmax * kmax);
    return w;
}

/* big-endian IEEE-754 double on the wire (parity: vtkWriter.c floatSwap) */
static void put_be64(FILE *fh, double v) {
    uint64_t bits;
    memcpy(&bits, &v, 8);
    unsigned char be[8];
    for (int b = 0; b < 8; b++)
        be[b] = (unsigned char)(bits >> (56 - 8 * b));
    fwrite(be, 1, 8, fh);
}

int pampi_vtk_scalar(PampiVtk *w, const char *name, const double *s, long n) {
    fprintf(w->fh, "SCALARS %s double 1\n", name);
    fprintf(w->fh, "LOOKUP_TABLE default\n");
    if (w->binary) {
        for (long i = 0; i < n; i++)
            put_be64(w->fh, s[i]);
        fputc('\n', w->fh);
    } else {
        for (long i = 0; i < n; i++)
            fprintf(w->fh, "%f\n", s[i]);
    }
    return ferror(w->fh) ? -1 : 0;
}

int pampi_vtk_vector(PampiVtk *w, const char *name, const double *u,
                     const double *v, const double *wv, long n) {
    fprintf(w->fh, "VECTORS %s double\n", name);
    if (w->binary) {
        for (long i = 0; i < n; i++) {
            put_be64(w->fh, u[i]);
            put_be64(w->fh, v[i]);
            put_be64(w->fh, wv[i]);
        }
        fputc('\n', w->fh);
    } else {
        for (long i = 0; i < n; i++)
            fprintf(w->fh, "%f %f %f\n", u[i], v[i], wv[i]);
    }
    return ferror(w->fh) ? -1 : 0;
}

int pampi_vtk_close(PampiVtk *w) {
    int rc = close_checked(w->fh, w->buf);
    free(w);
    return rc;
}
