/* exe shim — the native L6 driver for the TPU backend.
 *
 * Capability parity with the reference's per-assignment main.c CLI
 * (`./exe-<TAG> <file.par>`, /root/reference/assignment-6/src/main.c:21-110;
 * `./exe <N> <iter>` for DMVM, assignment-3a/src/main.c:25-34), TPU-first:
 * the heavy lifting runs in the JAX process, and this shim is the native
 * front door the reference's bench harness conventions expect:
 *
 *   make && ./exe-JAX configs/dcavity.par
 *
 * It validates argv, parses + echoes the .par natively (config errors are
 * caught before a Python interpreter ever starts), exports the build-time
 * feature flags (VERBOSE/DEBUG — config.mk OPTIONS parity) as PAMPI_*
 * environment variables, and execs `$PAMPI_PYTHON -m pampi_tpu <args>`.
 *
 * Flags:
 *   --dry-run   parse + echo the config and exit (no Python, no TPU)
 *   --halo-test [ndims]  pass through to the halo-exchange debug dump
 */
#include <libgen.h>
#include <limits.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "pampi.h"

#ifndef PAMPI_PYTHON_DEFAULT
#define PAMPI_PYTHON_DEFAULT "python3"
#endif

static int is_number(const char *s) {
    if (!*s)
        return 0;
    for (; *s; s++)
        if (*s < '0' || *s > '9')
            return 0;
    return 1;
}

static void export_build_options(void) {
#ifdef VERBOSE
    setenv("PAMPI_VERBOSE", "1", 0);
#endif
#ifdef DEBUG
    setenv("PAMPI_DEBUG", "1", 0);
#endif
#ifdef CHECK
    setenv("PAMPI_CHECK", "1", 0);
#endif
}

int main(int argc, char **argv) {
    const char *python = getenv("PAMPI_PYTHON");
    if (!python || !*python)
        python = PAMPI_PYTHON_DEFAULT;

    int dry = 0;
    /* strip flags */
    int nargs = 0;
    char *args[8];
    for (int i = 1; i < argc && nargs < 4; i++) {
        if (strcmp(argv[i], "--dry-run") == 0)
            dry = 1;
        else
            args[nargs++] = argv[i];
    }

    if (nargs < 1) {
        printf("Usage: %s <configFile.par> | %s <N> <iter>\n", argv[0],
               argv[0]);
        return 0;
    }

    export_build_options();

    int halo = strcmp(args[0], "--halo-test") == 0;
    if (halo || is_number(args[0])) {
        /* pass-through modes: DMVM benchmark (./exe <N> <iter>) and the
         * halo-exchange debug dump (./exe --halo-test [ndims]) */
        if (dry) {
            if (halo)
                printf("halo-test ndims=%s\n", nargs > 1 ? args[1] : "2");
            else
                printf("DMVM N=%s iter=%s\n", args[0],
                       nargs > 1 ? args[1] : "?");
            return 0;
        }
        char *xargs[6] = {(char *)python, "-m", "pampi_tpu", args[0],
                          nargs > 1 ? args[1] : NULL, NULL};
        execvp(python, xargs);
        perror("execvp");
        return EXIT_FAILURE;
    }

    PampiParam p;
    pampi_param_init(&p);
    if (pampi_param_read(&p, args[0]) != 0)
        return EXIT_FAILURE;
    if (p.imax < 1 || p.jmax < 1 || (pampi_param_is3d(&p) && p.kmax < 1)) {
        fprintf(stderr, "Invalid grid in %s: imax=%ld jmax=%ld kmax=%ld\n",
                args[0], p.imax, p.jmax, p.kmax);
        return EXIT_FAILURE;
    }
    if (dry) {
        pampi_param_print(&p, stdout);
        return 0;
    }
    /* the Python driver echoes the config itself; avoid a double echo */
    char *xargs[5] = {(char *)python, "-m", "pampi_tpu", args[0], NULL};
    execvp(python, xargs);
    perror("execvp");
    return EXIT_FAILURE;
}
