# PAMPI-TPU top-level build — native runtime layer + exe shim.
#
# Interface parity with the reference's out-of-tree Make build
# (/root/reference/assignment-6/Makefile:9-34): objects land in
# build/$(TAG)/, `make TAG=<tag>` switches toolchains via include_<TAG>.mk,
# and the result is a runnable `./exe-$(TAG) <file.par>`. The compute path
# is the JAX process; this builds the native layer around it
# (native/src: parser, allocator, writers, shim).
#
# Targets:
#   make            exe-$(TAG) + build/$(TAG)/libpampi_native.so
#   make test       native smoke test (shim --dry-run on configs/)
#   make asm        assembly listings for the native sources (ref: `make asm`)
#   make format     clang-format the native sources, if available
#   make clean      remove build/$(TAG) and exe-$(TAG)
#   make distclean  remove build/ and all exes

include config.mk
include include_$(TAG).mk

BUILD := build/$(TAG)
SRC := native/src
LIBSRCS := $(SRC)/param.c $(SRC)/alloc.c $(SRC)/writers.c
LIBOBJS := $(patsubst $(SRC)/%.c,$(BUILD)/%.o,$(LIBSRCS))
SHIMOBJ := $(BUILD)/shim_main.o

CPPFLAGS := $(DEFINES) $(OPTIONS) -I$(SRC)

all: exe-$(TAG) $(BUILD)/libpampi_native.so

$(BUILD):
	mkdir -p $(BUILD)

$(BUILD)/%.o: $(SRC)/%.c $(SRC)/pampi.h | $(BUILD)
	$(CC) $(CFLAGS) $(CPPFLAGS) -c -o $@ $<

exe-$(TAG): $(SHIMOBJ) $(LIBOBJS)
	$(CC) $(CFLAGS) -o $@ $^ -lm

$(BUILD)/libpampi_native.so: $(LIBOBJS)
	$(CC) $(CFLAGS) -shared -o $@ $^ -lm

test: all
	./exe-$(TAG) --dry-run configs/poisson.par
	./exe-$(TAG) --dry-run configs/dcavity3d.par

asm: | $(BUILD)
	for f in $(LIBSRCS) $(SRC)/shim_main.c; do \
	  $(CC) $(CFLAGS) $(CPPFLAGS) -S -o $(BUILD)/$$(basename $$f .c).s $$f \
	    || exit 1; done
	@echo "listings in $(BUILD)/"

format:
	@command -v clang-format >/dev/null 2>&1 \
	  && clang-format -i $(SRC)/*.c $(SRC)/*.h \
	  || echo "clang-format not installed; skipping"

# Render a PAMPI_TELEMETRY flight record (utils/telemetry.py JSONL) into a
# human-readable run report; MERGE=<artifact.json> additionally folds the
# summary block into a BENCH/MULTICHIP artifact (merge-preserving).
#   make telemetry-report TELEMETRY=run.jsonl [MERGE=BENCH_r07.json]
TELEMETRY ?= telemetry.jsonl
telemetry-report:
	python tools/telemetry_report.py $(TELEMETRY) \
	  $(if $(MERGE),--merge $(MERGE))

check-artifacts:
	python tools/check_artifact.py

# Perf trend over the committed BENCH_r*.json artifacts + regression
# gate: renders the (metric, backend) trajectory table and fails when
# the newest point of any same-backend series regresses beyond the
# tolerance vs the best earlier point. Also runs as the `trend` pass of
# `make lint`.
bench-trend:
	python tools/bench_trend.py

# Device-time profiling smoke: a tiny instrumented dist-NS run with
# PAMPI_TELEMETRY + PAMPI_XPROF armed, trace ingestion, and the
# comm-hidden-fraction block — CPU-safe, proves the xprof plane
# end-to-end before any TPU time is spent.
profile-smoke:
	JAX_PLATFORMS=cpu python tools/profile_smoke.py

# tracecheck: the static contract checker (pampi_tpu/analysis/) — AST
# lint rules over pampi_tpu/ tools/ tests/, stencil halo footprints vs
# declared depths, the dispatch-matrix jaxpr contracts vs CONTRACTS.json,
# the collective-schedule census (comm) and Pallas kernel-resource
# checks (pallas), the precision-flow contracts (prec) and the
# committed-artifact schema lint. Regenerate the baseline
# (configs + comm + precision sections) after an INTENDED change with
# `make lint-update`. `make lint-comm` runs the comm contract alone —
# the overlap refactor's inner loop (one matrix trace, no AST/halo);
# `make lint-prec` is the mixed-precision twin.
lint:
	python tools/lint.py

lint-update:
	python tools/lint.py --update

lint-comm:
	python tools/lint.py --only comm

lint-prec:
	python tools/lint.py --only prec

# MG fused-cycle smoke (ISSUE 16): fused-vs-ladder V-cycle parity on
# 2-D/3-D × plain/obstacle (CPU interpret mode), the 2-launch /
# 1-launch (class) static pins, the ragged refusal reason, and the
# mg_launches_per_cycle telemetry/merge/lint round trip. rc 0 = the
# whole fused-cycle seam holds before any TPU time is spent.
mg-smoke:
	JAX_PLATFORMS=cpu python tools/mg_smoke.py

# K-fused chunk smoke (ISSUE 17): K=4-vs-historical parity on the dist
# family (jnp bitwise, fused at the ulp contract), the per-tier depth
# census (exactly 1 dcn capture exchange per field per 4 steps, ici
# unchanged, tier bytes == flat census), the launches-per-step < 3
# static pin, and the launches_per_step telemetry/merge/lint round
# trip. rc 0 = the whole K-fusion seam holds before any TPU time.
chunk-smoke:
	JAX_PLATFORMS=cpu python tools/chunk_smoke.py

# The full mg-fused test file INCLUDING the slow-marked cases (3-D
# parity, the class-lane-vs-solo and rung-invariance contracts, the
# FFT coarse correction — tier-1 carries one cheap representative per
# axis to hold its 870 s window; this target is the complete matrix).
mg-suite:
	JAX_PLATFORMS=cpu python -m pytest tests/test_mg_fused.py -q

# Fleet smoke: a tiny mixed scenario queue through the whole serving
# stack on CPU (enqueue -> bucket -> batch -> per-scenario artifacts),
# with a drift gate — fails if any lane's result differs from its solo
# oracle — plus the fleet telemetry/merge/lint round trip and the
# fleet_scenarios_per_s throughput metric.
fleet-smoke:
	JAX_PLATFORMS=cpu python tools/fleet_smoke.py

# Serve smoke (serving v2/v3): the persistent daemon on CPU over a temp
# file-queue — three shape classes (6 distinct grids incl. a 3-D rung,
# at most ONE compile per class), a mid-run lane swap-in, one diverged
# lane isolated, one class-ineligible request with its refusal reason
# in the dispatch record, one malformed .par parked with a warning
# record, the live status endpoint, and the telemetry/merge/lint round
# trip. rc 0 = clean shutdown.
serve-smoke:
	JAX_PLATFORMS=cpu python tools/serve_smoke.py

# Soak smoke (serving observability, ISSUE 18): a synthetic mixed-grid /
# mixed-family / mixed-tenant request stream in waves through the daemon
# with SLO targets armed — injected divergences + a malformed .par —
# sampling the queue-depth/latency trajectory per poll. Asserts the
# request-trace decomposition closes (median request's stage sum ==
# its end-to-end latency within 5%), the registry/slo/trace blocks lint
# clean, and the Prometheus scrape file carries the latency histogram.
soak-smoke:
	JAX_PLATFORMS=cpu python tools/soak.py

# Chaos smoke (autopilot, ISSUE 19): the scripted storm through the
# self-healing elastic control plane — injected rank death at poll 3
# (auto shrink_resume, fault ledger carried), a sustained synthetic SLO
# burn (exactly one hysteresis-banded regrow, checkpoint-fenced, then
# the degradation ladder down to shedding and monotonically back up),
# and a high-priority preemption whose parked victim resumes bitwise.
# Asserts zero flaps, a monotone recorded rung sequence, two bitwise
# parity contracts (healed resident vs clean restore; preempted-run
# fields vs a flat run), and the autoscale/chaos_trajectory artifact
# blocks linting clean. rc 0 = the whole story holds.
chaos-smoke:
	JAX_PLATFORMS=cpu python tools/chaos_smoke.py

# The full fleet test file INCLUDING the slow-marked parity cases
# (fused / 3-D-dist vmap batches — tier-1 carries one representative
# per axis to hold its 870 s window; this target is the complete
# batch-of-N == N-solo matrix, all four families x jnp/fused).
fleet-suite:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q

# Standalone run of the fault-injection / recovery suite (PAMPI_FAULTS
# plane, retry budgets, rollback-recovery, checkpoint durability edges,
# the PR 10 coordinator protocol — tests/test_coordinator.py carries
# the simulated 4-rank chunk-boundary smoke plus the PR 12 dead-rank
# matrix (death at the boundary, hang past the watchdog, double-death,
# death during rollback, shrink-resume bitwise parity, ledger probation
# persistence) — the elastic-restore matrix in tests/test_checkpoint.py,
# and tests/test_multihost.py (the real kill-a-process acceptance cases;
# capability-gated, so on this container they SKIP with the gloo reason
# and on real hardware they are the gate).
# The same tests ride tier-1 at 16-squared size; this target is the quick
# focused loop while touching the recovery layer.
fault-suite:
	JAX_PLATFORMS=cpu python -m pytest tests/test_faultinject.py \
	  tests/test_driver.py tests/test_checkpoint.py \
	  tests/test_coordinator.py tests/test_multihost.py -q

# Dead-rank survival smoke (PR 12): a 2-virtual-rank lockstep run with
# an agreed elastic checkpoint cadence; rank 1 is killed at chunk 5 and
# the survivor must (a) raise the structured RankDeadError naming it,
# (b) shrink-resume from the newest agreed elastic generation, and
# (c) finish bitwise-identical to a clean shrunk-mesh run restored from
# the same generation. The quick loop while touching the dead-rank
# protocol; the pytest twins ride fault-suite/tier-1.
dead-rank-smoke:
	JAX_PLATFORMS=cpu python tools/dead_rank_smoke.py

# Offline checkpoint verifier (both formats: elastic manifest + shards,
# legacy single-.npz): generation, writing mesh, per-field CRC status.
# SURVIVORS=<N> additionally checks the set is restorable onto an
# N-rank survivor mesh (full shard coverage + fault ledger present —
# the dead-rank shrink-resume pre-flight).
#   make ckpt-fsck CKPT=ck.npz [SURVIVORS=4]
CKPT ?= ckpt.npz
ckpt-fsck:
	python tools/ckpt_fsck.py $(if $(SURVIVORS),--survivors $(SURVIVORS)) \
	  $(CKPT)

clean:
	rm -rf $(BUILD) exe-$(TAG)

distclean:
	rm -rf build exe-*

.PHONY: all test asm format telemetry-report check-artifacts bench-trend \
	profile-smoke mg-smoke chunk-smoke mg-suite fleet-smoke serve-smoke \
	soak-smoke chaos-smoke \
	fleet-suite \
	lint \
	lint-update lint-comm lint-prec \
	fault-suite dead-rank-smoke ckpt-fsck clean distclean
