# PAMPI-TPU build configuration (capability parity with the reference's
# config.mk switchboard, /root/reference/assignment-6/config.mk:72-84, with
# the TPU backend in place of the MPI toolchain matrix).

# Backend/toolchain tag: JAX (TPU backend via the python driver) or GCC
# (native lib + shim only, no backend default). include_<TAG>.mk supplies
# the toolchain specifics.
TAG ?= JAX

# Feature switches (≙ ENABLE_MPI/ENABLE_OPENMP): the TPU equivalents are
# runtime .par keys (tpu_mesh, tpu_dtype); build-time switches below control
# the native layer only.
#
# OPTIONS become -D defines in the native shim and PAMPI_* env vars for the
# JAX process (≙ config.mk OPTIONS VERBOSE/DEBUG/...).
#OPTIONS += -DVERBOSE
#OPTIONS += -DDEBUG

# Host array alignment for pampi_allocate callers
OPTIONS += -DARRAY_ALIGNMENT=64
