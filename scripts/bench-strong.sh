#!/bin/bash
# Strong-scaling sweep: FIXED problem (default configs/poisson8192.par),
# growing device mesh — BASELINE.json config 5 and the TPU analog of the
# reference's rank-scaling studies. Emits CSV `Ranks,N,Iterations,Time`.
# Virtual CPU mesh by default (the framework's "multi-node without a
# cluster"); on a real slice run each row with the ambient platform.
#
# Usage: scripts/bench-strong.sh [outfile.csv] [par-file] [mesh sizes...]
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-bench-strong.csv}
PAR=${2:-$REPO/configs/poisson8192.par}
shift 2 2>/dev/null || shift $# 2>/dev/null || true
MESHES=${@:-"1 2 4 8"}
N=$(grep -E "^imax" "$PAR" | awk '{print $2}')

# PYTHONPATH is deliberately REPLACED (an inherited sitecustomize can
# force-register an accelerator plugin and defeat the cpu virtual mesh);
# extra import roots go in PAMPI_PYTHONPATH.
echo "Ranks,N,Iterations,Time" > "$OUT"
# PAMPI_PLATFORM=axon (or tpu) runs rows on the ambient accelerator
# instead of the virtual CPU mesh — then R must match the real device count.
for R in $MESHES; do
    if ! out=$(JAX_PLATFORMS="${PAMPI_PLATFORM:-cpu}" \
          PYTHONPATH="$REPO${PAMPI_PYTHONPATH:+:$PAMPI_PYTHONPATH}" \
          XLA_FLAGS="--xla_force_host_platform_device_count=$R" \
          python -m pampi_tpu "$PAR"); then
        echo "R=$R failed" >&2; continue
    fi
    row=$(echo "$out" | tail -1)
    it=$(echo "$row" | awk '{print $1}')
    tm=$(echo "$row" | awk '{print $3}' | tr -d 's')
    echo "$R,$N,$it,$tm" >> "$OUT"
done
cat "$OUT"
