#!/bin/bash
# DMVM scaling sweep: mesh sizes x the FULL (N,NITER) grid — harness parity
# with the reference's internode sweep (/root/reference/assignment-3a/
# "bash scripts"/bench-cluster.sh: ranks {72,144,216,288} x
# (N,NITER) in {(1000,1e6),(4000,1e5),(10000,1e4),(20000,5e3)}, SLURM on 4
# nodes). TPU-first, the "nodes" axis is the device-mesh axis: each row runs
# the ppermute ring matvec over an R-device mesh. Without a multi-chip slice
# this uses the virtual CPU mesh (the framework's standard "multi-node
# without a cluster"); on a real slice drop JAX_PLATFORMS/XLA_FLAGS and R
# rides ICI. Iterations are divided by SCALE (default 1000) to keep each
# point in seconds; MFLOP/s is iteration-count invariant.
#
# Usage: scripts/bench-cluster.sh [outfile.csv] [SCALE] [mesh sizes...]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench-cluster.csv}
SCALE=${2:-1000}
shift 2 2>/dev/null || shift $# 2>/dev/null || true
MESHES=${@:-"2 4 8"}

# PYTHONPATH is deliberately REPLACED, not extended: an inherited entry may
# carry a sitecustomize that force-registers an accelerator plugin, which
# defeats the JAX_PLATFORMS=cpu virtual mesh. Extra import roots go in
# PAMPI_PYTHONPATH.
echo "Ranks,NITER,N,MFlops,Time" > "$OUT"
for R in $MESHES; do
    for NI in "1000 1000000" "4000 100000" "10000 10000" "20000 5000"; do
        set -- $NI
        N=$1
        ITER=$(( $2 / SCALE ))
        [ "$ITER" -lt 1 ] && ITER=1
        PAMPI_CSV="$OUT" JAX_PLATFORMS=cpu \
            PYTHONPATH="$PWD${PAMPI_PYTHONPATH:+:$PAMPI_PYTHONPATH}" \
            XLA_FLAGS="--xla_force_host_platform_device_count=$R" \
            python -m pampi_tpu "$N" "$ITER" || echo "R=$R N=$N failed" >&2
    done
done
cat "$OUT"
