#!/bin/bash
# Per-region counter sweep over the headline configs — the ≙ of the
# reference's perl likwid-mpirun harnesses (assignment-3a/perl
# scripts/bench-node.pl:17-27): one counter CSV per config, each region a
# separately-timed device kernel (tools/bench_regions.py).
#
# Usage: scripts/bench-regions.sh [outdir]   (default results/regions)
# Run on the real chip for the production numbers; runs anywhere.
set -eu
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-"$REPO/results/regions"}
mkdir -p "$OUT"

run() { # run <tag> <par-file>
    echo "== $1 ($2)"
    PAMPI_PROFILE=1 PAMPI_PROFILE_CSV="$OUT/$1.csv" \
        python "$REPO/tools/bench_regions.py" "$2"
}

run poisson8192   "$REPO/configs/poisson8192.par"   # 8192^2 strong-scaling grid
run dcavity256    "$REPO/configs/dcavity256.par"
run dcavity3d128  "$REPO/configs/dcavity3d.par"
run canal3d       "$REPO/configs/canal3d.par"

echo "CSVs in $OUT:"
ls -l "$OUT"
