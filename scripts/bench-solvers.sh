#!/bin/bash
# Solver wall-clock table — parity with the reference's per-assignment
# timing prints ("Walltime %.2fs", assignment-4/src/main.c:38; "Solution
# took %.2fs", assignment-5/sequential/src/main.c:63, assignment-6/src/
# main.c:73) gathered into one CSV. Runs each committed .par config through
# the driver on whatever backend jax selects (TPU chip if present; set
# JAX_PLATFORMS=cpu PYTHONPATH=$PWD to force host CPU).
#
# Usage: scripts/bench-solvers.sh [outfile.csv] [config ...]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench-solvers.csv}
shift 2>/dev/null || true
CONFIGS=${*:-"configs/poisson.par configs/dcavity.par configs/canal.par"}
EXE="./exe-JAX"
[ -x "$EXE" ] || EXE="python -m pampi_tpu"

echo "Config,Walltime" > "$OUT"
for cfg in $CONFIGS; do
    t=$($EXE "$cfg" | sed -n 's/.*\(Walltime\|Solution took\) \([0-9.]*\)s.*/\2/p' | tail -1)
    echo "$(basename "$cfg" .par),${t:-FAIL}" >> "$OUT"
done
cat "$OUT"
