#!/bin/bash
# DMVM fine-grained device sweep 1..K within one host — harness parity with
# the reference's memory-domain sweep (/root/reference/assignment-3a/
# "bash scripts"/bench-memdomain.sh: ranks 1..18 inside one 18-core memory
# domain, likwid-pinned). The TPU analog of "one memory domain" is the
# single-host device set: sweep every mesh size 1..K and watch where ring
# bandwidth saturates. Virtual CPU mesh by default; on a real slice drop
# JAX_PLATFORMS/XLA_FLAGS.
#
# Usage: scripts/bench-memdomain.sh [outfile.csv] [K] [N] [ITER]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench-memdomain.csv}
K=${2:-8}
N=${3:-4000}
ITER=${4:-100}

# PYTHONPATH is deliberately REPLACED, not extended: an inherited entry may
# carry a sitecustomize that force-registers an accelerator plugin, which
# defeats the JAX_PLATFORMS=cpu virtual mesh. Extra import roots go in
# PAMPI_PYTHONPATH.
echo "Ranks,NITER,N,MFlops,Time" > "$OUT"
R=1
while [ "$R" -le "$K" ]; do
    PAMPI_CSV="$OUT" JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PAMPI_PYTHONPATH:+:$PAMPI_PYTHONPATH}" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$R" \
        python -m pampi_tpu "$N" "$ITER" || echo "R=$R failed" >&2
    R=$(( R + 1 ))
done
cat "$OUT"
