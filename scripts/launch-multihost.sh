#!/bin/bash
# Multi-process launcher — the ≙ of `mpirun -n N ./exe-<TAG> <args>` (how the
# reference exercises multi-node locally: oversubscribed mpirun, SURVEY.md §4).
# Starts N python processes that join one jax.distributed process group via
# the PAMPI_COORDINATOR/PAMPI_NPROCS/PAMPI_PROC_ID triple
# (pampi_tpu/parallel/multihost.py); the device mesh then spans all
# processes and the solvers run unchanged.
#
# Local testing (no pod): PAMPI_LOCAL_DEVICES=K gives each process K virtual
# CPU devices, so `PAMPI_LOCAL_DEVICES=2 launch-multihost.sh 2 foo.par` runs
# the same 4-device mesh the tests fake in one process. On a real multi-host
# slice, run this once per host with the GLOBAL layout pinned:
#   PAMPI_COORDINATOR=<host0>:<port>          same on every host
#   PAMPI_TOTAL_PROCS=<hosts * procs_per_host> global process count
#   PAMPI_PROC_OFFSET=<host_rank * procs_per_host>
#   N=<procs on this host>
# or set PAMPI_MULTIHOST=auto per process and let the cloud runtime wire
# jax.distributed.initialize itself.
#
# Usage: [PAMPI_LOCAL_DEVICES=K] scripts/launch-multihost.sh N <cli args...>
set -u
# stay in the CALLER's directory (outputs and logs land there, like mpirun);
# the repo root is only needed as an import root
REPO=$(cd "$(dirname "$0")/.." && pwd)
[ $# -ge 2 ] || { echo "usage: launch-multihost.sh N <cli args...>" >&2; exit 2; }
N=$1; shift

# Coordinator port: take a flock on a per-port lockfile and HOLD it for the
# script's lifetime (fd 9), so concurrent launches on one host can never pick
# the same port (bind-and-release alone is a TOCTOU race). The bind probe
# only filters ports busied by unrelated processes.
# Base port/range overridable for operators who must move off the
# contended default (29500 is also torch.distributed's well-known default):
# PAMPI_PORT_BASE=<port> [PAMPI_PORT_RANGE=<n>] (round-2 advisor finding)
PORT_BASE=${PAMPI_PORT_BASE:-29500}
PORT_RANGE=${PAMPI_PORT_RANGE:-64}
if [ -z "${PAMPI_COORDINATOR:-}" ]; then
    if command -v flock >/dev/null 2>&1; then
        PORT=""
        for slot in $(seq 0 $(( PORT_RANGE - 1 ))); do
            CAND=$(( PORT_BASE + slot ))
            exec 9> "${TMPDIR:-/tmp}/pampi-port-$CAND.lock"
            if flock -n 9 && python -c "import socket; s=socket.socket(); s.bind(('127.0.0.1',$CAND)); s.close()" 2>/dev/null; then
                PORT=$CAND; break
            fi
            exec 9>&-
        done
        [ -n "$PORT" ] || { echo "launch-multihost.sh: no free coordinator port in $PORT_BASE-$(( PORT_BASE + PORT_RANGE - 1 )) (override with PAMPI_PORT_BASE/PAMPI_PORT_RANGE)" >&2; exit 1; }
    else
        # no flock on this host: fall back to bind-and-release (racy only
        # against concurrent launches in the same instant)
        PORT=$(python -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')
    fi
fi
COORD=${PAMPI_COORDINATOR:-127.0.0.1:$PORT}
OFFSET=${PAMPI_PROC_OFFSET:-0}
TOTAL=${PAMPI_TOTAL_PROCS:-$N}   # global count; defaults to single-host N

# PYTHONPATH is deliberately REPLACED for virtual-CPU runs (an inherited
# sitecustomize can force-register an accelerator plugin and defeat
# JAX_PLATFORMS=cpu); extra import roots go in PAMPI_PYTHONPATH.
PIDS=()
for p in $(seq 0 $(( N - 1 ))); do
    if [ -n "${PAMPI_LOCAL_DEVICES:-}" ]; then
        env PAMPI_COORDINATOR="$COORD" PAMPI_NPROCS="$TOTAL" \
            PAMPI_PROC_ID=$(( OFFSET + p )) \
            JAX_PLATFORMS=cpu \
            XLA_FLAGS="--xla_force_host_platform_device_count=$PAMPI_LOCAL_DEVICES" \
            PYTHONPATH="$REPO${PAMPI_PYTHONPATH:+:$PAMPI_PYTHONPATH}" \
            python -m pampi_tpu "$@" > "multihost-r$(( OFFSET + p )).log" 2>&1 &
    else
        env PAMPI_COORDINATOR="$COORD" PAMPI_NPROCS="$TOTAL" \
            PAMPI_PROC_ID=$(( OFFSET + p )) \
            PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
            python -m pampi_tpu "$@" > "multihost-r$(( OFFSET + p )).log" 2>&1 &
    fi
    PIDS+=($!)
done

FAIL=0
for p in $(seq 0 $(( N - 1 ))); do
    wait "${PIDS[$p]}" || { FAIL=1; echo "rank $(( OFFSET + p )) FAILED (multihost-r$(( OFFSET + p )).log):" >&2
                            tail -5 "multihost-r$(( OFFSET + p )).log" >&2; }
done
cat "multihost-r$OFFSET.log"
exit $FAIL
