#!/bin/bash
# DMVM ring-scaling sweep over mesh sizes — the TPU-native analog of the
# reference's rank sweeps (/root/reference/assignment-3a/"bash scripts"/
# bench-cluster.sh: ranks 72..288; bench-memdomain.sh: 1..18). Without a
# multi-chip slice this drives the ring matvec over an R-device VIRTUAL CPU
# mesh (XLA_FLAGS=--xla_force_host_platform_device_count=R — the framework's
# standard "multi-node without a cluster", SURVEY.md S4), exercising the real
# ppermute ring; on a real slice drop JAX_PLATFORMS/XLA_FLAGS and the same
# rows come from ICI. CSV schema matches the reference harness.
#
# Usage: scripts/bench-mesh.sh [outfile.csv] [N] [ITER]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench-mesh.csv}
N=${2:-4000}
ITER=${3:-100}

# PYTHONPATH is deliberately REPLACED, not extended: an inherited entry may
# carry a sitecustomize that force-registers an accelerator plugin, which
# defeats the JAX_PLATFORMS=cpu virtual mesh. Extra import roots go in
# PAMPI_PYTHONPATH.
echo "Ranks,NITER,N,MFlops,Time" > "$OUT"
for R in 1 2 4 8; do
    PAMPI_CSV="$OUT" JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PAMPI_PYTHONPATH:+:$PAMPI_PYTHONPATH}" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$R" \
        python -m pampi_tpu "$N" "$ITER" || echo "R=$R failed" >&2
done
cat "$OUT"
