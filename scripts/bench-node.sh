#!/bin/bash
# DMVM throughput sweep on the local accelerator — harness parity with the
# reference's single-node SLURM sweep (/root/reference/assignment-3a/
# "bash scripts"/bench-node.sh: CSV header `Ranks,NITER,N,MFlops,Time`, sweep
# grid (N,iter) in {1000,4000,10000,20000} x {1e6,1e5,1e4,5e3}), TPU-first:
# one chip replaces a node, and the rank sweep becomes the mesh sweep in
# bench-mesh.sh. Iterations are divided by SCALE (default 100) to keep the
# wall clock per point in seconds; MFLOP/s is iteration-count invariant.
#
# Usage: scripts/bench-node.sh [outfile.csv] [SCALE]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-bench-node.csv}
SCALE=${2:-100}
EXE="./exe-JAX"
[ -x "$EXE" ] || EXE="python -m pampi_tpu"

echo "Ranks,NITER,N,MFlops,Time" > "$OUT"
for NI in "1000 1000000" "4000 100000" "10000 10000" "20000 5000"; do
    set -- $NI
    N=$1
    ITER=$(( $2 / SCALE ))
    [ "$ITER" -lt 1 ] && ITER=1
    PAMPI_CSV="$OUT" $EXE "$N" "$ITER" || echo "N=$N failed" >&2
done
cat "$OUT"
