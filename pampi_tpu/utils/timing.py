"""Monotonic wall-clock timing (parity: assignment-4/src/timing.c:60-72).

The reference wraps CLOCK_MONOTONIC; Python's time.monotonic() is the same
clock. MPI mains use MPI_Wtime — also monotonic wall-clock.
"""

import time


def get_timestamp() -> float:
    return time.monotonic()


def get_time_resolution() -> float:
    return time.get_clock_info("monotonic").resolution


class Timer:
    """Context-manager convenience over get_timestamp()."""

    def __enter__(self):
        self.start = get_timestamp()
        return self

    def __exit__(self, *exc):
        self.elapsed = get_timestamp() - self.start
        return False
