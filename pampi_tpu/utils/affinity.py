"""Host-process CPU affinity (parity with the reference's L1 toolbox module
`assignment-4/src/affinity.c:34-61`: affinity_getProcessorId /
affinity_pinProcess / affinity_pinThread).

TPU-first framing: XLA owns the accelerator cores, so pinning governs the
HOST side only — the Python process that parses configs, dispatches jitted
steps, and writes output. That is also faithful to the reference, where the
module is plumbing no solver ever calls (SURVEY.md §1 L1). The reference
compiles to nothing outside `__linux__ && _OPENMP`; here every function is a
no-op (returning -1 where a value is expected) on platforms without
`os.sched_setaffinity`.
"""

from __future__ import annotations

import os
import threading

_HAVE_SCHED = hasattr(os, "sched_setaffinity")


def get_processor_id() -> int:
    """Lowest CPU in the calling thread's affinity mask — the reference's
    first-set-bit scan (affinity.c:19-31, getProcessorID), not the CPU the
    thread happens to be running on this instant."""
    if not _HAVE_SCHED:
        return -1
    mask = os.sched_getaffinity(0)
    return min(mask) if mask else -1


def pin_process(processor_id: int) -> bool:
    """≙ affinity_pinProcess: sched_setaffinity on pid 0, which on Linux pins
    the CALLING thread (threads already running — e.g. XLA's host threadpool —
    keep their masks; new threads inherit). The reference call has the same
    kernel semantics. Returns False on unsupported platforms or invalid ids
    instead of the reference's silent syscall failure."""
    if not _HAVE_SCHED:
        return False
    try:
        os.sched_setaffinity(0, {processor_id})
        return True
    except (OSError, ValueError):
        return False


def pin_thread(processor_id: int) -> bool:
    """Pin the CALLING thread only (≙ affinity_pinThread,
    pthread_setaffinity_np on pthread_self). Python exposes per-thread
    affinity through the thread's native TID."""
    if not _HAVE_SCHED:
        return False
    try:
        os.sched_setaffinity(threading.get_native_id(), {processor_id})
        return True
    except (OSError, ValueError):
        return False
