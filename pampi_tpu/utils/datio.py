"""Gnuplot-compatible `.dat` writers.

Format parity with the reference's L3 output layer:
 - write_matrix: the Poisson `p.dat` layout — full array incl. ghost layers,
   `%f ` per value, one row per j (assignment-4/src/solver.c:301-322).
 - write_pressure / write_velocity: the NS-2D `pressure.dat` / `velocity.dat`
   layouts at cell centers, with staggered->center averaging for velocity
   (assignment-5/sequential/src/solver.c:457-505). Compatible with the
   committed `surface.plot` / `vector.plot` gnuplot scripts.
"""

from __future__ import annotations

import numpy as np


def write_matrix(p, path: str) -> None:
    """Write the full (jmax+2, imax+2) array, `%f `-formatted, row per j."""
    arr = np.asarray(p, dtype=np.float64)
    from . import native

    if native.write_matrix(path, arr):
        return
    with open(path, "w") as fh:
        for row in arr:
            fh.write("".join("%f " % v for v in row))
            fh.write("\n")


def read_matrix(path: str) -> np.ndarray:
    return np.loadtxt(path)


def write_pressure(p, dx: float, dy: float, path: str) -> None:
    """x y p triples at cell centers, blank line between j-rows (gnuplot splot)."""
    arr = np.asarray(p, dtype=np.float64)
    from . import native

    if native.write_pressure(path, arr, dx, dy):
        return
    jmax, imax = arr.shape[0] - 2, arr.shape[1] - 2
    with open(path, "w") as fh:
        for j in range(1, jmax + 1):
            y = (j - 0.5) * dy
            for i in range(1, imax + 1):
                x = (i - 0.5) * dx
                fh.write("%.2f %.2f %f\n" % (x, y, arr[j, i]))
            fh.write("\n")


def write_velocity(u, v, dx: float, dy: float, path: str) -> None:
    """x y u v |vel| at cell centers; u,v averaged from staggered faces."""
    ua = np.asarray(u, dtype=np.float64)
    va = np.asarray(v, dtype=np.float64)
    from . import native

    if native.write_velocity(path, ua, va, dx, dy):
        return
    jmax, imax = ua.shape[0] - 2, ua.shape[1] - 2
    with open(path, "w") as fh:
        for j in range(1, jmax + 1):
            y = dy * (j - 0.5)
            for i in range(1, imax + 1):
                x = dx * (i - 0.5)
                uc = (ua[j, i] + ua[j, i - 1]) / 2.0
                vc = (va[j, i] + va[j - 1, i]) / 2.0
                ln = np.sqrt(uc * uc + vc * vc)
                fh.write("%.2f %.2f %f %f %f\n" % (x, y, uc, vc, ln))


def read_pressure(path: str) -> np.ndarray:
    """Read a pressure.dat back into an (jmax, imax) interior array."""
    rows = []
    block = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                if block:
                    rows.append([v for _, _, v in block])
                    block = []
                continue
            x, y, v = line.split()
            block.append((float(x), float(y), float(v)))
    if block:
        rows.append([v for _, _, v in block])
    return np.array(rows)


def read_velocity(path: str):
    """Read velocity.dat -> (u_center, v_center) arrays of shape (jmax, imax).

    imax is inferred from where x resets to the start of a new j-row (x is
    non-decreasing within a row even under %.2f rounding collisions)."""
    data = np.loadtxt(path)
    x = data[:, 0]
    resets = np.where(np.diff(x) < 0)[0]
    imax = int(resets[0]) + 1 if len(resets) else data.shape[0]
    jmax = data.shape[0] // imax
    u = data[:, 2].reshape(jmax, imax)
    v = data[:, 3].reshape(jmax, imax)
    return u, v
