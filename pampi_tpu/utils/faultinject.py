"""Deterministic fault injection: the proof plane for the recovery layer.

`PAMPI_FAULTS=<spec>` (TEST-ONLY — never set it on a production run) arms
deterministic faults at named trigger points, so the retry/rollback
machinery in `models/_driver.py` and `utils/checkpoint.py` can be exercised
end-to-end instead of waiting for real hardware to misbehave. The switch
follows the `utils/flags.py` convention: unset means every hook below is a
no-op, traced programs are byte-identical to the uninjected build, and the
drive loop takes the exact historical path (test-asserted in
tests/test_faultinject.py, the same contract as `PAMPI_TELEMETRY`).

Spec grammar — comma-separated clauses, each
`kind@site<N>[:field][@rank<R>][*count]`:

  pallas@chunk<N>         forged pallas runtime failure on the Nth chunk
                          dispatch (exercises the pallas->jnp rebuild)
  transient@chunk<N>      forged `UNAVAILABLE` device fault on the Nth
                          dispatch (exercises the transient retry budget;
                          repeat the clause with different N for spaced /
                          back-to-back transients)
  nan@step<N>:<field>     trace-time NaN corruption of solver field
                          u|v|w|p at step N (exercises the PR 3 in-band
                          divergence sentinel end-to-end)
  inf@step<N>:<field>     same, +inf

  dead@chunk<N>           the rank STOPS ANSWERING at its Nth chunk
                          dispatch (raises InjectedRankDeath before the
                          dispatch): under a coordinator the peers see a
                          missing fault word — the watchdog + membership
                          agreement round (parallel/coordinator.py) must
                          turn it into a structured RankDeadError instead
                          of a hang. Usually rank-targeted
                          (`dead@chunk<N>@rank<R>`); untargeted it kills
                          every rank.
  hang@chunk<N>           the rank SLEEPS past the watchdog at its Nth
                          dispatch (PAMPI_FAULT_HANG_S seconds, default
                          30 — set it above tpu_coord_timeout), then
                          dies: the mid-dispatch death shape, where a
                          peer is left waiting on the agreement round
                          rather than told. Same @rank targeting.

Chunk and step clauses take an optional `@rank<R>` suffix (PR 10): the
clause fires only on rank R — `jax.process_index()` under a real
multi-process launch, or the ambient virtual rank inside a
`rank_scope(R)` block (the coordinator lockstep simulation,
parallel/coordinator.py). `transient@chunk2@rank1` forges the fault on
rank 1's second dispatch only; the other ranks learn of it through the
coordinator's agreed fault word, which is the protocol under test. A
rank-suffixed clause on a non-matching rank neither fires nor consumes
its charge (the take_lane_faults convention). Rank-targeted FIELD
faults (`nan@step<N>:<field>@rank<R>`) are for the per-rank solver
builds of the SIMULATION path and single-controller runs: under a real
multi-process launch every process must trace the same SPMD program, so
baking a corruption into one rank's trace would itself desynchronize
the job — use the host-side chunk clauses there. The fault sites the
protocol never coordinates (lane/write/emit) refuse the suffix loudly.
  nan@lane<K>:<field>     host-side NaN corruption of scenario lane K's
                          field in a FLEET batch's initial state
                          (pampi_tpu/fleet/batch.py; 0-based lane index;
                          exercises diverged-lane isolation — the lane
                          freezes, batchmates must stay bitwise). Solo
                          runs never consult lane clauses.
  inf@lane<K>:<field>     same, +inf
  ckpt_torn@write<N>      forged crash mid-`np.savez` on the Nth checkpoint
                          write — a torn `.tmp` is left behind (proves the
                          atomic-rename protocol never corrupts the live file)
  ckpt_corrupt@write<N>   flip bytes in the primary checkpoint after the
                          Nth successful write (exercises CRC rejection +
                          the `.prev` generation fallback)
  telemetry@emit<N>       OSError on the Nth telemetry record write
                          (exercises the warn-once stand-down)

Daemon-plane clauses (PR 19) fire at the serving daemon's poll cycle —
the autopilot's `pre_poll` hook (fleet/autopilot.py) bumps the `poll`
counter once per `poll_once` and consumes whatever is armed; with the
autopilot off the hook is never called and the clauses stay inert:

  dead@poll<N>            the resident elastic job's rank dies at the
                          daemon's Nth poll (raises InjectedRankDeath
                          from the hook): the autopilot — not an
                          operator — must turn it into `shrink_resume`
                          onto survivor capacity, fault ledger carried.
  burst@poll<N>:<tenant>*<count>
                          synthetic SLO burn: <count> violating
                          observations (10x the tenant's target) folded
                          into the tenant's sliding window at poll N —
                          the hysteresis-banded grow/degrade plane's
                          deterministic fuel. The :<field> slot carries
                          the TENANT name here; *<count> is the
                          observation count (default 1), not a re-arm.
  slow_lane@poll<N>:<tenant>*<count>
                          same injection shape, but folded into the
                          per-class latency histograms as well — the
                          per-class-p95 policy input moves too.

Field-corruption clauses (`nan`/`inf`) are consumed by SOLVER GENERATIONS
(one take in __init__, one per recovery `_rebuild_chunk` — a pallas->jnp
fallback rebuild keeps the current generation): each clause arms `count`
generations (default 1, `*R` re-arms R), and a take spends one charge. A
rollback-recovery rebuild therefore re-drives CLEAN once the clause is
spent — the deterministic shape the recovery tests need (and `*99` makes
the corruption persistent, the recovery-exhaustion shape). Host-side
counters (chunk dispatches, checkpoint writes, telemetry records) are
process-global and 1-based; tests call `reset()` between runs.
"""

from __future__ import annotations

import os
import re

_FIELDS = ("u", "v", "w", "p")
_KIND_SITE = {
    "pallas": ("chunk",),
    "transient": ("chunk",),
    "dead": ("chunk", "poll"),
    "hang": ("chunk",),
    "nan": ("step", "lane"),
    "inf": ("step", "lane"),
    "ckpt_torn": ("write",),
    "ckpt_corrupt": ("write",),
    "telemetry": ("emit",),
    "burst": ("poll",),
    "slow_lane": ("poll",),
}

# the :<field> slot is a solver field (single letter) for nan/inf and a
# TENANT name for the daemon-plane burst/slow_lane clauses, so the group
# is a word, not a char; per-kind validation below keeps nan/inf pinned
# to u|v|w|p exactly as before
_CLAUSE_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<site>[a-z]+)(?P<n>\d+)"
    r"(?::(?P<field>[a-z][a-z0-9_]*))?(?:@rank(?P<rank>\d+))?"
    r"(?:\*(?P<count>\d+))?$"
)

# the sites a rank-targeted clause makes sense at: host-side chunk
# dispatches (each process/virtual rank counts its own) and per-rank
# solver-build field corruption. Writes/emits/lanes are rank-0-only or
# batch-level concerns — a rank suffix there is a broken spec.
_RANKABLE_SITES = ("chunk", "step")


class FaultSpecError(ValueError):
    """Unparseable PAMPI_FAULTS spec — fail loudly at the first hook, not
    silently run the uninjected program a test believes is injected."""


class InjectedPallasError(RuntimeError):
    """Forged pallas runtime failure (`pallas@chunk<N>`): NOT transient, so
    the drive loop routes it to the pallas->jnp rebuild hook, and a run
    with no jnp alternative terminates with this diagnostic."""


class JaxRuntimeError(Exception):
    """Name-alike of jax's runtime error for `transient@chunk<N>`:
    `_driver._is_transient_device_fault` matches on the type NAME plus
    `UNAVAILABLE` in the message, so the forged fault takes exactly the
    real transient's retry path without touching jax internals."""


class InjectedRankDeath(BaseException):
    """Forged rank death (`dead@chunk<N>` / `hang@chunk<N>`): the rank
    stops producing fault words. Deliberately NOT an Exception: the drive
    loops' fault-classification funnels catch Exception, and a death must
    never be classified as a transient or a pallas fault — it either
    surfaces to the lockstep simulation's watchdog collector (which turns
    it into the survivors' membership round) or kills an uncoordinated
    run loudly."""


class CheckpointWriteCrash(RuntimeError):
    """Forged process crash mid-checkpoint-write (`ckpt_torn@write<N>`):
    raised after garbage bytes went into the `.tmp`, before the atomic
    rename — the crash window the rename protocol must survive."""


# per-process mutable state: trigger counters (keyed per ambient rank so
# the lockstep simulation's virtual ranks count their own dispatches),
# per-clause build charges
_counters: dict[tuple, int] = {}
_charges: dict[int, int] = {}
_cache: tuple[str, tuple] | None = None
_rank_override: int | None = None  # ambient virtual rank (rank_scope)
_hang_cancel = None  # threading.Event, created on first hang (cancel_hangs)


def _hang_event():
    global _hang_cancel
    if _hang_cancel is None:
        import threading

        _hang_cancel = threading.Event()
    return _hang_cancel


def cancel_hangs() -> None:
    """Wake every in-flight `hang@chunk<N>` sleeper NOW (it still dies —
    the sleep just ends early). Called by the lockstep simulation once
    the membership round has its verdict, so the abandoned hung thread
    unwinds its rank_scope promptly instead of holding the ambient-rank
    global across the next test's solver builds."""
    _hang_event().set()


def current_rank() -> int:
    """The rank a `@rank<R>` clause is matched against: the ambient
    virtual rank inside a `rank_scope` block (the coordinator lockstep
    simulation), else this OS process's `jax.process_index()`."""
    if _rank_override is not None:
        return _rank_override
    try:
        import jax

        return jax.process_index()
    except Exception:  # lint: allow(broad-except) — any probe failure (jax not initialised, no runtime) means single-process rank 0
        return 0


class rank_scope:
    """Context manager pinning the ambient rank for rank-targeted clause
    matching — the lockstep simulation wraps each virtual rank's solver
    build and chunk dispatches in one. Reentrant (the previous rank is
    restored on exit); real multi-process runs never need it."""

    def __init__(self, rank: int):
        self.rank = int(rank)
        self._prev: int | None = None

    def __enter__(self):
        global _rank_override
        self._prev = _rank_override
        _rank_override = self.rank
        return self

    def __exit__(self, *exc):
        global _rank_override
        _rank_override = self._prev
        return False


def enabled() -> bool:
    from . import flags as _flags

    return bool(_flags.env("PAMPI_FAULTS",
                           doc="deterministic fault-injection spec "
                               "(test-only)"))


def hang_seconds() -> float:
    """How long a `hang@chunk<N>` clause sleeps before dying (seconds).
    Must exceed the watchdog under test (tpu_coord_timeout) — the default
    30 covers the test-sized windows; a production-timeout exercise sets
    PAMPI_FAULT_HANG_S above its tpu_coord_timeout."""
    from . import flags as _flags

    try:
        return float(_flags.env("PAMPI_FAULT_HANG_S", "30",
                                doc="injected-hang sleep, seconds "
                                    "(pair with dead/hang clauses)"))
    except ValueError:
        return 30.0


def reset() -> None:
    """Re-arm every clause and zero the trigger counters (tests)."""
    global _cache
    _counters.clear()
    _charges.clear()
    _cache = None
    if _hang_cancel is not None:
        _hang_cancel.clear()


def _clauses() -> tuple:
    """Parse (and cache) the spec: tuples of
    (kind, site, n, field, count, rank) with rank None = every rank."""
    from . import flags as _flags

    global _cache
    spec = _flags.env("PAMPI_FAULTS")
    if _cache is not None and _cache[0] == spec:
        return _cache[1]
    out = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _CLAUSE_RE.match(raw)
        if m is None or m["site"] not in _KIND_SITE.get(m["kind"], ()):
            raise FaultSpecError(
                f"bad PAMPI_FAULTS clause {raw!r}; grammar: "
                "pallas@chunk<N> | transient@chunk<N> | dead@chunk<N> | "
                "hang@chunk<N> | nan@step<N>:<field> "
                "| inf@step<N>:<field> | nan@lane<K>:<field> | "
                "inf@lane<K>:<field> | ckpt_torn@write<N> | "
                "ckpt_corrupt@write<N> | telemetry@emit<N> | dead@poll<N> | "
                "burst@poll<N>:<tenant>*<count> | "
                "slow_lane@poll<N>:<tenant>*<count>  (comma-separated;"
                " chunk/step clauses take an optional @rank<R> target, "
                "field faults an optional *<count> re-arm suffix)"
            )
        field = m["field"]
        if m["kind"] in ("nan", "inf"):
            if field not in _FIELDS:
                raise FaultSpecError(
                    f"PAMPI_FAULTS clause {raw!r}: field must be one of "
                    f"{'|'.join(_FIELDS)}"
                )
        elif m["kind"] in ("burst", "slow_lane"):
            # the :<field> slot carries the target TENANT for the
            # daemon-plane burn injections — required, any word
            if field is None:
                raise FaultSpecError(
                    f"PAMPI_FAULTS clause {raw!r}: burst/slow_lane need a "
                    ":<tenant> target"
                )
        elif field is not None:
            raise FaultSpecError(
                f"PAMPI_FAULTS clause {raw!r}: only nan/inf take a :<field>"
                " (and burst/slow_lane a :<tenant>)"
            )
        rank = m["rank"]
        if rank is not None and m["site"] not in _RANKABLE_SITES:
            raise FaultSpecError(
                f"PAMPI_FAULTS clause {raw!r}: @rank<R> targets chunk/step "
                "sites only (lane/write/emit faults are not per-rank)"
            )
        out.append((m["kind"], m["site"], int(m["n"]), field,
                    int(m["count"] or 1),
                    None if rank is None else int(rank)))
    _cache = (spec, tuple(out))
    return _cache[1]


def _bump(site: str) -> int:
    key = (site, _rank_override)
    n = _counters.get(key, 0) + 1
    _counters[key] = n
    return n


def _rank_hit(rank) -> bool:
    """Does a clause's rank target (None = all) match the ambient rank?
    current_rank() is only consulted for targeted clauses — untargeted
    specs never touch jax."""
    return rank is None or rank == current_rank()


# ---------------------------------------------------------------------------
# Host-side triggers
# ---------------------------------------------------------------------------

def maybe_chunk_fault() -> None:
    """Called by the drive loop once per chunk DISPATCH (1-based; a retried
    chunk is a new dispatch). Raises the forged fault armed for this index."""
    if not enabled():
        return
    n = _bump("chunk")
    for kind, site, when, _f, _c, rank in _clauses():
        if site != "chunk" or when != n or not _rank_hit(rank):
            continue
        if kind == "pallas":
            raise InjectedPallasError(
                f"PAMPI_FAULTS: injected pallas runtime failure at chunk "
                f"dispatch {n}"
            )
        if kind == "dead":
            raise InjectedRankDeath(
                f"PAMPI_FAULTS: rank {current_rank()} injected dead at "
                f"chunk dispatch {n} (stops answering)"
            )
        if kind == "hang":
            # a cancellable sleep, then death: the watchdog (not this
            # sleep ending) is what declares the rank dead — cancel only
            # bounds how long the abandoned daemon thread lingers
            _hang_event().wait(hang_seconds())
            raise InjectedRankDeath(
                f"PAMPI_FAULTS: rank {current_rank()} injected hang at "
                f"chunk dispatch {n} (slept past the watchdog)"
            )
        raise JaxRuntimeError(
            f"UNAVAILABLE: PAMPI_FAULTS injected transient device fault at "
            f"chunk dispatch {n}"
        )


def poll_faults() -> tuple:
    """Called by the serving autopilot once per daemon poll (1-based;
    fleet/autopilot.py `pre_poll` — with the autopilot off nothing bumps
    this counter and daemon-plane clauses stay inert). A `dead@poll<N>`
    armed for this poll raises InjectedRankDeath — the autopilot is the
    structured consumer here, the same role the lockstep watchdog
    collector plays for `dead@chunk` (which is why it may catch the
    BaseException: it turns the death into a membership verdict +
    `shrink_resume`, never misclassifies it as transient). Burn clauses
    return (kind, tenant, count) tuples for this poll, kind in
    {"burst", "slow_lane"}."""
    if not enabled():
        return ()
    n = _bump("poll")
    out = []
    for kind, site, when, field, count, _r in _clauses():
        if site != "poll" or when != n:
            continue
        if kind == "dead":
            raise InjectedRankDeath(
                f"PAMPI_FAULTS: resident rank injected dead at daemon "
                f"poll {n}"
            )
        out.append((kind, field, count))
    return tuple(out)


def ckpt_write_faults() -> frozenset:
    """Bump the checkpoint-write counter (one bump per save attempt) and
    return the fault kinds armed for this write: subset of
    {"torn", "corrupt"}."""
    if not enabled():
        return frozenset()
    n = _bump("write")
    hit = set()
    for kind, site, when, _f, _c, _r in _clauses():
        if site == "write" and when == n:
            hit.add(kind.replace("ckpt_", ""))
    return frozenset(hit)


def torn_write(fh) -> None:
    """The `ckpt_torn` payload: garbage partial bytes into the open `.tmp`,
    then the forged crash — `np.savez` never runs, the rename never happens."""
    fh.write(b"PAMPI-TORN-CHECKPOINT\x00\xde\xad")
    fh.flush()
    raise CheckpointWriteCrash(
        "PAMPI_FAULTS: injected crash mid-checkpoint-write (torn .tmp left "
        "behind; the live file must be untouched)"
    )


def corrupt_file(path: str, at: float = 0.5) -> None:
    """Flip bytes mid-file (the `ckpt_corrupt` payload; also a direct test
    helper for corruption-at-rest)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(max(0, int(size * at) - 8))
        fh.write(b"\xde\xad\xbe\xef" * 4)


def maybe_telemetry_fail() -> None:
    """Called by `telemetry.emit` once per record write; raises OSError for
    the armed index (the emit path's own except handles it — warn once,
    stand down, never sink the run)."""
    if not enabled():
        return
    n = _bump("emit")
    for kind, site, when, _f, _c, _r in _clauses():
        if kind == "telemetry" and site == "emit" and when == n:
            raise OSError(
                f"PAMPI_FAULTS: injected telemetry write failure at record {n}"
            )


# ---------------------------------------------------------------------------
# Trace-time field corruption
# ---------------------------------------------------------------------------

def take_field_faults() -> tuple:
    """Consume one solver generation of nan/inf clauses: every armed
    clause with charges left spends one and is returned as
    (field, step, value). Solvers call this in __init__ and
    `_rebuild_chunk` (NOT per `_build_chunk` — the pallas fallback rebuild
    reuses the armed generation) and bake the result, so consumption is
    deterministic at take time (lazy jit tracing never double-spends) and
    a rollback-recovery rebuild gets the NEXT generation — clean once the
    clause is spent."""
    if not enabled():
        return ()
    out = []
    for idx, (kind, site, step, field, count, rank) in enumerate(_clauses()):
        if kind not in ("nan", "inf") or site != "step":
            continue
        if not _rank_hit(rank):
            continue  # aimed at another rank: leave the charge armed
        used = _charges.get(idx, 0)
        if used >= count:
            continue
        _charges[idx] = used + 1
        out.append((field, step, float("nan" if kind == "nan" else "inf")))
    return tuple(out)


def take_lane_faults(n_lanes=None, fields=None) -> tuple:
    """Consume one fleet-batch generation of `nan|inf@lane<K>:<field>`
    clauses — same charge semantics as `take_field_faults`, consumed by
    `fleet/batch.BatchedSolver` at batch-build time. Each armed clause
    returns (field, lane, value); the batch driver corrupts that lane's
    field in the stacked INITIAL state host-side, so the traced program
    is untouched (lane isolation is proven on the identical compiled
    chunk, not an instrumented twin) and solo runs never see the clause.

    A clause the calling batch cannot express — lane index past
    `n_lanes`, field not in the family's `fields` — is NOT consumed: it
    stays armed for the batch it was aimed at (a 2-lane bucket built
    before the 3-lane target must not silently spend `nan@lane2:u`)."""
    if not enabled():
        return ()
    out = []
    for idx, (kind, site, lane, field, count, _r) in enumerate(_clauses()):
        if kind not in ("nan", "inf") or site != "lane":
            continue
        if n_lanes is not None and lane >= n_lanes:
            continue  # aimed past this batch: leave the charge armed
        if fields is not None and field not in fields:
            continue
        used = _charges.get(idx, 0)
        if used >= count:
            continue
        _charges[idx] = used + 1
        out.append((field, lane, float("nan" if kind == "nan" else "inf")))
    return tuple(out)


def apply_field_faults(faults, nt, **fields) -> tuple:
    """Bake taken clauses into a traced step: each becomes
    `where(nt == step, bad, x)` on its named field (values returned in
    keyword order). With no clauses — the PAMPI_FAULTS-unset path — the
    inputs pass through as the SAME tracers: zero added ops, jaxpr
    identity preserved."""
    if not faults:
        return tuple(fields.values())
    import jax.numpy as jnp

    out = dict(fields)
    for field, step, value in faults:
        if field in out:
            x = out[field]
            out[field] = jnp.where(
                jnp.asarray(nt) == step, jnp.asarray(value, x.dtype), x
            )
    return tuple(out.values())
