"""ctypes bridge to the native runtime layer (native/src, built by the
top-level Makefile into build/<TAG>/libpampi_native.so).

The native writers are byte-compatible with the pure-Python ones in
datio.py/vtkio.py (tested in tests/test_native.py); the IO layer calls
through here when the library is present and falls back to Python when not
(PAMPI_NATIVE=0 disables explicitly). This mirrors the reference's split of
math vs host plumbing: the compute path is XLA, the output plumbing is C
(≙ vtkWriter.c / writeResult in /root/reference)."""

from __future__ import annotations

import ctypes
import os
import pathlib

import numpy as np

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent


def _find_lib():
    from . import flags as _flags

    if _flags.env("PAMPI_NATIVE", "1",
                  doc="0 disables the native runtime layer") == "0":
        return None
    cand = [_flags.env("PAMPI_NATIVE_LIB",
                       doc="explicit libpampi_native.so path")]
    cand += [str(p) for p in _REPO.glob("build/*/libpampi_native.so")]
    for c in cand:
        if c and os.path.exists(c):
            try:
                return ctypes.CDLL(c)
            except OSError:
                continue
    return None


_lib = _find_lib()

if _lib is not None:
    _D = ctypes.POINTER(ctypes.c_double)
    _lib.pampi_write_matrix.argtypes = [
        ctypes.c_char_p, _D, ctypes.c_long, ctypes.c_long]
    _lib.pampi_write_matrix.restype = ctypes.c_int
    _lib.pampi_write_pressure.argtypes = [
        ctypes.c_char_p, _D, ctypes.c_long, ctypes.c_long,
        ctypes.c_double, ctypes.c_double]
    _lib.pampi_write_pressure.restype = ctypes.c_int
    _lib.pampi_write_velocity.argtypes = [
        ctypes.c_char_p, _D, _D, ctypes.c_long, ctypes.c_long,
        ctypes.c_double, ctypes.c_double]
    _lib.pampi_write_velocity.restype = ctypes.c_int
    _lib.pampi_vtk_open.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long, ctypes.c_long,
        ctypes.c_long, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_int]
    _lib.pampi_vtk_open.restype = ctypes.c_void_p
    _lib.pampi_vtk_scalar.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _D, ctypes.c_long]
    _lib.pampi_vtk_scalar.restype = ctypes.c_int
    _lib.pampi_vtk_vector.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _D, _D, _D, ctypes.c_long]
    _lib.pampi_vtk_vector.restype = ctypes.c_int
    _lib.pampi_vtk_close.argtypes = [ctypes.c_void_p]
    _lib.pampi_vtk_close.restype = ctypes.c_int


def available() -> bool:
    return _lib is not None


def _cbuf(a):
    arr = np.ascontiguousarray(a, dtype=np.float64)
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def write_matrix(path: str, a) -> bool:
    if _lib is None:
        return False
    arr, ptr = _cbuf(a)
    return _lib.pampi_write_matrix(
        path.encode(), ptr, arr.shape[0], arr.shape[1]) == 0


def write_pressure(path: str, p, dx: float, dy: float) -> bool:
    if _lib is None:
        return False
    arr, ptr = _cbuf(p)
    return _lib.pampi_write_pressure(
        path.encode(), ptr, arr.shape[0], arr.shape[1], dx, dy) == 0


def write_velocity(path: str, u, v, dx: float, dy: float) -> bool:
    if _lib is None:
        return False
    ua, up = _cbuf(u)
    va, vp = _cbuf(v)
    return _lib.pampi_write_velocity(
        path.encode(), up, vp, ua.shape[0], ua.shape[1], dx, dy) == 0


class NativeVtk:
    """Native twin of vtkio.VtkWriter (same file layout, same call shape)."""

    def __init__(self, path, title, imax, jmax, kmax, dx, dy, dz, binary):
        self._h = _lib.pampi_vtk_open(
            str(path).encode(), title.encode(), imax, jmax, kmax,
            dx, dy, dz, 1 if binary else 0)
        if not self._h:
            raise OSError(f"pampi_vtk_open failed for {path}")

    def scalar(self, name: str, s) -> None:
        arr, ptr = _cbuf(np.asarray(s).ravel())
        if _lib.pampi_vtk_scalar(self._h, name.encode(), ptr, arr.size) != 0:
            raise OSError(f"vtk scalar write failed: {name}")

    def vector(self, name: str, u, v, w) -> None:
        ua, up = _cbuf(np.asarray(u).ravel())
        va, vp = _cbuf(np.asarray(v).ravel())
        wa, wp = _cbuf(np.asarray(w).ravel())
        if _lib.pampi_vtk_vector(self._h, name.encode(), up, vp, wp,
                                 ua.size) != 0:
            raise OSError(f"vtk vector write failed: {name}")

    def close(self) -> None:
        if self._h:
            h, self._h = self._h, None
            if _lib.pampi_vtk_close(h) != 0:
                raise OSError("vtk close failed (short write?)")
