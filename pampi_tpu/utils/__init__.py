from .params import Parameter, read_parameter, print_parameter
from .grid import Grid
from .timing import get_timestamp, get_time_resolution
from .progress import Progress
