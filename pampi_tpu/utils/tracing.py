"""Request-lifecycle tracing: one trace id minted at admission, marked
at each serving-plane boundary, emitted at completion as PARENTED
`trace` records (schema v9) — the per-request waterfall behind
`tools/telemetry_report.py`'s latency decomposition.

Why marks + one flush instead of live span records: a request crosses
four modules (queue admission → scheduler bucketing → batched execute →
daemon emit) and its stages only become durations once the NEXT
boundary stamps its clock — the scheduler can't know queue_wait ended
until it starts the bucket. So each module just `mark()`s a named
timestamp on the trace, and `finish()` (the daemon, after the result is
written — or the failure path) converts the mark sequence into stage
records in one go. That also makes the off path trivial: `mint()`
returns None when telemetry is disabled and every helper no-ops on a
None trace, so flag-off serving does zero extra work and traced
programs stay byte-identical.

Record protocol (kind="trace", one line per stage per request):

    {trace, sid, stage, parent, t0_ms, ms, ...request fields}

- the ROOT record has stage="request", parent=None, t0_ms=0 and
  ms = end-to-end (admit → emit_end);
- the CRITICAL stages tile the root exactly: queue_wait (admit →
  exec_start) + compile (exec_start → run_start) + execute (run_start
  → done) + emit (done → emit_end) = end-to-end, so the report's
  per-stage p50 decomposition must sum to the e2e p50 within the
  bucket/percentile tolerance (soak-asserted at 5%);
- bucket / class_pad are DETAIL marks inside queue_wait (when the
  scheduler grouped the request, when its shape class resolved),
  parented under queue_wait — they render in the waterfall but do not
  enter the sum.

The trace table is process-local and BOUNDED (MAX_TRACES): a daemon
that parks requests forever cannot leak trace state — the oldest
unfinished trace is dropped (and counted) when the table is full.
"""

from __future__ import annotations

import os
import time

from . import telemetry as _tm

# the critical path: these tile admit -> emit_end with no gap/overlap,
# so their per-stage percentiles decompose the end-to-end latency
CRITICAL_STAGES = ("queue_wait", "compile", "execute", "emit")

# (stage, start-mark, end-mark) for the critical tiling
_STAGE_MARKS = (
    ("queue_wait", "admit", "exec_start"),
    ("compile", "exec_start", "run_start"),
    ("execute", "run_start", "done"),
    ("emit", "done", "emit_end"),
)

# unfinished-trace cap: a parked/never-served request must not leak
# table entries over a long soak
MAX_TRACES = 4096

_TRACES: dict[str, dict] = {}
_NEXT = 0
_EVICTED = 0


def mint(sid: str, **fields) -> str | None:
    """Start a trace at admission: stamps the `admit` mark now. Returns
    None when telemetry is disabled — all helpers no-op on None, so the
    flag-off serving path does no tracing work at all."""
    global _NEXT, _EVICTED
    if not _tm.enabled():
        return None
    if len(_TRACES) >= MAX_TRACES:
        _TRACES.pop(next(iter(_TRACES)))
        _EVICTED += 1
    _NEXT += 1
    trace = f"t{os.getpid():x}-{_NEXT:x}"
    _TRACES[trace] = {"sid": sid, "fields": dict(fields),
                      "marks": {"admit": time.time()}}
    return trace


def mark(trace: str | None, name: str, ts: float | None = None) -> None:
    """Stamp a named boundary clock on the trace (latest stamp wins — a
    quota-deferred request re-bucketed next poll keeps the bucketing
    that actually served it)."""
    ent = _TRACES.get(trace) if trace else None
    if ent is not None:
        ent["marks"][name] = time.time() if ts is None else ts


def note(trace: str | None, **fields) -> None:
    """Attach request fields (tenant, class, family, mode) that ride on
    every emitted stage record."""
    ent = _TRACES.get(trace) if trace else None
    if ent is not None:
        ent["fields"].update(fields)


def finish(trace: str | None, status: str = "ok",
           ts: float | None = None) -> None:
    """Flush the trace: emit the root + every stage whose marks exist,
    then drop the table entry. Tolerates partial mark sets (a request
    failed before execution emits only the stages it reached)."""
    ent = _TRACES.pop(trace, None) if trace else None
    if ent is None:
        return
    marks = ent["marks"]
    t_admit = marks.get("admit")
    if t_admit is None:
        return
    end = ts if ts is not None else marks.get(
        "emit_end", max(marks.values()))
    fields = ent["fields"]
    sid = ent["sid"]

    def rel_ms(t: float) -> float:
        return round((t - t_admit) * 1000.0, 4)

    _tm.emit("trace", trace=trace, sid=sid, stage="request", parent=None,
             t0_ms=0.0, ms=rel_ms(end), status=status, **fields)
    for stage, m0, m1 in _STAGE_MARKS:
        t0, t1 = marks.get(m0), marks.get(m1)
        if t0 is None or t1 is None:
            continue
        _tm.emit("trace", trace=trace, sid=sid, stage=stage,
                 parent="request", t0_ms=rel_ms(t0),
                 ms=round((t1 - t0) * 1000.0, 4), status=status, **fields)
    for detail in ("bucket", "class_pad"):
        t0 = marks.get(detail)
        if t0 is not None:
            _tm.emit("trace", trace=trace, sid=sid, stage=detail,
                     parent="queue_wait", t0_ms=rel_ms(t0), ms=None,
                     status=status, **fields)


def pending() -> int:
    """Unfinished traces in the table (tests / leak checks)."""
    return len(_TRACES)


def reset() -> None:
    """Drop all trace state (tests)."""
    global _NEXT, _EVICTED
    _TRACES.clear()
    _NEXT = 0
    _EVICTED = 0
