"""10-segment progress bar over simulated time, plus the per-chunk
ETA line.

Parity: initProgress/printProgress/stopProgress
(/root/reference/assignment-6/src/progress.c:17-50) — a `\r`-redrawn
`[####      ]` bar that fills as t approaches te. Only redraws when the
integer decile changes.

`ChunkEta` is the drive-loop twin (models/_driver.drive_chunks, armed by
PAMPI_PROFILE): one stderr line per confirmed chunk with steps/s and an
ETA extrapolated from the chunk trajectory — a multi-minute 4096² run
stops being a silent decile bar.
"""

import sys
import time


class Progress:
    def __init__(self, end: float, out=None, enabled: bool = True):
        self._end = end
        self._current = 0
        self._out = out if out is not None else sys.stdout
        out = self._out
        self._enabled = enabled
        if enabled:
            out.write("[          ]")
            out.flush()

    def update(self, current: float) -> None:
        if not self._enabled:
            return
        new = int(round((current / self._end) * 10.0))
        if new > self._current:
            self._current = new
            bar = "#" * min(new, 10) + " " * max(10 - new, 0)
            self._out.write(f"\r[{bar}]")
        self._out.flush()

    def stop(self) -> None:
        if self._enabled:
            self._out.write("\n")
            self._out.flush()

    def disable(self) -> None:
        """Stand the bar down mid-run (the ChunkEta line replaces it —
        two `\\r`-redrawn lines on one terminal would garble each other):
        finish the open bracket line, then every later update/stop is a
        no-op."""
        if self._enabled:
            self._out.write("\n")
            self._out.flush()
            self._enabled = False


def _fmt_eta(seconds: float) -> str:
    s = int(max(0.0, seconds))
    if s >= 3600:
        return f"{s // 3600}h{(s % 3600) // 60:02d}m"
    if s >= 60:
        return f"{s // 60}m{s % 60:02d}s"
    return f"{s}s"


class ChunkEta:
    """Per-chunk progress line: steps/s and ETA from the chunk trajectory.

    The rate is fit over the STEADY samples (the first chunk is
    compile-inclusive and would poison a naive average — it is kept as
    the time origin only once a second sample exists). ETA extrapolates
    simulated-time progress: (te - t) / (dt_sim/dwall of the steady
    window). NaN t (a diverged run) freezes the line rather than
    printing garbage."""

    def __init__(self, te: float, out=None):
        self._te = te
        self._out = out if out is not None else sys.stderr
        self._samples: list[tuple[float, float, int]] = []  # (wall, t, nt)

    def update(self, t: float, nt: int) -> None:
        if t != t:  # NaN loop time: divergence, nothing to extrapolate
            return
        now = time.perf_counter()
        self._samples.append((now, float(t), int(nt)))
        if len(self._samples) < 2:
            return
        # steady window: drop the compile-inclusive first sample when a
        # later pair exists
        base = self._samples[1] if len(self._samples) > 2 else \
            self._samples[0]
        dwall = now - base[0]
        dnt = nt - base[2]
        dt_sim = t - base[1]
        if dwall <= 0 or dnt <= 0:
            return
        sps = dnt / dwall
        eta = ((self._te - t) / (dt_sim / dwall)
               if dt_sim > 0 else float("inf"))
        self._out.write(
            f"\r[chunk] nt={nt} t={t:.6g}/{self._te:g} "
            f"{sps:.1f} steps/s "
            f"ETA {_fmt_eta(eta) if eta != float('inf') else '?'}   ")
        self._out.flush()

    def stop(self) -> None:
        if len(self._samples) >= 2:
            self._out.write("\n")
            self._out.flush()
