"""10-segment progress bar over simulated time.

Parity: initProgress/printProgress/stopProgress
(/root/reference/assignment-6/src/progress.c:17-50) — a `\r`-redrawn
`[####      ]` bar that fills as t approaches te. Only redraws when the
integer decile changes.
"""

import sys


class Progress:
    def __init__(self, end: float, out=None, enabled: bool = True):
        self._end = end
        self._current = 0
        self._out = out if out is not None else sys.stdout
        out = self._out
        self._enabled = enabled
        if enabled:
            out.write("[          ]")
            out.flush()

    def update(self, current: float) -> None:
        if not self._enabled:
            return
        new = int(round((current / self._end) * 10.0))
        if new > self._current:
            self._current = new
            bar = "#" * min(new, 10) + " " * max(10 - new, 0)
            self._out.write(f"\r[{bar}]")
        self._out.flush()

    def stop(self) -> None:
        if self._enabled:
            self._out.write("\n")
            self._out.flush()
