"""Checkpoint / restart for the NS time-steppers.

The reference has NO checkpoint subsystem (SURVEY.md §5: end-of-run output
only; its .par te/dt schema would support restart files but none exist) —
this closes that gap TPU-side. A checkpoint is a single .npz holding the
solver's field arrays (u, v[, w], p), simulated time t, step count nt, and
the grid extents for a shape sanity-check on load. Solvers expose host-sync
points (their chunked device loops return to Python every CHUNK steps);
the driver installs `periodic_writer` there, so checkpointing never forces
an extra device sync of its own.

.par keys (framework-only):
  tpu_checkpoint        path to write (every tpu_ckpt_every syncs +
                        once at the end); empty = off
  tpu_ckpt_every  host syncs between writes (default 10)
  tpu_restart           path to resume from before the run
"""

from __future__ import annotations

import numpy as np

_FIELDS = ("u", "v", "w", "p")


def _mesh_dims(solver):
    comm = getattr(solver, "comm", None)
    return tuple(comm.dims) if comm is not None else ()


def save_checkpoint(path: str, solver) -> None:
    from ..parallel.comm import CartComm

    # CartComm.collect is a plain device_get when fully addressable and a
    # cross-process allgather under a multi-process launch
    data = {
        f: CartComm.collect(getattr(solver, f))
        for f in _FIELDS
        if hasattr(solver, f)
    }
    data["t"] = np.float64(solver.t)
    data["nt"] = np.int64(solver.nt)
    data["shape"] = np.asarray(data["p"].shape)
    # distributed solvers carry stacked extended blocks, so the array layout
    # is mesh-dependent; record the mesh so a mismatched restart errors
    # clearly instead of with a confusing shape diff
    data["mesh"] = np.asarray(_mesh_dims(solver), dtype=np.int64)
    # the fetches above are collective under a multi-process launch; the
    # file itself is written by rank 0 only. Restart re-reads it on EVERY
    # rank, so under a real multi-host launch the path must live on storage
    # all hosts can see (the same contract MPI-IO restart files have)
    from ..parallel import multihost

    if not multihost.is_master():
        return
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **data)
    import os

    os.replace(tmp, path)  # atomic: a crash mid-write never corrupts


def load_checkpoint(path: str, solver) -> None:
    with np.load(path) as z:
        mesh_saved = tuple(z["mesh"]) if "mesh" in z else ()
        mesh_now = _mesh_dims(solver)
        if mesh_saved != mesh_now:
            raise ValueError(
                f"checkpoint was written under tpu_mesh {mesh_saved or '1'} "
                f"but this run uses {mesh_now or '1'}; restart on the same "
                f"mesh (field layout is mesh-dependent)"
            )
        shape = tuple(z["shape"])
        if tuple(solver.p.shape) != shape:
            raise ValueError(
                f"checkpoint grid {shape} != solver grid {tuple(solver.p.shape)}"
            )
        import jax
        import jax.numpy as jnp

        for f in _FIELDS:
            if f in z and hasattr(solver, f):
                cur = getattr(solver, f)
                new = jnp.asarray(z[f], dtype=cur.dtype)
                if getattr(cur, "sharding", None) is not None and not getattr(
                    cur, "is_fully_addressable", True
                ):
                    # multi-process mesh: place the (host-replicated) loaded
                    # array back on the global sharding the solver was built
                    # with, or the next jitted step rejects a local array
                    new = jax.device_put(new, cur.sharding)
                setattr(solver, f, new)
        solver.t = float(z["t"])
        solver.nt = int(z["nt"])


def periodic_writer(path: str, every: int = 10):
    """on_sync callback: writes `path` every `every` host syncs (values < 1
    mean every sync)."""
    every = max(1, every)
    count = {"n": 0}

    def on_sync(solver) -> None:
        count["n"] += 1
        if count["n"] % every == 0:
            save_checkpoint(path, solver)

    return on_sync
