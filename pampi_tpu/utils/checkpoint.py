"""Checkpoint / restart for the NS time-steppers.

The reference has NO checkpoint subsystem (SURVEY.md §5: end-of-run output
only; its .par te/dt schema would support restart files but none exist) —
this closes that gap TPU-side. A checkpoint is a single .npz holding the
solver's field arrays (u, v[, w], p), simulated time t, step count nt, the
grid extents for a shape sanity-check on load, a schema version, and a
CRC32 per field so a torn or bit-rotted file is REJECTED with a clear
error instead of silently restarting from garbage. Solvers expose
host-sync points (their chunked device loops return to Python every CHUNK
steps); the driver installs `periodic_writer` there, so checkpointing
never forces an extra device sync of its own.

Durability protocol (PR 4): writes go to `path.tmp` first and land via
atomic rename, and a write over an EXISTING checkpoint first rotates it to
`path.prev` — two generations on disk, so the crash/corruption window of
any single write never loses the run. `load_checkpoint` verifies the
per-field CRCs; a torn/corrupt/missing primary falls back to the `.prev`
generation (with a warning and a `ckpt reject` telemetry record).
Config-class mismatches (wrong mesh, wrong grid) are NOT corruption and
never fall back — they raise the clear ValueError they always did. The
drive loop's divergence rollback uses the newest on-disk generation as the
COLD tier under its in-memory state ring (models/_driver.RingRecovery).

Elastic checkpoints (PR 10): `save_elastic`/`load_elastic` replace the
single mesh-locked .npz with a JSON MANIFEST + per-rank shard files
holding the MESH-INDEPENDENT global reference-layout fields (assembled
exactly like `write_result`'s collection — interiors everywhere, ghost
ring from the wall shards). Restore accepts a DIFFERENT mesh: the global
array is reassembled from the shards and resharded onto the target
solver's NamedSharding (8->4->1 chip shrink, dist<->single, mesh-shape
transposes — the fleet autoscaling primitive,
fleet/scheduler.FleetScheduler.elastic_restore). Durability carries
over: every file lands via tmp+atomic-rename, the manifest rotates to
`.prev` (shard files embed their generation in the NAME, so the two
generations never share files), per-field CRCs guard every shard AND the
assembled global, and a torn/corrupt/missing piece falls back to the
`.prev` generation set. A shard whose embedded generation differs from
its manifest's is a MIXED-GENERATION set (the crash window between a
shard write and the manifest commit, or a mangled restore-from-backup)
and is refused — never silently combined. `tools/ckpt_fsck.py` verifies
a checkpoint offline (`--survivors N` additionally checks the set is
restorable onto an N-rank survivor mesh: full shard coverage + the
fault ledger present).

Fault ledger (PR 12): under an armed coordinator the manifest also
carries the fleet's protocol state (`ledger` key — spent global
transient budget, pallas deterministically-broken verdict, recovery
attempts + cumulative dt clamp, shrink epoch), written at every agreed
checkpoint commit and restored rank-symmetrically by `load_elastic`
(`_restore_ledger`): a restarted or shrunk-to-survivors fleet keeps a
pre-death broken-kernel verdict instead of re-entering probation.

.par keys (framework-only):
  tpu_checkpoint        path to write (every tpu_ckpt_every syncs +
                        once at the end); empty = off
  tpu_ckpt_every        host syncs between writes (default 10)
  tpu_ckpt_elastic      1 = elastic manifest format (default 0: legacy
                        single-.npz, mesh-locked but ghost-exact)
  tpu_restart           path to resume from before the run (either
                        format — load_any sniffs)
"""

from __future__ import annotations

import glob
import json
import math
import os
import warnings
import zlib

import numpy as np

from . import faultinject as _fi
from . import telemetry as _tm

_FIELDS = ("u", "v", "w", "p")

# bump when the .npz schema changes shape; version-1 files (pre-CRC) still
# load — their integrity is only the zip container's
CKPT_VERSION = 2


class CheckpointCorruptError(ValueError):
    """Torn or corrupt checkpoint file (CRC mismatch, truncated zip,
    missing member) — the class `load_checkpoint`'s `.prev` fallback
    catches. Config mismatches (mesh/grid) stay plain ValueError and never
    fall back: restarting an incompatible run is a user error, not rot."""


# the exception classes a torn/corrupt/missing .npz can surface as.
# FileNotFoundError (not all of OSError: an EACCES/EIO on a HEALTHY primary
# must surface raw, never masquerade as rot and silently restore stale
# state) covers a primary lost in the rotate->rename crash window
def _corrupt_classes():
    import zipfile

    return (CheckpointCorruptError, zipfile.BadZipFile, zlib.error,
            EOFError, FileNotFoundError, KeyError)


def _mesh_dims(solver):
    comm = getattr(solver, "comm", None)
    return tuple(comm.dims) if comm is not None else ()


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def save_checkpoint(path: str, solver, ledger=None) -> None:
    # `ledger` is accepted for writer_for signature parity only: the
    # fault ledger is an elastic-manifest feature (save_elastic) — the
    # legacy mesh-locked .npz never carried protocol state
    from ..parallel.comm import CartComm

    # CartComm.collect is a plain device_get when fully addressable and a
    # cross-process allgather under a multi-process launch
    data = {
        f: CartComm.collect(getattr(solver, f))
        for f in _FIELDS
        if hasattr(solver, f)
    }
    data["t"] = np.float64(solver.t)
    data["nt"] = np.int64(solver.nt)
    data["shape"] = np.asarray(data["p"].shape)
    # distributed solvers carry stacked extended blocks, so the array layout
    # is mesh-dependent; record the mesh so a mismatched restart errors
    # clearly instead of with a confusing shape diff
    data["mesh"] = np.asarray(_mesh_dims(solver), dtype=np.int64)
    data["version"] = np.int64(CKPT_VERSION)
    if not math.isfinite(float(data["t"])) or not all(
        np.isfinite(data[f]).all() for f in _FIELDS if f in data
    ):
        # a diverged state is a perfectly CRC-valid checkpoint — and
        # writing it would rotate the last GOOD generation to .prev (or
        # off the end). Refuse: restart/rollback must only ever see
        # finite states. (Every rank returns consistently — `data` is the
        # same collective gather everywhere.)
        warnings.warn(
            f"refusing to checkpoint a non-finite solver state to {path} "
            "(the existing generations are left untouched)",
            stacklevel=2,
        )
        _tm.emit("ckpt", event="skip", path=path, reason="non-finite state")
        return
    for f in _FIELDS:
        if f in data:
            data[f"crc_{f}"] = np.uint32(_crc(data[f]))
    # the fetches above are collective under a multi-process launch; the
    # file itself is written by rank 0 only. Restart re-reads it on EVERY
    # rank, so under a real multi-host launch the path must live on storage
    # all hosts can see (the same contract MPI-IO restart files have)
    from ..parallel import multihost

    if not multihost.is_master():
        return
    injected = _fi.ckpt_write_faults()
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        if "torn" in injected:
            _fi.torn_write(fh)  # garbage + forged crash: tmp torn, live safe
        np.savez(fh, **data)
    rotated = os.path.exists(path)
    if rotated:
        import zipfile

        if not zipfile.is_zipfile(path):
            # never rotate an evidently-torn primary over the .prev
            # generation — .prev may be the ONLY good state left (a full
            # CRC re-read per save would catch subtler rot too, but costs
            # a whole extra read of production-sized checkpoints; the
            # cheap container check covers the torn/garbage class, and a
            # bit-rotted member is displaced by the good new primary one
            # rename later anyway)
            os.replace(path, f"{path}.bad")
            rotated = False
            _tm.emit("ckpt", event="reject", path=path,
                     error="torn primary; not rotated over .prev")
            warnings.warn(
                f"existing checkpoint {path} is torn; keeping the .prev "
                f"generation and parking the bad file at {path}.bad",
                stacklevel=2,
            )
        else:
            # rotate ONLY once the new generation is fully on disk: the
            # live file stays the newest VALID checkpoint all the way
            os.replace(path, f"{path}.prev")
            _tm.emit("ckpt", event="rotate", path=path)
    os.replace(tmp, path)  # atomic: a crash mid-write never corrupts
    _tm.emit("ckpt", event="save", path=path, t=float(solver.t),
             nt=int(solver.nt), rotated=rotated)
    if "corrupt" in injected:
        _fi.corrupt_file(path)  # forged corruption-at-rest of this write


def _load_one(path: str, solver) -> None:
    try:
        z = np.load(path)
    except (ValueError, EOFError) as exc:
        # a garbage (non-zip) container surfaces as np.load's ValueError —
        # that's corruption, not a config error, so make it fall back
        raise CheckpointCorruptError(
            f"checkpoint {path}: unreadable container ({exc})"
        ) from exc
    with z:
        if "version" in z and int(z["version"]) > CKPT_VERSION:
            raise ValueError(
                f"checkpoint {path} has schema version {int(z['version'])}; "
                f"this build reads <= {CKPT_VERSION} (written by a newer "
                "pampi_tpu)"
            )
        for f in _FIELDS:
            key = f"crc_{f}"
            if f in z and key in z and _crc(z[f]) != int(z[key]):
                raise CheckpointCorruptError(
                    f"checkpoint {path}: field {f!r} fails its CRC32 "
                    "(torn or corrupt write)"
                )
        mesh_saved = tuple(z["mesh"]) if "mesh" in z else ()
        mesh_now = _mesh_dims(solver)
        if mesh_saved != mesh_now:
            raise ValueError(
                f"checkpoint was written under tpu_mesh {mesh_saved or '1'} "
                f"but this run uses {mesh_now or '1'}; restart on the same "
                f"mesh (field layout is mesh-dependent)"
            )
        shape = tuple(z["shape"])
        if tuple(solver.p.shape) != shape:
            raise ValueError(
                f"checkpoint grid {shape} != solver grid {tuple(solver.p.shape)}"
            )
        import jax
        import jax.numpy as jnp

        for f in _FIELDS:
            if f in z and hasattr(solver, f):
                cur = getattr(solver, f)
                new = jnp.asarray(z[f], dtype=cur.dtype)
                if getattr(cur, "sharding", None) is not None and not getattr(
                    cur, "is_fully_addressable", True
                ):
                    # multi-process mesh: place the (host-replicated) loaded
                    # array back on the global sharding the solver was built
                    # with, or the next jitted step rejects a local array
                    new = jax.device_put(new, cur.sharding)
                setattr(solver, f, new)
        solver.t = float(z["t"])
        solver.nt = int(z["nt"])


def load_checkpoint(path: str, solver, fallback: bool = True) -> None:
    """Restore `solver` from `path`. A torn/corrupt/missing primary falls
    back to the rotated `path.prev` generation (fallback=False disables,
    for callers that must see the raw failure); a corrupt file with no
    valid previous generation raises CheckpointCorruptError naming both."""
    try:
        _load_one(path, solver)
    except _corrupt_classes() as exc:
        _tm.emit("ckpt", event="reject", path=path, error=str(exc))
        prev = f"{path}.prev"
        if not fallback or not os.path.exists(prev):
            if isinstance(exc, FileNotFoundError):
                raise  # a plainly missing file is a config error, not rot
            raise CheckpointCorruptError(
                f"checkpoint {path} is torn or corrupt ({exc}) and no "
                f"previous generation exists at {prev}"
            ) from exc
        warnings.warn(
            f"checkpoint {path} is torn or corrupt ({exc}); falling back "
            f"to the previous generation {prev}",
            stacklevel=2,
        )
        try:
            _load_one(prev, solver)
        except _corrupt_classes() as exc2:
            # both generations gone: ONE structured error naming both (a
            # raw BadZipFile/zlib.error would escape cli.py's restart
            # handler, which catches OSError/ValueError/KeyError)
            raise CheckpointCorruptError(
                f"checkpoint {path} is torn or corrupt ({exc}) and so is "
                f"the previous generation {prev} ({exc2})"
            ) from exc2
        _tm.emit("ckpt", event="load", path=prev, generation="prev",
                 t=float(solver.t), nt=int(solver.nt))
        return
    _tm.emit("ckpt", event="load", path=path, generation="primary",
             t=float(solver.t), nt=int(solver.nt))


def periodic_writer(path: str, every: int = 10, save=None):
    """on_sync callback: writes `path` every `every` host syncs (values < 1
    mean every sync). `save` is the format callable — pass
    `writer_for(param)` (the ONE format switch); default is the legacy
    `save_checkpoint`. Used by the SINGLE-CONTROLLER path only — under an
    armed coordinator the drive loop owns the cadence through the agreed
    checkpoint vote (models/_driver.coord_ckpt_cadence), so cli.py wires
    exactly one of the two."""
    every = max(1, every)
    count = {"n": 0}
    save = save or save_checkpoint

    def on_sync(solver) -> None:
        count["n"] += 1
        if count["n"] % every == 0:
            save(path, solver)

    return on_sync


# ---------------------------------------------------------------------------
# Elastic checkpoints: manifest + per-rank shards, restore on ANY mesh
# ---------------------------------------------------------------------------

ELASTIC_VERSION = 1
ELASTIC_FORMAT = "pampi-elastic-ckpt"


def writer_for(param):
    """The save callable a run's .par selects: `save_elastic` under
    tpu_ckpt_elastic, else the legacy single-.npz `save_checkpoint` —
    the one switch the cli, the coordinated drive loop and the fleet
    scheduler all consult."""
    return save_elastic if getattr(param, "tpu_ckpt_elastic", 0) \
        else save_checkpoint


def assemble_global(stacked, dims, locs, interior) -> np.ndarray:
    """Stacked extended blocks -> the reference-layout global array
    (interior+ghost ring): block interiors everywhere, ghost strips only
    from wall shards — the N-D generalization of
    models/ns2d_dist._assemble, dtype-preserving (the CRCs hash the
    bytes as stored). `dims` is the mesh, `locs` the per-shard OWNED
    extents, `interior` the global interior extents; ragged trailing
    dead cells are cropped."""
    stacked = np.asarray(stacked)
    full = np.zeros([p * l + 2 for p, l in zip(dims, locs)], stacked.dtype)
    for c in np.ndindex(*dims):
        src, dst = [], []
        for a, (ca, pa, la) in enumerate(zip(c, dims, locs)):
            lo = 0 if ca == 0 else 1
            hi = la + 2 if ca == pa - 1 else la + 1
            src.append(slice(ca * (la + 2) + lo, ca * (la + 2) + hi))
            dst.append(slice(ca * la + lo, ca * la + hi))
        full[tuple(dst)] = stacked[tuple(src)]
    return full[tuple(slice(0, g + 2) for g in interior)]


def scatter_blocks(full, dims, locs) -> np.ndarray:
    """The inverse: a reference-layout global array -> stacked extended
    blocks for an ARBITRARY mesh (the elastic-restore resharding input).
    Interior-edge ghosts are filled from the neighbour interiors — the
    state a fresh halo exchange would produce, which every step refreshes
    before reading; physical-wall ghosts come through bit-exact. Ragged
    pad cells (past the global interior) zero-fill — they are excluded
    from updates, residuals and collection by the live masks."""
    full = np.asarray(full)
    pad_shape = [p * l + 2 for p, l in zip(dims, locs)]
    pad = np.zeros(pad_shape, full.dtype)
    pad[tuple(slice(0, s) for s in full.shape)] = full
    stacked = np.zeros([p * (l + 2) for p, l in zip(dims, locs)], full.dtype)
    for c in np.ndindex(*dims):
        dst = tuple(slice(ca * (la + 2), (ca + 1) * (la + 2))
                    for ca, la in zip(c, locs))
        src = tuple(slice(ca * la, ca * la + la + 2)
                    for ca, la in zip(c, locs))
        stacked[dst] = pad[src]
    return stacked


def _shard_path(path: str, gen: int, rank: int) -> str:
    """Shard files embed their GENERATION in the name, so the live and
    .prev manifests never share files — the rotation that makes the
    two-generation protocol crash-window-safe without cross-file
    renames (the manifest rename is the one commit point)."""
    return f"{path}.g{gen}.r{rank}.npz"


def _shard_bounds(rows: int, nshards: int) -> list:
    """Deterministic per-rank row slabs of the global array's axis 0
    (np.array_split semantics: sizes differ by at most one)."""
    splits = np.array_split(np.arange(rows), nshards)
    return [(int(s[0]), int(s[-1]) + 1) for s in splits if len(s)]


def _read_manifest(path: str) -> dict:
    """Parse + shape-check a manifest; unparseable/truncated JSON is
    CORRUPTION (falls back), a missing file stays FileNotFoundError."""
    with open(path) as fh:
        try:
            man = json.load(fh)
        except ValueError as exc:
            raise CheckpointCorruptError(
                f"elastic manifest {path}: unparseable JSON ({exc})"
            ) from exc
    if not isinstance(man, dict) or man.get("format") != ELASTIC_FORMAT:
        raise CheckpointCorruptError(
            f"elastic manifest {path}: not a {ELASTIC_FORMAT} manifest"
        )
    missing = [k for k in ("version", "generation", "t", "nt", "mesh",
                           "global_shape", "dtype", "fields", "shards",
                           "crc") if k not in man]
    if missing:
        raise CheckpointCorruptError(
            f"elastic manifest {path}: missing keys {missing}"
        )
    return man


def _manifest_generation(path: str) -> int:
    """Best-effort generation of an existing manifest chain (primary,
    else .prev), 0 when none parses — save_elastic numbers the next
    write from it. Tolerant BY DESIGN: a torn primary must not block
    the save that replaces it."""
    for p in (path, f"{path}.prev"):
        try:
            return int(_read_manifest(p)["generation"])
        except (FileNotFoundError, CheckpointCorruptError):
            continue
    return 0


def save_elastic(path: str, solver, ledger=None) -> None:
    """Write the elastic checkpoint set: every rank writes its row slab
    of the MESH-INDEPENDENT assembled global fields to its own shard
    file (generation-named), rank 0 commits the manifest last. Refuses
    non-finite states like save_checkpoint; shard writes take the same
    torn/corrupt fault injection (`ckpt_torn@write<N>` /
    `ckpt_corrupt@write<N>`).

    `ledger` (PR 12) is the coordinator's FAULT LEDGER (parallel/
    coordinator.CoordinatedLoop.ledger): spent global transient budget,
    the pallas deterministically-broken verdict, rollback attempts +
    cumulative dt clamp, shrink epoch. It rides in the manifest so a
    restarted or shrunk-to-survivors fleet resumes with the protocol
    state it died with instead of probation amnesia. None falls back to
    the ledger the solver itself was restored with (`_fault_ledger`) —
    a save on an already-resumed run re-persists its inherited state."""
    import jax

    from ..parallel import multihost

    fields = solver.global_fields()  # collective under multi-process
    t, nt = float(solver.t), int(solver.nt)
    if not math.isfinite(t) or not all(
        np.isfinite(a).all() for a in fields.values()
    ):
        warnings.warn(
            f"refusing to checkpoint a non-finite solver state to {path} "
            "(the existing generations are left untouched)",
            stacklevel=2,
        )
        _tm.emit("ckpt", event="skip", path=path, reason="non-finite state")
        return
    gen = _manifest_generation(path) + 1
    nshards = jax.process_count()
    rank = jax.process_index()
    names = list(fields)
    gshape = fields[names[0]].shape
    bounds = _shard_bounds(gshape[0], nshards)
    injected = _fi.ckpt_write_faults()
    # my shard: the rows this process owns (tmp + atomic rename)
    lo, hi = bounds[rank] if rank < len(bounds) else (0, 0)
    spath = _shard_path(path, gen, rank)
    data = {f: np.ascontiguousarray(a[lo:hi]) for f, a in fields.items()}
    for f in names:
        data[f"crc_{f}"] = np.uint32(_crc(data[f]))
    data.update(generation=np.int64(gen), rank=np.int64(rank),
                rows=np.asarray([lo, hi], np.int64))
    tmp = f"{spath}.tmp"
    with open(tmp, "wb") as fh:
        if "torn" in injected:
            _fi.torn_write(fh)  # forged crash: torn .tmp, manifest intact
        np.savez(fh, **data)
    os.replace(tmp, spath)
    if "corrupt" in injected:
        _fi.corrupt_file(spath)
    if not multihost.is_master():
        return
    manifest = {
        "format": ELASTIC_FORMAT,
        "version": ELASTIC_VERSION,
        "ckpt_version": CKPT_VERSION,
        "generation": gen,
        "t": t,
        "nt": nt,
        "mesh": list(_mesh_dims(solver)),
        "global_shape": [int(s) for s in gshape],
        "dtype": str(fields[names[0]].dtype),
        "fields": names,
        "nshards": nshards,
        "shards": [
            {"file": os.path.basename(_shard_path(path, gen, r)),
             "rank": r, "rows": [b[0], b[1]]}
            for r, b in enumerate(bounds)
        ],
        "crc": {f: int(_crc(a)) for f, a in fields.items()},
    }
    if ledger is None:
        ledger = getattr(solver, "_fault_ledger", None)
    if ledger is not None:
        manifest["ledger"] = ledger
        _tm.emit("ckpt", event="ledger_save", path=path, generation=gen,
                 ledger=ledger)
    rotated = os.path.exists(path)
    if rotated:
        try:
            _read_manifest(path)
        except CheckpointCorruptError:
            # same policy as the legacy torn-primary path: never rotate
            # an evidently-bad manifest over the good .prev generation
            os.replace(path, f"{path}.bad")
            rotated = False
            _tm.emit("ckpt", event="reject", path=path,
                     error="torn manifest; not rotated over .prev")
            warnings.warn(
                f"existing manifest {path} is torn; keeping the .prev "
                f"generation and parking the bad file at {path}.bad",
                stacklevel=2,
            )
        else:
            os.replace(path, f"{path}.prev")
            _tm.emit("ckpt", event="rotate", path=path)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
    os.replace(tmp, path)  # the commit point
    # retire shard files two generations back (the .prev manifest keeps
    # generation gen-1 alive; anything older is unreachable)
    for old in glob.glob(f"{glob.escape(path)}.g*.r*.npz"):
        try:
            old_gen = int(os.path.basename(old).rsplit(".g", 1)[1]
                          .split(".r", 1)[0])
        except (IndexError, ValueError):
            continue
        if old_gen <= gen - 2:
            try:
                os.remove(old)
            except OSError:
                pass  # a straggler shard is garbage, not a failure
    _tm.emit("ckpt", event="elastic_save", path=path, generation=gen,
             mesh=manifest["mesh"], t=t, nt=nt, rotated=rotated)


def _load_elastic_set(path: str, solver) -> int:
    """Load ONE manifest's generation set into the solver; returns the
    generation. Raises the corruption classes for anything torn, CRC-
    mismatched, missing or MIXED-GENERATION; config-class mismatches
    (wrong global shape, unknown schema) stay plain ValueError."""
    man = _read_manifest(path)
    if int(man["version"]) > ELASTIC_VERSION:
        raise ValueError(
            f"elastic manifest {path} has version {man['version']}; this "
            f"build reads <= {ELASTIC_VERSION} (written by a newer "
            "pampi_tpu)"
        )
    gshape = tuple(int(s) for s in man["global_shape"])
    expect = tuple(solver.global_shape())
    if gshape != expect:
        raise ValueError(
            f"elastic checkpoint global shape {gshape} != solver global "
            f"shape {expect}"
        )
    gen = int(man["generation"])
    dtype = np.dtype(man["dtype"])
    out = {f: np.zeros(gshape, dtype) for f in man["fields"]}
    base = os.path.dirname(path)
    for sh in man["shards"]:
        spath = os.path.join(base, sh["file"]) if base else sh["file"]
        try:
            z = np.load(spath)
        except FileNotFoundError as exc:
            # a MANIFEST plainly missing is a config error (stays
            # FileNotFoundError, no fallback) — but a shard missing
            # under a present manifest is a mutilated set: corruption
            raise CheckpointCorruptError(
                f"elastic shard {spath} is missing (manifest {path} "
                "names it)"
            ) from exc
        except (ValueError, EOFError) as exc:
            raise CheckpointCorruptError(
                f"elastic shard {spath}: unreadable container ({exc})"
            ) from exc
        with z:
            if int(z["generation"]) != gen:
                raise CheckpointCorruptError(
                    f"elastic shard {spath} is generation "
                    f"{int(z['generation'])} but manifest {path} is "
                    f"generation {gen} — mixed-generation set refused"
                )
            lo, hi = (int(x) for x in sh["rows"])
            for f in man["fields"]:
                slab = z[f]
                if _crc(slab) != int(z[f"crc_{f}"]):
                    raise CheckpointCorruptError(
                        f"elastic shard {spath}: field {f!r} fails its "
                        "CRC32 (torn or corrupt write)"
                    )
                out[f][lo:hi] = slab
    for f, arr in out.items():
        if _crc(arr) != int(man["crc"][f]):
            raise CheckpointCorruptError(
                f"elastic checkpoint {path}: assembled field {f!r} fails "
                "the manifest CRC32"
            )
    solver.set_global_fields(out)
    solver.t = float(man["t"])
    solver.nt = int(man["nt"])
    solver._elastic_generation = gen
    _restore_ledger(path, man.get("ledger"), solver)
    return gen


def _restore_ledger(path: str, ledger, solver) -> None:
    """Apply a manifest's fault ledger to the freshly-restored solver,
    rank-symmetrically (every rank read the same manifest): re-apply the
    cumulative recovery dt clamp, and hold a pallas kernel the dead
    fleet had judged deterministically broken ON THE JNP PATH — the
    no-probation-amnesia contract. Either change re-traces the chunk via
    the solver's own rebuild hook; the ledger itself is stashed at
    `_fault_ledger`, where `pallas_retry`/`make_recovery`/the
    coordinated loop pick up the rest (spent budget, attempts, epoch).
    Legacy manifests (no ledger) stash None — the historical restore."""
    solver._fault_ledger = ledger
    if not ledger:
        return
    rebuild = False
    dt_scale = float(ledger.get("dt_scale", 1.0))
    if dt_scale != getattr(solver, "_dt_scale", 1.0):
        solver._dt_scale = dt_scale
        rebuild = True
    pallas = ledger.get("pallas") or {}
    if pallas.get("broken") and getattr(solver, "_backend", "jnp") != "jnp":
        solver._backend = "jnp"
        rebuild = True
    if rebuild and hasattr(solver, "_rebuild_chunk"):
        solver._rebuild_chunk()
    _tm.emit("ckpt", event="ledger_restore", path=path, ledger=ledger,
             rebuilt=rebuild)


def load_elastic(path: str, solver, fallback: bool = True) -> None:
    """Restore `solver` from an elastic manifest — on WHATEVER mesh the
    solver was built with (the saved mesh is metadata, not a contract:
    set_global_fields reshards the assembled global array via the
    solver's own NamedSharding). Torn/corrupt/missing/mixed-generation
    pieces fall back to the `.prev` generation set, same semantics as
    `load_checkpoint`."""
    from ..parallel import multihost as _mh  # noqa: F401  (doc parity)

    try:
        gen = _load_elastic_set(path, solver)
    except _corrupt_classes() as exc:
        _tm.emit("ckpt", event="reject", path=path, error=str(exc))
        prev = f"{path}.prev"
        if not fallback or not os.path.exists(prev):
            if isinstance(exc, FileNotFoundError):
                raise
            raise CheckpointCorruptError(
                f"elastic checkpoint {path} is torn or corrupt ({exc}) "
                f"and no previous generation exists at {prev}"
            ) from exc
        warnings.warn(
            f"elastic checkpoint {path} is torn or corrupt ({exc}); "
            f"falling back to the previous generation {prev}",
            stacklevel=2,
        )
        try:
            gen = _load_elastic_set(prev, solver)
        except _corrupt_classes() as exc2:
            raise CheckpointCorruptError(
                f"elastic checkpoint {path} is torn or corrupt ({exc}) "
                f"and so is the previous generation {prev} ({exc2})"
            ) from exc2
        _tm.emit("ckpt", event="elastic_load", path=prev,
                 generation=gen, fell_back=True,
                 t=float(solver.t), nt=int(solver.nt))
        return
    _tm.emit("ckpt", event="elastic_load", path=path, generation=gen,
             mesh_now=list(_mesh_dims(solver)),
             t=float(solver.t), nt=int(solver.nt))


def is_elastic(path: str) -> bool:
    """Sniff the on-disk format: an elastic manifest is JSON (first
    byte '{'), the legacy checkpoint a zip (.npz). Missing files sniff
    legacy so the caller's FileNotFoundError names the path."""
    try:
        with open(path, "rb") as fh:
            return fh.read(1) == b"{"
    except OSError:
        return False


def load_any(path: str, solver, fallback: bool = True) -> None:
    """Restore from either checkpoint format — the restart entry point
    (cli.py `tpu_restart` takes a path of either kind)."""
    if is_elastic(path):
        load_elastic(path, solver, fallback=fallback)
    else:
        load_checkpoint(path, solver, fallback=fallback)


# ---------------------------------------------------------------------------
# Parked continuous-batching lanes (QoS preemption, fleet/autopilot.py)
# ---------------------------------------------------------------------------

PARKED_LANE_VERSION = 1


def save_parked_lane(path: str, sid: str, leaves) -> None:
    """Park one continuous-batching lane's full per-lane carry — every
    stacked leaf below the batch scalars: fields, the per-lane t/nt, the
    per-lane te — under the elastic-manifest write discipline (CRC32 per
    leaf, write to `.tmp`, atomic rename). The autopilot's preemption
    plane writes one of these when a higher-priority tenant evicts a
    running lane; `load_parked_lane` + `BatchedSolver.resume_lane`
    splice the arrays back and the lane continues from the exact chunk
    boundary it was parked at — bitwise, the same proof `shrink_resume`
    carries for whole meshes."""
    data = {"version": np.int64(PARKED_LANE_VERSION),
            "sid": np.asarray(sid),
            "n_leaves": np.int64(len(leaves))}
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        data[f"leaf_{i}"] = arr
        data[f"crc_{i}"] = np.uint32(_crc(arr))
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **data)
    os.replace(tmp, path)
    _tm.emit("ckpt", event="lane_park", path=path, sid=sid,
             leaves=len(leaves))


def load_parked_lane(path: str) -> list:
    """Read a parked lane back: per-leaf CRC verified (a corrupt park
    file must refuse loudly — resuming a half-true lane state would
    poison its batchmates' bitwise story), returns the leaf arrays in
    stack order."""
    with np.load(path) as z:
        n = int(z["n_leaves"])
        out = []
        for i in range(n):
            arr = z[f"leaf_{i}"]
            if _crc(arr) != int(z[f"crc_{i}"]):
                raise CheckpointCorruptError(
                    f"parked lane {path}: leaf {i} fails its CRC32"
                )
            out.append(arr)
        sid = str(z["sid"])
    _tm.emit("ckpt", event="lane_resume", path=path, sid=sid)
    return out
