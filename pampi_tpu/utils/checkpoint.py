"""Checkpoint / restart for the NS time-steppers.

The reference has NO checkpoint subsystem (SURVEY.md §5: end-of-run output
only; its .par te/dt schema would support restart files but none exist) —
this closes that gap TPU-side. A checkpoint is a single .npz holding the
solver's field arrays (u, v[, w], p), simulated time t, step count nt, the
grid extents for a shape sanity-check on load, a schema version, and a
CRC32 per field so a torn or bit-rotted file is REJECTED with a clear
error instead of silently restarting from garbage. Solvers expose
host-sync points (their chunked device loops return to Python every CHUNK
steps); the driver installs `periodic_writer` there, so checkpointing
never forces an extra device sync of its own.

Durability protocol (PR 4): writes go to `path.tmp` first and land via
atomic rename, and a write over an EXISTING checkpoint first rotates it to
`path.prev` — two generations on disk, so the crash/corruption window of
any single write never loses the run. `load_checkpoint` verifies the
per-field CRCs; a torn/corrupt/missing primary falls back to the `.prev`
generation (with a warning and a `ckpt reject` telemetry record).
Config-class mismatches (wrong mesh, wrong grid) are NOT corruption and
never fall back — they raise the clear ValueError they always did. The
drive loop's divergence rollback uses the newest on-disk generation as the
COLD tier under its in-memory state ring (models/_driver.RingRecovery).

.par keys (framework-only):
  tpu_checkpoint        path to write (every tpu_ckpt_every syncs +
                        once at the end); empty = off
  tpu_ckpt_every  host syncs between writes (default 10)
  tpu_restart           path to resume from before the run
"""

from __future__ import annotations

import math
import os
import warnings
import zlib

import numpy as np

from . import faultinject as _fi
from . import telemetry as _tm

_FIELDS = ("u", "v", "w", "p")

# bump when the .npz schema changes shape; version-1 files (pre-CRC) still
# load — their integrity is only the zip container's
CKPT_VERSION = 2


class CheckpointCorruptError(ValueError):
    """Torn or corrupt checkpoint file (CRC mismatch, truncated zip,
    missing member) — the class `load_checkpoint`'s `.prev` fallback
    catches. Config mismatches (mesh/grid) stay plain ValueError and never
    fall back: restarting an incompatible run is a user error, not rot."""


# the exception classes a torn/corrupt/missing .npz can surface as.
# FileNotFoundError (not all of OSError: an EACCES/EIO on a HEALTHY primary
# must surface raw, never masquerade as rot and silently restore stale
# state) covers a primary lost in the rotate->rename crash window
def _corrupt_classes():
    import zipfile

    return (CheckpointCorruptError, zipfile.BadZipFile, zlib.error,
            EOFError, FileNotFoundError, KeyError)


def _mesh_dims(solver):
    comm = getattr(solver, "comm", None)
    return tuple(comm.dims) if comm is not None else ()


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def save_checkpoint(path: str, solver) -> None:
    from ..parallel.comm import CartComm

    # CartComm.collect is a plain device_get when fully addressable and a
    # cross-process allgather under a multi-process launch
    data = {
        f: CartComm.collect(getattr(solver, f))
        for f in _FIELDS
        if hasattr(solver, f)
    }
    data["t"] = np.float64(solver.t)
    data["nt"] = np.int64(solver.nt)
    data["shape"] = np.asarray(data["p"].shape)
    # distributed solvers carry stacked extended blocks, so the array layout
    # is mesh-dependent; record the mesh so a mismatched restart errors
    # clearly instead of with a confusing shape diff
    data["mesh"] = np.asarray(_mesh_dims(solver), dtype=np.int64)
    data["version"] = np.int64(CKPT_VERSION)
    if not math.isfinite(float(data["t"])) or not all(
        np.isfinite(data[f]).all() for f in _FIELDS if f in data
    ):
        # a diverged state is a perfectly CRC-valid checkpoint — and
        # writing it would rotate the last GOOD generation to .prev (or
        # off the end). Refuse: restart/rollback must only ever see
        # finite states. (Every rank returns consistently — `data` is the
        # same collective gather everywhere.)
        warnings.warn(
            f"refusing to checkpoint a non-finite solver state to {path} "
            "(the existing generations are left untouched)",
            stacklevel=2,
        )
        _tm.emit("ckpt", event="skip", path=path, reason="non-finite state")
        return
    for f in _FIELDS:
        if f in data:
            data[f"crc_{f}"] = np.uint32(_crc(data[f]))
    # the fetches above are collective under a multi-process launch; the
    # file itself is written by rank 0 only. Restart re-reads it on EVERY
    # rank, so under a real multi-host launch the path must live on storage
    # all hosts can see (the same contract MPI-IO restart files have)
    from ..parallel import multihost

    if not multihost.is_master():
        return
    injected = _fi.ckpt_write_faults()
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        if "torn" in injected:
            _fi.torn_write(fh)  # garbage + forged crash: tmp torn, live safe
        np.savez(fh, **data)
    rotated = os.path.exists(path)
    if rotated:
        import zipfile

        if not zipfile.is_zipfile(path):
            # never rotate an evidently-torn primary over the .prev
            # generation — .prev may be the ONLY good state left (a full
            # CRC re-read per save would catch subtler rot too, but costs
            # a whole extra read of production-sized checkpoints; the
            # cheap container check covers the torn/garbage class, and a
            # bit-rotted member is displaced by the good new primary one
            # rename later anyway)
            os.replace(path, f"{path}.bad")
            rotated = False
            _tm.emit("ckpt", event="reject", path=path,
                     error="torn primary; not rotated over .prev")
            warnings.warn(
                f"existing checkpoint {path} is torn; keeping the .prev "
                f"generation and parking the bad file at {path}.bad",
                stacklevel=2,
            )
        else:
            # rotate ONLY once the new generation is fully on disk: the
            # live file stays the newest VALID checkpoint all the way
            os.replace(path, f"{path}.prev")
            _tm.emit("ckpt", event="rotate", path=path)
    os.replace(tmp, path)  # atomic: a crash mid-write never corrupts
    _tm.emit("ckpt", event="save", path=path, t=float(solver.t),
             nt=int(solver.nt), rotated=rotated)
    if "corrupt" in injected:
        _fi.corrupt_file(path)  # forged corruption-at-rest of this write


def _load_one(path: str, solver) -> None:
    try:
        z = np.load(path)
    except (ValueError, EOFError) as exc:
        # a garbage (non-zip) container surfaces as np.load's ValueError —
        # that's corruption, not a config error, so make it fall back
        raise CheckpointCorruptError(
            f"checkpoint {path}: unreadable container ({exc})"
        ) from exc
    with z:
        if "version" in z and int(z["version"]) > CKPT_VERSION:
            raise ValueError(
                f"checkpoint {path} has schema version {int(z['version'])}; "
                f"this build reads <= {CKPT_VERSION} (written by a newer "
                "pampi_tpu)"
            )
        for f in _FIELDS:
            key = f"crc_{f}"
            if f in z and key in z and _crc(z[f]) != int(z[key]):
                raise CheckpointCorruptError(
                    f"checkpoint {path}: field {f!r} fails its CRC32 "
                    "(torn or corrupt write)"
                )
        mesh_saved = tuple(z["mesh"]) if "mesh" in z else ()
        mesh_now = _mesh_dims(solver)
        if mesh_saved != mesh_now:
            raise ValueError(
                f"checkpoint was written under tpu_mesh {mesh_saved or '1'} "
                f"but this run uses {mesh_now or '1'}; restart on the same "
                f"mesh (field layout is mesh-dependent)"
            )
        shape = tuple(z["shape"])
        if tuple(solver.p.shape) != shape:
            raise ValueError(
                f"checkpoint grid {shape} != solver grid {tuple(solver.p.shape)}"
            )
        import jax
        import jax.numpy as jnp

        for f in _FIELDS:
            if f in z and hasattr(solver, f):
                cur = getattr(solver, f)
                new = jnp.asarray(z[f], dtype=cur.dtype)
                if getattr(cur, "sharding", None) is not None and not getattr(
                    cur, "is_fully_addressable", True
                ):
                    # multi-process mesh: place the (host-replicated) loaded
                    # array back on the global sharding the solver was built
                    # with, or the next jitted step rejects a local array
                    new = jax.device_put(new, cur.sharding)
                setattr(solver, f, new)
        solver.t = float(z["t"])
        solver.nt = int(z["nt"])


def load_checkpoint(path: str, solver, fallback: bool = True) -> None:
    """Restore `solver` from `path`. A torn/corrupt/missing primary falls
    back to the rotated `path.prev` generation (fallback=False disables,
    for callers that must see the raw failure); a corrupt file with no
    valid previous generation raises CheckpointCorruptError naming both."""
    try:
        _load_one(path, solver)
    except _corrupt_classes() as exc:
        _tm.emit("ckpt", event="reject", path=path, error=str(exc))
        prev = f"{path}.prev"
        if not fallback or not os.path.exists(prev):
            if isinstance(exc, FileNotFoundError):
                raise  # a plainly missing file is a config error, not rot
            raise CheckpointCorruptError(
                f"checkpoint {path} is torn or corrupt ({exc}) and no "
                f"previous generation exists at {prev}"
            ) from exc
        warnings.warn(
            f"checkpoint {path} is torn or corrupt ({exc}); falling back "
            f"to the previous generation {prev}",
            stacklevel=2,
        )
        try:
            _load_one(prev, solver)
        except _corrupt_classes() as exc2:
            # both generations gone: ONE structured error naming both (a
            # raw BadZipFile/zlib.error would escape cli.py's restart
            # handler, which catches OSError/ValueError/KeyError)
            raise CheckpointCorruptError(
                f"checkpoint {path} is torn or corrupt ({exc}) and so is "
                f"the previous generation {prev} ({exc2})"
            ) from exc2
        _tm.emit("ckpt", event="load", path=prev, generation="prev",
                 t=float(solver.t), nt=int(solver.nt))
        return
    _tm.emit("ckpt", event="load", path=path, generation="primary",
             t=float(solver.t), nt=int(solver.nt))


def periodic_writer(path: str, every: int = 10):
    """on_sync callback: writes `path` every `every` host syncs (values < 1
    mean every sync)."""
    every = max(1, every)
    count = {"n": 0}

    def on_sync(solver) -> None:
        count["n"] += 1
        if count["n"] % every == 0:
            save_checkpoint(path, solver)

    return on_sync
