"""Run-time configuration: the `.par` key-value file format.

Capability parity with the reference's L2 config layer (`parameter.{h,c}` in
assignments 4/5/6; see /root/reference/assignment-6/src/parameter.c:15-126):
`#` starts a comment, first whitespace token is the key, second is the value,
keys are matched by *prefix* (the reference uses `strncmp(tok, key, strlen(key))`,
so a token `imaxFoo` still sets `imax` — we keep that tolerance), unknown keys
are silently ignored, and every known key has a default.

The parameter set is the union of all assignments:
  A4  {xlength ylength imax jmax itermax eps omg}
  A5 += {re tau gamma dt te gx gy name bcLeft/Right/Bottom/Top u_init v_init p_init}
  A6 += {zlength kmax gz bcFront bcBack w_init}
plus framework-only keys (prefixed `tpu_`) controlling the TPU execution:
  tpu_mesh   "PJxPI" / "PKxPJxPI" device-mesh shape, "auto" (factorize like
             MPI_Dims_create, ref assignment-5/ex5-nazifkar/src/solver.c:445),
             or "1" (force single-device)
  tpu_dtype  "float32" | "float64" | "bfloat16"
"""

from __future__ import annotations

import dataclasses
import sys
from dataclasses import dataclass


@dataclass
class Parameter:
    # geometry
    xlength: float = 1.0
    ylength: float = 1.0
    zlength: float = 1.0
    imax: int = 100
    jmax: int = 100
    kmax: int = 50
    # pressure iteration
    itermax: int = 1000
    eps: float = 0.0001
    omg: float = 1.7
    rho: float = 0.99  # framework-reserve key (not in the reference schema)
    # flow
    re: float = 100.0
    tau: float = 0.5
    gamma: float = 0.9
    dt: float = 0.02
    te: float = 10.0
    gx: float = 0.0
    gy: float = 0.0
    gz: float = 0.0
    name: str = "poisson"
    bcLeft: int = 1
    bcRight: int = 1
    bcBottom: int = 1
    bcTop: int = 1
    bcFront: int = 1
    bcBack: int = 1
    u_init: float = 0.0
    v_init: float = 0.0
    w_init: float = 0.0
    p_init: float = 0.0
    # obstacle geometry (ops/obstacle.py; the reference's canal is an empty
    # channel — this drives the flag-masked channel-with-obstacle config):
    # semicolon-separated rectangles "x0,y0,x1,y1;..." in physical coords
    obstacles: str = ""
    # framework-only (TPU execution controls; not in the reference)
    tpu_mesh: str = "auto"
    tpu_dtype: str = "float64"
    # temporal-blocking depth of the pallas SOR kernel: red-black iterations
    # fused per HBM sweep; convergence is checked every tpu_sor_inner
    # iterations, so a solve may overshoot by up to tpu_sor_inner-1
    # iterations (jnp paths always step singly). Default 4 keeps overshoot
    # small for CONVERGING solves (a 5-iteration solve at n=16 would run
    # 16); itermax-CAPPED workloads want 16 — measured 12.7 vs 21.3 ms/step
    # at dcavity 4096² (round-3 depth sweep, quarters kernel; bench.py uses
    # n_inner=16 for the same reason).
    tpu_sor_inner: int = 4
    # pallas SOR layout (single-device AND per-shard distributed):
    #   "auto"         quarter (2-D) / octant (3-D) decomposition when
    #                  eligible (even extents — ~3× the checkerboard kernel
    #                  at 4096² f32 on v5e; per-cell arithmetic
    #                  ulp-equivalent, ops/sor_quarters.py/sor_octants.py),
    #                  else checkerboard. The distributed solvers dispatch
    #                  the same kernels per shard between CA exchanges
    #                  (parallel/quarters_dist.py, octants_dist.py).
    #   "checkerboard" the masked kernel (per-cell trajectory numerically
    #                  IDENTICAL to the jnp reference path). In DISTRIBUTED
    #                  context it also FORCES the per-shard masked kernel
    #                  (ops/sor_obsdist; interpret off-TPU) for obstacle
    #                  and ragged runs — the dryrun/test force mode, since
    #                  that kernel IS the dist masked-checkerboard layout
    #   "quarters"/"octants"  force the compressed layout (error when
    #                  ineligible; off-TPU runs the interpret kernel/twin)
    tpu_sor_layout: str = "auto"
    # communication-avoiding depth of the DISTRIBUTED red-black solve
    # (parallel/stencil2d.ca_rb_iters): n exact iterations computed locally
    # per depth-2n halo exchange; convergence is checked every n iterations
    # (same overshoot semantics as tpu_sor_inner). n is clamped so 2n never
    # exceeds a shard extent; 1 keeps today's per-iteration trajectory
    # granularity while still halving the message count. The distributed
    # quarters/octants kernel paths use max(tpu_ca_inner, tpu_sor_inner).
    tpu_ca_inner: int = 1
    # pressure/elliptic solver:
    #   "sor"  the reference's algorithm (default; trajectory parity)
    #   "sor_lex"  the reference's LEXICOGRAPHIC sweep ordering as an
    #          oracle (NS-2D + Poisson): capped solves then follow the C
    #          binary's exact iterate sequence — the C-vs-framework field
    #          comparison mode (tools/northstar.py match4096); jnp-only
    #   "mg"   geometric multigrid V-cycles with an exact DCT bottom solve
    #          (ops/multigrid.py) — O(1) cycles; same eps-residual stopping
    #          contract, `it` counts cycles; single-device or on a mesh
    #   "fft"  direct DCT-diagonalization solve (ops/dctpoisson.py, MXU
    #          matmuls; collective matmuls + psum_scatter on a mesh) —
    #          exact in ONE application, `it` reports 1
    # fft does not support obstacle flag fields; mg does (2-D and 3-D,
    # single-device AND distributed — per-level rediscretized
    # eps-coefficient operators with an exact dense bottom)
    #   "auto" picks the measured-best solver for the run's structure
    #          (utils/dispatch.resolve_solver: plain -> fft; obstacles ->
    #          mg; ragged -> sor) and records the decision under the
    #          "solver_auto" dispatch key. The default stays "sor" for
    #          reference-trajectory parity.
    tpu_solver: str = "sor"
    # fused step-phase kernels (ops/ns2d_fused.py, ns3d_fused.py): the
    # non-solve NS timestep phases (BCs + special BC + computeFG + RHS +
    # adaptUV + CFL max) collapse from the ~40-launch jnp chain into two
    # Pallas HBM sweeps bracketing the pressure solve — the round-5
    # north-star decomposition measured that chain at 6.4 ms/step vs a
    # ~0.8 ms HBM floor at dcavity 4096² (results/northstar_dcavity4096.json).
    #   "auto" fuse when eligible: real TPU + Mosaic dtype + one-time probe
    #          + VMEM-feasible geometry; plain and (2-D single-device)
    #          obstacle runs fuse, distributed divisible plain runs fuse
    #          per shard, ragged / dist-obstacle / 3-D-obstacle keep the
    #          jnp chain (utils/dispatch.resolve_fuse_phases records every
    #          decision under the "*_phases" keys)
    #   "on"   force (interpret off-TPU — the parity-test mode)
    #   "off"  always the jnp phase chain
    # Numerics: BC/select/max phases bitwise-identical; F/G/RHS/projection
    # ulp-equivalent (same formula functions, compiler fma differences only
    # — the quarters-layout precedent).
    tpu_fuse_phases: str = "auto"
    # comm/compute overlap (distributed fused paths only): the step-level
    # deep-halo exchange for step N+1 is posted right after step N's POST
    # kernel and carried as a DOUBLE-BUFFERED pair of deep blocks; the
    # fused PRE splits into an interior half (provably independent of the
    # exchange — the traced program carries no path from the ppermutes to
    # it) and a boundary half that consumes the buffered exchange, merged
    # by the global-gated interior mask (parallel/overlap.py). CFL dt
    # comes from the POST kernel's carried |u|/|v|(/|w|) maxima (max is
    # exact under any reduction order, so the trajectory equals the
    # serial schedule's — parity test-pinned).
    #   "auto" overlap when eligible: a real TPU + the fused deep-halo
    #          step dispatched (jnp paths and PAMPI_FAULTS field-fault
    #          builds keep the serial schedule;
    #          utils/dispatch.resolve_overlap records every decision
    #          under the "overlap_ns2d_dist"/"overlap_ns3d_dist" keys)
    #   "on"   force (interpret kernels off-TPU — the parity-test mode)
    #   "off"  the serial schedule (bitwise the historical program —
    #          jaxpr-hash identity vs CONTRACTS.json)
    tpu_overlap: str = "auto"
    # grid restriction of the overlapped PRE halves (parallel/overlap.py
    # region plan + ops/ns*_fused region grids): instead of two full
    # write-gated sweeps, the interior half's Pallas grid covers only the
    # row blocks of the interior core and the boundary half only the
    # OVERLAP_RIM (edge row bands + narrow column strips on partitioned
    # column axes) — the ~2x PRE HBM traffic of the PR 8 split drops back
    # toward 1x once PRE is bandwidth-bound.
    #   "auto" restrict when the overlapped schedule is dispatched AND the
    #          restricted plan's summed grid cells beat the two full
    #          sweeps at this shard geometry (tiny shards keep the full
    #          write-gated halves — banding cannot win below a few row
    #          blocks); decision recorded under the
    #          "overlap_grid_<family>" dispatch keys with the call count
    #   "on"   force the restricted plan whenever the overlap schedule
    #          runs (the structural-test/smoke mode)
    #   "off"  always the two full write-gated halves (the PR 8 program)
    tpu_overlap_restrict: str = "auto"
    # mesh-tier map for hierarchical halo exchange (parallel/comm
    # ExchangeSchedule): "auto" = every axis one tier (today's single-
    # slice meshes — exchange order and traces bitwise-unchanged), or a
    # comma list "axis=tier" over ici|dcn, e.g. "k=dcn,j=ici,i=ici" for a
    # multi-slice pod whose k axis crosses the DCN. DCN-tier strips are
    # posted FIRST (deepest/earliest — they have the most latency to
    # hide), ICI strips last, in every persistent ExchangeSchedule; the
    # comm census and the BENCH plane break traffic out per tier
    # (dcn_exchange_bytes).
    tpu_mesh_tiers: str = "auto"
    # residual-adaptive solve budget (ROADMAP item 1's last open bullet):
    # 0 (default) keeps the static itermax cap. N > 0 lets the previous
    # step's (res, it) shrink the NEXT step's sweep budget inside the
    # chunk loop: a solve that converged in `it` sweeps caps the next at
    # it + N (the slack); a capped solve restores the full itermax. The
    # budget rides the chunk carry (external arity unchanged, resets per
    # chunk dispatch); dist SOR paths only (mg counts cycles, fft does
    # not iterate) — the decision is recorded under the
    # "itermax_adaptive_<family>" dispatch keys and the per-step `it`
    # telemetry shows the budget taking effect.
    tpu_itermax_adaptive: int = 0
    # scenario-fleet dispatch (pampi_tpu/fleet/): how a bucket of
    # same-signature requests is executed by the fleet scheduler
    # (utils/dispatch.resolve_fleet records every decision under the
    # per-bucket `fleet_<bucket>` keys).
    #   "auto"  vmap-batch single-device buckets with >1 scenario (one
    #           compiled program advances every lane; a diverged lane is
    #           frozen by the in-band sentinel, batchmates continue);
    #           distributed buckets and 1-scenario buckets run pjit:
    #           each scenario occupies the whole mesh sequentially,
    #           reusing the bucket's one compiled program
    #   "auto" additionally picks "mesh" (below) when a multi-device
    #           host can split the lanes evenly
    #   "vmap"  force the batched driver (dist buckets too — vmap over
    #           the shard_map'ed chunk; the parity-test mode)
    #   "mesh"  fleet-over-mesh (serving v2): the vmapped chunk's
    #           scenario axis sharded across a device-mesh axis via
    #           NamedSharding — N single-chip lanes in true parallel,
    #           zero collectives between lanes (commcheck's
    #           zero-resharding ban pins it); lanes must divide the
    #           device count
    #   "pjit"  force whole-mesh-per-scenario with executable reuse
    #   "solo"  the historical path: every request builds and runs its
    #           own solver (no template reuse; the oracle mode the
    #           fleet-smoke drift check compares against)
    # Serving v2 (fleet/serve.py): `te` is per-lane (carried in the
    # batched chunk state), so mixed end times share one compile; the
    # scheduler's shape classes and continuous lane pool are daemon/
    # constructor knobs, not .par keys — see README "Fleet serving".
    tpu_fleet: str = "auto"
    # MG stall detector (tpu_solver mg only): a V-cycle whose residual
    # changed less than this RELATIVE tolerance is treated as floored and
    # the solve returns early (ops/multigrid.MG_STALL_RTOL rationale). Set 0
    # to disable and burn itermax like the reference's capped solves do.
    tpu_mg_stall_rtol: float = 1e-4
    # fused MG cycle (tpu_solver mg only): auto|on|off. On eligible plans
    # the whole V-cycle runs as TWO dynamic-extent Pallas launches (DOWN:
    # smooth+restrict all levels, UP: prolong+smooth; ops/mg_fused.py)
    # with the exact direct bottom solve between them, instead of the
    # per-level smoother-launch ladder. "on" also enables the coarse-level
    # continuation in the distributed MG bottoms (gather below the shard
    # floor and keep coarsening globally — "mg_aggregate" seam) and the
    # FFT-preconditioned coarse application for over-budget obstacle
    # bottoms. "auto" dispatches the fused cycle on TPU only and keeps the
    # historical distributed bottoms; "off" is bitwise the historical
    # ladder. Decisions recorded via utils/dispatch ("mg2d_fused", ...).
    tpu_mg_fused: str = "auto"
    # capped-solve flat path (models/poisson.make_solver_fn flat=True,
    # tpu_solver sor only): the pressure solve runs EXACTLY
    # ceil(itermax/n_inner) kernel trips under fori_loop instead of the
    # res-gated while. BITWISE identical on configs whose solves always
    # hit itermax (the north-star cavity, the reference's canal configs);
    # converging configs overdrive to the cap (extra sweeps only lower
    # the residual). MEASURED neutral at 4096² (19.01 vs 19.04 ms/step,
    # interleaved A/B, round 5): the loop TRIP overhead, not the residual
    # gating, is the per-trip cost — kept as the structural option it is,
    # not a speed claim. 0 = off (default).
    tpu_flat_solve: int = 0
    # time-loop dispatch pipelining (models/_driver.drive_chunks): up to
    # this many chunk dispatches queued BEYOND the one the host is
    # confirming (so lookahead+1 states in flight), hiding the per-chunk
    # host<->device round trip (under the axon tunnel: 19.4 -> 17.7 ms/step
    # at dcavity 4096^2 = the latency-cancelled protocol rate). 0 restores
    # dispatch-then-sync. Progress/checkpoint hooks see every chunk, just
    # this many chunks late. Cost: lookahead extra state copies on device.
    tpu_lookahead: int = 2
    # device steps per chunk dispatch (0 = the model default: 64 2-D, 32
    # 3-D). An escape hatch for programs the TPU runtime mishandles when
    # the step is wrapped in a multi-trip chunk loop (observed: 4096^2 f64
    # sor_lex crashes the TPU worker at any chunk > 1 — scan-in-while f64
    # at size — while tpu_chunk 1 runs; f32 production runs keep 64).
    tpu_chunk: int = 0
    # K-step fused chunks (ISSUE 17): auto|on|off|<int K>. When K >= 2
    # each trip of the chunk while-loop advances K steps inside ONE
    # `lax.scan` (the residual-adaptive itermax cap and the CFL/dt
    # scalars ride the scan carry; steps past te run a frozen identity
    # branch), so dispatch/carry-reshuffle overhead amortizes over K and
    # the static launches-per-step drops below 3. External chunk arity is
    # UNCHANGED — checkpoints, ring recovery, the coordinator fault word
    # and the fleet's BatchedSolver see the same state tuple. "off" (and
    # any resolution to K=1) is bitwise the historical chunk (jaxpr-hash
    # pinned in CONTRACTS.json); "auto" fuses K=4 on TPU only; "on"
    # forces K=4 anywhere (the CPU smoke/parity shape); an integer forces
    # that K (must divide the chunk length). Decisions recorded via
    # utils/dispatch ("<family>_chunk_fuse").
    tpu_chunk_fuse: str = "auto"
    # per-tier exchange depth (ISSUE 17): "axis=H" (e.g. "i=4") ships
    # depth-H halo strips on that DCN-tier axis so ONE slow exchange
    # covers H fused scan steps, while ICI axes keep fresh depth-1/deep
    # exchanges every step. RELAXED parity: slow-tier halo data is up to
    # H-1 steps stale at the strip's outer rim (the partitioned-
    # communication / halo-widening trade — PAPERS.md); CFL maxima stay
    # conservative. Eligibility (fused serial dist step, chunk_fuse
    # K >= 2 with H | K, tiered mesh with the axis declared dcn, shard
    # extent >= H, not ragged) is checked per build and refusals are
    # recorded ("<family>_exchange_depth"). "auto"/"off" = no depth map
    # (exact parity is never silently traded).
    tpu_exchange_depth: str = "auto"
    # 3-D VTK output mode: "ascii" (reference default), "binary", or
    # "sharded" — the MPI-IO-pattern parallel write (utils/vtkio.py
    # ShardedVtkWriter; binary, byte-identical to "binary"). On a
    # single-device run "sharded" degrades to "binary" (same bytes).
    tpu_vtk: str = "ascii"
    # checkpoint/restart (utils/checkpoint.py; the reference has none).
    # Writes rotate the live file to <path>.prev first (two generations on
    # disk) and carry per-field CRC32s; load rejects torn/corrupt files and
    # falls back to the .prev generation (README "Robustness").
    tpu_checkpoint: str = ""
    tpu_ckpt_every: int = 10
    tpu_restart: str = ""
    # elastic checkpoint format (utils/checkpoint.save_elastic): a JSON
    # manifest + per-rank shard files holding the MESH-INDEPENDENT global
    # reference-layout fields, so restore accepts a DIFFERENT mesh (or a
    # single device) by reassembling and resharding via NamedSharding —
    # the 8->4->1 chip shrink and the fleet autoscaling primitive
    # (fleet/scheduler.FleetScheduler.elastic_restore). 0 (default) keeps
    # the legacy single-.npz stacked-block format, which is
    # mesh-locked but preserves ghost state bit-exactly.
    tpu_ckpt_elastic: int = 0
    # chunk-boundary agreement protocol (parallel/coordinator.py):
    # auto = coordinate exactly under a multi-process launch (lifting
    # the PR 4 transient_budget=0 ban — the global budget, rollback and
    # checkpoint decisions are agreed via a host-side allgather at each
    # boundary), on = force the 1-rank coordinator single-process (the
    # protocol-path proof shape), off = the historical uncoordinated
    # loop (multi-process faults kill the job cleanly).
    tpu_coord: str = "auto"
    # boundary-allgather watchdog (parallel/coordinator.py, PR 12):
    # seconds a rank waits at the chunk-boundary rendezvous before the
    # survivors declare the silent rank(s) DEAD via the membership
    # agreement round and raise RankDeadError. Keep it well UNDER the
    # backend's own collective timeout (XLA cross-host barriers default
    # to 10+ minutes) so the host-side rendezvous is where a death
    # surfaces, and above the slowest honest chunk (a cold compile
    # inside a dispatch must not read as a death). 0 disables (the
    # pre-PR-12 hang-until-backend behavior).
    tpu_coord_timeout: float = 300.0
    # shrink-to-survivors resume (cli.py / fleet/scheduler.shrink_resume):
    # 1 (default) = on RankDeadError, when an elastic checkpoint is
    # armed, restore the newest agreed generation (+ fault ledger) onto
    # the surviving capacity and finish the run degraded; 0 = surface
    # the structured error and stop (operator-driven resume). The
    # in-process resume covers the single-process shapes (one host
    # owning local devices; the lockstep proof path) — under a real
    # multi-process launch the survivors PRINT the relaunch walkthrough
    # instead (an in-place process-group shrink would need a re-elected
    # coordinator; see cli._resume_after_death).
    tpu_dead_resume: int = 1
    # serving autopilot (fleet/autopilot.py, ISSUE 19): the policy loop
    # that closes observe->decide->act inside the daemon's poll cycle —
    # "off" (default: the daemon is byte-identical to the policy-less
    # build, test-pinned) or "on[:k=v,...]" with hysteresis overrides
    # (burn_high/burn_low/backlog_high/sustain/cooldown/min_lanes/
    # max_lanes/idle_polls/itermax_cap/flap_window — see
    # fleet/autopilot.parse_autopilot_spec). On: a RankDeadError from the
    # resident elastic job auto-`shrink_resume`s onto survivor capacity
    # (ledger carried), sustained SLO burn/backlog grows the lane pool
    # (checkpoint-fenced via the elastic manifest), sustained idle
    # shrinks it, and past capacity the daemon steps down the explicit
    # degradation ladder (class-lane consolidation -> itermax caps ->
    # lowest-priority admission shedding), back up when burn recovers.
    # Every decision is an `autoscale` telemetry record. A HOUSEKEEPING
    # key: never part of the bucket signature or traced programs.
    tpu_autopilot: str = "off"
    # divergence rollback-recovery (models/_driver.RingRecovery; README
    # "Robustness"): tpu_recover_ring > 0 arms an in-memory ring of the
    # last-K confirmed finite chunk states (no disk round-trip on the hot
    # path; the on-disk tpu_checkpoint is the cold tier when the ring is
    # exhausted). On a NaN loop time the drive loop rolls back to the
    # newest ring entry (successive attempts dig deeper) and re-drives
    # with dt clamped by tpu_recover_dt_scale (cumulative per attempt),
    # at most tpu_recover_max attempts per run — each attempt emits a
    # structured `recover` telemetry record. 0 (default) keeps the
    # historical terminate-on-NaN behavior. Memory cost: ring x one state
    # tuple held on device.
    tpu_recover_ring: int = 0
    tpu_recover_dt_scale: float = 0.5
    tpu_recover_max: int = 3
    # retry-budget replenishment (models/_driver.drive_chunks): the
    # one-shot transient device-fault budget refills — and a pallas->jnp
    # runtime fallback is allowed to restore the pallas chunk — after this
    # many consecutive clean chunks, so a 10-hour run survives more than
    # one spaced transient. 0 = never refill (the historical
    # one-fault-per-run budget).
    tpu_retry_replenish: int = 8
    # keys explicitly present in the parsed file (not a .par key itself);
    # lets the driver tell a 3-D config (kmax/zlength/bcFront set) from a
    # 2-D one, since the reference distinguishes by binary instead
    seen_keys: tuple = ()

    def replace(self, **kw) -> "Parameter":
        return dataclasses.replace(self, **kw)


_FIELDS = {
    f.name: f.type
    for f in dataclasses.fields(Parameter)
    if f.name != "seen_keys"
}
_CASTS = {"int": int, "float": float, "str": str}


def _parse_line(line: str):
    line = line.split("#", 1)[0]
    toks = line.split()
    if len(toks) < 2:
        return None
    return toks[0], toks[1]


def read_parameter(path: str, base: Parameter | None = None) -> Parameter:
    """Parse a .par file. Prefix-match keys like the reference parser does."""
    param = dataclasses.replace(base) if base is not None else Parameter()
    try:
        fh = open(path)
    except OSError:
        print(f"Could not open parameter file: {path}", file=sys.stderr)
        raise SystemExit(1)
    seen = set(param.seen_keys)
    with fh:
        for raw in fh:
            kv = _parse_line(raw)
            if kv is None:
                continue
            tok, val = kv
            # reference semantics: every known key whose name is a prefix of the
            # token gets assigned (independent `if`s, not elif) — EXCEPT an
            # exact key name, which assigns only itself: the framework keys
            # are namespaced (tpu_coord / tpu_coord_timeout) where the
            # reference's key set is prefix-free, so without exact-wins the
            # longer key's line would clobber the shorter key too
            keys = ([tok] if tok in _FIELDS
                    else [k for k in _FIELDS if tok.startswith(k)])
            for key in keys:
                ftype = _FIELDS[key]
                cast = _CASTS[ftype if isinstance(ftype, str) else ftype.__name__]
                try:
                    setattr(param, key, cast(val))
                    seen.add(key)
                except ValueError:
                    print(
                        f"bad value {val!r} for parameter {key}", file=sys.stderr
                    )
                    raise SystemExit(1)
    param.seen_keys = tuple(sorted(seen))
    return param


def is_3d_config(p: Parameter) -> bool:
    """True when the .par explicitly configures the third dimension (the
    reference distinguishes 2-D/3-D by binary; we dispatch on the geometry/BC
    keys every real 3-D config sets)."""
    return p.name.endswith("3d") or any(
        k in p.seen_keys for k in ("kmax", "zlength", "bcFront", "bcBack")
    )


def print_parameter(p: Parameter, out=None) -> None:
    """Echo the configuration (parity: A5 parameter.c:88-111 for 2-D configs,
    A6 parameter.c:95-126 — Front/Back, W, z-dims — for 3-D ones)."""
    out = out if out is not None else sys.stdout
    w = out.write
    three_d = is_3d_config(p)
    w(f"Parameters for {p.name}\n")
    if three_d:
        w(
            "Boundary conditions Left:%d Right:%d Bottom:%d Top:%d Front:%d "
            "Back:%d\n"
            % (p.bcLeft, p.bcRight, p.bcBottom, p.bcTop, p.bcFront, p.bcBack)
        )
    else:
        w(
            "Boundary conditions Left:%d Right:%d Bottom:%d Top:%d\n"
            % (p.bcLeft, p.bcRight, p.bcBottom, p.bcTop)
        )
    w("\tReynolds number: %.2f\n" % p.re)
    if three_d:
        w(
            "\tInit arrays: U:%.2f V:%.2f W:%.2f P:%.2f\n"
            % (p.u_init, p.v_init, p.w_init, p.p_init)
        )
    else:
        w("\tInit arrays: U:%.2f V:%.2f P:%.2f\n" % (p.u_init, p.v_init, p.p_init))
    w("Geometry data:\n")
    if three_d:
        w(
            "\tDomain box size (x, y, z): %.2f, %.2f, %.2f\n"
            % (p.xlength, p.ylength, p.zlength)
        )
        w("\tCells (x, y, z): %d, %d, %d\n" % (p.imax, p.jmax, p.kmax))
    else:
        w("\tDomain box size (x, y): %.2f, %.2f\n" % (p.xlength, p.ylength))
        w("\tCells (x, y): %d, %d\n" % (p.imax, p.jmax))
    w("Timestep parameters:\n")
    w("\tDefault stepsize: %.2f, Final time %.2f\n" % (p.dt, p.te))
    w("\tTau factor: %.2f\n" % p.tau)
    w("Iterative solver parameters:\n")
    w("\tMax iterations: %d\n" % p.itermax)
    w("\tepsilon (stopping tolerance) : %f\n" % p.eps)
    w("\tgamma factor: %f\n" % p.gamma)
    w("\tomega (SOR relaxation): %f\n" % p.omg)


def print_solver_config(p, grid, dt_bound, out=None) -> None:
    """The reference's -DVERBOSE solver-config block, 3-D driver only
    (assignment-6/src/solver.c:36-73 printConfig, gated like main.c's
    VERBOSE): computed grid spacings and the CFL dt bound, on top of the
    always-printed parameter echo (print_parameter)."""
    out = out or sys.stdout
    w = out.write
    w("Parameters for #%s#\n" % p.name)
    w(
        "BC Left:%d Right:%d Bottom:%d Top:%d Front:%d Back:%d\n"
        % (p.bcLeft, p.bcRight, p.bcBottom, p.bcTop, p.bcFront, p.bcBack)
    )
    w("\tReynolds number: %.2f\n" % p.re)
    w("\tGx Gy: %.2f %.2f %.2f\n" % (p.gx, p.gy, p.gz))
    w("Geometry data:\n")
    w(
        "\tDomain box size (x, y, z): %.2f, %.2f, %.2f\n"
        % (grid.xlength, grid.ylength, grid.zlength)
    )
    w("\tCells (x, y, z): %d, %d, %d\n" % (grid.imax, grid.jmax, grid.kmax))
    w(
        "\tCell size (dx, dy, dz): %f, %f, %f\n" % (grid.dx, grid.dy, grid.dz)
    )
    w("Timestep parameters:\n")
    w("\tDefault stepsize: %.2f, Final time %.2f\n" % (p.dt, p.te))
    w("\tdt bound: %.6f\n" % dt_bound)
    w("\tTau factor: %.2f\n" % p.tau)
    w("Iterative parameters:\n")
    w("\tMax iterations: %d\n" % p.itermax)
    w("\tepsilon (stopping tolerance) : %f\n" % p.eps)
    w("\tgamma factor: %f\n" % p.gamma)
    w("\tomega (SOR relaxation): %f\n" % p.omg)


def validate_obstacle_layout(layout: str) -> None:
    """Obstacle flag fields run only on the masked checkerboard kernel
    (2-D and 3-D alike); reject a forced compressed layout instead of
    silently ignoring it. Shared by NS2DSolver and NS3DSolver."""
    if layout not in ("auto", "checkerboard"):
        raise ValueError(
            f"tpu_sor_layout {layout} does not support obstacle flag "
            "fields; obstacle runs use the masked checkerboard kernel "
            "(auto|checkerboard)"
        )
