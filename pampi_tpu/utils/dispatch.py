"""Dispatch probe: a per-process record of which execution path each solver
actually selected (pallas kernel vs jnp twin, layout, CA depth).

Tests assert on it (the distributed solvers must hit the Pallas path when
eligible — VERDICT round 2 item 1), and `__graft_entry__.dryrun_multichip`
prints it so the driver artifact shows the dispatch decision."""

from __future__ import annotations

_RECORD: dict[str, str] = {}


def record(key: str, value: str) -> None:
    _RECORD[key] = value
    # stream the decision to the flight recorder (no-op when PAMPI_TELEMETRY
    # is unset) — dryrun artifacts and the run report show every dispatch
    from . import telemetry

    telemetry.emit("dispatch", key=key, value=value)


def last(key: str) -> str | None:
    return _RECORD.get(key)


def snapshot() -> dict[str, str]:
    return dict(_RECORD)


def resolve_solver(param, obstacles: bool, ragged: bool = False):
    """`tpu_solver auto` -> the measured-best solver for the run's
    structure (VERDICT r4 item 4: the solver-selection knowledge lived only
    in BASELINE.md prose — a user typing `mg` on a plain 4096² grid got the
    worst solver with no warning). Returns the param with a concrete
    solver; every model resolves through here FIRST, so the downstream
    solver checks (fft-refuses-obstacles, ragged-refuses-mg/fft) see only
    concrete values. The default stays `sor` (reference-trajectory parity);
    `auto` is opt-in. Decision matrix (BASELINE.md measured rows):

    - ragged distributed runs -> sor (mg/fft structurally refuse the
      pad-with-mask decomposition; the flag-masked SOR kernel composes)
    - obstacles -> mg (dense exact bottom, converged solves: 6.9x the
      capped-SOR step in 2-D at 2048x512, results/obsdist_mg2048.json;
      4.8x in 3-D at 96³, results/obstacle_mg3d_96.json — round 4's
      '3-D mg 9x slower' was a cross-session measurement artifact, the
      same-session decomposition shows 4 cycles x 2.3 ms/cycle)
    - plain constant-coefficient grids -> fft (exact DCT direct solve in
      one application: 6.9 vs 12.7 ms/step at dcavity 4096², 146x at
      NS-3D 128³)
    """
    if param.tpu_solver != "auto":
        return param
    if ragged:
        choice, why = "sor", "ragged decomposition (mg/fft unsupported)"
    elif obstacles:
        choice, why = "mg", "obstacles: dense-bottom MG, converged solves"
    else:
        choice, why = "fft", "plain grid: exact DCT direct solve"
    record("solver_auto", f"{choice} ({why})")
    return param.replace(tpu_solver=choice)


def resolve_fuse_phases(param, backend: str, dtype, probe, key: str,
                        why_not: str | None = None) -> bool:
    """`tpu_fuse_phases` -> whether this build dispatches the fused NS
    step-phase kernels (ops/ns2d_fused.py / ns3d_fused.py), extending the
    measured `auto` matrix to the phase chain: the round-5 north-star
    decomposition showed the ~40-launch jnp chain at 6.4 ms/step vs a
    ~0.8 ms HBM floor, so on TPU fusing is the measured-best choice
    wherever the kernels exist. Decision recorded under `key` (dryrun
    artifacts, tests assert on it).

    backend is the model's retry-protocol backend: "jnp" (the pallas-retry
    fallback) always disables fusion — that IS the retry's contract.
    `why_not` marks structurally ineligible builds (shard extents smaller
    than the deep halo — ragged, distributed-obstacle and 3-D-obstacle
    builds fuse since PR 2); `probe` is the kernel-family one-time smoke
    test ("on" skips it: the interpret-mode force used by parity tests and
    dryruns)."""
    import jax
    import jax.numpy as jnp

    knob = param.tpu_fuse_phases
    if knob not in ("auto", "on", "off"):
        raise ValueError(
            f"tpu_fuse_phases must be auto|on|off, got {knob!r}"
        )
    if knob == "off":
        record(key, "jnp (tpu_fuse_phases off)")
        return False
    if backend == "jnp":
        record(key, "jnp (retry fallback backend)")
        return False
    if why_not is not None:
        record(key, f"jnp ({why_not})")
        return False
    if knob == "on":
        record(key, "pallas_fused (forced)")
        return True
    if jax.default_backend() != "tpu":
        record(key, "jnp (no TPU)")
        return False
    if jnp.dtype(dtype).itemsize > 4:
        record(key, "jnp (dtype not Mosaic-lowerable)")
        return False
    if not probe():
        record(key, "jnp (probe failed)")
        return False
    record(key, "pallas_fused")
    return True


def resolve_mg_fused(knob: str, backend: str, dtype, key: str,
                     why_not: str | None = None, probe=None) -> bool:
    """`tpu_mg_fused` -> whether this MG build dispatches the fused
    V-cycle kernels (ops/mg_fused.py: the whole restrict→smooth→prolong
    chain as two dynamic-extent Pallas launches per cycle) instead of the
    per-level smoother-launch ladder. Decision recorded under `key`
    ("mg2d_fused", "mg3d_fused", "mg2d_obstacle_fused", ... — the factory
    re-records with the launch/level census once the kernels are built).

    Same contract as resolve_fuse_phases: "off" and the retry-fallback
    backend are hard offs; `why_not` marks structurally ineligible plans
    (single-level, VMEM-infeasible stacks, distributed builds — those
    get the coarse-aggregation seam instead); "on" forces dispatch before
    the backend checks (the interpret-mode force the parity tests and the
    CPU smoke drive use); `probe` is the kernel-family one-time smoke."""
    import jax
    import jax.numpy as jnp

    if knob not in ("auto", "on", "off"):
        raise ValueError(f"tpu_mg_fused must be auto|on|off, got {knob!r}")
    if knob == "off":
        record(key, "jnp (tpu_mg_fused off)")
        return False
    if backend == "jnp":
        record(key, "jnp (retry fallback backend)")
        return False
    if why_not is not None:
        record(key, f"jnp ({why_not})")
        return False
    if knob == "on":
        record(key, "pallas_fused_cycle (forced)")
        return True
    if jax.default_backend() != "tpu":
        record(key, "jnp (no TPU)")
        return False
    if jnp.dtype(dtype).itemsize > 4:
        record(key, "jnp (dtype not Mosaic-lowerable)")
        return False
    if probe is not None and not probe():
        record(key, "jnp (probe failed)")
        return False
    record(key, "pallas_fused_cycle")
    return True


def resolve_overlap(param, key: str, why_not: str | None = None) -> bool:
    """`tpu_overlap` -> whether this dist build dispatches the
    double-buffered comm/compute-overlap schedule (parallel/overlap.py:
    interior/boundary PRE split, the step N+1 deep exchange posted after
    step N's POST) instead of the serial exchange-then-compute step.
    Decision recorded under `key` ("overlap_ns2d_dist" /
    "overlap_ns3d_dist" — the dryrun snapshot and tests assert on it).

    `why_not` marks structurally ineligible builds: the overlap schedule
    rides the fused deep-halo step (a jnp phase chain has per-phase
    exchanges that cannot be posted early without redundant halo
    recompute), and PAMPI_FAULTS field-fault builds keep the serial
    schedule (the in-step fault write would postdate the posted
    exchange). `off` must reproduce the serial schedule bitwise — the
    jaxpr-hash identity contract vs CONTRACTS.json."""
    import jax

    knob = param.tpu_overlap
    if knob not in ("auto", "on", "off"):
        raise ValueError(
            f"tpu_overlap must be auto|on|off, got {knob!r}"
        )
    if knob == "off":
        record(key, "serial (tpu_overlap off)")
        return False
    if why_not is not None:
        record(key, f"serial ({why_not})")
        return False
    if knob == "on":
        record(key, "overlap (forced)")
        return True
    if jax.default_backend() != "tpu":
        record(key, "serial (no TPU)")
        return False
    record(key, "overlap")
    return True


def resolve_overlap_restrict(param, key: str, plan,
                             why_not: str | None = None) -> bool:
    """`tpu_overlap_restrict` -> whether the overlapped PRE halves run
    GRID-RESTRICTED (parallel/overlap.region_plan: the interior half's
    Pallas grid bands over the interior core only, the boundary half
    over the OVERLAP_RIM bands) instead of two full write-gated sweeps.
    Decision recorded under `key` ("overlap_grid_<family>") with the
    swept-cell accounting, so the dryrun snapshot shows the ~2x-PRE-HBM
    question answered per build.

    `plan` is the region plan (None = the interior region is empty —
    boundary-everywhere, nothing to restrict). `auto` restricts exactly
    when the plan's summed banded cells beat the two full sweeps at this
    shard geometry; tiny shards keep the full halves (banding cannot
    win below a few row blocks). `on` forces the restricted plan
    (structural tests / smoke); `off` keeps the PR 8 full halves."""
    knob = param.tpu_overlap_restrict
    if knob not in ("auto", "on", "off"):
        raise ValueError(
            f"tpu_overlap_restrict must be auto|on|off, got {knob!r}"
        )
    if knob == "off":
        record(key, "full (tpu_overlap_restrict off)")
        return False
    if why_not is not None:
        record(key, f"full ({why_not})")
        return False
    if plan is None:
        record(key, "full (interior region empty: boundary-everywhere)")
        return False
    cells, full = plan["cells"], plan["cells_full"]
    if knob == "on":
        record(key, f"restricted (forced; {cells} vs {full} cells)")
        return True
    if plan["win"]:
        record(key, f"restricted (grid plan wins: {cells} vs {full} "
                    "cells)")
        return True
    record(key, f"full (banding cannot win at this shard geometry: "
                f"{cells} vs {full} cells)")
    return False


def resolve_class(key: str, grid, why_not: str | None) -> bool:
    """Shape-class eligibility of ONE request, recorded per bucket like
    `tpu_overlap`/`fleet_<bucket>` (ISSUE 15 satellite): `key` is
    `class_<bucket>` — the CLASS bucket's label when eligible, the
    exact-shape bucket's when not — `grid` the padded class rungs, and
    `why_not` the `fleet/shapeclass.class_eligible` refusal string. A
    tenant silently landing on the exact-shape bucket is then visible in
    the dispatch snapshot and the telemetry report. Returns whether the
    request rides a class bucket."""
    if why_not is not None:
        record(key, f"exact ({why_not})")
        return False
    record(key, f"class (padded {'x'.join(str(g) for g in grid)})")
    return True


def resolve_fleet(param, n_scenarios: int, dist: bool, key: str) -> str:
    """`tpu_fleet` -> how the fleet scheduler executes one bucket of
    same-signature scenario requests (pampi_tpu/fleet/scheduler.py).
    Returns "vmap" (the batched driver: one vmapped chunk advances every
    lane), "pjit" (whole-mesh per scenario, sequential, reusing the
    bucket's compiled program) or "solo" (every request its own solver —
    the historical path and the drift-check oracle). Decision recorded
    under `key` (one `fleet_<bucket>` key per bucket — the fleet summary
    and tests assert on it).

    `auto` policy: vmap for single-device buckets with more than one
    scenario (scenario-parallelism is embarrassingly parallel — the
    batch rides one program at near-100% efficiency); MESH — the fleet
    v2 middle mode: the vmapped chunk's scenario axis sharded across a
    device-mesh axis via NamedSharding — when a multi-device host can
    split the lanes evenly (a v5e-8 serves 8 single-chip lanes in true
    parallel, zero collectives between lanes); pjit for distributed
    buckets (vmapping a shard_map'ed chunk multiplies per-device live
    state by the lane count — whole-mesh sequential keeps the memory
    bound while still amortizing the compile) and for 1-scenario
    buckets (a size-1 batch axis buys nothing)."""
    import jax

    knob = param.tpu_fleet
    if knob not in ("auto", "vmap", "mesh", "pjit", "solo"):
        raise ValueError(
            f"tpu_fleet must be auto|vmap|mesh|pjit|solo, got {knob!r}"
        )
    if knob == "solo":
        record(key, "solo (tpu_fleet solo)")
        return "solo"
    if knob == "mesh":
        if dist:
            raise ValueError(
                "tpu_fleet mesh shards the SCENARIO axis — a "
                "distributed bucket already shards its grids; use "
                "auto/pjit")
        n_dev = len(jax.devices())
        if n_scenarios % max(1, n_dev) != 0:
            raise ValueError(
                f"tpu_fleet mesh needs lanes ({n_scenarios}) divisible "
                f"by devices ({n_dev})")
        record(key, f"mesh (forced; {n_scenarios} lanes over "
                    f"{n_dev} devices)")
        return "mesh"
    if knob in ("vmap", "pjit"):
        record(key, f"{knob} (forced)")
        return knob
    if dist:
        record(key, "pjit (dist bucket: whole-mesh per scenario)")
        return "pjit"
    if n_scenarios <= 1:
        record(key, "pjit (single-scenario bucket)")
        return "pjit"
    n_dev = len(jax.devices())
    if (n_dev > 1 and n_scenarios % n_dev == 0
            and jax.default_backend() != "cpu"):
        # real accelerators only: a CPU "mesh" is virtual host devices
        # sharing one core — sharding lanes across it serializes them
        # with partitioning overhead on top (measured ~10x the vmap
        # warm rate on this container), so auto keeps vmap there and
        # `tpu_fleet mesh` remains the forced/test mode
        record(key, f"mesh (scenario axis over {n_dev} devices, "
                    f"{n_scenarios // n_dev} lanes each)")
        return "mesh"
    record(key, f"vmap (same-trace bucket of {n_scenarios})")
    return "vmap"


def resolve_coord(param, key: str) -> str:
    """`tpu_coord` -> whether this run's drive loop rides the chunk-
    boundary agreement protocol (parallel/coordinator.py). Returns
    "multihost" (real cross-process allgather transport), "solo" (the
    1-rank coordinator — protocol path exercised without a launch) or
    "none" (the exact historical uncoordinated loop). Decision recorded
    under `key` ("coord_<family>") like every other knob.

    `auto` policy: coordinate exactly when there is more than one OS
    process — that is when a rank-local retry would desynchronize
    collectives (the PR 4 ban this protocol lifts). `off` restores the
    ban: multi-process runs get transient_budget=0 and any fault kills
    the job cleanly."""
    import jax

    knob = param.tpu_coord
    if knob not in ("auto", "on", "off"):
        raise ValueError(f"tpu_coord must be auto|on|off, got {knob!r}")
    if knob == "off":
        record(key, "uncoordinated (tpu_coord off)")
        return "none"
    nprocs = jax.process_count()
    if nprocs > 1:
        record(key, f"coordinated ({nprocs} processes)")
        return "multihost"
    if knob == "on":
        record(key, "coordinated (forced, 1 process)")
        return "solo"
    record(key, "uncoordinated (single process)")
    return "none"


_CHUNK_FUSE_K = 4  # the auto/forced K: divides both model chunks (64, 32)


def resolve_chunk_fuse(param, key: str, chunk: int,
                       why_not: str | None = None) -> int:
    """`tpu_chunk_fuse` -> the number of steps one trip of the chunk
    while-loop advances (ISSUE 17). K == 1 is EXACTLY the historical
    chunk (the builders keep the old body verbatim — the jaxpr-hash
    identity contract); K >= 2 wraps K gated steps in one `lax.scan`
    whose body traces ONCE, so the static launches-per-step is the
    K=1 launch count divided by K. Decision recorded under `key`
    ("<family>_chunk_fuse") in a form jaxprcheck parses ("K=<n>").

    `why_not` marks structurally ineligible builds (the overlapped
    schedule carries its own cross-step pipeline; K must divide the
    chunk so nt stays exact at every boundary). `auto` fuses on TPU
    only — off-TPU the historical trace is kept bitwise, so the
    committed CONTRACTS.json hashes stay valid."""
    import jax

    knob = param.tpu_chunk_fuse
    if knob == "off":
        record(key, "historical (tpu_chunk_fuse off)")
        return 1
    if knob not in ("auto", "on"):
        try:
            k = int(knob)
        except ValueError:
            raise ValueError(
                f"tpu_chunk_fuse must be auto|on|off|<int>, got {knob!r}"
            ) from None
        if k < 1:
            raise ValueError(
                f"tpu_chunk_fuse K must be >= 1, got {k}")
    else:
        k = _CHUNK_FUSE_K
    if why_not is not None:
        record(key, f"historical ({why_not})")
        return 1
    if k == 1:
        record(key, "historical (K=1)")
        return 1
    if chunk % k != 0:
        record(key, f"historical (K={k} does not divide chunk {chunk})")
        return 1
    if knob == "on":
        record(key, f"scan (K={k}, forced)")
        return k
    if knob == "auto" and jax.default_backend() != "tpu":
        record(key, "historical (no TPU)")
        return 1
    record(key, f"scan (K={k})")
    return k


def resolve_exchange_depth(param, key: str, k: int, tiers: dict,
                           axis_names, shard_extents, min_depth: int,
                           why_not: str | None = None) -> dict:
    """`tpu_exchange_depth` -> the per-tier depth map {axis: H} for the
    fused dist step's u/v exchanges (ISSUE 17): the mapped DCN axis
    ships ONE depth-H strip per H fused scan steps while every other
    axis keeps its fresh per-step exchange. Returns {} (no depth
    scheduling) unless the build is eligible; refusals are recorded
    under `key` ("<family>_exchange_depth") with the reason.

    This is a RELAXED-parity trade (bounded staleness on the slow-tier
    rim), so `auto` NEVER silently enables it — the map only arms on an
    explicit "axis=H". Eligibility: K-step fusion active with H | K,
    H >= the fused step's own deep-halo depth (`min_depth`), the axis
    present, declared dcn-tier and actually partitioned, and the shard
    extent on it >= H (the owned strip must cover the fat halo)."""
    knob = param.tpu_exchange_depth
    if knob in ("auto", "off"):
        record(key, f"per-step (tpu_exchange_depth {knob})")
        return {}
    try:
        ax, hs = knob.split("=")
        ax, h = ax.strip(), int(hs)
    except ValueError:
        raise ValueError(
            f"tpu_exchange_depth must be auto|off|<axis>=<H>, got "
            f"{knob!r}") from None
    if h < 1:
        raise ValueError(f"tpu_exchange_depth H must be >= 1, got {h}")
    if why_not is not None:
        record(key, f"per-step ({why_not})")
        return {}
    if k < 2:
        record(key, "per-step (needs tpu_chunk_fuse K >= 2)")
        return {}
    if k % h != 0:
        record(key, f"per-step (H={h} does not divide K={k})")
        return {}
    if h < min_depth:
        record(key, f"per-step (H={h} < deep halo {min_depth})")
        return {}
    if ax not in axis_names:
        record(key, f"per-step (no axis {ax!r} on this mesh)")
        return {}
    i = list(axis_names).index(ax)
    if shard_extents[i] < h:
        record(key, f"per-step (shard extent {shard_extents[i]} on "
                    f"{ax!r} < H={h})")
        return {}
    if tiers.get(ax, "ici") != "dcn":
        record(key, f"per-step (axis {ax!r} is not dcn-tier)")
        return {}
    record(key, f"depth ({ax}={h}: 1 {ax}-exchange per {h} steps)")
    return {ax: h}
