"""Dispatch probe: a per-process record of which execution path each solver
actually selected (pallas kernel vs jnp twin, layout, CA depth).

Tests assert on it (the distributed solvers must hit the Pallas path when
eligible — VERDICT round 2 item 1), and `__graft_entry__.dryrun_multichip`
prints it so the driver artifact shows the dispatch decision."""

from __future__ import annotations

_RECORD: dict[str, str] = {}


def record(key: str, value: str) -> None:
    _RECORD[key] = value


def last(key: str) -> str | None:
    return _RECORD.get(key)


def snapshot() -> dict[str, str]:
    return dict(_RECORD)
