"""dtype policy: the `tpu_dtype` .par key selects the compute precision.

The reference is double everywhere (C99 `double`); on TPU the native fast path
is float32 (VPU) / bfloat16 (MXU), and float64 is software-emulated. Solvers
default to the .par's `tpu_dtype`; float64 requires jax_enable_x64 (the CLI
turns it on when requested)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DTYPES = {
    "float64": jnp.float64,
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "f64": jnp.float64,
    "f32": jnp.float32,
    "bf16": jnp.bfloat16,
}


def resolve_dtype(name: str):
    try:
        dt = _DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown tpu_dtype {name!r}; expected one of {sorted(_DTYPES)}"
        )
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        # requested double but x64 is off — fall back loudly
        import warnings

        warnings.warn(
            "tpu_dtype float64 requested but jax_enable_x64 is off; using float32"
        )
        return jnp.float32
    return dt
