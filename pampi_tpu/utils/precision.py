"""dtype policy: the `tpu_dtype` .par key selects the compute precision.

The reference is double everywhere (C99 `double`); on TPU the native fast path
is float32 (VPU) / bfloat16 (MXU), and float64 is software-emulated. Solvers
default to the .par's `tpu_dtype`; float64 requires jax_enable_x64 (the CLI
turns it on when requested)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DTYPES = {
    "float64": jnp.float64,
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "f64": jnp.float64,
    "f32": jnp.float32,
    "bf16": jnp.bfloat16,
}


def residual_floor(ncells: int, dtype) -> float:
    """The smallest L2-style residual a reduced-precision solve can
    reliably distinguish from zero: machine epsilon scaled by the RMS
    accumulation factor sqrt(ncells). Below roughly this level the
    residual is summation-order noise — two algebraically identical
    cycles (ladder vs fused) legitimately disagree on whether `eps` was
    reached, so an A/B at such an eps compares tail behaviour, not
    speed (the ROADMAP "eps at the f32 floor" footgun). f64 returns 0.0:
    no practical .par eps sits near its floor."""
    if jnp.dtype(dtype) == jnp.dtype(jnp.float32):
        return float(jnp.finfo(jnp.float32).eps) * float(ncells) ** 0.5
    return 0.0


def check_eps_floor(eps: float, ncells: int, dtype, where: str) -> bool:
    """Warn (host-side, build time — never inside a trace) when a
    convergence `eps` sits within a decade of the dtype's residual
    floor. Returns True when the warning fired. eps <= 0 is the
    explicit fixed-iteration comparison mode (run to itermax) and is
    always silent — that is the sanctioned way to A/B two cycle
    shapes at a floor-adjacent tolerance."""
    floor = residual_floor(ncells, dtype)
    if not (0.0 < float(eps) < 10.0 * floor):
        return False
    import warnings

    from . import telemetry as _tm

    warnings.warn(
        f"{where}: eps={eps:g} is within a decade of the "
        f"{jnp.dtype(dtype).name} residual floor (~{floor:.3g} at "
        f"{ncells} cells) — convergence there measures summation-order "
        "noise, not solver speed. For A/B timing, raise eps a decade "
        "above the floor or compare at fixed iteration counts "
        "(eps=0 runs every solve to itermax).",
        stacklevel=3,
    )
    _tm.emit("warning", component="precision", reason="eps_near_floor",
             where=where, eps=float(eps), floor=floor,
             ncells=int(ncells), dtype=jnp.dtype(dtype).name)
    return True


def resolve_dtype(name: str):
    try:
        dt = _DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown tpu_dtype {name!r}; expected one of {sorted(_DTYPES)}"
        )
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        # requested double but x64 is off — fall back loudly
        import warnings

        warnings.warn(
            "tpu_dtype float64 requested but jax_enable_x64 is off; using float32"
        )
        return jnp.float32
    return dt
