"""dtype policy: the `tpu_dtype` .par key selects the compute precision.

The reference is double everywhere (C99 `double`); on TPU the native fast path
is float32 (VPU) / bfloat16 (MXU), and float64 is software-emulated. Solvers
default to the .par's `tpu_dtype`; float64 requires jax_enable_x64 (the CLI
turns it on when requested)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DTYPES = {
    "float64": jnp.float64,
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "f64": jnp.float64,
    "f32": jnp.float32,
    "bf16": jnp.bfloat16,
}


# Convergence-feeding reductions whose accumulation order is an
# ACKNOWLEDGED precision trade rather than an oversight. Keyed by
# "<source file basename>:<accumulator dtype>" — the file names the
# reduction's home and the dtype names the trade, while staying stable
# under line churn. preccheck's reduction-order audit (the static twin
# of the fused-vs-ladder hazard the eps-floor caveat documents) requires
# every reduce feeding a convergence predicate to be f64-accumulated OR
# declared here; an undeclared sub-f64 accumulation fails the lint with
# the reduce's file:line. Declare sparingly, with a why.
DECLARED_ORDER_SENSITIVE = {
    # the SOR residual accumulation: the solve deliberately accumulates
    # the residual at res_dtype = promote(dtype, f32) so bf16 lanes
    # don't re-quantize the convergence scalar (models/poisson.py's
    # carry comment) — the eps-floor check prices the resulting
    # summation-order noise. One key per reduction home: the jnp rb
    # sweep, the tblock kernel, and the 3-D jnp solve.
    "sor.py:float32",
    "sor_pallas.py:float32",
    "ns3d.py:float32",
}


def residual_floor(ncells: int, dtype) -> float:
    """The smallest L2-style residual a reduced-precision solve can
    reliably distinguish from zero: machine epsilon scaled by the RMS
    accumulation factor sqrt(ncells). Below roughly this level the
    residual is summation-order noise — two algebraically identical
    cycles (ladder vs fused) legitimately disagree on whether `eps` was
    reached, so an A/B at such an eps compares tail behaviour, not
    speed (the ROADMAP "eps at the f32 floor" footgun). Any sub-f64
    float (f32, bf16, f16) has a floor; f64 returns 0.0: no practical
    .par eps sits near its floor."""
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.floating) and jnp.finfo(dt).bits < 64:
        return float(jnp.finfo(dt).eps) * float(ncells) ** 0.5
    return 0.0


def check_eps_floor(eps: float, ncells: int, dtype, where: str) -> bool:
    """Warn (host-side, build time — never inside a trace) when a
    convergence `eps` sits within a decade of the dtype's residual
    floor. Returns True when the warning fired. eps <= 0 is the
    explicit fixed-iteration comparison mode (run to itermax) and is
    always silent — that is the sanctioned way to A/B two cycle
    shapes at a floor-adjacent tolerance."""
    floor = residual_floor(ncells, dtype)
    if not (0.0 < float(eps) < 10.0 * floor):
        return False
    import warnings

    from . import telemetry as _tm

    warnings.warn(
        f"{where}: eps={eps:g} is within a decade of the "
        f"{jnp.dtype(dtype).name} residual floor (~{floor:.3g} at "
        f"{ncells} cells) — convergence there measures summation-order "
        "noise, not solver speed. For A/B timing, raise eps a decade "
        "above the floor or compare at fixed iteration counts "
        "(eps=0 runs every solve to itermax).",
        stacklevel=3,
    )
    _tm.emit("warning", component="precision", reason="eps_near_floor",
             where=where, eps=float(eps), floor=floor,
             ncells=int(ncells), dtype=jnp.dtype(dtype).name)
    return True


def cast(x, dtype, why: str):
    """The DECLARED downcast: every intentional narrowing conversion in
    library code routes through here, wrapped in a
    `precision.cast.<why>` named scope. preccheck's dtype-lattice pass
    reads that scope off the convert eqn's name stack (the same
    convention the comm census uses for `halo_exchange.*`): a narrowing
    convert under the scope is censused by its `why`; one without it is
    an IMPLICIT downcast and fails the lint. `why` is a short token
    ("metrics", "storage", "smoother") — it becomes the census key."""
    with jax.named_scope(f"precision.cast.{why}"):
        return jnp.asarray(x).astype(dtype)


def resolve_dtype(name: str, record_key: str | None = None):
    """Resolve a `tpu_dtype` .par value to the compute dtype. With
    `record_key` ("<family>_dtype"), the decision is recorded through
    `utils/dispatch.record` like every other knob, so MULTICHIP dryrun
    snapshots carry the per-family dtype decision and
    `check_artifact.lint_dispatch_snapshot` can require it."""
    try:
        dt = _DTYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown tpu_dtype {name!r}; expected one of {sorted(_DTYPES)}"
        )
    if dt == jnp.float64 and not jax.config.jax_enable_x64:
        # requested double but x64 is off — fall back loudly
        import warnings

        warnings.warn(
            "tpu_dtype float64 requested but jax_enable_x64 is off; using float32"
        )
        dt = jnp.float32
        if record_key is not None:
            from . import dispatch as _dispatch

            _dispatch.record(
                record_key,
                f"float32 (tpu_dtype={name}, jax_enable_x64 off)")
        return dt
    if record_key is not None:
        from . import dispatch as _dispatch

        _dispatch.record(record_key,
                         f"{jnp.dtype(dt).name} (tpu_dtype={name})")
    return dt
