"""Flight-recorder telemetry: in-band solver metrics + a JSONL run log.

Two planes, one switch (`PAMPI_TELEMETRY=<path>`, read at trace/call time
like `utils/flags.py` — unset means every call is a no-op and the traced
programs are UNCHANGED, test-asserted in tests/test_telemetry.py):

Device plane — the jitted chunk already carries scalars (the fused-phase
CFL maxima); with telemetry enabled the chunk additionally carries a small
METRICS vector (layout below): final pressure residual, solve iterations,
dt, velocity maxima, and a non-finite sentinel derived from those carried
scalars. The vector is read out only at chunk boundaries, where the host
already syncs on the loop time — the hot loop gains ZERO extra launches or
syncs; the extra per-step work is a handful of fused scalar ops (plus the
|u|/|v| max reductions on paths that did not already carry them). The
sentinel records the step count at which the state FIRST went non-finite,
upgrading a blow-up from silent NaN garbage to a structured diagnostic
naming the last-good step.

Host plane — every record is one JSON line appended to the
`PAMPI_TELEMETRY` file, schema-versioned (`"v"`) and kind-tagged:

  run         process/run metadata (emitted once, before any other record)
  dispatch    a `utils/dispatch.record` decision (streamed as it happens)
  build       solver construction: per-family trace/build wall time
  chunk       one host sync: steps, wall, ms/step, res/it/dt/maxima; the
              FIRST chunk record is compile-inclusive (includes_compile)
  divergence  the sentinel fired: first_bad_step / last_good_step
  recover     a divergence rollback-recovery attempt (models/_driver.
              RingRecovery): attempt #, rollback target t/nt, dt clamp
  retry       a retry-budget consumption (transient device fault retried,
              pallas->jnp fallback, pallas restore after clean chunks)
  ckpt        a checkpoint event (utils/checkpoint.py): save / rotate /
              load / reject / skip, plus the elastic-manifest events
              elastic_save / elastic_load (generation, writing mesh,
              fell_back), with path and t/nt where meaningful
  coord       one GLOBAL decision of the chunk-boundary agreement
              protocol (parallel/coordinator.py): armed / retry /
              fallback / rollback / ckpt / giveup / abort, with the
              boundary index and the decision's operand (budget_left,
              target_nt, ...). Emitted once per decision from rank 0 —
              the merged fault word is identical everywhere by
              construction, so one line IS the fleet's decision
  warning     a structured degradation notice from a subsystem that
              proceeded anyway (component + reason — e.g. utils/xlacache
              probing its cache dir unreachable and running uncached)
  dead        the boundary watchdog fired and the survivors' membership
              agreement round declared rank(s) DEAD (parallel/
              coordinator.py): agreed ranks, post-shrink epoch,
              boundary, watchdog window
  epoch       a shrink-epoch transition: the agreed new epoch plus the
              surviving rank set — the membership history of the run
  shrink      a shrink-to-survivors elastic resume committed
              (fleet/scheduler.shrink_resume): survivor capacity,
              restored generation, the dead set it recovers from
  ckpt (+v6)  the elastic events grow ledger_save / ledger_restore —
              the coordinator fault ledger riding the manifest
  solve       a driver-level Poisson solve (iters, residual, wall)
  halo        static per-shard halo-exchange byte counts (dist solvers)
  span        a named timing span — the ONE decomposition protocol the
              perf tools share (bench.py, tools/northstar.py, tools/perf_*);
              the dist solvers' `<family>.exchange` span records the
              serial critical-path cost of one step's declared halo
              schedule (parallel/comm.time_exchange_ms)
  xprof       one captured device-trace region (utils/xprof.capture):
              per-scope/collective/kernel device ms, busy/idle, and the
              exchange device-vs-exposed split behind the comm-hidden
              fraction
  metric      a headline metric line (bench.py's JSON lines, artifacts)
  metrics     one registry snapshot (utils/metrics.py): counters, gauges,
              and log-bucket histograms labeled tenant/class/family, with
              a per-process source id + sequence number — snapshots are
              CUMULATIVE, so readers take the LAST per source and fold
              ACROSS sources (tools/telemetry_report.metrics_summary)
  trace       one request-lifecycle stage (utils/tracing.py): trace id,
              stage, PARENT stage, offset + duration — the root
              `request` record carries end-to-end latency and the
              critical stages (queue_wait/compile/execute/emit) tile it
              exactly, so the report's per-stage decomposition must sum
              to end-to-end
  slo         one tenant's sliding-window SLO accounting (fleet/slo.py):
              target p95, window requests/violations, error-budget burn
              rate — burn beyond the alert threshold additionally emits
              a `warning` record
  autoscale   one autopilot decision (fleet/autopilot.py, schema v9):
              decision (hold/grow/shrink/degrade/recover/heal/preempt/
              resume/shed/inject/resident), degradation rung + name,
              lane/capacity counts, the policy INPUTS that drove it
              (burn_max, queue depth, backlog trend, worst class p95)
              and the live hysteresis state (above/below/cooldown_left)
              — one per daemon poll minimum (hold included), so the
              flight record replays the whole observe→decide→act loop
  fleet       one fleet run's summary (pampi_tpu/fleet/scheduler.py):
              per-bucket mode/compile-vs-run walls, scenarios/s
              throughput, and the divergence census — the block
              `tools/telemetry_report.py --merge` folds into artifacts
              as `fleet_summary` and `tools/check_artifact.py` lints
  finalize    end of run: the `utils/profiling` region table, plus
              `dropped_records` when any write failed — a truncated
              flight record names its own truncation instead of reading
              as a quiet run

Multi-process runs emit from process 0 only. `tools/telemetry_report.py`
aggregates a JSONL into a human-readable report and a summary block for
the BENCH/MULTICHIP artifacts.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import time
import warnings

SCHEMA_VERSION = 9  # v9: + autoscale record kind (the fleet autopilot's
#                     observe→decide→act loop: every policy decision
#                     with its inputs and hysteresis state), ckpt
#                     lane_park / lane_resume / fence events
#                     (v8: + metrics / slo / trace record kinds (the
#                      serving-plane observability layer: registry
#                      snapshots, tenant SLO burn, parented request
#                      spans);
#                      v7, PR 13: + serving / admission / latency / swap
#                      record kinds (the persistent fleet daemon);
#                      v6, PR 12: + dead / epoch / shrink record kinds,
#                      ckpt ledger_save / ledger_restore events;
#                      v5, PR 10: + coord record kind, elastic ckpt
#                      events, warning record kind;
#                      v4, PR 9: + fleet record kind, scenario dimension;
#                      v3, PR 7: + xprof record kind, drop accounting;
#                      v2, PR 4: + recover / retry / ckpt record kinds)

# METRICS vector layout (float32, shared by the 2-D and 3-D families; the
# 2-D solvers leave M_WMAX at 0). M_BAD < 0 means all-finite so far;
# otherwise it holds the step count `nt` AFTER which the carried scalars
# first went non-finite (so the last fully-good step is M_BAD - 1).
M_RES, M_IT, M_DT, M_UMAX, M_VMAX, M_WMAX, M_BAD = range(7)
METRICS_LEN = 7

_run_emitted = False
_finalized = False
_atexit_registered = False
_write_failed = False
_dropped = 0  # records lost to write failures (reported by finalize)
_scenario = None  # current tenant/scenario id (scenario_scope)


def _path() -> str:
    from . import flags as _flags

    return _flags.env("PAMPI_TELEMETRY",
                      doc="flight-recorder JSONL path (unset = off)")


def enabled() -> bool:
    return bool(_path())


def reset() -> None:
    """Re-arm the per-process one-shot records (tests)."""
    global _run_emitted, _finalized, _write_failed, _dropped
    _run_emitted = False
    _finalized = False
    _write_failed = False
    _dropped = 0


@contextlib.contextmanager
def scenario_scope(sid):
    """Tag every record emitted inside the block with a `scenario` id —
    the multi-tenant dimension (pampi_tpu/fleet/): a fleet run's
    chunk/divergence/solve records name the scenario they belong to, so
    `tools/telemetry_report.py` can render per-tenant tables. Records
    that pass an explicit `scenario=` keyword (the batched driver's
    per-lane recorders) win over the ambient scope. No-op nesting-safe;
    None restores untagged emission."""
    global _scenario
    prev = _scenario
    _scenario = sid
    try:
        yield
    finally:
        _scenario = prev


def _is_master() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # lint: allow(broad-except) — any probe failure (jax not initialised, no runtime) means single-process
        return True


def emit(kind: str, **fields) -> None:
    """Append one schema-versioned record; no-op when disabled. A write
    failure (bad path, full disk) costs the flight record, never the run:
    warn once and stand down instead of sinking the solver or a bench
    headline behind an observability layer. Every record lost to the
    stand-down is COUNTED (`_dropped`) and reported by the finalize
    record, so a truncated flight record is never mistaken for a quiet
    run."""
    global _atexit_registered, _write_failed, _dropped
    if not enabled() or not _is_master():
        return
    if _write_failed:
        _dropped += 1
        return
    if kind != "run":
        _ensure_run()
    if not _atexit_registered:
        # the finalize record must survive a driver that exits early or
        # raises (the same contract as profiling.finalize's atexit hook)
        import atexit

        atexit.register(finalize)
        _atexit_registered = True
    rec = {"v": SCHEMA_VERSION, "kind": kind, "ts": round(time.time(), 3)}
    if _scenario is not None and "scenario" not in fields:
        rec["scenario"] = _scenario
    rec.update(fields)
    try:
        from . import faultinject as _fi

        _fi.maybe_telemetry_fail()  # injected write failure (test-only)
        with open(_path(), "a") as fh:
            # allow_nan=False + the sanitizer: divergence records carry
            # non-finite scalars BY DESIGN, and Python's default NaN/Inf
            # tokens are invalid JSON for every strict parser downstream
            # (jq, JS, a --merge'd committed artifact) — encode them as
            # strings ("nan"/"inf"/"-inf"; float() round-trips them)
            fh.write(json.dumps(_json_safe(rec), allow_nan=False) + "\n")
    except OSError as exc:
        _write_failed = True
        _dropped += 1
        warnings.warn(
            f"PAMPI_TELEMETRY write to {_path()!r} failed ({exc}); "
            "telemetry disabled for the rest of this run",
            stacklevel=2,
        )


def _json_safe(x):
    """Strict-JSON encoding of non-finite floats as strings (recursive)."""
    if isinstance(x, float) and not math.isfinite(x):
        return repr(x)  # "nan" / "inf" / "-inf" — float() round-trips
    if isinstance(x, dict):
        return {k: _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_json_safe(v) for v in x]
    return x


def _run_meta() -> dict:
    import sys

    meta = {"argv": sys.argv, "pid": os.getpid()}
    try:
        import jax

        meta.update(
            backend=jax.default_backend(),
            n_devices=len(jax.devices()),
            n_processes=jax.process_count(),
            jax_version=jax.__version__,
        )
    except Exception:  # lint: allow(broad-except) — metadata is best-effort; a probe crash must never sink the run record
        pass
    return meta


def _ensure_run() -> None:
    global _run_emitted
    if _run_emitted:
        return
    _run_emitted = True  # before emit: emit() calls back into _ensure_run
    emit("run", **_run_meta())


def start_run(**fields) -> None:
    """Emit the run-metadata record with caller context (tool name, config).
    Safe to call when disabled; the `run` record is emitted exactly once
    per process (a later implicit emit sees it already written)."""
    global _run_emitted
    if not enabled() or not _is_master() or _run_emitted:
        return
    _run_emitted = True
    emit("run", **{**_run_meta(), **fields})


def emit_span(name: str, ms, **fields) -> None:
    """The shared span record: one named timing, milliseconds. Every perf
    tool's decomposition row goes through here — one protocol instead of
    per-tool two-point differencing formats."""
    emit("span", name=name, ms=None if ms is None else round(float(ms), 4),
         **fields)


def emit_decomposition(name: str, step_ms, solve_ms, nonsolve_ms, **fields):
    """A solve/non-solve step decomposition as three spans (`<name>.step`,
    `.solve`, `.nonsolve`). solve/nonsolve may be None (the TPU-only
    contract of bench.py): only the step span is emitted then."""
    emit_span(f"{name}.step", step_ms, **fields)
    if solve_ms is not None:
        emit_span(f"{name}.solve", solve_ms, **fields)
        emit_span(f"{name}.nonsolve", nonsolve_ms, **fields)


@contextlib.contextmanager
def span(name: str, **fields):
    """Wall-clock a block as a span record; no-op when disabled."""
    if not enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        # a raising block still leaves its span in the flight record (the
        # crash-surviving contract: that block is the one worth reading)
        emit_span(name, (time.perf_counter() - t0) * 1e3, **fields)


def finalize() -> None:
    """Emit the end-of-run record (the profiling region table, when any
    regions were recorded, plus the count of records dropped by write
    failures). Idempotent — the atexit hook and an explicit driver call
    must not double-emit. After a write-failure stand-down, ONE last
    write is attempted for this record: a flight record that ends by
    naming its own truncation beats one that is silently clipped (if the
    path is still broken the attempt fails like any other write)."""
    global _finalized, _write_failed
    if _finalized or not enabled():
        return
    _finalized = True
    from . import profiling as prof

    table = prof.table()
    dropped = _dropped
    if _write_failed:
        _write_failed = False  # the one last-gasp attempt
    emit("finalize", profile_regions=table if table else None,
         dropped_records=dropped if dropped else None)


# ---------------------------------------------------------------------------
# Device plane: the in-band metrics vector carried through the jitted chunk.
# All helpers are traced into the chunk ONLY when enabled() at build time —
# the off path never sees them (jaxpr identity, tests/test_telemetry.py).
# ---------------------------------------------------------------------------

def metrics_init():
    """Fresh metrics vector: all zeros, sentinel at -1 (all finite)."""
    import jax.numpy as jnp

    return jnp.zeros((METRICS_LEN,), jnp.float32).at[M_BAD].set(-1.0)


def metrics_pack(res, it, dt, umax, vmax, wmax, bad):
    """Pack the carried scalars into the f32 metrics vector."""
    import jax.numpy as jnp

    return jnp.stack([
        jnp.asarray(x).astype(jnp.float32)
        for x in (res, it, dt, umax, vmax, wmax, bad)
    ])


def metrics_step(bad, nt_after, res, it, dt, *maxes):
    """One step's update of a metrics chunk's f32 scalar carry: cast the
    step's metric scalars to the in-band precision and latch the
    non-finite sentinel. Returns (res, it, dt, *maxes, bad), all f32 —
    the ONE cast/sentinel wiring every family's metrics loop threads
    (callers whose loop carries the maxima natively, e.g. the fused
    chunks' CFL scalars, discard the f32 max copies)."""
    import jax.numpy as jnp

    vals = [jnp.asarray(x).astype(jnp.float32)
            for x in (res, it, dt) + maxes]
    res32, _it32, dt32 = vals[:3]
    bad = sentinel_update(bad, nt_after, res32, dt32, *vals[3:])
    return (*vals, bad)


def sentinel_update(bad, nt_after, *scalars):
    """First-non-finite tracking: once any carried scalar is non-finite,
    latch the step count `nt_after` (the value of nt AFTER the offending
    step). All f32 scalar math — fuses into the chunk program."""
    import jax.numpy as jnp

    finite = jnp.asarray(True)
    for s in scalars:
        finite = jnp.logical_and(finite, jnp.isfinite(s))
    hit = jnp.logical_and(bad < 0, jnp.logical_not(finite))
    return jnp.where(hit, jnp.asarray(nt_after).astype(jnp.float32), bad)


def halo_exchange_bytes(extents, depth: int, itemsize: int) -> int:
    """Static per-shard bytes one full `parallel/comm.halo_exchange` moves.
    The accounting LIVES in `parallel/comm.halo_exchange_bytes` (next to
    the exchange whose messages it describes, where the commcheck contract
    pass cross-checks it); this alias keeps the telemetry-record spelling
    the PR 3 callers and tests use."""
    from ..parallel.comm import halo_exchange_bytes as _comm_bytes

    return _comm_bytes(extents, depth, itemsize)


class ChunkRecorder:
    """Host-plane per-chunk recorder: call update(t, nt, metrics) at each
    host sync. Emits one `chunk` record per sync (the first is
    compile-inclusive) and a single `divergence` record + warning the first
    time the in-band sentinel reports a non-finite step.

    `scenario` tags every record with a tenant/scenario id (the fleet
    driver runs one recorder per lane); None keeps the solo-run shape."""

    def __init__(self, family: str, nt0: int = 0, scenario=None):
        self.family = family
        self.scenario = scenario
        self._last = time.perf_counter()
        self._nt = nt0
        self._first = True
        self._diverged = False

    def _tag(self) -> dict:
        return {} if self.scenario is None else {"scenario": self.scenario}

    def rearm(self, nt=None) -> None:
        """Re-arm the one-shot divergence latch: rollback-recovery rolled
        the state back, so a SECOND blow-up must record again. Passing the
        rollback target `nt` also re-baselines the step counter and wall
        timer (without it the first post-rollback chunk record would
        report negative steps/ms_per_step) and marks that record
        compile-inclusive — the rebuilt chunk re-traces."""
        self._diverged = False
        if nt is not None:
            self._nt = int(nt)
            self._last = time.perf_counter()
            self._first = True

    def update(self, t: float, nt: int, metrics) -> None:
        if not enabled():
            return
        import numpy as np

        m = np.asarray(metrics, dtype=np.float64)
        now = time.perf_counter()
        wall = now - self._last
        self._last = now
        steps = nt - self._nt
        self._nt = nt
        emit(
            "chunk",
            family=self.family,
            **self._tag(),
            t=float(t),
            nt=int(nt),
            steps=steps,
            wall_s=round(wall, 4),
            ms_per_step=(round(wall / steps * 1e3, 4) if steps else None),
            includes_compile=self._first,
            res=float(m[M_RES]),
            iters=int(m[M_IT]),
            dt=float(m[M_DT]),
            umax=float(m[M_UMAX]),
            vmax=float(m[M_VMAX]),
            wmax=float(m[M_WMAX]),
        )
        self._first = False
        bad = m[M_BAD]
        if bad >= 0 and not self._diverged:
            self._diverged = True
            first_bad, last_good = int(bad), int(bad) - 1
            emit(
                "divergence",
                family=self.family,
                **self._tag(),
                first_bad_step=first_bad,
                last_good_step=last_good,
                res=float(m[M_RES]),
                dt=float(m[M_DT]),
                umax=float(m[M_UMAX]),
                vmax=float(m[M_VMAX]),
                wmax=float(m[M_WMAX]),
            )
            who = (self.family if self.scenario is None
                   else f"{self.family}[{self.scenario}]")
            warnings.warn(
                f"{who}: solver state went non-finite at step "
                f"{first_bad} (last good step {last_good}) — see the "
                "telemetry divergence record",
                stacklevel=2,
            )
