"""Serving-plane metrics registry: counters, gauges, and MERGEABLE
log-bucket histograms — the bounded-memory measurement layer under the
fleet daemon's status endpoint and the SLO plane (ROADMAP item 3).

Why a registry and not the flight record alone: the daemon used to keep
every request latency in an unbounded Python list to compute its status
percentiles — fine for a smoke, wrong for a 10k-request soak. A
log-bucket histogram holds the SAME percentiles in O(#buckets) memory
(a few hundred ints regardless of request count), and two histograms
FOLD by summing bucket counts — so per-rank registries merge into one
fleet view exactly like the artifact blocks `--merge` already folds.

Design:

- `Counter` / `Gauge` / `Histogram`, each labeled (tenant/class/family —
  any string labels); a `Registry` holds one instance per (name, labels)
  and hands the same object back on re-request.
- Histogram buckets are LOGARITHMIC: bucket k covers (BASE^(k-1),
  BASE^k] with BASE = 2^(1/8) (~9.05% relative width). Quantiles are
  exact WITHIN a bucket's resolution: nearest-rank over the cumulative
  counts — the same rank rule as `fleet/serve._percentile` — then the
  bucket's geometric midpoint, so histogram p50/p95 agree with the
  exact sorted-list computation to within half a bucket (<5% relative,
  test-pinned in tests/test_metrics.py).
- `snapshot()` is a plain-JSON dict; `emit_snapshot()` writes it as one
  `metrics` telemetry record (schema v9) tagged with a per-process
  source id + sequence number, so `tools/telemetry_report.
  metrics_summary` can take the LAST snapshot per process and fold
  across processes (cumulative snapshots from one process must never be
  summed with each other).
- `merge_snapshots(a, b)` is the fold: counters sum, gauges keep the
  max (the conservative cross-rank reading for depth/backlog gauges),
  histograms sum per-bucket — associative and commutative, test-pinned.
- `render_prometheus()` / `write_prometheus(path)`: the classic
  text-exposition format (`*_bucket{le=...}` cumulative counts +
  `_sum`/`_count`), deterministically ordered so the output is
  golden-pinnable; the daemon writes it next to status.json every poll.

Everything here is HOST-side: observing into the registry touches no
traced program (off-path jaxpr identity with the registry armed is
test-pinned). The process-wide default registry (`registry()` /
`counter()` / `gauge()` / `histogram()`) serves library callers; the
serving daemon scopes a fresh `Registry` per session so back-to-back
daemons in one process never mix latency populations.
"""

from __future__ import annotations

import math
import os

from . import telemetry as _tm

# log-bucket width: 2^(1/8) per bucket (~9.05%); quantile error vs the
# exact computation is at most half a bucket (BASE^0.5 - 1 ~ 4.4%)
BASE = 2.0 ** 0.125
_LOG_BASE = math.log(BASE)
# bucket-index clamp: BASE^±400 spans ~1e-15..1e15 — any observable
# latency/size; the clamp bounds memory even against garbage inputs
_IDX_MIN, _IDX_MAX = -400, 400


def _bucket_index(value: float) -> int:
    """Bucket k covers (BASE^(k-1), BASE^k]; non-positive values get the
    dedicated floor bucket _IDX_MIN (counted, excluded from the log
    range)."""
    if not (value > 0.0) or not math.isfinite(value):
        return _IDX_MIN
    k = math.ceil(math.log(value) / _LOG_BASE)
    # float fuzz at an exact edge: log(BASE**k)/log(BASE) can land a
    # hair above k; pull back when value is within one ulp-ish of the
    # lower edge
    if value <= BASE ** (k - 1):
        k -= 1
    return max(_IDX_MIN, min(_IDX_MAX, k))


def bucket_edge(index: int) -> float:
    """The INCLUSIVE upper edge of bucket `index`."""
    return BASE ** index


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """A monotone count (requests served, violations, swaps)."""

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = dict(labels)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A point-in-time level (queue depth, active lanes)."""

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = dict(labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Mergeable log-bucket histogram: O(#touched buckets) memory over
    any observation count, nearest-rank quantiles at bucket resolution,
    exact min/max/sum alongside (so `max` in a status block is exact)."""

    def __init__(self, name: str = "", labels: dict | None = None):
        self.name = name
        self.labels = dict(labels or {})
        self.counts: dict[int, int] = {}
        self.n = 0
        self.total = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[_bucket_index(v)] = \
            self.counts.get(_bucket_index(v), 0) + 1
        self.n += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile (the `fleet/serve._percentile` rank
        rule: rank = round(q * (n - 1))) resolved to the holding
        bucket's geometric midpoint. None when empty. The floor bucket
        (non-positive observations) resolves to 0.0."""
        if self.n == 0:
            return None
        rank = min(self.n - 1, max(0, int(round(q * (self.n - 1)))))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen > rank:
                if idx <= _IDX_MIN:
                    return 0.0
                # geometric midpoint of (BASE^(idx-1), BASE^idx]
                return round(BASE ** (idx - 0.5), 6)
        return round(BASE ** (max(self.counts) - 0.5), 6)

    def merge(self, other: "Histogram") -> "Histogram":
        """The fold: bucket-count sum (associative + commutative)."""
        out = Histogram(self.name, self.labels)
        out.counts = dict(self.counts)
        for idx, c in other.counts.items():
            out.counts[idx] = out.counts.get(idx, 0) + c
        out.n = self.n + other.n
        out.total = self.total + other.total
        mins = [m for m in (self.vmin, other.vmin) if m is not None]
        maxs = [m for m in (self.vmax, other.vmax) if m is not None]
        out.vmin = min(mins) if mins else None
        out.vmax = max(maxs) if maxs else None
        return out

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "labels": self.labels,
            "base": round(BASE, 9),
            "n": self.n,
            "sum": round(self.total, 6),
            "min": self.vmin,
            "max": self.vmax,
            # JSON object keys are strings; parsers int() them back
            "buckets": {str(k): v for k, v in sorted(self.counts.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls(str(d.get("name", "")), d.get("labels") or {})
        h.counts = {int(k): int(v)
                    for k, v in (d.get("buckets") or {}).items()}
        h.n = int(d.get("n", 0))
        h.total = float(d.get("sum", 0.0))
        h.vmin = d.get("min")
        h.vmax = d.get("max")
        return h


class Registry:
    """One namespace of metrics: instruments keyed by (name, labels),
    snapshot/emit/Prometheus surfaces. The module-level default is the
    process-wide registry; the serving daemon scopes its own per
    session (two daemons in one process must not share a latency
    population)."""

    def __init__(self):
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}
        self._seq = 0

    def counter(self, name: str, **labels) -> Counter:
        key = (name, _label_key(labels))
        if key not in self._counters:
            self._counters[key] = Counter(name, labels)
        return self._counters[key]

    def gauge(self, name: str, **labels) -> Gauge:
        key = (name, _label_key(labels))
        if key not in self._gauges:
            self._gauges[key] = Gauge(name, labels)
        return self._gauges[key]

    def histogram(self, name: str, **labels) -> Histogram:
        key = (name, _label_key(labels))
        if key not in self._hists:
            self._hists[key] = Histogram(name, labels)
        return self._hists[key]

    def histograms(self, name: str | None = None) -> list[Histogram]:
        return [h for h in self._hists.values()
                if name is None or h.name == name]

    def snapshot(self) -> dict:
        """The plain-JSON registry state (the `metrics` record body and
        the merge_snapshots operand)."""
        return {
            "counters": [
                {"name": c.name, "labels": c.labels, "value": c.value}
                for c in self._counters.values()
            ],
            "gauges": [
                {"name": g.name, "labels": g.labels, "value": g.value}
                for g in self._gauges.values()
            ],
            "histograms": [h.to_dict() for h in self._hists.values()],
        }

    def emit_snapshot(self, **fields) -> None:
        """One `metrics` telemetry record: the full snapshot + a
        per-process source id and sequence number. Snapshots are
        CUMULATIVE — a reader takes the last per source and folds
        ACROSS sources only (telemetry_report.metrics_summary)."""
        self._seq += 1
        _tm.emit("metrics", source=f"pid{os.getpid()}", seq=self._seq,
                 **self.snapshot(), **fields)

    # -- Prometheus text exposition ------------------------------------
    def render_prometheus(self) -> str:
        """The classic text format, deterministically ordered (sorted
        by name then labels) so the output is golden-pinnable."""
        lines: list[str] = []

        def fmt_labels(labels: dict, extra: str = "") -> str:
            parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        def fnum(v) -> str:
            if v is None:
                return "NaN"
            f = float(v)
            return str(int(f)) if f == int(f) else format(f, ".6g")

        for c in sorted(self._counters.values(),
                        key=lambda c: (c.name, _label_key(c.labels))):
            if not any(ln.startswith(f"# TYPE {c.name} ")
                       for ln in lines):
                lines.append(f"# TYPE {c.name} counter")
            lines.append(f"{c.name}{fmt_labels(c.labels)} {fnum(c.value)}")
        for g in sorted(self._gauges.values(),
                        key=lambda g: (g.name, _label_key(g.labels))):
            if not any(ln.startswith(f"# TYPE {g.name} ")
                       for ln in lines):
                lines.append(f"# TYPE {g.name} gauge")
            lines.append(f"{g.name}{fmt_labels(g.labels)} {fnum(g.value)}")
        for h in sorted(self._hists.values(),
                        key=lambda h: (h.name, _label_key(h.labels))):
            if not any(ln.startswith(f"# TYPE {h.name} ")
                       for ln in lines):
                lines.append(f"# TYPE {h.name} histogram")
            cum = 0
            for idx in sorted(h.counts):
                cum += h.counts[idx]
                le = fnum(bucket_edge(idx)) if idx > _IDX_MIN else "0"
                le_attr = 'le="%s"' % le
                lines.append(
                    f"{h.name}_bucket"
                    f"{fmt_labels(h.labels, le_attr)} {cum}")
            inf_attr = 'le="+Inf"'
            lines.append(
                f"{h.name}_bucket"
                f"{fmt_labels(h.labels, inf_attr)} {h.n}")
            lines.append(f"{h.name}_sum{fmt_labels(h.labels)} "
                         f"{fnum(round(h.total, 6))}")
            lines.append(f"{h.name}_count{fmt_labels(h.labels)} {h.n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str) -> None:
        """Atomic write (tmp + replace — the status.json convention, so
        a scraper never reads a torn file)."""
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(self.render_prometheus())
        os.replace(tmp, path)


# -- snapshot-level fold (the cross-rank / cross-process merge) ---------

def merge_snapshots(a: dict, b: dict) -> dict:
    """Fold two registry snapshots: counters SUM, gauges take the MAX
    (the conservative reading for backlog/depth levels), histograms sum
    per bucket. Associative and commutative (test-pinned), so any fold
    order over N ranks lands on the same fleet view."""

    def key(row: dict) -> tuple:
        return (row.get("name"), _label_key(row.get("labels") or {}))

    counters: dict[tuple, dict] = {}
    for row in list(a.get("counters") or []) + list(b.get("counters")
                                                    or []):
        k = key(row)
        if k in counters:
            counters[k] = {**counters[k],
                           "value": counters[k]["value"] + row["value"]}
        else:
            counters[k] = dict(row)
    gauges: dict[tuple, dict] = {}
    for row in list(a.get("gauges") or []) + list(b.get("gauges") or []):
        k = key(row)
        if k in gauges:
            gauges[k] = {**gauges[k],
                         "value": max(gauges[k]["value"], row["value"])}
        else:
            gauges[k] = dict(row)
    hists: dict[tuple, Histogram] = {}
    for row in list(a.get("histograms") or []) + list(b.get("histograms")
                                                      or []):
        k = key(row)
        h = Histogram.from_dict(row)
        hists[k] = hists[k].merge(h) if k in hists else h
    return {
        "counters": sorted(counters.values(),
                           key=lambda r: (r["name"],
                                          _label_key(r["labels"]))),
        "gauges": sorted(gauges.values(),
                         key=lambda r: (r["name"],
                                        _label_key(r["labels"]))),
        "histograms": sorted((h.to_dict() for h in hists.values()),
                             key=lambda r: (r["name"],
                                            _label_key(r["labels"]))),
    }


def snapshot_quantile(hist_dict: dict, q: float) -> float | None:
    """Quantile straight off a snapshot's histogram entry (readers that
    never build a Histogram object — tools/telemetry_report.py)."""
    return Histogram.from_dict(hist_dict).quantile(q)


# -- the process-wide default registry ---------------------------------

_DEFAULT = Registry()


def registry() -> Registry:
    return _DEFAULT


def counter(name: str, **labels) -> Counter:
    return _DEFAULT.counter(name, **labels)


def gauge(name: str, **labels) -> Gauge:
    return _DEFAULT.gauge(name, **labels)


def histogram(name: str, **labels) -> Histogram:
    return _DEFAULT.histogram(name, **labels)


def reset() -> None:
    """Fresh process-wide registry (tests)."""
    global _DEFAULT
    _DEFAULT = Registry()
