"""Legacy-VTK STRUCTURED_POINTS writer (ASCII and BINARY big-endian).

Byte-format parity with /root/reference/assignment-6/src/vtkWriter.c:
header (:43-66), `SCALARS <name> double 1` + LOOKUP_TABLE with `%f` per line
(:83-105,116), `VECTORS <name> double` with `%f %f %f` (:146-175), binary
mode = big-endian float64 stream terminated by a newline (floatSwap :24-41).
Values are cell-centered (ORIGIN at dx/2), i fastest, then j, then k.
"""

from __future__ import annotations

import os

import numpy as np

from .grid import Grid


class VtkWriter:
    """Writes through the native C layer (same file bytes, C speed) when
    build/*/libpampi_native.so is present, else pure Python — one class, one
    attribute contract (.path/.grid/.fmt) either way. `.fh` is only open in
    the Python path (None under native)."""

    def __init__(self, problem: str, grid: Grid, fmt: str = "ascii", path=None):
        assert fmt in ("ascii", "binary")
        self.grid = grid
        self.fmt = fmt
        self.path = path or f"{problem}.vtk"
        self.fh = None
        from . import native

        if native.available():
            self._impl = native.NativeVtk(
                self.path,
                "PAMPI cfd solver output",
                grid.imax,
                grid.jmax,
                grid.kmax,
                grid.dx,
                grid.dy,
                grid.dz,
                fmt == "binary",
            )
        else:
            self._impl = None
            self.fh = open(self.path, "wb")
            self._header(problem)

    def _w(self, s: str) -> None:
        self.fh.write(s.encode())

    def _header(self, problem: str) -> None:
        g = self.grid
        self._w("# vtk DataFile Version 3.0\n")
        self._w("PAMPI cfd solver output\n")
        self._w("ASCII\n" if self.fmt == "ascii" else "BINARY\n")
        self._w("DATASET STRUCTURED_POINTS\n")
        self._w("DIMENSIONS %d %d %d\n" % (g.imax, g.jmax, g.kmax))
        self._w("ORIGIN %f %f %f\n" % (g.dx * 0.5, g.dy * 0.5, g.dz * 0.5))
        self._w("SPACING %f %f %f\n" % (g.dx, g.dy, g.dz))
        self._w("POINT_DATA %d\n" % (g.imax * g.jmax * g.kmax))

    def scalar(self, name: str, s) -> None:
        """s: (kmax, jmax, imax) cell-centered array."""
        if self._impl is not None:
            self._impl.scalar(name, s)
            return
        arr = np.asarray(s, dtype=np.float64)
        self._w("SCALARS %s double 1\n" % name)
        self._w("LOOKUP_TABLE default\n")
        if self.fmt == "ascii":
            self._w("".join("%f\n" % val for val in arr.ravel()))
        else:
            self.fh.write(arr.astype(">f8").tobytes())
            self._w("\n")

    def vector(self, name: str, u, v, w) -> None:
        """u, v, w: (kmax, jmax, imax) cell-centered arrays."""
        if self._impl is not None:
            self._impl.vector(name, u, v, w)
            return
        uu = np.asarray(u, dtype=np.float64).ravel()
        vv = np.asarray(v, dtype=np.float64).ravel()
        ww = np.asarray(w, dtype=np.float64).ravel()
        self._w("VECTORS %s double\n" % name)
        if self.fmt == "ascii":
            self._w(
                "".join(
                    "%f %f %f\n" % (a, b, c) for a, b, c in zip(uu, vv, ww)
                )
            )
        else:
            inter = np.stack([uu, vv, ww], axis=1).astype(">f8")
            self.fh.write(inter.tobytes())
            self._w("\n")

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
        else:
            self.fh.close()


class ShardedVtkWriter:
    """MPI-IO-pattern parallel VTK writer: each subdomain slab is written at
    the exact byte ranges it owns inside one shared file (seek + write per
    i-row — the subarray-filetype discipline of `MPI_File_set_view`), with no
    global array ever materialized. This is the completed form of the
    reference's scaffolded parallel-write path
    (/root/reference/assignment-6/src/vtkWriter.c:15-22,118-143, the `// fill`
    MPI-IO exercise), TPU-style: the natural producers of slabs are the
    addressable shards of a distributed `jax.Array`, so a multi-host run can
    have every host write exactly its own slabs.

    BINARY format only — ASCII `%f` records are variable-width and therefore
    not offset-addressable (the same restriction real MPI-IO writers have).
    Output is byte-identical to `VtkWriter(fmt="binary")` (tested).

    Usage (section order must match across participants, like collective IO):
        w = ShardedVtkWriter("canal3d", grid, path="out.vtk")
        w.scalar("pressure", [(slab, (k0, j0, i0)), ...])
        w.vector("velocity", [(us, vs, ws, (k0, j0, i0)), ...])
        w.close()
    """

    def __init__(self, problem: str, grid: Grid, path=None):
        self.grid = grid
        self.path = path or f"{problem}.vtk"
        header = (
            "# vtk DataFile Version 3.0\n"
            "PAMPI cfd solver output\n"
            "BINARY\n"
            "DATASET STRUCTURED_POINTS\n"
            "DIMENSIONS %d %d %d\n" % (grid.imax, grid.jmax, grid.kmax)
            + "ORIGIN %f %f %f\n" % (grid.dx * 0.5, grid.dy * 0.5, grid.dz * 0.5)
            + "SPACING %f %f %f\n" % (grid.dx, grid.dy, grid.dz)
            + "POINT_DATA %d\n" % (grid.imax * grid.jmax * grid.kmax)
        ).encode()
        # Non-truncating open: several participants (hosts) may hold the same
        # shared file concurrently, MPI-IO style. The header bytes are a pure
        # function of the grid, so every participant writing them at offset 0
        # is idempotent; O_TRUNC here would destroy slabs peers already wrote.
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        self.fh = os.fdopen(fd, "r+b")
        self.fh.write(header)
        self._offset = len(header)  # start of the next section
        self._n = grid.imax * grid.jmax * grid.kmax

    def _write_slab(self, data_base: int, vals: np.ndarray, origin,
                    ncomp: int) -> None:
        """vals: (dk, dj, di[, ncomp]) big-endian f8; seek+write one i-row at
        a time — the contiguous runs a subarray filetype would describe."""
        g = self.grid
        dk, dj, di = vals.shape[0], vals.shape[1], vals.shape[2]
        k0, j0, i0 = origin
        if not (0 <= k0 and k0 + dk <= g.kmax and 0 <= j0
                and j0 + dj <= g.jmax and 0 <= i0 and i0 + di <= g.imax):
            raise ValueError(
                f"slab {vals.shape[:3]} at {origin} exceeds the "
                f"({g.kmax},{g.jmax},{g.imax}) domain"
            )
        del di
        for k in range(dk):
            for j in range(dj):
                idx = ((k0 + k) * g.jmax + (j0 + j)) * g.imax + i0
                self.fh.seek(data_base + idx * ncomp * 8)
                self.fh.write(vals[k, j].tobytes())

    def scalar(self, name: str, slabs) -> None:
        """slabs: iterable of (array (dk,dj,di), origin (k0,j0,i0))."""
        head = ("SCALARS %s double 1\nLOOKUP_TABLE default\n" % name).encode()
        self.fh.seek(self._offset)
        self.fh.write(head)
        data_base = self._offset + len(head)
        self.fh.seek(data_base + self._n * 8)
        self.fh.write(b"\n")
        for arr, origin in slabs:
            vals = np.ascontiguousarray(np.asarray(arr, dtype=np.float64)
                                        .astype(">f8"))
            self._write_slab(data_base, vals, origin, 1)
        self._offset = data_base + self._n * 8 + 1

    def vector(self, name: str, slabs) -> None:
        """slabs: iterable of (u, v, w arrays (dk,dj,di), origin)."""
        head = ("VECTORS %s double\n" % name).encode()
        self.fh.seek(self._offset)
        self.fh.write(head)
        data_base = self._offset + len(head)
        self.fh.seek(data_base + self._n * 24)
        self.fh.write(b"\n")
        for u, v, w, origin in slabs:
            inter = np.stack(
                [np.asarray(u, np.float64), np.asarray(v, np.float64),
                 np.asarray(w, np.float64)],
                axis=-1,
            ).astype(">f8")
            self._write_slab(data_base, np.ascontiguousarray(inter), origin, 3)
        self._offset = data_base + self._n * 24 + 1

    def close(self) -> None:
        # The final size is a pure function of the sections written, so every
        # participant truncating to it is idempotent; this drops stale bytes
        # when overwriting a larger file from an earlier run.
        self.fh.truncate(self._offset)
        self.fh.close()


def shards_of(arr) -> list:
    """(data, (k0, j0, i0)) for every addressable shard of a (possibly
    distributed) jax array — the producer side of ShardedVtkWriter. Works for
    3-D cell-centered arrays whose sharding tiles the array."""
    out = []
    for s in arr.addressable_shards:
        idx = s.index
        origin = tuple(
            (sl.start or 0) if isinstance(sl, slice) else 0 for sl in idx
        )
        out.append((np.asarray(s.data), origin))
    return out


def read_vtk_ascii(path: str):
    """Parse an ASCII legacy VTK file back into {name: array} dicts for
    regression tests. Scalars -> (kmax, jmax, imax); vectors -> tuple of 3."""
    scalars, vectors = {}, {}
    with open(path) as fh:
        lines = fh.read().splitlines()
    dims = None
    i = 0
    while i < len(lines):
        ln = lines[i].split()
        if not ln:
            i += 1
            continue
        if ln[0] == "DIMENSIONS":
            dims = (int(ln[3]), int(ln[2]), int(ln[1]))  # (kmax, jmax, imax)
        elif ln[0] == "SCALARS":
            name = ln[1]
            n = dims[0] * dims[1] * dims[2]
            vals = []
            j = i + 2  # skip LOOKUP_TABLE
            while len(vals) < n:
                vals.extend(float(x) for x in lines[j].split())
                j += 1
            scalars[name] = np.array(vals).reshape(dims)
            i = j - 1
        elif ln[0] == "VECTORS":
            name = ln[1]
            n = dims[0] * dims[1] * dims[2]
            vals = []
            j = i + 1
            while len(vals) < 3 * n:
                vals.extend(float(x) for x in lines[j].split())
                j += 1
            arr = np.array(vals).reshape(n, 3)
            vectors[name] = tuple(arr[:, c].reshape(dims) for c in range(3))
            i = j - 1
        i += 1
    return scalars, vectors
