"""Legacy-VTK STRUCTURED_POINTS writer (ASCII and BINARY big-endian).

Byte-format parity with /root/reference/assignment-6/src/vtkWriter.c:
header (:43-66), `SCALARS <name> double 1` + LOOKUP_TABLE with `%f` per line
(:83-105,116), `VECTORS <name> double` with `%f %f %f` (:146-175), binary
mode = big-endian float64 stream terminated by a newline (floatSwap :24-41).
Values are cell-centered (ORIGIN at dx/2), i fastest, then j, then k.
"""

from __future__ import annotations

import numpy as np

from .grid import Grid


class VtkWriter:
    """Writes through the native C layer (same file bytes, C speed) when
    build/*/libpampi_native.so is present, else pure Python — one class, one
    attribute contract (.path/.grid/.fmt) either way. `.fh` is only open in
    the Python path (None under native)."""

    def __init__(self, problem: str, grid: Grid, fmt: str = "ascii", path=None):
        assert fmt in ("ascii", "binary")
        self.grid = grid
        self.fmt = fmt
        self.path = path or f"{problem}.vtk"
        self.fh = None
        from . import native

        if native.available():
            self._impl = native.NativeVtk(
                self.path,
                "PAMPI cfd solver output",
                grid.imax,
                grid.jmax,
                grid.kmax,
                grid.dx,
                grid.dy,
                grid.dz,
                fmt == "binary",
            )
        else:
            self._impl = None
            self.fh = open(self.path, "wb")
            self._header(problem)

    def _w(self, s: str) -> None:
        self.fh.write(s.encode())

    def _header(self, problem: str) -> None:
        g = self.grid
        self._w("# vtk DataFile Version 3.0\n")
        self._w("PAMPI cfd solver output\n")
        self._w("ASCII\n" if self.fmt == "ascii" else "BINARY\n")
        self._w("DATASET STRUCTURED_POINTS\n")
        self._w("DIMENSIONS %d %d %d\n" % (g.imax, g.jmax, g.kmax))
        self._w("ORIGIN %f %f %f\n" % (g.dx * 0.5, g.dy * 0.5, g.dz * 0.5))
        self._w("SPACING %f %f %f\n" % (g.dx, g.dy, g.dz))
        self._w("POINT_DATA %d\n" % (g.imax * g.jmax * g.kmax))

    def scalar(self, name: str, s) -> None:
        """s: (kmax, jmax, imax) cell-centered array."""
        if self._impl is not None:
            self._impl.scalar(name, s)
            return
        arr = np.asarray(s, dtype=np.float64)
        self._w("SCALARS %s double 1\n" % name)
        self._w("LOOKUP_TABLE default\n")
        if self.fmt == "ascii":
            self._w("".join("%f\n" % val for val in arr.ravel()))
        else:
            self.fh.write(arr.astype(">f8").tobytes())
            self._w("\n")

    def vector(self, name: str, u, v, w) -> None:
        """u, v, w: (kmax, jmax, imax) cell-centered arrays."""
        if self._impl is not None:
            self._impl.vector(name, u, v, w)
            return
        uu = np.asarray(u, dtype=np.float64).ravel()
        vv = np.asarray(v, dtype=np.float64).ravel()
        ww = np.asarray(w, dtype=np.float64).ravel()
        self._w("VECTORS %s double\n" % name)
        if self.fmt == "ascii":
            self._w(
                "".join(
                    "%f %f %f\n" % (a, b, c) for a, b, c in zip(uu, vv, ww)
                )
            )
        else:
            inter = np.stack([uu, vv, ww], axis=1).astype(">f8")
            self.fh.write(inter.tobytes())
            self._w("\n")

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
        else:
            self.fh.close()


def read_vtk_ascii(path: str):
    """Parse an ASCII legacy VTK file back into {name: array} dicts for
    regression tests. Scalars -> (kmax, jmax, imax); vectors -> tuple of 3."""
    scalars, vectors = {}, {}
    with open(path) as fh:
        lines = fh.read().splitlines()
    dims = None
    i = 0
    while i < len(lines):
        ln = lines[i].split()
        if not ln:
            i += 1
            continue
        if ln[0] == "DIMENSIONS":
            dims = (int(ln[3]), int(ln[2]), int(ln[1]))  # (kmax, jmax, imax)
        elif ln[0] == "SCALARS":
            name = ln[1]
            n = dims[0] * dims[1] * dims[2]
            vals = []
            j = i + 2  # skip LOOKUP_TABLE
            while len(vals) < n:
                vals.extend(float(x) for x in lines[j].split())
                j += 1
            scalars[name] = np.array(vals).reshape(dims)
            i = j - 1
        elif ln[0] == "VECTORS":
            name = ln[1]
            n = dims[0] * dims[1] * dims[2]
            vals = []
            j = i + 1
            while len(vals) < 3 * n:
                vals.extend(float(x) for x in lines[j].split())
                j += 1
            arr = np.array(vals).reshape(n, 3)
            vectors[name] = tuple(arr[:, c].reshape(dims) for c in range(3))
            i = j - 1
        i += 1
    return scalars, vectors
