"""Device-time profiling plane: XLA trace capture + host-side ingestion.

The flight recorder (utils/telemetry.py) tells us what the HOST saw —
chunk walls, residuals, spans. The north-star questions are device-side:
which Pallas kernel, which collective, and how much of the halo exchange
hides behind compute. This module closes that gap behind one switch:

  PAMPI_XPROF=<dir>   capture a `jax.profiler` trace around each
                      instrumented region (`capture(...)` — the solver
                      drive loops and the bench/perf timed windows wrap
                      themselves in it), ingest the resulting
                      trace-event file on the host, and emit ONE
                      schema-versioned `xprof` telemetry record per
                      region with per-scope device times.
  unset               every call is a no-op. Capture and ingestion are
                      host-side only — the traced programs are
                      byte-identical either way (the PAMPI_TELEMETRY /
                      PAMPI_FAULTS contract, pinned in
                      tests/test_xprof.py).

Ingestion reads the Chrome trace-event JSON the profiler writes next to
its XPlane file (`<host>.trace.json.gz` — present on this container's
CPU backend too, so the whole plane is testable off-chip; a committed
golden fixture pins the aggregation). Events are attributed three ways:

  scopes       the `halo_exchange.<axis>.<strip>` / `halo_shift.*`
               names `parallel/comm._scope` stamps on every exchange
               axis (visible in TPU op metadata), falling back to the
               collective's own HLO family name — one naming convention
               with the commcheck census (`parallel/comm.strip_key`)
  collectives  HLO collective families (collective-permute, all-reduce,
               ...) — collective-permute IS the halo exchange traffic
  kernels      everything else on a device track (fusions, pallas
               kernels), top-N by total time

plus per-track busy/idle (gap) time and the comm-hidden numbers ROADMAP
item 2 is built against: `exchange_device_ms` (device time the exchange
occupies) vs `exchange_exposed_ms` (the part of it during which no
compute runs on the same track — the critical-path share). The
comm-hidden fraction is 1 - exposed/device: today's serial schedule
measures ~0; the overlap refactor is judged by how far it rises.

Degraded wall-clock mode: when the profiler cannot start (no runtime
support, a PAMPI_PROFILE=<dir> trace already active) or leaves no
parseable trace-event file, the region still emits an `xprof` record
with `mode: "wallclock"` and its wall time — a truncated record, never a
sunk run.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import time
import warnings

XPROF_SCHEMA_VERSION = 1

# the comm._scope attribution tokens (one convention with commcheck's
# strip keys) as they appear inside op metadata / event names
_SCOPE_RE = re.compile(r"halo_(?:exchange|shift)\.[^\s/;,\"'()]+")
# HLO collective families; collective-permute is the exchange traffic
COLLECTIVE_TOKENS = ("collective-permute", "all-reduce", "all-gather",
                     "all-to-all", "reduce-scatter", "collective-broadcast")
EXCHANGE_TOKENS = ("collective-permute",)
# control-flow CONTAINER ops (the chunk's while loop on the CPU thunk
# executor): their events span every op they contain, so counting them
# as compute would mark all nested exchange time "hidden" — they stay in
# the kernel table but are excluded from the overlap cover
_CONTAINER_RE = re.compile(r"^(while|conditional|call)[.\d]*$")
TOP_KERNELS = 12


def _dir() -> str:
    from . import flags as _flags

    return _flags.env("PAMPI_XPROF",
                      doc="device-trace capture dir (unset = off)")


def enabled() -> bool:
    return bool(_dir())


_active = False  # one capture at a time; nested regions ride the outer one
_warned_no_sink = False


@contextlib.contextmanager
def capture(region: str, steps=None):
    """Capture a profiler trace around the block and emit one `xprof`
    telemetry record (no-op when PAMPI_XPROF is unset, or nested inside
    an active capture). `steps` is an int or 0-arg callable evaluated at
    exit — it rides the record so report tooling can normalize device
    times per step. With PAMPI_XPROF armed but PAMPI_TELEMETRY unset the
    trace files still land on disk (open them in XProf/Perfetto), but
    there is no flight record to carry the ingested summary — warn once
    and skip the ingestion instead of silently discarding it."""
    global _active, _warned_no_sink
    from . import telemetry as _tm

    if not enabled() or _active:
        yield
        return
    if not _tm.enabled() and not _warned_no_sink:
        _warned_no_sink = True
        warnings.warn(
            "PAMPI_XPROF is armed but PAMPI_TELEMETRY is not: trace files "
            "are written for offline viewing, but the ingested xprof "
            "record (and the comm_hidden_fraction block) needs the flight "
            "recorder — set PAMPI_TELEMETRY too", stacklevel=2)

    root = _dir()
    started = False
    t0 = time.perf_counter()
    try:
        import jax

        os.makedirs(root, exist_ok=True)
        jax.profiler.start_trace(root)
        started = True
    except Exception as exc:  # lint: allow(broad-except) — profiler unavailability (no runtime support, a PAMPI_PROFILE trace already active) degrades to wall-clock, never sinks the run
        warnings.warn(
            f"PAMPI_XPROF: trace capture unavailable ({exc}); recording "
            "wall-clock only", stacklevel=2)
    _active = True
    try:
        yield
    finally:
        _active = False
        wall_ms = (time.perf_counter() - t0) * 1e3
        summary = None
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
                if _tm.enabled():  # no flight record = no sink to ingest to
                    path = latest_trace_file(root)
                    if path:
                        summary = summarize(load_trace_events(path),
                                            source=path)
            except Exception as exc:  # lint: allow(broad-except) — a stop/ingest failure of any class costs the device numbers, never the run (the crash-surviving span contract)
                warnings.warn(
                    f"PAMPI_XPROF: trace ingestion failed ({exc}); "
                    "recording wall-clock only", stacklevel=2)
        rec = {"schema": XPROF_SCHEMA_VERSION, "region": region,
               "steps": steps() if callable(steps) else steps,
               "wall_ms": round(wall_ms, 3)}
        if summary is not None:
            rec.update(summary)
            rec["mode"] = "trace"
        else:
            rec["mode"] = "wallclock"
        _tm.emit("xprof", **rec)


# ---------------------------------------------------------------------------
# trace-event ingestion (host-side; fully testable off-chip)
# ---------------------------------------------------------------------------

def latest_trace_file(root: str) -> str | None:
    """Newest trace-event JSON under a profiler log dir (the profiler
    writes plugins/profile/<ts>/<host>.trace.json.gz next to the XPlane
    file; repeated captures leave several <ts> dirs)."""
    hits: list[str] = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        hits += glob.glob(os.path.join(root, "**", pat), recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_trace_events(path: str) -> list[dict]:
    """The Chrome trace-event list of a (possibly gzipped) trace file —
    either the {"traceEvents": [...]} envelope or a bare event list."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as fh:
        d = json.load(fh)
    return d.get("traceEvents", []) if isinstance(d, dict) else d


def _merge(intervals):
    """Sorted union of (start, end) intervals."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1][1] = e
        else:
            out.append([s, e])
    return out


def _length(merged) -> float:
    return sum(e - s for s, e in merged)


def _exposed(target, cover) -> float:
    """Length of the merged `target` intervals NOT covered by the merged
    `cover` intervals — the exchange time with no compute over it."""
    total = 0.0
    j = 0
    for s, e in target:
        pos = s
        while j < len(cover) and cover[j][1] <= pos:
            j += 1
        k = j
        while pos < e:
            if k >= len(cover) or cover[k][0] >= e:
                total += e - pos
                break
            cs, ce = cover[k]
            if cs > pos:
                total += cs - pos
            pos = max(pos, ce)
            k += 1
    return total


def _scope_of(ev: dict) -> str | None:
    """The comm named-scope label of one event, from its name or its op
    metadata args (TPU traces carry the scope path in long_name/tf_op)."""
    m = _SCOPE_RE.search(ev.get("name", ""))
    if m:
        return m.group(0)
    args = ev.get("args")
    if isinstance(args, dict):
        for v in args.values():
            if isinstance(v, str):
                m = _SCOPE_RE.search(v)
                if m:
                    return m.group(0)
    return None


def _family(name: str) -> str | None:
    low = name.lower()
    for tok in COLLECTIVE_TOKENS:
        if tok in low:
            return tok
    return None


def _device_events(events: list[dict]) -> list[dict]:
    """The device-op events of a trace: X events carrying HLO op metadata
    (the CPU runtime's form), plus every X event on a pid whose
    process_name marks a device track (the TPU/GPU form)."""
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = (e.get("args") or {}).get("name", "")
            if re.search(r"/device:|TPU|GPU", str(pname)):
                dev_pids.add(e.get("pid"))
    out = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e or "ts" not in e:
            continue
        args = e.get("args")
        if (isinstance(args, dict) and ("hlo_op" in args
                                        or "hlo_module" in args)) \
                or e.get("pid") in dev_pids:
            out.append(e)
    return out


def summarize(events: list[dict], source: str | None = None) -> dict:
    """Aggregate a trace-event list into the `xprof` record body: per-scope
    / per-collective / per-kernel device ms, busy/idle per device track,
    and the exchange device-vs-exposed split the comm-hidden fraction is
    computed from. All times in ms (trace events are microseconds)."""
    devs = _device_events(events)
    tracks: dict[tuple, dict] = {}
    # per-scope intervals kept PER TRACK: a union across concurrent
    # device tracks would collapse their parallelism and under-count
    # device time — union within a track (nested scope/op events), sum
    # across tracks
    scope_iv: dict[str, dict[tuple, list]] = {}
    coll_ms: dict[str, float] = {}
    kern_ms: dict[str, float] = {}
    for e in devs:
        ts, dur = float(e["ts"]), float(e["dur"])
        if dur <= 0:
            continue
        track = (e.get("pid"), e.get("tid"))
        tr = tracks.setdefault(track, {"all": [], "exch": [], "compute": []})
        iv = (ts, ts + dur)
        tr["all"].append(iv)
        name = e.get("name", "")
        scope = _scope_of(e)
        fam = _family(name)
        exch = (scope is not None
                or any(tok in name.lower() for tok in EXCHANGE_TOKENS))
        if exch:
            tr["exch"].append(iv)
            scope_iv.setdefault(scope or fam or "exchange", {}) \
                .setdefault(track, []).append(iv)
        elif not _CONTAINER_RE.match(name):
            tr["compute"].append(iv)
        if fam is not None:
            coll_ms[fam] = coll_ms.get(fam, 0.0) + dur
        elif scope is None:
            kern_ms[name] = kern_ms.get(name, 0.0) + dur
    if not tracks:
        return {"tracks": 0, "total_ms": 0.0, "busy_ms": 0.0,
                "idle_ms": 0.0, "scopes": {}, "collectives": {},
                "kernels": {}, "exchange_device_ms": 0.0,
                "exchange_exposed_ms": 0.0, "source": source}
    busy = idle = span = exch_dev = exch_exp = 0.0
    for tr in tracks.values():
        merged = _merge(tr["all"])
        t_span = merged[-1][1] - merged[0][0]
        t_busy = _length(merged)
        span = max(span, t_span)
        busy += t_busy
        idle += t_span - t_busy
        ex = _merge(tr["exch"])
        exch_dev += _length(ex)
        exch_exp += _exposed(ex, _merge(tr["compute"]))
    top = dict(sorted(kern_ms.items(), key=lambda kv: -kv[1])[:TOP_KERNELS])
    ms = 1e-3  # trace-event timestamps are microseconds

    def r(x):
        return round(x * ms, 4)

    return {
        "tracks": len(tracks),
        "total_ms": r(span),
        "busy_ms": r(busy),
        "idle_ms": r(idle),
        "scopes": {
            k: r(sum(_length(_merge(iv)) for iv in per_track.values()))
            for k, per_track in scope_iv.items()
        },
        "collectives": {k: r(v) for k, v in coll_ms.items()},
        "kernels": {k: r(v) for k, v in top.items()},
        "exchange_device_ms": r(exch_dev),
        "exchange_exposed_ms": r(exch_exp),
        "source": source,
    }


def hidden_fraction(summary: dict) -> float | None:
    """1 - exposed/device: the share of exchange device time hidden
    behind compute. None when the trace carried no exchange events."""
    dev = summary.get("exchange_device_ms") or 0.0
    if dev <= 0:
        return None
    exp = summary.get("exchange_exposed_ms") or 0.0
    return round(max(0.0, 1.0 - exp / dev), 4)
