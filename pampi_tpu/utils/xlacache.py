"""Persistent XLA compilation cache.

The heaviest fixed cost of a TPU run is compilation (~20-40s for the big
jitted solvers; the reference's C build pays its analog once at `make`).
Enabling JAX's persistent cache makes recompiles of an unchanged program a
disk load (measured on the v5e tunnel: 23s -> 4s for the 2048² Poisson
solver program). The CLI and bench.py enable it by default.

  PAMPI_XLA_CACHE=<dir>   cache location (default ~/.cache/pampi_tpu/xla)
  PAMPI_XLA_CACHE=0       disable (also: off, none)

Multi-process launches share the directory; the cache is content-addressed
and concurrent-access safe.
"""

from __future__ import annotations

import os


def enable(path: str | None = None) -> str | None:
    """Turn the cache on; returns the directory, or None when disabled or
    unavailable. Call before the first compilation.

    Default-on for accelerator backends only: CPU compiles are cheap, and a
    cached XLA:CPU AOT executable records the exact machine-feature set of
    the compiling context — loading it from a context with different
    XLA/compile flags fails ("+prefer-no-gather is not supported on the
    host machine") and can wedge a multi-process run with one rank dead and
    its peers blocked in a collective (observed). Set PAMPI_XLA_CACHE=<dir>
    to opt a CPU run in anyway."""
    from . import flags as _flags

    val = _flags.env("PAMPI_XLA_CACHE",
                     doc="XLA compilation-cache dir; 0/off disables, "
                         "unset = accelerator-only default")
    if val.lower() in ("0", "off", "none"):
        return None
    if not val:
        import jax

        if jax.default_backend() == "cpu":
            return None
    path = val or path or os.path.join(
        os.path.expanduser("~"), ".cache", "pampi_tpu", "xla"
    )
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        # min-compile-time first, dir last: until the dir is set nothing is
        # persisted, so a failure between the two leaves the cache fully OFF
        # (cache everything that took real compile time; trivial programs
        # aren't worth the disk round-trip)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_compilation_cache_dir", path)
    except (OSError, AttributeError):
        return None
    return path
