"""Persistent XLA compilation cache.

The heaviest fixed cost of a TPU run is compilation (~20-40s for the big
jitted solvers; the reference's C build pays its analog once at `make`).
Enabling JAX's persistent cache makes recompiles of an unchanged program a
disk load (measured on the v5e tunnel: 23s -> 4s for the 2048² Poisson
solver program). The CLI and bench.py enable it by default.

  PAMPI_XLA_CACHE=<dir>     cache location (default ~/.cache/pampi_tpu/xla)
  PAMPI_XLA_CACHE=0         disable (also: off, none)
  PAMPI_XLA_CACHE_TIMEOUT   cache-dir reachability probe budget in seconds
                            (default 5; 0 skips the probe)

Multi-process launches share the directory; the cache is content-addressed
and concurrent-access safe. The directory is PROBED (with a hard timeout)
before it is handed to XLA: on a shared filesystem a dead NFS/GCS mount —
or the documented wedge below, where one rank's cache access hangs while
its peers block inside a collective waiting for it — must degrade to a
warn-and-run-uncached, never to a hung fleet. The probe failure emits a
structured telemetry `warning` record, so a silently-slow serving process
names its own degradation in the flight record.
"""

from __future__ import annotations

import os
import warnings


def enable(path: str | None = None) -> str | None:
    """Turn the cache on; returns the directory, or None when disabled or
    unavailable. Call before the first compilation.

    Default-on for accelerator backends only: CPU compiles are cheap, and a
    cached XLA:CPU AOT executable records the exact machine-feature set of
    the compiling context — loading it from a context with different
    XLA/compile flags fails ("+prefer-no-gather is not supported on the
    host machine") and can wedge a multi-process run with one rank dead and
    its peers blocked in a collective (observed). Set PAMPI_XLA_CACHE=<dir>
    to opt a CPU run in anyway."""
    from . import flags as _flags

    val = _flags.env("PAMPI_XLA_CACHE",
                     doc="XLA compilation-cache dir; 0/off disables, "
                         "unset = accelerator-only default")
    if val.lower() in ("0", "off", "none"):
        return None
    if not val:
        import jax

        if jax.default_backend() == "cpu":
            return None
    path = val or path or os.path.join(
        os.path.expanduser("~"), ".cache", "pampi_tpu", "xla"
    )
    try:
        timeout = float(_flags.env(
            "PAMPI_XLA_CACHE_TIMEOUT", "5",
            doc="cache-dir reachability probe budget, seconds (0 skips)"))
    except ValueError:
        timeout = 5.0
    reason = _probe_dir(path, timeout) if timeout > 0 else None
    if reason is not None:
        # the wedge guard: a dead rank (or dead shared storage) must not
        # leave peers blocked on the cache path — proceed UNCACHED with a
        # loud, structured degradation notice instead
        from . import telemetry as _tm

        warnings.warn(
            f"XLA compilation cache at {path!r} is unusable ({reason}); "
            "proceeding UNCACHED — compiles will pay full cost this run",
            stacklevel=2,
        )
        _tm.emit("warning", component="xlacache", reason=reason, path=path)
        return None
    import jax

    try:
        os.makedirs(path, exist_ok=True)
        # min-compile-time first, dir last: until the dir is set nothing is
        # persisted, so a failure between the two leaves the cache fully OFF
        # (cache everything that took real compile time; trivial programs
        # aren't worth the disk round-trip)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_compilation_cache_dir", path)
    except (OSError, AttributeError):
        return None
    return path


def _probe_dir(path: str, timeout_s: float):
    """Reachability probe with a HARD timeout: create + write + remove a
    marker in the cache dir on a daemon thread, give it `timeout_s`.
    Returns None when healthy, else the reason string. A hung shared
    mount makes plain os calls block indefinitely — the thread is the
    only portable way to bound that (the blocked thread is abandoned;
    daemon threads die with the process)."""
    import threading

    err: list = []

    def probe():
        try:
            os.makedirs(path, exist_ok=True)
            marker = os.path.join(path, f".pampi-probe-{os.getpid()}")
            with open(marker, "w") as fh:
                fh.write("ok")
            os.remove(marker)
        except OSError as exc:
            err.append(f"cache dir unusable ({exc})")

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return (f"cache-dir probe exceeded {timeout_s:g}s "
                "(hung shared storage?)")
    return err[0] if err else None
