"""Grid descriptor (parity: /root/reference/assignment-6/src/grid.h:149-153)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Grid:
    imax: int
    jmax: int
    kmax: int = 1
    xlength: float = 1.0
    ylength: float = 1.0
    zlength: float = 1.0

    @property
    def dx(self) -> float:
        return self.xlength / self.imax

    @property
    def dy(self) -> float:
        return self.ylength / self.jmax

    @property
    def dz(self) -> float:
        return self.zlength / self.kmax

    @property
    def ndim(self) -> int:
        return 2 if self.kmax <= 1 else 3
