"""Runtime flags ≙ the reference's build-time `-D` OPTIONS
(assignment-6/config.mk:72-84: VERBOSE, DEBUG, ...).

The reference bakes these in at compile time; here they are environment
variables read at trace time, so the same binary serves both. The native
shim completes the chain: `make` with OPTIONS += -DVERBOSE/-DDEBUG exports
PAMPI_VERBOSE/PAMPI_DEBUG to the JAX process (native/src/shim_main.c:43-46).

  PAMPI_DEBUG    pressure residual per CONVERGENCE CHECK, `"%d Residuum: %e"`
                 (≙ assignment-4/src/solver.c:169-171, A6 solver.c:283-287).
                 One check per iteration on the jnp paths; every tpu_sor_inner
                 iterations on the temporal-blocked kernels and the CA
                 distributed solves (intermediate residuals don't exist
                 there); per V-cycle under tpu_solver=mg; never under fft
                 (a direct solve has no iteration to report). Distributed,
                 the line is printed by the (0,..,0) shard only
                 (comm.master_print — res is identical on all shards).
  PAMPI_VERBOSE  per-timestep `"TIME %f , TIMESTEP %f"` instead of the
                 progress bar (≙ assignment-5/sequential/src/main.c:33-57)
  PAMPI_CHECK    DMVM self-check: per iteration, print `"Sum: %f"` of y to
                 stderr and reset y (≙ -DCHECK, assignment-3a/src/dmvm.c:26-36)

The prints are `jax.debug.print` host callbacks inside the jitted loops —
tracing bakes the flag in, so runs without the env pay zero cost.

This module is also the ONE registered home of environment reads: every
PAMPI_* variable the package consumes is read through `env()` (or the
`_on` boolean wrapper), which records the variable in a per-process
inventory (`registered()`). The static lint (analysis/astlint.py rule
`env-read`) rejects direct `os.environ`/`os.getenv` use anywhere else in
`pampi_tpu/`, so the inventory is complete by construction — a new knob
cannot ship without appearing here, in the lint's static scan of
`flags.env("PAMPI_...")` literals, and in the README env-var table.
"""

from __future__ import annotations

import os

# every env var read through env()/set_default() so far this process,
# keeping the most recent non-empty doc — the runtime twin of astlint's
# static inventory (tests/test_analysis.py asserts the two agree)
_REGISTRY: dict[str, str] = {}


def env(name: str, default: str = "", doc: str = "") -> str:
    """Read an environment variable at CALL time (trace-time semantics:
    the caller bakes the value into whatever it builds next, and a later
    build re-reads). The one registered accessor — see the module
    docstring."""
    if name not in _REGISTRY or doc:
        _REGISTRY[name] = doc or _REGISTRY.get(name, "")
    return os.environ.get(name, default)


def set_default(name: str, value: str) -> None:
    """Registered `os.environ.setdefault` twin: exports a value to child
    contexts (the native shim, subprocess tools) without clobbering an
    operator-set one."""
    if name not in _REGISTRY:
        _REGISTRY[name] = ""
    os.environ.setdefault(name, value)


def registered() -> dict[str, str]:
    """The env vars read through this accessor so far this process."""
    return dict(_REGISTRY)


def _on(name: str) -> bool:
    return env(name) not in ("", "0")


def debug() -> bool:
    return _on("PAMPI_DEBUG")


def verbose() -> bool:
    return _on("PAMPI_VERBOSE")


def check() -> bool:
    return _on("PAMPI_CHECK")
