"""Runtime flags ≙ the reference's build-time `-D` OPTIONS
(assignment-6/config.mk:72-84: VERBOSE, DEBUG, ...).

The reference bakes these in at compile time; here they are environment
variables read at trace time, so the same binary serves both. The native
shim completes the chain: `make` with OPTIONS += -DVERBOSE/-DDEBUG exports
PAMPI_VERBOSE/PAMPI_DEBUG to the JAX process (native/src/shim_main.c:43-46).

  PAMPI_DEBUG    pressure residual per CONVERGENCE CHECK, `"%d Residuum: %e"`
                 (≙ assignment-4/src/solver.c:169-171, A6 solver.c:283-287).
                 One check per iteration on the jnp paths; every tpu_sor_inner
                 iterations on the temporal-blocked kernels and the CA
                 distributed solves (intermediate residuals don't exist
                 there); per V-cycle under tpu_solver=mg; never under fft
                 (a direct solve has no iteration to report). Distributed,
                 the line is printed by the (0,..,0) shard only
                 (comm.master_print — res is identical on all shards).
  PAMPI_VERBOSE  per-timestep `"TIME %f , TIMESTEP %f"` instead of the
                 progress bar (≙ assignment-5/sequential/src/main.c:33-57)
  PAMPI_CHECK    DMVM self-check: per iteration, print `"Sum: %f"` of y to
                 stderr and reset y (≙ -DCHECK, assignment-3a/src/dmvm.c:26-36)

The prints are `jax.debug.print` host callbacks inside the jitted loops —
tracing bakes the flag in, so runs without the env pay zero cost.
"""

from __future__ import annotations

import os


def _on(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0")


def debug() -> bool:
    return _on("PAMPI_DEBUG")


def verbose() -> bool:
    return _on("PAMPI_VERBOSE")


def check() -> bool:
    return _on("PAMPI_CHECK")
