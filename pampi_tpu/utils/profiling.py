"""Region-marker profiling hooks.

Capability parity with the reference's LIKWID marker layer
(/root/reference/assignment-4/src/likwid-marker.h:104-130: START/STOP region
macros that compile to no-ops unless -DLIKWID_PERFMON) re-designed for the
TPU stack: regions become `jax.profiler` trace annotations (visible in a
TensorBoard/XProf trace) plus optional wall-clock accounting, and the no-op
switch is the PAMPI_PROFILE environment variable instead of a compile flag.

  PAMPI_PROFILE=0/unset  every call is a no-op (the likwid default)
  PAMPI_PROFILE=1        region wall-clock accounting + trace annotations
  PAMPI_PROFILE=<dir>    additionally jax.profiler.start_trace(<dir>) on
                         init and stop on finalize (full XProf trace)
  PAMPI_PROFILE_CSV=<f>  finalize() additionally writes the region table as
                         machine-readable CSV (region,calls,wall_s,device_s)
                         — the counter-CSV surface of the reference's perl
                         likwid-mpirun harness (assignment-3a/perl
                         scripts/bench-node.pl:17-27). device_s rows come
                         from add_device_time() (harnesses that time a
                         region's device work to completion, e.g.
                         tools/bench_regions.py); empty when only host-side
                         wall clock was recorded.

Usage (mirrors LIKWID_MARKER_*):
    prof.init(); with prof.region("solve"): ...; prof.finalize()
"""

from __future__ import annotations

import contextlib
import sys
import time
from collections import defaultdict

_times: dict[str, float] = defaultdict(float)
_counts: dict[str, int] = defaultdict(int)
_device_times: dict[str, float] = defaultdict(float)
_tracing = False
_finalized = False
_atexit_registered = False


def _mode() -> str:
    """PAMPI_PROFILE read at CALL time through the registered accessor
    (utils/flags.py) — an import-time cache would bake the value of
    whichever process imported this module first (observed: a harness
    setting PAMPI_PROFILE after `import pampi_tpu` silently got no-op
    regions), and would hide the variable from the lint's env inventory."""
    from . import flags as _flags

    return _flags.env("PAMPI_PROFILE", "0",
                      doc="0/unset off; 1 region accounting; <dir> also "
                          "writes an XProf trace")


def enabled() -> bool:
    return _mode() not in ("", "0")


def init() -> None:
    """≙ LIKWID_MARKER_INIT. Also arms the atexit finalize hook so the
    region table / PAMPI_PROFILE_CSV survives a driver that exits early or
    raises without reaching its own finalize() call."""
    global _tracing, _finalized, _atexit_registered
    if not enabled():
        return
    _finalized = False  # re-arm after a prior finalize (init/finalize pairs)
    if not _atexit_registered:
        import atexit

        atexit.register(finalize)
        _atexit_registered = True
    if _mode() != "1":
        import jax

        jax.profiler.start_trace(_mode())
        _tracing = True


@contextlib.contextmanager
def region(name: str):
    """≙ LIKWID_MARKER_START/STOP pair. Also a jax.profiler annotation so the
    region shows up on the device timeline."""
    if not enabled():
        yield
        return
    import jax

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    _times[name] += time.perf_counter() - t0
    _counts[name] += 1


def add_device_time(name: str, seconds: float, calls: int = 1) -> None:
    """Record device-inclusive time for a region (the caller timed the work
    to completion, e.g. around a scalar fence). The measurement IS wall
    time around completion, so it fills BOTH CSV columns — wall_s and
    device_s coincide for harness-recorded regions (previously wall_s was
    left empty, a half-filled schema: round-2 verdict weak item 4)."""
    if not enabled():
        return
    _device_times[name] += seconds
    _times[name] += seconds
    _counts[name] += calls


def table() -> dict[str, dict]:
    """The region table as data ({region: {calls, wall_s, device_s}}) —
    the telemetry finalize record's source; empty when nothing recorded."""
    names = set(_times) | set(_device_times)
    return {
        name: {
            "calls": _counts[name],
            "wall_s": round(_times[name], 6) if name in _times else None,
            "device_s": (
                round(_device_times[name], 6)
                if name in _device_times else None
            ),
        }
        for name in names
    }


def finalize(out=None) -> None:
    """≙ LIKWID_MARKER_CLOSE: stop the trace, print the region table, and
    write the CSV twin when PAMPI_PROFILE_CSV is set. Idempotent: the
    atexit hook and an explicit driver call must not print the table (or
    rewrite the CSV) twice; init() re-arms."""
    global _tracing, _finalized
    out = out if out is not None else sys.stderr
    if not enabled() or _finalized:
        return
    _finalized = True
    if _tracing:
        import jax

        jax.profiler.stop_trace()
        _tracing = False
    names = sorted(
        set(_times) | set(_device_times),
        key=lambda n: max(_times.get(n, 0.0), _device_times.get(n, 0.0)),
        reverse=True,
    )
    if names:
        out.write("Region                    calls      time[s]\n")
        for name in names:
            t = _times.get(name) or _device_times.get(name, 0.0)
            out.write(f"{name:<24} {_counts[name]:>6} {t:>12.4f}\n")
    from . import flags as _flags

    csv_path = _flags.env("PAMPI_PROFILE_CSV",
                          doc="finalize() writes the region table as CSV")
    if csv_path and names:
        with open(csv_path, "w") as fh:
            fh.write("region,calls,wall_s,device_s\n")
            for name in names:
                wall = f"{_times[name]:.6f}" if name in _times else ""
                dev = (
                    f"{_device_times[name]:.6f}"
                    if name in _device_times
                    else ""
                )
                fh.write(f"{name},{_counts[name]},{wall},{dev}\n")


def reset() -> None:
    global _finalized
    _times.clear()
    _counts.clear()
    _device_times.clear()
    _finalized = False
