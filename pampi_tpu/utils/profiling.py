"""Region-marker profiling hooks.

Capability parity with the reference's LIKWID marker layer
(/root/reference/assignment-4/src/likwid-marker.h:104-130: START/STOP region
macros that compile to no-ops unless -DLIKWID_PERFMON) re-designed for the
TPU stack: regions become `jax.profiler` trace annotations (visible in a
TensorBoard/XProf trace) plus optional wall-clock accounting, and the no-op
switch is the PAMPI_PROFILE environment variable instead of a compile flag.

  PAMPI_PROFILE=0/unset  every call is a no-op (the likwid default)
  PAMPI_PROFILE=1        region wall-clock accounting + trace annotations
  PAMPI_PROFILE=<dir>    additionally jax.profiler.start_trace(<dir>) on
                         init and stop on finalize (full XProf trace)

Usage (mirrors LIKWID_MARKER_*):
    prof.init(); with prof.region("solve"): ...; prof.finalize()
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from collections import defaultdict

_MODE = os.environ.get("PAMPI_PROFILE", "0")
_times: dict[str, float] = defaultdict(float)
_counts: dict[str, int] = defaultdict(int)
_tracing = False


def enabled() -> bool:
    return _MODE not in ("", "0")


def init() -> None:
    """≙ LIKWID_MARKER_INIT."""
    global _tracing
    if not enabled():
        return
    if _MODE != "1":
        import jax

        jax.profiler.start_trace(_MODE)
        _tracing = True


@contextlib.contextmanager
def region(name: str):
    """≙ LIKWID_MARKER_START/STOP pair. Also a jax.profiler annotation so the
    region shows up on the device timeline."""
    if not enabled():
        yield
        return
    import jax

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    _times[name] += time.perf_counter() - t0
    _counts[name] += 1


def finalize(out=None) -> None:
    """≙ LIKWID_MARKER_CLOSE: stop the trace and print the region table."""
    global _tracing
    out = out if out is not None else sys.stderr
    if not enabled():
        return
    if _tracing:
        import jax

        jax.profiler.stop_trace()
        _tracing = False
    if _times:
        out.write("Region                    calls      time[s]\n")
        for name in sorted(_times, key=_times.get, reverse=True):
            out.write(f"{name:<24} {_counts[name]:>6} {_times[name]:>12.4f}\n")


def reset() -> None:
    _times.clear()
    _counts.clear()
