"""Comm/compute overlap: the interior/boundary split machinery shared by
the overlapped distributed solvers (models/ns2d_dist, ns3d_dist).

The overlapped step (`tpu_overlap`, ROADMAP item 2) restructures the
fused deep-halo step so the ppermute exchange for step N+1's halos rides
the loop carry as a DOUBLE-BUFFERED pair of deep blocks: posted right
after step N's POST kernel (the moment the new edge cells exist), and
consumed one iteration later by the BOUNDARY half of the PRE kernel only.
The INTERIOR half of PRE runs on the stale re-embedded block, so the
traced program carries no dependency path from the exchange to it — the
structural property that lets XLA's latency-hiding scheduler / collective
pipeliner fly the exchange behind the interior compute, and the property
`analysis/commcheck.overlap_schedule_violations` pins statically.

The split is write-gated, not grid-gated: both halves are the SAME
Pallas kernel (ops/ns2d_fused, ns3d_fused — the global-coordinate-gated
discipline) on the two buffers, merged by `merge_halves` with the
interior mask below. Cells in the interior region have a FUSE_CHAIN
dependency cone that never reaches the exchanged strips (the outer
FUSE_DEEP_HALO layers of the deep block), so the interior half's values
are bitwise those of the serial fused step; the boundary half reads the
exchanged buffer — bitwise the block the serial step exchanges — so the
merge reproduces the serial trajectory exactly (parity test-pinned,
tests/test_overlap.py; footprint-pinned, analysis/halocheck.py's
overlap-interior entries). Restricting each half's GRID to its region is
the follow-on optimization; the dataflow split is what buys the overlap.

Staleness safety: the carried buffers wear a generation tag (the step
count they were exchanged for). `generation_guard` poisons dt with NaN
on a mismatch, which the drive loop's divergence trigger catches — a
skewed double buffer is detected, never silently consumed (mutation
test-pinned via the GEN_SKEW hook).
"""

from __future__ import annotations

import jax.numpy as jnp

# Test hook: the generation-skew mutation test (tests/test_overlap.py)
# monkeypatches this to a nonzero offset before building an overlapped
# solver, forging a step that consumes a stale double buffer. Production
# value is 0 — the guard then compiles to a compare that always passes.
GEN_SKEW = 0


def overlap_rim(chain: int) -> int:
    """Width (in extended-block cells, from the block edge inward) of the
    boundary region: the extended ghost layer itself (1) plus the
    `chain`-cell validity cone of the fused PRE formulas. Every output
    cell at least this far from the block edge has a dependency cone that
    stays inside the OWNED cells — provably independent of the exchanged
    strips."""
    return chain + 1


def interior_slices(local_extents, rim: int):
    """Per-axis slices of the interior region on the (l+2)-extended
    block: indices [rim, l+2-rim). Empty when a shard is thinner than
    two rims — the split then degenerates to boundary-everywhere, which
    is correct (and overlap-free)."""
    return tuple(slice(rim, ext + 2 - rim) for ext in local_extents)


def interior_mask(local_extents, rim: int):
    """Boolean interior mask on the extended block (the merge gate of
    `merge_halves`). Local-geometry only: ragged pad cells and wall
    shards need no special case — both halves compute identical values
    wherever the cone avoids the strips, and the strips are a local
    property of the block."""
    shape = tuple(ext + 2 for ext in local_extents)
    m = jnp.zeros(shape, bool)
    return m.at[interior_slices(local_extents, rim)].set(True)


def merge_halves(mask, interior_vals, boundary_vals):
    """Elementwise merge of the two PRE halves: interior cells from the
    stale-block call, the rim from the exchanged-buffer call. A
    `jnp.where` (not masked addition) so -0.0/NaN payloads survive
    bit-exactly."""
    return tuple(
        jnp.where(mask, i, b) for i, b in zip(interior_vals, boundary_vals)
    )


def generation_guard(dt, gen, nt):
    """Stale-double-buffer detector: the carried halo buffers were
    exchanged for step `gen`; the consuming step is `nt`. On a mismatch
    dt is poisoned with NaN, so t goes NaN and the drive loop's
    divergence trigger (models/_driver.drive_chunks) reports a
    structured failure instead of the solver silently consuming stale
    halos. GEN_SKEW (module hook) forges the mismatch for the mutation
    test."""
    ok = (gen + GEN_SKEW) == nt
    return jnp.where(ok, dt, jnp.asarray(jnp.nan, dt.dtype))
