"""Comm/compute overlap: the interior/boundary split machinery shared by
the overlapped distributed solvers (models/ns2d_dist, ns3d_dist).

The overlapped step (`tpu_overlap`, ROADMAP item 2) restructures the
fused deep-halo step so the ppermute exchange for step N+1's halos rides
the loop carry as a DOUBLE-BUFFERED pair of deep blocks: posted right
after step N's POST kernel (the moment the new edge cells exist), and
consumed one iteration later by the BOUNDARY half of the PRE kernel only.
The INTERIOR half of PRE runs on the stale re-embedded block, so the
traced program carries no dependency path from the exchange to it — the
structural property that lets XLA's latency-hiding scheduler / collective
pipeliner fly the exchange behind the interior compute, and the property
`analysis/commcheck.overlap_schedule_violations` pins statically.

The split is write-gated, not grid-gated: both halves are the SAME
Pallas kernel (ops/ns2d_fused, ns3d_fused — the global-coordinate-gated
discipline) on the two buffers, merged by `merge_halves` with the
interior mask below. Cells in the interior region have a FUSE_CHAIN
dependency cone that never reaches the exchanged strips (the outer
FUSE_DEEP_HALO layers of the deep block), so the interior half's values
are bitwise those of the serial fused step; the boundary half reads the
exchanged buffer — bitwise the block the serial step exchanges — so the
merge reproduces the serial trajectory exactly (parity test-pinned,
tests/test_overlap.py; footprint-pinned, analysis/halocheck.py's
overlap-interior entries). Restricting each half's GRID to its region is
the follow-on optimization; the dataflow split is what buys the overlap.

Staleness safety: the carried buffers wear a generation tag (the step
count they were exchanged for). `generation_guard` poisons dt with NaN
on a mismatch, which the drive loop's divergence trigger catches — a
skewed double buffer is detected, never silently consumed (mutation
test-pinned via the GEN_SKEW hook).
"""

from __future__ import annotations

import jax.numpy as jnp

# Test hook: the generation-skew mutation test (tests/test_overlap.py)
# monkeypatches this to a nonzero offset before building an overlapped
# solver, forging a step that consumes a stale double buffer. Production
# value is 0 — the guard then compiles to a compare that always passes.
GEN_SKEW = 0


def interior_slices(local_extents, rim: int, partitioned=None):
    """Per-axis slices of the interior region on the (l+2)-extended
    block: indices [rim, l+2-rim). Empty when a shard is thinner than
    two rims — the split then degenerates to boundary-everywhere, which
    is correct (and overlap-free).

    `partitioned` (per-axis bools, default all True) drops the rim on
    UNPARTITIONED mesh axes: a size-1 axis exchanges nothing
    (`_exchange_axis` short-circuits), so the stale block and the
    double-buffered exchanged block are bit-identical along it — the
    interior half's cone may touch those sides freely. This is what
    lets the grid-restricted boundary half shrink to two row bands on
    a (P, 1) mesh instead of sweeping every row for column strips that
    do not exist."""
    if partitioned is None:
        partitioned = (True,) * len(local_extents)
    return tuple(
        slice(rim if part else 0, ext + 2 - (rim if part else 0))
        for ext, part in zip(local_extents, partitioned)
    )


def interior_mask(local_extents, rim: int, partitioned=None):
    """Boolean interior mask on the extended block (the merge gate of
    `merge_halves`). Local-geometry only: ragged pad cells and wall
    shards need no special case — both halves compute identical values
    wherever the cone avoids the strips, and the strips are a local
    property of the block. See `interior_slices` for `partitioned`."""
    shape = tuple(ext + 2 for ext in local_extents)
    m = jnp.zeros(shape, bool)
    return m.at[interior_slices(local_extents, rim, partitioned)].set(True)


def merge_halves(mask, interior_vals, boundary_vals):
    """Elementwise merge of the two PRE halves: interior cells from the
    stale-block call, the rim from the exchanged-buffer call. A
    `jnp.where` (not masked addition) so -0.0/NaN payloads survive
    bit-exactly."""
    return tuple(
        jnp.where(mask, i, b) for i, b in zip(interior_vals, boundary_vals)
    )


# ----------------------------------------------------------------------
# Grid restriction (ROADMAP item 3 / `tpu_overlap_restrict`): the region
# plan that turns the two full write-gated PRE sweeps into banded Pallas
# grids — the interior half sweeps only the row blocks of the interior
# core, the boundary half only the OVERLAP_RIM bands (plus the full rows
# whenever a non-leading axis is partitioned: column strips cannot be
# row-banded). Rows are in the padded-layout frame the fused kernels
# block over (ops/ns2d_fused._layout): the full sweep's block k covers
# rows [k*br, (k+1)*br) of R = nblocks*br total.
# ----------------------------------------------------------------------


def check_bands(grid_bands, block_rows: int, nblocks: int,
                label: str = "block_rows") -> None:
    """Refuse a band list that is not sorted-disjoint or that overhangs
    the padded layout — the one validation both fused-PRE builders run
    on `grid_bands` before restricting their grid (a double-stored row
    would race the output DMA; an overhanging band would DMA past the
    padded array)."""
    last_end = 0
    for s, n in grid_bands:
        if s < last_end or n < 1 or s + n * block_rows > \
                nblocks * block_rows:
            raise ValueError(
                f"grid_bands {grid_bands} do not tile the padded "
                f"layout ({label}={block_rows}, nblocks={nblocks}) "
                "disjointly")
        last_end = s + n * block_rows


def band_cover(lo: int, hi: int, block_rows: int, total_rows: int):
    """The (start_row, n_blocks) band of `block_rows`-row blocks that
    covers rows [lo, hi) and stays inside [0, total_rows): the start is
    shifted down when the rounded-up coverage would overhang (extra
    covered rows are valid compute — every write is globally gated)."""
    n = -(-(hi - lo) // block_rows)
    start = max(0, min(lo, total_rows - n * block_rows))
    return (start, n)


def _merge_bands(bands, block_rows, total_rows):
    """Coalesce overlapping/adjacent bands so no row is stored twice
    (a double-store would race the output DMA), keeping every band
    inside [0, total_rows): a merged band's rounded-up block count can
    overhang the layout (its end is the max of the inputs' ends but its
    count is re-derived by ceil), so merged starts are re-clamped like
    `band_cover`'s — which can re-overlap the previous band, hence the
    fixpoint loop (bands only move down and merge, so it terminates)."""
    out = [b for b in bands if b[1] > 0]
    while True:
        merged = []
        for s, n in sorted(out):
            if merged and s <= merged[-1][0] + merged[-1][1] * block_rows:
                ps, pn = merged[-1]
                end = max(ps + pn * block_rows, s + n * block_rows)
                merged[-1] = (ps, -(-(end - ps) // block_rows))
            else:
                merged.append((s, n))
        clamped = [(max(0, min(s, total_rows - n * block_rows)), n)
                   for s, n in merged]
        if clamped == out:
            return tuple(clamped)
        out = clamped


def region_plan(local_extents, rim: int, ext_pad: int, block_rows: int,
                nblocks: int, width: int, partitioned):
    """Banded grid plan for the two PRE halves of one shard geometry,
    over the LEADING (block-tiled) axis. Returns None when the interior
    region is empty (the split is boundary-everywhere — nothing to
    restrict); otherwise a dict:

      int_bands / bnd_bands   ((start_row, n_blocks), ...) for the
                              interior / boundary half's Pallas grid
      cells                   summed swept cells of the two banded
                              grids (blocks x block_rows x width)
      cells_full              the 2x full-sweep count they replace
      win                     cells < cells_full — the `auto` predicate

    The interior band covers exactly the interior-merge region
    (`interior_slices` with the same `partitioned` flags — the mask and
    the grid cannot drift apart); the boundary band covers the rim rows,
    widened to every row when any non-leading axis is partitioned (its
    column strips live in every row)."""
    L0 = local_extents[0]
    R = nblocks * block_rows
    lead = partitioned[0]
    cross = any(partitioned[1:])
    rim0 = rim if lead else 0
    int_lo = ext_pad + rim0
    int_hi = ext_pad + L0 + 2 - rim0
    if int_hi <= int_lo:
        return None
    int_bands = _merge_bands(
        [band_cover(int_lo, int_hi, block_rows, R)], block_rows, R)
    if cross:
        bnd = [band_cover(ext_pad, ext_pad + L0 + 2, block_rows, R)]
    elif lead:
        bnd = [band_cover(ext_pad, ext_pad + rim, block_rows, R),
               band_cover(ext_pad + L0 + 2 - rim, ext_pad + L0 + 2,
                          block_rows, R)]
    else:
        # no partitioned axis at all: no exchange, no overlap, no plan
        return None
    bnd_bands = _merge_bands(bnd, block_rows, R)
    blocks = sum(n for _, n in int_bands) + sum(n for _, n in bnd_bands)
    cells = blocks * block_rows * width
    cells_full = 2 * R * width
    return {
        "int_bands": int_bands,
        "bnd_bands": bnd_bands,
        "cells": cells,
        "cells_full": cells_full,
        "win": cells < cells_full,
    }


def generation_guard(dt, gen, nt):
    """Stale-double-buffer detector: the carried halo buffers were
    exchanged for step `gen`; the consuming step is `nt`. On a mismatch
    dt is poisoned with NaN, so t goes NaN and the drive loop's
    divergence trigger (models/_driver.drive_chunks) reports a
    structured failure instead of the solver silently consuming stale
    halos. GEN_SKEW (module hook) forges the mismatch for the mutation
    test."""
    ok = (gen + GEN_SKEW) == nt
    return jnp.where(ok, dt, jnp.asarray(jnp.nan, dt.dtype))
