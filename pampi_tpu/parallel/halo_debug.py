"""Halo-exchange debug dump — the framework's version of the reference's
manual exchange checker (/root/reference/assignment-6/src/test.c:125-228
`testInit`/`testPrintHalo`, and printExchange/printShift in
assignment-5/ex5-nazifkar/src/solver.c:34-124): fill every rank's local
block with its rank id, run the real halo exchange, and dump each ghost
face to `halo-<dir>-r<rank>.txt` so a human (or a test) can confirm the
neighbour's id appears.

Run via the driver: `python -m pampi_tpu --halo-test [2|3]`
(on CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8 to fake the
mesh — SURVEY.md §4's "multi-node without a cluster").
"""

from __future__ import annotations

import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .comm import CartComm, halo_exchange

_DIR_2D = ("bottom", "top", "left", "right")
_DIR_3D = ("front", "back", "bottom", "top", "left", "right")


def _faces(block, ndims):
    """(name, ghost-face array) pairs of the extended local block — low/high
    face per array dim, ordered like the reference's Direction enum."""
    if ndims == 2:
        return [
            ("bottom", block[0, :]),
            ("top", block[-1, :]),
            ("left", block[:, 0]),
            ("right", block[:, -1]),
        ]
    return [
        ("front", block[0, :, :]),
        ("back", block[-1, :, :]),
        ("bottom", block[:, 0, :]),
        ("top", block[:, -1, :]),
        ("left", block[:, :, 0]),
        ("right", block[:, :, -1]),
    ]


def rank_id_blocks(comm: CartComm, local_interior):
    """Fill each rank's extended block with its linear rank id, exchange all
    halos, return host blocks indexed by mesh coordinates."""
    ext = tuple(e + 2 for e in local_interior)

    def kernel():
        import jax.numpy as jnp

        rid = 0
        for ax in comm.axis_names:
            rid = rid * comm.axis_size(ax) + lax.axis_index(ax)
        blk = jnp.full(ext, rid, jnp.float32)
        return halo_exchange(blk, comm)

    out = comm.shard_map(kernel, in_specs=(), out_specs=P(*comm.axis_names))()
    glob = CartComm.collect(out)  # multihost-safe host gather
    blocks = {}
    for coords in np.ndindex(*comm.dims):
        sl = tuple(
            slice(c * e, (c + 1) * e) for c, e in zip(coords, ext)
        )
        blocks[coords] = glob[sl]
    return blocks


def dump_halos(comm: CartComm, local_interior=None, outdir=".") -> list[str]:
    """Write halo-<dir>-r<rank>.txt per rank and ghost face; returns paths."""
    if local_interior is None:
        local_interior = (4,) * comm.ndims
    blocks = rank_id_blocks(comm, local_interior)
    if not comm.is_master:
        return []  # collect was collective; rank 0 writes every file
    paths = []
    for coords, blk in blocks.items():
        rid = 0
        for c, d in zip(coords, comm.dims):
            rid = rid * d + c
        for name, face in _faces(blk, comm.ndims):
            path = f"{outdir}/halo-{name}-r{rid}.txt"
            np.savetxt(path, np.atleast_2d(face), fmt="%5.1f")
            paths.append(path)
    return paths


def main(argv) -> int:
    from . import multihost

    with multihost.session():
        ndims = int(argv[2]) if len(argv) > 2 else 2
        comm = CartComm(ndims=ndims)
        comm.print_config()
        paths = dump_halos(comm)
        print(f"wrote {len(paths)} ghost-face dumps (halo-<dir>-r<rank>.txt)")  # lint: allow(print-call) — interactive debug CLI
    return 0
