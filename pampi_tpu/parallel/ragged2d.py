"""Ragged (pad-with-mask) NS-2D wall handling: global-coordinate masked
boundary conditions for ceil-divided meshes.

On a divisible mesh every physical wall coincides with an array edge of a
wall shard, so models/ns2d_dist.py applies the reference's BC strip writes
(solver.c:236-337) wall-gated at the array edges. A ragged decomposition
breaks that coincidence on the HI sides: the wall row gi == imax (and the
ghost row gi == imax+1) can sit anywhere inside the trailing shard — or
open a fully-dead shard. These variants express the SAME arithmetic as
select-by-global-index: a wall write `x[wall] = g(x[src])` becomes
`where(mask_wall, g(roll(x)), x)`, where the roll reads the +-1 neighbour
in the local block (fresh after the preceding halo exchange; models call
these right after exchanging u and v).

Lo-side walls always sit at shard-0 array edges (padding is trailing), but
the masked forms handle them uniformly — one code path, every wall.

The value arithmetic mirrors ops/ns2d.py exactly (NOSLIP mirror, SLIP
copy, OUTFLOW copy-from-interior, PERIODIC no-op), so a ragged run tracks
the single-device trajectory to reduction order.
"""

from __future__ import annotations

import jax.numpy as jnp

from .comm import CartComm, get_offsets

NOSLIP, SLIP, OUTFLOW, PERIODIC = 1, 2, 3, 4


def global_index_vectors(comm: CartComm, jl: int, il: int):
    """(gj[col-vector], gi[row-vector]) of the (jl+2, il+2) extended block:
    ext index a maps to global extended index offset + a (interior cell 1
    is global 1 on the first shard)."""
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    gj = (jnp.arange(jl + 2, dtype=jnp.int32) + joff)[:, None]
    gi = (jnp.arange(il + 2, dtype=jnp.int32) + ioff)[None, :]
    return gj, gi


def live_masks(comm: CartComm, jl: int, il: int, jmax: int, imax: int, dtype):
    """Multiply-mask zeroing DEAD cells (beyond the global ghost ring) of
    the extended block — applied to u/v after the projection so pad-cell
    garbage never reaches maxElement's CFL scan (the reference's ghost-
    inclusive maxElement quirk makes every stored cell scan-relevant)."""
    gj, gi = global_index_vectors(comm, jl, il)
    live = (gj <= jmax + 1) & (gi <= imax + 1)
    return live.astype(dtype)


def set_bcs_ragged(u, v, param, comm: CartComm, jl: int, il: int,
                   jmax: int, imax: int, grids=None):
    """setBoundaryConditions (solver.c:236-337) as global-index selects.

    `grids` (the (gj, gi) index grids) lets callers OUTSIDE shard_map —
    the fleet's shape-class chunk, which runs this chain on one full
    padded block with TRACED jmax/imax — supply precomputed vectors
    instead of the shard-offset lookup (get_offsets reads the shard_map
    axis index). The arithmetic is unchanged: jmax/imax appear only in
    comparisons and value terms, so they may be ints or traced scalars."""
    gj, gi = (global_index_vectors(comm, jl, il)
              if grids is None else grids)
    tan_j = (gj >= 1) & (gj <= jmax)
    tan_i = (gi >= 1) & (gi <= imax)

    def sel(mask, new, old):
        return jnp.where(mask, new, old)

    # east/west/north/south reads as local rolls (halos fresh by contract)
    def w_of(x):   # value one column west
        return jnp.roll(x, 1, axis=1)

    def e_of(x):
        return jnp.roll(x, -1, axis=1)

    def s_of(x):   # value one row south
        return jnp.roll(x, 1, axis=0)

    def n_of(x):
        return jnp.roll(x, -1, axis=0)

    # left wall: U(0,j) on the wall, V(0,j) ghost mirrors V(1,j)
    m_u = (gi == 0) & tan_j
    if param.bcLeft == NOSLIP:
        u = sel(m_u, jnp.zeros_like(u), u)
        v = sel(m_u, -e_of(v), v)
    elif param.bcLeft == SLIP:
        u = sel(m_u, jnp.zeros_like(u), u)
        v = sel(m_u, e_of(v), v)
    elif param.bcLeft == OUTFLOW:
        u = sel(m_u, e_of(u), u)
        v = sel(m_u, e_of(v), v)
    # right wall: U(imax,j) ON the wall, V(imax+1,j) ghost
    m_w = (gi == imax) & tan_j
    m_g = (gi == imax + 1) & tan_j
    if param.bcRight == NOSLIP:
        u = sel(m_w, jnp.zeros_like(u), u)
        v = sel(m_g, -w_of(v), v)
    elif param.bcRight == SLIP:
        u = sel(m_w, jnp.zeros_like(u), u)
        v = sel(m_g, w_of(v), v)
    elif param.bcRight == OUTFLOW:
        u = sel(m_w, w_of(u), u)
        v = sel(m_g, w_of(v), v)
    # bottom wall: V(i,0) on the wall, U(i,0) ghost
    m_v = (gj == 0) & tan_i
    if param.bcBottom == NOSLIP:
        v = sel(m_v, jnp.zeros_like(v), v)
        u = sel(m_v, -n_of(u), u)
    elif param.bcBottom == SLIP:
        v = sel(m_v, jnp.zeros_like(v), v)
        u = sel(m_v, n_of(u), u)
    elif param.bcBottom == OUTFLOW:
        u = sel(m_v, n_of(u), u)
        v = sel(m_v, n_of(v), v)
    # top wall: V(i,jmax) ON the wall, U(i,jmax+1) ghost
    m_vw = (gj == jmax) & tan_i
    m_ug = (gj == jmax + 1) & tan_i
    if param.bcTop == NOSLIP:
        v = sel(m_vw, jnp.zeros_like(v), v)
        u = sel(m_ug, -s_of(u), u)
    elif param.bcTop == SLIP:
        v = sel(m_vw, jnp.zeros_like(v), v)
        u = sel(m_ug, s_of(u), u)
    elif param.bcTop == OUTFLOW:
        u = sel(m_ug, s_of(u), u)
        v = sel(m_vw, s_of(v), v)
    return u, v


def set_special_bc_ragged(u, param, comm: CartComm, jl: int, il: int,
                          jmax: int, imax: int, dy, idx_dtype,
                          grids=None):
    """setSpecialBoundaryCondition (solver.c:339-357) masked by global
    index; replicates the reference's dcavity lid loop-bound quirk (skips
    i == imax). `grids` as in set_bcs_ragged (offset-0 callers)."""
    gj, gi = (global_index_vectors(comm, jl, il)
              if grids is None else grids)
    if param.name == "dcavity":
        m = (gj == jmax + 1) & (gi >= 1) & (gi <= imax - 1)
        return jnp.where(m, 2.0 - jnp.roll(u, 1, axis=0), u)
    if param.name in ("canal", "canal_obstacle"):
        joff = 0 if grids is not None else get_offsets("j", jl)
        jj = jnp.arange(jl + 2, dtype=idx_dtype) + joff
        y = ((jj - 0.5) * dy).astype(u.dtype)
        prof = (y * (param.ylength - y) * 4.0 / (param.ylength**2))[:, None]
        m = (gi == 0) & (gj >= 1) & (gj <= jmax)
        return jnp.where(m, jnp.broadcast_to(prof, u.shape), u)
    return u


def fg_fixups_ragged(f, g, u, v, comm: CartComm, jl: int, il: int,
                     jmax: int, imax: int, grids=None):
    """F/G wall fixups (solver.c:425-435): same-position copies from u/v,
    masked by global index. `grids` as in set_bcs_ragged."""
    gj, gi = (global_index_vectors(comm, jl, il)
              if grids is None else grids)
    tan_j = (gj >= 1) & (gj <= jmax)
    tan_i = (gi >= 1) & (gi <= imax)
    f = jnp.where(((gi == 0) | (gi == imax)) & tan_j, u, f)
    g = jnp.where(((gj == 0) | (gj == jmax)) & tan_i, v, g)
    return f, g


def wall_weight_ragged(comm: CartComm, jl: int, il: int,
                       jmax: int, imax: int, dtype):
    """normalizePressure weighting: count every global position of the full
    (jmax+2)x(imax+2) array exactly once across the stacked extended blocks.
    Owned interior rows carry gj in [1, jmax+1] (the global hi ghost row is
    interior-stored when ragged); the array-edge ghost rows count only where
    they ARE the global ghost rows (gj == 0 / jmax+1), which covers the
    divisible case and zeroes dead trailing edges."""
    gj, gi = global_index_vectors(comm, jl, il)
    lj = jnp.arange(jl + 2, dtype=jnp.int32)[:, None]
    li = jnp.arange(il + 2, dtype=jnp.int32)[None, :]
    # the global hi ghost row is interior-stored exactly when the axis is
    # ragged; count it at the array edge only when it is NOT (else the next
    # shard's lo edge would double-count it) — static per axis
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    edge_j = [0] if jmax + 1 <= Pj * jl else [0, jmax + 1]
    edge_i = [0] if imax + 1 <= Pi * il else [0, imax + 1]

    def axis_own(l, g, loc_n, gmax, edges):
        owned = (l >= 1) & (l <= loc_n) & (g <= gmax + 1)
        at_edge = (l == 0) | (l == loc_n + 1)
        edge_ok = jnp.zeros_like(owned)
        for e in edges:
            edge_ok = edge_ok | (g == e)
        return owned | (at_edge & edge_ok)

    own_j = axis_own(lj, gj, jl, jmax, edge_j)
    own_i = axis_own(li, gi, il, imax, edge_i)
    return (own_j & own_i).astype(dtype)
