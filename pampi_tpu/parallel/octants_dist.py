"""Distributed OCTANT-layout 3-D red-black SOR: geometry, packing, deep-halo
exchange, and the jnp twin of the per-shard Pallas kernel.

The 3-D form of parallel/quarters_dist.py (same derivation, one dimension
up): the octant decomposition of ops/sor_octants.py — every 7-point
neighbour a uniform shift, every lane productive (the 4.9×/RB-iteration
NS-3D kernel) — carried ACROSS the distributed convergence loop of
models/ns3d_dist.py with one communication-avoiding depth-n octant exchange
per n red-black iterations.

LAYOUT: all eight octants of a shard are GLOBALLY ALIGNED. Stored indices
(s, r, c) of every slot hold global octant coords

    go_k = (s - h) - d_k + qoff_k   (h = kernel k-window halo = n planes,
    go_j = r - d_j + qoff_j          no alignment needed on the untiled k
    go_i = c - d_i + qoff_i          axis; j/i pad to sublane/lane tiles)

with qoff_* = shard offset / 2 (shard extents even ⇒ offsets even ⇒ the
parity split is decomposition-invariant and the single-device neighbour/
Neumann identities hold verbatim). Per parity bit b of an axis, owned
stored indices start at base + (1 if b == 0 else 0) — static bounds.

d_ax is the PER-AXIS stored deep-halo depth: n on mesh axes that actually
exchange (size > 1), 0 on axes the shard fully owns. A size-1 axis has
physical walls on both sides whose ghosts the in-kernel Neumann refresh
maintains every iteration — exactly the single-device kernel's situation —
so storing 2n CA ghost planes there would only inflate the window with
redundantly-recomputed cells (measured 32% per-iteration cost at 128^3 on
a (1,1,1) mesh, round 4; with d=(0,0,0) the kernel is geometrically the
single-device octant kernel).

CA semantics match the 2-D module exactly on exchanged axes: one iteration
consumes one octant plane of validity per side; the outermost stored ring
is frozen (read-only — in grid space it IS the outermost grid ghost plane,
so the proven depth-2n grid CA argument carries over); ghost cells are
redundantly recomputed; residuals count owned cells only. On d_ax = 0 axes
there is no frozen ring and no consumption — the per-parity global-index
bounds alone clip the updates, as in ops/sor3d_pallas's octant kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from ..ops.sor_octants import BITS, EVEN, ODD, _flip
from .comm import CartComm, _nbr_perm

# slot index per bits tuple (pk, pj, pi) in the stacked (8, ...) array
QIDX = {bits: i for i, bits in enumerate(BITS)}
# per axis: slots whose parity bit on that axis is 0 / 1
AXIS_SLOTS = [
    ([QIDX[b] for b in BITS if b[ax] == 0], [QIDX[b] for b in BITS if b[ax] == 1])
    for ax in range(3)
]


@dataclass(frozen=True)
class OGeom:
    """Static geometry of the distributed stacked octant layout."""

    kmax: int
    jmax: int
    imax: int
    kl: int  # per-shard interior extents (even)
    jl: int
    il: int
    n: int    # RB iterations per exchange (temporal depth)
    h: int    # kernel k-window halo (= n; untiled axis)
    bk: int   # kernel block depth (octant planes)
    kq: int   # logical stored k span: kl/2 + 2*d_k + 1
    jq: int
    iq: int
    sp: int   # padded stored k: nblocks*bk + 2h
    jp2: int  # padded stored j (sublane multiple)
    ip2: int  # padded stored i (lane multiple)
    nblocks: int
    d: tuple[int, int, int] = None  # stored deep-halo depth per axis
    #   (n on exchanged mesh axes, 0 on fully-owned ones; None -> (n,n,n))

    def __post_init__(self):
        if self.d is None:
            object.__setattr__(self, "d", (self.n, self.n, self.n))

    @property
    def base(self) -> tuple[int, int, int]:
        """Stored index of global octant coord qoff_* per axis."""
        return (self.h + self.d[0], self.d[1], self.d[2])

    def gmax2(self, axis: int) -> int:
        return (self.kmax, self.jmax, self.imax)[axis] // 2

    def local2(self, axis: int) -> int:
        return (self.kl, self.jl, self.il)[axis] // 2

    def span(self, axis: int) -> int:
        return (self.kq, self.jq, self.iq)[axis]


def make_ogeom(kmax, jmax, imax, kl, jl, il, n, dtype,
               bk: int | None = None,
               dims: tuple[int, int, int] | None = None) -> OGeom:
    """dims = mesh sizes per ("k","j","i") axis; axes of size 1 store no
    deep halo (see the module docstring). dims=None keeps d=(n,n,n) — the
    conservative all-halo layout (used by geometry unit tests)."""
    from ..ops import sor_pallas as sp

    a = sp._align(dtype)
    h = n  # k axis is untiled: halo needs no alignment rounding
    d = (n, n, n) if dims is None else tuple(
        n if sz > 1 else 0 for sz in dims
    )
    kq = kl // 2 + 2 * d[0] + 1
    jq = jl // 2 + 2 * d[1] + 1
    iq = il // 2 + 2 * d[2] + 1
    jp2 = -(-jq // a) * a
    ip2 = -(-iq // sp.LANE) * sp.LANE
    if bk is None:
        from ..ops.sor3d_pallas import VMEM_LIMIT_BYTES

        plane = jp2 * ip2 * jnp.dtype(dtype).itemsize
        feasible = ((VMEM_LIMIT_BYTES // 2) // max(plane, 1) - 64 * n) // 48
        bk = max(1, min(feasible, kq, 64))
    nblocks = -(-kq // bk)
    sp_ = nblocks * bk + 2 * h
    return OGeom(kmax, jmax, imax, kl, jl, il, n, h, bk, kq, jq, iq,
                 sp_, jp2, ip2, nblocks, d)


def odist_supported(kmax, jmax, imax, kl, jl, il) -> bool:
    return (
        kmax % 2 == 0 and jmax % 2 == 0 and imax % 2 == 0
        and kl % 2 == 0 and jl % 2 == 0 and il % 2 == 0
        and kl >= 4 and jl >= 4 and il >= 4
    )


def odist_clamp(n: int, kl: int, jl: int, il: int,
                dims: tuple[int, int, int] | None = None) -> int:
    """CA-depth clamp: owned strips must be able to ship depth-n ghost
    slabs, so n is bounded by the EXCHANGED axes' extents — a fully-owned
    j/i axis (mesh size 1) stores no deep halo and imposes no bound. The k
    axis always bounds n regardless of its mesh size: the kernel's k-window
    temporal halo is n planes whatever d_k is, and n >> kl/2 would be
    mostly redundant recompute (and can blow the VMEM feasibility check)."""
    exts = [kl]
    if dims is None:
        exts = [kl, jl, il]
    else:
        exts += [e for e, sz in zip((kl, jl, il), dims) if sz > 1]
    return max(1, min(n, min(exts) // 2 - 1))


def octants_dispatch(param, kmax, jmax, imax, kl, jl, il, dx, dy, dz, dtype,
                     record_key: str, plain_sor: bool,
                     dims: tuple[int, int, int] | None = None):
    """3-D twin of quarters_dist.quarters_dispatch (models/ns3d_dist):
    returns (rb_o, og, n_o, pallas_o); rb_o None -> grid-space jnp CA."""
    from ..utils import dispatch as _dispatch

    layout = param.tpu_sor_layout
    osup = odist_supported(kmax, jmax, imax, kl, jl, il)
    if layout == "octants" and not (osup and plain_sor):
        raise ValueError(
            "tpu_sor_layout octants needs even global and per-shard "
            "extents (>= 4) and the plain tpu_solver sor path"
        )
    if not (plain_sor and osup and layout in ("auto", "octants")):
        return None, None, 0, False
    from ..models.ns3d import _use_pallas_3d

    if not (layout == "octants" or _use_pallas_3d("auto", dtype)):
        return None, None, 0, False
    n_o = odist_clamp(
        max(param.tpu_ca_inner, param.tpu_sor_inner), kl, jl, il, dims
    )
    og = make_ogeom(kmax, jmax, imax, kl, jl, il, n_o, dtype, dims=dims)
    try:
        from ..ops.sor_odist import make_rb_iters_odist

        rb_o = make_rb_iters_odist(og, dx, dy, dz, param.omg, dtype)
    except ValueError:
        rb_o = None
    if rb_o is not None:
        _dispatch.record(record_key, f"pallas_octants ca{n_o}")
        return rb_o, og, n_o, True
    if layout == "octants":
        from ..models.ns3d import sor_coefficients_3d

        factor, idx2, idy2, idz2 = sor_coefficients_3d(
            dx, dy, dz, param.omg
        )

        def rb_o(qoffs, xo, ro):
            m = o_masks(og, qoffs[0], qoffs[1], qoffs[2])
            return rb_iters_o_jnp(xo, ro, og, m, factor, idx2, idy2, idz2)

        _dispatch.record(record_key, f"jnp_octants ca{n_o}")
        return rb_o, og, n_o, False
    return None, None, 0, False


def _owned_start(g: OGeom, axis: int, bit: int) -> int:
    return g.base[axis] + (1 if bit == 0 else 0)


# ----------------------------------------------------------------------
# Packing: (kl+2, jl+2, il+2) extended block <-> stacked (8, sp, jp2, ip2)
# ----------------------------------------------------------------------


def pack_ext_to_o(ext, g: OGeom):
    """Extended halo-1 block -> stacked octant layout (staged single-axis
    stride-2 slices — the layout-safe form of sor3d_pallas.pad_octants)."""
    slabs = {}
    for pk in (0, 1):
        sk = ext[pk::2]
        for pj in (0, 1):
            skj = sk[:, pj::2]
            for pi in (0, 1):
                slabs[(pk, pj, pi)] = skj[:, :, pi::2]
    stacked = jnp.stack([slabs[bits] for bits in BITS])
    bk_, bj, bi = g.base
    out = jnp.zeros((8, g.sp, g.jp2, g.ip2), ext.dtype)
    return out.at[
        :,
        bk_ : bk_ + g.kl // 2 + 1,
        bj : bj + g.jl // 2 + 1,
        bi : bi + g.il // 2 + 1,
    ].set(stacked)


def unpack_o_to_ext(xo, g: OGeom):
    """Inverse of pack_ext_to_o, staged axis-at-a-time scatter."""
    k2, j2, i2 = g.kl // 2 + 1, g.jl // 2 + 1, g.il // 2 + 1
    bk_, bj, bi = g.base
    stacked = xo[:, bk_ : bk_ + k2, bj : bj + j2, bi : bi + i2]
    q = {bits: stacked[qi] for qi, bits in enumerate(BITS)}
    kj = {}
    for pk in (0, 1):
        for pj in (0, 1):
            m = jnp.zeros((k2, j2, 2 * i2), xo.dtype)
            m = m.at[:, :, 0::2].set(q[(pk, pj, 0)])
            m = m.at[:, :, 1::2].set(q[(pk, pj, 1)])
            kj[(pk, pj)] = m
    slabs = {}
    for pk in (0, 1):
        m = jnp.zeros((k2, 2 * j2, 2 * i2), xo.dtype)
        m = m.at[:, 0::2].set(kj[(pk, 0)])
        m = m.at[:, 1::2].set(kj[(pk, 1)])
        slabs[pk] = m
    p = jnp.zeros((2 * k2, 2 * j2, 2 * i2), xo.dtype)
    p = p.at[0::2].set(slabs[0])
    p = p.at[1::2].set(slabs[1])
    return p


# ----------------------------------------------------------------------
# Deep-halo exchange in octant space
# ----------------------------------------------------------------------


def o_exchange(xo, comm: CartComm, g: OGeom):
    """commExchange in octant space: depth-d_ax ghost slabs per axis per
    parity group, PROC_NULL at physical walls. 12 ppermutes total (3 axes ×
    2 directions × 2 parity groups), each carrying a stacked 4-slot strip.
    Axes with mesh size 1 store no deep halo and are skipped."""
    for axis, name in enumerate(("k", "j", "i")):
        nper = comm.axis_size(name)
        n = g.d[axis]
        if nper > 1 and n == 0:
            raise ValueError(
                f"OGeom stores no deep halo on axis {name!r} but the mesh "
                f"has {nper} shards there — the geometry was built for a "
                "different mesh (pass dims=comm.dims to make_ogeom)"
            )
        if nper == 1:
            continue
        adim = axis + 1  # array axis in the (8, s, r, c) stacked layout
        l2 = g.local2(axis)
        idx = lax.axis_index(name)
        for bit in (0, 1):
            slots = AXIS_SLOTS[axis][bit]
            os = _owned_start(g, axis, bit)
            grp = xo[jnp.asarray(slots)]
            # low ghosts [os-n, os) <- -1 neighbour's owned top slab
            strip = lax.slice_in_dim(grp, os + l2 - n, os + l2, axis=adim)
            recv = lax.ppermute(strip, name, _nbr_perm(nper, True, False))
            old = lax.slice_in_dim(grp, os - n, os, axis=adim)
            recv = jnp.where(idx > 0, recv, old)
            grp = lax.dynamic_update_slice_in_dim(grp, recv, os - n, axis=adim)
            # high ghosts [os+l2, os+l2+n) <- +1 neighbour's owned bottom
            strip = lax.slice_in_dim(grp, os, os + n, axis=adim)
            recv = lax.ppermute(strip, name, _nbr_perm(nper, False, False))
            old = lax.slice_in_dim(grp, os + l2, os + l2 + n, axis=adim)
            recv = jnp.where(idx < nper - 1, recv, old)
            grp = lax.dynamic_update_slice_in_dim(grp, recv, os + l2, axis=adim)
            for gi, si in enumerate(slots):
                xo = xo.at[si].set(grp[gi])
    return xo


# ----------------------------------------------------------------------
# Masks + the jnp twin of the per-shard kernel
# ----------------------------------------------------------------------


def o_masks(g: OGeom, qoff_k, qoff_j, qoff_i):
    """Per-slot masks on the full (sp, jp2, ip2) stored volume from GLOBAL
    octant coordinates — keep in lockstep with ops/sor_odist.py. One
    DELIBERATE asymmetry: this twin keeps all three ax_own terms in
    m["own"] while the kernel drops the d_ax = 0 terms — equivalent
    because on a fully-owned axis ax_own equals the ax_int interior where
    rm is already zero, so the owned residual sums are identical."""
    s = jnp.arange(g.sp, dtype=jnp.int32)[:, None, None]
    r = jnp.arange(g.jp2, dtype=jnp.int32)[None, :, None]
    c = jnp.arange(g.ip2, dtype=jnp.int32)[None, None, :]
    lam = (s - g.h, r, c)
    go = (lam[0] - g.d[0] + qoff_k, lam[1] - g.d[1] + qoff_j,
          lam[2] - g.d[2] + qoff_i)
    # the frozen-outermost-ring clip exists only on deep-halo axes; on
    # d_ax = 0 axes the per-parity global bounds (ax_int) are the full clip
    valid_upd_ax = [
        (lam[a] >= 1) & (lam[a] <= g.span(a) - 2) if g.d[a] > 0
        else jnp.ones_like(lam[a], dtype=bool)
        for a in range(3)
    ]
    valid_upd = valid_upd_ax[0] & valid_upd_ax[1] & valid_upd_ax[2]

    def ax_int(axis, bit):
        if bit == 0:
            return (go[axis] >= 1) & (go[axis] <= g.gmax2(axis))
        return (go[axis] >= 0) & (go[axis] <= g.gmax2(axis) - 1)

    def ax_own(axis, bit):
        st = (s, r, c)[axis]
        os = _owned_start(g, axis, bit)
        return (st >= os) & (st < os + g.local2(axis))

    m = {"upd": {}, "own": {}, "wall": {}}
    for bits in BITS:
        m["upd"][bits] = (
            ax_int(0, bits[0]) & ax_int(1, bits[1]) & ax_int(2, bits[2])
            & valid_upd
        )
        m["own"][bits] = (
            ax_own(0, bits[0]) & ax_own(1, bits[1]) & ax_own(2, bits[2])
        )
    # 24 Neumann face selects: (axis, hi, bits) -> mask on the TARGET slot
    valid_any = (
        (lam[0] >= 0) & (lam[0] < g.kq)
        & (lam[1] >= 0) & (lam[1] < g.jq)
        & (lam[2] >= 0) & (lam[2] < g.iq)
    )
    for axis in range(3):
        for hi in (False, True):
            plane = (
                go[axis] == (g.gmax2(axis) if hi else 0)
            )
            for bits in BITS:
                if bits[axis] != (1 if hi else 0):
                    continue
                a2, a3 = [a for a in range(3) if a != axis]
                m["wall"][(axis, hi, bits)] = (
                    plane & ax_int(a2, bits[a2]) & ax_int(a3, bits[a3])
                    & valid_any
                )
    return m


def rb_iters_o_jnp(xo, rhso, g: OGeom, m, factor, idx2, idy2, idz2):
    """g.n full 3-D red-black iterations + Neumann refresh on the stacked
    stored volume — the jnp twin of ops/sor_odist's kernel (identical
    neighbour identities, select masks, update order). Returns
    (xo', owned sum of r² of the LAST iteration)."""
    octs = {bits: xo[QIDX[bits]] for bits in BITS}
    rhs_o = {bits: rhso[QIDX[bits]] for bits in BITS}

    def nbrs(bits):
        def ax_pair(axis):
            partner = octs[_flip(bits, axis)]
            if bits[axis] == 0:
                return jnp.roll(partner, 1, axis), partner
            return partner, jnp.roll(partner, -1, axis)

        f, bk_ = ax_pair(0)
        s_, n_ = ax_pair(1)
        w, e = ax_pair(2)
        return w, e, s_, n_, f, bk_

    resids = {}
    for _ in range(g.n):
        for group in (ODD, EVEN):
            for bits in group:
                cen = octs[bits]
                w, e, s_, n_, f, bk_ = nbrs(bits)
                r = rhs_o[bits] - (
                    (e - 2.0 * cen + w) * idx2
                    + (n_ - 2.0 * cen + s_) * idy2
                    + (bk_ - 2.0 * cen + f) * idz2
                )
                rm = jnp.where(m["upd"][bits], r, jnp.zeros_like(r))
                octs[bits] = cen - factor * rm
                resids[bits] = rm
        for axis in range(3):
            for hi in (False, True):
                for bits in BITS:
                    if bits[axis] != (1 if hi else 0):
                        continue
                    octs[bits] = jnp.where(
                        m["wall"][(axis, hi, bits)],
                        octs[_flip(bits, axis)], octs[bits],
                    )

    rsq = jnp.zeros((), xo.dtype)
    for bits in BITS:
        rq = resids[bits]
        rsq = rsq + jnp.sum(
            jnp.where(m["own"][bits], rq * rq, jnp.zeros_like(rq))
        )
    return jnp.stack([octs[bits] for bits in BITS]), rsq
