"""Distributed QUARTER-layout red-black SOR: geometry, packing, deep-halo
exchange, and the jnp twin of the per-shard Pallas kernel.

This is the production multi-chip pressure-solve path (round-3 close of the
round-2 gap "the hot Pallas kernels are not wired into the distributed
solvers"): the quarter decomposition of ops/sor_quarters.py — every 5-point
neighbour a uniform ±1 shift, every lane productive (the 4096² single-chip
headline kernel) — carried ACROSS the distributed convergence loop, with one
communication-avoiding deep-halo exchange per n red-black iterations, exactly
like the jnp CA path of parallel/stencil2d.py. In the reference the hot SOR
kernel is what runs on every rank (assignment-5/ex5-nazifkar/src/solver.c:
586-655); here the quarters kernel runs on every TPU chip.

LAYOUT (the one idea everything below depends on): all four quarters of a
shard are GLOBALLY ALIGNED — stored row ρ of every quarter slot holds global
quarter-row gqr = ρ - h - n + qoff_j (qoff_j = joff/2; h = kernel window
halo, n = CA depth in quarter rows), and stored col c holds
gqc = c - n + qoff_i. Because shard extents jl/il are even, joff/ioff are
even on every shard, so the parity split is decomposition-invariant and the
same-index inter-quarter identities of the single-device kernel (W/E/S/N
uniform shifts, 8 same-index Neumann edge selects) hold verbatim. What
becomes per-parity is only WHICH stored rows are owned: even-parity rows own
[h+n+1, h+n+jl/2], odd-parity rows [h+n, h+n+jl/2-1] — static bounds, baked
into masks.

CA semantics (≙ stencil2d.ca_rb_iters): one iteration consumes ONE quarter
row of ghost validity per side; a depth-n quarter exchange buys n exact
iterations; ghost cells are redundantly recomputed by both neighbouring
shards with identical arithmetic, so the distributed trajectory equals the
single-device quarters trajectory. Updates are clipped to the stored logical
region (static bounds), so dead padding never evolves and every value is
deterministic.

The Pallas kernel twin lives in ops/sor_qdist.py; this module's
`rb_iters_q_jnp` mirrors its per-cell arithmetic op-for-op (roll +
where-select, reference association) so interpret-mode kernel output is
bitwise-comparable on the CPU mesh (tests/test_quarters_dist.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
from jax import lax

from .comm import CartComm, _nbr_perm

# slot order in the stacked (4, rp, w2p) array; (pr, pc) = global row/col
# parity of the cells each slot holds (ops/sor_quarters.py derivation)
SLOTS = ("R0", "R1", "B0", "B1")
SLOT_PARITY = ((0, 0), (1, 1), (0, 1), (1, 0))  # (pr, pc) per slot


@dataclass(frozen=True)
class QGeom:
    """Static geometry of the distributed stacked quarter layout."""

    jmax: int  # global interior rows
    imax: int
    jl: int  # per-shard interior rows (even)
    il: int
    n: int  # CA depth in quarter rows = RB iterations per exchange
    h: int  # kernel window halo (>= n, sublane-aligned)
    brq: int  # kernel block height (quarter rows)
    jq: int  # logical stored row span: jl/2 + 2n + 1
    iq: int  # logical stored col span: il/2 + 2n + 1
    rp: int  # padded stored rows: nblocks*brq + 2h
    w2p: int  # padded stored cols (lane multiple)
    nblocks: int

    @property
    def row_base(self) -> int:
        """Stored row of global quarter-row qoff_j (= λ n + window halo h)."""
        return self.h + self.n

    @property
    def col_base(self) -> int:
        return self.n


def make_qgeom(jmax, imax, jl, il, n, dtype, brq: int | None = None) -> QGeom:
    from ..ops import sor_pallas as sp

    a = sp._align(dtype)
    h = max(a, -(-n // a) * a)  # sublane-aligned window halo >= n
    jq = jl // 2 + 2 * n + 1
    iq = il // 2 + 2 * n + 1
    if brq is None:
        # same depth-aware policy as the single-device maker: deeper
        # temporal blocking wants taller blocks to amortize halo recompute
        # (sor_pallas.make_rb_iter_tblock_quarters round-3 sweep)
        whole = -(-jq // a) * a
        base = 64 if n < 12 else 128
        brq = max(a, h, min(base, whole))
    nblocks = -(-jq // brq)
    rp = nblocks * brq + 2 * h
    w2p = -(-iq // sp.LANE) * sp.LANE
    return QGeom(jmax, imax, jl, il, n, h, brq, jq, iq, rp, w2p, nblocks)


def qdist_supported(jmax, imax, jl, il) -> bool:
    """Even global dims (quarter structure) + even shard extents (parity
    alignment) + enough owned rows to ship a depth-1 strip."""
    return (
        jmax % 2 == 0 and imax % 2 == 0
        and jl % 2 == 0 and il % 2 == 0
        and jl >= 4 and il >= 4
    )


def qdist_clamp(n: int, jl: int, il: int) -> int:
    """Ghost strips must come from owned cells: n <= min(jl, il)/2 - 1
    (the odd-parity owned extent is jl/2 with a one-row stagger, so keep a
    one-row margin)."""
    return max(1, min(n, min(jl, il) // 2 - 1))


def quarters_dispatch(param, jmax, imax, jl, il, dx, dy, dtype,
                      record_key: str, plain_sor: bool):
    """The dispatch ladder shared by the 2-D distributed solvers
    (models/poisson_dist, models/ns2d_dist): decide whether the
    quarter-layout production path runs, build the per-shard Pallas kernel
    (interpret off-TPU) or the jnp twin under a forced layout, and record
    the decision in the dispatch probe.

    Returns (rb_q, qg, n_q, pallas_q); rb_q is None when the caller should
    run its grid-space jnp CA path (and record its own fallback label).
    Raises ValueError on a forced `tpu_sor_layout quarters` that is
    structurally ineligible."""
    from ..utils import dispatch as _dispatch

    layout = param.tpu_sor_layout
    qsup = qdist_supported(jmax, imax, jl, il)
    if layout == "quarters" and not (qsup and plain_sor):
        raise ValueError(
            "tpu_sor_layout quarters needs even global and per-shard "
            "extents (>= 4) and the plain tpu_solver sor path"
        )
    if not (plain_sor and qsup and layout in ("auto", "quarters")):
        return None, None, 0, False
    from ..models.poisson import _use_pallas

    if not (layout == "quarters" or _use_pallas("auto", dtype)):
        return None, None, 0, False
    n_q = qdist_clamp(max(param.tpu_ca_inner, param.tpu_sor_inner), jl, il)
    qg = make_qgeom(jmax, imax, jl, il, n_q, dtype)
    try:
        from ..ops.sor_qdist import make_rb_iters_qdist

        rb_q = make_rb_iters_qdist(qg, dx, dy, param.omg, dtype)
    except ValueError:
        rb_q = None
    if rb_q is not None:
        _dispatch.record(record_key, f"pallas_quarters ca{n_q}")
        return rb_q, qg, n_q, True
    if layout == "quarters":
        # forced layout without a lowerable kernel (e.g. f64): the jnp twin
        # runs the same quarter-space CA choreography
        dx2, dy2 = dx * dx, dy * dy
        factor = param.omg * 0.5 * (dx2 * dy2) / (dx2 + dy2)

        def rb_q(qoffs, xq, rq):
            m = q_masks(qg, qoffs[0], qoffs[1])
            return rb_iters_q_jnp(
                xq, rq, qg, m, factor, 1.0 / dx2, 1.0 / dy2
            )

        _dispatch.record(record_key, f"jnp_quarters ca{n_q}")
        return rb_q, qg, n_q, False
    return None, None, 0, False


# ----------------------------------------------------------------------
# Packing: (jl+2, il+2) extended block <-> stacked (4, rp, w2p)
# ----------------------------------------------------------------------


def pack_ext_to_q(ext, g: QGeom):
    """Extended halo-1 block -> stacked quarter layout. Extended cell (a, b)
    is global (a + joff, b + ioff); joff/ioff even, so local parity IS
    global parity and the slot split is the single-device one. All four
    quarters land at the same stored offsets [row_base, row_base + jl/2]
    × [col_base, col_base + il/2] (the +1 ghost row/col included)."""
    stacked = jnp.stack([
        ext[0::2, 0::2],  # R0 (even, even)
        ext[1::2, 1::2],  # R1 (odd, odd)
        ext[0::2, 1::2],  # B0 (even, odd)
        ext[1::2, 0::2],  # B1 (odd, even)
    ])
    out = jnp.zeros((4, g.rp, g.w2p), ext.dtype)
    return out.at[
        :,
        g.row_base : g.row_base + g.jl // 2 + 1,
        g.col_base : g.col_base + g.il // 2 + 1,
    ].set(stacked)


def unpack_q_to_ext(xq, g: QGeom):
    """Inverse of pack_ext_to_q (staged axis-at-a-time interleave — the
    layout-safe form of ops/sor_pallas.unpad_quarters)."""
    j2 = g.jl // 2 + 1
    i2 = g.il // 2 + 1
    q = xq[:, g.row_base : g.row_base + j2, g.col_base : g.col_base + i2]
    r_even = jnp.zeros((j2, 2 * i2), xq.dtype)
    r_even = r_even.at[:, 0::2].set(q[0])  # R0
    r_even = r_even.at[:, 1::2].set(q[2])  # B0
    r_odd = jnp.zeros((j2, 2 * i2), xq.dtype)
    r_odd = r_odd.at[:, 0::2].set(q[3])  # B1
    r_odd = r_odd.at[:, 1::2].set(q[1])  # R1
    p = jnp.zeros((2 * j2, 2 * i2), xq.dtype)
    p = p.at[0::2].set(r_even)
    p = p.at[1::2].set(r_odd)
    return p


# ----------------------------------------------------------------------
# Deep-halo exchange in quarter space
# ----------------------------------------------------------------------


def _owned_start_row(g: QGeom, pr: int) -> int:
    return g.row_base + (1 if pr == 0 else 0)


def _owned_start_col(g: QGeom, pc: int) -> int:
    return g.col_base + (1 if pc == 0 else 0)


def q_exchange(xq, comm: CartComm, g: QGeom):
    """commExchange in quarter space: refresh the depth-n ghost strips of
    every quarter from the ±1 mesh neighbours, PROC_NULL semantics at the
    physical walls (≙ halo_exchange(depth=2n) of the grid-space CA path —
    n quarter rows = 2n grid rows). Slots pair by parity — (R0, B0) share
    row offsets, (R1, B1) the staggered ones — so each (axis, direction,
    parity) is ONE ppermute of a stacked 2-slot strip: 8 ppermutes total."""
    n = g.n
    jl2, il2 = g.jl // 2, g.il // 2

    # rows over mesh axis "j" (array axis 1 of each slot)
    nper = comm.axis_size("j")
    if nper > 1:
        idx = lax.axis_index("j")
        for pr, slots in ((0, (0, 2)), (1, (1, 3))):
            os = _owned_start_row(g, pr)
            pair = jnp.stack([xq[slots[0]], xq[slots[1]]])
            # low ghosts [os-n, os) <- -1 neighbour's owned top strip
            strip = pair[:, os + jl2 - n : os + jl2, :]
            recv = lax.ppermute(strip, "j", _nbr_perm(nper, True, False))
            recv = jnp.where(idx > 0, recv, pair[:, os - n : os, :])
            pair = pair.at[:, os - n : os, :].set(recv)
            # high ghosts [os+jl2, os+jl2+n) <- +1 neighbour's owned bottom
            strip = pair[:, os : os + n, :]
            recv = lax.ppermute(strip, "j", _nbr_perm(nper, False, False))
            recv = jnp.where(
                idx < nper - 1, recv, pair[:, os + jl2 : os + jl2 + n, :]
            )
            pair = pair.at[:, os + jl2 : os + jl2 + n, :].set(recv)
            xq = xq.at[slots[0]].set(pair[0]).at[slots[1]].set(pair[1])

    # cols over mesh axis "i" (array axis 2 of each slot)
    nper = comm.axis_size("i")
    if nper > 1:
        idx = lax.axis_index("i")
        for pc, slots in ((0, (0, 3)), (1, (1, 2))):
            os = _owned_start_col(g, pc)
            pair = jnp.stack([xq[slots[0]], xq[slots[1]]])
            strip = pair[:, :, os + il2 - n : os + il2]
            recv = lax.ppermute(strip, "i", _nbr_perm(nper, True, False))
            recv = jnp.where(idx > 0, recv, pair[:, :, os - n : os])
            pair = pair.at[:, :, os - n : os].set(recv)
            strip = pair[:, :, os : os + n]
            recv = lax.ppermute(strip, "i", _nbr_perm(nper, False, False))
            recv = jnp.where(
                idx < nper - 1, recv, pair[:, :, os + il2 : os + il2 + n]
            )
            pair = pair.at[:, :, os + il2 : os + il2 + n].set(recv)
            xq = xq.at[slots[0]].set(pair[0]).at[slots[1]].set(pair[1])
    return xq


# ----------------------------------------------------------------------
# Masks + the jnp twin of the per-shard kernel
# ----------------------------------------------------------------------


def q_masks(g: QGeom, qoff_j, qoff_i):
    """Per-slot boolean masks on the full (rp, w2p) stored plane, from
    GLOBAL quarter coordinates (qoff_j/qoff_i are the shard's traced
    offsets). Same formulas the Pallas kernel computes from its scalar
    prefetch — keep the two in lockstep (ops/sor_qdist.py).

    Returns dict with per-slot 'upd' (update = global interior ∩ stored
    logical region), 'own' (static owned region, residual accounting),
    and the 8 wall-refresh masks keyed like the kernel's select order."""
    rho = jnp.arange(g.rp, dtype=jnp.int32)[:, None]
    col = jnp.arange(g.w2p, dtype=jnp.int32)[None, :]
    lam = rho - g.h  # logical stored row
    gqr = lam - g.n + qoff_j
    gqc = col - g.n + qoff_i
    valid = (lam >= 0) & (lam < g.jq) & (col >= 0) & (col < g.iq)
    # updates freeze the outermost stored ring (read-only, like the grid CA
    # path's ca_half_sweep [1:-1] slice): its neighbours are dead padding.
    # In grid space the frozen ring IS the outermost grid ghost row/col, so
    # the proven depth-2n CA validity argument carries over unchanged.
    valid_upd = (
        (lam >= 1) & (lam <= g.jq - 2) & (col >= 1) & (col <= g.iq - 2)
    )

    def row_int(pr):
        if pr == 0:
            return (gqr >= 1) & (gqr <= g.jmax // 2)
        return (gqr >= 0) & (gqr <= g.jmax // 2 - 1)

    def col_int(pc):
        if pc == 0:
            return (gqc >= 1) & (gqc <= g.imax // 2)
        return (gqc >= 0) & (gqc <= g.imax // 2 - 1)

    def own_rows(pr):
        os = _owned_start_row(g, pr)
        return (rho >= os) & (rho < os + g.jl // 2)

    def own_cols(pc):
        os = _owned_start_col(g, pc)
        return (col >= os) & (col < os + g.il // 2)

    m = {"upd": [], "own": []}
    for pr, pc in SLOT_PARITY:
        m["upd"].append(row_int(pr) & col_int(pc) & valid_upd)
        m["own"].append(own_rows(pr) & own_cols(pc))
    # wall-refresh masks (tangentially clipped to the global interior)
    m["row_lo_pc0"] = (gqr == 0) & col_int(0) & valid  # gj==0, even i
    m["row_lo_pc1"] = (gqr == 0) & col_int(1) & valid  # gj==0, odd i
    m["row_hi_pc0"] = (gqr == g.jmax // 2) & col_int(0) & valid
    m["row_hi_pc1"] = (gqr == g.jmax // 2) & col_int(1) & valid
    m["col_lo_pr0"] = (gqc == 0) & row_int(0) & valid
    m["col_lo_pr1"] = (gqc == 0) & row_int(1) & valid
    m["col_hi_pr0"] = (gqc == g.imax // 2) & row_int(0) & valid
    m["col_hi_pr1"] = (gqc == g.imax // 2) & row_int(1) & valid
    return m


def _upd(center, rhs_q, w, e, s, n_, mask, factor, idx2, idy2):
    """The kernel's per-cell arithmetic, op-for-op (reference association;
    where-select, not multiply — ghost garbage must not poison via inf·0)."""
    r = rhs_q - ((e - 2.0 * center + w) * idx2 + (n_ - 2.0 * center + s) * idy2)
    rm = jnp.where(mask, r, jnp.zeros_like(r))
    return center - factor * rm, rm


def rb_iters_q_jnp(xq, rhsq, g: QGeom, m, factor, idx2, idy2):
    """n full red-black iterations + Neumann refresh on the stacked stored
    plane — the jnp twin of ops/sor_qdist's Pallas kernel (identical
    neighbour identities, select masks, and update order; rolls wrap dead
    cells that every mask excludes). Returns (xq', owned sum of r² of the
    LAST iteration)."""
    R0, R1, B0, B1 = xq[0], xq[1], xq[2], xq[3]
    F0, F1, G0, G1 = rhsq[0], rhsq[1], rhsq[2], rhsq[3]

    def east(x):
        return jnp.roll(x, -1, axis=1)

    def west(x):
        return jnp.roll(x, 1, axis=1)

    def north(x):
        return jnp.roll(x, -1, axis=0)

    def south(x):
        return jnp.roll(x, 1, axis=0)

    r0 = r1 = r2 = r3 = None
    for _ in range(g.n):
        R0, r0 = _upd(R0, F0, west(B0), B0, south(B1), B1, m["upd"][0],
                      factor, idx2, idy2)
        R1, r1 = _upd(R1, F1, B1, east(B1), B0, north(B0), m["upd"][1],
                      factor, idx2, idy2)
        B0, r2 = _upd(B0, G0, R0, east(R0), south(R1), R1, m["upd"][2],
                      factor, idx2, idy2)
        B1, r3 = _upd(B1, G1, west(R1), R1, R0, north(R0), m["upd"][3],
                      factor, idx2, idy2)
        R0 = jnp.where(m["row_lo_pc0"], B1, R0)
        B0 = jnp.where(m["row_lo_pc1"], R1, B0)
        R1 = jnp.where(m["row_hi_pc1"], B0, R1)
        B1 = jnp.where(m["row_hi_pc0"], R0, B1)
        R0 = jnp.where(m["col_lo_pr0"], B0, R0)
        B1 = jnp.where(m["col_lo_pr1"], R1, B1)
        B0 = jnp.where(m["col_hi_pr0"], R0, B0)
        R1 = jnp.where(m["col_hi_pr1"], B1, R1)

    rsq = jnp.zeros((), xq.dtype)
    for rq, own in zip((r0, r1, r2, r3), m["own"]):
        rsq = rsq + jnp.sum(jnp.where(own, rq * rq, jnp.zeros_like(rq)))
    return jnp.stack([R0, R1, B0, B1]), rsq
