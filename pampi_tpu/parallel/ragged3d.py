"""Ragged (pad-with-mask) NS-3D wall handling — the 3-D twin of
parallel/ragged2d.py.

Global-index masked forms of the 6-face BC application
(ops/ns3d.set_boundary_conditions_3d), the special BCs, and the F/G/H wall
fixups, for ceil-divided ("k","j","i") meshes where the HI walls may sit
anywhere inside (or before) trailing shards. Value arithmetic mirrors
ops/ns3d.py exactly (same face application order, same staggered write
positions, same tangential clips), so a ragged run tracks the
single-device trajectory to reduction order.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.ns3d import FACES, NOSLIP, OUTFLOW, PERIODIC, SLIP
from .comm import CartComm, get_offsets

AXIS_NAMES = ("k", "j", "i")


def global_index_grids(comm: CartComm, kl: int, jl: int, il: int):
    """Broadcastable (gk, gj, gi) of the (kl+2, jl+2, il+2) extended block."""
    koff = get_offsets("k", kl)
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    gk = (jnp.arange(kl + 2, dtype=jnp.int32) + koff)[:, None, None]
    gj = (jnp.arange(jl + 2, dtype=jnp.int32) + joff)[None, :, None]
    gi = (jnp.arange(il + 2, dtype=jnp.int32) + ioff)[None, None, :]
    return gk, gj, gi


def live_masks_3d(comm: CartComm, kl, jl, il, kmax, jmax, imax, dtype):
    """Multiply-mask zeroing DEAD cells beyond the global ghost ring."""
    gk, gj, gi = global_index_grids(comm, kl, jl, il)
    live = (gk <= kmax + 1) & (gj <= jmax + 1) & (gi <= imax + 1)
    return live.astype(dtype)


def set_bcs_3d_ragged(u, v, w, bcs, comm: CartComm, kl, jl, il,
                      kmax, jmax, imax, grids=None):
    """set_boundary_conditions_3d as global-index selects; same face
    iteration order and staggered positions (wall normal at g == gmax on HI
    faces, tangential ghosts at g == gmax+1; both at 0 on LO faces).

    `grids` (the (gk, gj, gi) index grids) is the ragged2d.set_bcs_ragged
    hook: callers OUTSIDE shard_map — the fleet's 3-D shape-class chunk,
    which runs this chain on one full padded block with TRACED
    kmax/jmax/imax — supply precomputed offset-0 vectors instead of the
    shard-offset lookup."""
    g = (global_index_grids(comm, kl, jl, il)
         if grids is None else grids)
    gmaxes = (kmax, jmax, imax)
    fields = {0: w, 1: v, 2: u}

    def tan_clip(axis):
        m = True
        for a in (0, 1, 2):
            if a == axis:
                continue
            m = m & (g[a] >= 1) & (g[a] <= gmaxes[a])
        return m

    for face, kind in bcs.items():
        axis, side = FACES[face]
        if side == "lo":
            wall = ghost = g[axis] == 0
            step = -1  # inner neighbour is one index up -> roll by -1
        else:
            wall = g[axis] == gmaxes[axis]
            ghost = g[axis] == gmaxes[axis] + 1
            step = 1
        clip = tan_clip(axis)
        m_wall = wall & clip
        m_ghost = ghost & clip
        normal = fields[axis]
        t_axes = [a for a in (0, 1, 2) if a != axis]

        def inner(arr):
            return jnp.roll(arr, step, axis=axis)

        if kind == NOSLIP:
            fields[axis] = jnp.where(m_wall, jnp.zeros_like(normal), normal)
            for a in t_axes:
                fields[a] = jnp.where(m_ghost, -inner(fields[a]), fields[a])
        elif kind == SLIP:
            fields[axis] = jnp.where(m_wall, jnp.zeros_like(normal), normal)
            for a in t_axes:
                fields[a] = jnp.where(m_ghost, inner(fields[a]), fields[a])
        elif kind == OUTFLOW:
            fields[axis] = jnp.where(m_wall, inner(normal), normal)
            for a in t_axes:
                fields[a] = jnp.where(m_ghost, inner(fields[a]), fields[a])
        elif kind == PERIODIC:
            pass
    return fields[2], fields[1], fields[0]


def set_special_bc_3d_ragged(u, problem, comm: CartComm, kl, jl, il,
                             kmax, jmax, imax, grids=None):
    """setSpecialBoundaryCondition (solver.c:579-602) masked by global
    index, replicating the reference's dcavity loop-bound quirk (skips the
    last interior i and k). `grids` as in set_bcs_3d_ragged (offset-0
    callers)."""
    gk, gj, gi = (global_index_grids(comm, kl, jl, il)
                  if grids is None else grids)
    if problem == "dcavity":
        m = (
            (gj == jmax + 1)
            & (gk >= 1) & (gk <= kmax - 1)
            & (gi >= 1) & (gi <= imax - 1)
        )
        return jnp.where(m, 2.0 - jnp.roll(u, 1, axis=1), u)
    if problem == "canal":
        m = (
            (gi == 0)
            & (gk >= 1) & (gk <= kmax)
            & (gj >= 1) & (gj <= jmax)
        )
        return jnp.where(m, jnp.full_like(u, 2.0), u)
    return u


def fgh_fixups_ragged(f, g_, h, u, v, w, comm: CartComm, kl, jl, il,
                      kmax, jmax, imax, grids=None):
    """F/G/H wall fixups (solver.c:771-823): same-position copies from
    u/v/w on both walls of each axis, tangentially clipped. `grids` as in
    set_bcs_3d_ragged (offset-0 callers)."""
    gk, gj, gi = (global_index_grids(comm, kl, jl, il)
                  if grids is None else grids)
    tan_ji = (gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax)
    tan_ki = (gk >= 1) & (gk <= kmax) & (gi >= 1) & (gi <= imax)
    tan_kj = (gk >= 1) & (gk <= kmax) & (gj >= 1) & (gj <= jmax)
    f = jnp.where(((gi == 0) | (gi == imax)) & tan_kj, u, f)
    g_ = jnp.where(((gj == 0) | (gj == jmax)) & tan_ki, v, g_)
    h = jnp.where(((gk == 0) | (gk == kmax)) & tan_ji, w, h)
    return f, g_, h
