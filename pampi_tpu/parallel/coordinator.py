"""Coordinated multi-host fault handling: the agree-then-act protocol.

The reference MPI stack has no fault story — one rank dies and the whole
`MPI_Cart` job dies with it — and the PR 4 recovery layer was explicitly
single-controller: multi-process dist runs passed `transient_budget=0`
because a rank-local retry would desynchronize the chunk's collectives
across ranks. This module closes that gap the way the partitioned-MPI
literature structures it (PAPERS.md, "Persistent and Partitioned MPI for
Stencil Communication"): the chunk boundary — where the host already
syncs on the loop time — is the one safe rendezvous, so that is where
ranks agree.

Protocol (one round per chunk boundary):

1. Every rank dispatches the same chunk and builds a small integer FAULT
   WORD from what it observed locally: done flag, transient-fault flag,
   pallas-fallback flag, divergence flag, proposed rollback generation
   (the newest ring-captured `nt`), checkpoint vote.
2. The words are allgathered (`multihost_utils.process_allgather` — a
   host-side collective of WORD_LEN ints, nothing rides the traced
   programs) and merged with fixed per-slot min/max reductions, so every
   rank holds the identical merged word.
3. Every rank takes the SAME decision deterministically from the merged
   word: re-dispatch the same chunk on a transient (the budget is now
   GLOBAL — one rank's hiccup spends everyone's charge, replenish
   semantics unchanged), fall back to the jnp chunk together on a pallas
   failure, roll back to the agreed RingRecovery generation on a
   divergence, commit a checkpoint on a vote, finish when ALL ranks are
   past te. A rank that is locally done keeps joining the allgather
   (dispatching device no-op chunks) until the merged word says done —
   the DONE path never leaves a peer blocked in the collective.

DEAD RANKS (PR 12): the boundary allgather is WATCHDOG-TIMED — a rank
that stops answering (process death, wedged host) no longer hangs its
peers at the rendezvous until the backend's opaque timeout. The watchdog
(`tpu_coord_timeout` seconds; the utils/xlacache probe pattern — the
blocking collect runs on a daemon thread with a hard join timeout) fires
on every survivor at the same boundary; the survivors then run one
MEMBERSHIP AGREEMENT round over the surviving set — each submits an
epoch-tagged word whose dead-rank bitmask is OR-merged — so every
survivor lands on the identical DEAD verdict and the identical
incremented shrink epoch, and raises the same structured `RankDeadError`
naming the lost rank(s). Words are EPOCH-TAGGED (W_EPOCH): a stale
straggler word from before a shrink can never merge into a post-shrink
round (apply() aborts on skew). Recovery is the shrink-to-survivors
resume layer (fleet/scheduler.shrink_resume: re-init on the survivor
set, rebuild the solver on the shrunk mesh, restore the newest agreed
elastic generation + the persisted fault ledger). Remaining window: a
rank that dies INSIDE a chunk's device collectives still waits out the
backend's own collective timeout before its peers reach the boundary —
the watchdog owns the HOST-side rendezvous. The verdict + shrink epoch
+ elastic resume chain is tier-1-proven on the LockstepSim virtual-rank
path (a dead virtual rank simply stops producing words —
`dead@chunk<N>@rank<R>` / `hang@chunk<N>@rank<R>` clauses); the real
kill-a-process acceptance case is capability-gated in
tests/test_multihost.py.

The seam is `models/_driver.drive_chunks(coordinator=...)`: None (the
single-process default) is the exact historical host loop, and the
protocol itself is host-side only — all CONTRACTS.json jaxpr hashes are
unchanged.

Two coordinator transports share the one loop (`CoordinatedLoop`, an
explicit boundary state machine):

- `MultihostCoordinator` — the real cross-process allgather (TPU/GPU
  pods; CPU only with a gloo jaxlib — `multihost.multiprocess_capable`).
- `LockstepSim` — N virtual ranks driven in lockstep inside ONE process
  (each rank a full solver instance built under
  `faultinject.rank_scope`), merging words with the same reduction the
  allgather path uses. This is what makes the agree-then-act logic,
  the global budget accounting and the rollback agreement
  tier-1-testable on this CPU container (tests/test_coordinator.py);
  `tests/test_multihost.py` holds the real multi-process acceptance
  cases that un-gate on capable hardware.

Every global decision is a flight-recorder line: telemetry `coord`
records (schema v5), emitted once per decision from rank 0.

CONSUMERS of the RankDeadError verdict: besides the CLI surfaces
(tools/serve_elastic.py-style operator flows and the test harnesses),
the serving daemon's autopilot (fleet/autopilot.py, PR 19) subscribes
to it as a POLICY INPUT — a verdict raised by a resident elastic job is
turned into an automatic `shrink_resume` onto survivor capacity (fault
ledger carried through the elastic manifest), no operator in the loop.
The protocol's guarantee that every survivor raises the IDENTICAL
structured verdict is what makes that safe to automate.
"""

from __future__ import annotations

import warnings

import numpy as np

from ..utils import faultinject as _fi
from ..utils import telemetry as _tm

# the fault word: one int64 per slot, merged elementwise with _MERGE_OPS.
# W_ROLLBACK_NT proposes the newest ring-captured step count; NO_ROLLBACK
# (merge-neutral under min) means "nothing to roll back to here".
# W_EPOCH tags the word with the sender's shrink epoch (uniform by
# construction — apply() aborts on skew, the stale-straggler guard);
# W_DEADMASK is the membership round's payload: a bitmask of the ranks
# this sender observed dead, OR-merged so the survivors' union IS the
# agreed verdict (ranks 0..62 — the real transport's membership round
# goes through the coordination-service KV store, not the mask).
(W_DONE, W_FAULT, W_FALLBACK, W_DIVERGED, W_ROLLBACK_NT, W_CKPT,
 W_EPOCH, W_DEADMASK) = range(8)
WORD_LEN = 8
NO_ROLLBACK = np.int64(2**62)


def _or_reduce(col):
    return np.bitwise_or.reduce(np.asarray(col, np.int64))


_MERGE_OPS = (np.min, np.max, np.max, np.max, np.min, np.max,
              np.max, _or_reduce)

# the watchdog default: well under the backend collective timeouts
# (XLA's cross-host barriers sit at 10+ minutes) so a dead rank is agreed
# at the HOST rendezvous first, and generous enough that a straggler
# paying a cold compile inside its chunk is never misdeclared dead.
DEFAULT_WATCHDOG_S = 300.0


class CoordinatorAbort(RuntimeError):
    """The agreed decision is to abort: the global transient budget is
    exhausted (or a peer hit a fault this rank cannot act on). Raised on
    EVERY rank at the same boundary, so the job dies cleanly instead of
    one rank dying inside a collective with its peers blocked."""


class RankDeadError(RuntimeError):
    """A rank stopped answering the boundary allgather: the watchdog
    fired and the survivors' membership agreement round produced this —
    the SAME verdict, on every survivor, at the same boundary. Carries
    the agreed dead set (`ranks`; empty when the transport could not
    attribute the timeout to specific ranks), the post-shrink `epoch`,
    the surviving ranks and the boundary index. The structured recovery
    is the shrink-to-survivors resume: restore the newest agreed elastic
    checkpoint generation onto the survivor set
    (fleet/scheduler.shrink_resume; cli.py catches this exception when
    `tpu_dead_resume` is armed)."""

    def __init__(self, ranks=(), epoch=None, boundary=None, family="",
                 survivors=None, reason=""):
        self.ranks = sorted(int(r) for r in ranks)
        self.epoch = epoch
        self.boundary = boundary
        self.family = family
        self.survivors = (None if survivors is None
                          else sorted(int(r) for r in survivors))
        self.reason = reason
        super().__init__()

    def __str__(self) -> str:
        # composed late: drive_coordinated annotates boundary/family
        # after the transport raised
        who = (f"rank(s) {self.ranks}" if self.ranks
               else "unattributed rank(s) (allgather timed out)")
        return (f"{self.family or 'coordinated run'}: DEAD {who} at "
                f"boundary {self.boundary} — survivors agreed shrink "
                f"epoch {self.epoch}"
                + (f"; {self.reason}" if self.reason else "")
                + "; resume on the survivor set from the newest elastic "
                  "checkpoint generation (fleet/scheduler.shrink_resume)")


def dead_mask(ranks) -> int:
    """Encode a dead-rank set as the W_DEADMASK bitmask (ranks 0..62)."""
    m = 0
    for r in ranks:
        if not 0 <= int(r) < 63:
            raise ValueError(f"W_DEADMASK encodes ranks 0..62, got {r}")
        m |= 1 << int(r)
    return m


def mask_ranks(mask: int) -> list:
    """Decode a W_DEADMASK bitmask back to the sorted rank list."""
    return [r for r in range(63) if (int(mask) >> r) & 1]


def blank_word() -> np.ndarray:
    w = np.zeros(WORD_LEN, np.int64)
    w[W_ROLLBACK_NT] = NO_ROLLBACK
    return w


def merge_words(words) -> np.ndarray:
    """The one merge rule both transports share: elementwise fixed
    reductions over the (nranks, WORD_LEN) matrix — min for done (all
    ranks must be past te) and the rollback target (every rank can dig
    to the shallowest common generation), max for the fault/divergence/
    vote flags (any rank's fault is everyone's fault)."""
    mat = np.asarray(words, np.int64).reshape(-1, WORD_LEN)
    return np.asarray(
        [op(mat[:, i]) for i, op in enumerate(_MERGE_OPS)], np.int64
    )


class SoloCoordinator:
    """1-rank coordinator (`tpu_coord on` under a single process): the
    merged word IS the local word. Exists so the production protocol
    path can be exercised — and kept bitwise-identical to the
    uncoordinated loop — without a multi-process launch."""

    nranks = 1
    rank = 0

    def agree(self, word: np.ndarray) -> np.ndarray:
        return merge_words(word)


class MultihostCoordinator:
    """The real transport: allgather the WORD_LEN-int fault word across
    OS processes at each chunk boundary. The allgather is itself a
    collective — which is exactly why every decision below it must be
    taken identically everywhere, and why locally-done ranks keep
    joining it until the merged word says done.

    WATCHDOG (PR 12): the allgather runs on a daemon thread with a hard
    `timeout` join (0 disables — the pre-watchdog hang-until-backend
    behavior). On expiry every surviving rank raises RankDeadError at
    the same boundary; the dead set is attributed best-effort through
    the coordination-service KV store (each survivor posts an
    epoch-tagged liveness key and reads its peers' with the same grace
    window — a rank that never posts is dead). Attribution failing
    (older jax, no KV client) degrades to an EMPTY dead set with the
    timeout named in the reason — structured and loud either way, never
    a wedge. The abandoned allgather thread is a daemon: it dies with
    the process, exactly the xlacache probe contract."""

    def __init__(self, timeout: float = DEFAULT_WATCHDOG_S):
        import jax

        self.nranks = jax.process_count()
        self.rank = jax.process_index()
        self.timeout = timeout
        self._round = 0  # agree rounds so far (keys the membership round)

    def agree(self, word: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        self._round += 1
        if not self.timeout or self.timeout <= 0:
            return merge_words(
                np.asarray(multihost_utils.process_allgather(word)))
        import threading

        box: dict = {}

        def gather():
            try:
                box["mat"] = np.asarray(
                    multihost_utils.process_allgather(word))
            except Exception as exc:  # lint: allow(broad-except) — surfaced on the driving thread below
                box["exc"] = exc

        t = threading.Thread(target=gather, daemon=True,
                             name=f"pampi-coord-agree-{self._round}")
        t.start()
        t.join(self.timeout)
        if t.is_alive():
            dead = self._membership_round()
            survivors = ([r for r in range(self.nranks) if r not in dead]
                         if dead else None)
            epoch = int(word[W_EPOCH]) + 1
            # the flight-recorder `dead` line is emitted by
            # drive_coordinated's handler, where boundary/family are
            # known — one record shape for both transports
            raise RankDeadError(
                ranks=dead or (), epoch=epoch, survivors=survivors,
                reason=(f"boundary allgather exceeded the "
                        f"{self.timeout:g}s watchdog"),
            )
        if "exc" in box:
            raise box["exc"]
        return merge_words(box["mat"])

    def _membership_round(self) -> list:
        """Best-effort dead-set attribution over the jax coordination
        service's KV store: post my liveness key for this round, then
        blocking-read every rank's against ONE shared deadline a
        watchdog window and a half out — a rank that never posts is
        dead. The watchdog is the documented bound on an honest rank's
        lag (`tpu_coord_timeout` must exceed the slowest honest chunk),
        so survivors enter this round at most one window apart; the
        extra half window is the margin for KV round-trips and
        scheduling latency, without which a rank arriving exactly one
        window late would post AT the deadline and be misdeclared. The
        verdict is still BEST-EFFORT — a rank slower than the knob it
        was configured with can be misdeclared, which is the knob's
        documented contract, and the cross-process resume stays
        operator-driven (cli.py prints the walkthrough; nothing
        auto-resumes on a possibly-split verdict). Returns [] when the
        KV client is unreachable on this jax."""
        import time

        try:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is None:
                return []
            prefix = f"pampi_coord/alive/round{self._round}"
            client.key_value_set(f"{prefix}/r{self.rank}", "1")
            # one deadline for the WHOLE read set: N dead ranks must not
            # cost N grace windows (each get consumes remaining budget)
            deadline = time.monotonic() + 1.5 * max(self.timeout, 1.0)
            dead = []
            for r in range(self.nranks):
                left_ms = max(1, int((deadline - time.monotonic()) * 1e3))
                try:
                    client.blocking_key_value_get(
                        f"{prefix}/r{r}", left_ms)
                except Exception:  # lint: allow(broad-except) — a missing key IS the verdict; any get failure reads as dead
                    dead.append(r)
            return dead
        except Exception:  # lint: allow(broad-except) — attribution is best-effort; the structured RankDeadError fires regardless
            return []


class CoordinatedLoop:
    """One rank's chunked drive loop as an explicit boundary machine:
    `local_word()` dispatches the next chunk and reports what happened;
    `apply(merged)` acts on the agreed decision. `drive_coordinated`
    wires the two around a coordinator's `agree`; `LockstepSim` advances
    N of these in lockstep with the same merge.

    Semantics mirror `models/_driver.drive_chunks` with three deliberate
    deviations, all protocol-forced: lookahead pipelining is off (every
    boundary is a rendezvous), the transient budget is GLOBAL (any
    rank's fault spends the shared charge; replenish-after-clean-chunks
    unchanged), and the pallas->jnp fallback / restore runs on EVERY
    rank at the same boundary (a lone rank changing its compiled program
    would desynchronize the collectives the fallback exists to save)."""

    def __init__(self, state, chunk_fn, te, time_index, bar, retry,
                 on_state=None, replenish_after: int = 8, recover=None,
                 transient_budget: int = 1, rank: int = 0,
                 ckpt_every: int = 0, on_ckpt=None, family: str = "",
                 watchdog: float = DEFAULT_WATCHDOG_S, ledger=None):
        self.chunk_fn = chunk_fn
        self.te = te
        self.time_index = time_index
        self.bar = bar
        self.retry = retry
        self.on_state = on_state
        self.replenish_after = replenish_after
        self.recover = recover
        self.rank = int(rank)
        self.ckpt_every = max(0, int(ckpt_every))
        self.on_ckpt = on_ckpt
        self.family = family
        self.watchdog = watchdog
        self.on_final = None  # optional publish-back hook (LockstepSim)
        self.final = None

        self._confirmed = state
        self._pending = None
        self._t_pending = None
        self._budget = max(0, int(transient_budget))
        self._max_budget = self._budget
        # the restored fault ledger (utils/checkpoint elastic manifest):
        # a resumed fleet starts with the SPENT budget and the shrink
        # epoch it died with, rank-symmetrically — every rank read the
        # same manifest
        ledger = ledger or {}
        self.epoch = int(ledger.get("epoch", 0))
        spent = max(0, int(ledger.get("budget_spent", 0)))
        self._budget = max(0, self._budget - spent)
        self._clean = 0
        self._boundary = 0  # agreed boundaries so far (rounds of agree)
        self._confirms = 0  # confirmed (clean) chunks — the ckpt cadence
        self._local_done = float(state[time_index]) > te
        self._local_exc = None
        self._took_fallback = False  # this rank already swapped this round

    # -- step 1: dispatch + observe -----------------------------------
    def local_word(self) -> np.ndarray:
        """Dispatch the next chunk (a device no-op once past te) and
        report the local observation. Never acts — every action waits
        for the merged word."""
        w = blank_word()
        w[W_EPOCH] = self.epoch
        self._local_exc = None
        self._took_fallback = False
        if self.final is not None or self._local_done:
            w[W_DONE] = 1
            return w
        try:
            with _fi.rank_scope(self.rank):
                _fi.maybe_chunk_fault()  # injected fault plane (test-only)
                pending = self.chunk_fn(*self._confirmed)
                # force completion: async runtime faults surface here
                t = float(pending[self.time_index])
        except Exception as exc:  # lint: allow(broad-except) — the fault-classification funnel, same contract as drive_chunks
            if isinstance(exc, _fi.FaultSpecError):
                raise  # a broken TEST spec fails loudly, never classified
            self._pending = None
            self._local_exc = exc
            from ..models._driver import _is_transient_device_fault

            if _is_transient_device_fault(exc):
                w[W_FAULT] = 1
                return w
            new_fn = self.retry()
            if new_fn is None:
                raise  # no alternative program: a genuine error kills
                # the job on this rank; peers abort at the next agree
                # round when the allgather dies with it
            self.chunk_fn = new_fn
            self._took_fallback = True
            w[W_FALLBACK] = 1
            return w
        self._pending = pending
        self._t_pending = t
        diverged = t != t or (
            self.recover is not None and self.recover.poisoned(pending)
        )
        if diverged:
            w[W_DIVERGED] = 1
            if self.recover is not None:
                nt = self.recover.newest_nt()
                if nt >= 0:
                    w[W_ROLLBACK_NT] = nt
        elif t > self.te:
            w[W_DONE] = 1
        if (self.on_ckpt is not None and self.ckpt_every > 0
                and not diverged
                and (self._confirms + 1) % self.ckpt_every == 0):
            w[W_CKPT] = 1
        return w

    # -- step 3: the one decision, taken identically everywhere -------
    def apply(self, merged: np.ndarray) -> None:
        if self.final is not None:
            return
        if int(merged[W_EPOCH]) != self.epoch:
            # a stale word from before a shrink leaked into this round —
            # the merge is undefined across epochs, so die loudly rather
            # than act on a verdict half the fleet never saw
            raise CoordinatorAbort(
                f"{self.family}: epoch skew in the merged fault word "
                f"(merged epoch {int(merged[W_EPOCH])}, this rank's "
                f"epoch {self.epoch}) at boundary {self._boundary}"
            )
        self._boundary += 1
        if merged[W_FALLBACK]:
            self._apply_fallback()
            return
        if merged[W_FAULT]:
            self._apply_transient()
            return
        if merged[W_DIVERGED]:
            self._apply_rollback(merged)
            return
        self._apply_confirm(merged)

    def _reset_streak(self) -> None:
        self._clean = 0
        reset_clean = getattr(self.retry, "reset_clean", None)
        if reset_clean is not None:
            reset_clean()

    def _emit(self, event: str, **fields) -> None:
        """One flight-recorder line per GLOBAL decision (rank 0 only —
        the word is identical everywhere by construction)."""
        if self.rank == 0:
            _tm.emit("coord", event=event, boundary=self._boundary,
                     family=self.family, **fields)

    def _apply_fallback(self) -> None:
        """A pallas runtime failure somewhere: every rank swaps to its
        jnp rebuild so the fleet keeps tracing ONE program. Ranks whose
        dispatch succeeded discard the pending state (it ran the old
        program) and re-dispatch."""
        self._pending = None
        self._reset_streak()
        if not self._took_fallback:
            # a peer fell back; mirror it locally — EVERY rank that has
            # not already swapped must, including one that raised a
            # transient in the same round (guarding on "did I raise
            # anything" would leave that rank on the pallas program and
            # desynchronize the fleet's traced programs). retry() on a
            # healthy rank rebuilds the same jnp chunk (and shares the
            # deterministically-broken probation accounting, which stays
            # rank-symmetric because every transition is agreed).
            new_fn = self.retry()
            if new_fn is None:
                raise CoordinatorAbort(
                    f"{self.family}: a peer rank took the pallas->jnp "
                    "fallback but this rank has no alternative chunk "
                    "program — configs have desynchronized"
                )
            self.chunk_fn = new_fn
        self._emit("fallback")

    def _apply_transient(self) -> None:
        """A transient device fault somewhere: all ranks re-dispatch the
        same chunk (inputs unchanged — the loop is functional) on one
        shared, replenishing budget."""
        self._pending = None
        self._reset_streak()
        if self._budget <= 0:
            self._emit("abort", reason="transient budget exhausted")
            raise CoordinatorAbort(
                f"{self.family}: global transient budget exhausted at "
                f"boundary {self._boundary}"
            ) from self._local_exc
        self._budget -= 1
        warnings.warn(
            f"{self.family}: transient device fault on a rank; all ranks "
            f"retrying the chunk (global budget left {self._budget})",
            stacklevel=2,
        )
        self._emit("retry", budget_left=self._budget,
                   t=float(self._confirmed[self.time_index]))

    def _apply_rollback(self, merged: np.ndarray) -> None:
        """A divergence somewhere: every rank rolls back to the AGREED
        generation (the merged min of the proposed ring entries) and
        re-drives with the same clamped dt — or, when no rank has a
        state to offer (or recovery is exhausted), every rank terminates
        on its diverged state exactly like the single-controller loop."""
        target = int(merged[W_ROLLBACK_NT])
        rolled = None
        if self.recover is not None and target < int(NO_ROLLBACK):
            rolled = self.recover.attempt(target_nt=target)
        if rolled is None:
            self._emit("giveup",
                       target_nt=None if target >= int(NO_ROLLBACK)
                       else target)
            self.final = (self._pending if self._pending is not None
                          else self._confirmed)
            self._finish()
            return
        state_rb, new_fn = rolled
        self._confirmed = state_rb
        self._pending = None
        self.chunk_fn = new_fn
        self._reset_streak()
        self._emit("rollback", target_nt=target,
                   t=float(state_rb[self.time_index]))

    def _apply_confirm(self, merged: np.ndarray) -> None:
        if self._pending is not None:
            self._confirmed = self._pending
            self._pending = None
            self._confirms += 1
            self._clean += 1
            if (self.replenish_after > 0
                    and self._clean >= self.replenish_after
                    and self._budget < self._max_budget):
                self._budget = self._max_budget
            restore = getattr(self.retry, "on_clean_chunk", None)
            if restore is not None:
                # deterministic on every rank: the clean streak advances
                # at agreed boundaries only, so all ranks restore their
                # pallas chunk at the SAME boundary
                restored_fn = restore()
                if restored_fn is not None:
                    self.chunk_fn = restored_fn
            if self.bar is not None:
                self.bar.update(self._t_pending)
            if self.on_state is not None:
                self.on_state(self._confirmed)
            if merged[W_CKPT] and self.on_ckpt is not None:
                self._emit("ckpt", t=self._t_pending)
                if getattr(self.on_ckpt, "takes_ledger", False):
                    # the coordinated writer persists the fault ledger
                    # into the elastic manifest alongside the fields
                    # (models/_driver.coord_ckpt_cadence marks itself)
                    self.on_ckpt(self._confirmed, ledger=self.ledger())
                else:
                    self.on_ckpt(self._confirmed)
            if self._t_pending > self.te:
                self._local_done = True
        if merged[W_DONE]:
            self.final = self._confirmed
            self._finish()

    def _finish(self) -> None:
        if self.bar is not None:
            self.bar.stop()
        if self.on_final is not None:
            self.on_final(self.final)

    def ledger(self) -> dict:
        """The FAULT LEDGER: the protocol state a restarted/shrunk fleet
        must not forget — spent global transient budget, the pallas
        probation verdict (a deterministically-broken kernel stays
        broken across a restart), the divergence-recovery attempts +
        cumulative dt clamp, and the shrink epoch. Persisted into the
        elastic manifest at every agreed checkpoint commit
        (utils/checkpoint.save_elastic) and restored rank-symmetrically
        by load_elastic — every rank reads the same manifest, so the
        restored state can never skew."""
        led = {
            "budget_spent": int(self._max_budget - self._budget),
            "epoch": int(self.epoch),
        }
        pallas_ledger = getattr(self.retry, "ledger", None)
        if pallas_ledger is not None:
            led["pallas"] = pallas_ledger()
        if self.recover is not None:
            led["recover_attempts"] = int(self.recover._attempts)
            led["dt_scale"] = float(
                getattr(self.recover.solver, "_dt_scale", 1.0))
        return led


def drive_coordinated(state, chunk_fn, te, time_index, bar, retry,
                      coordinator, on_state=None, replenish_after: int = 8,
                      recover=None, transient_budget: int = 1,
                      ckpt_every: int = 0, on_ckpt=None, family: str = "",
                      ledger=None):
    """The coordinated drive loop: one CoordinatedLoop per rank, one
    `agree` round per chunk boundary. Entered through
    `models/_driver.drive_chunks(coordinator=...)`. A RankDeadError from
    the transport's watchdog is annotated with this loop's boundary and
    family, the progress bar stopped, and re-raised — the resume layer
    (cli.py / fleet.scheduler.shrink_resume) owns what happens next."""
    loop = CoordinatedLoop(
        state, chunk_fn, te, time_index, bar, retry, on_state=on_state,
        replenish_after=replenish_after, recover=recover,
        transient_budget=transient_budget, rank=coordinator.rank,
        ckpt_every=ckpt_every, on_ckpt=on_ckpt, family=family,
        watchdog=getattr(coordinator, "timeout", DEFAULT_WATCHDOG_S),
        ledger=ledger,
    )
    while loop.final is None:
        try:
            merged = coordinator.agree(loop.local_word())
        except RankDeadError as exc:
            if exc.boundary is None:
                exc.boundary = loop._boundary
            if not exc.family:
                exc.family = family
            # the transport raises bare (it knows neither boundary nor
            # family); the flight-recorder line lands here so both
            # transports' `dead` records carry the same fields
            _tm.emit("dead", ranks=exc.ranks or None, epoch=exc.epoch,
                     boundary=exc.boundary, family=exc.family,
                     nranks=coordinator.nranks,
                     watchdog_s=getattr(coordinator, "timeout", None))
            if exc.survivors is not None:
                _tm.emit("epoch", epoch=exc.epoch,
                         nranks=len(exc.survivors),
                         survivors=exc.survivors)
            if bar is not None:
                bar.stop()
            raise
        loop.apply(merged)
    stash = getattr(on_ckpt, "stash_ledger", None)
    if stash is not None:
        # the agreed-done ledger survives even when the run finished
        # before the first cadence commit: the cli's end-of-run elastic
        # write reads it back via save_elastic's _fault_ledger fallback
        stash(loop.ledger())
    return loop.final


class LockstepSim:
    """N virtual ranks in ONE process: every round, collect each rank's
    local word, merge with the same reduction the allgather transport
    uses, apply everywhere. A rank here is a full solver instance (a
    replica, built under `faultinject.rank_scope(r)` so rank-targeted
    clauses arm only their target) — the collective coupling of a real
    mesh is replaced by the replicas' determinism, which is exactly what
    lets the agree-then-act logic be proven on one CPU.

    DEAD RANKS: each rank's word is collected under the WATCHDOG — the
    dispatch runs on a daemon thread with a hard join timeout (ranks
    stay SEQUENTIAL: the virtual-rank fault counters are process
    globals, and determinism is the whole point). A rank that raises
    InjectedRankDeath (`dead@chunk<N>@rank<R>`) or overruns the window
    (`hang@chunk<N>@rank<R>`) produces no word; the survivors then run
    the membership agreement round — the same epoch-tagged OR-merge the
    word protocol uses — and every survivor raises the identical
    RankDeadError. This is the tier-1 proof of the dead-rank protocol;
    the abandoned hung thread is a daemon and dies with the process."""

    def __init__(self, loops, watchdog: float | None = None):
        self.loops = list(loops)
        # None: take the per-loop watchdog (sim_rank_loop wires it from
        # the .par tpu_coord_timeout key)
        self.watchdog = watchdog

    def _window(self) -> float:
        if self.watchdog is not None:
            return self.watchdog
        return getattr(self.loops[0], "watchdog", DEFAULT_WATCHDOG_S)

    def _collect_word(self, loop):
        """One rank's local_word under the watchdog; None = this rank is
        dead (stopped answering or overran the window). Any other
        exception re-raises on the driving thread — the historical
        propagate-loudly contract."""
        import threading

        box: dict = {}

        def work():
            try:
                box["word"] = loop.local_word()
            except _fi.InjectedRankDeath:
                box["dead"] = True
            except BaseException as exc:  # lint: allow(broad-except) — ferried to the driving thread and re-raised there
                box["exc"] = exc

        t = threading.Thread(target=work, daemon=True,
                             name=f"pampi-sim-rank{loop.rank}")
        t.start()
        window = self._window()
        t.join(window if window and window > 0 else None)
        if t.is_alive() or box.get("dead"):
            return None
        if "exc" in box:
            raise box["exc"]
        return box["word"]

    def _declare_dead(self, dead_ranks, survivors):
        """The membership agreement round: every survivor submits an
        epoch-tagged word carrying its observed dead-rank bitmask; the
        OR-merge is the agreed verdict, the incremented epoch the agreed
        shrink — then every survivor raises the same RankDeadError."""
        if not survivors:
            # total fleet loss (an untargeted dead clause): nothing left
            # to agree with — one structured error instead of a merge of
            # zero words. Hung sleepers still unwind NOW: an abandoned
            # hang thread exiting its rank_scope later would restore the
            # ambient-rank global mid-way through the next test's builds
            _fi.cancel_hangs()
            raise RankDeadError(
                ranks=dead_ranks, epoch=self.loops[0].epoch + 1,
                survivors=[], reason="no survivors")
        words = []
        for loop in survivors:
            w = blank_word()
            w[W_EPOCH] = loop.epoch
            w[W_DEADMASK] = dead_mask(dead_ranks)
            words.append(w)
        merged = merge_words(np.stack(words))
        ranks = mask_ranks(int(merged[W_DEADMASK]))
        epoch = int(merged[W_EPOCH]) + 1
        boundary = survivors[0]._boundary if survivors else None
        _fi.cancel_hangs()  # the verdict is in; hung sleepers may unwind
        for loop in survivors:
            loop.epoch = epoch
            if loop.bar is not None:
                loop.bar.stop()
        _tm.emit("dead", ranks=ranks, epoch=epoch,
                 boundary=boundary, nranks=len(self.loops),
                 watchdog_s=self._window(),
                 family=survivors[0].family if survivors else "")
        _tm.emit("epoch", epoch=epoch, nranks=len(survivors),
                 survivors=[loop.rank for loop in survivors])
        raise RankDeadError(
            ranks=ranks, epoch=epoch, boundary=boundary,
            family=survivors[0].family if survivors else "",
            survivors=[loop.rank for loop in survivors],
        )

    def run(self) -> list:
        """Drive all ranks to agreement-confirmed completion; returns
        the per-rank final states. A CoordinatorAbort (or an unhandled
        fault) on any rank propagates — the job dies, it never hangs;
        a dead/hung rank raises RankDeadError on the survivors within
        one watchdog window per rank."""
        while any(loop.final is None for loop in self.loops):
            words, dead = [], []
            for loop in self.loops:
                w = self._collect_word(loop)
                if w is None:
                    dead.append(loop.rank)
                else:
                    words.append(w)
            if dead:
                self._declare_dead(
                    dead,
                    [lp for lp in self.loops if lp.rank not in dead])
            merged = merge_words(np.stack(words))
            for loop in self.loops:
                loop.apply(merged)
        return [loop.final for loop in self.loops]


def sim_rank_loop(solver, family: str, time_index: int, rank: int,
                  te=None, transient_budget: int = 1,
                  replenish_after: int = 8, ckpt_every: int = 0,
                  on_ckpt=None) -> CoordinatedLoop:
    """Build one virtual rank's CoordinatedLoop over a solver instance,
    mirroring the solver run() wiring (per-rank ChunkRecorder tagged
    through the telemetry scenario dimension, ring recovery from the
    .par keys, publish-back of the final state). The simulation's
    constructor — build the solver itself under
    `faultinject.rank_scope(rank)` first so rank-targeted field faults
    bake only into their target."""
    from ..models._driver import make_recovery

    rec = (_tm.ChunkRecorder(family, solver.nt, scenario=f"rank{rank}")
           if getattr(solver, "_metrics", False) else None)
    recover = make_recovery(solver, family, time_index, recorder=rec)
    state = solver.initial_state()
    if recover is not None:
        recover.capture(state)  # first-chunk divergence is recoverable

    n_fields = time_index
    names = ("u", "v", "p") if n_fields == 3 else ("u", "v", "w", "p")

    def publish(s):
        for name, value in zip(names, s[:n_fields]):
            setattr(solver, name, value)
        solver.t = float(s[time_index])
        solver.nt = int(s[time_index + 1])

    def on_state(s):
        if rec is not None:
            rec.update(float(s[time_index]), int(s[time_index + 1]),
                       s[time_index + 2])
        if recover is not None:
            recover.capture(s)

    chunk_fn = getattr(solver, "_chunk_sm", None) or solver._chunk_fn
    loop = CoordinatedLoop(
        state, chunk_fn, solver.param.te if te is None else te,
        time_index, bar=None, retry=lambda: None, on_state=on_state,
        replenish_after=replenish_after, recover=recover,
        transient_budget=transient_budget, rank=rank,
        ckpt_every=ckpt_every, on_ckpt=on_ckpt, family=family,
        watchdog=getattr(solver.param, "tpu_coord_timeout",
                         DEFAULT_WATCHDOG_S),
        ledger=getattr(solver, "_fault_ledger", None),
    )
    loop.on_final = publish
    return loop


def coord_armed(param) -> bool:
    """Side-effect-free predicate of `make_coordinator`'s answer — the
    cli asks it before wiring the single-controller periodic checkpoint
    writer (the coordinated loop owns the cadence itself, through the
    agreed checkpoint vote)."""
    import jax

    knob = getattr(param, "tpu_coord", "auto")
    if knob == "off":
        return False
    return jax.process_count() > 1 or knob == "on"


def make_coordinator(param, family: str):
    """The `tpu_coord` knob -> a coordinator or None (utils/dispatch
    records the decision like every other knob): `auto` arms the
    multihost transport under a multi-process launch and nothing
    otherwise — so a single-process run's drive loop is the exact
    historical path; `on` forces the protocol through the 1-rank
    SoloCoordinator (the seam-identity proof shape); `off` restores the
    PR 4 guard (multi-process runs get transient_budget=0 and a fault
    kills the job cleanly)."""
    from ..utils import dispatch as _dispatch

    mode = _dispatch.resolve_coord(param, f"coord_{family}")
    if mode == "none":
        return None
    coord = (MultihostCoordinator(
                 timeout=getattr(param, "tpu_coord_timeout",
                                 DEFAULT_WATCHDOG_S))
             if mode == "multihost" else SoloCoordinator())
    _tm.emit("coord", event="armed", family=family, mode=mode,
             nranks=coord.nranks, rank=coord.rank)
    return coord
