from .comm import (
    CartComm,
    dims_create,
    halo_exchange,
    halo_shift,
    reduction,
    is_boundary,
    axis_coord,
    get_offsets,
)
