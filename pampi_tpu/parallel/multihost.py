"""Multi-process (multi-host) runtime — the `commInit`/`commFinalize` pair
for real distributed launches.

Reference parity: `commInit` is MPI_Init + rank/size discovery and
`commFinalize` is MPI_Finalize (assignment-6/src/comm.c:464-523); processes
are launched by `mpirun -n N` / SLURM (SURVEY.md §5 "Distributed
communication backend"). TPU-native, the launcher contract is environment
variables consumed by `jax.distributed.initialize`:

  PAMPI_COORDINATOR   host:port of process 0 (≙ the mpirun wireup)
  PAMPI_NPROCS        total number of processes
  PAMPI_PROC_ID       this process's id (≙ MPI rank)

`scripts/launch-multihost.sh` sets the triple for local oversubscribed runs
(the reference's "mpirun -n locally" way of exercising multi-node without a
cluster, SURVEY.md §4). On a real TPU pod each host runs one process and the
cloud runtime already knows the topology: set `PAMPI_MULTIHOST=auto` instead
of the triple and this calls argless `jax.distributed.initialize()`
(auto-detection from the TPU/SLURM environment). After init, `jax.devices()`
is the GLOBAL device list and the existing `CartComm` meshes span it —
nothing else in the framework changes.

Single-process runs (no triple in the environment) are a no-op, exactly like
the reference's ENABLE_MPI=false build of the same API (comm.c:470-488).
"""

from __future__ import annotations

import contextlib
import os
import sys

_initialized = False


def init_from_env() -> tuple[int, int]:
    """commInit. Returns (process_id, num_processes); (0, 1) when the
    environment requests no distributed runtime. Must run before the first
    use of jax devices."""
    global _initialized
    import jax

    from ..utils import flags as _flags

    coord = _flags.env("PAMPI_COORDINATOR",
                       doc="host:port of the jax.distributed coordinator")
    auto = _flags.env("PAMPI_MULTIHOST",
                      doc="'auto' = pod/SLURM topology from the "
                          "environment") == "auto"
    if _initialized or not (coord or auto):
        return jax.process_index(), jax.process_count()
    if coord:
        nprocs = int(_flags.env("PAMPI_NPROCS",
                                doc="process count (with PAMPI_COORDINATOR)"))
        proc_id = int(_flags.env("PAMPI_PROC_ID",
                                 doc="this process's rank (with "
                                     "PAMPI_COORDINATOR)"))
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=nprocs, process_id=proc_id
        )
    else:
        # pod/SLURM launch: the environment carries the topology
        jax.distributed.initialize()
    _initialized = True
    return jax.process_index(), jax.process_count()


def multiprocess_capable() -> tuple[bool, str]:
    """Can THIS jax build run cross-process collectives on the current
    backend? Backend DETECTION, not a blanket environment guess: TPU/GPU
    runtimes always can; the CPU backend can only when its collectives
    implementation (gloo) is compiled into the jaxlib — absent it, every
    cross-process ppermute dies with "Multiprocess computations aren't
    implemented on the CPU backend". Returns (capable, reason-if-not).
    tests/test_multihost.py gates on this (ROADMAP item 4 names that
    suite the acceptance gate on real hardware, so it must SKIP with
    this reason on incapable containers, not fail)."""
    import jax

    backend = jax.default_backend()
    if backend != "cpu":
        return True, ""
    try:
        from jax._src.lib import xla_client

        collectives = getattr(xla_client._xla, "collectives", None)
    except (ImportError, AttributeError):
        collectives = None
    if collectives is not None and hasattr(
            collectives, "make_gloo_tcp_collectives"):
        return True, ""
    return False, (
        "cpu backend without a cross-process collectives implementation "
        "(this jaxlib ships no gloo: xla_client._xla.collectives is "
        "unavailable) — multi-process launches would fail with "
        "'Multiprocess computations aren't implemented on the CPU "
        "backend'"
    )


def is_master() -> bool:
    """commIsMaster (comm.h:138) at process granularity."""
    import jax

    return jax.process_index() == 0


def shutdown() -> None:
    """commFinalize. Safe to call unconditionally; no-op when single-process."""
    global _initialized
    if _initialized:
        import jax

        jax.distributed.shutdown()
        _initialized = False


@contextlib.contextmanager
def session():
    """The commInit/commFinalize bracket as a context manager: join the
    process group (env-triggered no-op otherwise), mute non-master stdout,
    and shut down on exit — restoring stdout so output after the bracket
    (embedding/test use) isn't silently lost. Both CLI branches run inside
    one."""
    init_from_env()
    saved_stdout = sys.stdout
    devnull = mute_non_master()
    try:
        yield
    finally:
        if devnull is not None:
            sys.stdout = saved_stdout
            devnull.close()
        shutdown()


def mute_non_master():
    """Rank-0-only printing, the reference driver convention
    (assignment-5/ex5-nazifkar/src/main.c: every print gated on rank 0).
    Redirects this process's stdout to /dev/null when not master; stderr
    stays live so errors from any rank surface. Returns the devnull handle
    (None when master) so the caller can restore and close it."""
    if not is_master():
        sys.stdout = open(os.devnull, "w")
        return sys.stdout
    return None
