"""Shared 2-D distributed-stencil helpers used by every ("j","i")-mesh solver.

These encode the invariants the distributed solvers must keep in lockstep:
- wall-gated homogeneous-Neumann ghost copies (≙ the reference's pressure BC
  loops, assignment-4/src/solver.c:157-165, gated like commIsBoundary),
- GLOBAL (i+j)-parity checkerboard masks, so red-black colouring is
  decomposition-invariant (assignment-4 solveRB cell sets, solver.c:197-234),
- and the communication-avoiding red-black machinery (ca_*): the distributed
  twin of the Pallas temporal-block kernel (ops/sor_pallas._tblock_kernel).
  One depth-2n halo exchange buys n EXACT red-black iterations computed
  locally: each iteration consumes 2 layers of halo validity (red reads ±1,
  black reads red-updated values ±1), and halo cells are recomputed
  redundantly by both neighbouring shards — same data, same arithmetic,
  identical values — so the distributed trajectory stays equal to the
  sequential red-black solver (mod reduction order). The reference pays one
  MPI_Neighbor_alltoallw per HALF-sweep
  (assignment-5/ex5-nazifkar/src/solver.c:609); this pays one ppermute round
  per n full iterations.

Bitwise-parity discipline: every update is structured EXACTLY like
ops/sor.sor_pass (interior-sliced laplacian, float mask multiply, at[].add)
so XLA compiles the same per-element arithmetic as the single-device solver
— the distributed fields equal the single-device fields bitwise, not just
ulp-close (tests/test_ns2d_dist.py asserts array_equal).
"""

from __future__ import annotations

import jax.numpy as jnp

from .comm import CartComm, get_offsets, halo_exchange, is_boundary


def wall_flags(comm: CartComm):
    """(lo_i, hi_i, lo_j, hi_j) boundary predicates for the current shard."""
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    return (
        is_boundary("i", Pi, "lo"),
        is_boundary("i", Pi, "hi"),
        is_boundary("j", Pj, "lo"),
        is_boundary("j", Pj, "hi"),
    )


# ----------------------------------------------------------------------
# Communication-avoiding red-black SOR (see module docstring).
# ----------------------------------------------------------------------


def ca_masks(jl: int, il: int, halo: int, jmax: int, imax: int, dtype,
             joff=None, ioff=None):
    """Mask set on the (jl+2·halo, il+2·halo) extended block, from GLOBAL
    coordinates (local cell (a, b) ↔ global extended index
    (joff + a - halo + 1, ioff + b - halo + 1); owned interior starts at
    local index `halo`). Returns a dict: red/black update masks (global
    interior ∩ parity), wall-ghost refresh masks per side (tangentially
    clipped to the global interior like the sequential Neumann BC), and the
    owned-cell mask for non-redundant residual accounting.

    joff/ioff default to the calling shard's mesh offsets (get_offsets —
    requires a shard_map context); explicit values build the mask set for
    a CHOSEN shard geometry outside any mesh, which is how the halo
    analyzer (analysis/halocheck.py) probes the CA footprint per shard
    position without spinning up a device mesh.

    halo=1 degenerates to the classic 1-ghost-layer extended block (owned ==
    interior), used by the extent-1 fallback path below."""
    H = halo
    joff = get_offsets("j", jl) if joff is None else joff
    ioff = get_offsets("i", il) if ioff is None else ioff
    gj = jnp.arange(jl + 2 * H, dtype=jnp.int32)[:, None] - (H - 1) + joff
    gi = jnp.arange(il + 2 * H, dtype=jnp.int32)[None, :] - (H - 1) + ioff
    interior = (gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax)
    par = (gi + gj) % 2
    lj = jnp.arange(jl + 2 * H, dtype=jnp.int32)[:, None]
    li = jnp.arange(il + 2 * H, dtype=jnp.int32)[None, :]
    owned = (lj >= H) & (lj < H + jl) & (li >= H) & (li < H + il)
    tan_j = (gj >= 1) & (gj <= jmax)
    tan_i = (gi >= 1) & (gi <= imax)
    # red/black are FLOAT multiply-masks (not boolean selects) so the update
    # expression is op-for-op the one in ops/sor.sor_pass — XLA then emits
    # identical per-element code and the distributed trajectory stays
    # BITWISE equal to the single-device solver, not just ulp-close
    return {
        "red": (interior & (par == 0)).astype(dtype),
        "black": (interior & (par == 1)).astype(dtype),
        "owned": owned,
        "wall_jlo": (gj == 0) & tan_i,
        "wall_jhi": (gj == jmax + 1) & tan_i,
        "wall_ilo": (gi == 0) & tan_j,
        "wall_ihi": (gi == imax + 1) & tan_j,
    }


def ca_half_sweep(p, rhs, mask_interior, factor, idx2, idy2):
    """One masked half-sweep on the extended block — the exact arithmetic of
    ops/sor.sor_pass (bitwise-parity discipline). `mask_interior` is the
    [1:-1, 1:-1] slice of a ca_masks red/black mask. Returns (p, r)."""
    x = p
    lap = (x[1:-1, 2:] - 2.0 * x[1:-1, 1:-1] + x[1:-1, :-2]) * idx2 + (
        x[2:, 1:-1] - 2.0 * x[1:-1, 1:-1] + x[:-2, 1:-1]
    ) * idy2
    r = (rhs[1:-1, 1:-1] - lap) * mask_interior
    return p.at[1:-1, 1:-1].add(-factor * r), r


def neumann_masked(p, masks):
    """Homogeneous-Neumann wall-ghost refresh via the ca_masks wall masks
    (global-coordinate gated, tangentially clipped, corners untouched) —
    shared by the CA iteration and the solvers' ghost reconstruction."""
    p = jnp.where(masks["wall_jlo"], jnp.roll(p, -1, axis=0), p)
    p = jnp.where(masks["wall_jhi"], jnp.roll(p, 1, axis=0), p)
    p = jnp.where(masks["wall_ilo"], jnp.roll(p, -1, axis=1), p)
    p = jnp.where(masks["wall_ihi"], jnp.roll(p, 1, axis=1), p)
    return p


def _owned_r2(r_red, r_blk, masks):
    """Residual sum of r² over OWNED cells only (halo cells are recomputed
    redundantly by neighbours; summing owned avoids double counting)."""
    return jnp.sum(
        jnp.where(
            masks["owned"][1:-1, 1:-1], r_red * r_red + r_blk * r_blk, 0.0
        )
    )


def ca_rb_iters(p, rhs, n: int, masks, factor, idx2, idy2):
    """n full red-black iterations (+ Neumann wall refresh each, matching the
    sequential loop) on the deep-halo extended block; returns the updated
    block and the owned-cells residual sum of r² of the LAST iteration (the
    value a per-iteration loop would observe at that count). Requires a
    depth-ca_halo(n) exchange before the call."""
    red = masks["red"][1:-1, 1:-1]
    black = masks["black"][1:-1, 1:-1]
    r_red = r_blk = None
    for _ in range(n):
        p, r_red = ca_half_sweep(p, rhs, red, factor, idx2, idy2)
        p, r_blk = ca_half_sweep(p, rhs, black, factor, idx2, idy2)
        p = neumann_masked(p, masks)
    return p, _owned_r2(r_red, r_blk, masks)


def rb_exchange_per_sweep(p, rhs, masks, comm: CartComm, factor, idx2, idy2,
                          ragged: bool = False):
    """Extent-1-safe fallback: one red-black iteration with the classic
    exchange-per-half-sweep choreography on the halo=1 layout (a depth-2
    strip structurally needs neighbour-of-neighbour data a single ppermute
    cannot provide when a shard extent is 1). Same arithmetic pieces as
    ca_rb_iters — bitwise parity holds on this path too. Ragged layouts
    refresh the halos once more before the wall copy: the wall-ghost row
    can open a dead shard whose Neumann source is a neighbour's row (see
    ca_halo)."""
    red = masks["red"][1:-1, 1:-1]
    black = masks["black"][1:-1, 1:-1]
    p = halo_exchange(p, comm)
    p, r_red = ca_half_sweep(p, rhs, red, factor, idx2, idy2)
    p = halo_exchange(p, comm)
    p, r_blk = ca_half_sweep(p, rhs, black, factor, idx2, idy2)
    if ragged:
        p = halo_exchange(p, comm)
    p = neumann_masked(p, masks)
    return p, _owned_r2(r_red, r_blk, masks)


def rb_split_iter(p, rhs, masks, sched, int_mask, factor, idx2, idy2,
                  ragged: bool = False):
    """One red-black iteration with each half-sweep SPLIT
    interior/boundary — the solve-sweep twin of the overlapped PRE split
    (ROADMAP item 3): per colour, the depth-1 exchange is posted and its
    results consumed ONLY by the boundary-region update, while the
    interior-region update (whose 5-point stencil never reaches the
    ghost ring) runs on the unexchanged block. The traced program
    carries no dependency path from the ppermutes to the interior
    update, so XLA's scheduler can fly each colour's exchange behind
    the interior compute — per iteration the exchange serialization the
    WaterLily.jl MPI paper (PAPERS.md) measured as the MG strong-scaling
    limit disappears from the critical path.

    `sched` is the persistent depth-1 `ExchangeSchedule`; `int_mask` the
    rim-2 interior mask (`overlap.interior_mask(local, 2, partitioned)`
    — cells whose stencil cannot read the exchanged ring). Values are
    BITWISE the serial per-half-sweep form (`rb_exchange_per_sweep`,
    itself bitwise the CA form): interior cells compute identical
    values from either block, boundary cells read the exchanged buffer.
    Ragged layouts split the extra pre-Neumann refresh the same way
    (interior wall-ghost rows sit >= 2 cells from the block edge or in
    the boundary region — either way their Neumann source is fresh)."""
    red = masks["red"][1:-1, 1:-1]
    black = masks["black"][1:-1, 1:-1]
    inner = int_mask[1:-1, 1:-1]

    def half(p, colour):
        g = sched(p)
        pi, ri = ca_half_sweep(p, rhs, colour, factor, idx2, idy2)
        pb, rb = ca_half_sweep(g, rhs, colour, factor, idx2, idy2)
        return jnp.where(int_mask, pi, pb), jnp.where(inner, ri, rb)

    p, r_red = half(p, red)
    p, r_blk = half(p, black)
    if ragged:
        g = sched(p)
        p = jnp.where(int_mask, neumann_masked(p, masks),
                      neumann_masked(g, masks))
    else:
        p = neumann_masked(p, masks)
    return p, _owned_r2(r_red, r_blk, masks)


def ca_halo(n: int, ragged: bool = False) -> int:
    """Halo depth consumed by n fused red-black iterations. Ragged
    decompositions need ONE extra layer: the wall-ghost row gj == jmax+1
    can start a fully-dead shard, so its Neumann refresh (after 2n
    half-sweeps) reads the INNERMOST halo cell — that cell must carry a
    validity budget of 2n half-sweeps, i.e. sit at halo depth 2n+1. In
    divisible layouts the refresh only ever reads owned cells (the wall
    shard's own interior edge) and 2n suffices."""
    return 2 * n + (1 if ragged else 0)


def ca_supported(*local_extents) -> bool:
    """Deep-halo exchange needs every shard to OWN at least the depth-2
    strips it ships (extent >= 2); below that the solvers use
    rb_exchange_per_sweep."""
    return min(local_extents) >= 2


def ca_clamp(n: int, *local_extents) -> int:
    """Clamp a requested CA block size so the 2n-deep halo strips still come
    from the shard's OWNED cells (2n <= min local extent) — the single home
    of the clamp policy."""
    cap = min(local_extents) // 2
    return max(1, min(n, cap))


def ca_inner(param, *local_extents) -> int:
    """Effective communication-avoiding block size: the .par knob
    `tpu_ca_inner` through ca_clamp."""
    return ca_clamp(param.tpu_ca_inner, *local_extents)


def ceil_overhang(nper: int, local: int, gmax: int) -> int:
    """Trailing dead cells of a ceil-divided axis (0 when divisible) — the
    single home of the overhang formula (used by deep_pad_widths and by
    the obstacle shard/deep-mask pads)."""
    return max(0, nper * local - gmax)


def deep_pad_widths(halo: int, local: int, nper: int, gmax: int):
    """Per-axis pad widths for slicing a GLOBAL (gmax+2)-extent constant
    into (local + 2*halo)-extent deep shard blocks at the plain mesh
    offsets: lo side halo-1 as always; the HI side additionally absorbs the
    ragged ceil-division overhang (nper*local - gmax > 0), without which
    the trailing shard's dynamic_slice would CLAMP its start index and
    silently read shifted values into what must be dead-zero cells."""
    over = ceil_overhang(nper, local, gmax)
    return (halo - 1, halo - 1 + over)


def embed_deep(x, halo: int):
    """Grow a 1-ghost-layer extended block into the deep-halo layout (any
    rank): along each axis of owned extent L, the old ghost layers land at
    local indices H-1 and H+L (wall ghosts keep their BC-owned values); the
    new outer layers are zero until the first deep exchange fills them."""
    return jnp.pad(x, [(halo - 1, halo - 1)] * x.ndim)


def strip_deep(x, halo: int):
    """Inverse of embed_deep: back to the 1-ghost-layer extended block."""
    sl = tuple(slice(halo - 1, d - (halo - 1)) for d in x.shape)
    return x[sl]
