"""Shared 2-D distributed-stencil helpers used by every ("j","i")-mesh solver.

These encode the two invariants the distributed solvers must keep in lockstep:
- wall-gated homogeneous-Neumann ghost copies (≙ the reference's pressure BC
  loops, assignment-4/src/solver.c:157-165, gated like commIsBoundary), and
- GLOBAL (i+j)-parity checkerboard masks, so red-black colouring is
  decomposition-invariant (assignment-4 solveRB cell sets, solver.c:197-234).
"""

from __future__ import annotations

import jax.numpy as jnp

from .comm import CartComm, get_offsets, is_boundary


def wall_flags(comm: CartComm):
    """(lo_i, hi_i, lo_j, hi_j) boundary predicates for the current shard."""
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    return (
        is_boundary("i", Pi, "lo"),
        is_boundary("i", Pi, "hi"),
        is_boundary("j", Pj, "lo"),
        is_boundary("j", Pj, "hi"),
    )


def neumann_walls(p, comm: CartComm):
    """Homogeneous-Neumann ghost copy on physical walls only; corners
    untouched (the reference's loops run 1..imax / 1..jmax)."""
    lo_i, hi_i, lo_j, hi_j = wall_flags(comm)
    p = p.at[0, 1:-1].set(jnp.where(lo_j, p[1, 1:-1], p[0, 1:-1]))
    p = p.at[-1, 1:-1].set(jnp.where(hi_j, p[-2, 1:-1], p[-1, 1:-1]))
    p = p.at[1:-1, 0].set(jnp.where(lo_i, p[1:-1, 1], p[1:-1, 0]))
    p = p.at[1:-1, -1].set(jnp.where(hi_i, p[1:-1, -2], p[1:-1, -1]))
    return p


def global_checkerboard_masks(jl: int, il: int, dtype):
    """(red, black) interior masks on the (jl, il) local block using GLOBAL
    1-based (i + j) parity via the shard's mesh offsets."""
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    jj = jnp.arange(1, jl + 1, dtype=jnp.int32)[:, None] + joff
    ii = jnp.arange(1, il + 1, dtype=jnp.int32)[None, :] + ioff
    par = (ii + jj) % 2
    return (par == 0).astype(dtype), (par == 1).astype(dtype)
