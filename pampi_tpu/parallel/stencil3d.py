"""Shared 3-D distributed-stencil helpers for ("k","j","i")-mesh solvers
(3-D twins of stencil2d; ≙ assignment-6's commIsBoundary-gated face loops).
The communication-avoiding pieces (ca_*) follow the design note in
stencil2d: one depth-2n halo exchange per n exact red-black iterations,
with the bitwise-parity arithmetic discipline (interior-sliced laplacian,
float mask multiply, at[].add — op-for-op models/ns3d.sor_pass_3d)."""

from __future__ import annotations

import jax.numpy as jnp

from .comm import CartComm, get_offsets, halo_exchange, is_boundary


def face_flags(comm: CartComm):
    """dict face-name -> boundary predicate for the current shard, matching
    the reference's Direction enum faces (comm.h:98)."""
    Pk = comm.axis_size("k")
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    return {
        "front": is_boundary("k", Pk, "lo"),
        "back": is_boundary("k", Pk, "hi"),
        "bottom": is_boundary("j", Pj, "lo"),
        "top": is_boundary("j", Pj, "hi"),
        "left": is_boundary("i", Pi, "lo"),
        "right": is_boundary("i", Pi, "hi"),
    }


def ca_masks_3d(kl: int, jl: int, il: int, halo: int,
                kmax: int, jmax: int, imax: int, dtype,
                koff=None, joff=None, ioff=None):
    """Mask set on the (kl+2H, jl+2H, il+2H) extended block from GLOBAL
    coordinates (owned interior starts at local index H). odd/even follow the
    reference's pass order (pass 0 = (i+j+k) parity 1, solver.c:203-231).
    Explicit koff/joff/ioff build a chosen shard geometry outside any mesh
    (the stencil2d.ca_masks contract — used by analysis/halocheck.py);
    None reads the calling shard's offsets. halo=1 degenerates to the
    classic 1-ghost-layer layout for the extent-1 fallback."""
    H = halo
    koff = get_offsets("k", kl) if koff is None else koff
    joff = get_offsets("j", jl) if joff is None else joff
    ioff = get_offsets("i", il) if ioff is None else ioff
    gk = jnp.arange(kl + 2 * H, dtype=jnp.int32)[:, None, None] - (H - 1) + koff
    gj = jnp.arange(jl + 2 * H, dtype=jnp.int32)[None, :, None] - (H - 1) + joff
    gi = jnp.arange(il + 2 * H, dtype=jnp.int32)[None, None, :] - (H - 1) + ioff
    interior = (
        (gk >= 1) & (gk <= kmax)
        & (gj >= 1) & (gj <= jmax)
        & (gi >= 1) & (gi <= imax)
    )
    par = (gi + gj + gk) % 2
    lk = jnp.arange(kl + 2 * H, dtype=jnp.int32)[:, None, None]
    lj = jnp.arange(jl + 2 * H, dtype=jnp.int32)[None, :, None]
    li = jnp.arange(il + 2 * H, dtype=jnp.int32)[None, None, :]
    owned = (
        (lk >= H) & (lk < H + kl)
        & (lj >= H) & (lj < H + jl)
        & (li >= H) & (li < H + il)
    )
    tan_ji = (gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax)
    tan_ki = (gk >= 1) & (gk <= kmax) & (gi >= 1) & (gi <= imax)
    tan_kj = (gk >= 1) & (gk <= kmax) & (gj >= 1) & (gj <= jmax)
    # odd/even are FLOAT multiply-masks: the update is then op-for-op the
    # single-device sor_pass_3d expression → bitwise trajectory parity
    return {
        "odd": (interior & (par == 1)).astype(dtype),
        "even": (interior & (par == 0)).astype(dtype),
        "owned": owned,
        "wall_klo": (gk == 0) & tan_ji,
        "wall_khi": (gk == kmax + 1) & tan_ji,
        "wall_jlo": (gj == 0) & tan_ki,
        "wall_jhi": (gj == jmax + 1) & tan_ki,
        "wall_ilo": (gi == 0) & tan_kj,
        "wall_ihi": (gi == imax + 1) & tan_kj,
    }


def ca_half_sweep_3d(p, rhs, mask_interior, factor, idx2, idy2, idz2):
    """One masked half-sweep — the exact arithmetic of models/ns3d.sor_pass_3d
    (bitwise-parity discipline). Returns (p, r)."""
    x = p
    lap = (
        (x[1:-1, 1:-1, 2:] - 2.0 * x[1:-1, 1:-1, 1:-1] + x[1:-1, 1:-1, :-2])
        * idx2
        + (x[1:-1, 2:, 1:-1] - 2.0 * x[1:-1, 1:-1, 1:-1] + x[1:-1, :-2, 1:-1])
        * idy2
        + (x[2:, 1:-1, 1:-1] - 2.0 * x[1:-1, 1:-1, 1:-1] + x[:-2, 1:-1, 1:-1])
        * idz2
    )
    r = (rhs[1:-1, 1:-1, 1:-1] - lap) * mask_interior
    return p.at[1:-1, 1:-1, 1:-1].add(-factor * r), r


def neumann_masked_3d(p, masks):
    """6-face Neumann wall-ghost refresh via the ca_masks_3d wall masks."""
    p = jnp.where(masks["wall_klo"], jnp.roll(p, -1, axis=0), p)
    p = jnp.where(masks["wall_khi"], jnp.roll(p, 1, axis=0), p)
    p = jnp.where(masks["wall_jlo"], jnp.roll(p, -1, axis=1), p)
    p = jnp.where(masks["wall_jhi"], jnp.roll(p, 1, axis=1), p)
    p = jnp.where(masks["wall_ilo"], jnp.roll(p, -1, axis=2), p)
    p = jnp.where(masks["wall_ihi"], jnp.roll(p, 1, axis=2), p)
    return p


def _owned_r2_3d(r_odd, r_evn, masks):
    return jnp.sum(
        jnp.where(
            masks["owned"][1:-1, 1:-1, 1:-1],
            r_odd * r_odd + r_evn * r_evn,
            0.0,
        )
    )


def ca_rb_iters_3d(p, rhs, n: int, masks, factor, idx2, idy2, idz2):
    """n full red-black iterations (odd pass, even pass, 6-face Neumann
    refresh — the sequential loop order) on the deep-halo extended block;
    returns the block and the owned-cells r² sum of the LAST iteration.
    Requires a depth-ca_halo(n) exchange before the call."""
    odd = masks["odd"][1:-1, 1:-1, 1:-1]
    even = masks["even"][1:-1, 1:-1, 1:-1]
    r_odd = r_evn = None
    for _ in range(n):
        p, r_odd = ca_half_sweep_3d(p, rhs, odd, factor, idx2, idy2, idz2)
        p, r_evn = ca_half_sweep_3d(p, rhs, even, factor, idx2, idy2, idz2)
        p = neumann_masked_3d(p, masks)
    return p, _owned_r2_3d(r_odd, r_evn, masks)


def rb_split_iter_3d(p, rhs, masks, sched, int_mask, factor, idx2, idy2,
                     idz2, ragged: bool = False):
    """3-D twin of stencil2d.rb_split_iter: one odd/even iteration with
    each half-sweep split interior/boundary, the per-colour depth-1
    exchange consumed only by the boundary-region update (bitwise the
    serial per-half-sweep form)."""
    odd = masks["odd"][1:-1, 1:-1, 1:-1]
    even = masks["even"][1:-1, 1:-1, 1:-1]
    inner = int_mask[1:-1, 1:-1, 1:-1]

    def half(p, colour):
        g = sched(p)
        pi, ri = ca_half_sweep_3d(p, rhs, colour, factor, idx2, idy2, idz2)
        pb, rb = ca_half_sweep_3d(g, rhs, colour, factor, idx2, idy2, idz2)
        return jnp.where(int_mask, pi, pb), jnp.where(inner, ri, rb)

    p, r_odd = half(p, odd)
    p, r_evn = half(p, even)
    if ragged:
        g = sched(p)
        p = jnp.where(int_mask, neumann_masked_3d(p, masks),
                      neumann_masked_3d(g, masks))
    else:
        p = neumann_masked_3d(p, masks)
    return p, _owned_r2_3d(r_odd, r_evn, masks)


def rb_exchange_per_sweep_3d(p, rhs, masks, comm: CartComm,
                             factor, idx2, idy2, idz2, ragged: bool = False):
    """Extent-1-safe fallback on the halo=1 layout (see
    stencil2d.rb_exchange_per_sweep; ragged refreshes halos once more
    before the wall copy — the wall ghost plane can open a dead shard)."""
    odd = masks["odd"][1:-1, 1:-1, 1:-1]
    even = masks["even"][1:-1, 1:-1, 1:-1]
    p = halo_exchange(p, comm)
    p, r_odd = ca_half_sweep_3d(p, rhs, odd, factor, idx2, idy2, idz2)
    p = halo_exchange(p, comm)
    p, r_evn = ca_half_sweep_3d(p, rhs, even, factor, idx2, idy2, idz2)
    if ragged:
        p = halo_exchange(p, comm)
    p = neumann_masked_3d(p, masks)
    return p, _owned_r2_3d(r_odd, r_evn, masks)
