"""Shared 3-D distributed-stencil helpers for ("k","j","i")-mesh solvers
(3-D twins of stencil2d; ≙ assignment-6's commIsBoundary-gated face loops)."""

from __future__ import annotations

import jax.numpy as jnp

from .comm import CartComm, get_offsets, is_boundary


def face_flags(comm: CartComm):
    """dict face-name -> boundary predicate for the current shard, matching
    the reference's Direction enum faces (comm.h:98)."""
    Pk = comm.axis_size("k")
    Pj = comm.axis_size("j")
    Pi = comm.axis_size("i")
    return {
        "front": is_boundary("k", Pk, "lo"),
        "back": is_boundary("k", Pk, "hi"),
        "bottom": is_boundary("j", Pj, "lo"),
        "top": is_boundary("j", Pj, "hi"),
        "left": is_boundary("i", Pi, "lo"),
        "right": is_boundary("i", Pi, "hi"),
    }


def neumann_faces(p, comm: CartComm):
    """6-face pressure ghost copy, wall shards only (solver.c:233-279)."""
    f = face_flags(comm)
    p = p.at[0, 1:-1, 1:-1].set(
        jnp.where(f["front"], p[1, 1:-1, 1:-1], p[0, 1:-1, 1:-1])
    )
    p = p.at[-1, 1:-1, 1:-1].set(
        jnp.where(f["back"], p[-2, 1:-1, 1:-1], p[-1, 1:-1, 1:-1])
    )
    p = p.at[1:-1, 0, 1:-1].set(
        jnp.where(f["bottom"], p[1:-1, 1, 1:-1], p[1:-1, 0, 1:-1])
    )
    p = p.at[1:-1, -1, 1:-1].set(
        jnp.where(f["top"], p[1:-1, -2, 1:-1], p[1:-1, -1, 1:-1])
    )
    p = p.at[1:-1, 1:-1, 0].set(
        jnp.where(f["left"], p[1:-1, 1:-1, 1], p[1:-1, 1:-1, 0])
    )
    p = p.at[1:-1, 1:-1, -1].set(
        jnp.where(f["right"], p[1:-1, 1:-1, -2], p[1:-1, 1:-1, -1])
    )
    return p


def global_checkerboard_masks_3d(kl: int, jl: int, il: int, dtype):
    """(odd, even) interior masks by GLOBAL 1-based (i+j+k) parity — pass 0
    of the reference's sweep is parity 1 (solver.c:203-231)."""
    koff = get_offsets("k", kl)
    joff = get_offsets("j", jl)
    ioff = get_offsets("i", il)
    kk = jnp.arange(1, kl + 1, dtype=jnp.int32)[:, None, None] + koff
    jj = jnp.arange(1, jl + 1, dtype=jnp.int32)[None, :, None] + joff
    ii = jnp.arange(1, il + 1, dtype=jnp.int32)[None, None, :] + ioff
    par = (ii + jj + kk) % 2
    return (par == 1).astype(dtype), (par == 0).astype(dtype)
