"""The distributed communication layer, TPU-native.

Capability parity with the reference's only real abstraction boundary — the
ten-function Comm API of /root/reference/assignment-6/src/comm.h:104-138
(commInit/commPartition/commFinalize/commPrintConfig/commExchange/commShift/
commReduction/commIsBoundary/commCollectResult/commIsMaster + commGetOffsets)
— re-designed for a TPU device mesh instead of translated from MPI:

  MPI concept (reference)                   TPU-native equivalent (here)
  ----------------------------------------  ---------------------------------
  MPI_Init / MPI_Comm_size  (commInit)      jax.devices() / jax.distributed
  MPI_Dims_create+Cart_create(commPartition) dims_create() + jax.sharding.Mesh
  MPI_Cart_shift neighbours                 lax.ppermute permutation lists
  MPI_Neighbor_alltoallw halo (commExchange) halo_exchange(): per-axis ppermute
                                            of edge strips inside shard_map
  one-directional staggered shift(commShift) halo_shift(): single-direction
                                            ppermute (F/G/H donor edges)
  MPI_Allreduce MAX|SUM     (commReduction) lax.pmax / lax.psum over mesh axes
  cart coords boundary test (commIsBoundary) lax.axis_index() == 0 / dim-1
  subarray gather to rank 0 (commCollectResult) the sharded global array IS the
                                            result — jax.device_get triggers
                                            XLA's gather; no assembly code
  prefix-sum of local sizes (commGetOffsets) axis_index * block (uniform blocks)
  MPI_PROC_NULL edges                       jnp.where(has_neighbour, recv, old)

Design notes (TPU-first, not a translation):
- Decomposition is UNIFORM: XLA sharding wants equal blocks, so instead of the
  reference's remainder-spread `sizeOfRank` (comm.c:19-22) we require
  divisibility (pad-with-mask is the policy for ragged cases). This is a
  documented deviation, not an omission.
- Halo exchange is axis-by-axis with FULL edge strips (ghost corners included),
  which makes corners consistent after the second axis — equivalent to the
  reference's ordered per-direction sends.
- Exchanges live INSIDE jit/shard_map: XLA schedules the ppermutes
  asynchronously and overlaps them with compute — the hand-rolled goal of
  assignment-3b's Isend/Irecv overlap, for free.
- Fields inside the kernel are "extended" local blocks (+1 ghost layer per
  side). Physical-boundary ghosts are never written by the exchange (the
  MPI_PROC_NULL convention), so BC code owns them exactly as in the reference.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# dimension order matches the reference's enum {KDIM, JDIM, IDIM} (comm.h:101):
# slowest-varying first; arrays are [k, j, i] / [j, i].
AXIS_NAMES = ("k", "j", "i")

# mesh interconnect tiers, in POSTING order: DCN (inter-slice, the slow
# fabric of a multi-slice pod) strips are posted first/deepest so they
# have the whole interior compute to hide behind; ICI (intra-slice)
# strips last/shallowest. "Persistent and Partitioned MPI for Stencil
# Communication" (PAPERS.md) is the per-strip partitioned-send pattern
# this ordering realizes on the ExchangeSchedule seam.
TIERS = ("dcn", "ici")


def parse_mesh_tiers(spec: str, axis_names) -> dict:
    """`tpu_mesh_tiers` -> {axis name: tier}. "auto" (the default) maps
    every axis to the single "ici" tier — today's single-slice meshes,
    bitwise-unchanged exchange order. A comma list "k=dcn,j=ici,i=ici"
    declares the hierarchy explicitly; unlisted axes default to "ici",
    unknown axes/tiers refuse loudly (a typo'd tier map must not
    silently serve the flat schedule)."""
    tiers = {name: "ici" for name in axis_names}
    spec = (spec or "auto").strip()
    if spec == "auto":
        return tiers
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"tpu_mesh_tiers entry {part!r} is not axis=tier "
                f"(axes {tuple(axis_names)}, tiers {TIERS})")
        axis, tier = (t.strip() for t in part.split("=", 1))
        if axis not in tiers:
            raise ValueError(
                f"tpu_mesh_tiers names unknown mesh axis {axis!r} "
                f"(this mesh has {tuple(axis_names)})")
        if tier not in TIERS:
            raise ValueError(
                f"tpu_mesh_tiers tier {tier!r} for axis {axis!r} not in "
                f"{TIERS}")
        tiers[axis] = tier
    return tiers


def master_print(comm: "CartComm", fmt: str, *args) -> None:
    """`jax.debug.print` from the (0,...,0) mesh shard only — the rank-0
    printing convention of the reference drivers, usable INSIDE shard_map
    (plain is_master can't be: it's a host-side property). Values printed
    after a `reduction` are identical on every shard, so one line loses
    nothing."""
    idx = jnp.int32(0)
    for ax in comm.axis_names:
        idx = idx + lax.axis_index(ax)
    lax.cond(
        idx == 0,
        lambda: jax.debug.print(fmt, *args),
        lambda: None,
    )


def dims_create(nranks: int, ndims: int,
                extents: tuple[int, ...] | None = None) -> tuple[int, ...]:
    """Balanced factorization of nranks over ndims — MPI_Dims_create
    semantics (used by commPartition, and by
    assignment-5/ex5-nazifkar/src/solver.c:445).

    Without `extents`: non-increasing balanced factors (the MPI default).
    With `extents` (the grid's interior extents in mesh-axis order): GRID-
    AWARE — among all ordered factorizations, prefer (1) every axis evenly
    divisible, then (2) least pad-with-mask overhead, then (3) smallest
    local-block perimeter (halo volume), then (4) most balanced. MPI gets
    this for free because its ranks tolerate remainders (sizeOfRank,
    assignment-6/src/comm.c:19-22); uniform XLA shardings do not, so the
    factorization must look at the grid: e.g. the reference's canal.par
    (200x50) on 8 devices needs (2,4), not the blind (4,2)."""
    if extents is not None and len(extents) != ndims:
        raise ValueError(
            f"extents {extents} rank does not match ndims={ndims}"
        )

    def factorizations(n, k):
        if k == 1:
            yield (n,)
            return
        for f in range(1, n + 1):
            if n % f == 0:
                for rest in factorizations(n // f, k - 1):
                    yield (f,) + rest

    if extents is None:
        primes = []
        n = nranks
        f = 2
        while f * f <= n:
            while n % f == 0:
                primes.append(f)
                n //= f
            f += 1
        if n > 1:
            primes.append(n)
        dims = [1] * ndims
        for prime in sorted(primes, reverse=True):
            # multiply the currently-smallest dimension (latest index on
            # ties so dims stays non-increasing)
            k = min(range(ndims), key=lambda d: (dims[d], -d))
            dims[k] *= prime
        return tuple(sorted(dims, reverse=True))

    import math as _math

    def score(dims):
        locals_ = [-(-e // p) for e, p in zip(extents, dims)]
        nondiv = sum(1 for e, p in zip(extents, dims) if e % p)
        pad = sum((l * p - e) / e for e, p, l in zip(extents, dims, locals_))
        # halo traffic: cut-plane area summed over the partitioned axes
        padded = [l * p for l, p in zip(locals_, dims)]
        vol = _math.prod(padded)
        comm_vol = sum(
            (p - 1) * vol // ep for p, ep in zip(dims, padded) if p > 1
        )
        spread = max(dims) - min(dims)
        # final tie-break keeps the MPI-style non-increasing order
        return (nondiv, round(pad, 9), comm_vol, spread,
                tuple(-d for d in dims))

    return min(factorizations(nranks, ndims), key=score)


def compat_shard_map(fn, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across toolchains — the ONE place the version shim
    lives (CartComm.shard_map, models/dmvm.py and tests/test_sor_pallas.py
    all route through it). Older jax only ships
    `jax.experimental.shard_map`, whose check_rep predates the while-loop
    replication rule every chunked solver needs, so validation is forced
    off on that branch; the check_vma contract is still enforced wherever
    `jax.shard_map` exists (the TPU image and the CI mesh tests there)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


@dataclass
class CartComm:
    """Cartesian device-mesh communicator (≙ the Comm struct, comm.h:104-115).

    ndims-dimensional mesh over the given devices; axis names are the last
    `ndims` of ("k", "j", "i") so a 2-D field [j, i] shards over ("j", "i").
    """

    ndims: int = 2
    dims: tuple[int, ...] | None = None
    devices: list | None = None
    extents: tuple[int, ...] | None = None  # grid interior extents, mesh
    #   order — makes auto dims GRID-AWARE (prefers feasible factorizations)
    tiers: str | dict | None = None  # axis->interconnect-tier map
    #   (tpu_mesh_tiers spec string or a ready dict); None/"auto" = one
    #   tier — exchange order and every cached schedule bitwise-unchanged
    mesh: Mesh = field(init=False)
    axis_names: tuple[str, ...] = field(init=False)

    def __post_init__(self):
        devs = self.devices if self.devices is not None else jax.devices()
        n = len(devs)
        if self.dims is None:
            self.dims = dims_create(n, self.ndims, self.extents)
        if len(self.dims) != self.ndims:
            raise ValueError(
                f"tpu_mesh has {len(self.dims)} dims {self.dims} but this "
                f"problem needs a {self.ndims}-D mesh"
            )
        if any(d < 1 for d in self.dims):
            raise ValueError(f"mesh dims must be positive, got {self.dims}")
        if math.prod(self.dims) > n:
            raise ValueError(
                f"mesh dims {self.dims} need {math.prod(self.dims)} devices "
                f"but only {n} are available"
            )
        # like `mpirun -n k` on a larger node: an explicit smaller mesh uses
        # the first prod(dims) devices
        devs = list(devs)[: math.prod(self.dims)]
        self.axis_names = AXIS_NAMES[3 - self.ndims :]
        self.mesh = Mesh(np.asarray(devs).reshape(self.dims), self.axis_names)
        if not isinstance(self.tiers, dict):
            self.tiers = parse_mesh_tiers(self.tiers, self.axis_names)
        else:
            # a ready dict still goes through validation (the cli passes
            # the spec string; tests may hand a dict)
            self.tiers = parse_mesh_tiers(
                ",".join(f"{a}={t}" for a, t in self.tiers.items()),
                self.axis_names)

    def tier_of(self, axis: str) -> str:
        return self.tiers[axis]

    @property
    def multi_tier(self) -> bool:
        return len(set(self.tiers.values())) > 1

    # --- commIsMaster (comm.h:138) -------------------------------------
    @property
    def is_master(self) -> bool:
        return jax.process_index() == 0

    @property
    def size(self) -> int:
        return math.prod(self.dims)

    def axis_size(self, axis: str) -> int:
        return self.dims[self.axis_names.index(axis)]

    # --- commPartition helpers -----------------------------------------
    def spec(self) -> P:
        """PartitionSpec sharding array dim d over mesh axis d."""
        return P(*self.axis_names)

    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec())

    def shard(self, arr):
        """Place a global (interior-only) array sharded over the mesh."""
        return jax.device_put(arr, self.sharding())

    def local_shape(self, global_shape, ragged: bool = False) -> tuple[int, ...]:
        """Uniform per-shard block extents. ragged=False enforces the
        divisibility policy; ragged=True returns ceil-divided blocks — the
        pad-with-mask decomposition (trailing shards carry dead cells that
        the global-coordinate masks exclude from updates, residuals, walls
        and collection; ≙ the reference's remainder-spread sizeOfRank,
        assignment-6/src/comm.c:19-22, realized the uniform-sharding way)."""
        if ragged:
            return tuple(-(-e // p) for e, p in zip(global_shape, self.dims))
        for ext, p in zip(global_shape, self.dims):
            if ext % p:
                raise ValueError(
                    f"extent {ext} not divisible by mesh dim {p} "
                    f"(uniform-block policy; ragged pad-with-mask runs pass "
                    f"ragged=True, or change tpu_mesh)"
                )
        return tuple(e // p for e, p in zip(global_shape, self.dims))

    def shard_map(self, fn, in_specs, out_specs, check_vma: bool = True):
        """Wrap `jax.shard_map` over this comm's mesh.

        check_vma=False is required ONLY when the traced body dispatches a
        pallas_call (its out_shape declares no varying-mesh-axes info — the
        standard composition form, validated bitwise on real TPU hardware).
        The relaxation is necessarily step-wide (JAX scopes the check per
        shard_map, not per region), which disables varying-mesh-axes
        validation for EVERY collective in that body — so callers must NOT
        widen its use beyond the pallas-dispatch case: every solver keeps a
        jnp twin of the same step that runs with check_vma=True on the CPU
        test meshes (test_ns2d_dist/test_ns3d_dist/test_poisson_dist), which
        is what catches out_spec/ppermute mistakes the relaxed production
        trace would hide."""
        return compat_shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

    # --- commPrintConfig (comm.c:429-462) ------------------------------
    def print_config(self, out=None) -> None:
        import sys

        out = out or sys.stdout
        out.write("Communication setup:\n")
        out.write(f"\tMesh dims: {self.dims} axes {self.axis_names}\n")
        for d in self.mesh.devices.flat:
            out.write(f"\tDevice {d.id}: {d.platform} {getattr(d, 'coords', '')}\n")

    # --- commCollectResult (comm.c:246-427) ----------------------------
    @staticmethod
    def collect(arr) -> np.ndarray:
        """Gather a sharded global array to the host. The reference needs 80
        lines of subarray datatypes + Isend/Irecv (assembleResult); here the
        sharded array is already globally addressable. Under a multi-process
        launch shards live on other hosts, so the fetch is a cross-process
        allgather (every process gets the full array — the reference gathers
        to rank 0 only, but its non-root ranks simply discard theirs)."""
        # branch on process_count, NOT per-array addressability: with a
        # sub-mesh one process could own every shard and skip a collective
        # the others enter — all processes must take the same path
        if jax.process_count() == 1:
            return np.asarray(jax.device_get(arr))
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


# ----------------------------------------------------------------------
# In-kernel collectives: call these INSIDE a shard_map-wrapped function.
# ----------------------------------------------------------------------


def axis_coord(axis_name: str):
    """Cartesian coordinate along a mesh axis (≙ Comm.coords, comm.h:113)."""
    return lax.axis_index(axis_name)


def is_boundary(axis_name: str, nper: int, side: str):
    """commIsBoundary (comm.c:169-182): True on shards owning the physical
    wall. side is "lo" (LEFT/BOTTOM/FRONT) or "hi" (RIGHT/TOP/BACK)."""
    idx = lax.axis_index(axis_name)
    return idx == 0 if side == "lo" else idx == nper - 1


def get_offsets(axis_name: str, local_extent: int):
    """commGetOffsets (comm.c:491-513): global start index of this shard's
    block — uniform blocks, so a multiply instead of a prefix sum."""
    return lax.axis_index(axis_name) * local_extent


def strip_key(shape, dtype) -> str:
    """Canonical name of one exchange message: '4x16:float64'. The ONE
    naming convention across the observability plane — the commcheck
    collective census keys ppermute messages with it
    (analysis/commcheck.py), the `jax.named_scope` device-time scopes
    below embed it, and `utils/xprof.py` aggregates trace events back by
    the same token — so a lint census entry, a profiler scope and a
    telemetry record all name the same strip."""
    return "x".join(str(int(s)) for s in shape) + f":{jnp.dtype(dtype).name}"


def _scope(kind: str, axis_name: str, shape, dtype):
    """Device-time attribution scope of one exchange axis:
    `halo_exchange.j.4x18:float64`. jax.named_scope leaves the jaxpr
    byte-identical (only eqn name stacks / lowered-HLO metadata change),
    so the flag-off trace-identity contract (CONTRACTS.json hashes) is
    untouched — test-pinned in tests/test_xprof.py."""
    return jax.named_scope(f"{kind}.{axis_name}.{strip_key(shape, dtype)}")


def _nbr_perm(nper: int, up: bool, periodic: bool):
    if periodic:
        return [(r, (r + 1) % nper) for r in range(nper)] if up else [
            ((r + 1) % nper, r) for r in range(nper)
        ]
    return [(r, r + 1) for r in range(nper - 1)] if up else [
        (r + 1, r) for r in range(nper - 1)
    ]


def _exchange_axis(x, axis_name: str, nper: int, dim: int, periodic: bool,
                   depth: int = 1, perms=None):
    """Fill both `depth`-wide ghost strips of `x` along array dim `dim` from
    the ±1 neighbours on mesh axis `axis_name`. Physical-wall ghosts keep
    their previous contents (MPI_PROC_NULL semantics). `perms` is an
    optional precomputed (up, down) permutation-list pair — the
    persistent-schedule path (ExchangeSchedule) resolves them once per
    (mesh, depth, dtype); the default recomputes the identical lists, so
    both paths trace the same program."""
    if nper == 1 and not periodic:
        return x
    n = x.shape[dim]
    d = depth
    up, down = perms if perms is not None else (
        _nbr_perm(nper, True, periodic), _nbr_perm(nper, False, periodic))
    strip = tuple(d if a == dim else x.shape[a] for a in range(x.ndim))
    with _scope("halo_exchange", axis_name, strip, x.dtype):
        # my high/low OWNED strips (d innermost owned layers on each side)
        hi_edge = lax.slice_in_dim(x, n - 2 * d, n - d, axis=dim)
        lo_edge = lax.slice_in_dim(x, d, 2 * d, axis=dim)
        # strip travelling "up" (to +1 neighbour) fills their LOW ghost,
        # and v.v.
        from_lo = lax.ppermute(hi_edge, axis_name, up)
        from_hi = lax.ppermute(lo_edge, axis_name, down)
        if not periodic:
            idx = lax.axis_index(axis_name)
            old_lo = lax.slice_in_dim(x, 0, d, axis=dim)
            old_hi = lax.slice_in_dim(x, n - d, n, axis=dim)
            from_lo = jnp.where(idx > 0, from_lo, old_lo)
            from_hi = jnp.where(idx < nper - 1, from_hi, old_hi)
        x = lax.dynamic_update_slice_in_dim(x, from_lo, 0, axis=dim)
        x = lax.dynamic_update_slice_in_dim(x, from_hi, n - d, axis=dim)
    return x


def halo_exchange(x, comm: CartComm, periodic=(), depth: int = 1):
    """commExchange (comm.c:184-195): refresh ALL ghost layers of the extended
    local block `x` (`depth` ghost layers per side, array dims ordered like
    the mesh axes). Axis-by-axis with full strips ⇒ ghost corners are
    consistent after the last axis. depth > 1 is the communication-avoiding
    deep-halo exchange: one fat ppermute message replaces `depth` thin ones —
    the right trade on latency-bound ICI hops (see parallel/stencil2d.py
    `ca_rb_iters` for the local temporal blocking that consumes it)."""
    for dim, axis_name in enumerate(comm.axis_names):
        x = _exchange_axis(
            x, axis_name, comm.axis_size(axis_name), dim,
            axis_name in periodic, depth,
        )
    return x


def capture_axis_strips(x_ext, comm: CartComm, axis: str, depth: int,
                        inner: int, periodic: bool = False):
    """The capture half of the per-tier depth schedule (ISSUE 17,
    `tpu_exchange_depth axis=H`): ONE depth-`depth` exchange on the slow
    mesh `axis` over the deep-embedded block, cropped to the two
    paste-ready `inner`-deep ghost strips of the step's own deep layout.
    A fused-chunk depth block calls this once, then `paste_axis_strips`
    re-applies the strips for `depth` scan steps — one slow-fabric
    exchange amortized over H steps (the partitioned-communication
    trade: bounded staleness <= H-1 steps on the slow rim, fresh
    exchanges everywhere else). Requires depth >= inner; `x_ext` is the
    1-ghost-layer extended block."""
    if depth < inner:
        raise ValueError(f"capture depth {depth} < inner depth {inner}")
    dim = comm.axis_names.index(axis)
    xw = jnp.pad(x_ext, [(depth - 1, depth - 1)] * x_ext.ndim)
    xw = _exchange_axis(
        xw, axis, comm.axis_size(axis), dim, periodic, depth)
    # the inner-deep block's window starts at depth-inner along every
    # axis; its two `axis` ghost strips are the innermost `inner` layers
    # of the fat captured halo
    lo_start = [depth - inner] * x_ext.ndim
    hi_start = [depth - inner] * x_ext.ndim
    hi_start[dim] = depth + (x_ext.shape[dim] - 2)
    sizes = [x_ext.shape[a] + 2 * (inner - 1) for a in range(x_ext.ndim)]
    sizes[dim] = inner
    lo = lax.dynamic_slice(xw, lo_start, sizes)
    hi = lax.dynamic_slice(xw, hi_start, sizes)
    return lo, hi


def paste_axis_strips(xd, comm: CartComm, axis: str, inner: int, lo, hi,
                      periodic=()):
    """The per-step paste half: fill `axis`'s two `inner`-deep ghost
    strips of the deep-embedded block `xd` from the block-start captured
    strips (no collective — the amortized slow-tier exchange already
    ran in `capture_axis_strips`), then run the fresh per-step exchange
    on every OTHER mesh axis. Wall shards keep their own ghost contents
    (the MPI_PROC_NULL gate `_exchange_axis` applies), so the paste is
    an identity there and wall-BC history stays current. Axis-by-axis
    order puts the pasted axis first: ghost corners take the fresh
    axes' strips, exactly like `halo_exchange`'s last-axis rule."""
    dim = comm.axis_names.index(axis)
    nper = comm.axis_size(axis)
    n = xd.shape[dim]
    if nper > 1:
        idx = lax.axis_index(axis)
        old_lo = lax.slice_in_dim(xd, 0, inner, axis=dim)
        old_hi = lax.slice_in_dim(xd, n - inner, n, axis=dim)
        lo = jnp.where(idx > 0, lo, old_lo)
        hi = jnp.where(idx < nper - 1, hi, old_hi)
        xd = lax.dynamic_update_slice_in_dim(xd, lo, 0, axis=dim)
        xd = lax.dynamic_update_slice_in_dim(xd, hi, n - inner, axis=dim)
    for d2, name in enumerate(comm.axis_names):
        if name == axis:
            continue
        xd = _exchange_axis(
            xd, name, comm.axis_size(name), d2, name in periodic, inner)
    return xd


class ExchangeSchedule:
    """Persistent halo-exchange schedule — the partitioned-MPI seam
    (ROADMAP item 2; "Persistent and Partitioned MPI for Stencil
    Communication", PAPERS.md): everything static about one exchange
    class — the per-axis neighbour permutation lists, the travelling-strip
    depth, the dtype contract — is resolved ONCE per (mesh, halo-depth,
    dtype, periodic set) and reused by every exchange of that class,
    instead of being re-derived at every `halo_exchange` trace site.
    `__call__` traces the IDENTICAL program to
    `halo_exchange(x, comm, periodic, depth)` (same slices, same
    ppermutes with the same permutation lists, same named scopes), so a
    solver can swap between the two forms without moving a byte of the
    collective contract (commcheck census, CONTRACTS.json).

    Hierarchical meshes (ROADMAP item 3): the plan is TIER-ORDERED by the
    comm's axis->tier map (`tpu_mesh_tiers`) — DCN-tier axes exchange
    first (posted deepest/earliest, the partitioned-send discipline:
    inter-slice strips have the most latency to hide and the whole
    interior compute to hide behind), ICI-tier axes last. Reordering
    full-strip axis exchanges is VALUE-safe: every strip spans the full
    extended extent of the other axes, so a ghost corner receives the
    diagonal neighbour's owned value by either route — the same copied
    bytes, just posted in a latency-aware order. With the single-tier
    default the plan keeps the historical axis order and traces
    bitwise-identically (test-pinned)."""

    def __init__(self, comm: CartComm, depth: int = 1, dtype=None,
                 periodic=()):
        self.comm = comm
        self.depth = int(depth)
        self.dtype = None if dtype is None else jnp.dtype(dtype)
        self.periodic = tuple(periodic)
        # the static plan: one entry per mesh axis, permutation lists
        # resolved now (MPI_Send_init semantics — the "build once" half
        # of persistent requests), tier-ordered (DCN first, stable
        # within a tier — the single-tier default is the identity order)
        self.plan = []
        order = sorted(
            range(comm.ndims),
            key=lambda d: (TIERS.index(comm.tier_of(comm.axis_names[d])),
                           d))
        for dim in order:
            name = comm.axis_names[dim]
            nper = comm.axis_size(name)
            per = name in self.periodic
            self.plan.append((dim, name, nper, per, (
                _nbr_perm(nper, True, per), _nbr_perm(nper, False, per))))

    def __call__(self, x):
        if self.dtype is not None and x.dtype != self.dtype:
            raise TypeError(
                f"ExchangeSchedule built for {self.dtype} applied to "
                f"{x.dtype} — schedules are cached per (mesh, depth, "
                "dtype); take the right one from persistent_exchange()"
            )
        for dim, name, nper, per, perms in self.plan:
            x = _exchange_axis(x, name, nper, dim, per, self.depth, perms)
        return x

    def strip_shapes(self, owned_extents) -> list[tuple[int, ...]]:
        """The per-axis message shapes of this schedule over a block with
        the given owned extents (see halo_strip_shapes)."""
        return halo_strip_shapes(owned_extents, self.depth)


_SCHEDULE_CACHE: dict = {}


def _mesh_key(comm: CartComm) -> tuple:
    """Hashable identity of a comm's mesh (axis names + dims + device
    ids + the axis->tier map) — stable across jax versions that may or
    may not hash Mesh. The tier map is part of the identity: a re-tiered
    mesh orders its exchange plan differently, so neither a cached
    schedule nor a cached `.exchange`-span probe may be served across a
    tier change (the stale-probe bug class)."""
    return (tuple(comm.axis_names), tuple(comm.dims),
            tuple(d.id for d in comm.mesh.devices.flat),
            tuple(sorted(comm.tiers.items())))


def persistent_exchange(comm: CartComm, depth: int = 1, dtype=None,
                        periodic=()) -> ExchangeSchedule:
    """The cached `ExchangeSchedule` for (mesh incl. tier map,
    halo-depth, dtype, periodic) — built once per process, returned by
    identity afterwards (test-pinned). Callers that exchange the same
    class of block many times (the overlapped solvers, the exchange
    probe) hold one schedule instead of re-deriving the plan per trace
    site."""
    key = (_mesh_key(comm), int(depth),
           None if dtype is None else jnp.dtype(dtype).name,
           tuple(sorted(periodic)))
    sched = _SCHEDULE_CACHE.get(key)
    if sched is None:
        sched = ExchangeSchedule(comm, depth, dtype, periodic)
        _SCHEDULE_CACHE[key] = sched
    return sched


def halo_strip_shapes(extents, depth: int = 1) -> list[tuple[int, ...]]:
    """Per-axis ppermute message shapes of ONE full `halo_exchange` over an
    extended block with the given OWNED extents: along each exchanged axis
    the two travelling strips are `depth` ghost layers wide and span the
    full EXTENDED extent of every other axis (ghost corners included —
    that is what makes the axis-by-axis exchange corner-consistent). This
    is the one statement of the exchange's message geometry: the byte
    accounting below, the PR 3 telemetry records, and the commcheck trace
    census (analysis/commcheck.py) all derive from it, so the accountings
    cannot diverge."""
    ext = [e + 2 * depth for e in extents]
    return [
        tuple(depth if a == ax else ext[a] for a in range(len(ext)))
        for ax in range(len(extents))
    ]


def halo_exchange_bytes(extents, depth: int, itemsize: int) -> int:
    """Static per-shard bytes one full `halo_exchange` moves: two strips
    (one per direction) of every `halo_strip_shapes` message. THE shared
    byte accounting — solver-__init__ telemetry `halo` records
    (models/ns*_dist.py) and the commcheck contract pass both call this
    helper rather than re-deriving."""
    total = 0
    for shape in halo_strip_shapes(extents, depth):
        n = 1
        for s in shape:
            n *= s
        total += 2 * n
    return total * itemsize


def halo_tier_bytes(comm: CartComm, extents, depth: int,
                    itemsize: int) -> dict:
    """Per-TIER bytes of one full `halo_exchange` over a block with the
    given OWNED extents: each axis's two travelling strips charged to
    that axis's interconnect tier (`tpu_mesh_tiers`). Axes of size 1
    move nothing and charge nothing — this is the traffic accounting,
    not the static geometry. The single-tier default puts everything
    under "ici", so the per-tier sum equals the moved subset of
    `halo_exchange_bytes` by construction."""
    out: dict[str, int] = {t: 0 for t in sorted(set(comm.tiers.values()))}
    for ax, shape in enumerate(halo_strip_shapes(extents, depth)):
        name = comm.axis_names[ax]
        if comm.axis_size(name) == 1:
            continue
        n = 1
        for s in shape:
            n *= s
        out[comm.tiers[name]] += 2 * n * itemsize
    return out


def exchange_schedule_tier_bytes(comm: CartComm, record: dict) -> dict:
    """Per-tier twin of `exchange_schedule_bytes`: the per-step bytes of
    a solver's declared step-level schedule broken out by interconnect
    tier. The `dcn` entry is the first-class BENCH metric
    (`dcn_exchange_bytes`) — the slow-fabric traffic a multi-slice pod
    pays per step. Priced through the same strip helpers as the flat
    total, but counting only strips that MOVE (size-1 mesh axes charge
    nothing — see `halo_tier_bytes`), so on a partially-partitioned
    mesh the per-tier sum is the moved subset of
    `exchange_schedule_bytes`, not its full static geometry."""
    import numpy as np

    shard = tuple(record["shard"])
    isz = np.dtype(record["dtype"]).itemsize
    per = record.get("exchanges_per_step", {})
    out: dict[str, int] = {t: 0 for t in sorted(set(comm.tiers.values()))}

    def add(bytes_by_tier, times):
        for t, b in bytes_by_tier.items():
            out[t] += times * b

    add(halo_tier_bytes(comm, shard, 1, isz), per.get("depth1", 0))
    if "deep" in per:
        # per-tier depth map (ISSUE 17): mapped axes capture ONE
        # depth-H strip pair per `depth_block` steps (amortized, like
        # the flat accounting below); unmapped axes keep the per-step
        # deep strip. Empty map reduces to the historical flat add.
        depths = record.get("exchange_depths") or {}
        blk = max(int(record.get("depth_block", 1)), 1)
        epb = record.get("exchanges_per_block", {}).get(
            "deep", per["deep"])
        for ax, shape in enumerate(
                halo_strip_shapes(shard, record["deep_halo"])):
            name = comm.axis_names[ax]
            if comm.axis_size(name) == 1:
                continue
            if name in depths:
                cap = halo_strip_shapes(shard, depths[name])[ax]
                n = 1
                for s in cap:
                    n *= s
                out[comm.tiers[name]] += int(round(
                    epb * 2 * n * isz / blk))
            else:
                n = 1
                for s in shape:
                    n *= s
                out[comm.tiers[name]] += per["deep"] * 2 * n * isz
    if per.get("shift"):
        # one single-direction depth-1 strip per shifted axis
        per_axis = per["shift"] // len(shard)
        for ax, shape in enumerate(halo_strip_shapes(shard, 1)):
            name = comm.axis_names[ax]
            if comm.axis_size(name) == 1:
                continue
            n = 1
            for s in shape:
                n *= s
            out[comm.tiers[name]] += per_axis * n * isz
    return out


def halo_shift(x, comm: CartComm, axis: str):
    """commShift (comm.c:196-244): one-directional staggered exchange — fill
    the LOW ghost strip along `axis` from the minus-neighbour's high interior
    edge (the donor edge of staggered fluxes F/G/H). The plus-most shard's
    physical ghost is untouched."""
    dim = comm.axis_names.index(axis)
    nper = comm.axis_size(axis)
    if nper == 1:
        return x
    n = x.shape[dim]
    strip = tuple(1 if a == dim else x.shape[a] for a in range(x.ndim))
    with _scope("halo_shift", axis, strip, x.dtype):
        hi_edge = lax.slice_in_dim(x, n - 2, n - 1, axis=dim)
        from_lo = lax.ppermute(hi_edge, axis, _nbr_perm(nper, True, False))
        idx = lax.axis_index(axis)
        old_lo = lax.slice_in_dim(x, 0, 1, axis=dim)
        from_lo = jnp.where(idx > 0, from_lo, old_lo)
        return lax.dynamic_update_slice_in_dim(x, from_lo, 0, axis=dim)


def exchange_schedule_bytes(record: dict) -> int:
    """Per-step bytes of a solver's declared step-level exchange schedule
    (the `_halo_record()` dict): full exchanges at their depths plus the
    one-strip staggered shifts. Priced through `halo_exchange_bytes` /
    `halo_strip_shapes` so this total and the commcheck census cannot
    diverge. Per-STEP only: the overlap path's once-per-chunk prologue
    exchanges (`exchanges_per_chunk`) amortize to ~0 and are excluded,
    like the solve's internal exchanges."""
    import numpy as np

    shard = tuple(record["shard"])
    isz = np.dtype(record["dtype"]).itemsize
    per = record.get("exchanges_per_step", {})
    total = per.get("depth1", 0) * halo_exchange_bytes(shard, 1, isz)
    if "deep" in per:
        # per-tier depth map (ISSUE 17): mapped axes amortize ONE
        # depth-H capture pair over `depth_block` steps; unmapped axes
        # keep the per-step deep strip. Static geometry like the rest
        # of this accounting (size-1 axes count); empty map reduces to
        # the historical flat line bit-for-bit.
        depths = record.get("exchange_depths") or {}
        if not depths:
            total += per["deep"] * halo_exchange_bytes(
                shard, record["deep_halo"], isz)
        else:
            blk = max(int(record.get("depth_block", 1)), 1)
            epb = record.get("exchanges_per_block", {}).get(
                "deep", per["deep"])
            axes = record.get("axes") or [str(a) for a in range(len(shard))]
            for ax, shape in enumerate(
                    halo_strip_shapes(shard, record["deep_halo"])):
                if axes[ax] in depths:
                    cap = halo_strip_shapes(shard, depths[axes[ax]])[ax]
                    total += int(round(
                        epb * 2 * int(np.prod(cap)) * isz / blk))
                else:
                    total += per["deep"] * 2 * int(np.prod(shape)) * isz
    if per.get("shift"):
        # one shift per axis (F/G/H donor edges): a single depth-1 strip,
        # one direction
        per_axis = per["shift"] // len(shard)
        total += sum(per_axis * int(np.prod(s)) * isz
                     for s in halo_strip_shapes(shard, 1))
    return total


_PROBE_CACHE: dict = {}


def make_exchange_probe(comm: CartComm, record: dict):
    """Jitted exchange-only program of a solver's declared step-level
    schedule (`_halo_record()`): the SERIAL cost of one step's halo
    traffic with nothing overlapping it — the `exchange` span's
    critical-path number (ROADMAP item 2: the comm/compute-overlap
    refactor is judged by how much of this time it hides). The exchanges
    chain through one carried block per depth class so XLA cannot
    reorder or elide them. Returns (fn, args).

    Cached per (mesh, record geometry, dtype) — the first consumer of
    the persistent-schedule layer: repeated `time_exchange_ms` spans
    (every dist run's epilogue, every `dist_step_decomposition`) reuse
    one compiled probe instead of recompiling per call (identity
    test-pinned). The deep exchange routes through the cached
    `persistent_exchange` schedule; the per-step schedule it prices is
    unchanged by the overlap refactor (`exchanges_per_chunk` prologue
    exchanges are amortized over the chunk and deliberately excluded,
    like the solve's internal exchanges)."""
    per = record.get("exchanges_per_step", {})
    shard = tuple(int(s) for s in record["shard"])
    dtype = jnp.dtype(record["dtype"])
    H = int(record.get("deep_halo", 1))
    key = (_mesh_key(comm), shard, dtype.name, H,
           tuple(sorted((k, int(v)) for k, v in per.items())))
    fn = _PROBE_CACHE.get(key)
    if fn is None:
        names = comm.axis_names
        deep_sched = persistent_exchange(comm, H, dtype)

        def body(x1, xd):
            for _ in range(int(per.get("depth1", 0))):
                x1 = halo_exchange(x1, comm)
            for k in range(int(per.get("shift", 0))):
                x1 = halo_shift(x1, comm, names[k % len(names)])
            for _ in range(int(per.get("deep", 0))):
                xd = deep_sched(xd)
            return x1, xd

        spec = comm.spec()
        fn = jax.jit(comm.shard_map(body, in_specs=(spec, spec),
                                    out_specs=(spec, spec)))
        _PROBE_CACHE[key] = fn
    # only the jitted program is cached (the recompile was the cost);
    # the zero-filled argument blocks are rebuilt per call so the cache
    # never pins two full-grid device buffers for the process lifetime
    sh = comm.sharding()
    x1 = jax.device_put(
        jnp.zeros(tuple(p * (s + 2) for p, s in zip(comm.dims, shard)),
                  dtype), sh)
    xd = jax.device_put(
        jnp.zeros(tuple(p * (s + 2 * H) for p, s in zip(comm.dims, shard)),
                  dtype), sh)
    return fn, (x1, xd)


def time_exchange_ms(comm: CartComm, record: dict, reps: int = 3) -> float:
    """Best-of-reps wall time of ONE serial pass of the declared exchange
    schedule, in ms (compile + one warm dispatch excluded). Off-TPU the
    number is trend-only, like every other wall measurement here."""
    import time as _time

    fn, args = make_exchange_probe(comm, record)
    jax.block_until_ready(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, _time.perf_counter() - t0)
    return best * 1e3


def reduction(val, comm: CartComm, op: str = "sum"):
    """commReduction (comm.c:158-167): global MAX/SUM across the whole mesh."""
    axes = tuple(comm.axis_names)
    if op == "sum":
        return lax.psum(val, axes)
    if op == "max":
        return lax.pmax(val, axes)
    raise ValueError(f"unknown reduction op {op!r}")
