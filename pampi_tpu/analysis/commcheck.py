"""Collective-contract checker: does any chunk program move more halo
traffic than the communication schedule declares?

The bug class this guards is the distributed twin of the launch-count
contract: the overlap refactor (ROADMAP item 2) will rewrite exactly the
step-level exchange schedule, and "Persistent and Partitioned MPI for
Stencil Communication" (PAPERS.md) shows the overlap win evaporates if
extra exchanges sneak onto the critical path — a resharding collective
introduced by sharding propagation, a duplicated `halo_exchange`, or a
solve that silently re-exchanges per iteration would all cost latency the
telemetry only notices on real hardware. A static census of the traced
program catches them on CPU, before any TPU time is spent.

What one trace proves (`jax.make_jaxpr` of the chunk, no execution —
shapes inside `shard_map` are per-shard, so the census is the per-shard
accounting the PR 3 telemetry records use):

  collective census   occurrences of every collective primitive
                      (`ppermute`/`psum`/`pmax`/... ) in the chunk. The
                      while-loop step body traces once, so the counts are
                      per-STEP (solve-internal `fori` iterations likewise
                      trace once). Pinned env-keyed in the `comm` section
                      of CONTRACTS.json; drift fails with a per-primitive
                      diff (`tools/lint.py --update` after an intended
                      schedule change).
  resharding ban      `all_gather`/`all_to_all`/`reduce_scatter` never
                      appear: every production chunk is a manual
                      shard_map program whose only data motion is the
                      explicit ppermute exchange — a resharding
                      collective means sharding propagation re-laid data
                      out behind the schedule's back.
  halo traffic bytes  per-step ppermute payload bytes, derived from the
                      collective operands' shapes/dtypes. Baseline-pinned
                      (byte-volume drift is the "one fat message became
                      three thin ones" regression), and cross-checked
                      against the solver's own static accounting:
  telemetry cross-check  the PR 3 `halo` telemetry record
                      (`solver._halo_record()`, priced by
                      `parallel/comm.halo_exchange_bytes`) must agree
                      with the trace — its byte totals must equal the
                      strip geometry `comm.halo_strip_shapes` implies,
                      and the trace must actually contain the declared
                      step-level exchange messages (exact count for the
                      fused deep exchange; at-least for the depth-1
                      class, whose strip shape the staggered shifts
                      share). The record and this pass both lean on the
                      ONE helper in `parallel/comm.py`, so the two byte
                      accountings cannot diverge silently.

Single-device configs are checked too: their contract is zero collectives
(a collective in a single-device chunk means a mesh axis leaked into the
trace).
"""

from __future__ import annotations

from .astlint import Violation
from .jaxprcheck import _anchor, count_prim, iter_eqns

RULE_COUNT = "comm-collective"
RULE_BYTES = "comm-bytes"
RULE_RESHARD = "comm-reshard"
RULE_XCHECK = "comm-telemetry"
RULE_SCOPE = "comm-scope"
RULE_TIER = "comm-tier"

# the census vocabulary: every cross-shard primitive a chunk could carry
COLLECTIVES = ("ppermute", "psum", "pmax", "pmin", "all_gather",
               "all_to_all", "reduce_scatter")
# manual shard_map chunks may permute and reduce; re-LAYOUT collectives
# only appear when sharding propagation re-distributes behind the
# explicit schedule — banned outright, not baseline-pinned
RESHARDING = ("all_gather", "all_to_all", "reduce_scatter")


def strip_key(shape, dtype) -> str:
    """Census key of one ppermute message: '4x16:float64'. The ONE
    convention, homed in `parallel/comm.strip_key` next to the exchange
    whose messages it names — the `jax.named_scope` device-time scopes
    and `utils/xprof`'s trace aggregation use the same token, so a lint
    census entry and a profiler scope cannot drift apart."""
    from ..parallel.comm import strip_key as _key

    return _key(shape, dtype)


def scoped_exchanges(jaxpr) -> dict[str, int]:
    """ppermute eqns by their `halo_exchange.*`/`halo_shift.*` name-stack
    scope (parallel/comm wraps every exchange axis in a jax.named_scope) —
    the static twin of the xprof trace attribution. Unscoped ppermutes
    (e.g. the quarters solve's own q_exchange) land under ''."""
    out: dict[str, int] = {}
    for e in iter_eqns(jaxpr):
        if e.primitive.name != "ppermute":
            continue
        stack = str(getattr(e.source_info, "name_stack", "") or "")
        label = ""
        for part in stack.split("/"):
            if part.startswith(("halo_exchange.", "halo_shift.")):
                label = part
                break
        out[label] = out.get(label, 0) + 1
    return out


def aggregation_gathers(jaxpr) -> dict[str, int]:
    """all_gather eqns by their `mg_aggregate.*` name-stack scope — the
    DECLARED coarse-aggregation boundary of the distributed MG bottom
    (ops/multigrid wraps the bottom-residual gather in a
    jax.named_scope). These are the only resharding collectives a chunk
    may carry: check_config subtracts them from the RULE_RESHARD ban and
    pins the census in the baseline, so an UNDECLARED gather still fails
    the ban and a declared one cannot silently multiply."""
    out: dict[str, int] = {}
    for e in iter_eqns(jaxpr):
        if e.primitive.name != "all_gather":
            continue
        stack = str(getattr(e.source_info, "name_stack", "") or "")
        for part in stack.split("/"):
            if part.startswith("mg_aggregate."):
                out[part] = out.get(part, 0) + 1
                break
    return out


def census(jaxpr) -> dict:
    """The collective content of a traced program: per-primitive counts,
    the ppermute message multiset (shape×dtype -> occurrences), and the
    total ppermute payload bytes per shard."""
    import numpy as np

    counts = {name: 0 for name in COLLECTIVES}
    strips: dict[str, int] = {}
    total = 0
    for e in iter_eqns(jaxpr):
        name = e.primitive.name
        if name not in counts:
            continue
        counts[name] += 1
        if name == "ppermute":
            aval = e.invars[0].aval
            key = strip_key(aval.shape, aval.dtype)
            strips[key] = strips.get(key, 0) + 1
            total += int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    return {"collectives": counts, "ppermute_bytes": total,
            "strips": strips}


def _scope_axis(e) -> str | None:
    """Mesh-axis name of a ppermute eqn's halo_exchange./halo_shift.
    named scope ('halo_exchange.j.4x18:float64' -> 'j'), or None when
    the eqn is unscoped (e.g. the quarters solve's own q_exchange)."""
    stack = str(getattr(e.source_info, "name_stack", "") or "")
    for part in stack.split("/"):
        if part.startswith(("halo_exchange.", "halo_shift.")):
            bits = part.split(".")
            if len(bits) >= 2:
                return bits[1]
    return None


def census_tiers(jaxpr, tiers: dict) -> dict:
    """The per-TIER traffic breakdown of a traced program's ppermutes
    (ROADMAP item 3 — DCN bytes as a first-class contract): every
    ppermute is attributed through its named scope's mesh axis to the
    comm's axis->tier map (`tpu_mesh_tiers`); unscoped ppermutes land
    under 'untiered'. Per tier: collective count, payload bytes, and
    the strip multiset. The per-tier byte sum always equals the flat
    census's `ppermute_bytes` (structurally enforced in check_config),
    so the single-tier default is byte-identical to the historical
    totals with everything under 'ici'."""
    import numpy as np

    out: dict[str, dict] = {}
    for e in iter_eqns(jaxpr):
        if e.primitive.name != "ppermute":
            continue
        axis = _scope_axis(e)
        tier = tiers.get(axis, "untiered") if axis else "untiered"
        t = out.setdefault(tier, {"ppermute": 0, "bytes": 0, "strips": {}})
        aval = e.invars[0].aval
        key = strip_key(aval.shape, aval.dtype)
        t["ppermute"] += 1
        t["bytes"] += int(np.prod(aval.shape)) * np.dtype(
            aval.dtype).itemsize
        t["strips"][key] = t["strips"].get(key, 0) + 1
    return out


def config_entry(traced) -> dict:
    """The fresh `comm` baseline entry for one traced config."""
    entry = census(traced.jaxpr.jaxpr)
    agg = aggregation_gathers(traced.jaxpr.jaxpr)
    if agg:
        entry["aggregation"] = agg
    rec = getattr(traced.solver, "_halo_record", None)
    entry["halo"] = rec() if callable(rec) else None
    comm = getattr(traced.solver, "comm", None)
    tiers = getattr(comm, "tiers", None)
    if tiers and entry["collectives"].get("ppermute"):
        entry["tiers"] = census_tiers(traced.jaxpr.jaxpr, tiers)
    return entry


def diff_counts(old: dict, new: dict, kind: str) -> list[str]:
    """Per-primitive (or per-strip) deltas — the drift diagnostic."""
    lines = []
    for name in sorted(set(old) | set(new)):
        a, b = old.get(name, 0), new.get(name, 0)
        if a != b:
            lines.append(f"{kind} {name}: {a} -> {b} ({b - a:+d})")
    return lines


# ---------------------------------------------------------------------------
# the overlap-schedule checker (ROADMAP item 2)
# ---------------------------------------------------------------------------

def _deep_strip_keys(rec: dict) -> set[str]:
    """Strip-key tokens of the record's deep-exchange messages on the
    partitioned axes — the shapes that identify the STEP-LEVEL deep
    exchange among a chunk's ppermutes (the solve's internal exchanges
    travel at other depths)."""
    from ..parallel.comm import halo_strip_shapes

    import numpy as np

    if "deep_halo" not in rec:
        return set()
    shard = tuple(rec["shard"])
    mesh = tuple(rec["mesh"])
    dtype = np.dtype(rec["dtype"])
    return {
        strip_key(shape, dtype)
        for ax, shape in enumerate(halo_strip_shapes(shard,
                                                     rec["deep_halo"]))
        if mesh[ax] > 1
    }


def _find_chunk_loop(jaxpr):
    """(enclosing jaxpr, while eqn) of the outermost while whose body
    dispatches a pallas_call — the chunk step loop. None when the
    program has no such loop (jnp solve paths still qualify via the
    fused PRE/POST kernels)."""
    for e in jaxpr.eqns:
        if e.primitive.name == "while":
            body = e.params["body_jaxpr"].jaxpr
            if count_prim(body, "pallas_call"):
                return jaxpr, e
        for v in e.params.values():
            vals = v if isinstance(v, (tuple, list)) else (v,)
            for x in vals:
                inner = None
                if type(x).__name__ == "ClosedJaxpr":
                    inner = x.jaxpr
                elif type(x).__name__ == "Jaxpr":
                    inner = x
                if inner is not None:
                    found = _find_chunk_loop(inner)
                    if found is not None:
                        return found
    return None


def overlap_schedule_violations(closed, rec: dict,
                                sweeps: bool = False) -> list[str]:
    """Static proof that a chunk program carries the DOUBLE-BUFFERED
    overlap schedule (models/ns*_dist step_overlap; `make profile-smoke`
    and tests assert through this one helper). `sweeps=True` is the
    sweep-loop mode: additionally prove the solve's convergence loops
    post their depth-1 exchanges split interior/boundary
    (`sweep_split_violations`).

    1. the chunk's step loop posts the deep exchange but no pallas_call
       of the same iteration consumes its results (forward dataflow
       taint over the flat loop body — the ppermutes feed only the loop
       carry, i.e. next iteration's boundary half), and
    2. a prologue deep exchange precedes the loop (the first
       double-buffer generation is filled before step 1 consumes it).

    Together these pin "exchange posted before the compute that could
    hide it": within the traced schedule the exchange is no longer
    serialized against the kernels — the structural precondition for a
    nonzero comm-hidden fraction on chip. Returns diagnostics (empty =
    the schedule holds); a SERIAL fused chunk fails check 1 (its PRE
    kernel consumes the same-step exchange) — the negative control the
    mutation test pins."""
    deep_keys = _deep_strip_keys(rec)
    if not deep_keys:
        return ["halo record declares no deep exchange on a partitioned "
                "axis — the overlap schedule has nothing to check"]
    jaxpr = closed.jaxpr

    def is_deep_ppermute(e):
        if e.primitive.name != "ppermute":
            return False
        aval = e.invars[0].aval
        return strip_key(aval.shape, aval.dtype) in deep_keys

    found = _find_chunk_loop(jaxpr)
    if found is None:
        return ["chunk program has no pallas-dispatching step loop"]
    level, while_eqn = found
    body = while_eqn.params["body_jaxpr"].jaxpr
    errs = []
    # (1) dataflow: deep ppermute results must not reach any pallas_call
    # of the same iteration (nested eqns treated atomically — taint
    # flows through them conservatively)
    deep_eqns = [e for e in body.eqns if is_deep_ppermute(e)]
    if not deep_eqns:
        errs.append(
            "step loop body carries no deep-strip ppermute "
            f"({sorted(deep_keys)}) — the step-level exchange vanished")
    tainted: set[int] = set()
    for e in body.eqns:
        if is_deep_ppermute(e):
            tainted.update(id(v) for v in e.outvars)
            continue
        hit = any(id(v) in tainted for v in e.invars)
        if hit:
            if e.primitive.name == "pallas_call":
                errs.append(
                    "a deep-exchange ppermute result feeds a pallas_call "
                    "in the SAME iteration — the exchange is serialized "
                    "against the kernel, not double-buffered")
            tainted.update(id(v) for v in e.outvars)
    # (2) the prologue exchange fills the first buffer generation
    before = []
    for e in level.eqns:
        if e is while_eqn:
            break
        before.append(e)
    if not any(is_deep_ppermute(e) for e in before):
        errs.append(
            "no prologue deep exchange precedes the step loop — the "
            "first iteration's double buffer is never filled")
    if sweeps:
        errs += sweep_split_violations(closed, rec)
    return errs


def _depth1_strip_keys(rec: dict) -> set[str]:
    """Strip-key tokens of the halo-1 exchange messages on the
    partitioned axes (the depth-1 class the split solve sweeps post)."""
    from ..parallel.comm import halo_strip_shapes

    import numpy as np

    shard = tuple(rec["shard"])
    mesh = tuple(rec["mesh"])
    dtype = np.dtype(rec["dtype"])
    return {
        strip_key(shape, dtype)
        for ax, shape in enumerate(halo_strip_shapes(shard, 1))
        if mesh[ax] > 1
    }


def _all_whiles(jaxpr):
    """Every while eqn anywhere in the program, with its body jaxpr."""
    for e in jaxpr.eqns:
        if e.primitive.name == "while":
            yield e.params["body_jaxpr"].jaxpr
        for v in e.params.values():
            vals = v if isinstance(v, (tuple, list)) else (v,)
            for x in vals:
                inner = None
                if type(x).__name__ == "ClosedJaxpr":
                    inner = x.jaxpr
                elif type(x).__name__ == "Jaxpr":
                    inner = x
                if inner is not None:
                    yield from _all_whiles(inner)


def sweep_split_violations(closed, rec: dict) -> list[str]:
    """The sweep-loop mode of the overlap schedule proof (ROADMAP item 3
    layer 2): statically prove the solve's convergence loops post their
    depth-1 exchanges SPLIT — no half-sweep's whole update consumes the
    posted ppermutes.

    A candidate sweep loop is any while whose body DIRECTLY carries a
    depth-1-strip ppermute and a psum (the residual reduction) — the
    shape of the split RB-SOR loop and the split MG smoother's enclosing
    cycle loop. For each candidate, the ppermute outputs are tainted
    forward; the loop passes when some full-block `select_n` merges a
    tainted (boundary) half with an UNTAINTED (interior) float half —
    the structural witness that an interior-region update exists with no
    dependency path from the exchange, i.e. compute the scheduler can
    hide the exchange behind. A SERIAL solve fails at step one: its
    sweeps either exchange at CA depth (no depth-1 loop exists) or feed
    the whole update from the exchanged block (no untainted merge half)
    — the negative control the mutation test pins. Returns diagnostics
    (empty = the split holds)."""
    import numpy as np

    keys = _depth1_strip_keys(rec)
    if not keys:
        return ["halo record declares no partitioned axis — no sweep "
                "loop to check"]
    block = tuple(int(s) + 2 for s in rec["shard"])

    def is_d1(e):
        if e.primitive.name != "ppermute":
            return False
        aval = e.invars[0].aval
        return strip_key(aval.shape, aval.dtype) in keys

    candidates = []
    for body in _all_whiles(closed.jaxpr):
        has_d1 = any(is_d1(e) for e in body.eqns)
        has_psum = any(e.primitive.name == "psum" for e in body.eqns)
        if has_d1 and has_psum:
            candidates.append(body)
    if not candidates:
        return [
            "no depth-1-exchanging sweep loop in the chunk — the solve "
            "sweeps serialize their exchanges (CA/deep or in-kernel), "
            "nothing is split"]
    def contains_select(e) -> bool:
        """select_n directly, or inside a sub-jaxpr (jnp.where is an
        internally-jitted function, so the select arrives wrapped in a
        pjit eqn)."""
        if e.primitive.name == "select_n":
            return True
        for v in e.params.values():
            vals = v if isinstance(v, (tuple, list)) else (v,)
            for x in vals:
                inner = None
                if type(x).__name__ == "ClosedJaxpr":
                    inner = x.jaxpr
                elif type(x).__name__ == "Jaxpr":
                    inner = x
                if inner is not None and any(
                        ie.primitive.name == "select_n"
                        for ie in inner.eqns):
                    return True
        return False

    errs = []
    for body in candidates:
        tainted: set[int] = set()
        split_merge = False
        for e in body.eqns:
            if is_d1(e):
                tainted.update(id(v) for v in e.outvars)
                continue
            hit = any(id(v) in tainted for v in e.invars)
            if hit and contains_select(e):
                floats = [v for v in e.invars
                          if getattr(v.aval, "shape", None) == block
                          and np.issubdtype(
                              np.dtype(getattr(v.aval, "dtype", bool)),
                              np.floating)]
                if (any(id(v) in tainted for v in floats)
                        and any(id(v) not in tainted for v in floats)):
                    split_merge = True
            if hit:
                tainted.update(id(v) for v in e.outvars)
        if not split_merge:
            errs.append(
                "a sweep loop's depth-1 ppermutes feed every full-block "
                "update — the exchange is serialized against the whole "
                "half-sweep, not split interior/boundary")
    return errs


# ---------------------------------------------------------------------------
# the telemetry cross-check
# ---------------------------------------------------------------------------

def _expected_strips(rec: dict) -> list[tuple[str, int, bool]]:
    """The step-level exchange messages the solver's `halo` record
    declares, as (strip key, per-axis count, exact) triples. Axes whose
    mesh dim is 1 exchange nothing (`_exchange_axis` short-circuits) and
    are skipped. The deep fused exchange is checked EXACTLY — its strip
    shape is unique to the deep block, so a duplicated deep exchange
    cannot hide; the overlapped schedule's once-per-chunk prologue
    exchanges (`exchanges_per_chunk`, the double-buffer fill) trace into
    the same chunk program and are added to the exact count. The depth-1
    class is checked at-least: its strip shape is shared with the
    staggered shifts and with depth-1 exchanges inside solve/POST
    plumbing the record deliberately excludes.

    A record carrying `exchange_depths` (ISSUE 17, the per-tier depth
    map) reroutes the mapped axes: their per-step deep strips are GONE
    from the trace — replaced by one depth-H capture pair per H-step
    block, whose strip geometry is `halo_strip_shapes(shard, H)` and
    whose exact count is 2 x `exchanges_per_block["deep"]` (the K-scan
    body traces once, so the traced chunk carries exactly one block's
    capture). Unmapped axes keep the historical exact pin — the ICI
    depth is provably unchanged."""
    from ..parallel.comm import halo_strip_shapes

    import numpy as np

    shard = tuple(rec["shard"])
    mesh = tuple(rec["mesh"])
    dtype = np.dtype(rec["dtype"])
    per_step = rec.get("exchanges_per_step", {})
    per_chunk = rec.get("exchanges_per_chunk", {})
    depths = rec.get("exchange_depths") or {}
    axes = rec.get("axes") or []
    out = []
    if "deep" in per_step:
        shapes = halo_strip_shapes(shard, rec["deep_halo"])
        deep = per_step["deep"] + per_chunk.get("deep", 0)
        for ax, shape in enumerate(shapes):
            if mesh[ax] > 1 and not (
                    ax < len(axes) and axes[ax] in depths):
                out.append((strip_key(shape, dtype), 2 * deep, True))
        if depths:
            epb = rec.get("exchanges_per_block", {}).get("deep", deep)
            for ax, name in enumerate(axes):
                if mesh[ax] > 1 and name in depths:
                    cap = halo_strip_shapes(shard, depths[name])[ax]
                    out.append((strip_key(cap, dtype), 2 * epb, True))
    if "depth1" in per_step:
        shapes = halo_strip_shapes(shard, 1)
        # one staggered shift per axis (F/G/H donor edges) shares the
        # depth-1 strip shape
        shifts = per_step.get("shift", 0) // len(shard)
        for ax, shape in enumerate(shapes):
            if mesh[ax] > 1:
                out.append((strip_key(shape, dtype),
                            2 * per_step["depth1"] + shifts, False))
    return out


def crosscheck_record(rec: dict, entry: dict) -> list[str]:
    """The PR 3 halo record vs this trace census. Returns diagnostic
    strings (empty = the two byte accountings agree)."""
    from ..parallel.comm import halo_exchange_bytes

    import numpy as np

    errs = []
    shard = tuple(rec["shard"])
    isz = np.dtype(rec["dtype"]).itemsize
    # (1) the record's byte totals are exactly what the shared strip
    # geometry prices — a record hand-computing bytes would drift here
    want = halo_exchange_bytes(shard, 1, isz)
    if rec["exchange_bytes_depth1"] != want:
        errs.append(
            f"halo record exchange_bytes_depth1={rec['exchange_bytes_depth1']}"
            f" != comm.halo_exchange_bytes({shard}, 1) = {want}")
    if "deep_exchange_bytes" in rec:
        want = halo_exchange_bytes(shard, rec["deep_halo"], isz)
        if rec["deep_exchange_bytes"] != want:
            errs.append(
                f"halo record deep_exchange_bytes={rec['deep_exchange_bytes']}"
                f" != comm.halo_exchange_bytes({shard}, "
                f"{rec['deep_halo']}) = {want}")
    # (2) the trace actually contains the declared step-level messages
    strips = entry["strips"]
    for key, count, exact in _expected_strips(rec):
        have = strips.get(key, 0)
        if exact and have != count:
            errs.append(
                f"deep-exchange strip {key}: trace carries {have} "
                f"ppermute(s), the halo record declares exactly {count} "
                "(a duplicated or dropped deep exchange)")
        elif not exact and have < count:
            errs.append(
                f"depth-1 strip {key}: trace carries {have} ppermute(s), "
                f"the halo record declares at least {count}")
    return errs


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def check_config(traced, baseline: dict | None,
                 env_matches: bool) -> tuple[list[Violation], dict]:
    """Census one traced config, apply the structural rules, and compare
    against its `comm` baseline entry. Returns (violations, fresh
    entry)."""
    cfg = traced.cfg
    path, line = _anchor(cfg.family)
    entry = config_entry(traced)
    counts = entry["collectives"]
    vs: list[Violation] = []

    def emit(rule, msg):
        vs.append(Violation(path, line, rule, f"{cfg.name}: {msg}"))

    # resharding collectives are banned on every chunk path — EXCEPT the
    # declared coarse-aggregation boundary (ISSUE 16): all_gathers under
    # an `mg_aggregate.*` named scope are the distributed MG bottom's
    # replicated-solve gather, censused and baseline-pinned below; any
    # gather OUTSIDE that scope still trips the ban
    resharded = {n: counts[n] for n in RESHARDING if counts[n]}
    declared = sum(entry.get("aggregation", {}).values())
    if declared and "all_gather" in resharded:
        undeclared = resharded["all_gather"] - declared
        if undeclared > 0:
            resharded["all_gather"] = undeclared
        else:
            del resharded["all_gather"]
    if resharded:
        emit(RULE_RESHARD,
             f"chunk contains resharding collectives {resharded} — "
             "sharding propagation re-laid data out behind the explicit "
             "exchange schedule (coarse-aggregation gathers must carry "
             "the mg_aggregate.* named scope)")
    # single-device chunks carry no collectives at all
    if cfg.dims is None and any(counts.values()):
        emit(RULE_COUNT,
             f"single-device chunk contains collectives "
             f"{ {k: v for k, v in counts.items() if v} } — a mesh axis "
             "leaked into the trace")
    # every dist chunk's step-level exchanges must carry the named-scope
    # attribution (parallel/comm._scope) — without it the xprof plane
    # cannot attribute device time to the exchange and the comm-hidden
    # fraction (ROADMAP item 2's headline) is unmeasurable
    if cfg.dims is not None and counts.get("ppermute"):
        scoped = scoped_exchanges(traced.jaxpr.jaxpr)
        if not any(label for label in scoped):
            emit(RULE_SCOPE,
                 f"chunk carries {counts['ppermute']} ppermute(s) but none "
                 "under a halo_exchange./halo_shift. named scope — the "
                 "exchange lost its device-time attribution "
                 "(parallel/comm._scope)")
    # per-tier coverage invariant: the tier breakdown must account for
    # every ppermute byte of the flat census (a mis-attributed strip
    # would silently vanish from the DCN accounting)
    if "tiers" in entry:
        tsum = sum(t["bytes"] for t in entry["tiers"].values())
        if tsum != entry["ppermute_bytes"]:
            emit(RULE_TIER,
                 f"per-tier census covers {tsum} of "
                 f"{entry['ppermute_bytes']} ppermute bytes — a strip "
                 "lost its tier attribution")
    # the telemetry cross-check (dist solvers expose _halo_record)
    if entry["halo"] is not None:
        for msg in crosscheck_record(entry["halo"], entry):
            emit(RULE_XCHECK, msg)
    # the per-tier depth pin (ISSUE 17): a record declaring
    # `exchange_depths` claims the mapped slow-fabric axis ships ONE
    # depth-H strip pair per field per H-step block instead of one per
    # step. The traced K-block is the proof: the mapped axis's tier
    # must carry EXACTLY 2 x exchanges_per_block["deep"] ppermutes of
    # the depth-H capture strip and ZERO of the historical per-step
    # deep strip — "1 slow-tier exchange per H steps", statically.
    rec = entry["halo"]
    if rec and rec.get("exchange_depths") and "tiers" in entry:
        from ..parallel.comm import halo_strip_shapes

        import numpy as np

        shard = tuple(rec["shard"])
        dtype = np.dtype(rec["dtype"])
        axes = rec.get("axes") or []
        tmap = rec.get("tier_map") or {}
        epb = rec.get("exchanges_per_block", {}).get("deep", 0)
        for name, h in rec["exchange_depths"].items():
            ax = axes.index(name)
            cap_key = strip_key(halo_strip_shapes(shard, h)[ax], dtype)
            deep_key = strip_key(
                halo_strip_shapes(shard, rec["deep_halo"])[ax], dtype)
            tier = tmap.get(name, "untiered")
            tstrips = entry["tiers"].get(tier, {}).get("strips", {})
            have = tstrips.get(cap_key, 0)
            if have != 2 * epb:
                emit(RULE_TIER,
                     f"depth map {name}={h}: the {tier} tier carries "
                     f"{have} capture-strip ({cap_key}) ppermute(s) per "
                     f"K-block, the record declares exactly {2 * epb} — "
                     "the amortized slow exchange drifted")
            if tstrips.get(deep_key, 0):
                emit(RULE_TIER,
                     f"depth map {name}={h}: the {tier} tier still "
                     f"carries {tstrips[deep_key]} per-step deep strip "
                     f"({deep_key}) ppermute(s) — the step-level "
                     "exchange was amortized AND kept")
    # baseline comparison — env-gated like the jaxpr hash: collective
    # schedules follow the solve dispatch, which follows toolchain probes
    if baseline is not None and env_matches:
        cdiff = diff_counts(baseline.get("collectives", {}), counts,
                            "collective")
        if cdiff:
            emit(RULE_COUNT,
                 "collective schedule drifted from the comm baseline: "
                 + "; ".join(cdiff)
                 + " (tools/lint.py --update if intended)")
        if baseline.get("ppermute_bytes") != entry["ppermute_bytes"]:
            sdiff = diff_counts(baseline.get("strips", {}),
                                entry["strips"], "strip")
            emit(RULE_BYTES,
                 f"per-step halo traffic drifted: "
                 f"{baseline.get('ppermute_bytes')} -> "
                 f"{entry['ppermute_bytes']} bytes/shard ("
                 + ("; ".join(sdiff) if sdiff else "same strips, other "
                    "dtype/shape change")
                 + ") (tools/lint.py --update if intended)")
        elif baseline.get("strips") != entry["strips"]:
            # byte-neutral reshuffle (e.g. one fat message split into
            # equal thin ones) still drifts the schedule
            sdiff = diff_counts(baseline.get("strips", {}),
                                entry["strips"], "strip")
            emit(RULE_BYTES,
                 "halo message geometry drifted at equal byte volume: "
                 + "; ".join(sdiff)
                 + " (tools/lint.py --update if intended)")
        if baseline.get("aggregation") != entry.get("aggregation"):
            # the declared aggregation boundary is pinned like any other
            # schedule fact: a gather appearing, vanishing, or
            # multiplying is a dispatch change, not a tolerance
            adiff = diff_counts(baseline.get("aggregation") or {},
                                entry.get("aggregation") or {},
                                "aggregation")
            emit(RULE_RESHARD,
                 "declared coarse-aggregation boundary drifted from the "
                 "comm baseline: "
                 + ("; ".join(adiff) if adiff else "scope set changed")
                 + " (tools/lint.py --update if intended)")
        if "tiers" in baseline and baseline["tiers"] != entry.get("tiers"):
            # the per-tier breakdown is pinned too: a re-tiered strip
            # (bytes migrating between ICI and DCN) is a schedule
            # change even at constant totals
            old_t = baseline.get("tiers") or {}
            new_t = entry.get("tiers") or {}
            tdiff = diff_counts(
                {k: v.get("bytes", 0) for k, v in old_t.items()},
                {k: v.get("bytes", 0) for k, v in new_t.items()},
                "tier-bytes")
            emit(RULE_TIER,
                 "per-tier traffic drifted from the comm baseline: "
                 + ("; ".join(tdiff) if tdiff
                    else "same bytes, strip/count reshuffle")
                 + " (tools/lint.py --update if intended)")
    return vs, entry


def run(baseline: dict | None = None, configs=None, update: bool = False,
        traced=None, env_matches: bool = True) -> tuple[list, dict]:
    """Check every config of the matrix. `baseline` is the `comm` section
    of CONTRACTS.json ({config name: entry}); returns (violations, fresh
    comm section). `traced` (jaxprcheck.trace_matrix) shares solver
    builds across passes."""
    from . import jaxprcheck

    if traced is None:
        traced = jaxprcheck.trace_matrix(configs)
    vs: list[Violation] = []
    fresh: dict[str, dict] = {}
    for t in traced:
        entry = (baseline or {}).get(t.cfg.name)
        if entry is None and baseline is not None and not update:
            vs.append(Violation(
                "CONTRACTS.json", 1, RULE_COUNT,
                f"{t.cfg.name}: no comm baseline entry "
                "(tools/lint.py --update)"))
        t_vs, fresh_entry = check_config(
            t, None if update else entry, env_matches)
        vs += t_vs
        fresh[t.cfg.name] = fresh_entry
    return vs, fresh
