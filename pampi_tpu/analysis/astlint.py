"""AST lint: repo-specific source rules with file:line diagnostics.

Every rule guards a contract that past PRs fixed by hand at least once:

  env-read       environment reads (`os.environ`, `os.getenv`) outside
                 the registered accessor layer (`utils/flags.py`). The
                 accessor records every variable in one inventory, so a
                 rogue read is a knob invisible to the docs, the lint,
                 and the flag-off identity tests.
  raw-shard-map  `jax.shard_map` / `jax.experimental.shard_map` used
                 outside `parallel/comm.compat_shard_map` — the version
                 shim lives there ONLY (two past PRs routed stragglers).
  np-in-traced   `np.*` inside a traced closure — a def nested in a
                 `_build_*`/`make_*` builder, the repo's convention for
                 the functions jit/while_loop traces per step (builder
                 BODIES run once at build time, where numpy is the
                 correct tool for baking constants): numpy on a tracer
                 fails at trace time, numpy on a constant silently bakes
                 host values/dtypes the precision contract never sees.
  traced-nondet  wall-clock/random calls (`time.*`, `random.*`,
                 `np.random.*`, `datetime.*`) in the same traced
                 contexts — a nondeterministic trace breaks the flag-off
                 byte-identity contract and the XLA cache.
  broad-except   `except Exception:`/bare `except:` without an allow
                 escape — fault classification (models/_driver.py) depends
                 on concrete exception classes reaching it.
  print-call     `print()` in library code where telemetry/progress
                 records exist (CLI entry points are exempt).
  dtype-policy   raw float-dtype literals in solver/ops builder code
                 (`.astype(jnp.float32)`, `jnp.float64(x)`,
                 `dtype=jnp.bfloat16`) — the compute dtype is a POLICY
                 (`utils/precision.resolve_dtype` resolves it once per
                 solver; `precision.cast` declares every intentional
                 downcast), so a hard-coded dtype in models/ or ops/ is
                 a precision decision the preccheck census cannot see
                 coming. Builder-context only (constants baked by
                 builders ARE the traced program); passing a dtype
                 VARIABLE is always fine.

Escape hatch: a trailing `# lint: allow(<rule>[, <rule>...])` comment on
the offending line (for `except` clauses, on the `except` line), with a
short justification after it. The escape is per-line and per-rule — a
file-wide opt-out does not exist by design.

API: `lint_file(path)` / `lint_tree(root)` -> list[Violation]; the
`tools/lint.py` driver renders them as `file:line: [rule] message`.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

# rule ids (the allow-escape vocabulary)
ENV_READ = "env-read"
RAW_SHARD_MAP = "raw-shard-map"
NP_IN_TRACED = "np-in-traced"
TRACED_NONDET = "traced-nondet"
BROAD_EXCEPT = "broad-except"
PRINT_CALL = "print-call"
DTYPE_POLICY = "dtype-policy"

ALL_RULES = (ENV_READ, RAW_SHARD_MAP, NP_IN_TRACED, TRACED_NONDET,
             BROAD_EXCEPT, PRINT_CALL, DTYPE_POLICY)

# rule sets by tree: library code gets everything; tools/tests are
# harness code (prints, env knobs and numpy are their job) but must still
# route shard_map through the compat shim
LIBRARY_RULES = ALL_RULES
HARNESS_RULES = (RAW_SHARD_MAP,)

# modules where the rule's guarded behaviour IS the module's purpose
ENV_ACCESSOR_FILES = ("utils/flags.py",)
SHARD_MAP_HOME_FILES = ("parallel/comm.py",)
PRINT_EXEMPT_FILES = ("cli.py", "__main__.py", "utils/progress.py",
                      "utils/params.py")

# the dtype-policy rule applies only where solver/ops builders live —
# elsewhere (utils/precision.py above all) a dtype literal IS the policy
DTYPE_POLICY_DIRS = ("models", "ops")

_FLOAT_DTYPE_NAMES = frozenset(
    ("float16", "float32", "float64", "bfloat16",
     "half", "single", "double"))

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")


@dataclass
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _allowed(source_lines: list[str], lineno: int, rule: str) -> bool:
    """True when the 1-indexed line carries `# lint: allow(...)` naming
    `rule` (comma-separated list accepted)."""
    if not (1 <= lineno <= len(source_lines)):
        return False
    m = _ALLOW_RE.search(source_lines[lineno - 1])
    if not m:
        return False
    allowed = {tok.strip() for tok in m.group(1).split(",")}
    return rule in allowed


def _dotted(node: ast.AST) -> str:
    """`a.b.c` attribute chains as a dotted string ('' when not one)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _dtype_literal(node: ast.AST) -> str:
    """The spelled-out float-dtype literal an expression hard-codes
    ('jnp.float32', "'float64'"), or '' when the expression is a name/
    computed value (a dtype VARIABLE — policy-resolved, always fine)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _FLOAT_DTYPE_NAMES:
        return repr(node.value)
    dotted = _dotted(node)
    if dotted:
        parts = dotted.split(".")
        if parts[-1] in _FLOAT_DTYPE_NAMES \
                and parts[0] in ("jnp", "np", "numpy", "jax"):
            return dotted
    return ""


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str, rules):
        self.path = path
        self.rel = rel
        self.lines = source.splitlines()
        self.rules = set(rules)
        self.out: list[Violation] = []
        # stack of (function name, is_traced_context)
        self._funcs: list[tuple[str, bool]] = []
        # local aliases of the jax.experimental.shard_map MODULE
        # (`import jax.experimental.shard_map as sm` -> "sm")
        self._sm_aliases: set[str] = set()

    # -- helpers --------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        if rule not in self.rules:
            return
        if _allowed(self.lines, node.lineno, rule):
            return
        self.out.append(Violation(self.rel, node.lineno, rule, message))

    def _traced(self) -> bool:
        """Inside a def nested under a `_build_*`/`make_*` builder (the
        repo's traced-closure convention)."""
        return any(traced for _name, traced in self._funcs)

    def _in_builder(self) -> bool:
        """Inside a builder's own body OR a def nested under one — the
        dtype-policy scope: both the baked constants and the traced
        closures are the program the precision contract governs."""
        return self._traced() or any(
            name.startswith(("_build_", "make_"))
            for name, _traced in self._funcs)

    # -- visitors -------------------------------------------------------
    def _visit_funcdef(self, node) -> None:
        name = node.name
        parent_is_builder = bool(self._funcs) and (
            self._funcs[-1][0].startswith("_build_")
            or self._funcs[-1][0].startswith("make_")
        )
        traced = parent_is_builder or (self._funcs and self._funcs[-1][1])
        self._funcs.append((name, bool(traced)))
        self.generic_visit(node)
        self._funcs.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        if dotted in ("os.environ.get", "os.getenv", "os.environ.setdefault"):
            self._emit(node, ENV_READ,
                       f"{dotted} outside utils/flags.py — route through "
                       "flags.env()/set_default() so the env-var inventory "
                       "stays complete")
        parts = dotted.split(".") if dotted else []
        raw_sm = parts and parts[-1] == "shard_map" and (
            dotted == "shard_map"                    # from jax import ...
            or parts[0] == "jax"                     # jax.shard_map & co
            or parts[0] in self._sm_aliases          # aliased module
        )
        if raw_sm:
            # the call site is the authoritative trigger (the import-site
            # rules can't see `from jax import shard_map` on every jax
            # version); method calls on repo objects (CartComm.shard_map
            # routes through the shim internally) don't match — their
            # receiver is neither jax nor a tracked module alias
            self._emit(node, RAW_SHARD_MAP,
                       f"{dotted}() called directly — route through "
                       "parallel/comm.compat_shard_map (the one "
                       "version shim)")
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self._emit(node, PRINT_CALL,
                       "print() in library code — emit a telemetry record "
                       "(utils/telemetry), a progress update, or a warning "
                       "instead")
        if self._traced():
            root = dotted.split(".")[0] if dotted else ""
            if root == "np" and not dotted.startswith("np.random"):
                self._emit(node, NP_IN_TRACED,
                           f"{dotted}() inside a traced context — numpy "
                           "bakes host values/dtypes into the trace; use "
                           "jnp (or hoist to the builder body and mark "
                           "the constant intent)")
            if (dotted.startswith("np.random") or root in ("random",)
                    or dotted.startswith("datetime.")
                    or dotted in ("time.time", "time.perf_counter",
                                  "time.monotonic")):
                self._emit(node, TRACED_NONDET,
                           f"{dotted}() inside a traced context — a "
                           "nondeterministic trace breaks the flag-off "
                           "byte-identity contract and the XLA cache")
        if self._in_builder():
            # raw `.astype(<float literal>)`
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" and node.args:
                lit = _dtype_literal(node.args[0])
                if lit:
                    self._emit(node, DTYPE_POLICY,
                               f".astype({lit}) hard-codes a float dtype "
                               "in builder code — the compute dtype is "
                               "policy (utils/precision.resolve_dtype); "
                               "declare an intentional downcast through "
                               "precision.cast(x, dtype, why)")
            # `jnp.float64(x)` constructor casts
            parts = dotted.split(".") if dotted else []
            if len(parts) == 2 and parts[0] in ("jnp", "np", "numpy") \
                    and parts[1] in _FLOAT_DTYPE_NAMES and node.args:
                self._emit(node, DTYPE_POLICY,
                           f"{dotted}(...) hard-codes a float dtype in "
                           "builder code — resolve the dtype through "
                           "utils/precision instead of constructing one")
            # `dtype=<float literal>` keywords
            for kw in node.keywords:
                if kw.arg == "dtype":
                    lit = _dtype_literal(kw.value)
                    if lit:
                        self._emit(node, DTYPE_POLICY,
                                   f"dtype={lit} hard-codes a float dtype "
                                   "in builder code — thread the solver's "
                                   "policy dtype (or annotate `# lint: "
                                   "allow(dtype-policy)` with the why)")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _dotted(node.value) == "os.environ":
            self._emit(node, ENV_READ,
                       "os.environ[...] outside utils/flags.py — route "
                       "through flags.env() so the env-var inventory "
                       "stays complete")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if mod.startswith("jax.experimental.shard_map") or (
            mod in ("jax", "jax.experimental")
            and any(a.name == "shard_map" for a in node.names)
        ):
            self._emit(node, RAW_SHARD_MAP,
                       f"importing shard_map from {mod} — use "
                       "parallel/comm.compat_shard_map (the one version "
                       "shim)")
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name.startswith("jax.experimental.shard_map"):
                if a.asname:
                    self._sm_aliases.add(a.asname)
                self._emit(node, RAW_SHARD_MAP,
                           f"importing {a.name} — use parallel/comm."
                           "compat_shard_map (the one version shim)")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        broad = node.type is None or (
            isinstance(node.type, ast.Name) and node.type.id == "Exception"
        )
        if broad:
            self._emit(node, BROAD_EXCEPT,
                       "bare `except Exception` — narrow to the concrete "
                       "class(es), or annotate `# lint: allow(broad-"
                       "except)` with a one-line justification")
        self.generic_visit(node)


def _rel(path: str, root: str | None) -> str:
    if root:
        try:
            return os.path.relpath(path, root)
        except ValueError:
            pass
    return path


def rules_for(rel: str):
    """Rule set by tree position (see module docstring)."""
    top = rel.replace(os.sep, "/").split("/", 1)[0]
    if top in ("tools", "tests", "scripts"):
        return HARNESS_RULES
    return LIBRARY_RULES


def lint_file(path: str, rules=None, root: str | None = None):
    """Lint one file. `rules=None` selects by tree position. Returns
    (violations, None) or ([], error_string) on a parse failure."""
    rel = _rel(path, root)
    rules = rules_for(rel) if rules is None else rules
    norm = rel.replace(os.sep, "/")
    rules = set(rules)

    def matches(f: str) -> bool:
        # path-component boundary, never a bare suffix: `webcli.py` must
        # not inherit `cli.py`'s exemption
        return norm == f or norm.endswith("/" + f)

    # module-purpose exemptions (the rule's target behaviour IS the file)
    if any(matches(f) for f in ENV_ACCESSOR_FILES):
        rules.discard(ENV_READ)
    if any(matches(f) for f in SHARD_MAP_HOME_FILES):
        rules.discard(RAW_SHARD_MAP)
    if any(matches(f) for f in PRINT_EXEMPT_FILES):
        rules.discard(PRINT_CALL)
    # dtype-policy scopes to the solver/ops trees by directory component
    comps = norm.split("/")[:-1]
    if not any(d in comps for d in DTYPE_POLICY_DIRS):
        rules.discard(DTYPE_POLICY)
    try:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as exc:
        return [], f"{rel}: unparseable ({exc})"
    linter = _Linter(path, rel, source, rules)
    linter.visit(tree)
    return sorted(linter.out, key=lambda v: (v.path, v.line)), None


def lint_tree(root: str, subdirs=("pampi_tpu", "tools", "tests")):
    """Lint every .py under root/<subdirs>. Returns (violations, errors)."""
    violations: list[Violation] = []
    errors: list[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                vs, err = lint_file(os.path.join(dirpath, fn), root=root)
                violations += vs
                if err:
                    errors.append(err)
    return violations, errors


def env_inventory(root: str) -> dict[str, list[str]]:
    """The static env-var inventory: every string literal read through
    `flags.env(...)` / `flags._on(...)` / `flags.set_default(...)` in the
    library tree, mapped to its `file:line` registration sites. The
    env-read rule makes this complete by construction."""
    inv: dict[str, list[str]] = {}
    base = os.path.join(root, "pampi_tpu")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                tree = ast.parse(open(path, encoding="utf-8").read(),
                                 filename=path)
            except (OSError, SyntaxError):
                continue
            rel = _rel(path, root)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _dotted(node.func)
                if not (name.endswith(".env") or name.endswith(".set_default")
                        or name.endswith("._on") or name in (
                            "env", "set_default", "_on")):
                    continue
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    var = node.args[0].value
                    if var.startswith("PAMPI_"):
                        inv.setdefault(var, []).append(
                            f"{rel}:{node.lineno}")
    return inv
