"""Tracecheck: static contract checking for the pampi-tpu tree.

The codebase rests on implicit contracts that no single runtime test can
guard globally — fused chunks lower to a pinned number of Pallas launches,
flag-off builds trace byte-identical programs, deep-halo kernels never
read past their declared halo, env vars are read only through the
`utils/flags.py` accessor, `shard_map` only through
`parallel/comm.compat_shard_map`. This package checks them statically
(trace + analyze, no device execution), the same role compile-time
footprint/race analysis plays for MPI stencil codes:

  jaxprcheck  trace every solver family's chunk under the dispatch matrix
              and assert launch counts, host-callback absence, dtype
              discipline, metrics arity, and jaxpr-hash identity against
              the committed CONTRACTS.json baseline
  halocheck   derive each stencil kernel's static access footprint (the
              dependency cone of owned outputs on the exchanged block)
              and compare against the declared halo depths
  commcheck   census the collectives of every traced chunk (counts,
              ppermute message multiset, per-step halo traffic bytes)
              against the env-keyed `comm` section of CONTRACTS.json and
              the solvers' own static halo-byte records
  palcheck    check every pallas_call's block tiling, static VMEM
              footprint, grid×index-map bounds, and aliasing hazards —
              the Mosaic compile-time failures, decided on CPU
  astlint     repo-specific AST rules with file:line diagnostics and
              inline `# lint: allow(<rule>)` escapes

Driver: `tools/lint.py` (all passes; `--update` regenerates the
CONTRACTS.json baseline, configs + comm sections). Tier-1 coverage:
tests/test_analysis.py.
"""

import importlib

__all__ = ["astlint", "commcheck", "halocheck", "jaxprcheck", "palcheck"]


def __getattr__(name):
    # lazy: astlint is pure stdlib and must stay importable (and fast)
    # without pulling jax in through the trace-analysis siblings
    if name in __all__:
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
