"""Halo footprint analyzer: does any stencil kernel read past its halo?

The distributed-correctness bug class this guards (the stencil-code
analog of a race): a shard's kernel computes its OWNED cells from the
extended block one depth-H exchange filled, so every input cell in the
dependency cone of an owned output must lie within H layers of the owned
region — a read one layer deeper consumes a stale/unexchanged value and
the distributed trajectory silently diverges from the sequential one.
Past contracts of exactly this shape: `stencil2d.ca_halo(n) = 2n` (+1 on
ragged layouts — the dead-shard wall-ghost refresh), and the fused PRE
kernels' 3-layer validity chain (`ops/ns2d_fused.FUSE_CHAIN`).

Method — the static access footprint, derived from the program itself:
each checked kernel is a pure jnp function (the CA iteration bodies are
the importable production functions; the Pallas PRE/POST chains are
composed here from the SAME window formulas the kernels store —
`apply_wall_bcs_2d`, `fg_predictor_terms`, ... — in the kernels' own
order). We linearize it once at random inputs (one `jax.grad` of a
random projection of the owned outputs) and read the dependency cone off
the gradient's nonzero pattern: grad[cell] != 0  ⟺  that input cell
influences some owned output. Masked branches (`jnp.where` wall gates,
flag multiplies) are handled exactly — a masked-off read is NOT a
dependency — which pure index-offset interval analysis cannot do (a
`where(wall, roll(p), p)` would blow its bounding box to the whole
array). With float64 random inputs an existing dependency cancelling to
an exact numerical zero has probability ~0; the mutation tests (a seeded
under-halo declaration, an over-wide stencil) pin that the detector
actually fires.

The registry (`standard_entries()`) carries, per kernel: the function,
the owned-region box, and the DECLARED halo (read from the same source
the production dispatch uses — `ca_halo`, `FUSE_CHAIN`). `check_all()`
re-measures and reports `footprint > declared` as an error with a
file:line anchor at the kernel's source.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass

from .astlint import Violation

RULE = "halo-footprint"


def _anchor(obj) -> tuple[str, int]:
    """file:line of a function/module object for diagnostics."""
    try:
        path = inspect.getsourcefile(obj) or "<unknown>"
        line = inspect.getsourcelines(obj)[1]
        return path, line
    except (OSError, TypeError):
        return getattr(obj, "__file__", "<unknown>"), 1

@dataclass
class HaloEntry:
    """One checked kernel: `fn(*arrays)` -> array or tuple of arrays, all
    inputs/outputs in ONE index frame; `owned` is the box (tuple of
    slices) of cells the shard owns in that frame; `declared` the halo
    depth the production dispatch exchanges for it; `anchor` the source
    location blamed on violation."""

    name: str
    fn: object
    in_shapes: tuple
    owned: tuple
    declared: int
    anchor: tuple = ("<unknown>", 1)
    # indices of inputs whose footprint participates in the check (e.g.
    # scalar dt operands are excluded); default: every array input
    checked_inputs: tuple = ()
    note: str = ""


def _beyond_owned_depth(nonzero, owned) -> int:
    """Max per-axis distance of a True cell beyond the owned box (0 when
    every dependency is owned)."""
    import numpy as np

    idx = np.argwhere(nonzero)
    if idx.size == 0:
        return 0
    depth = 0
    for ax, sl in enumerate(owned):
        lo, hi, _ = sl.indices(nonzero.shape[ax])
        below = lo - idx[:, ax]
        above = idx[:, ax] - (hi - 1)
        depth = max(depth, int(np.maximum(below, above).clip(min=0).max()))
    return depth


def measure(entry: HaloEntry, seed: int = 0) -> dict[int, int]:
    """The access footprint: per checked input, the max depth (in cells)
    beyond the owned box that influences any owned output. One
    linearization — see the module docstring."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    # float64 when x64 is on (the tools/lint.py and test harness default);
    # f32 otherwise — either way an existing dependency cancelling to an
    # exact zero under random N(0,1) inputs has probability ~0
    xs = [jnp.asarray(rng.standard_normal(s)) for s in entry.in_shapes]
    checked = entry.checked_inputs or tuple(range(len(xs)))

    # one scalar projection of the owned outputs with random weights: its
    # gradient's nonzero pattern is the union dependency cone
    weights = None

    def projected(*inp):
        nonlocal weights
        out = entry.fn(*inp)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        outs = [o for o in outs if getattr(o, "ndim", 0) == len(entry.owned)]
        if weights is None:
            weights = [
                jnp.asarray(rng.standard_normal(o[tuple(entry.owned)].shape))
                for o in outs
            ]
        acc = 0.0
        for o, r in zip(outs, weights):
            acc = acc + jnp.vdot(o[tuple(entry.owned)], r.astype(o.dtype))
        return acc

    grads = jax.grad(projected, argnums=checked)(*xs)
    out = {}
    for i, g in zip(checked, grads):
        out[i] = _beyond_owned_depth(np.asarray(g) != 0.0, entry.owned)
    return out


def check_entry(entry: HaloEntry, seed: int = 0) -> list[Violation]:
    """footprint > declared  ->  one violation per offending input."""
    vs = []
    path, line = entry.anchor
    for i, depth in measure(entry, seed=seed).items():
        if depth > entry.declared:
            vs.append(Violation(
                path, line, RULE,
                f"{entry.name}: input #{i} read footprint reaches "
                f"{depth} cells beyond the owned region but the declared "
                f"halo is {entry.declared} — an under-halo read consumes "
                f"stale/unexchanged data on distributed shards"
                + (f" ({entry.note})" if entry.note else ""),
            ))
    return vs


# ---------------------------------------------------------------------------
# the production registry
# ---------------------------------------------------------------------------

def _ca2d_entry(n: int, ragged: bool = False) -> HaloEntry:
    """stencil2d.ca_rb_iters at CA depth n: the depth-ca_halo(n) exchange
    must cover n fused red-black iterations. `ragged=True` builds the
    dead-trailing-shard geometry whose wall-ghost refresh consumes the one
    extra layer ca_halo ships there."""
    from ..parallel import stencil2d as s2

    jl = il = 6
    jmax = imax = 30
    H = s2.ca_halo(n, ragged=ragged)
    if ragged:
        # the shard whose FIRST owned row is the wall-ghost row
        # gj == jmax+1 (every later row dead): its Neumann refresh after
        # 2n half-sweeps reads the innermost halo cell (ca_halo docstring)
        joff, ioff = jmax, 8
    else:
        joff, ioff = 8, 8
    masks = s2.ca_masks(jl, il, H, jmax, imax, float, joff=joff, ioff=ioff)
    shape = (jl + 2 * H, il + 2 * H)

    def fn(p, rhs):
        return s2.ca_rb_iters(p, rhs, n, masks, 0.45, 1.0, 1.3)[0]

    owned = (slice(H, H + jl), slice(H, H + il))
    return HaloEntry(
        name=f"stencil2d.ca_rb_iters[n={n}{', ragged' if ragged else ''}]",
        fn=fn,
        in_shapes=(shape, shape),
        owned=owned,
        declared=H,
        anchor=_anchor(s2.ca_rb_iters),
        note=f"declared = ca_halo({n}, ragged={ragged}) = {H}",
    )


def _ca3d_entry(n: int) -> HaloEntry:
    from ..parallel import stencil2d as s2
    from ..parallel import stencil3d as s3

    kl = jl = il = 4
    gmax = 20
    H = s2.ca_halo(n)
    masks = s3.ca_masks_3d(kl, jl, il, H, gmax, gmax, gmax, float,
                           koff=6, joff=6, ioff=6)
    shape = (kl + 2 * H, jl + 2 * H, il + 2 * H)

    def fn(p, rhs):
        return s3.ca_rb_iters_3d(p, rhs, n, masks, 0.45, 1.0, 1.3, 0.8)[0]

    owned = (slice(H, H + kl), slice(H, H + jl), slice(H, H + il))
    return HaloEntry(
        name=f"stencil3d.ca_rb_iters_3d[n={n}]",
        fn=fn,
        in_shapes=(shape, shape),
        owned=owned,
        declared=H,
        anchor=_anchor(s3.ca_rb_iters_3d),
        note=f"declared = ca_halo({n}) = {H}",
    )


def _pre2d_entry(shard: str, obstacles: bool = False,
                 size: int = 6) -> HaloEntry:
    """The fused 2-D PRE chain (deep-halo kernel): the same window
    formulas _pre_kernel stores, in its order — wall BCs, special BC,
    obstacle velocity BC, F/G predictor, wall fixups, obstacle F/G mask,
    RHS with the local-interior clip. The dependency cone of the outputs
    restricted to the shard's OWNED interior must stay within FUSE_CHAIN
    layers — the per-step validity budget the deep exchange covers.
    `size` widens the shard (the overlap-interior entry needs one wide
    enough for a non-empty interior region)."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops import ns2d as ops
    from ..ops import ns2d_fused as nf

    jl = il = size
    gjmax = gimax = max(24, 2 * size)
    ext_pad = nf.FUSE_DEEP_HALO - 1
    rows = jl + 2 + 2 * ext_pad
    cols = il + 2 + 2 * ext_pad
    offsets = {
        "interior": (8, 8),
        "corner_lo": (0, 0),
        "wall_hi": (gjmax - jl, 8),
    }
    joff, ioff = offsets[shard]
    a_j = jnp.arange(rows, dtype=jnp.int32)[:, None] * jnp.ones(
        (1, cols), jnp.int32)
    a_i = jnp.arange(cols, dtype=jnp.int32)[None, :] * jnp.ones(
        (rows, 1), jnp.int32)
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff
    bc = (nf.NOSLIP, nf.NOSLIP, nf.NOSLIP, nf.NOSLIP)
    dt, re, gamma = 0.01, 10.0, 0.9
    dx, dy = 1.0 / gimax, 1.0 / gjmax
    interior = (gj >= 1) & (gj <= gjmax) & (gi >= 1) & (gi <= gimax)
    rows_m = (gj >= 1) & (gj <= gjmax)
    cols_m = (gi >= 1) & (gi <= gimax)
    local_int = (
        (a_j >= ext_pad + 1) & (a_j <= ext_pad + jl)
        & (a_i >= ext_pad + 1) & (a_i <= ext_pad + il)
    )
    fl = None
    if obstacles:
        # a deterministic obstacle block straddling the owned low edge so
        # every term of the obstacle BC's mirror stencil is live. The
        # measured footprint comes out at 2 (< FUSE_CHAIN = 3): the chain
        # budget charges each stage ≤1 conservatively, but RHS reads F/G
        # only same-row/low-side and G reads u only northward, so no
        # composed path actually consumes all three layers — the declared
        # halo has one layer of genuine slack, which this entry records
        # (and which a widened stencil would eat before ever corrupting a
        # distributed run).
        flag = np.ones((rows, cols))
        pj, pi = ext_pad - 1, ext_pad + 3
        flag[pj:pj + 3, pi:pi + 2] = 0.0
        fl = jnp.asarray(flag)

    def fn(u, v):
        u, v = nf.apply_wall_bcs_2d(u, v, gj, gi, bc, gjmax, gimax)
        u = nf.apply_special_bc_2d(u, gj, gi, "dcavity", gjmax, gimax,
                                   dy, 1.0, u.dtype, u.dtype)
        if obstacles:
            u_face, v_face = nf._obstacle_faces(fl, gj, gi, gjmax, gimax)
            u, v = nf.apply_obstacle_velocity_bc_window(
                u, v, fl, u_face, v_face)
        f_full, g_full = ops.fg_predictor_terms(
            u, v, dt, re, 0.0, 0.0, gamma, dx, dy)
        f = jnp.where(interior, f_full, 0.0)
        g = jnp.where(interior, g_full, 0.0)
        f = jnp.where((gi == 0) & rows_m, u, f)
        f = jnp.where((gi == gimax) & rows_m, u, f)
        g = jnp.where((gj == 0) & cols_m, v, g)
        g = jnp.where((gj == gimax) & cols_m, v, g)
        if obstacles:
            one = jnp.ones((), u.dtype)
            f = u_face * f + (one - u_face) * u
            g = v_face * g + (one - v_face) * v
        rhs = jnp.where(
            interior & local_int, ops.rhs_terms(f, g, dt, dx, dy), 0.0)
        return u, v, f, g, rhs

    owned = (slice(ext_pad + 1, ext_pad + 1 + jl),
             slice(ext_pad + 1, ext_pad + 1 + il))
    return HaloEntry(
        name=("ns2d_fused.PRE"
              f"[{shard}{', obstacles' if obstacles else ''}]"),
        fn=fn,
        in_shapes=((rows, cols), (rows, cols)),
        owned=owned,
        declared=nf.FUSE_FOOTPRINT,
        anchor=_anchor(nf.make_fused_pre_2d),
        note="declared = FUSE_FOOTPRINT (the deep exchange ships "
             "FUSE_DEEP_HALO = footprint + 1 — zero slack: a widened "
             "chain must bump both)",
    )


def _post2d_entry() -> HaloEntry:
    """The fused 2-D POST chain: adaptUV's p reads must stay inside the
    exchanged halo-1 ring of the plain extended block."""
    from ..ops import ns2d as ops
    from ..ops import ns2d_fused as nf

    jl = il = 8
    shape = (jl + 2, il + 2)

    def fn(f, g, p):
        return ops.adapt_terms(f, g, p, 0.01, 1.0 / il, 1.0 / jl)

    owned = (slice(1, 1 + jl), slice(1, 1 + il))
    return HaloEntry(
        name="ns2d_fused.POST[adapt_terms]",
        fn=fn,
        in_shapes=(shape, shape, shape),
        owned=owned,
        declared=1,
        anchor=_anchor(nf.make_fused_post_2d),
        note="declared = 1 (plain extended block, halo-1 exchange)",
    )


def _pre3d_entry(size: int = 4) -> HaloEntry:
    """The fused 3-D PRE chain (same structure as _pre2d_entry, on a
    dcavity3d lid shard) against the shared FUSE_CHAIN declaration."""
    import jax.numpy as jnp

    from ..ops import ns3d as ops3
    from ..ops import ns3d_fused as nf3
    from ..ops.ns3d import FACES

    kl = jl = il = size
    gmax = max(12, 3 * size)
    ext_pad = nf3.FUSE_DEEP_HALO - 1
    ext = (kl + 2 + 2 * ext_pad, jl + 2 + 2 * ext_pad,
           il + 2 + 2 * ext_pad)
    koff, joff, ioff = 4, gmax - jl, 4  # lid (j-hi) shard
    a_k = jnp.arange(ext[0], dtype=jnp.int32)[:, None, None] + jnp.zeros(
        ext, jnp.int32)
    a_j = jnp.arange(ext[1], dtype=jnp.int32)[None, :, None] + jnp.zeros(
        ext, jnp.int32)
    a_i = jnp.arange(ext[2], dtype=jnp.int32)[None, None, :] + jnp.zeros(
        ext, jnp.int32)
    gk = a_k - ext_pad + koff
    gj = a_j - ext_pad + joff
    gi = a_i - ext_pad + ioff
    bcs = {face: nf3.NOSLIP for face in FACES}
    dt, re, gamma = 0.01, 10.0, 0.9
    dx = dy = dz = 1.0 / gmax
    interior = (
        (gk >= 1) & (gk <= gmax) & (gj >= 1) & (gj <= gmax)
        & (gi >= 1) & (gi <= gmax)
    )
    tan_k = (gk >= 1) & (gk <= gmax)
    tan_j = (gj >= 1) & (gj <= gmax)
    tan_i = (gi >= 1) & (gi <= gmax)
    local_int = (
        (a_k >= ext_pad + 1) & (a_k <= ext_pad + kl)
        & (a_j >= ext_pad + 1) & (a_j <= ext_pad + jl)
        & (a_i >= ext_pad + 1) & (a_i <= ext_pad + il)
    )

    def fn(u, v, w):
        u, v, w = nf3.apply_wall_bcs_3d(
            u, v, w, gk, gj, gi, dict(bcs), gmax, gmax, gmax)
        u = nf3.apply_special_bc_3d(u, gk, gj, gi, "dcavity",
                                    gmax, gmax, gmax)
        f_full, g_full, h_full = ops3.fgh_predictor_terms(
            u, v, w, dt, re, 0.0, 0.0, 0.0, gamma, dx, dy, dz,
            sh=nf3._win_shift)
        f = jnp.where(interior, f_full, 0.0)
        g = jnp.where(interior, g_full, 0.0)
        hh = jnp.where(interior, h_full, 0.0)
        f = jnp.where(((gi == 0) | (gi == gmax)) & tan_k & tan_j, u, f)
        g = jnp.where(((gj == 0) | (gj == gmax)) & tan_k & tan_i, v, g)
        hh = jnp.where(((gk == 0) | (gk == gmax)) & tan_j & tan_i, w, hh)
        rhs = jnp.where(
            interior & local_int,
            ops3.rhs_terms_3d(f, g, hh, dt, dx, dy, dz, sh=nf3._win_shift),
            0.0,
        )
        return u, v, w, f, g, hh, rhs

    owned = (slice(ext_pad + 1, ext_pad + 1 + kl),
             slice(ext_pad + 1, ext_pad + 1 + jl),
             slice(ext_pad + 1, ext_pad + 1 + il))
    return HaloEntry(
        name="ns3d_fused.PRE[lid shard]",
        fn=fn,
        in_shapes=(ext, ext, ext),
        owned=owned,
        declared=nf3.FUSE_FOOTPRINT,
        anchor=_anchor(nf3.make_fused_pre_3d),
        note="declared = FUSE_FOOTPRINT (the deep exchange ships "
             "FUSE_DEEP_HALO = footprint + 1 — zero slack)",
    )


def _overlap_box(local_extents, ext_pad: int, rim: int):
    """The overlap interior region (parallel/overlap.interior_slices)
    mapped into a PRE entry's deep-block index frame."""
    from ..parallel.overlap import interior_slices

    return tuple(
        slice(s.start + ext_pad, s.stop + ext_pad)
        for s in interior_slices(local_extents, rim)
    )


def overlap_interior_entry_2d(smuggle: int = 0,
                              rim: int | None = None) -> HaloEntry:
    """The overlapped 2-D PRE's INTERIOR half: the same chain, owned box
    restricted to the interior-merge region (parallel/overlap.py). The
    declared budget is `rim - 1`: the exchanged strips start one layer
    outside the extended block's interior, so a cone reaching further
    than rim - 1 from the interior box touches a strip — the stale
    double buffer would be consumed. With the production OVERLAP_RIM
    (= FUSE_FOOTPRINT + 1) the budget equals the measured footprint
    exactly (zero slack). `smuggle > 0` (mutation-test hook) forges a
    read `smuggle` layers past the footprint; `rim` below OVERLAP_RIM
    forges a dropped/too-tight grid restriction — a region plan whose
    interior band leaks toward the strips fails here with the kernel's
    file:line."""
    import jax.numpy as jnp

    from ..ops import ns2d_fused as nf

    jl = il = 12
    base = _pre2d_entry("interior", size=jl)
    ext_pad = nf.FUSE_DEEP_HALO - 1
    rim = nf.OVERLAP_RIM if rim is None else rim
    owned = _overlap_box((jl, il), ext_pad, rim)
    fn = base.fn
    if smuggle:
        base_fn = base.fn

        def fn(u, v):
            u = u + 1e-3 * jnp.roll(u, nf.FUSE_FOOTPRINT + smuggle, axis=0)
            return base_fn(u, v)

    return HaloEntry(
        name="ns2d_fused.PRE[overlap interior half"
             + (", smuggled]" if smuggle else f", rim={rim}]"
                if rim != nf.OVERLAP_RIM else "]"),
        fn=fn,
        in_shapes=base.in_shapes,
        owned=owned,
        declared=rim - 1,
        anchor=base.anchor,
        note="overlap interior region: cone must exclude the exchanged "
             "deep strips (stale-buffer safety, parallel/overlap.py)",
    )


def overlap_interior_entry_3d(smuggle: int = 0,
                              rim: int | None = None) -> HaloEntry:
    """The 3-D twin of overlap_interior_entry_2d."""
    import jax.numpy as jnp

    from ..ops import ns3d_fused as nf3

    size = 8
    base = _pre3d_entry(size=size)
    ext_pad = nf3.FUSE_DEEP_HALO - 1
    rim = nf3.OVERLAP_RIM if rim is None else rim
    owned = _overlap_box((size, size, size), ext_pad, rim)
    fn = base.fn
    if smuggle:
        base_fn = base.fn

        def fn(u, v, w):
            u = u + 1e-3 * jnp.roll(u, nf3.FUSE_FOOTPRINT + smuggle,
                                    axis=0)
            return base_fn(u, v, w)

    return HaloEntry(
        name="ns3d_fused.PRE[overlap interior half"
             + (", smuggled]" if smuggle else "]"),
        fn=fn,
        in_shapes=base.in_shapes,
        owned=owned,
        declared=rim - 1,
        anchor=base.anchor,
        note="overlap interior region: cone must exclude the exchanged "
             "deep strips (stale-buffer safety, parallel/overlap.py)",
    )


def depth_capture_violations(extents, depth: int, inner: int) -> list:
    """The widened footprint of the per-tier depth capture (ISSUE 17,
    `comm.capture_axis_strips`), re-derived from first principles and
    checked against the production slice arithmetic's geometry. The
    capture pads the 1-ghost extended block by depth-1, exchanges the
    padded block at depth H on the slow axis, and crops two inner-deep
    paste-ready strips — four facts must hold for the strips to carry
    only VALID donor cells:

      1. the shipped depth-H edge window, mapped back into the donor's
         extended frame, is [e-H+1, e+1) — it stays inside the donor's
         owned+ghost cells iff H <= e (the `resolve_exchange_depth`
         shard-extent floor; a deeper capture would ship pad zeros);
      2. the receiver's crop window [H-inner, H) lies inside the
         received depth block iff inner <= H (the capture's own
         ValueError guard);
      3. the paste windows [0, inner) and [n-inner, n) exactly tile
         the deep block's ghost ring (n = e + 2*inner), overlapping no
         owned cell;
      4. the capture's ppermute message shape equals
         `halo_strip_shapes(extents, H)` on the captured axis — the
         commcheck census and the byte accounting key the amortized
         exchange by exactly that strip.

    Gradient entries cannot measure this (the exchange is mesh-bound:
    ppermute needs an axis binding `measure()` cannot provide); the
    runtime twin is tools/chunk_smoke.py's bitwise pin of the step-0
    paste against a fresh deep exchange."""
    from ..parallel import comm as pcomm

    vs = []
    path, line = _anchor(pcomm.capture_axis_strips)

    def emit(msg):
        vs.append(Violation(
            path, line, RULE,
            f"capture_axis_strips[extents={tuple(extents)}, depth={depth}, "
            f"inner={inner}]: {msg}"))

    for ax, e in enumerate(extents):
        # (1) the shipped window in the donor frame
        lo_cell = e - depth + 1
        if lo_cell < 1:
            emit(f"axis {ax}: the depth-{depth} edge window starts at cell "
                 f"{lo_cell} of the donor's extended block — outside the "
                 f"owned cells [1, {e}] when the shard extent {e} < depth, "
                 "so the capture would ship ghost/pad contents "
                 "(resolve_exchange_depth must refuse this geometry)")
        # (2) the crop window
        if inner > depth:
            emit(f"crop window [{depth - inner}, {depth}) underruns the "
                 f"received depth block — inner {inner} > depth {depth}")
        # (3) the paste ring tiling
        n = e + 2 * inner
        if inner * 2 > n:
            emit(f"axis {ax}: paste windows [0, {inner}) and "
                 f"[{n - inner}, {n}) overlap an owned cell of the "
                 f"{n}-deep block")
        # (4) the census strip geometry
        want = pcomm.halo_strip_shapes(extents, depth)[ax]
        widened = tuple(
            depth if a == ax else extents[a] + 2 * depth
            for a in range(len(extents)))
        if want != widened:
            emit(f"axis {ax}: the widened capture strip {widened} drifted "
                 f"from halo_strip_shapes(extents, {depth}) = {want} — "
                 "the commcheck census would mis-key the amortized "
                 "exchange")
    return vs


def standard_entries() -> list:
    """The production registry: every deep-halo contract the dispatch
    layer relies on. Kept cheap (tiny blocks, one linearization each) so
    tier-1 and `make lint` both run it."""
    return [
        _ca2d_entry(1),
        _ca2d_entry(2),
        _ca2d_entry(1, ragged=True),
        _ca3d_entry(1),
        _pre2d_entry("interior"),
        _pre2d_entry("corner_lo"),
        _pre2d_entry("wall_hi"),
        _pre2d_entry("interior", obstacles=True),
        _post2d_entry(),
        _pre3d_entry(),
        overlap_interior_entry_2d(),
        overlap_interior_entry_3d(),
    ]


def pre_chain_footprint(seed: int = 0) -> int:
    """The MEASURED access footprint of the fused PRE chains (max over
    the registry's PRE entries and inputs). Since the ROADMAP
    carried-forward shrink landed, this IS the declaration:
    `FUSE_FOOTPRINT` pins it and `FUSE_DEEP_HALO = FUSE_FOOTPRINT + 1`
    ships exactly one strip layer beyond it (the extended ghost ring) —
    zero slack, so a chain edit that widens any composed read path
    fails the PRE entries loudly before a distributed run can consume
    stale halos. tests/test_analysis.py pins the measured value against
    the declaration."""
    depth = 0
    for entry in standard_entries():
        if ".PRE" not in entry.name or "[overlap" in entry.name:
            # the overlap-interior entries re-check the SAME chain on a
            # restricted box; including them would double-count
            continue
        depth = max(depth, max(measure(entry, seed=seed).values()))
    return depth


def check_all(entries=None, seed: int = 0) -> list[Violation]:
    vs: list[Violation] = []
    for entry in (standard_entries() if entries is None else entries):
        vs += check_entry(entry, seed=seed)
    if entries is None:
        # the per-tier depth capture at the matrix geometry
        # (jaxprcheck ns2d_dist_depth: 16^2 on (2,2), i=dcn at H=4)
        from ..ops import ns2d_fused as nf

        vs += depth_capture_violations((8, 8), 4, nf.FUSE_DEEP_HALO)
    return vs
