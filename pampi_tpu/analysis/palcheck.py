"""Pallas kernel-resource checker: would this `pallas_call` compile and
fit on a TPU core?

The bug class this guards: a VMEM-overflowing scratch buffer, a mistiled
block, or an out-of-bounds index map in a Pallas kernel fails only at
Mosaic compile time ON A TPU — which this container does not have. Every
such failure found during the on-chip campaign so far (the tblock
feasibility guard, the quarters VMEM fallback, the 128-lane padding
convention) is statically decidable from the traced program, so this pass
decides them at lint time, on CPU, over the same `jaxprcheck`
trace matrix the launch-count contract uses plus standalone large-grid
kernel builds (`extra_entries`) where the grids are big enough to
actually partition into blocks.

Per `pallas_call` eqn (all data read off `grid_mapping` — block shapes,
index maps, memory spaces — and the kernel jaxpr's scratch operands):

  tiling       blocks that PARTITION an array dimension (block extent <
               array extent) must be multiples of the dtype tile
               granularity in the last two dims — lane 128 always,
               sublane 8/16/32 by itemsize (f32 (8,128), bf16 (16,128),
               int8 (32,128)). Full-extent blocks are exempt: Mosaic
               pads a whole-array window, but a misaligned PARTITIONED
               block re-tiles every grid step.
  vmem budget  static per-launch footprint: block windows bound to VMEM
               (double-buffered when the grid pipelines, i.e. >1 step)
               plus VMEM scratch, against the kernel's own declared
               `vmem_limit_bytes` (falling back to the repo-wide
               `ops/sor_pallas.VMEM_LIMIT_BYTES`). `pl.ANY` operands
               live in HBM and are charged nothing — their windows enter
               via the explicit scratch buffers the kernel DMAs into.
  index bounds grid × index map must stay in-bounds of each operand:
               every grid point's block start (Blocked semantics:
               index × block shape) must land inside the array (the
               final block may overhang — Mosaic masks it). Index maps
               are evaluated concretely per grid point; maps that read
               scalar-prefetch operands with nontrivial arithmetic are
               reported unevaluable rather than guessed at.
  aliasing     `input_output_aliases` pairs must window the SAME
               geometry (equal array shape/dtype, block shape, index
               map), and a donated input buffer must not also be read
               through another operand of the same call — the classic
               use-after-donation hazard.

Diagnostics carry the kernel's own file:line (from the pallas_call's
`name_and_src_info`), so a violation points at the kernel source, not at
the solver that dispatched it.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from .astlint import Violation
from .jaxprcheck import iter_eqns

RULE_TILE = "pallas-tile"
RULE_VMEM = "pallas-vmem"
RULE_OOB = "pallas-index-oob"
RULE_ALIAS = "pallas-alias"

# enumerate the full grid up to this many points; beyond it, check the
# corner/edge sample (first/middle/last per dim) — index maps are affine
# in practice, so extremes catch sign/offset errors
GRID_ENUM_LIMIT = 4096

_SRC_RE = re.compile(r"at (.+?):(\d+)")


def min_tile(dtype) -> tuple[int, int]:
    """TPU native tile granularity (sublane, lane) by dtype width: f32
    (8, 128); second-to-last dim doubles as the dtype narrows."""
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    return {2: 16, 1: 32}.get(itemsize, 8), 128


def block_extents(bm) -> tuple[int, ...]:
    """`block_shape` as plain element extents: squeezed dims (spelled
    `None` in the BlockSpec, a `Mapped` sentinel in the jaxpr param) are
    extent 1 — one element per grid step along that dim."""
    import numpy as np

    return tuple(int(s) if isinstance(s, (int, np.integer)) else 1
                 for s in bm.block_shape)


def _mspace(aval) -> str:
    """Normalized memory-space tag of a MemRef aval: 'vmem' (the default
    when unannotated), 'smem', 'any', 'semaphore_mem'."""
    ms = getattr(aval, "memory_space", None)
    if ms is None:
        return "vmem"
    return getattr(ms, "value", str(ms))


@dataclass
class Launch:
    """One pallas_call eqn, decoded for checking."""

    name: str
    path: str
    line: int
    grid: tuple
    in_mappings: list
    out_mappings: list
    scratch_avals: list
    aliases: tuple
    vmem_limit: int | None
    num_index_operands: int
    eqn: object

    @property
    def mappings(self):
        return self.in_mappings + self.out_mappings


def decode(eqn) -> Launch:
    gm = eqn.params["grid_mapping"]
    nsi = eqn.params["name_and_src_info"]
    m = _SRC_RE.search(getattr(nsi, "src_info", "") or "")
    path, line = (m.group(1), int(m.group(2))) if m else ("<unknown>", 1)
    kernel_jaxpr = eqn.params["jaxpr"]
    nscratch = gm.num_scratch_operands
    scratch = [v.aval for v in kernel_jaxpr.invars[len(kernel_jaxpr.invars)
                                                   - nscratch:]] \
        if nscratch else []
    mosaic = (eqn.params.get("compiler_params") or {}).get("mosaic", {})
    return Launch(
        name=nsi.name,
        path=path,
        line=line,
        grid=tuple(gm.grid),
        in_mappings=list(gm.block_mappings[:gm.num_inputs]),
        out_mappings=list(
            gm.block_mappings[gm.num_inputs:gm.num_inputs + gm.num_outputs]),
        scratch_avals=scratch,
        aliases=tuple(eqn.params.get("input_output_aliases") or ()),
        vmem_limit=mosaic.get("vmem_limit_bytes"),
        num_index_operands=gm.num_index_operands,
        eqn=eqn,
    )


def launches(jaxpr) -> list[Launch]:
    """Every pallas_call anywhere in the program (while/cond/pjit bodies
    included)."""
    return [decode(e) for e in iter_eqns(jaxpr)
            if e.primitive.name == "pallas_call"]


# ---------------------------------------------------------------------------
# index-map evaluation
# ---------------------------------------------------------------------------

def eval_index_map(closed, grid_idx: tuple) -> tuple | None:
    """Concrete block indices for one grid point, or None when the map
    depends on a scalar-prefetch operand through real arithmetic (then
    the coverage check abstains instead of guessing)."""
    import jax
    import jax.core

    jaxpr = closed.jaxpr
    n = len(grid_idx)
    if not jaxpr.eqns:
        env = dict(zip(jaxpr.invars[:n], grid_idx))
        out = []
        for v in jaxpr.outvars:
            if isinstance(v, jax.core.Literal):
                out.append(int(v.val))
            elif v in env:
                out.append(int(env[v]))
            else:
                return None
        return tuple(out)
    if len(jaxpr.invars) == n and all(
            getattr(v.aval, "shape", None) == () for v in jaxpr.invars):
        import numpy as np

        args = [np.asarray(i, dtype=v.aval.dtype)
                for v, i in zip(jaxpr.invars, grid_idx)]
        vals = jax.core.eval_jaxpr(jaxpr, closed.consts, *args)
        return tuple(int(v) for v in vals)
    return None


def grid_points(grid: tuple):
    """Every grid point when the grid is small; the first/middle/last
    corner sample otherwise."""
    import itertools

    total = 1
    for g in grid:
        total *= g
    if total <= GRID_ENUM_LIMIT:
        yield from itertools.product(*(range(g) for g in grid))
        return
    axes = [sorted({0, g // 2, g - 1}) for g in grid]
    yield from itertools.product(*axes)


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def vmem_estimate(launch: Launch) -> int:
    """Static per-launch VMEM bytes: VMEM-bound block windows (×2 when
    the grid pipelines — Mosaic double-buffers the automatic windows)
    plus VMEM scratch."""
    import numpy as np

    pipelined = 1
    for g in launch.grid:
        pipelined *= g
    buf = 2 if pipelined > 1 else 1
    total = 0
    for bm in launch.mappings:
        aval = bm.transformed_block_aval
        if _mspace(aval) != "vmem":
            continue
        n = 1
        for s in block_extents(bm):
            n *= s
        total += buf * n * np.dtype(aval.dtype).itemsize
    for aval in launch.scratch_avals:
        if _mspace(aval) != "vmem":
            continue
        n = 1
        for s in aval.shape:
            n *= int(s)
        total += n * np.dtype(aval.dtype).itemsize
    return total


def check_launch(launch: Launch, budget: int | None = None,
                 context: str = "") -> list[Violation]:
    """All four rules over one decoded pallas_call."""
    vs: list[Violation] = []
    where = f"{context}{launch.name}"

    def emit(rule, msg):
        vs.append(Violation(launch.path, launch.line, rule,
                            f"{where}: {msg}"))

    # --- tiling ---------------------------------------------------------
    for bm in launch.mappings:
        aval = bm.transformed_block_aval
        if _mspace(aval) not in ("vmem",):
            continue
        array = bm.array_shape_dtype.shape
        block = block_extents(bm)
        if len(block) < 2 or len(block) != len(array):
            continue
        # squeezed dims (extent 1 by iteration, not by windowing) are
        # the programmer's explicit layout choice — not a tiling bug
        squeezed = {d for d, s in enumerate(bm.block_shape)
                    if block[d] != s}
        sub, lane = min_tile(aval.dtype)
        for dim, need in ((len(block) - 1, lane), (len(block) - 2, sub)):
            if dim in squeezed:
                continue
            if block[dim] < array[dim] and block[dim] % need:
                emit(RULE_TILE,
                     f"operand {bm.origin}: block {block} partitions a "
                     f"{array} {aval.dtype} array but dim {dim} extent "
                     f"{block[dim]} is not a multiple of the tile "
                     f"granularity {need} — Mosaic re-tiles every grid "
                     "step (or refuses the layout)")
    # --- vmem budget ----------------------------------------------------
    est = vmem_estimate(launch)
    limit = budget if budget is not None else launch.vmem_limit
    if limit is None:
        from ..ops.sor_pallas import VMEM_LIMIT_BYTES

        limit = VMEM_LIMIT_BYTES
    if est > limit:
        emit(RULE_VMEM,
             f"static VMEM footprint {est} bytes ({est >> 20} MiB) "
             f"exceeds the budget {limit} bytes — blocks "
             f"{[block_extents(bm) for bm in launch.mappings if _mspace(bm.transformed_block_aval) == 'vmem']}, "
             f"scratch {[tuple(a.shape) for a in launch.scratch_avals if _mspace(a) == 'vmem']}"
             )
    # --- grid × index-map coverage --------------------------------------
    for bm in launch.mappings:
        array = bm.array_shape_dtype.shape
        block = block_extents(bm)
        if len(block) != len(array):
            continue
        for point in grid_points(launch.grid):
            idx = eval_index_map(bm.index_map_jaxpr, point)
            if idx is None:
                break  # unevaluable map: abstain for this operand
            if len(idx) != len(block):
                break
            for d, (i, b, a) in enumerate(zip(idx, block, array)):
                start = i * b
                if start < 0 or start >= a:
                    emit(RULE_OOB,
                         f"operand {bm.origin}: grid point {point} maps "
                         f"to block index {idx} — dim {d} starts at "
                         f"element {start}, outside the array extent "
                         f"{a} (stale/garbage window every launch)")
                    break
            else:
                continue
            break
    # --- aliasing -------------------------------------------------------
    seen_in, seen_out = set(), set()
    for i, o in launch.aliases:
        if i in seen_in or o in seen_out:
            emit(RULE_ALIAS,
                 f"alias ({i} -> {o}) re-donates an operand already "
                 "aliased — double donation")
        seen_in.add(i)
        seen_out.add(o)
        if i >= len(launch.in_mappings) or o >= len(launch.out_mappings):
            emit(RULE_ALIAS, f"alias ({i} -> {o}) out of operand range")
            continue
        bi, bo = launch.in_mappings[i], launch.out_mappings[o]
        same = (
            bi.array_shape_dtype.shape == bo.array_shape_dtype.shape
            and bi.array_shape_dtype.dtype == bo.array_shape_dtype.dtype
            and tuple(bi.block_shape) == tuple(bo.block_shape)
            and str(bi.index_map_jaxpr) == str(bo.index_map_jaxpr)
        )
        if not same:
            how = ("index maps differ"
                   if tuple(bi.block_shape) == tuple(bo.block_shape)
                   and bi.array_shape_dtype == bo.array_shape_dtype
                   else f"input block {tuple(bi.block_shape)} of "
                        f"{bi.array_shape_dtype.shape} vs output block "
                        f"{tuple(bo.block_shape)} of "
                        f"{bo.array_shape_dtype.shape}")
            emit(RULE_ALIAS,
                 f"alias ({i} -> {o}) windows differ ({how}) — the "
                 "donated buffer is rewritten through a different window "
                 "than it is read")
        # a donated input read through a SECOND operand of the same call
        invars = list(launch.eqn.invars)
        opvars = invars[launch.num_index_operands:]
        if i < len(opvars):
            donated = opvars[i]
            dups = [k for k, v in enumerate(opvars)
                    if v is donated and k != i]
            if dups:
                emit(RULE_ALIAS,
                     f"donated input #{i} is also read through operand(s) "
                     f"{dups} of the same call — use-after-donation")
    return vs


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

def extra_entries() -> list:
    """Standalone large-grid kernel builds: the production solve kernels
    at extents big enough that the grid actually partitions (the matrix
    configs trace at 16²/8³ where every launch collapses to one
    full-array block). Trace-only — nothing executes."""
    import jax
    import jax.numpy as jnp

    from ..ops import sor_pallas as sp

    out = []
    n = 512
    rb, br = sp.make_rb_iter_pallas(n, n, 1.0 / n, 1.0 / n, 1.7,
                                    jnp.float32, interpret=True)
    if rb is not None:
        p = jnp.zeros((sp.padded_rows(n, br, jnp.float32),
                       sp.padded_width(n)), jnp.float32)
        out.append(("sor_pallas.rb_iter[512²]", jax.make_jaxpr(rb)(p, p)))
    rb_t, br_t, h = sp.make_rb_iter_tblock(n, n, 1.0 / n, 1.0 / n, 1.7,
                                           jnp.float32, n_inner=4,
                                           interpret=True)
    if rb_t is not None:
        nblocks = -(-(n + 2) // br_t)
        pt = jnp.zeros((nblocks * br_t + 2 * h, sp.padded_width(n)),
                       jnp.float32)
        out.append(("sor_pallas.rb_iter_tblock[512²]",
                    jax.make_jaxpr(rb_t)(pt, pt)))
    rb_q, brq, hq = sp.make_rb_iter_tblock_quarters(
        n, n, 1.0 / n, 1.0 / n, 1.7, jnp.float32, n_inner=2,
        interpret=True)
    if rb_q is not None:
        pq = sp.pad_quarters(jnp.zeros((n + 2, n + 2), jnp.float32),
                             brq, hq)
        out.append(("sor_pallas.rb_iter_tblock_quarters[512²]",
                    jax.make_jaxpr(rb_q)(pq, pq)))
    from ..ops import sor3d_pallas as sp3

    m = 64
    rb_3, bk = sp3.make_rb_iter_tblock_3d(
        m, m, m, 1.0 / m, 1.0 / m, 1.0 / m, 1.7, jnp.float32,
        n_inner=1, interpret=True)
    if rb_3 is not None:
        p3 = sp3.pad_array_3d(jnp.zeros((m + 2, m + 2, m + 2),
                                        jnp.float32), bk, 1)
        out.append(("sor3d_pallas.rb_iter_tblock_3d[64³]",
                    jax.make_jaxpr(rb_3)(p3, p3)))
    return out


RULE_GRID = "pallas-grid-region"


def restricted_grid_entries():
    """The grid-restricted overlap PRE halves at a geometry where the
    bands actually differ from the full sweep (explicit block_rows — the
    matrix's 16² shards collapse to one block): builds the interior and
    boundary halves for a (P,1)-mesh shard plus the full-sweep control,
    and returns [(name, jaxpr, expected_grid_blocks, full_blocks), ...].
    Trace-only. The standard resource rules run over these launches too
    (`run`), and `restricted_grid_violations` pins that each half's grid
    covers only its region — fewer grid steps than the full sweep, and
    interior + boundary strictly below the 2x full-sweep count the
    restriction replaced."""
    import jax
    import jax.numpy as jnp

    from ..ops import ns2d_fused as nf
    from ..parallel import overlap as ovl
    from ..utils.params import Parameter

    jl = il = 40
    ext_pad = nf.FUSE_DEEP_HALO - 1
    param = Parameter(name="dcavity", imax=80, jmax=80)
    dt = jnp.float32
    kw = dict(jl=jl, il=il, ext_pad=ext_pad, block_rows=8, interpret=True)
    br, _h, wp, nb = nf.fused_deep_layout_2d(jl, il, dt, ext_pad,
                                             block_rows=8)
    plan = ovl.region_plan((jl, il), nf.OVERLAP_RIM, ext_pad, br, nb, wp,
                           (True, False))
    out = []
    for name, bands in (("interior", plan["int_bands"]),
                        ("boundary", plan["bnd_bands"]), ("full", None)):
        pre, pad, _unpad, _hh = nf.make_fused_pre_2d(
            param, 80, 80, 1.0 / 80, 1.0 / 80, dt, **kw, grid_bands=bands)
        z = pad(jnp.zeros((jl + 2 + 2 * ext_pad,) * 2, dt))
        offs = jnp.zeros((2,), jnp.int32)
        dt11 = jnp.full((1, 1), 0.01, dt)
        jx = jax.make_jaxpr(pre)(offs, dt11, z, z)
        expect = (sum(n for _, n in bands) if bands is not None else nb)
        out.append((f"ns2d_fused.PRE[restricted {name} half]", jx,
                    expect, nb))
    return out


def restricted_grid_violations() -> list[Violation]:
    """Grid-coverage pin for the restricted halves (see
    restricted_grid_entries): each half's Pallas grid must have exactly
    its band's block count, each below the full sweep, and the two
    halves summed strictly below 2x full — the acceptance contract of
    `tpu_overlap_restrict`."""
    entries = restricted_grid_entries()
    vs: list[Violation] = []
    halves = {}
    for name, jx, expect, full in entries:
        ls = launches(jx.jaxpr)
        if len(ls) != 1:
            vs.append(Violation("<restricted-grid>", 1, RULE_GRID,
                                f"{name}: expected 1 pallas_call, "
                                f"traced {len(ls)}"))
            continue
        got = ls[0].grid[0] if ls[0].grid else 0
        if got != expect:
            vs.append(Violation(ls[0].path, ls[0].line, RULE_GRID,
                                f"{name}: grid covers {got} blocks, the "
                                f"region plan declares {expect} (of "
                                f"{full} full-sweep blocks)"))
        if "full" not in name:
            halves[name] = got
    if len(halves) == 2 and entries:
        full = entries[0][3]
        if sum(halves.values()) >= 2 * full:
            vs.append(Violation(
                "<restricted-grid>", 1, RULE_GRID,
                f"restricted halves sweep {halves} blocks — not below "
                f"the 2x{full} full-sweep count they must beat"))
    return vs


RULE_SHAPECLASS = "shapeclass-waste"


def shapeclass_violations() -> list[Violation]:
    """The shape-class padding-waste contract (fleet/shapeclass.py,
    serving v2): for every class-eligible extent the rung ladder must be
    covering (class >= live), idempotent (a class maps to itself — a
    padded lane re-bucketed lands in the same compile), power-of-two
    above the floor, and BOUNDED — per-axis padded extent under 2x the
    live extent, so a 2-D class never burns more than WASTE_BOUND (4x)
    the live cells. Checked over the whole eligible range plus explicit
    rung-differing geometries; stateless, like every palcheck rule."""
    from ..fleet import shapeclass as sc

    where = "pampi_tpu/fleet/shapeclass.py"
    vs: list[Violation] = []
    for n in range(sc.MIN_CLASS_EXTENT, 4097):
        c = sc.class_extent(n)
        if c < n:
            vs.append(Violation(where, 1, RULE_SHAPECLASS,
                                f"class_extent({n}) = {c} < live"))
        if sc.class_extent(c) != c:
            vs.append(Violation(where, 1, RULE_SHAPECLASS,
                                f"rung {c} is not idempotent"))
        if c > sc.RUNG_FLOOR and (c & (c - 1)) != 0:
            vs.append(Violation(where, 1, RULE_SHAPECLASS,
                                f"rung {c} not a power of two"))
        if c + 2 >= 2 * (n + 2):
            vs.append(Violation(
                where, 1, RULE_SHAPECLASS,
                f"extent {n}: padded {c + 2} >= 2x live {n + 2} — "
                "per-axis waste bound broken"))
    # rung-differing 2-D geometries: the cells bound (the palcheck
    # contract ISSUE 14 names) must hold where the two axes land on
    # different rungs
    for grid in ((17, 33), (9, 129), (20, 48), (16, 16), (255, 9),
                 (100, 100), (8, 4096)):
        w = sc.padding_waste(grid)
        if w >= sc.WASTE_BOUND:
            vs.append(Violation(
                where, 1, RULE_SHAPECLASS,
                f"grid {grid}: padding waste {w:.2f}x >= the "
                f"{sc.WASTE_BOUND}x bound"))
    # 3-D rungs (serving v3): the same per-axis bound cubed
    for grid in ((17, 33, 9), (9, 9, 9), (20, 48, 12), (16, 16, 16),
                 (100, 100, 100), (8, 8, 255)):
        w = sc.padding_waste(grid)
        if w >= sc.WASTE_BOUND_3D:
            vs.append(Violation(
                where, 1, RULE_SHAPECLASS,
                f"grid {grid}: padding waste {w:.2f}x >= the 3-D "
                f"{sc.WASTE_BOUND_3D}x bound"))
    return vs


def class_kernel_entries() -> list:
    """The dynamic-extent CLASS kernels at padded geometries sized for
    the 2x-per-axis waste bound's worst case (live extent one past half
    the rung, so the padded block is as oversized as eligibility ever
    allows): the fused 2-D PRE/POST + the padded-class tblock solve at a
    256² class, and the 3-D PRE/POST at a 32³ class. Trace-only — the
    standard resource rules (tiling/VMEM/index/alias) then price the
    class blocks the serving plane actually launches."""
    import jax
    import jax.numpy as jnp

    from ..fleet.shapeclass import make_padded_class_solve
    from ..ops import ns2d_fused as nf
    from ..ops import ns3d_fused as nf3
    from ..utils.params import Parameter

    out = []
    n = 256  # rung for live extents 129..256 (worst pad: live 129)
    param = Parameter(name="dcavity", imax=n, jmax=n)
    dt = jnp.float32
    solve, br, h = make_padded_class_solve(param, n, n, dt,
                                           interpret=True)
    pre, pad, _unpad, _h = nf.make_fused_pre_2d(
        param, n, n, 1.0, 1.0, dt, block_rows=br, interpret=True,
        dynamic=True)
    post, _p, _u, _h2 = nf.make_fused_post_2d(
        param, n, n, 1.0, 1.0, dt, block_rows=br, ragged=True,
        interpret=True, dynamic=True)
    z = pad(jnp.zeros((n + 2, n + 2), dt))
    offs = jnp.zeros((2,), jnp.int32)
    ext = jnp.asarray([[129, 129]], jnp.int32)
    geo = jnp.asarray([[1.0 / 129, 1.0 / 129]], dt)
    dt11 = jnp.full((1, 1), 0.01, dt)
    out.append((f"ns2d_class.PRE[{n}²]",
                jax.make_jaxpr(pre)(offs, ext, geo, dt11, z, z)))
    out.append((f"ns2d_class.POST[{n}²]",
                jax.make_jaxpr(post)(offs, ext, geo, dt11,
                                     z, z, z, z, z)))
    sgeo = jnp.asarray([[0.9, 1.0, 1.0]], dt)
    norm = jnp.asarray(129.0 * 129.0, dt)
    out.append((f"ns2d_class.solve[{n}²]",
                jax.make_jaxpr(solve)(z, z, ext, sgeo, norm)))
    m = 32  # 3-D rung for live extents 17..32
    param3 = Parameter(name="dcavity3d", imax=m, jmax=m, kmax=m,
                       seen_keys=("kmax",))
    pre3, pad3, _u3, _h3 = nf3.make_fused_pre_3d(
        param3, m, m, m, 1.0, 1.0, 1.0, dt, interpret=True, dynamic=True)
    post3, _p3, _uu3, _hh3 = nf3.make_fused_post_3d(
        param3, m, m, m, 1.0, 1.0, 1.0, dt, ragged=True, interpret=True,
        dynamic=True)
    z3 = pad3(jnp.zeros((m + 2, m + 2, m + 2), dt))
    offs3 = jnp.zeros((3,), jnp.int32)
    ext3 = jnp.asarray([[17, 17, 17]], jnp.int32)
    geo3 = jnp.asarray([[1.0 / 17, 1.0 / 17, 1.0 / 17]], dt)
    out.append((f"ns3d_class.PRE[{m}³]",
                jax.make_jaxpr(pre3)(offs3, ext3, geo3, dt11,
                                     z3, z3, z3)))
    out.append((f"ns3d_class.POST[{m}³]",
                jax.make_jaxpr(post3)(offs3, ext3, geo3, dt11,
                                      z3, z3, z3, z3, z3, z3, z3)))
    return out


def mg_cycle_entries() -> list:
    """The fused V-cycle kernels (ops/mg_fused.py, ISSUE 16) at the
    worst-case geometries the solo dispatchers can actually build: the
    2-D DOWN/UP pair at the 512x256 two-level plan (the smallest plain
    grid whose plan survives the default DCT-bottom budget — and so the
    largest plane per level the dispatcher emits), the 3-D pair at the
    64³ plan, the masked obstacle pair (fluid + factor stacks double the
    resident inputs — the VMEM worst case per plane), and the one-launch
    class cycle at a 256² class with a worst-pad live extent (129: the
    deepest unroll at the biggest plane). Trace-only — the standard
    resource rules (tiling/VMEM/index/alias) then price every launch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import mg_fused as mf

    out = []
    dt = jnp.float32
    for tag, levels, spacings in (
            ("mg2d_cycle[512x256]", [(256, 512), (128, 256)],
             (1.0 / 512, 1.0 / 256)),
            ("mg3d_cycle[64³]", [(64, 64, 64), (32, 32, 32)],
             (1.0 / 64, 1.0 / 64, 1.0 / 64))):
        down, up, plane = mf.make_cycle_kernels(levels, spacings, dt,
                                                interpret=True)
        stack = (len(levels),) + plane
        p = jnp.zeros(plane, dt)
        s = jnp.zeros(stack, dt)
        out.append((f"{tag}.DOWN", jax.make_jaxpr(down)(p, p)))
        out.append((f"{tag}.UP", jax.make_jaxpr(up)(s, s, p)))
    # the masked obstacle pair: per-level fluid/factor stacks ride as two
    # extra VMEM-resident inputs (the fused cycle's heaviest layout)
    levels = [(64, 64), (32, 32)]
    fluids = [np.ones((j + 2, i + 2)) for j, i in levels]
    factors = [np.full((j, i), 0.25) for j, i in levels]
    down, up, plane = mf.make_cycle_kernels(
        levels, (1.0 / 64, 1.0 / 64), dt, interpret=True,
        fluid_levels=fluids, factor_levels=factors)
    stack = (len(levels),) + plane
    p = jnp.zeros(plane, dt)
    s = jnp.zeros(stack, dt)
    out.append(("mg2d_obstacle_cycle[64²].DOWN",
                jax.make_jaxpr(down)(p, p)))
    out.append(("mg2d_obstacle_cycle[64²].UP",
                jax.make_jaxpr(up)(s, s, p)))
    # the one-launch class cycle at the worst-pad lane of a 256² class
    n = 256
    cycle, plane, lmax = mf.make_class_cycle_2d(n, n, dt, interpret=True)
    live = jnp.asarray(129, jnp.int32)  # worst pad on the 256 rung
    inv2 = jnp.asarray(129.0 * 129.0, dt)
    ext, geo = mf.class_level_plan(live, live, inv2, inv2, lmax, dt)
    pc = jnp.zeros(plane, dt)
    out.append((f"mg_class_cycle[{n}²]",
                jax.make_jaxpr(cycle)(pc, pc, ext, geo)))
    return out


def check_jaxpr(jaxpr, budget: int | None = None,
                context: str = "") -> list[Violation]:
    vs: list[Violation] = []
    for launch in launches(jaxpr):
        vs += check_launch(launch, budget=budget, context=context)
    return vs


def run(traced=None, configs=None, budget: int | None = None,
        extras: bool = True) -> list[Violation]:
    """Check every pallas_call of the trace matrix plus the standalone
    large-grid builds. Stateless (no baseline): every rule is decidable
    from the program alone."""
    from . import jaxprcheck

    if traced is None:
        traced = jaxprcheck.trace_matrix(configs)
    vs: list[Violation] = []
    for t in traced:
        vs += check_jaxpr(t.jaxpr.jaxpr, budget=budget,
                          context=f"{t.cfg.name}/")
    if extras:
        for name, jx in extra_entries():
            vs += check_jaxpr(jx.jaxpr, budget=budget, context=f"{name}/")
        # the grid-restricted overlap halves: resource rules + the
        # region-coverage pin (tpu_overlap_restrict)
        for name, jx, _expect, _full in restricted_grid_entries():
            vs += check_jaxpr(jx.jaxpr, budget=budget, context=f"{name}/")
        vs += restricted_grid_violations()
        # the serving-v2 shape-class rung ladder: covering, idempotent,
        # waste-bounded (fleet/shapeclass.py)
        vs += shapeclass_violations()
        # the serving-v3 class KERNELS (fused PRE/POST + padded-class
        # solve) at the waste bound's worst-case padded geometry
        for name, jx in class_kernel_entries():
            vs += check_jaxpr(jx.jaxpr, budget=budget, context=f"{name}/")
        # the fused V-cycle kernels (ISSUE 16): DOWN/UP pairs at the
        # worst-case solo level plans + the one-launch class cycle
        for name, jx in mg_cycle_entries():
            vs += check_jaxpr(jx.jaxpr, budget=budget, context=f"{name}/")
    return vs
