"""Precision-flow contract checker: where does every bit of precision
go, statically, before the mixed-precision knob exists?

ROADMAP item 2's mixed-precision bullet (bf16/f32 smoothing under an f64
residual) needs a merge gate: today dtype policy is a runtime convention
(`utils/precision.py`), the f32 eps-floor caveat is a build-time warning,
and the fused-vs-ladder summation-order hazard was found by hand. This
pass derives the precision contract from the SAME one trace of the config
matrix the jaxpr/comm/pallas passes share (`jaxprcheck.trace_matrix`),
pins it env-keyed in the `precision` section of CONTRACTS.json, and fails
drift with per-site src->dst diffs + file:line via jaxpr source info.

Four analyses over every config's chunk jaxpr:

  dtype lattice   every `convert_element_type` is censused by
                  (src->dst dtype, scope) and classified narrowing /
                  widening / preserving. A NARROWING float cast must be
                  DECLARED by routing through `utils/precision.cast(x,
                  dtype, why)` — the `precision.cast.<why>` named scope
                  is read off the eqn's name stack exactly like the comm
                  census reads `halo_exchange.*`. An undeclared downcast
                  fails with its file:line (prec-cast).
  oracle purity   configs marked `oracle=True` (the jnp f64 parity
                  oracles) must contain ZERO sub-f64 float compute
                  anywhere in the trace — the property the mixed-
                  precision knob must never break (prec-oracle).
                  Detection uses jnp.issubdtype: the ml_dtypes extension
                  floats (bfloat16) are invisible to np.floating.
  reduction order each `reduce_sum`/cumulative reduction whose result
                  feeds a while-loop convergence predicate (the residual
                  accumulations behind the eps-floor caveat) must be
                  f64-accumulated or declared in
                  `precision.DECLARED_ORDER_SENSITIVE` (prec-reduce).
                  The audit also generalizes `check_eps_floor` from a
                  build-time warning into a matrix-wide static check of
                  every (eps, ncells, dtype) triple the standard configs
                  imply (prec-floor).
  advisory bf16   configs marked `advisory=True` (the forced-bf16
                  scouts) run every analysis and PIN their census in the
                  baseline, but their rule findings are REPORTED (the
                  driver prints them) instead of gating — the pass
                  prices exactly which casts/accumulations the future
                  `tpu_dtype bf16` lanes add before that knob lands.
                  Census drift still gates: the scout's precision shape
                  is a contract like any other.

Baseline workflow: `tools/lint.py --only prec` checks against the
`precision` section; `--update` regenerates it through the same merged
single-write as the configs/comm sections (prec-baseline on drift).
"""

from __future__ import annotations

import os

from .astlint import Violation
from .jaxprcheck import _anchor, float_dtypes, iter_eqns

RULE_CAST = "prec-cast"
RULE_ORACLE = "prec-oracle"
RULE_REDUCE = "prec-reduce"
RULE_FLOOR = "prec-floor"
RULE_BASELINE = "prec-baseline"

# the declared-downcast scope convention (utils/precision.cast)
CAST_SCOPE_PREFIX = "precision.cast."

# order-sensitive accumulation primitives: sequential/tree association
# changes their result; max/min-style reductions are order-insensitive
REDUCTIONS = ("reduce_sum", "cumsum", "cumlogsumexp")
COMPARISONS = ("lt", "le", "gt", "ge", "eq", "ne")


# ---------------------------------------------------------------------------
# jaxpr helpers
# ---------------------------------------------------------------------------

def _is_literal(v) -> bool:
    return type(v).__name__ == "Literal"


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if type(x).__name__ == "ClosedJaxpr":
                yield x.jaxpr
            elif type(x).__name__ == "Jaxpr":
                yield x


def _dtype_of(v):
    return getattr(getattr(v, "aval", None), "dtype", None)


def _float_name(dt) -> str | None:
    """str dtype name when `dt` is ANY float (incl. the ml_dtypes
    extension floats np.issubdtype cannot see), else None."""
    import jax.numpy as jnp

    if dt is None:
        return None
    try:
        if jnp.issubdtype(dt, jnp.floating):
            return str(jnp.dtype(dt))
    except TypeError:
        return None
    return None


def float_bits(name) -> int:
    import jax.numpy as jnp

    return int(jnp.finfo(name).bits)


def eqn_src(eqn) -> tuple[str, int]:
    """(file, line) of the user frame that created an eqn — the
    diagnostic anchor of every per-site finding."""
    try:
        from jax._src import source_info_util

        fr = source_info_util.user_frame(eqn.source_info)
    except (ImportError, AttributeError):
        fr = None
    if fr is None:
        return "<unknown>", 0
    return fr.file_name, int(fr.start_line)


def cast_scope(eqn) -> str:
    """The `precision.cast.<why>` token on an eqn's name stack ('' when
    undeclared) — same name-stack read as commcheck.scoped_exchanges."""
    stack = str(getattr(eqn.source_info, "name_stack", "") or "")
    for part in stack.split("/"):
        if part.startswith(CAST_SCOPE_PREFIX):
            return part[len(CAST_SCOPE_PREFIX):]
    return ""


# ---------------------------------------------------------------------------
# (1) dtype-lattice dataflow: the cast census
# ---------------------------------------------------------------------------

def cast_sites(jaxpr) -> list[dict]:
    """Every `convert_element_type` anywhere in the program, as a site
    dict: src/dst dtype names, narrowing/widening/preserving/boundary
    classification (float lattice; int<->float edges are 'boundary'),
    declared scope, file:line."""
    import jax.numpy as jnp

    sites = []
    for e in iter_eqns(jaxpr):
        if e.primitive.name != "convert_element_type":
            continue
        src_dt = _dtype_of(e.invars[0]) if e.invars else None
        dst_dt = _dtype_of(e.outvars[0]) if e.outvars else None
        if src_dt is None or dst_dt is None:
            continue
        src_f, dst_f = _float_name(src_dt), _float_name(dst_dt)
        if src_f and dst_f:
            sb, db = float_bits(src_f), float_bits(dst_f)
            kind = ("narrowing" if db < sb
                    else "widening" if db > sb else "preserving")
        else:
            kind = "boundary"
        f, ln = eqn_src(e)
        sites.append({
            "src": str(jnp.dtype(src_dt)), "dst": str(jnp.dtype(dst_dt)),
            "kind": kind, "scope": cast_scope(e), "file": f, "line": ln,
        })
    return sites


def site_key(site: dict) -> str:
    """Census key of one cast site: 'float64->bfloat16@implicit' /
    '...@metrics' (the declared `why`)."""
    return (f"{site['src']}->{site['dst']}"
            f"@{site['scope'] or 'implicit'}")


def cast_census(sites: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for s in sites:
        k = site_key(s)
        out[k] = out.get(k, 0) + 1
    return out


def implicit_narrowing(sites: list[dict]) -> list[dict]:
    """The banned class: float downcasts carrying no declared scope."""
    return [s for s in sites
            if s["kind"] == "narrowing" and not s["scope"]]


# ---------------------------------------------------------------------------
# (2) oracle purity
# ---------------------------------------------------------------------------

def subf64_sites(jaxpr) -> list[dict]:
    """Eqns producing any sub-f64 float output — empty on a pure f64
    oracle program."""
    out = []
    for e in iter_eqns(jaxpr):
        for v in e.outvars:
            nm = _float_name(_dtype_of(v))
            if nm and float_bits(nm) < 64:
                f, ln = eqn_src(e)
                out.append({"prim": e.primitive.name, "dtype": nm,
                            "file": f, "line": ln})
                break
    return out


# ---------------------------------------------------------------------------
# (3) reduction-order audit
# ---------------------------------------------------------------------------

def _reduction_site(e) -> dict | None:
    if e.primitive.name not in REDUCTIONS:
        return None
    nm = _float_name(_dtype_of(e.outvars[0])) if e.outvars else None
    if nm is None:
        return None
    f, ln = eqn_src(e)
    return {"prim": e.primitive.name, "dtype": nm, "file": f, "line": ln}


def _cond_read_carry(cond_closed, nconsts: int) -> set[int]:
    """Carry positions a while cond's float comparisons transitively
    read (backward slice over the cond jaxpr's top-level eqns)."""
    cj = cond_closed.jaxpr
    prod = {}
    for e in cj.eqns:
        for ov in e.outvars:
            prod[id(ov)] = e
    work = [e for e in cj.eqns
            if e.primitive.name in COMPARISONS
            and any(_float_name(_dtype_of(v))
                    for v in e.invars if not _is_literal(v))]
    reach: set[int] = set()
    seen: set[int] = set()
    while work:
        e = work.pop()
        if id(e) in seen:
            continue
        seen.add(id(e))
        for v in e.invars:
            if _is_literal(v):
                continue
            reach.add(id(v))
            pe = prod.get(id(v))
            if pe is not None:
                work.append(pe)
    return {i - nconsts for i, v in enumerate(cj.invars)
            if id(v) in reach and i >= nconsts}


def _dedup(sites: list[dict]) -> list[dict]:
    uniq = {(s["file"], s["line"], s["prim"], s["dtype"]): s
            for s in sites}
    return list(uniq.values())


def _body_reduction_taint(body_closed) -> dict[int, list[dict]]:
    """Forward taint over the while body's top-level eqns: which carry
    outvar positions a float reduction's result reaches. Reductions
    inside an eqn's sub-jaxprs (pjit bodies, pallas kernels, nested
    loops) taint that eqn's outputs — conservative across control flow;
    nested whiles additionally get their own direct audit."""
    bj = body_closed.jaxpr
    by_var: dict[int, list[dict]] = {}
    for e in bj.eqns:
        sites: list[dict] = []
        for v in e.invars:
            if not _is_literal(v):
                sites += by_var.get(id(v), [])
        own = _reduction_site(e)
        if own is not None:
            sites = sites + [own]
        else:
            for sub in _sub_jaxprs(e):
                for se in iter_eqns(sub):
                    s = _reduction_site(se)
                    if s is not None:
                        sites.append(s)
        if sites:
            sites = _dedup(sites)
            for v in e.outvars:
                by_var[id(v)] = sites
    return {pos: by_var[id(v)] for pos, v in enumerate(bj.outvars)
            if id(v) in by_var}


def convergence_reductions(jaxpr) -> list[dict]:
    """Every float reduction whose result feeds a while convergence
    predicate, anywhere in the program (each while — including nested
    solve loops — is audited against its own cond)."""
    out: list[dict] = []
    for e in iter_eqns(jaxpr):
        if e.primitive.name != "while":
            continue
        cond_c = e.params.get("cond_jaxpr")
        body_c = e.params.get("body_jaxpr")
        if cond_c is None or body_c is None:
            continue
        read = _cond_read_carry(cond_c, e.params.get("cond_nconsts", 0))
        if not read:
            continue
        taint = _body_reduction_taint(body_c)
        nbc = e.params.get("body_nconsts", 0)
        del nbc  # body outvars ARE the carry; consts only pad invars
        for pos, sites in taint.items():
            if pos in read:
                out += sites
    return _dedup(out)


def registry_key(site: dict) -> str:
    """DECLARED_ORDER_SENSITIVE key of one reduction site:
    '<file basename>:<accumulator dtype>' — names the trade, survives
    line churn."""
    return f"{os.path.basename(site['file'])}:{site['dtype']}"


# ---------------------------------------------------------------------------
# the per-config entry + checks
# ---------------------------------------------------------------------------

def config_entry(traced) -> tuple[dict, list[dict], list[dict]]:
    """(fresh `precision` baseline entry, cast sites, convergence
    reduction sites) for one traced config."""
    import jax.numpy as jnp

    sites = cast_sites(traced.jaxpr.jaxpr)
    reds = convergence_reductions(traced.jaxpr.jaxpr)
    red_census: dict[str, int] = {}
    for s in reds:
        k = registry_key(s)
        red_census[k] = red_census.get(k, 0) + 1
    entry = {
        "dtype": str(jnp.dtype(traced.solver.dtype)),
        "float_dtypes": sorted(float_dtypes(traced.jaxpr.jaxpr)),
        "casts": cast_census(sites),
        "narrowing": sum(1 for s in sites if s["kind"] == "narrowing"),
        "reductions": red_census,
    }
    if traced.cfg.oracle:
        entry["oracle"] = True
    if traced.cfg.advisory:
        entry["advisory"] = True
    return entry, sites, reds


def _diff_casts(old: dict, new: dict, sites: list[dict]) -> list[str]:
    """Per-site src->dst census diff, with the fresh sites' file:line
    so a drifted key points at the code that moved."""
    lines = []
    for key in sorted(set(old) | set(new)):
        a, b = old.get(key, 0), new.get(key, 0)
        if a == b:
            continue
        where = sorted({f"{s['file']}:{s['line']}"
                        for s in sites if site_key(s) == key})[:3]
        lines.append(f"{key}: {a} -> {b} ({b - a:+d})"
                     + (f" at {'; '.join(where)}" if where else ""))
    return lines


def check_config(traced, baseline: dict | None,
                 env_matches: bool) -> tuple[list[Violation], dict, list]:
    """One traced config against the four precision rules and its
    `precision` baseline entry. Returns (violations, fresh entry,
    advisory notes) — on an `advisory` config the rule findings land in
    the notes (the driver reports them) and only baseline drift gates."""
    from ..utils import precision

    cfg = traced.cfg
    path, line = _anchor(cfg.family)
    entry, sites, reds = config_entry(traced)
    findings: list[tuple[str, str]] = []

    # (1) implicit-narrowing ban
    for s in implicit_narrowing(sites):
        findings.append((RULE_CAST,
                         f"implicit downcast {s['src']} -> {s['dst']} at "
                         f"{s['file']}:{s['line']} — declare it through "
                         "utils/precision.cast(x, dtype, why) so the "
                         "census carries its purpose"))
    # (2) oracle purity
    if cfg.oracle:
        bad = subf64_sites(traced.jaxpr.jaxpr)
        for s in bad[:3]:
            findings.append((RULE_ORACLE,
                             f"f64 parity oracle computes at {s['dtype']} "
                             f"({s['prim']} at {s['file']}:{s['line']}) — "
                             "the oracle must stay pure f64 end-to-end"))
        if len(bad) > 3:
            findings.append((RULE_ORACLE,
                             f"... and {len(bad) - 3} more sub-f64 "
                             "site(s)"))
    # (3) reduction-order audit
    for s in reds:
        if float_bits(s["dtype"]) >= 64:
            continue
        key = registry_key(s)
        if key not in precision.DECLARED_ORDER_SENSITIVE:
            findings.append((RULE_REDUCE,
                             f"{s['prim']} accumulates at {s['dtype']} "
                             "and feeds a convergence predicate "
                             f"({s['file']}:{s['line']}) — accumulate at "
                             f"f64 or declare {key!r} in "
                             "precision.DECLARED_ORDER_SENSITIVE with a "
                             "why"))
    # (4) the static eps-floor check, matrix-wide: every (eps, ncells,
    # dtype) triple the config implies, without building a solve
    p = cfg.params
    eps = float(p.get("eps", 0.0) or 0.0)
    ncells = int(p.get("imax", 1)) * int(p.get("jmax", 1)) \
        * int(p.get("kmax", 1) or 1)
    floor = precision.residual_floor(ncells, traced.solver.dtype)
    if 0.0 < eps < 10.0 * floor:
        findings.append((RULE_FLOOR,
                         f"eps={eps:g} sits within a decade of the "
                         f"{entry['dtype']} residual floor (~{floor:.3g} "
                         f"at {ncells} cells) — convergence there "
                         "measures summation-order noise (raise eps or "
                         "run fixed-iteration, eps=0)"))

    vs: list[Violation] = []
    notes: list[str] = []
    if cfg.advisory:
        notes = [f"{cfg.name}: [{r}] {m}" for r, m in findings]
    else:
        vs = [Violation(path, line, r, f"{cfg.name}: {m}")
              for r, m in findings]

    # baseline comparison — env-gated like every trace pass; advisory
    # configs gate here too (the scout's census is pinned, its rule
    # findings are not)
    if baseline is not None and env_matches:
        def emit(msg):
            vs.append(Violation(path, line, RULE_BASELINE,
                                f"{cfg.name}: {msg}"))

        if baseline.get("dtype") != entry["dtype"]:
            emit(f"compute dtype drifted from the precision baseline: "
                 f"{baseline.get('dtype')} -> {entry['dtype']} "
                 "(tools/lint.py --update if intended)")
        if baseline.get("float_dtypes") != entry["float_dtypes"]:
            emit(f"float dtype set drifted: "
                 f"{baseline.get('float_dtypes')} -> "
                 f"{entry['float_dtypes']} (tools/lint.py --update if "
                 "intended)")
        if baseline.get("casts") != entry["casts"]:
            diff = _diff_casts(baseline.get("casts", {}),
                               entry["casts"], sites)
            emit("cast census drifted from the precision baseline: "
                 + "; ".join(diff)
                 + " (tools/lint.py --update if intended)")
        if baseline.get("reductions") != entry["reductions"]:
            old_r = baseline.get("reductions", {})
            rdiff = [f"{k}: {old_r.get(k, 0)} -> "
                     f"{entry['reductions'].get(k, 0)}"
                     for k in sorted(set(old_r) | set(entry["reductions"]))
                     if old_r.get(k, 0) != entry["reductions"].get(k, 0)]
            emit("convergence-reduction census drifted: "
                 + "; ".join(rdiff)
                 + " (tools/lint.py --update if intended)")
    return vs, entry, notes


def run(baseline: dict | None = None, configs=None, update: bool = False,
        traced=None, env_matches: bool = True) -> tuple[list, dict, list]:
    """Check every config of the matrix. `baseline` is the `precision`
    section of CONTRACTS.json ({config name: entry}); returns
    (violations, fresh precision section, advisory notes). `traced`
    (jaxprcheck.trace_matrix) shares solver builds across passes."""
    from . import jaxprcheck

    if traced is None:
        traced = jaxprcheck.trace_matrix(configs)
    vs: list[Violation] = []
    fresh: dict[str, dict] = {}
    notes: list[str] = []
    for t in traced:
        entry = (baseline or {}).get(t.cfg.name)
        if entry is None and baseline is not None and not update:
            vs.append(Violation(
                "CONTRACTS.json", 1, RULE_BASELINE,
                f"{t.cfg.name}: no precision baseline entry "
                "(tools/lint.py --update)"))
        t_vs, fresh_entry, t_notes = check_config(
            t, None if update else entry, env_matches)
        vs += t_vs
        notes += t_notes
        fresh[t.cfg.name] = fresh_entry
    return vs, fresh, notes
