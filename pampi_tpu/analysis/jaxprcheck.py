"""Jaxpr contract checker: trace every solver family's chunk under the
dispatch matrix and statically assert the program-shape contracts.

What one trace proves (no device execution — `jax.make_jaxpr` only):

  launch counts   the chunk lowers to EXACTLY the number of `pallas_call`s
                  the `resolve_fuse_phases` / p-fold dispatch decision
                  implies (fused = 2, + 1 when the solve is folded onto
                  the shared padded layout, 0 on the jnp chain; fft
                  contributes none) — the launch-amortization property
                  the fused kernels exist for.
  host callbacks  no `*_callback` primitive unless a PAMPI_DEBUG /
                  PAMPI_VERBOSE / PAMPI_CHECK flag was armed at trace
                  time — a stray `jax.debug.print` in a hot loop costs a
                  host sync per step.
  dtype policy    every float intermediate is the compute dtype, the
                  time-accumulator dtype, or f32 (the in-band metrics
                  precision) — a silent promotion off the `precision.py`
                  contract doubles memory traffic before any test sees a
                  numeric difference.
  metrics arity   `initial_state()` arity == chunk invars/outvars, with
                  telemetry off AND on (the PR 3 contract every
                  measurement tool leans on).
  trace identity  the flag-off jaxpr hash matches the committed
                  `CONTRACTS.json` baseline (regenerate with
                  `tools/lint.py --update`); drift fails with a primitive
                  -histogram diff of the offending eqns. Hashes are
                  compared only when the baseline's environment (jax
                  version, x64, backend) matches — a toolchain bump
                  regenerates, it does not silently pass.

The config matrix spans the dispatch dimensions: jnp/fused ×
single-device/distributed × plain/obstacle/ragged × explicit/folded p
layout. Knobs are FORCED (never `auto`) so the expected launch counts are
platform-independent wherever the kernel family is (fft solves carry no
kernel; forced fusion and the forced checkerboard fold build the same
program on CPU and TPU); paths whose solve dispatch is genuinely
platform-dependent pin their count through the env-keyed baseline
instead.

Shared helpers (`count_prim`, `trace_chunk`, `assert_offpath_identity`)
are THE home of the jaxpr pins the test suite previously hand-rolled per
file (tests/test_telemetry.py, tests/test_faultinject.py,
tests/test_ns*_fused.py import from here).
"""

from __future__ import annotations

import hashlib
import inspect
import re
from dataclasses import dataclass

from .astlint import Violation

RULE_LAUNCH = "launch-count"
RULE_CALLBACK = "host-callback"
RULE_DTYPE = "dtype-promotion"
RULE_ARITY = "metrics-arity"
RULE_HASH = "trace-drift"

BASELINE_VERSION = 1


# ---------------------------------------------------------------------------
# jaxpr walkers (shared with the test suite)
# ---------------------------------------------------------------------------

def iter_eqns(jaxpr):
    """Every eqn of a jaxpr, recursing into sub-jaxprs (while/cond/pjit/
    pallas bodies)."""
    for e in jaxpr.eqns:
        yield e
        for v in e.params.values():
            vals = v if isinstance(v, (tuple, list)) else (v,)
            for x in vals:
                if type(x).__name__ == "ClosedJaxpr":
                    yield from iter_eqns(x.jaxpr)
                elif type(x).__name__ == "Jaxpr":
                    yield from iter_eqns(x)


def count_prim(jaxpr, name: str) -> int:
    """Occurrences of a primitive anywhere in the program (the pin the
    fused-kernel launch-count tests assert on)."""
    return sum(1 for e in iter_eqns(jaxpr) if e.primitive.name == name)


def prim_histogram(jaxpr) -> dict[str, int]:
    hist: dict[str, int] = {}
    for e in iter_eqns(jaxpr):
        hist[e.primitive.name] = hist.get(e.primitive.name, 0) + 1
    return hist


def host_callbacks(jaxpr) -> list[str]:
    """Primitive names of host-callback eqns (debug_callback from
    jax.debug.print, io_callback, pure_callback, legacy outside_call)."""
    return [
        e.primitive.name
        for e in iter_eqns(jaxpr)
        if "callback" in e.primitive.name or e.primitive.name == "outside_call"
    ]


def float_dtypes(jaxpr) -> set[str]:
    """Every floating dtype appearing on an eqn output anywhere.
    jnp.issubdtype, not np: the ml_dtypes extension floats (bfloat16)
    are NOT np.floating subtypes, so an np-based check is blind to
    exactly the dtypes the mixed-precision work introduces."""
    import jax.numpy as jnp

    out = set()
    for e in iter_eqns(jaxpr):
        for v in e.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating):
                out.add(str(dt))
    return out


# `name_and_src_info=<kernel> at <file>:<line>` in pallas_call params:
# the line number is SOURCE metadata, not program structure — an edit
# that merely shifts a kernel def down the file must not read as trace
# drift (found in round 20: every fused-config hash churned on a
# pure-addition kernel change with zero primitive deltas)
_SRC_INFO_RE = re.compile(r" at [^\s]+:\d+")


def jaxpr_hash(closed) -> str:
    """sha256 of the pretty-printed program with source-location
    metadata stripped — the trace-identity token. Stable within one
    (jax version, x64, backend) environment; the baseline stores that
    environment and hashes are only compared when it matches."""
    return hashlib.sha256(
        _SRC_INFO_RE.sub("", str(closed)).encode()).hexdigest()


def diff_histograms(old: dict, new: dict) -> list[str]:
    """Primitive-count deltas, the drift diagnostic: which eqns appeared/
    vanished."""
    lines = []
    for name in sorted(set(old) | set(new)):
        a, b = old.get(name, 0), new.get(name, 0)
        if a != b:
            lines.append(f"{name}: {a} -> {b} ({b - a:+d})")
    return lines


# ---------------------------------------------------------------------------
# chunk tracing
# ---------------------------------------------------------------------------

def chunk_callable(solver):
    """The traced chunk entry point, uniformly across families: the
    distributed solvers expose the shard_map'ed `_chunk_sm`; the
    single-device ones rebuild via `_build_chunk()` (same builder the
    production `_chunk_fn` wraps)."""
    if hasattr(solver, "_chunk_sm"):
        return solver._chunk_sm
    return solver._build_chunk()


def trace_chunk(solver):
    """ClosedJaxpr of the solver's chunk at its own initial_state arity."""
    import jax

    return jax.make_jaxpr(chunk_callable(solver))(*solver.initial_state())


def chunk_signature(solver, jaxpr=None) -> dict:
    """The contract-relevant shape of a chunk program."""
    jx = trace_chunk(solver) if jaxpr is None else jaxpr
    return {
        "outvars": len(jx.jaxpr.outvars),
        "invars": len(jx.jaxpr.invars),
        "pallas_calls": count_prim(jx.jaxpr, "pallas_call"),
        "callbacks": host_callbacks(jx.jaxpr),
        "state_arity": len(solver.initial_state()),
        "hash": jaxpr_hash(jx),
        "prims": prim_histogram(jx.jaxpr),
    }


def assert_offpath_identity(make_solver, expect_outvars: int = 5):
    """THE flag-off identity pin, shared by the telemetry and
    fault-injection suites: two independent builds trace byte-identically,
    with the expected plain arity and no sentinel ops. Returns
    (second solver, its ClosedJaxpr) for follow-on pins."""
    a = make_solver()
    jx_a = trace_chunk(a)
    b = make_solver()
    jx_b = trace_chunk(b)
    assert str(jx_a) == str(jx_b), "flag-off build is not deterministic"
    assert len(jx_a.jaxpr.outvars) == expect_outvars, (
        f"flag-off chunk arity {len(jx_a.jaxpr.outvars)} != "
        f"{expect_outvars}"
    )
    assert "is_finite" not in str(jx_a), (
        "flag-off chunk contains sentinel ops"
    )
    return b, jx_b


# ---------------------------------------------------------------------------
# the dispatch-matrix configs
# ---------------------------------------------------------------------------

@dataclass
class ChunkConfig:
    """One traced build of the dispatch matrix. The launch-count contract
    comes in three strengths:

    - `expected_pallas` set: a platform-independent static pin (fft
      solves, forced fusion).
    - `derive=True`: the expected count is DERIVED from the recorded
      dispatch decisions — 2 for a `pallas_fused` phase decision, +1 for
      a folded p layout, +1 for a solve whose dispatch record starts with
      "pallas" (`solve_key`), +1 for an overlapped schedule
      (`overlap_key`: the PRE kernel runs as interior + boundary
      halves). This is the per-decision contract: whatever the
      dispatcher chose, the trace must contain exactly the kernels that
      choice implies.
    - neither: only the env-keyed baseline pins the count (single-device
      solve paths that record no dispatch decision).

    `dispatch_keys` are recorded into the baseline and diffed on drift.

    `fleet` > 0 wraps the built solver in a `fleet/batch.BatchedSolver`
    of that many identical lanes: the traced chunk is the VMAPPED fleet
    program (ROADMAP item 3) — the same launch/census/resharding
    contracts then pin the batched trace (a vmapped chunk must lower to
    the same pallas launches and census the same collectives as the
    dispatch decisions imply, with zero resharding collectives)."""

    name: str
    family: str
    params: dict
    dims: tuple | None = None
    expected_pallas: int | None = None
    derive: bool = False
    phases_key: str = ""
    fold_key: str = ""
    solve_key: str = ""
    overlap_key: str = ""
    # the fused-V-cycle dispatch key (ISSUE 16): its record carries the
    # launch census verbatim — "pallas_*_cycle (launches=N, ...)" — and
    # the derived budget adds exactly that N (2 for the solo DOWN/UP
    # pair, 1 for the one-launch class cycle)
    mg_key: str = ""
    dispatch_keys: tuple = ()
    fleet: int = 0
    # serving-v2 batched variants (all imply `fleet`): mixed per-lane te
    # (the te-carried chunk), a shape-class padded batch (grid extents
    # per-lane data), the scenario axis sharded over the device mesh
    fleet_te: bool = False
    fleet_class: bool = False
    fleet_mesh: bool = False
    # precision-flow contract strength (analysis/preccheck.py):
    # `oracle` pins jnp f64 parity-oracle purity — zero sub-f64 float
    # compute anywhere in the trace; `advisory` traces the config and
    # pins its precision census in the baseline but REPORTS the
    # precision rule findings instead of gating on them (the forced-
    # bf16 scouts that price the future mixed-precision lanes)
    oracle: bool = False
    advisory: bool = False
    notes: str = ""

    def build(self):
        from ..utils.params import Parameter

        param = Parameter(**self.params)
        if self.dims is None:
            if self.family == "ns2d":
                from ..models.ns2d import NS2DSolver

                solver = NS2DSolver(param)
            else:
                from ..models.ns3d import NS3DSolver

                solver = NS3DSolver(param)
        else:
            from ..parallel.comm import CartComm

            comm = CartComm(ndims=len(self.dims), dims=self.dims,
                            tiers=param.tpu_mesh_tiers)
            if self.family == "ns2d_dist":
                from ..models.ns2d_dist import NS2DDistSolver

                solver = NS2DDistSolver(param, comm)
            else:
                from ..models.ns3d_dist import NS3DDistSolver

                solver = NS3DDistSolver(param, comm)
        if self.fleet:
            from ..fleet.batch import BatchedSolver

            params = [param] * self.fleet
            if self.fleet_te:
                # mixed end times: BatchedSolver auto-arms the per-lane
                # te carry (the te-arg chunk) — the serving-v2 trace
                params = [param.replace(te=param.te * (i + 1))
                          for i in range(self.fleet)]
            if self.fleet_class:
                from ..fleet.shapeclass import (
                    Class3DSolver,
                    ClassSolver,
                    class_grid,
                )

                if self.family == "ns3d":
                    grid = class_grid((param.imax, param.jmax,
                                       param.kmax))
                    solver = Class3DSolver(param, ic=grid[0], jc=grid[1],
                                           kc=grid[2])
                    other = param.replace(imax=param.imax + 2,
                                          jmax=param.jmax + 1)
                else:
                    grid = class_grid((param.imax, param.jmax))
                    solver = ClassSolver(param, ic=grid[0], jc=grid[1])
                    other = param.replace(imax=param.imax - 2,
                                          jmax=param.jmax - 4)
                if self.fleet >= 2:
                    # mixed GRIDS share the class compile: the second
                    # lane is a different grid riding the same program
                    params = [param, other] + [param] * (self.fleet - 2)
            mesh = None
            if self.fleet_mesh:
                import jax

                mesh = list(jax.devices())
            return BatchedSolver(solver, params,
                                 [f"lane{i}" for i in range(self.fleet)],
                                 family=self.family, mesh=mesh)
        return solver


_B2 = dict(name="dcavity", imax=16, jmax=16, re=10.0, te=0.02, tau=0.5,
           itermax=10, eps=1e-4, omg=1.7, gamma=0.9)
_B3 = dict(name="dcavity3d", imax=8, jmax=8, kmax=8, re=10.0, te=0.02,
           tau=0.5, itermax=8, eps=1e-4, omg=1.7, gamma=0.9)
_OBS = dict(name="canal_obstacle", imax=24, jmax=12, re=10.0, te=0.02,
            tau=0.5, itermax=10, eps=1e-3, omg=1.7, gamma=0.9,
            bcLeft=3, bcRight=3, obstacles="0.3,0.3,0.6,0.6")


def standard_configs() -> list[ChunkConfig]:
    """The dispatch matrix: jnp/fused × single/dist × plain/obstacle/
    ragged × explicit/folded p layout × serial/overlapped exchange
    schedule. Grids are 16²/8³ — each config is one trace, no compile."""
    return [
        ChunkConfig(
            "ns2d_jnp", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="fft"),
            expected_pallas=0, dispatch_keys=("ns2d_phases",),
            oracle=True,
            notes="jnp phase chain + fft solve: zero kernels by contract"),
        ChunkConfig(
            "ns2d_fused_fft", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="fft"),
            expected_pallas=2, dispatch_keys=("ns2d_phases",),
            notes="fused phases bracket an fft solve: PRE + POST only"),
        ChunkConfig(
            "ns2d_fused_fold", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_sor_inner=1),
            derive=True, phases_key="ns2d_phases",
            fold_key="ns2d_p_layout",
            dispatch_keys=("ns2d_phases", "ns2d_p_layout"),
            notes="p-layout fold: PRE + tblock solve + POST, no layout "
                  "passes between them"),
        ChunkConfig(
            "ns2d_obstacle_fused", "ns2d",
            dict(_OBS, tpu_fuse_phases="on", tpu_solver="sor"),
            expected_pallas=None, dispatch_keys=("ns2d_phases",),
            notes="single-device obstacle solve records no dispatch "
                  "decision and is platform-dependent: baseline-pinned"),
        ChunkConfig(
            "ns2d_dist_jnp", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_sor_layout="checkerboard"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"), oracle=True),
        ChunkConfig(
            "ns2d_dist_fused", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"),
            notes="fused dist: PRE + POST per shard + whatever the solve "
                  "dispatch chose"),
        ChunkConfig(
            "ns2d_dist_overlap", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_overlap="on",
                 tpu_solver="sor", tpu_sor_layout="checkerboard"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"),
            notes="double-buffered overlap: interior + boundary PRE "
                  "halves, the step N+1 deep exchange posted after POST "
                  "(ppermutes feed only the loop carry)"),
        ChunkConfig(
            "ns2d_dist_overlap_split", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_overlap="on",
                 tpu_overlap_restrict="on", tpu_solver="sor"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist", "overlap_grid_ns2d_dist",
                           "sweep_split_ns2d_dist"),
            notes="the full item-3 schedule: grid-restricted PRE halves "
                  "(forced — degenerate single-band at this shard size) "
                  "+ jnp RB-SOR with SPLIT sweeps (per-colour depth-1 "
                  "exchange posted behind the interior update)"),
        ChunkConfig(
            "ns2d_dist_tiered", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_mesh_tiers="i=dcn"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"),
            notes="hierarchical mesh tiers: the i axis declared DCN — "
                  "its strips post first in every persistent exchange "
                  "and the census breaks traffic out per tier "
                  "(dcn/ici); same collectives, same bytes"),
        ChunkConfig(
            "ns2d_dist_ragged_fused", "ns2d_dist",
            dict(_B2, imax=18, jmax=18, tpu_fuse_phases="on",
                 tpu_solver="sor", tpu_sor_layout="checkerboard"),
            dims=(4, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"),
            notes="ragged shards ride the same kernels at uneven bounds"),
        ChunkConfig(
            "ns2d_dist_obstacle_fused", "ns2d_dist",
            dict(_OBS, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="obstacle_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "obstacle_dist", "overlap_ns2d_dist"),
            notes="dist obstacle flags compose via call-time flag blocks"),
        ChunkConfig(
            "ns3d_jnp", "ns3d",
            dict(_B3, tpu_fuse_phases="off", tpu_solver="fft"),
            expected_pallas=0, dispatch_keys=("ns3d_phases",),
            oracle=True),
        ChunkConfig(
            "ns3d_fused_fft", "ns3d",
            dict(_B3, tpu_fuse_phases="on", tpu_solver="fft"),
            expected_pallas=2, dispatch_keys=("ns3d_phases",)),
        ChunkConfig(
            "ns3d_dist_fused", "ns3d_dist",
            dict(_B3, tpu_fuse_phases="on", tpu_solver="sor"),
            dims=(2, 2, 2), derive=True, phases_key="ns3d_dist_phases",
            solve_key="ns3d_dist", overlap_key="overlap_ns3d_dist",
            dispatch_keys=("ns3d_dist_phases", "ns3d_dist",
                           "overlap_ns3d_dist")),
        ChunkConfig(
            "ns3d_dist_overlap", "ns3d_dist",
            dict(_B3, tpu_fuse_phases="on", tpu_overlap="on",
                 tpu_solver="sor"),
            dims=(2, 2, 2), derive=True, phases_key="ns3d_dist_phases",
            solve_key="ns3d_dist", overlap_key="overlap_ns3d_dist",
            dispatch_keys=("ns3d_dist_phases", "ns3d_dist",
                           "overlap_ns3d_dist"),
            notes="the 3-D overlapped schedule (4-cell shards: interior "
                  "region empty, boundary half covers the block — "
                  "degenerate but schedule-correct)"),
        # the scenario-fleet batched programs (ROADMAP item 3): the
        # vmapped chunk must keep the solo chunk's launch counts (vmap
        # adds a batch grid dim, never a second launch), census the same
        # collectives as its solo twin, and introduce zero resharding
        # collectives — the contracts that make vmap-batching a safe
        # serving default rather than a hope
        ChunkConfig(
            "ns2d_fleet_jnp", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="fft"),
            expected_pallas=0, dispatch_keys=("ns2d_phases",), fleet=3,
            oracle=True,
            notes="3-lane vmapped jnp+fft chunk: still zero kernels"),
        ChunkConfig(
            "ns2d_fleet_fused", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="fft"),
            expected_pallas=2, dispatch_keys=("ns2d_phases",), fleet=3,
            notes="3-lane vmapped fused chunk: PRE + POST exactly, the "
                  "batch rides the kernels' leading grid axis"),
        ChunkConfig(
            "ns2d_dist_fleet", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_sor_layout="checkerboard"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist"), fleet=2,
            notes="2-lane vmapped dist chunk: identical collective "
                  "counts to the solo dist trace (lanes ride the "
                  "messages, never add messages), named scopes intact"),
        # serving v2 (ISSUE 14): the continuous-batching / shape-class /
        # fleet-over-mesh programs — pure additions, the PR 9 fleet
        # configs above keep their baked-te traces (hashes unchanged)
        ChunkConfig(
            "ns2d_fleet_te", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="fft"),
            expected_pallas=0, dispatch_keys=("ns2d_phases",), fleet=3,
            fleet_te=True,
            notes="mixed per-lane te: the end time rides the batched "
                  "carry as an (N,) vector and each lane's while-cond "
                  "reads its own — still zero kernels on jnp+fft"),
        ChunkConfig(
            "ns2d_fleet_class", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_mesh="1"),
            expected_pallas=0, dispatch_keys=(), fleet=2,
            fleet_class=True,
            notes="shape-class padded batch (fleet/shapeclass.py): two "
                  "DIFFERENT grids ride one 16x16-class program whose "
                  "extents are per-lane data — all-jnp masked chain, "
                  "zero kernels, dead pad cells masked from every "
                  "reduction"),
        ChunkConfig(
            "ns2d_fleet_mesh", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="fft"),
            expected_pallas=0, dispatch_keys=("ns2d_phases",), fleet=8,
            fleet_mesh=True,
            notes="fleet-over-mesh: 8 lanes NamedSharding-sharded over "
                  "the 8-device lint mesh — the traced program is the "
                  "identical vmapped chunk (shardings live at the jit "
                  "boundary), so the census must stay collective-free "
                  "(the zero-resharding serving contract)"),
        # serving v3 (ISSUE 15): the class chunk rides the PRODUCTION
        # kernels — fused PRE/POST at call-time extents plus the padded-
        # class tblock solve. Pure additions; the serving-v2 jnp class
        # config above keeps its byte-identical trace (hash unchanged).
        ChunkConfig(
            "ns2d_fleet_class_fused", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_mesh="1"),
            derive=True, phases_key="ns2d_class_phases",
            solve_key="ns2d_class_solve",
            dispatch_keys=("ns2d_class_phases", "ns2d_class_solve"),
            fleet=2, fleet_class=True,
            notes="the fused class chunk: PRE + padded-class solve + "
                  "POST — exactly three launches per step, extents as "
                  "per-lane SMEM scalars, two DIFFERENT grids on one "
                  "compile"),
        ChunkConfig(
            "ns3d_fleet_class", "ns3d",
            dict(_B3, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_mesh="1"),
            expected_pallas=0, dispatch_keys=("ns3d_class_phases",),
            fleet=2, fleet_class=True,
            notes="3-D class rungs (serving v3): the masked jnp chain "
                  "over ragged3d's select machinery — zero kernels, "
                  "kmax joins the per-lane data"),
        ChunkConfig(
            "ns3d_fleet_class_fused", "ns3d",
            dict(_B3, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_mesh="1"),
            derive=True, phases_key="ns3d_class_phases",
            dispatch_keys=("ns3d_class_phases",),
            fleet=2, fleet_class=True,
            notes="the 3-D fused class chunk: dynamic-extent PRE + POST "
                  "around the masked jnp class solve — exactly two "
                  "launches per step"),
        # the fused V-cycle (ISSUE 16): one dynamic-extent cycle kernel
        # pair per cycle (DOWN: smooth+residual+restrict, UP: prolong+
        # neumann+post-smooth), the jnp bottom between them. Grids here
        # are the SMALLEST that yield a multi-level plan at the default
        # budgets (the fused cycle refuses single-level plans), so the
        # launches=2 census is exercised for real, not vacuously.
        ChunkConfig(
            "ns2d_mg_fused", "ns2d",
            dict(_B2, imax=512, jmax=256, tpu_fuse_phases="off",
                 tpu_solver="mg", tpu_mg_fused="on"),
            derive=True, phases_key="ns2d_phases", mg_key="mg2d_fused",
            dispatch_keys=("ns2d_phases", "mg2d_fused"),
            notes="the fused 2-D V-cycle: jnp phase chain + exactly the "
                  "DOWN/UP kernel pair the mg2d_fused census records — "
                  "512x256 is the smallest plain grid with a 2-level "
                  "plan at the default DCT-bottom budget"),
        ChunkConfig(
            "ns2d_obstacle_mg_fused", "ns2d",
            dict(_OBS, imax=64, jmax=64, tpu_fuse_phases="off",
                 tpu_solver="mg", tpu_mg_fused="on"),
            derive=True, phases_key="ns2d_phases",
            mg_key="mg2d_obstacle_fused",
            dispatch_keys=("ns2d_phases", "mg2d_obstacle_fused"),
            notes="the fused obstacle V-cycle: rediscretized "
                  "eps-coefficient operator per level, masks in the "
                  "kernel, dense exact bottom (64^2 -> 32^2 = exactly "
                  "the dense-bottom budget)"),
        ChunkConfig(
            "ns3d_mg_fused", "ns3d",
            dict(_B3, imax=64, jmax=64, kmax=64, tpu_fuse_phases="off",
                 tpu_solver="mg", tpu_mg_fused="on"),
            derive=True, phases_key="ns3d_phases", mg_key="mg3d_fused",
            dispatch_keys=("ns3d_phases", "mg3d_fused"),
            notes="the fused 3-D V-cycle: the same DOWN/UP pair over "
                  "volume planes (64^3 -> 32^3 two-level plan)"),
        ChunkConfig(
            "ns2d_dist_mg_agg", "ns2d_dist",
            dict(_B2, imax=256, jmax=258, tpu_fuse_phases="off",
                 tpu_solver="mg", tpu_mg_fused="on"),
            dims=(2, 2), expected_pallas=None,
            dispatch_keys=("ns2d_dist_phases", "mg_dist",
                           "mg_dist_fused", "mg_dist_agg"),
            notes="coarse-level aggregation below the shard floor: the "
                  "odd local extent (jl=129) stops the shard ladder at "
                  "one over-budget level, so tpu_mg_fused on continues "
                  "the hierarchy with the replicated global mini-V-cycle "
                  "(mg_dist_agg census; the gather is the declared "
                  "mg_aggregate boundary) — baseline-pinned"),
        ChunkConfig(
            "ns2d_fleet_class_mg", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="mg",
                 tpu_mg_fused="on", tpu_mesh="1"),
            derive=True, phases_key="ns2d_class_phases",
            mg_key="mg_class_fused",
            dispatch_keys=("ns2d_class_phases", "mg_class_fused"),
            fleet=2, fleet_class=True,
            notes="the mg class lane: the whole V-cycle is ONE "
                  "whole-cycle kernel (in-kernel smoothed bottom), so "
                  "the chunk is jnp phases + exactly one launch — two "
                  "DIFFERENT grids ride the same class program via the "
                  "traced-scalar level plan"),
        # K-step fused chunks (ISSUE 17): tpu_chunk_fuse=<K> is forced,
        # so the scan-wrapped chunks trace on CPU. The launch contracts
        # are the SAME counts as the K=1 twins — the scan body traces
        # ONCE, which is the whole point: the static launches-per-step
        # is count/K, derived from the "scan (K=...)" dispatch record
        # and pinned < 3 in check_config.
        ChunkConfig(
            "ns2d_fused_fft_k4", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="fft",
                 tpu_chunk_fuse="4"),
            expected_pallas=2,
            dispatch_keys=("ns2d_phases", "ns2d_chunk_fuse"),
            notes="K=4 scan chunk: still PRE + POST exactly — 0.5 "
                  "launches/step"),
        ChunkConfig(
            "ns3d_fused_fft_k4", "ns3d",
            dict(_B3, tpu_fuse_phases="on", tpu_solver="fft",
                 tpu_chunk_fuse="4"),
            expected_pallas=2,
            dispatch_keys=("ns3d_phases", "ns3d_chunk_fuse"),
            notes="the 3-D K=4 scan chunk: PRE + POST exactly"),
        ChunkConfig(
            "ns2d_dist_fused_k4", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_chunk_fuse="4"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist", "ns2d_dist_chunk_fuse"),
            notes="the K=4 dist scan keeps the K=1 launch budget"),
        ChunkConfig(
            "ns2d_dist_ragged_k4", "ns2d_dist",
            dict(_B2, imax=18, jmax=18, tpu_fuse_phases="on",
                 tpu_solver="sor", tpu_sor_layout="checkerboard",
                 tpu_chunk_fuse="4"),
            dims=(4, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist", "ns2d_dist_chunk_fuse"),
            notes="ragged shards ride the K-scan at uneven bounds"),
        ChunkConfig(
            "ns2d_dist_obstacle_k4", "ns2d_dist",
            dict(_OBS, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_chunk_fuse="4"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="obstacle_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "obstacle_dist", "overlap_ns2d_dist",
                           "ns2d_dist_chunk_fuse"),
            notes="dist obstacle flag blocks compose under the K-scan"),
        ChunkConfig(
            "ns2d_dist_depth", "ns2d_dist",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_mesh_tiers="i=dcn",
                 tpu_chunk_fuse="4", tpu_exchange_depth="i=4"),
            dims=(2, 2), derive=True, phases_key="ns2d_dist_phases",
            solve_key="ns2d_dist", overlap_key="overlap_ns2d_dist",
            dispatch_keys=("ns2d_dist_phases", "ns2d_dist",
                           "overlap_ns2d_dist", "ns2d_dist_chunk_fuse",
                           "ns2d_dist_exchange_depth"),
            notes="per-tier exchange depth: the dcn i axis captures ONE "
                  "depth-4 strip pair per 4-step block (commcheck "
                  "census pins 1 slow exchange per H steps; relaxed "
                  "parity, explicit opt-in)"),
        ChunkConfig(
            "ns3d_dist_fused_k4", "ns3d_dist",
            dict(_B3, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_chunk_fuse="4"),
            dims=(2, 2, 2), derive=True, phases_key="ns3d_dist_phases",
            solve_key="ns3d_dist", overlap_key="overlap_ns3d_dist",
            dispatch_keys=("ns3d_dist_phases", "ns3d_dist",
                           "overlap_ns3d_dist", "ns3d_dist_chunk_fuse"),
            notes="the 3-D K=4 dist scan keeps the K=1 launch budget"),
        # advisory bf16 scouts (ISSUE 20): tpu_dtype=bf16 FORCED onto
        # the NS2D/NS3D SOR paths before the mixed-precision knob
        # exists. Advisory = the precision rule findings (implicit
        # downcasts, f32 residual accumulations, the bf16 eps floor —
        # ~0.125 at 16², far above eps=1e-4, deliberately) are REPORTED
        # by the prec pass, not gated; the cast/reduction census IS
        # pinned in the baseline, so the future bf16 lanes land against
        # a priced contract, not a blank slate.
        ChunkConfig(
            "ns2d_bf16_sor", "ns2d",
            dict(_B2, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_dtype="bf16"),
            expected_pallas=0,
            dispatch_keys=("ns2d_phases", "ns2d_dtype"),
            advisory=True,
            notes="the jnp rb chain at forced bf16: zero kernels, the "
                  "residual accumulates at f32 (sor.py) and every "
                  "f64->bf16 entry cast shows up in the census"),
        ChunkConfig(
            "ns2d_bf16_fused", "ns2d",
            dict(_B2, tpu_fuse_phases="on", tpu_solver="sor",
                 tpu_sor_layout="checkerboard", tpu_dtype="bf16"),
            expected_pallas=None,
            dispatch_keys=("ns2d_phases", "ns2d_p_layout", "ns2d_dtype"),
            advisory=True,
            notes="the fused bf16 chunk (PRE + tblock solve + POST): "
                  "baseline-pinned launches, the kernels' f32 residual "
                  "accumulation (sor_pallas.py) joins the census"),
        ChunkConfig(
            "ns3d_bf16_sor", "ns3d",
            dict(_B3, tpu_fuse_phases="off", tpu_solver="sor",
                 tpu_dtype="bf16"),
            expected_pallas=0,
            dispatch_keys=("ns3d_phases", "ns3d_dtype"),
            advisory=True,
            notes="the 3-D jnp solve at forced bf16: the volume twin of "
                  "the 2-D scout (f32 residual home: ns3d.py)"),
    ]


def chunk_fuse_k(decisions: dict) -> int:
    """The K a traced chunk actually fused, read off its chunk_fuse
    dispatch record. Only a "scan (K=...)" record counts — every
    refusal spelling ("historical (...)") means the chunk advances one
    step per body and the per-step launch math divides by 1."""
    for dkey, dval in decisions.items():
        if not dkey.endswith("chunk_fuse"):
            continue
        sval = str(dval or "")
        km = re.search(r"scan \(K=(\d+)", sval)
        if km:
            return int(km.group(1))
    return 1


def expected_launches(cfg: ChunkConfig, decisions: dict):
    """The launch budget a build's recorded dispatch decisions imply (see
    ChunkConfig). Returns (count, how) — count None when only the
    baseline pins this config."""
    if cfg.expected_pallas is not None:
        return cfg.expected_pallas, "static"
    if not cfg.derive:
        return None, "baseline"
    n = 0
    if (decisions.get(cfg.phases_key) or "").startswith("pallas_fused"):
        n += 2
    if (decisions.get(cfg.fold_key) or "").startswith("folded"):
        n += 1
    if (decisions.get(cfg.solve_key) or "").startswith("pallas"):
        n += 1
    if (decisions.get(cfg.overlap_key) or "").startswith("overlap"):
        n += 1  # the PRE kernel runs twice: interior + boundary halves
    mg = decisions.get(cfg.mg_key) or ""
    if mg.startswith("pallas"):
        # the fused cycle's record IS the budget: "launches=N" names how
        # many pallas_calls one V-cycle costs (2 solo, 1 class lane)
        lm = re.search(r"launches=(\d+)", mg)
        n += int(lm.group(1)) if lm else 1
    return n, "derived"


# ---------------------------------------------------------------------------
# the shared trace matrix
# ---------------------------------------------------------------------------

@dataclass
class TracedConfig:
    """One built-and-traced config of the matrix: the solver, its chunk
    ClosedJaxpr, and the dispatch decisions recorded DURING the build
    (dispatch.last is a last-write register, so they must be captured
    before the next config builds). The jaxpr, comm and pallas passes all
    analyze this one object — tracing the matrix once per lint run, not
    once per pass."""

    cfg: ChunkConfig
    solver: object
    jaxpr: object
    decisions: dict


def trace_config(cfg: ChunkConfig) -> TracedConfig:
    from ..utils import dispatch

    solver = cfg.build()
    jx = trace_chunk(solver)
    return TracedConfig(
        cfg, solver, jx, {k: dispatch.last(k) for k in cfg.dispatch_keys})


def trace_matrix(configs=None) -> list[TracedConfig]:
    return [trace_config(cfg)
            for cfg in (standard_configs() if configs is None else configs)]


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def environment() -> dict:
    import jax

    return {
        "jax": jax.__version__,
        "x64": bool(jax.config.jax_enable_x64),
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
    }


def _anchor(family: str) -> tuple[str, int]:
    import importlib

    mod = importlib.import_module(f"pampi_tpu.models.{family}")
    try:
        return inspect.getsourcefile(mod), 1
    except TypeError:
        return f"pampi_tpu/models/{family}.py", 1


def _forbidden_floats(solver, jaxpr) -> set[str]:
    """Float dtypes outside the precision contract: compute dtype, the
    time-accumulator dtype, f32 (metrics / index math)."""
    import jax
    import jax.numpy as jnp

    allowed = {
        str(jnp.dtype(solver.dtype)),
        "float64" if jax.config.jax_enable_x64 else "float32",
        "float32",
    }
    return float_dtypes(jaxpr.jaxpr) - allowed


def check_config(cfg: ChunkConfig, baseline: dict | None,
                 env_matches: bool,
                 traced: TracedConfig | None = None) -> tuple[list, dict]:
    """Build + trace one config (or reuse a `trace_matrix` entry), check
    the live contracts, and compare against its baseline entry (hash only
    when the environment matches). Returns (violations, fresh baseline
    entry)."""
    path, line = _anchor(cfg.family)
    if traced is None:
        traced = trace_config(cfg)
    solver, jx, decisions = traced.solver, traced.jaxpr, traced.decisions
    sig = chunk_signature(solver, jx)
    entry = {
        "hash": sig["hash"],
        "outvars": sig["outvars"],
        "pallas_calls": sig["pallas_calls"],
        "eqns": sum(sig["prims"].values()),
        "prims": sig["prims"],
        "dispatch": decisions,
    }
    vs: list[Violation] = []

    def emit(rule, msg):
        vs.append(Violation(path, line, rule, f"{cfg.name}: {msg}"))

    # launch count per dispatch decision
    expected, how = expected_launches(cfg, decisions)
    entry["expected_pallas"] = expected
    if expected is not None and sig["pallas_calls"] != expected:
        emit(RULE_LAUNCH,
             f"chunk lowers to {sig['pallas_calls']} pallas_call(s), the "
             f"{how} contract says {expected} "
             f"(dispatch: {decisions}; {cfg.notes})")
    # the fused-cycle launch ceiling (ISSUE 16): any dispatch decision
    # advertising a per-cycle launch census must stay within the budget
    # the amortization argument rests on — 2 solo (DOWN + UP), 1 on the
    # class lane, 3 the hard ceiling
    for dkey, dval in decisions.items():
        lm = re.search(r"launches=(\d+)", str(dval or ""))
        if lm and int(lm.group(1)) > 3:
            emit(RULE_LAUNCH,
                 f"dispatch {dkey} = {dval!r} advertises "
                 f"{lm.group(1)} launches/cycle — the fused-cycle "
                 "contract pins <= 3")
    # launches-per-step (ISSUE 17): a K-fused chunk's scan body traces
    # ONCE, so the static pallas count covers K steps. The per-step
    # ratio is the serving-regime launch metric (bench.py threads it as
    # `launches_per_step`) and is pinned < 3 for any config that traced
    # with K >= 2 — a K-scan that still multiplies launches per step
    # has lost the whole point of fusing across the step boundary.
    kf = chunk_fuse_k(decisions)
    if kf >= 2:
        lps = sig["pallas_calls"] / kf
        entry["launches_per_step"] = lps
        if lps >= 3:
            emit(RULE_LAUNCH,
                 f"K={kf} chunk lowers to {sig['pallas_calls']} pallas "
                 f"launch(es) = {lps:.2f}/step — the K-fusion contract "
                 "pins < 3 launches per step")
    # host callbacks only behind armed flags
    from ..utils import flags as _flags

    if not (_flags.debug() or _flags.verbose() or _flags.check()):
        if sig["callbacks"]:
            emit(RULE_CALLBACK,
                 f"chunk contains host callbacks {sig['callbacks']} with "
                 "no PAMPI_DEBUG/PAMPI_VERBOSE/PAMPI_CHECK armed — each "
                 "costs a host sync per step")
    # dtype policy
    bad = _forbidden_floats(solver, jx)
    if bad:
        emit(RULE_DTYPE,
             f"float dtypes {sorted(bad)} off the precision contract "
             f"(compute dtype {solver.dtype.__name__ if hasattr(solver.dtype, '__name__') else solver.dtype})")
    # metrics arity: initial_state drives every tool's chunk call
    if sig["state_arity"] != sig["invars"] \
            or sig["state_arity"] != sig["outvars"]:
        emit(RULE_ARITY,
             f"initial_state() arity {sig['state_arity']} vs chunk "
             f"invars {sig['invars']} / outvars {sig['outvars']}")
    # baseline comparison — env-gated throughout: launch counts on
    # baseline-only paths depend on toolchain probe outcomes just like
    # the hash does (a mismatched jax reports environment drift once,
    # it does not fail per config)
    if baseline is not None and env_matches:
        if baseline.get("pallas_calls") != sig["pallas_calls"]:
            emit(RULE_LAUNCH,
                 f"pallas_call count drifted from the baseline: "
                 f"{baseline.get('pallas_calls')} -> "
                 f"{sig['pallas_calls']} (tools/lint.py --update if "
                 "intended)")
        if baseline.get("hash") != sig["hash"]:
            diff = diff_histograms(baseline.get("prims", {}), sig["prims"])
            base_disp = baseline.get("dispatch", {})
            ddiff = [f"{k}: {base_disp.get(k)!r} -> {v!r}"
                     for k, v in decisions.items()
                     if base_disp.get(k) != v]
            emit(RULE_HASH,
                 "flag-off trace drifted from CONTRACTS.json; offending "
                 "eqns (primitive-count deltas): "
                 + ("; ".join(diff) if diff else
                    "none — op parameters/ordering changed")
                 + (f"; dispatch: {'; '.join(ddiff)}" if ddiff else "")
                 + " (tools/lint.py --update if intended)")
    return vs, entry


def run(baseline: dict | None = None, configs=None,
        update: bool = False, traced=None) -> tuple[list[Violation], dict]:
    """Check every config. Returns (violations, fresh baseline dict) —
    the driver writes the latter on --update. A missing baseline (or a
    missing config entry) is only an error when not updating. `traced`
    (a `trace_matrix` result) short-circuits the per-config builds so
    several passes can share one matrix."""
    if traced is not None:
        configs = [t.cfg for t in traced]
    configs = standard_configs() if configs is None else configs
    by_name = {t.cfg.name: t for t in traced} if traced else {}
    env = environment()
    base_env = (baseline or {}).get("env")
    env_matches = base_env == env
    base_cfgs = (baseline or {}).get("configs", {})
    vs: list[Violation] = []
    fresh = {"version": BASELINE_VERSION, "env": env, "configs": {}}
    if baseline is not None and not env_matches and not update:
        vs.append(Violation(
            "CONTRACTS.json", 1, RULE_HASH,
            f"baseline environment {base_env} != current {env}: trace-"
            "hash identity not comparable (structural contracts still "
            "checked; regenerate the baseline on this toolchain with "
            "tools/lint.py --update)"))
    for cfg in configs:
        entry = base_cfgs.get(cfg.name)
        if entry is None and baseline is not None and not update:
            vs.append(Violation(
                "CONTRACTS.json", 1, RULE_HASH,
                f"{cfg.name}: no baseline entry (tools/lint.py --update)"))
        cfg_vs, fresh_entry = check_config(
            cfg, None if update else entry, env_matches,
            traced=by_name.get(cfg.name))
        vs += cfg_vs
        fresh["configs"][cfg.name] = fresh_entry
    return vs, fresh
