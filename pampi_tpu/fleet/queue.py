"""Scenario request queue + bucketing: the serving front's intake.

A scenario request is one `.par`-equivalent configuration (utils/params.
Parameter) plus a tenant/scenario id. The scheduler executes requests in
BUCKETS — groups that share one traced program — so a thousand per-user
configs compile once per bucket, not once per user.

Bucketing policy (the one statement of "what may share a trace"):

- The bucket key is (family, grid extents, knob-signature hash). Family
  is ns2d/ns3d (the reference's 2-D/3-D drivers; Poisson requests are
  refused — the fleet serves the NS time-steppers, whose chunk protocol
  `models/_driver.drive_chunks` drives).
- The knob signature is the canonical string of every Parameter field
  that shapes the TRACED program (solver/layout/fusion knobs, physics
  constants baked as trace constants, BC codes, obstacle geometry, te,
  mesh...). Two requests with equal signatures lower to the identical
  chunk program and may ride one vmap batch.
- Excluded from the signature: the per-lane STATE keys (`u_init`,
  `v_init`, `w_init`, `p_init` — pure initial-field values, the natural
  sweep axis: a hundred initial conditions of one configuration is one
  bucket), the per-lane DRIVE keys (`te` — carried in the batched chunk
  state since fleet v2, so mixed end times share one compile and a
  finished lane can be swapped for a queued scenario), and drive-loop
  housekeeping that never enters the trace (checkpoint/restart paths,
  vtk mode, lookahead, retry/recovery knobs, `tpu_fleet` itself).
  Distributed buckets sub-group by te (their shard_map chunk still
  bakes it — fleet/scheduler splits such buckets per te, recorded).

Shape classes (fleet/shapeclass.py) coarsen the key further when the
scheduler enables them: eligible mixed-GRID requests coalesce into one
power-of-two class bucket whose grid extents are per-lane data.

The signature is a string, the bucket id a stable short hash of it —
artifact keys and dispatch records stay readable and machine-stable
across processes (no Python hash randomization).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

from ..utils import tracing as _tr
from ..utils.params import Parameter, is_3d_config, read_parameter

# per-lane state-only keys: they set initial FIELD VALUES, never trace
# structure — the vmap sweep axis
LANE_KEYS = ("u_init", "v_init", "w_init", "p_init")

# per-lane DRIVE keys (fleet v2): trace-shaping for a SOLO build, but
# the batched chunk carries them per lane (te rides the chunk state like
# the per-lane dt already does), so they leave the bucket signature
PER_LANE_KEYS = ("te",)

# drive-loop housekeeping: consumed by the host driver, never traced
HOUSEKEEPING_KEYS = (
    "tpu_checkpoint", "tpu_ckpt_every", "tpu_restart", "tpu_vtk",
    "tpu_lookahead", "tpu_retry_replenish", "tpu_recover_ring",
    "tpu_recover_dt_scale", "tpu_recover_max", "tpu_fleet",
    "tpu_autopilot", "seen_keys",
)

# the signature-excluded keys that still STEER the drive loop (retry /
# recovery / pipelining policy). They can differ within a bucket, so the
# executors must take them from the REQUESTS, never from whichever
# tenant happened to build the cached template: pjit lanes honor each
# request's own values (scheduler._reset_lane), a vmap batch — which has
# ONE drive loop for all lanes — takes its FIRST request's values
# (batch.BatchedSolver, documented batch-level policy).
DRIVE_KEYS = ("tpu_lookahead", "tpu_retry_replenish", "tpu_recover_ring",
              "tpu_recover_dt_scale", "tpu_recover_max",
              "tpu_checkpoint", "tpu_ckpt_every")


@dataclasses.dataclass
class ScenarioRequest:
    """One tenant's run request: a scenario id + its configuration."""

    sid: str
    param: Parameter
    # request-lifecycle trace id (utils/tracing.mint at daemon
    # admission); None outside the traced serving path — every tracing
    # helper no-ops on None, so batch-mode callers never pay for it
    trace: str | None = None


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """The shared-trace equivalence class of a request."""

    family: str      # ns2d | ns3d
    grid: tuple      # (imax, jmax[, kmax])
    sig: str         # knob-signature hash (stable across processes)

    @property
    def label(self) -> str:
        return f"{self.family}_{'x'.join(str(g) for g in self.grid)}" \
               f"_{self.sig}"


def family_of(param: Parameter) -> str:
    """ns2d/ns3d from the config geometry (utils/params.is_3d_config —
    the same dispatch the CLI driver uses). Poisson requests are refused
    (the fleet drives the NS chunk protocol), and so are restart
    requests: the CLI wires `tpu_restart` into the solver before the
    drive, the fleet builds fresh per-lane initial states — silently
    serving a t=0 run where the tenant asked for a restart would be a
    wrong answer, not a degraded one. (`tpu_checkpoint` is merely INERT
    here — no fleet path passes the checkpoint hook — which loses
    durability, never correctness.)"""
    if param.name == "poisson":
        raise ValueError(
            "the scenario fleet serves the NS families (dcavity/canal/"
            "canal_obstacle and the 3-D twins); run poisson configs "
            "through the CLI driver"
        )
    if param.tpu_restart:
        raise ValueError(
            "fleet requests cannot restart from a checkpoint "
            "(tpu_restart is set); run restarts through the CLI driver "
            "— fleet lanes always start from their .par initial fields"
        )
    return "ns3d" if is_3d_config(param) else "ns2d"


def knob_signature(param: Parameter) -> str:
    """Canonical string of every trace-shaping Parameter field — equal
    signatures <=> the solvers build the identical chunk program (the
    vmap-batch eligibility contract, test-pinned)."""
    skip = set(LANE_KEYS) | set(HOUSEKEEPING_KEYS) | set(PER_LANE_KEYS)
    parts = []
    for f in dataclasses.fields(Parameter):
        if f.name in skip:
            continue
        parts.append(f"{f.name}={getattr(param, f.name)!r}")
    return "|".join(parts)


def signature_hash(param: Parameter) -> str:
    return hashlib.sha1(
        knob_signature(param).encode()).hexdigest()[:12]


def bucket_key(param: Parameter) -> BucketKey:
    family = family_of(param)
    grid = ((param.imax, param.jmax, param.kmax) if family == "ns3d"
            else (param.imax, param.jmax))
    return BucketKey(family=family, grid=grid, sig=signature_hash(param))


_UNSET = object()


def class_bucket_key(param, why_not=_UNSET) -> "BucketKey | None":
    """The SHAPE-CLASS bucket of a request, or None when it must keep
    its exact-shape bucket (fleet/shapeclass.class_eligible). The key's
    grid is the padded class grid — 2-D or 3-D rungs per family (3-D
    classes since serving v3); the signature hash excludes the grid
    extents (per-lane data in the class chunk) and carries a "cls"
    prefix so a class bucket can never collide with an exact bucket of
    the same grid. `why_not` takes a precomputed class_eligible result
    (bucket()'s admission hot path runs eligibility once per request,
    not twice)."""
    from . import shapeclass as sc

    family = family_of(param)
    if why_not is _UNSET:
        why_not = sc.class_eligible(param)
    if why_not is not None:
        return None
    grid = sc.class_grid(
        (param.imax, param.jmax, param.kmax) if family == "ns3d"
        else (param.imax, param.jmax))
    return BucketKey(family=family, grid=grid,
                     sig=sc.class_sig_hash(param))


def bucket(requests, classes: bool = False) -> dict:
    """Group requests by shared-trace bucket; insertion-ordered (the
    scheduler executes buckets in first-seen order, lanes in submit
    order — deterministic end-to-end). `classes=True` routes eligible
    requests into shape-class buckets (pad-and-mask shared compiles),
    RECORDING each request's eligibility decision per bucket
    (`utils/dispatch.resolve_class`, key `class_<bucket>` — a refused
    request's exact-shape landing carries the class_eligible reason);
    ineligible requests keep their exact-shape bucket either way."""
    from ..utils import dispatch as _dispatch
    from . import shapeclass as sc

    out: dict[BucketKey, list[ScenarioRequest]] = {}
    for req in requests:
        key = None
        if classes:
            why_not = sc.class_eligible(req.param)
            key = class_bucket_key(req.param, why_not=why_not)
            label = (key if key is not None
                     else bucket_key(req.param)).label
            _dispatch.resolve_class(
                f"class_{label}",
                key.grid if key is not None else (), why_not)
            if key is not None:
                # class resolution is a waterfall detail mark: when the
                # request's shape class resolved, inside queue_wait
                _tr.mark(req.trace, "class_pad")
        if key is None:
            key = bucket_key(req.param)
        _tr.mark(req.trace, "bucket")
        _tr.note(req.trace, bucket=key.label, family=key.family)
        out.setdefault(key, []).append(req)
    return out


def load_queue(paths, base: Parameter | None = None,
               on_error=None) -> list[ScenarioRequest]:
    """Read a queue of `.par` files into requests; the scenario id is the
    file stem (deduplicated with #k suffixes for repeated stems).

    `on_error(path, exc)`, when given, HARDENS the intake: a malformed
    or unreadable .par (parse failure, bad value, unreadable file,
    fleet-ineligible config like a poisson/restart request) is handed to
    the callback and SKIPPED instead of killing the caller — the serving
    daemon parks such files with a structured `warning` telemetry record
    (fleet/serve.py). None keeps the historical raise-through behavior.
    read_parameter's reference-parity SystemExit on bad input is caught
    and converted like any other error (a daemon must never inherit the
    CLI's exit-on-bad-config semantics from one tenant's file)."""
    reqs: list[ScenarioRequest] = []
    seen: dict[str, int] = {}
    for path in paths:
        stem = os.path.splitext(os.path.basename(path))[0]
        n = seen.get(stem, 0)
        sid = stem if n == 0 else f"{stem}#{n}"
        try:
            param = read_parameter(path, base)
            if on_error is not None:
                # hardened intake only: refuse poisson/restart requests
                # HERE so the daemon parks them (the historical path
                # keeps refusing at bucketing time, unchanged)
                family_of(param)
        except (SystemExit, ValueError, OSError) as exc:
            if on_error is None:
                raise
            on_error(path, exc)
            continue
        seen[stem] = n + 1
        reqs.append(ScenarioRequest(sid=sid, param=param))
    return reqs
