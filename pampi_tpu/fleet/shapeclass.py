"""Shape-class batching: pad-and-mask mixed-GRID requests into shared
compiles (ROADMAP item 2's serving rung).

A fleet serving thousands of slightly-different grids must not compile
thousands of programs. This module defines a small ladder of SHAPE
CLASSES — power-of-two rungs per axis with a floor — and one traced
chunk per class whose grid EXTENTS are per-lane data, not trace
constants: a 20x24 request and a 28x17 request both ride the 32x32
class program, each lane carrying its own (imax, jmax, dx, dy, ...) as
traced scalars.

The chunk is the ragged machinery promoted to the serving layer: the
dist solvers already express every wall write as a select by GLOBAL
index (parallel/ragged2d.py — proven against the solo solver at the ulp
contract), and those selects work unchanged when jmax/imax are traced
per-lane scalars on ONE full padded block (grids= hooks, offset 0, no
shard_map). Dead pad cells hold exact 0.0 and are kept out of every
reduction by live/interior masks built from the same global-index
comparisons (`live_masks` semantics), so pad garbage never reaches the
CFL scan, the residual sum, or the pressure mean — and a padded lane
tracks its unpadded solo twin to reduction order (bitwise coefficients:
every grid-derived constant the solo solver folds in Python f64 — dx,
dy, dt_bound, the SOR factor, idx2/idy2, the residual norm — is
computed host-side per lane with the identical expressions and carried
in the lane's geometry vector).

Class eligibility is conservative (the exact-shape bucket is always the
fallback, recorded per bucket): 2-D, no obstacle flags, the reference
"sor" solve, a single-device lane, grids at least MIN_CLASS_EXTENT per
axis. `palcheck.shapeclass_violations` bounds the padding waste per
class: above the eligibility floor the padded extent stays under 2x the
live extent per axis, so a class never burns more than WASTE_BOUND
(4x) the live cells.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

# the rung ladder: per-axis class extent = next power of two, floored —
# a floor keeps the tiny end of the ladder from fragmenting into many
# near-empty compiles (a 9x12 and a 14x10 request share the 16x16 rung)
RUNG_FLOOR = 16
# smallest per-axis live extent the class path accepts; below it the
# pad ratio can exceed the waste bound, so such requests keep their
# exact-shape bucket (recorded)
MIN_CLASS_EXTENT = 8
# padding-waste contract, checked by analysis/palcheck: padded cells /
# live cells (ghost-inclusive) stays strictly under this per class for
# every eligible grid
WASTE_BOUND = 4.0

# geometry-vector slots (per lane, time-dtype precision): every
# grid-derived scalar the solo solver folds as a Python-float constant,
# computed host-side with the IDENTICAL expressions (bitwise at f64)
G_IMAX, G_JMAX, G_DX, G_DY, G_DTB, G_FACTOR, G_IDX2, G_IDY2, G_NORM = \
    range(9)
GEOM_LEN = 9

# class-signature exclusions ON TOP of the queue's lane/housekeeping
# sets: the grid extents become per-lane data (xlength/ylength stay in
# the signature — the canal inflow profile bakes ylength as a value)
CLASS_KEYS = ("imax", "jmax")


def class_extent(n: int) -> int:
    """The rung of one live extent: next power of two, >= RUNG_FLOOR."""
    c = RUNG_FLOOR
    while c < n:
        c *= 2
    return c


def class_grid(grid) -> tuple:
    return tuple(class_extent(int(n)) for n in grid)


def padding_waste(grid) -> float:
    """Padded cells / live cells, ghost rings included — the per-class
    waste the palcheck contract bounds."""
    cls = class_grid(grid)
    padded = 1.0
    live = 1.0
    for n, c in zip(grid, cls):
        padded *= c + 2
        live *= n + 2
    return padded / live


def class_eligible(param) -> str | None:
    """None when the request may ride a shape class; else the reason it
    keeps its exact-shape bucket (recorded per bucket)."""
    from ..cli import mesh_is_single
    from ..utils.params import is_3d_config

    if is_3d_config(param):
        return "3-D family (shape classes are 2-D; exact bucket)"
    if param.obstacles.strip():
        return "obstacle flags are trace-baked geometry"
    if param.tpu_solver != "sor":
        return f"tpu_solver {param.tpu_solver} (class solve is rb-sor)"
    if param.tpu_flat_solve:
        return "tpu_flat_solve trips are extent-derived"
    if not mesh_is_single(param):
        return "distributed lane (whole-mesh shards are shape-baked)"
    if param.tpu_fleet not in ("auto", "vmap"):
        return f"tpu_fleet {param.tpu_fleet} forced"
    if param.imax < MIN_CLASS_EXTENT or param.jmax < MIN_CLASS_EXTENT:
        return (f"grid {param.imax}x{param.jmax} below the "
                f"{MIN_CLASS_EXTENT}-cell class floor (padding waste "
                "would exceed the bound)")
    return None


def class_signature(param) -> str:
    """The shape-class knob signature: the queue's trace-shaping
    signature minus the per-lane grid extents."""
    from . import queue as _q

    skip = set(_q.LANE_KEYS) | set(_q.HOUSEKEEPING_KEYS) \
        | set(_q.PER_LANE_KEYS) | set(CLASS_KEYS)
    parts = []
    for f in dataclasses.fields(type(param)):
        if f.name in skip:
            continue
        parts.append(f"{f.name}={getattr(param, f.name)!r}")
    return "|".join(parts)


def class_sig_hash(param) -> str:
    return "cls" + hashlib.sha1(
        class_signature(param).encode()).hexdigest()[:12]


def lane_geometry(param):
    """The per-lane geometry scalars, each computed in Python f64 exactly
    as the solo solver folds them (NS2DSolver.__init__ /
    models/poisson.make_rb_step) — the bitwise-coefficient contract."""
    dx = param.xlength / param.imax
    dy = param.ylength / param.jmax
    inv_sqr_sum = 1.0 / (dx * dx) + 1.0 / (dy * dy)
    dt_bound = 0.5 * param.re / inv_sqr_sum
    dx2, dy2 = dx * dx, dy * dy
    factor = param.omg * 0.5 * (dx2 * dy2) / (dx2 + dy2)
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    norm = float(param.imax * param.jmax)
    return (float(param.imax), float(param.jmax), dx, dy, dt_bound,
            factor, idx2, idy2, norm)


def _index_grids(jc: int, ic: int):
    import jax.numpy as jnp

    gj = jnp.arange(jc + 2, dtype=jnp.int32)[:, None]
    gi = jnp.arange(ic + 2, dtype=jnp.int32)[None, :]
    return gj, gi


def make_class_solve(param, jc: int, ic: int, dtype, grids):
    """The masked red-black SOR convergence loop at TRACED extents —
    models/poisson.make_solver_fn's jnp rb path (red half-sweep, black
    half-sweep seeing red's updates, Neumann ghost copy, normalized
    residual vs eps^2) with every position select-by-global-index and
    every reduction confined to the dynamic interior (dead cells
    contribute exact zeros)."""
    import jax.numpy as jnp
    from jax import lax

    gj, gi = grids
    epssq = param.eps * param.eps
    itermax = param.itermax
    res_dtype = jnp.promote_types(dtype, jnp.float32)

    def solve(p0, rhs, imax, jmax, factor, idx2, idy2, norm):
        factor = factor.astype(dtype)
        idx2 = idx2.astype(dtype)
        idy2 = idy2.astype(dtype)
        norm = norm.astype(dtype)
        interior = ((gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax))
        parity = (gi + gj) % 2
        red = (interior & (parity == 0)).astype(dtype)
        black = (interior & (parity == 1)).astype(dtype)
        tan_j = (gj >= 1) & (gj <= jmax)
        tan_i = (gi >= 1) & (gi <= imax)
        m_s = (gj == 0) & tan_i
        m_n = (gj == jmax + 1) & tan_i
        m_w = (gi == 0) & tan_j
        m_e = (gi == imax + 1) & tan_j

        def sweep(p, mask):
            # ops/sor.sor_pass arithmetic on the full block: the masked
            # r is exact 0 off its colour, so the update adds -0.0
            # (identity) everywhere the solo .at[].add never touched
            lap = (
                (jnp.roll(p, -1, axis=1) - 2.0 * p
                 + jnp.roll(p, 1, axis=1)) * idx2
                + (jnp.roll(p, -1, axis=0) - 2.0 * p
                   + jnp.roll(p, 1, axis=0)) * idy2
            )
            r = (rhs - lap) * mask
            return p + (-factor) * r, jnp.sum(r * r)

        def neumann(p):
            # ops/sor.neumann_bc as selects: same write order, corners
            # untouched (the masks exclude them)
            p = jnp.where(m_s, jnp.roll(p, -1, axis=0), p)
            p = jnp.where(m_n, jnp.roll(p, 1, axis=0), p)
            p = jnp.where(m_w, jnp.roll(p, -1, axis=1), p)
            p = jnp.where(m_e, jnp.roll(p, 1, axis=1), p)
            return p

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, r0 = sweep(p, red)
            p, r1 = sweep(p, black)
            p = neumann(p)
            res = ((r0 + r1) / norm).astype(res_dtype)
            return p, res, it + 1

        return lax.while_loop(
            cond, body,
            (p0, jnp.asarray(1.0, res_dtype), jnp.asarray(0, jnp.int32)))

    return solve


def make_class_chunk(param, jc: int, ic: int, dtype,
                     metrics: bool = False, chunk_default: int = 64):
    """One shape class's chunk program: models/ns2d._build_step's phase
    order with grid extents as per-lane traced scalars. Lane state is
    (u, v, p, t, nt, gm[, m]) plus the carried te (the fleet's per-lane
    te convention — te is always the trailing argument)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import ns2d as ops
    from ..parallel import ragged2d as rg
    from ..utils import telemetry as _tm

    grids = _index_grids(jc, ic)
    gj, gi = grids
    adaptive = param.tau > 0.0
    chunk = param.tpu_chunk or chunk_default
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    solve = make_class_solve(param, jc, ic, dtype, grids)

    def step(u, v, p, t, nt, gm):
        imax, jmax = gm[G_IMAX], gm[G_JMAX]  # whole-number scalars
        dx = gm[G_DX].astype(dtype)
        dy = gm[G_DY].astype(dtype)
        dtb = gm[G_DTB].astype(dtype)
        interior = ((gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax))
        live = (gj <= jmax + 1) & (gi <= imax + 1)
        if adaptive:
            # ghost-inclusive maxElement scan: dead cells are exact 0,
            # so the padded max IS the live max
            dt = ops.cfl_dt(ops.max_element(u), ops.max_element(v),
                            dtb, dx, dy, param.tau)
        else:
            dt = jnp.asarray(param.dt, dtype)
        u, v = rg.set_bcs_ragged(u, v, param, None, jc, ic, jmax, imax,
                                 grids=grids)
        u = rg.set_special_bc_ragged(u, param, None, jc, ic, jmax, imax,
                                     dy, dtype, grids=grids)
        f, g = ops.compute_fg_interior(u, v, dt, param.re, param.gx,
                                       param.gy, param.gamma, dx, dy)
        f, g = rg.fg_fixups_ragged(f, g, u, v, None, jc, ic, jmax, imax,
                                   grids=grids)
        rhs = jnp.where(interior, ops.rhs_terms(f, g, dt, dx, dy),
                        jnp.zeros_like(f))

        def norm_p(q):
            # normalizePressure over the live array only: the dynamic
            # count replaces the static size, dead cells stay 0
            cnt = ((jmax + 2.0) * (imax + 2.0)).astype(dtype)
            mean = jnp.sum(jnp.where(live, q, jnp.zeros_like(q))) / cnt
            return jnp.where(live, q - mean, q)

        p = lax.cond(nt % 100 == 0, norm_p, lambda q: q, p)
        p, res, it = solve(p, rhs, imax, jmax, gm[G_FACTOR],
                           gm[G_IDX2], gm[G_IDY2], gm[G_NORM])
        u_new, v_new = ops.adapt_terms(f, g, p, dt, dx, dy)
        u = jnp.where(interior, u_new, u)
        v = jnp.where(interior, v_new, v)
        # the ragged POST convention: multiply-mask so pad cells stay
        # exact 0 for the next step's scans (identity on live cells)
        lm = live.astype(dtype)
        u = u * lm
        v = v * lm
        t_next = t + dt.astype(time_dtype)
        return u, v, p, t_next, nt + 1, res, it, dt

    def chunk_fn(u, v, p, t, nt, gm, te):
        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            u, v, p, t, nt, gm, k = c
            u, v, p, t, nt, _res, _it, _dt = step(u, v, p, t, nt, gm)
            return u, v, p, t, nt, gm, k + 1

        u, v, p, t, nt, gm, _k = lax.while_loop(
            cond, body, (u, v, p, t, nt, gm, jnp.asarray(0, jnp.int32)))
        return u, v, p, t, nt, gm

    def chunk_fn_metrics(u, v, p, t, nt, gm, m, te):
        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            u, v, p, t, nt, gm, k, res, it, dtv, um, vm, bad = c
            u, v, p, t, nt, res, it, dtv = step(u, v, p, t, nt, gm)
            res, it, dtv, um, vm, bad = _tm.metrics_step(
                bad, nt, res, it, dtv,
                ops.max_element(u), ops.max_element(v))
            return u, v, p, t, nt, gm, k + 1, res, it, dtv, um, vm, bad

        (u, v, p, t, nt, gm, _k,
         res, it, dtv, um, vm, bad) = lax.while_loop(
            cond, body,
            (u, v, p, t, nt, gm, jnp.asarray(0, jnp.int32),
             m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
             m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_BAD]))
        return u, v, p, t, nt, gm, _tm.metrics_pack(
            res, it, dtv, um, vm, 0.0, bad)

    return chunk_fn_metrics if metrics else chunk_fn


class ClassSolver:
    """The template of one shape class: a BatchedSolver-compatible
    template whose chunk takes grid extents as per-lane data. Built from
    a representative request; every same-class-signature request of any
    eligible grid rides this one compile (`fleet/batch.BatchedSolver`
    with te always carried)."""

    CHUNK = 64
    # the class chunk takes te unconditionally (its carry is inherently
    # per-lane) — BatchedSolver reads this and always carries te
    _te_always = True

    def __init__(self, param, ic: int, jc: int, dtype=None):
        import time as _time

        import jax

        from ..utils import telemetry as _tm
        from ..utils.precision import resolve_dtype

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        if class_extent(param.imax) > ic or class_extent(param.jmax) > jc:
            raise ValueError(
                f"grid {param.imax}x{param.jmax} exceeds class "
                f"{ic}x{jc}")
        self.param = param.replace(imax=ic, jmax=jc)
        self._request = param
        self.ic, self.jc = ic, jc
        self.dtype = resolve_dtype(param.tpu_dtype) if dtype is None \
            else dtype
        self._backend = "jnp"  # the class chunk is the masked jnp chain
        self._dt_scale = 1.0
        self._metrics = _tm.enabled()
        self._time_index = 3
        self._n_fields = 3
        t0 = _time.perf_counter()
        self._chunk_fn = jax.jit(self._build_chunk())
        _tm.emit("build", family="ns2d_class",
                 grid=[jc, ic], cls=f"{ic}x{jc}",
                 trace_wall_s=round(_time.perf_counter() - t0, 3))

    def _uses_pallas(self) -> bool:
        return False

    def _build_chunk(self, backend: str | None = None,
                     te_arg: bool = True):
        # backend is accepted for the retry-protocol surface; the class
        # chunk has exactly one (jnp) program. te is ALWAYS the trailing
        # traced argument — the class carry is inherently per-lane.
        self._metrics = _metrics_enabled()
        return make_class_chunk(self.param, self.jc, self.ic, self.dtype,
                                metrics=self._metrics,
                                chunk_default=self.CHUNK)

    # -- per-lane state (the BatchedSolver template hooks) --------------
    def lane_state(self, param) -> tuple:
        import jax
        import jax.numpy as jnp

        from ..utils import telemetry as _tm

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        jc, ic = self.jc, self.ic
        live = ((np.arange(jc + 2)[:, None] <= param.jmax + 1)
                & (np.arange(ic + 2)[None, :] <= param.imax + 1))
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32

        def field(init):
            return jnp.asarray(
                np.where(live, init, 0.0), self.dtype)

        gm = jnp.asarray(lane_geometry(param), time_dtype)
        out = (field(param.u_init), field(param.v_init),
               field(param.p_init),
               jnp.asarray(0.0, time_dtype), jnp.asarray(0, jnp.int32),
               gm)
        if self._metrics:
            out = out + (_tm.metrics_init(),)
        return out

    def crop_lane(self, fields, param) -> tuple:
        """Unpad one lane's published fields back to the request's own
        (jmax+2, imax+2) reference layout."""
        return tuple(np.asarray(f)[:param.jmax + 2, :param.imax + 2]
                     for f in fields)

    def initial_state(self) -> tuple:
        return self.lane_state(self._request)


def _metrics_enabled() -> bool:
    from ..utils import telemetry as _tm

    return _tm.enabled()
