"""Shape-class batching: pad-and-mask mixed-GRID requests into shared
compiles (ROADMAP item 2's serving rung).

A fleet serving thousands of slightly-different grids must not compile
thousands of programs. This module defines a small ladder of SHAPE
CLASSES — power-of-two rungs per axis with a floor — and one traced
chunk per class whose grid EXTENTS are per-lane data, not trace
constants: a 20x24 request and a 28x17 request both ride the 32x32
class program, each lane carrying its own (imax, jmax, dx, dy, ...) as
traced scalars.

The chunk is the ragged machinery promoted to the serving layer: the
dist solvers already express every wall write as a select by GLOBAL
index (parallel/ragged2d.py — proven against the solo solver at the ulp
contract), and those selects work unchanged when jmax/imax are traced
per-lane scalars on ONE full padded block (grids= hooks, offset 0, no
shard_map). Dead pad cells hold exact 0.0 and are kept out of every
reduction by live/interior masks built from the same global-index
comparisons (`live_masks` semantics), so pad garbage never reaches the
CFL scan, the residual sum, or the pressure mean — and a padded lane
tracks its unpadded solo twin to reduction order (bitwise coefficients:
every grid-derived constant the solo solver folds in Python f64 — dx,
dy, dt_bound, the SOR factor, idx2/idy2, the residual norm — is
computed host-side per lane with the identical expressions and carried
in the lane's geometry vector).

Class eligibility is conservative (the exact-shape bucket is always the
fallback, recorded per bucket via `utils/dispatch.resolve_class`): no
obstacle flags, the reference "sor" solve in the checkerboard-compatible
layouts, a single-device lane, grids at least MIN_CLASS_EXTENT per axis.
Since serving v3 (ISSUE 15) the ladder covers BOTH NS families — 3-D
rungs ride `parallel/ragged3d.py`'s identical select machinery
(`Class3DSolver`) — and the class chunk rides the PRODUCTION kernels:
when `tpu_fuse_phases` dispatches (the solo policy, `resolve_fuse_phases`
under the `ns2d_class_phases`/`ns3d_class_phases` keys), the chunk lowers
to the fused PRE/POST megakernels with the per-lane live extents as
call-time SMEM scalars (`ops/ns2d_fused.py` / `ops/ns3d_fused.py`
`dynamic=True` — pad cells are dead writes inside the same kernel), and
the 2-D pressure solve runs as the extent-gated `sor_pallas` tblock
kernel in the padded class layout (`make_padded_class_solve` — the
dominant per-step cost stops being jnp inside class lanes; the 3-D class
solve stays the masked jnp rb loop). The jnp masked chain remains the
parity oracle (`tpu_fuse_phases off` forces it — kernel-off lanes trace
byte-identically to serving v2). Since the fused-V-cycle PR, 2-D
`tpu_solver mg` requests join the ladder: their solve is the ONE-LAUNCH
dynamic-extent cycle kernel (`ops/mg_fused.make_class_cycle_2d` via
`make_class_mg_solve` — level plan from per-lane call-time extents,
in-kernel smoothed bottom, `tpu_mg_fused` gated under the
`mg_class_fused` dispatch key; knob-off mg requests keep their
exact-shape bucket). `palcheck.shapeclass_violations` bounds
the padding waste per class: above the eligibility floor the padded
extent stays under 2x the live extent per axis, so a 2-D class never
burns more than WASTE_BOUND (4x) the live cells (8x for a 3-D class,
the same per-axis bound cubed).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

# the rung ladder: per-axis class extent = next power of two, floored —
# a floor keeps the tiny end of the ladder from fragmenting into many
# near-empty compiles (a 9x12 and a 14x10 request share the 16x16 rung)
RUNG_FLOOR = 16
# smallest per-axis live extent the class path accepts; below it the
# pad ratio can exceed the waste bound, so such requests keep their
# exact-shape bucket (recorded)
MIN_CLASS_EXTENT = 8
# padding-waste contract, checked by analysis/palcheck: padded cells /
# live cells (ghost-inclusive) stays strictly under this per class for
# every eligible grid (the per-axis < 2x bound squared; cubed for the
# 3-D rungs — serving v3)
WASTE_BOUND = 4.0
WASTE_BOUND_3D = 8.0

# geometry-vector slots (per lane, time-dtype precision): every
# grid-derived scalar the solo solver folds as a Python-float constant,
# computed host-side with the IDENTICAL expressions (bitwise at f64)
G_IMAX, G_JMAX, G_DX, G_DY, G_DTB, G_FACTOR, G_IDX2, G_IDY2, G_NORM = \
    range(9)
GEOM_LEN = 9

# class-signature exclusions ON TOP of the queue's lane/housekeeping
# sets: the grid extents become per-lane data (xlength/ylength/zlength
# stay in the signature — the canal inflow profile bakes ylength as a
# value). kmax joins for the 3-D rungs; for a 2-D family it is a default
# the signature never needed.
CLASS_KEYS = ("imax", "jmax", "kmax")


def class_extent(n: int) -> int:
    """The rung of one live extent: next power of two, >= RUNG_FLOOR."""
    c = RUNG_FLOOR
    while c < n:
        c *= 2
    return c


def class_grid(grid) -> tuple:
    return tuple(class_extent(int(n)) for n in grid)


def padding_waste(grid) -> float:
    """Padded cells / live cells, ghost rings included — the per-class
    waste the palcheck contract bounds."""
    cls = class_grid(grid)
    padded = 1.0
    live = 1.0
    for n, c in zip(grid, cls):
        padded *= c + 2
        live *= n + 2
    return padded / live


def class_eligible(param) -> str | None:
    """None when the request may ride a shape class; else the reason it
    keeps its exact-shape bucket (recorded per bucket via
    `utils/dispatch.resolve_class`). 2-D AND 3-D families are eligible
    since serving v3 — the 3-D rungs ride the same select machinery."""
    from ..cli import mesh_is_single
    from ..utils.params import is_3d_config

    if param.obstacles.strip():
        return "obstacle flags are trace-baked geometry"
    if param.tpu_solver not in ("sor", "mg"):
        return (f"tpu_solver {param.tpu_solver} (class solves are rb-sor "
                "and the one-launch mg cycle)")
    if param.tpu_solver == "mg":
        if is_3d_config(param):
            return "3-D mg lane (the one-launch class cycle is 2-D)"
        if param.tpu_mg_fused == "off":
            return ("tpu_mg_fused off (the mg class solve IS the fused "
                    "cycle kernel)")
    if param.tpu_sor_layout not in ("auto", "checkerboard"):
        return (f"tpu_sor_layout {param.tpu_sor_layout} forced (the "
                "class solve is the checkerboard padded layout)")
    if param.tpu_flat_solve:
        return "tpu_flat_solve trips are extent-derived"
    if not mesh_is_single(param):
        return "distributed lane (whole-mesh shards are shape-baked)"
    if param.tpu_fleet not in ("auto", "vmap"):
        return f"tpu_fleet {param.tpu_fleet} forced"
    extents = ((param.imax, param.jmax, param.kmax)
               if is_3d_config(param) else (param.imax, param.jmax))
    if any(n < MIN_CLASS_EXTENT for n in extents):
        return (f"grid {'x'.join(str(n) for n in extents)} below the "
                f"{MIN_CLASS_EXTENT}-cell class floor (padding waste "
                "would exceed the bound)")
    return None


def class_signature(param) -> str:
    """The shape-class knob signature: the queue's trace-shaping
    signature minus the per-lane grid extents."""
    from . import queue as _q

    skip = set(_q.LANE_KEYS) | set(_q.HOUSEKEEPING_KEYS) \
        | set(_q.PER_LANE_KEYS) | set(CLASS_KEYS)
    parts = []
    for f in dataclasses.fields(type(param)):
        if f.name in skip:
            continue
        parts.append(f"{f.name}={getattr(param, f.name)!r}")
    # the RUNG is part of the traced program's shape even though the
    # request's own extents are per-lane data: two rungs of otherwise
    # equal knobs must never share a signature (the scheduler's
    # _TEMPLATES cache is sig-keyed — a collision hands a 16^2 template
    # to a 32^2 bucket and every lane trips the exceeds-class guard)
    from ..utils.params import is_3d_config

    extents = ((param.imax, param.jmax, param.kmax)
               if is_3d_config(param) else (param.imax, param.jmax))
    parts.append("rung=" + "x".join(str(c) for c in class_grid(extents)))
    return "|".join(parts)


def class_sig_hash(param) -> str:
    return "cls" + hashlib.sha1(
        class_signature(param).encode()).hexdigest()[:12]


def lane_geometry(param):
    """The per-lane geometry scalars, each computed in Python f64 exactly
    as the solo solver folds them (NS2DSolver.__init__ /
    models/poisson.make_rb_step) — the bitwise-coefficient contract."""
    dx = param.xlength / param.imax
    dy = param.ylength / param.jmax
    inv_sqr_sum = 1.0 / (dx * dx) + 1.0 / (dy * dy)
    dt_bound = 0.5 * param.re / inv_sqr_sum
    dx2, dy2 = dx * dx, dy * dy
    factor = param.omg * 0.5 * (dx2 * dy2) / (dx2 + dy2)
    idx2, idy2 = 1.0 / dx2, 1.0 / dy2
    norm = float(param.imax * param.jmax)
    return (float(param.imax), float(param.jmax), dx, dy, dt_bound,
            factor, idx2, idy2, norm)


def _index_grids(jc: int, ic: int):
    import jax.numpy as jnp

    gj = jnp.arange(jc + 2, dtype=jnp.int32)[:, None]
    gi = jnp.arange(ic + 2, dtype=jnp.int32)[None, :]
    return gj, gi


def make_class_solve(param, jc: int, ic: int, dtype, grids):
    """The masked red-black SOR convergence loop at TRACED extents —
    models/poisson.make_solver_fn's jnp rb path (red half-sweep, black
    half-sweep seeing red's updates, Neumann ghost copy, normalized
    residual vs eps^2) with every position select-by-global-index and
    every reduction confined to the dynamic interior (dead cells
    contribute exact zeros)."""
    import jax.numpy as jnp
    from jax import lax

    gj, gi = grids
    epssq = param.eps * param.eps
    itermax = param.itermax
    res_dtype = jnp_promote(dtype)

    def solve(p0, rhs, imax, jmax, factor, idx2, idy2, norm):
        factor = factor.astype(dtype)
        idx2 = idx2.astype(dtype)
        idy2 = idy2.astype(dtype)
        norm = norm.astype(dtype)
        interior = ((gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax))
        parity = (gi + gj) % 2
        red = (interior & (parity == 0)).astype(dtype)
        black = (interior & (parity == 1)).astype(dtype)
        tan_j = (gj >= 1) & (gj <= jmax)
        tan_i = (gi >= 1) & (gi <= imax)
        m_s = (gj == 0) & tan_i
        m_n = (gj == jmax + 1) & tan_i
        m_w = (gi == 0) & tan_j
        m_e = (gi == imax + 1) & tan_j

        def sweep(p, mask):
            # ops/sor.sor_pass arithmetic on the full block: the masked
            # r is exact 0 off its colour, so the update adds -0.0
            # (identity) everywhere the solo .at[].add never touched
            lap = (
                (jnp.roll(p, -1, axis=1) - 2.0 * p
                 + jnp.roll(p, 1, axis=1)) * idx2
                + (jnp.roll(p, -1, axis=0) - 2.0 * p
                   + jnp.roll(p, 1, axis=0)) * idy2
            )
            r = (rhs - lap) * mask
            return p + (-factor) * r, jnp.sum(r * r)

        def neumann(p):
            # ops/sor.neumann_bc as selects: same write order, corners
            # untouched (the masks exclude them)
            p = jnp.where(m_s, jnp.roll(p, -1, axis=0), p)
            p = jnp.where(m_n, jnp.roll(p, 1, axis=0), p)
            p = jnp.where(m_w, jnp.roll(p, -1, axis=1), p)
            p = jnp.where(m_e, jnp.roll(p, 1, axis=1), p)
            return p

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, r0 = sweep(p, red)
            p, r1 = sweep(p, black)
            p = neumann(p)
            res = ((r0 + r1) / norm).astype(res_dtype)
            return p, res, it + 1

        return lax.while_loop(
            cond, body,
            (p0, jnp.asarray(1.0, res_dtype), jnp.asarray(0, jnp.int32)))

    return solve


def make_class_chunk(param, jc: int, ic: int, dtype,
                     metrics: bool = False, chunk_default: int = 64,
                     backend: str = "auto"):
    """One shape class's chunk program: models/ns2d._build_step's phase
    order with grid extents as per-lane traced scalars. Lane state is
    (u, v, p, t, nt, gm[, m]) plus the carried te (the fleet's per-lane
    te convention — te is always the trailing argument). The solve is
    per-lane-dispatched: rb-sor lanes keep the masked loop, mg lanes ride
    the one-launch fused cycle when it dispatches (_class_solve_for)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import ns2d as ops
    from ..parallel import ragged2d as rg
    from ..utils import telemetry as _tm

    grids = _index_grids(jc, ic)
    gj, gi = grids
    adaptive = param.tau > 0.0
    chunk = param.tpu_chunk or chunk_default
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    solve = _class_solve_for(param, jc, ic, dtype, grids, backend=backend)

    def step(u, v, p, t, nt, gm):
        imax, jmax = gm[G_IMAX], gm[G_JMAX]  # whole-number scalars
        dx = gm[G_DX].astype(dtype)
        dy = gm[G_DY].astype(dtype)
        dtb = gm[G_DTB].astype(dtype)
        interior = ((gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax))
        live = (gj <= jmax + 1) & (gi <= imax + 1)
        if adaptive:
            # ghost-inclusive maxElement scan: dead cells are exact 0,
            # so the padded max IS the live max
            dt = ops.cfl_dt(ops.max_element(u), ops.max_element(v),
                            dtb, dx, dy, param.tau)
        else:
            dt = jnp.asarray(param.dt, dtype)
        u, v = rg.set_bcs_ragged(u, v, param, None, jc, ic, jmax, imax,
                                 grids=grids)
        u = rg.set_special_bc_ragged(u, param, None, jc, ic, jmax, imax,
                                     dy, dtype, grids=grids)
        f, g = ops.compute_fg_interior(u, v, dt, param.re, param.gx,
                                       param.gy, param.gamma, dx, dy)
        f, g = rg.fg_fixups_ragged(f, g, u, v, None, jc, ic, jmax, imax,
                                   grids=grids)
        rhs = jnp.where(interior, ops.rhs_terms(f, g, dt, dx, dy),
                        jnp.zeros_like(f))

        def norm_p(q):
            # normalizePressure over the live array only: the dynamic
            # count replaces the static size, dead cells stay 0
            cnt = ((jmax + 2.0) * (imax + 2.0)).astype(dtype)
            mean = jnp.sum(jnp.where(live, q, jnp.zeros_like(q))) / cnt
            return jnp.where(live, q - mean, q)

        p = lax.cond(nt % 100 == 0, norm_p, lambda q: q, p)
        p, res, it = solve(p, rhs, imax, jmax, gm[G_FACTOR],
                           gm[G_IDX2], gm[G_IDY2], gm[G_NORM])
        u_new, v_new = ops.adapt_terms(f, g, p, dt, dx, dy)
        u = jnp.where(interior, u_new, u)
        v = jnp.where(interior, v_new, v)
        # the ragged POST convention: multiply-mask so pad cells stay
        # exact 0 for the next step's scans (identity on live cells)
        lm = live.astype(dtype)
        u = u * lm
        v = v * lm
        t_next = t + dt.astype(time_dtype)
        return u, v, p, t_next, nt + 1, res, it, dt

    def chunk_fn(u, v, p, t, nt, gm, te):
        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            u, v, p, t, nt, gm, k = c
            u, v, p, t, nt, _res, _it, _dt = step(u, v, p, t, nt, gm)
            return u, v, p, t, nt, gm, k + 1

        u, v, p, t, nt, gm, _k = lax.while_loop(
            cond, body, (u, v, p, t, nt, gm, jnp.asarray(0, jnp.int32)))
        return u, v, p, t, nt, gm

    def chunk_fn_metrics(u, v, p, t, nt, gm, m, te):
        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            u, v, p, t, nt, gm, k, res, it, dtv, um, vm, bad = c
            u, v, p, t, nt, res, it, dtv = step(u, v, p, t, nt, gm)
            res, it, dtv, um, vm, bad = _tm.metrics_step(
                bad, nt, res, it, dtv,
                ops.max_element(u), ops.max_element(v))
            return u, v, p, t, nt, gm, k + 1, res, it, dtv, um, vm, bad

        (u, v, p, t, nt, gm, _k,
         res, it, dtv, um, vm, bad) = lax.while_loop(
            cond, body,
            (u, v, p, t, nt, gm, jnp.asarray(0, jnp.int32),
             m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
             m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_BAD]))
        return u, v, p, t, nt, gm, _tm.metrics_pack(
            res, it, dtv, um, vm, 0.0, bad)

    return chunk_fn_metrics if metrics else chunk_fn


def make_padded_class_solve(param, jc: int, ic: int, dtype,
                            block_rows: int | None = None,
                            interpret: bool | None = None):
    """The rb convergence loop as the extent-gated `sor_pallas` tblock
    kernel in the padded CLASS layout — models/poisson.
    make_padded_solver_fn with the live extents and update constants as
    call-time data (`make_rb_iter_tblock(dynamic=True)`), so ONE compiled
    solve serves every lane of the class:

        solve(p_pad, rhs_pad, ext_i32_12, geo_13, norm) -> (p', res, it)

    ext = (jmax, imax), geo = (factor, idx2, idy2) — each computed
    host-side per lane in Python f64 with the solo solver's own
    expressions (the lane geometry vector). Cells beyond the live extent
    pass through untouched (where-selects), and the masked residual sums
    exact zeros there — the live-mask residual reduction. Raises
    ValueError when the kernel is unavailable/VMEM-infeasible (callers
    fall back to the jnp class chain). Returns (solve, block_rows, halo).
    """
    from ..ops import sor_pallas as sp

    eff = max(1, param.tpu_sor_inner)
    rb_iter, block_rows, halo = sp.make_rb_iter_tblock(
        ic, jc, 1.0, 1.0, param.omg, dtype, n_inner=eff,
        block_rows=block_rows, interpret=interpret, dynamic=True,
    )
    if rb_iter is None:
        raise ValueError("pallas backend unavailable")
    epssq = param.eps * param.eps
    itermax = param.itermax
    res_dtype = jnp_promote(dtype)

    import jax.numpy as jnp
    from jax import lax

    def solve(p_pad, rhs_pad, ext, geo, norm):
        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, rsq = rb_iter(p, rhs_pad, ext, geo)
            res = (rsq / norm).astype(res_dtype)
            return p, res, it + eff

        return lax.while_loop(
            cond, body,
            (p_pad, jnp.asarray(1.0, res_dtype),
             jnp.asarray(0, jnp.int32)))

    return solve, block_rows, halo


def make_class_mg_solve(param, jc: int, ic: int, dtype,
                        interpret: bool | None = None):
    """The mg class lane's solve: ops/mg_fused.make_class_cycle_2d — the
    WHOLE V-cycle (pre-smooth, restrict, in-kernel smoothed bottom,
    prolong, post-smooth, fine residual) as ONE pallas launch whose level
    plan comes from the lane's call-time extents (class_level_plan), so
    every mg lane of the class shares one compile at one launch per
    cycle. Same call contract as make_class_solve:

        solve(p0, rhs, imax, jmax, factor, idx2, idy2, norm) -> (p, res, it)

    on the reference (jc+2, ic+2) block; `it` counts V-cycles; the
    convergence scalar is the in-kernel fine-level residual riding back
    through SMEM (no extra launch). The lane's SOR `factor` slot is
    unused — the cycle's ω=1 smoother factor is re-derived per level from
    idx2/idy2 inside class_level_plan (the multigrid convention), and the
    in-kernel smoothed bottom makes the class-lane parity contract
    padding-invariance + convergence-to-eps rather than the solo ulp bar.
    Dead cells beyond the lane's live extent are re-zeroed on exit (the
    class chunk's exact-0 pad contract). Raises when the kernel is
    unavailable (callers record why and keep the rb-sor chain)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops import mg_fused as mf

    cycle, plane, lmax = mf.make_class_cycle_2d(jc, ic, dtype,
                                                interpret=interpret)
    epssq = param.eps * param.eps
    itermax = param.itermax
    res_dtype = jnp_promote(dtype)
    gj, gi = _index_grids(jc, ic)

    def solve(p0, rhs, imax, jmax, factor, idx2, idy2, norm):
        del factor  # ω=1 per-level factors come from class_level_plan
        ext, geo = mf.class_level_plan(jmax, imax, idx2, idy2, lmax,
                                       dtype)
        norm = norm.astype(res_dtype)
        rp = mf.pad_plane(rhs, plane)

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, rsq = cycle(p, rp, ext, geo)
            return p, rsq.astype(res_dtype) / norm, it + 1

        pp, res, it = lax.while_loop(
            cond, body,
            (mf.pad_plane(p0, plane), jnp.asarray(1.0, res_dtype),
             jnp.asarray(0, jnp.int32)))
        p = mf.unpad_plane(pp, (jc, ic))
        live = (gj <= jmax + 1) & (gi <= imax + 1)
        return jnp.where(live, p, jnp.zeros_like(p)), res, it

    return solve


def _class_solve_for(param, jc: int, ic: int, dtype, grids,
                     backend: str = "auto"):
    """The class chunk's solve dispatch: mg lanes ride the one-launch
    fused cycle (decision recorded under `mg_class_fused` via
    resolve_mg_fused); any refusal — knob, retry backend, probe, or an
    infeasible kernel build — keeps the rb-sor masked chain with the
    reason recorded (mg lanes converge to the same eps either way, the
    class-lane contract)."""
    from ..utils import dispatch as _dispatch

    if param.tpu_solver == "mg":
        from ..ops import mg_fused as mf

        if _dispatch.resolve_mg_fused(
            param.tpu_mg_fused, backend, dtype, "mg_class_fused",
            probe=mf.probe_mg_fused,
        ):
            try:
                solve = make_class_mg_solve(param, jc, ic, dtype)
            except (ValueError, RuntimeError) as exc:
                _dispatch.record("mg_class_fused", f"jnp ({exc})")
            else:
                _dispatch.record(
                    "mg_class_fused",
                    "pallas_class_cycle (launches=1, levels<="
                    f"{mf.class_level_max(jc, ic)})")
                return solve
    return make_class_solve(param, jc, ic, dtype, grids)


def jnp_promote(dtype):
    """The class solves' residual dtype: the storage dtype promoted to at
    least f32 (the convergence scalar must not re-quantize to bf16)."""
    import jax.numpy as jnp

    return jnp.promote_types(dtype, jnp.float32)


def make_fused_class_chunk(param, jc: int, ic: int, dtype,
                           metrics: bool = False, chunk_default: int = 64):
    """The PRODUCTION-kernel class chunk (ISSUE 15's tentpole): one shape
    class's chunk program lowered to the solo fused composition —
    PRE megakernel -> padded-class tblock solve -> POST megakernel, three
    pallas launches per step (launch-count test-pinned) — with the
    per-lane live extents/cell sizes as call-time SMEM scalars
    (`dynamic=True` kernels), so a padded lane matches its exact-shape
    fused solo at the ulp contract while every lane of the class shares
    this ONE compile. External state layout is identical to
    make_class_chunk's ((u, v, p, t, nt, gm[, m], te) in the reference
    layout — padding lives inside the chunk like models/ns2d's fused
    chunk), so BatchedSolver/lane_state/crop_lane ride it unchanged.
    Raises ValueError when a kernel build is infeasible (the caller
    records why and falls back to the jnp class chain)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import ns2d_fused as nf
    from ..ops import ns2d as ops
    from ..utils import telemetry as _tm

    # the solve picks the shared layout (the p-layout fold contract of
    # models/ns2d._build_fused_chunk): p and rhs stay padded across the
    # whole chunk, zero layout passes between the three kernels
    solve_pad, br, h = make_padded_class_solve(param, jc, ic, dtype)
    if (br, h) != nf.fused_layout_2d(jc, ic, dtype, block_rows=br):
        raise ValueError(
            f"padded-class solve layout ({br}, {h}) does not match the "
            "fused phase kernels' (no shared padded layout)")
    pre, pad, unpad, _h = nf.make_fused_pre_2d(
        param, jc, ic, 1.0, 1.0, dtype, block_rows=br, dynamic=True)
    post, _p2, _u2, _h2 = nf.make_fused_post_2d(
        param, jc, ic, 1.0, 1.0, dtype, block_rows=br, ragged=True,
        dynamic=True)

    grids = _index_grids(jc, ic)
    gj, gi = grids
    adaptive = param.tau > 0.0
    chunk = param.tpu_chunk or chunk_default
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    offs = jnp.zeros((2,), jnp.int32)

    def norm_p(q, jmax, imax):
        # the jnp class chunk's dynamic normalizePressure, on the
        # unpadded block (the conversion pair runs only inside the
        # every-100-steps cond branch, the models/ns2d fold convention)
        live = (gj <= jmax + 1) & (gi <= imax + 1)
        cnt = ((jmax + 2.0) * (imax + 2.0)).astype(dtype)
        mean = jnp.sum(jnp.where(live, q, jnp.zeros_like(q))) / cnt
        return jnp.where(live, q - mean, q)

    def step(up, vp, p, t, nt, gm, umax, vmax):
        jmax, imax = gm[G_JMAX], gm[G_IMAX]
        dx = gm[G_DX].astype(dtype)
        dy = gm[G_DY].astype(dtype)
        dtb = gm[G_DTB].astype(dtype)
        if adaptive:
            dt = ops.cfl_dt(umax, vmax, dtb, dx, dy, param.tau)
        else:
            dt = jnp.asarray(param.dt, dtype)
        dt11 = jnp.full((1, 1), dt, dtype)
        ext = jnp.stack([jmax, imax]).astype(jnp.int32).reshape(1, 2)
        geo = jnp.stack([dx, dy]).reshape(1, 2)
        up, vp, fp, gp, rhsp = pre(offs, ext, geo, dt11, up, vp)
        p = lax.cond(
            nt % 100 == 0,
            lambda q: pad(norm_p(unpad(q), jmax, imax)),
            lambda q: q, p)
        sgeo = jnp.stack([gm[G_FACTOR].astype(dtype),
                          gm[G_IDX2].astype(dtype),
                          gm[G_IDY2].astype(dtype)]).reshape(1, 3)
        p, res, it = solve_pad(p, rhsp, ext, sgeo,
                               gm[G_NORM].astype(dtype))
        up, vp, umax, vmax = post(offs, ext, geo, dt11, up, vp, fp, gp, p)
        t_next = t + dt.astype(time_dtype)
        return up, vp, p, t_next, nt + 1, umax, vmax, res, it, dt

    def chunk_fn(u, v, p, t, nt, gm, te):
        up, vp, pp = pad(u), pad(v), pad(p)
        umax = jnp.max(jnp.abs(u))
        vmax = jnp.max(jnp.abs(v))

        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            up, vp, p, t, nt, gm, k, umax, vmax = c
            up, vp, p, t, nt, umax, vmax, _res, _it, _dt = step(
                up, vp, p, t, nt, gm, umax, vmax)
            return up, vp, p, t, nt, gm, k + 1, umax, vmax

        up, vp, pp, t, nt, gm, _k, _um, _vm = lax.while_loop(
            cond, body,
            (up, vp, pp, t, nt, gm, jnp.asarray(0, jnp.int32),
             umax, vmax))
        return unpad(up), unpad(vp), unpad(pp), t, nt, gm

    def chunk_fn_metrics(u, v, p, t, nt, gm, m, te):
        up, vp, pp = pad(u), pad(v), pad(p)
        umax = jnp.max(jnp.abs(u))
        vmax = jnp.max(jnp.abs(v))

        def cond(c):
            return jnp.logical_and(c[3] <= te, c[6] < chunk)

        def body(c):
            (up, vp, p, t, nt, gm, k, umax, vmax,
             res, it, dtv, bad) = c
            up, vp, p, t, nt, umax, vmax, res, it, dtv = step(
                up, vp, p, t, nt, gm, umax, vmax)
            res, it, dtv, _um, _vm, bad = _tm.metrics_step(
                bad, nt, res, it, dtv, umax, vmax)
            return (up, vp, p, t, nt, gm, k + 1, umax, vmax,
                    res, it, dtv, bad)

        (up, vp, pp, t, nt, gm, _k, umax, vmax,
         res, it, dtv, bad) = lax.while_loop(
            cond, body,
            (up, vp, pp, t, nt, gm, jnp.asarray(0, jnp.int32),
             umax, vmax,
             m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT], m[_tm.M_BAD]))
        return (unpad(up), unpad(vp), unpad(pp), t, nt, gm,
                _tm.metrics_pack(res, it, dtv, umax, vmax, 0.0, bad))

    return chunk_fn_metrics if metrics else chunk_fn


class ClassSolver:
    """The template of one shape class: a BatchedSolver-compatible
    template whose chunk takes grid extents as per-lane data. Built from
    a representative request; every same-class-signature request of any
    eligible grid rides this one compile (`fleet/batch.BatchedSolver`
    with te always carried).

    Since serving v3 the chunk rides the production kernels wherever the
    solo solver would (`resolve_fuse_phases` under `ns2d_class_phases`):
    fused PRE + padded-class tblock solve + POST, kernel-identical to an
    exact-shape fused solo modulo the traced extents. `tpu_fuse_phases
    off` (or any refusal) keeps the jnp masked chain — the parity oracle,
    byte-identical to the serving-v2 trace — and the pallas-retry
    protocol's jnp rebuild lands there too (`_rebuild_chunk`)."""

    CHUNK = 64
    # the class chunk takes te unconditionally (its carry is inherently
    # per-lane) — BatchedSolver reads this and always carries te
    _te_always = True

    def __init__(self, param, ic: int, jc: int, dtype=None):
        import time as _time

        import jax

        from ..utils import telemetry as _tm
        from ..utils.precision import resolve_dtype

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        if class_extent(param.imax) > ic or class_extent(param.jmax) > jc:
            raise ValueError(
                f"grid {param.imax}x{param.jmax} exceeds class "
                f"{ic}x{jc}")
        self.param = param.replace(imax=ic, jmax=jc)
        self._request = param
        self.ic, self.jc = ic, jc
        self.dtype = resolve_dtype(
            param.tpu_dtype, record_key="ns2d_class_dtype") \
            if dtype is None else dtype
        self._backend = "auto"
        self._fused = False  # set by _build_chunk (fused-class dispatch)
        self._solve_pallas = False  # mg class lane: one-launch cycle
        self._dt_scale = 1.0
        self._metrics = _tm.enabled()
        self._time_index = 3
        self._n_fields = 3
        t0 = _time.perf_counter()
        self._chunk_fn = jax.jit(self._build_chunk())
        from ..utils import dispatch as _dispatch

        _tm.emit("build", family="ns2d_class",
                 grid=[jc, ic], cls=f"{ic}x{jc}",
                 trace_wall_s=round(_time.perf_counter() - t0, 3),
                 phases=_dispatch.last("ns2d_class_phases"))

    def _uses_pallas(self) -> bool:
        return self._fused or self._solve_pallas

    def _build_fused_chunk(self, backend: str, metrics: bool):
        """The fused-class dispatch (the models/ns2d._build_fused_chunk
        shape): None when the production kernels are not dispatched —
        knob off, jnp retry backend, no TPU/probe failure, or an
        infeasible kernel build — and the jnp masked chain is the
        fallback (decision recorded either way)."""
        from ..ops.ns2d_fused import probe_fused_2d
        from ..utils.dispatch import record, resolve_fuse_phases

        if self.param.tpu_solver == "mg":
            # mg class lanes: the solve IS the one-launch cycle kernel
            # (make_class_mg_solve, dispatched inside the jnp chunk); the
            # phase megakernels' padded-layout fold assumes the tblock
            # sor solve, so the phases stay the masked chain
            record("ns2d_class_phases",
                   "jnp (mg class lane: the solve is the one-launch "
                   "fused cycle)")
            return None
        if not resolve_fuse_phases(
            self.param, backend, self.dtype, probe_fused_2d,
            "ns2d_class_phases",
        ):
            return None
        try:
            fused = make_fused_class_chunk(
                self.param, self.jc, self.ic, self.dtype,
                metrics=metrics, chunk_default=self.CHUNK)
        except ValueError as exc:  # kernel unavailable/VMEM-infeasible
            record("ns2d_class_phases", f"jnp ({exc})")
            return None
        record("ns2d_class_solve",
               "pallas_padded_class (extent-gated tblock, n_inner="
               f"{max(1, self.param.tpu_sor_inner)})")
        return fused

    def _build_chunk(self, backend: str | None = None,
                     te_arg: bool = True):
        # backend follows the retry-protocol surface ("jnp" = the pallas
        # fallback rebuild -> the masked jnp chain). te is ALWAYS the
        # trailing traced argument — the class carry is inherently
        # per-lane.
        backend = self._backend if backend is None else backend
        self._metrics = _metrics_enabled()
        fused = self._build_fused_chunk(backend, self._metrics)
        self._fused = fused is not None
        if fused is not None:
            return fused
        chunk = make_class_chunk(self.param, self.jc, self.ic, self.dtype,
                                 metrics=self._metrics,
                                 chunk_default=self.CHUNK,
                                 backend=backend)
        if self.param.tpu_solver == "mg":
            from ..utils import dispatch as _dispatch

            last = _dispatch.last("mg_class_fused") or ""
            self._solve_pallas = last.startswith("pallas")
        return chunk

    def _rebuild_chunk(self):
        """Re-trace against the solver's CURRENT `_backend` — the
        pallas-retry/contamination-heal hook (models/ns2d convention;
        the class template has no recovery dt clamp)."""
        import jax

        self._chunk_fn = jax.jit(self._build_chunk(backend=self._backend))
        return self._chunk_fn

    # -- per-lane state (the BatchedSolver template hooks) --------------
    def lane_state(self, param) -> tuple:
        import jax
        import jax.numpy as jnp

        from ..utils import telemetry as _tm

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        jc, ic = self.jc, self.ic
        if param.imax > ic or param.jmax > jc:
            # the __init__ guard, repeated per lane: swap_lane feeds
            # requests straight through here — an oversized lane would
            # otherwise saturate the live mask silently and crop_lane
            # would hand the tenant a wrong-shaped result
            raise ValueError(
                f"grid {param.imax}x{param.jmax} exceeds class "
                f"{ic}x{jc}")
        live = ((np.arange(jc + 2)[:, None] <= param.jmax + 1)
                & (np.arange(ic + 2)[None, :] <= param.imax + 1))
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32

        def field(init):
            return jnp.asarray(
                np.where(live, init, 0.0), self.dtype)

        gm = jnp.asarray(lane_geometry(param), time_dtype)
        out = (field(param.u_init), field(param.v_init),
               field(param.p_init),
               jnp.asarray(0.0, time_dtype), jnp.asarray(0, jnp.int32),
               gm)
        if self._metrics:
            out = out + (_tm.metrics_init(),)
        return out

    def crop_lane(self, fields, param) -> tuple:
        """Unpad one lane's published fields back to the request's own
        (jmax+2, imax+2) reference layout."""
        return tuple(np.asarray(f)[:param.jmax + 2, :param.imax + 2]
                     for f in fields)

    def initial_state(self) -> tuple:
        return self.lane_state(self._request)


def _metrics_enabled() -> bool:
    from ..utils import telemetry as _tm

    return _tm.enabled()


# ---------------------------------------------------------------------------
# 3-D class rungs (ISSUE 15): the identical ladder over ragged3d's select
# machinery — kmax joins the per-lane data, the solve is the masked jnp
# 3-D rb loop (models/ns3d.make_pressure_solve_3d's jnp path at traced
# extents; the octant/tblock3d pallas solves stay exact-shape programs),
# and the fused chunk rides ops/ns3d_fused's dynamic-extent PRE/POST.
# ---------------------------------------------------------------------------

# 3-D geometry-vector slots (per lane): the grid-derived scalars
# NS3DSolver folds as Python-float constants, computed host-side with the
# identical expressions (utils/grid.Grid + ops/ns3d.sor_coefficients_3d)
(G3_KMAX, G3_JMAX, G3_IMAX, G3_DX, G3_DY, G3_DZ, G3_DTB,
 G3_FACTOR, G3_IDX2, G3_IDY2, G3_IDZ2, G3_NORM) = range(12)
GEOM3_LEN = 12


def lane_geometry_3d(param):
    """The 3-D per-lane geometry scalars — NS3DSolver.__init__'s own
    Python f64 expressions (Grid dx/dy/dz, the dt bound) plus
    ops/ns3d.sor_coefficients_3d (the single source of the 3-D SOR
    constants), the bitwise-coefficient contract."""
    from ..models.ns3d import sor_coefficients_3d

    dx = param.xlength / param.imax
    dy = param.ylength / param.jmax
    dz = param.zlength / param.kmax
    inv_sqr_sum = 1.0 / dx**2 + 1.0 / dy**2 + 1.0 / dz**2
    dt_bound = 0.5 * param.re / inv_sqr_sum
    factor, idx2, idy2, idz2 = sor_coefficients_3d(dx, dy, dz, param.omg)
    norm = float(param.imax * param.jmax * param.kmax)
    return (float(param.kmax), float(param.jmax), float(param.imax),
            dx, dy, dz, dt_bound, factor, idx2, idy2, idz2, norm)


def _index_grids_3d(kc: int, jc: int, ic: int):
    import jax.numpy as jnp

    gk = jnp.arange(kc + 2, dtype=jnp.int32)[:, None, None]
    gj = jnp.arange(jc + 2, dtype=jnp.int32)[None, :, None]
    gi = jnp.arange(ic + 2, dtype=jnp.int32)[None, None, :]
    return gk, gj, gi


def make_class_solve_3d(param, kc: int, jc: int, ic: int, dtype, grids):
    """The masked 3-D red-black SOR convergence loop at TRACED extents —
    models/ns3d.make_pressure_solve_3d's jnp path (odd half-sweep, even
    half-sweep seeing odd's updates, 6-face Neumann ghost copy,
    normalized residual vs eps^2) with every position select-by-global-
    index and every reduction confined to the dynamic interior."""
    import jax.numpy as jnp
    from jax import lax

    gk, gj, gi = grids
    epssq = param.eps * param.eps
    itermax = param.itermax
    res_dtype = jnp_promote(dtype)

    def solve(p0, rhs, kmax, jmax, imax, factor, idx2, idy2, idz2, norm):
        factor = factor.astype(dtype)
        idx2 = idx2.astype(dtype)
        idy2 = idy2.astype(dtype)
        idz2 = idz2.astype(dtype)
        norm = norm.astype(dtype)
        interior = ((gk >= 1) & (gk <= kmax) & (gj >= 1) & (gj <= jmax)
                    & (gi >= 1) & (gi <= imax))
        parity = (gi + gj + gk) % 2
        # pass 0 visits parity 1 (odd), pass 1 parity 0 — the reference's
        # ksw/jsw/isw ordering (models/ns3d.checkerboard_mask_3d)
        odd = (interior & (parity == 1)).astype(dtype)
        even = (interior & (parity == 0)).astype(dtype)
        tan_ji = (gj >= 1) & (gj <= jmax) & (gi >= 1) & (gi <= imax)
        tan_ki = (gk >= 1) & (gk <= kmax) & (gi >= 1) & (gi <= imax)
        tan_kj = (gk >= 1) & (gk <= kmax) & (gj >= 1) & (gj <= jmax)
        m_front = (gk == 0) & tan_ji
        m_back = (gk == kmax + 1) & tan_ji
        m_bottom = (gj == 0) & tan_ki
        m_top = (gj == jmax + 1) & tan_ki
        m_left = (gi == 0) & tan_kj
        m_right = (gi == imax + 1) & tan_kj

        def sweep(p, mask):
            # interior_residual_3d's 7-point stencil on the full block
            # (rolls deliver the same neighbour values at every cell
            # whose neighbours are real; the masked r is exact 0 off its
            # colour, so dead cells add -0.0 — identity)
            lap = (
                (jnp.roll(p, -1, axis=2) - 2.0 * p
                 + jnp.roll(p, 1, axis=2)) * idx2
                + (jnp.roll(p, -1, axis=1) - 2.0 * p
                   + jnp.roll(p, 1, axis=1)) * idy2
                + (jnp.roll(p, -1, axis=0) - 2.0 * p
                   + jnp.roll(p, 1, axis=0)) * idz2
            )
            r = (rhs - lap) * mask
            return p + (-factor) * r, jnp.sum(r * r)

        def neumann(p):
            # neumann_faces_3d's face order as selects, corners untouched
            p = jnp.where(m_front, jnp.roll(p, -1, axis=0), p)
            p = jnp.where(m_back, jnp.roll(p, 1, axis=0), p)
            p = jnp.where(m_bottom, jnp.roll(p, -1, axis=1), p)
            p = jnp.where(m_top, jnp.roll(p, 1, axis=1), p)
            p = jnp.where(m_left, jnp.roll(p, -1, axis=2), p)
            p = jnp.where(m_right, jnp.roll(p, 1, axis=2), p)
            return p

        def cond(carry):
            _, res, it = carry
            return jnp.logical_and(res >= epssq, it < itermax)

        def body(carry):
            p, _, it = carry
            p, r0 = sweep(p, odd)
            p, r1 = sweep(p, even)
            p = neumann(p)
            res = ((r0 + r1) / norm).astype(res_dtype)
            return p, res, it + 1

        return lax.while_loop(
            cond, body,
            (p0, jnp.asarray(1.0, res_dtype), jnp.asarray(0, jnp.int32)))

    return solve


def _class_step_3d(param, kc: int, jc: int, ic: int, dtype, grids,
                   solve, fused=None):
    """One 3-D class timestep at traced extents — NS3DSolver._build_step's
    phase order (NO normalizePressure in the 3-D loop) over the ragged3d
    select machinery. `fused=(pre, post, pad3, unpad3)` swaps the
    non-solve phases for the dynamic-extent megakernels (u/v/w arrive and
    leave PADDED, plus carried CFL maxima — the solo fused composition);
    None is the jnp masked chain."""
    import jax
    import jax.numpy as jnp

    from ..ops import ns3d as ops3
    from ..ops.ns3d_fused import _win_shift
    from ..parallel import ragged3d as rg3

    gk, gj, gi = grids
    adaptive = param.tau > 0.0
    problem = param.name.replace("3d", "")
    bcs = {
        "top": param.bcTop,
        "bottom": param.bcBottom,
        "left": param.bcLeft,
        "right": param.bcRight,
        "front": param.bcFront,
        "back": param.bcBack,
    }
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    def unpack(gm):
        kmax, jmax, imax = gm[G3_KMAX], gm[G3_JMAX], gm[G3_IMAX]
        dx = gm[G3_DX].astype(dtype)
        dy = gm[G3_DY].astype(dtype)
        dz = gm[G3_DZ].astype(dtype)
        return kmax, jmax, imax, dx, dy, dz

    def do_solve(p, rhs, gm):
        kmax, jmax, imax, *_ = unpack(gm)
        return solve(p, rhs, kmax, jmax, imax, gm[G3_FACTOR],
                     gm[G3_IDX2], gm[G3_IDY2], gm[G3_IDZ2],
                     gm[G3_NORM])

    if fused is not None:
        pre, post, pad3, unpad3 = fused
        offs = jnp.zeros((3,), jnp.int32)

        def step(up, vp, wp, p, t, nt, gm, umax, vmax, wmax):
            kmax, jmax, imax, dx, dy, dz = unpack(gm)
            dtb = gm[G3_DTB].astype(dtype)
            if adaptive:
                dt = ops3.cfl_dt_3d(umax, vmax, wmax, dtb, dx, dy, dz,
                                    param.tau)
            else:
                dt = jnp.asarray(param.dt, dtype)
            dt11 = jnp.full((1, 1), dt, dtype)
            ext = jnp.stack([kmax, jmax, imax]).astype(
                jnp.int32).reshape(1, 3)
            geo = jnp.stack([dx, dy, dz]).reshape(1, 3)
            up, vp, wp, fp, gp, hp, rhsp = pre(offs, ext, geo, dt11,
                                               up, vp, wp)
            p, res, it = do_solve(p, unpad3(rhsp), gm)
            up, vp, wp, umax, vmax, wmax = post(
                offs, ext, geo, dt11, up, vp, wp, fp, gp, hp, pad3(p))
            t_next = t + dt.astype(time_dtype)
            return (up, vp, wp, p, t_next, nt + 1, umax, vmax, wmax,
                    res, it, dt)

        return step

    def step(u, v, w, p, t, nt, gm):
        kmax, jmax, imax, dx, dy, dz = unpack(gm)
        dtb = gm[G3_DTB].astype(dtype)
        interior = ((gk >= 1) & (gk <= kmax) & (gj >= 1) & (gj <= jmax)
                    & (gi >= 1) & (gi <= imax))
        live = (gk <= kmax + 1) & (gj <= jmax + 1) & (gi <= imax + 1)
        if adaptive:
            # ghost-inclusive maxElement scans: dead cells are exact 0
            dt = ops3.cfl_dt_3d(ops3.max_element(u), ops3.max_element(v),
                                ops3.max_element(w), dtb, dx, dy, dz,
                                param.tau)
        else:
            dt = jnp.asarray(param.dt, dtype)
        u, v, w = rg3.set_bcs_3d_ragged(u, v, w, bcs, None, kc, jc, ic,
                                        kmax, jmax, imax, grids=grids)
        u = rg3.set_special_bc_3d_ragged(u, problem, None, kc, jc, ic,
                                         kmax, jmax, imax, grids=grids)
        f_full, g_full, h_full = ops3.fgh_predictor_terms(
            u, v, w, dt, param.re, param.gx, param.gy, param.gz,
            param.gamma, dx, dy, dz, sh=_win_shift)
        zero = jnp.zeros_like(u)
        f = jnp.where(interior, f_full, zero)
        g_ = jnp.where(interior, g_full, zero)
        h = jnp.where(interior, h_full, zero)
        f, g_, h = rg3.fgh_fixups_ragged(f, g_, h, u, v, w, None,
                                         kc, jc, ic, kmax, jmax, imax,
                                         grids=grids)
        rhs = jnp.where(
            interior,
            ops3.rhs_terms_3d(f, g_, h, dt, dx, dy, dz, sh=_win_shift),
            zero)
        p, res, it = do_solve(p, rhs, gm)
        un, vn, wn = ops3.adapt_terms_3d(f, g_, h, p, dt, dx, dy, dz,
                                         sh=_win_shift)
        u = jnp.where(interior, un, u)
        v = jnp.where(interior, vn, v)
        w = jnp.where(interior, wn, w)
        # the ragged POST convention (live_masks_3d): dead pad cells go
        # to exact 0 before the next step's ghost-inclusive CFL scans
        lm = live.astype(dtype)
        u = u * lm
        v = v * lm
        w = w * lm
        t_next = t + dt.astype(time_dtype)
        return u, v, w, p, t_next, nt + 1, res, it, dt

    return step


def make_class_chunk_3d(param, kc: int, jc: int, ic: int, dtype,
                        metrics: bool = False, chunk_default: int = 32,
                        fused=None):
    """One 3-D shape class's chunk program: NS3DSolver's phase order with
    grid extents as per-lane traced scalars. Lane state is
    (u, v, w, p, t, nt, gm[, m]) plus the carried te. `fused` (the
    dynamic-extent kernel tuple) selects the production-kernel step;
    None is the jnp masked chain."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..utils import telemetry as _tm

    grids = _index_grids_3d(kc, jc, ic)
    chunk = param.tpu_chunk or chunk_default
    solve = make_class_solve_3d(param, kc, jc, ic, dtype, grids)
    step = _class_step_3d(param, kc, jc, ic, dtype, grids, solve,
                          fused=fused)

    if fused is not None:
        _pre, _post, pad3, unpad3 = fused

        def chunk_fn(u, v, w, p, t, nt, gm, te):
            up, vp, wp = pad3(u), pad3(v), pad3(w)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))
            wmax = jnp.max(jnp.abs(w))

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[7] < chunk)

            def body(c):
                up, vp, wp, p, t, nt, gm, k, um, vm, wm = c
                (up, vp, wp, p, t, nt, um, vm, wm,
                 _res, _it, _dt) = step(up, vp, wp, p, t, nt, gm,
                                        um, vm, wm)
                return up, vp, wp, p, t, nt, gm, k + 1, um, vm, wm

            (up, vp, wp, p, t, nt, gm, _k,
             _um, _vm, _wm) = lax.while_loop(
                cond, body,
                (up, vp, wp, p, t, nt, gm, jnp.asarray(0, jnp.int32),
                 umax, vmax, wmax))
            return unpad3(up), unpad3(vp), unpad3(wp), p, t, nt, gm

        def chunk_fn_metrics(u, v, w, p, t, nt, gm, m, te):
            up, vp, wp = pad3(u), pad3(v), pad3(w)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))
            wmax = jnp.max(jnp.abs(w))

            def cond(c):
                return jnp.logical_and(c[4] <= te, c[7] < chunk)

            def body(c):
                (up, vp, wp, p, t, nt, gm, k, um, vm, wm,
                 res, it, dtv, bad) = c
                (up, vp, wp, p, t, nt, um, vm, wm,
                 res, it, dtv) = step(up, vp, wp, p, t, nt, gm,
                                      um, vm, wm)
                res, it, dtv, _u, _v, _w, bad = _tm.metrics_step(
                    bad, nt, res, it, dtv, um, vm, wm)
                return (up, vp, wp, p, t, nt, gm, k + 1, um, vm, wm,
                        res, it, dtv, bad)

            (up, vp, wp, p, t, nt, gm, _k, um, vm, wm,
             res, it, dtv, bad) = lax.while_loop(
                cond, body,
                (up, vp, wp, p, t, nt, gm, jnp.asarray(0, jnp.int32),
                 umax, vmax, wmax,
                 m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT], m[_tm.M_BAD]))
            return (unpad3(up), unpad3(vp), unpad3(wp), p, t, nt, gm,
                    _tm.metrics_pack(res, it, dtv, um, vm, wm, bad))

        return chunk_fn_metrics if metrics else chunk_fn

    def chunk_fn(u, v, w, p, t, nt, gm, te):
        def cond(c):
            return jnp.logical_and(c[4] <= te, c[7] < chunk)

        def body(c):
            u, v, w, p, t, nt, gm, k = c
            u, v, w, p, t, nt, _res, _it, _dt = step(u, v, w, p, t, nt,
                                                     gm)
            return u, v, w, p, t, nt, gm, k + 1

        u, v, w, p, t, nt, gm, _k = lax.while_loop(
            cond, body,
            (u, v, w, p, t, nt, gm, jnp.asarray(0, jnp.int32)))
        return u, v, w, p, t, nt, gm

    def chunk_fn_metrics(u, v, w, p, t, nt, gm, m, te):
        from ..ops import ns3d as ops3

        def cond(c):
            return jnp.logical_and(c[4] <= te, c[7] < chunk)

        def body(c):
            u, v, w, p, t, nt, gm, k, res, it, dtv, um, vm, wm, bad = c
            u, v, w, p, t, nt, res, it, dtv = step(u, v, w, p, t, nt, gm)
            res, it, dtv, um, vm, wm, bad = _tm.metrics_step(
                bad, nt, res, it, dtv, ops3.max_element(u),
                ops3.max_element(v), ops3.max_element(w))
            return (u, v, w, p, t, nt, gm, k + 1,
                    res, it, dtv, um, vm, wm, bad)

        (u, v, w, p, t, nt, gm, _k,
         res, it, dtv, um, vm, wm, bad) = lax.while_loop(
            cond, body,
            (u, v, w, p, t, nt, gm, jnp.asarray(0, jnp.int32),
             m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
             m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_WMAX], m[_tm.M_BAD]))
        return u, v, w, p, t, nt, gm, _tm.metrics_pack(
            res, it, dtv, um, vm, wm, bad)

    return chunk_fn_metrics if metrics else chunk_fn


class Class3DSolver:
    """The 3-D twin of ClassSolver: one 3-D shape class's
    BatchedSolver-compatible template — (kc, jc, ic) power-of-two rungs,
    per-lane (kmax, jmax, imax) as traced data over ragged3d's select
    machinery, and the production fused PRE/POST kernels when
    `tpu_fuse_phases` dispatches (`ns3d_class_phases`; the 3-D class
    solve stays the masked jnp rb loop — PRE + POST per step,
    launch-count test-pinned)."""

    CHUNK = 32
    _te_always = True

    def __init__(self, param, ic: int, jc: int, kc: int, dtype=None):
        import time as _time

        import jax

        from ..utils import telemetry as _tm
        from ..utils.precision import resolve_dtype

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        if (class_extent(param.imax) > ic or class_extent(param.jmax) > jc
                or class_extent(param.kmax) > kc):
            raise ValueError(
                f"grid {param.imax}x{param.jmax}x{param.kmax} exceeds "
                f"class {ic}x{jc}x{kc}")
        self.param = param.replace(imax=ic, jmax=jc, kmax=kc)
        self._request = param
        self.ic, self.jc, self.kc = ic, jc, kc
        self.dtype = resolve_dtype(
            param.tpu_dtype, record_key="ns3d_class_dtype") \
            if dtype is None else dtype
        self._backend = "auto"
        self._fused = False
        self._dt_scale = 1.0
        self._metrics = _tm.enabled()
        self._time_index = 4
        self._n_fields = 4
        t0 = _time.perf_counter()
        self._chunk_fn = jax.jit(self._build_chunk())
        from ..utils import dispatch as _dispatch

        _tm.emit("build", family="ns3d_class",
                 grid=[kc, jc, ic], cls=f"{ic}x{jc}x{kc}",
                 trace_wall_s=round(_time.perf_counter() - t0, 3),
                 phases=_dispatch.last("ns3d_class_phases"))

    def _uses_pallas(self) -> bool:
        return self._fused

    def _build_chunk(self, backend: str | None = None,
                     te_arg: bool = True):
        from ..ops.ns3d_fused import probe_fused_3d
        from ..utils.dispatch import record, resolve_fuse_phases

        backend = self._backend if backend is None else backend
        self._metrics = _metrics_enabled()
        fused = None
        if resolve_fuse_phases(
            self.param, backend, self.dtype, probe_fused_3d,
            "ns3d_class_phases",
        ):
            from ..ops import ns3d_fused as nf3

            try:
                pre, pad3, unpad3, _h = nf3.make_fused_pre_3d(
                    self.param, self.kc, self.jc, self.ic,
                    1.0, 1.0, 1.0, self.dtype, dynamic=True)
                post, _p, _u, _h2 = nf3.make_fused_post_3d(
                    self.param, self.kc, self.jc, self.ic,
                    1.0, 1.0, 1.0, self.dtype, ragged=True, dynamic=True)
                fused = (pre, post, pad3, unpad3)
            except ValueError as exc:  # VMEM-infeasible geometry
                record("ns3d_class_phases", f"jnp ({exc})")
                fused = None
        self._fused = fused is not None
        return make_class_chunk_3d(self.param, self.kc, self.jc, self.ic,
                                   self.dtype, metrics=self._metrics,
                                   chunk_default=self.CHUNK, fused=fused)

    def _rebuild_chunk(self):
        import jax

        self._chunk_fn = jax.jit(self._build_chunk(backend=self._backend))
        return self._chunk_fn

    # -- per-lane state (the BatchedSolver template hooks) --------------
    def lane_state(self, param) -> tuple:
        import jax
        import jax.numpy as jnp

        from ..utils import telemetry as _tm

        reason = class_eligible(param)
        if reason is not None:
            raise ValueError(f"request is not class-eligible: {reason}")
        kc, jc, ic = self.kc, self.jc, self.ic
        if param.imax > ic or param.jmax > jc or param.kmax > kc:
            # the __init__ guard, repeated per lane (the swap_lane path)
            raise ValueError(
                f"grid {param.imax}x{param.jmax}x{param.kmax} exceeds "
                f"class {ic}x{jc}x{kc}")
        live = ((np.arange(kc + 2)[:, None, None] <= param.kmax + 1)
                & (np.arange(jc + 2)[None, :, None] <= param.jmax + 1)
                & (np.arange(ic + 2)[None, None, :] <= param.imax + 1))
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32

        def field(init):
            return jnp.asarray(np.where(live, init, 0.0), self.dtype)

        gm = jnp.asarray(lane_geometry_3d(param), time_dtype)
        out = (field(param.u_init), field(param.v_init),
               field(param.w_init), field(param.p_init),
               jnp.asarray(0.0, time_dtype), jnp.asarray(0, jnp.int32),
               gm)
        if self._metrics:
            out = out + (_tm.metrics_init(),)
        return out

    def crop_lane(self, fields, param) -> tuple:
        """Unpad one lane's published fields back to the request's own
        (kmax+2, jmax+2, imax+2) reference layout."""
        return tuple(
            np.asarray(f)[:param.kmax + 2, :param.jmax + 2,
                          :param.imax + 2]
            for f in fields)

    def initial_state(self) -> tuple:
        return self.lane_state(self._request)
