"""Autopilot: the self-healing elastic control plane for the serving
fleet (ISSUE 19 — ROADMAP item 3's closing move).

PR 18 built the observability plane (per-tenant SLO burn rates, the
queue-depth gauge, per-class latency histograms) and PR 10/12 built the
elastic machinery (mesh-independent manifests, `shrink_resume`,
survivor consensus) — but nothing consumed the signals to drive the
machinery: a dead rank, an SLO burn, a backlog spike all waited for an
operator. This module is the policy loop that closes observe→decide→act
inside the daemon's poll cycle:

observe   every poll: max tenant burn rate (fleet/slo.burn_snapshot),
          queue depth + backlog trend (a short depth window), worst
          per-class p95 from the registry histograms.
decide    a hysteresis BAND, not a threshold: hot above
          `burn_high`/`backlog_high`, calm below `burn_low` — the gap
          between them is where nothing changes, so a burn oscillating
          around one number cannot flap the fleet. Transitions need
          `sustain` consecutive hot (or calm) polls AND `cooldown`
          polls since the last transition.
act       through surfaces that already exist, never new ones:

  self-healing      a RankDeadError from the resident elastic job (or a
                    `dead@poll<N>` injection) triggers automatic
                    `shrink_resume` onto survivor capacity — no
                    operator; the fault ledger rides the manifest so
                    probation history survives the shrink.
  elastic scaling   sustained burn/backlog grows the continuous-batch
                    lane pool (and checkpoint-FENCES the resident
                    through its elastic manifest: save a generation,
                    restore from it — every transition provably
                    resumable, bitwise vs a clean run from the same
                    generation); sustained idle shrinks it.
  QoS preemption    tenant priority classes (`high`/`normal`/`low`)
                    weight admission quotas, and the scheduler's
                    continuous loop parks a low-priority lane's full
                    per-lane carry through a parked-lane manifest
                    (utils/checkpoint.save_parked_lane) when a
                    higher-priority request has no slot — the victim
                    resumes bitwise once the queue drains.
  degraded rungs    when the pool is at capacity and burn persists, an
                    EXPLICIT degradation ladder (LADDER below), one
                    rung per decision, telemetry-recorded:
                      1 class_consolidation  force shape-class batching
                                             (fewer compiles, shared
                                             lanes)
                      2 itermax_cap          cap admitted requests'
                                             pressure-solve budget
                      3 shed_low_priority    refuse lowest-priority
                                             tenants at admission
                    and the same ladder back UP, one rung per sustained
                    calm window.

Every decision — including "hold" — lands as an `autoscale` telemetry
record (schema v9): policy inputs, decision, rung, lane/capacity counts
and the live hysteresis state, rendered by tools/telemetry_report and
linted by tools/check_artifact. Transition counts and time-to-recover
land as trend-gated metrics at daemon stop (`autoscale_flaps`,
`autoscale_time_to_recover_ms` — both lower-is-better in bench_trend).

The knob is `tpu_autopilot` (utils/params.py) / `--autopilot`
(tools/serve.py): "off" — the default — constructs NO Autopilot and the
daemon is byte-identical to the policy-less build (test-pinned);
"on[:k=v,...]" arms the loop with optional hysteresis overrides.
tools/chaos_smoke.py is the proof harness: injected kill →
auto-shrink → synthetic-burn regrow (exactly once across the band) →
preempt → bitwise resume, on CPU.
"""

from __future__ import annotations

import dataclasses
import os

from ..utils import faultinject as _fi
from ..utils import telemetry as _tm

# tenant priority classes: lower = more important. Admission quotas are
# WEIGHTED by class (never reordered — FIFO within a tenant is part of
# the starvation story), preemption is strict: only a strictly
# higher-priority pending request may evict a running lane.
PRIORITY_CLASSES = {"high": 0, "normal": 1, "low": 2}
PRIORITY_WEIGHTS = {0: 2.0, 1: 1.0, 2: 0.5}
# the class the shed rung refuses (only ever the lowest)
SHED_CLASS = 2

# the degradation ladder, rung 0 = full service. Moves are one rung per
# decision in BOTH directions and every move is an `autoscale` record —
# the chaos smoke asserts the recorded sequence is monotone (no
# skipping, no oscillation inside one hot/calm phase).
LADDER = ("full_service", "class_consolidation", "itermax_cap",
          "shed_low_priority")


@dataclasses.dataclass
class AutopilotConfig:
    """The hysteresis band and pool bounds (parse_autopilot_spec)."""

    burn_high: float = 3.0    # hot above this max-tenant burn rate...
    burn_low: float = 1.0     # ...calm below this one; between = hold
    backlog_high: int = 8     # queue depth that also counts as hot
    sustain: int = 2          # consecutive hot/calm polls to act
    cooldown: int = 3         # min polls between transitions
    min_lanes: int = 1        # deliberate shrink floor
    max_lanes: int = 0        # grow ceiling (0 = 2x the starting pool,
    #                           capped by local device count)
    idle_polls: int = 6       # consecutive empty-queue calm polls
    #                           before a deliberate shrink
    itermax_cap: int = 4      # rung-2 admission cap on itermax
    flap_window: int = 6      # opposite-direction capacity moves
    #                           within this many polls count as a flap
    trend_window: int = 4     # queue-depth polls behind backlog_trend


def parse_autopilot_spec(spec: str | None):
    """`"off"`/empty -> None (policy plane off). `"on"` -> defaults,
    `"on:burn_high=4,sustain=3"` -> overridden config. Unknown keys and
    unparsable values fail loudly — a mistyped policy knob must not
    silently run a different policy."""
    spec = (spec or "").strip()
    if spec in ("", "off"):
        return None
    head, _, tail = spec.partition(":")
    if head != "on":
        raise ValueError(
            f"bad tpu_autopilot spec {spec!r} (want off | on[:k=v,...])")
    cfg = AutopilotConfig()
    for part in tail.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad tpu_autopilot override {part!r} (want k=v)")
        key, _, val = part.partition("=")
        key = key.strip()
        if not hasattr(cfg, key):
            raise ValueError(
                f"unknown tpu_autopilot key {key!r} (have "
                f"{', '.join(f.name for f in dataclasses.fields(cfg))})")
        kind = type(getattr(cfg, key))
        try:
            setattr(cfg, key, kind(val))
        except ValueError:
            raise ValueError(
                f"bad tpu_autopilot value {val!r} for {key} "
                f"(want {kind.__name__})")
    return cfg


def parse_priority_spec(spec: str | None) -> dict[str, int]:
    """`"zoe=high,bob=low,default=normal"` -> {tenant: class int}.
    Empty -> {} (flat priorities: weighted admission and preemption both
    off). Unknown class names fail loudly."""
    out: dict[str, int] = {}
    if not spec:
        return out
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad priority entry {part!r} "
                             "(want tenant=high|normal|low)")
        tenant, _, klass = part.partition("=")
        tenant, klass = tenant.strip(), klass.strip()
        if not tenant or klass not in PRIORITY_CLASSES:
            raise ValueError(
                f"bad priority entry {part!r} (tenant non-empty, class "
                f"one of {'|'.join(PRIORITY_CLASSES)})")
        out[tenant] = PRIORITY_CLASSES[klass]
    return out


@dataclasses.dataclass
class ParkedLane:
    """One preempted lane: sid + its param in memory, the leaf arrays on
    disk behind a CRC-checked manifest (utils/checkpoint)."""

    sid: str
    param: object
    path: str

    def load(self) -> list:
        from ..utils import checkpoint as _ckpt

        return _ckpt.load_parked_lane(self.path)


class ParkStore:
    """Parked-lane manifests for the preemption plane, keyed by bucket
    signature (a parked lane may only resume into the SAME compiled
    shape it left — the signature is that contract). FIFO per bucket:
    the first victim parked is the first resumed."""

    def __init__(self, dirpath: str):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self._by_bucket: dict[str, list[ParkedLane]] = {}
        self.parked_total = 0
        self.resumed_total = 0

    def park(self, bucket_sig: str, sid: str, param, leaves) -> str:
        from ..utils import checkpoint as _ckpt

        path = os.path.join(self.dir, f"{sid}.lane.npz")
        _ckpt.save_parked_lane(path, sid, leaves)
        self._by_bucket.setdefault(bucket_sig, []).append(
            ParkedLane(sid=sid, param=param, path=path))
        self.parked_total += 1
        return path

    def pop(self, bucket_sig: str) -> ParkedLane | None:
        q = self._by_bucket.get(bucket_sig)
        if not q:
            return None
        self.resumed_total += 1
        return q.pop(0)

    def count(self, bucket_sig: str | None = None) -> int:
        if bucket_sig is not None:
            return len(self._by_bucket.get(bucket_sig, ()))
        return sum(len(q) for q in self._by_bucket.values())


@dataclasses.dataclass
class ResidentJob:
    """The long-lived elastic job the heal/fence plane acts on: its
    manifest path + rebuild parameters. `solver` is the live restored
    solver after a heal/fence (None until the first one)."""

    path: str
    param: object
    family: str = "ns2d"
    solver: object = None
    devices: int = 0


class Autopilot:
    """The per-daemon policy loop. Constructed by FleetDaemon only when
    the knob is on; every method is driven from the daemon's poll cycle
    (`pre_poll` before the scan, `tick` after the SLO poll)."""

    def __init__(self, daemon, spec: str):
        import jax

        cfg = parse_autopilot_spec(spec)
        if cfg is None:
            raise ValueError("Autopilot constructed with the knob off — "
                             "the daemon must not build one")
        self.d = daemon
        self.cfg = cfg
        self.priorities = parse_priority_spec(
            getattr(daemon.cfg, "priorities", ""))
        self.devices = list(jax.devices())
        self.lanes = daemon.cfg.max_lanes
        if cfg.max_lanes <= 0:
            cfg.max_lanes = max(self.lanes,
                                min(len(self.devices), self.lanes * 2))
        self.rung = 0
        self.epoch = 0
        self.resident: ResidentJob | None = None
        # hysteresis state
        self._above = 0
        self._below = 0
        self._idle = 0
        self._last_transition = -(10 ** 9)  # poll index
        self._last_dir: str | None = None
        self._last_dir_poll = -(10 ** 9)
        self._breach_ts: float | None = None
        self._depths: list[int] = []
        self._saved_classes: str | None = None
        # the trend-gated tallies
        self.counts = {"heal": 0, "grow": 0, "shrink": 0,
                       "degrade": 0, "recover": 0, "shed": 0}
        self.flaps = 0
        self.recoveries_ms: list[float] = []
        if self.priorities:
            # arm the scheduler's preemption hooks (scheduler defaults
            # are None/None — the byte-identical hookless loop)
            daemon.sched.park_store = ParkStore(
                os.path.join(daemon.cfg.queue_dir, "parked_lanes"))
            daemon.sched.priority_of = self.priority_of_sid
        from ..utils import dispatch as _dispatch

        _dispatch.record(
            "tpu_autopilot",
            f"on (burn {cfg.burn_low}..{cfg.burn_high}, backlog "
            f"{cfg.backlog_high}, sustain {cfg.sustain}, cooldown "
            f"{cfg.cooldown}, lanes {cfg.min_lanes}..{cfg.max_lanes}, "
            f"{len(self.priorities)} priority entries)")

    # -- tenant QoS ------------------------------------------------------
    def priority_for(self, tenant: str) -> int:
        return self.priorities.get(
            tenant, self.priorities.get(
                "default", PRIORITY_CLASSES["normal"]))

    def priority_of_sid(self, sid: str) -> int:
        from .serve import tenant_of

        return self.priority_for(tenant_of(sid))

    def quota_for(self, tenant: str) -> int:
        """WEIGHTED admission: the per-tenant pending cap scaled by
        priority class (2x / 1x / 0.5x), floor 1 — a low-priority tenant
        is throttled, never locked out (shedding is rung 3's explicit,
        recorded move, not a quota side effect)."""
        base = self.d.cfg.tenant_quota
        if not self.priorities:
            return base
        return max(1, int(round(base
                                * PRIORITY_WEIGHTS[
                                    self.priority_for(tenant)])))

    def should_shed(self, tenant: str) -> bool:
        """Rung 3: refuse the lowest class at admission."""
        return (self.rung >= LADDER.index("shed_low_priority")
                and bool(self.priorities)
                and self.priority_for(tenant) >= SHED_CLASS)

    def admit(self, req):
        """Rung-2 degradation applied at admission: cap the request's
        pressure-solve budget. Returns the (possibly replaced) request;
        below rung 2 the request passes through untouched."""
        if self.rung < LADDER.index("itermax_cap"):
            return req
        cap = self.cfg.itermax_cap
        if int(req.param.itermax) <= cap:
            return req
        _tm.emit("admission", action="degrade", sid=req.sid,
                 reason="itermax_cap", itermax=cap,
                 requested=int(req.param.itermax), rung=self.rung)
        return dataclasses.replace(req, param=req.param.replace(
            itermax=cap))

    # -- the resident elastic job ---------------------------------------
    def register_resident(self, path: str, param,
                          family: str = "ns2d") -> None:
        """Tell the autopilot which elastic manifest the heal/fence
        plane owns. The daemon serves request traffic; the RESIDENT is
        the long-lived distributed job sharing the capacity — the thing
        a rank death actually hits."""
        self.resident = ResidentJob(path=path, param=param,
                                    family=family,
                                    devices=len(self.devices))
        self._record("resident", manifest=path, family=family)

    def _restore_resident(self, shrink: bool, dead=None, epoch=None):
        """(Re)build the resident on current capacity, stepping the
        device count DOWN on an infeasible mesh (CartComm refuses
        factorizations the grid cannot shard — a 7-survivor mesh on a
        16x16 grid falls back to 4; the divisibility fallback is itself
        a policy decision, recorded via the shrink/fence record's
        devices field)."""
        r = self.resident
        last_exc = None
        for n in range(len(self.devices), 0, -1):
            devs = self.devices[:n]
            try:
                if shrink:
                    from .scheduler import shrink_resume

                    solver = shrink_resume(
                        r.path, r.param, family=r.family, devices=devs,
                        dead=dead, epoch=epoch, scheduler=self.d.sched)
                else:
                    solver = self.d.sched.elastic_restore(
                        r.path, r.param, family=r.family, devices=devs)
            except ValueError as exc:
                last_exc = exc
                continue
            r.solver = solver
            r.devices = n
            return solver
        raise last_exc if last_exc is not None else RuntimeError(
            "no feasible device count for the resident")

    def heal(self, exc=None) -> None:
        """Self-healing: a rank death becomes `shrink_resume` onto
        survivor capacity — no operator. Accepts the structured
        RankDeadError (ranks/epoch/survivors attached) or the raw
        InjectedRankDeath from a `dead@poll<N>` clause (no verdict
        attached: the last device is taken as the casualty)."""
        from ..parallel.coordinator import RankDeadError

        if isinstance(exc, RankDeadError):
            dead = list(exc.ranks)
            epoch = exc.epoch
        else:
            dead = [len(self.devices) - 1]
            epoch = self.epoch + 1
        lost = {r for r in dead if 0 <= r < len(self.devices)}
        survivors = [d for i, d in enumerate(self.devices)
                     if i not in lost]
        if not survivors:
            survivors = self.devices[:1]
        self.devices = survivors
        self.epoch = int(epoch) if epoch is not None else self.epoch + 1
        gen = None
        if self.resident is not None:
            solver = self._restore_resident(shrink=True, dead=dead,
                                            epoch=self.epoch)
            gen = getattr(solver, "_elastic_generation", None)
        # the pool never exceeds capacity: a heal that drops below the
        # current lane count shrinks the pool with it (not a flap — the
        # fleet did not oscillate, it lost hardware)
        cap = max(self.cfg.min_lanes, len(self.devices))
        if self.lanes > cap:
            self.lanes = cap
            self.d.sched.lanes = cap
        self.counts["heal"] += 1
        self._last_transition = self.d.polls
        self._record("heal", dead=sorted(lost), epoch=self.epoch,
                     survivors=len(self.devices), generation=gen,
                     resident_devices=(self.resident.devices
                                       if self.resident else None))

    def _fence(self, reason: str):
        """Checkpoint-fence a capacity transition: save the resident's
        state as a NEW manifest generation, then restore from it — every
        grow/shrink leaves a generation a clean run can bitwise-match
        (the chaos smoke's twin-restore assertion)."""
        if self.resident is None or self.resident.solver is None:
            return None
        from ..utils import checkpoint as _ckpt

        solver = self.resident.solver
        _ckpt.save_elastic(self.resident.path, solver,
                           ledger=getattr(solver, "_fault_ledger",
                                          None))
        solver = self._restore_resident(shrink=False)
        gen = getattr(solver, "_elastic_generation", None)
        _tm.emit("ckpt", event="fence", path=self.resident.path,
                 reason=reason, generation=gen,
                 devices=self.resident.devices)
        return gen

    # -- the poll-cycle hooks -------------------------------------------
    def pre_poll(self, now: float) -> None:
        """Before the scan: consume the daemon-plane fault clauses
        (utils/faultinject.poll_faults). Catching InjectedRankDeath — a
        BaseException by design — is correct HERE and only here: the
        autopilot is the structured consumer that turns a death into
        `shrink_resume`, the same role the lockstep watchdog collector
        plays for `dead@chunk`; it must never reach the generic
        Exception funnels that would misread it as a request failure."""
        try:
            directives = _fi.poll_faults()
        except _fi.InjectedRankDeath:
            self.heal()
            return
        for kind, tenant, count in directives:
            if kind == "burst":
                n = self.d.slo.inject_synthetic(tenant, count, now)
                self._record("inject", fault="burst", tenant=tenant,
                             injected=n)
            elif kind == "slow_lane":
                target = self.d.slo.target_for(tenant) or 1000.0
                for _ in range(int(count)):
                    self.d.metrics.histogram(
                        "fleet_request_latency_ms",
                        tenant=tenant).observe(target * 10.0)
                    self.d.metrics.histogram(
                        "fleet_class_latency_ms", klass="synthetic",
                        family="synthetic").observe(target * 10.0)
                self.d.slo.inject_synthetic(tenant, count, now)
                self._record("inject", fault="slow_lane", tenant=tenant,
                             injected=int(count))

    def tick(self, now: float) -> None:
        """After the SLO poll: one observe→decide→act step. Every tick
        emits exactly one `autoscale` record (decision "hold" included —
        the flight record shows the policy SEEING the signals, not just
        reacting)."""
        inputs = self._observe(now)
        decision = self._decide(inputs, now)
        if decision == "hold":
            self._record("hold", inputs=inputs)
        else:
            self._act(decision, inputs, now)

    # -- observe / decide / act -----------------------------------------
    def _observe(self, now: float) -> dict:
        d = self.d
        burns = d.slo.burn_snapshot(now)
        self._depths.append(int(d.queue_depth))
        if len(self._depths) > self.cfg.trend_window:
            self._depths.pop(0)
        p95s = [h.quantile(0.95)
                for h in d.metrics.histograms("fleet_class_latency_ms")
                if h.n]
        return {
            "burn_max": max(burns.values(), default=0.0),
            "burns": burns,
            "queue_depth": int(d.queue_depth),
            "backlog_trend": int(d.queue_depth) - self._depths[0],
            "p95_worst_ms": (round(max(p95s), 3) if p95s else None),
        }

    def _decide(self, inputs: dict, now: float) -> str:
        cfg = self.cfg
        hot = (inputs["burn_max"] > cfg.burn_high
               or inputs["queue_depth"] >= cfg.backlog_high)
        calm = (inputs["burn_max"] < cfg.burn_low
                and inputs["queue_depth"] < cfg.backlog_high)
        if hot:
            self._above += 1
            self._below = 0
            self._idle = 0
            if self._breach_ts is None:
                self._breach_ts = now  # the time-to-recover clock
        elif calm:
            self._below += 1
            self._above = 0
            self._idle = (self._idle + 1
                          if inputs["queue_depth"] == 0 else 0)
        else:
            # INSIDE the band: hold, and reset both sustain counters —
            # the band is the no-flap buffer
            self._above = 0
            self._below = 0
            self._idle = 0
        # recovery completes when calm has sustained AND the ladder is
        # back at full service — the clock spans breach to full recovery
        if (self._breach_ts is not None and self.rung == 0
                and self._below >= cfg.sustain):
            self.recoveries_ms.append(
                round((now - self._breach_ts) * 1e3, 3))
            self._breach_ts = None
        if self.d.polls - self._last_transition < cfg.cooldown:
            return "hold"
        if self._above >= cfg.sustain:
            cap = min(cfg.max_lanes, len(self.devices))
            if self.lanes < cap:
                return "grow"
            if self.rung < len(LADDER) - 1:
                return "degrade"
            return "hold"  # bottom rung: nothing left to give up
        if self._below >= cfg.sustain:
            if self.rung > 0:
                return "recover"
            if (self._idle >= cfg.idle_polls
                    and self.lanes > cfg.min_lanes):
                return "shrink"
        return "hold"

    def _act(self, decision: str, inputs: dict, now: float) -> None:
        gen = None
        if decision == "grow":
            self.lanes += 1
            self.d.sched.lanes = self.lanes
            gen = self._fence("grow")
            self._mark_dir("up")
        elif decision == "shrink":
            self.lanes -= 1
            self.d.sched.lanes = self.lanes
            gen = self._fence("shrink")
            self._mark_dir("down")
        elif decision == "degrade":
            self.rung += 1
            self._apply_rung()
        elif decision == "recover":
            self.rung -= 1
            self._apply_rung()
        self.counts[decision] += 1
        self._above = 0
        self._below = 0
        self._idle = 0
        self._last_transition = self.d.polls
        self._record(decision, inputs=inputs, generation=gen)

    def _apply_rung(self) -> None:
        """Rung 1 is the only rung with daemon state to flip NOW (force
        shape-class consolidation); rungs 2/3 are consulted at admission
        (`admit` / `should_shed`) so they need no apply step."""
        if (self.rung >= LADDER.index("class_consolidation")
                and self._saved_classes is None):
            self._saved_classes = self.d.sched.classes
            self.d.sched.classes = "on"
        elif self.rung == 0 and self._saved_classes is not None:
            self.d.sched.classes = self._saved_classes
            self._saved_classes = None

    def _mark_dir(self, direction: str) -> None:
        """Flap accounting: an opposite-direction CAPACITY move within
        flap_window polls of the last one is a flap — the thing the
        hysteresis band exists to make zero (trend-gated)."""
        if (self._last_dir is not None and direction != self._last_dir
                and self.d.polls - self._last_dir_poll
                <= self.cfg.flap_window):
            self.flaps += 1
        self._last_dir = direction
        self._last_dir_poll = self.d.polls

    # -- reporting -------------------------------------------------------
    def _record(self, decision: str, **extra) -> None:
        cfg = self.cfg
        _tm.emit("autoscale", decision=decision, poll=self.d.polls,
                 rung=self.rung, rung_name=LADDER[self.rung],
                 lanes=self.lanes, capacity=len(self.devices),
                 hysteresis={
                     "above": self._above, "below": self._below,
                     "cooldown_left": max(
                         0, cfg.cooldown
                         - (self.d.polls - self._last_transition)),
                 },
                 **extra)

    def status_block(self) -> dict:
        store = self.d.sched.park_store
        return {
            "mode": "on",
            "lanes": self.lanes,
            "capacity": len(self.devices),
            "rung": self.rung,
            "rung_name": LADDER[self.rung],
            "epoch": self.epoch,
            "counts": dict(self.counts),
            "flaps": self.flaps,
            "recoveries_ms": list(self.recoveries_ms),
            "parked_lanes": (store.count() if store is not None
                             else 0),
        }

    def emit_stop_metrics(self, backend: str) -> None:
        """The trend-gated autoscale metrics (bench_trend
        NAME_DIRECTIONS pins both lower-is-better): flap count always,
        WORST-case time-to-recover when a breach recovered, and the
        total transition tally (render-only — unitless context, not a
        gate)."""
        _tm.emit("metric", metric="autoscale_flaps", value=self.flaps,
                 unit="transitions", backend=backend)
        if self.recoveries_ms:
            _tm.emit("metric", metric="autoscale_time_to_recover_ms",
                     value=max(self.recoveries_ms), unit="ms",
                     backend=backend)
        transitions = sum(self.counts[k] for k in
                          ("heal", "grow", "shrink", "degrade",
                           "recover"))
        _tm.emit("metric", metric="autoscale_transitions",
                 value=transitions, unit="transitions",
                 backend=backend)
