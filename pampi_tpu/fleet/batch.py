"""Batched multi-tenant driver: N same-bucket scenarios through ONE
vmapped chunk program.

The solvers already expose everything a batch needs — `_build_chunk()` /
`_chunk_sm` (the traced chunk), `initial_state()` (the chunk-arity state
tuple) — so the batched driver is a thin functional wrapper: stack N
per-lane state tuples on a leading scenario axis, vmap the chunk over
it, and drive the result through `models/_driver.drive_chunks` exactly
like a solo run (same retry protocol, same progress/telemetry hook
points). jax batches the chunk's `lax.while_loop`s per lane (a lane
whose own cond is false passes through by `select` — bitwise identity),
so per-lane dt/CFL/residual trajectories are each lane's OWN: the jnp
and dist chunks batch bitwise-equal to solo runs, the fused kernels at
the repo's ulp contract (fma re-association under the batched grid —
the quarters-layout precedent; test-pinned in tests/test_fleet.py).

Diverged-lane isolation (the PR 3 sentinel put to work): the fleet
wrapper appends a per-lane `active` mask plus two drive scalars to the
stacked state. After each vmapped chunk, a lane whose in-band sentinel
fired (or, without telemetry, whose loop time / fields went non-finite)
is retired: `active` drops, and every later chunk passes its state
through bitwise (`where(active, new, old)`) — the blown-up scenario
freezes AT its divergence chunk holding the diagnostic-bearing state,
keeps its emitted divergence record, and its batchmates continue
untouched. The drive loop reads `t_drive = min over active lanes` (+inf
once none remain), so a dead lane never blocks — and never spins — the
fleet. Ring rollback-recovery stays a solo-run feature: a fleet-level
rollback would rewind HEALTHY batchmates to recover one lane, the
opposite of the isolation contract, so the batch driver does not arm it
(requests carrying tpu_recover_ring are still served; the knob is
recorded as inert for the batch).

Per-lane fault injection (`nan|inf@lane<K>:<field>`, utils/faultinject):
consumed at batch build, applied host-side to the stacked INITIAL state
— the compiled chunk is byte-identical to the uninjected batch, so the
isolation proof runs on the production program.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import faultinject as _fi
from ..utils import telemetry as _tm


def lane_state(template, param) -> tuple:
    """One scenario's initial chunk state from the bucket's template
    solver: the template's geometry/arity with the request's init values.
    Exact — every family initializes its fields as constant fills (the
    reference's init_arrays), so `full_like` reproduces precisely what a
    solver built from `param` would hold."""
    fields, tail = _split_state(template, template.initial_state())
    names = _field_names(len(fields))
    inits = {"u": param.u_init, "v": param.v_init, "w": param.w_init,
             "p": param.p_init}
    fresh = tuple(jnp.full_like(x, inits[n])
                  for n, x in zip(names, fields))
    # t/nt restart at zero per scenario; the metrics vector (when it
    # rides) re-arms its sentinel
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    out = fresh + (jnp.asarray(0.0, time_dtype), jnp.asarray(0, jnp.int32))
    if template._metrics:
        out = out + (_tm.metrics_init(),)
    return out


def _field_names(n_fields: int) -> tuple:
    return ("u", "v", "p") if n_fields == 3 else ("u", "v", "w", "p")


def _split_state(template, state):
    """(field leaves, trailing scalars) of one lane state: the state
    convention is (fields..., t, nt[, metrics])."""
    n_tail = 3 if template._metrics else 2
    return state[:len(state) - n_tail], state[len(state) - n_tail:]


class BatchedSolver:
    """N same-signature scenarios as one drive_chunks-compatible solver.

    State layout: (stacked lane leaves..., active, t_drive, nt_drive)
    where the lane leaves follow the template's own chunk arity with a
    leading scenario axis, `active` is the (N,) lane-liveness mask and
    the two drive scalars are what the host loop reads (`time_index` =
    the t_drive slot). Exposes the retry-protocol surface
    (`_backend`/`_uses_pallas`/`_build_chunk`/`_chunk_fn`) by delegating
    to the template, so `models/_driver.pallas_retry` recovers a batched
    pallas failure with the same jnp-fallback/restore protocol as a solo
    run — one fallback covers all N lanes (they share the program)."""

    def __init__(self, template, params, sids, family: str = ""):
        if not params:
            raise ValueError("BatchedSolver needs at least one scenario")
        from .queue import DRIVE_KEYS

        self.template = template
        self.params = list(params)
        self.sids = list(sids)
        self.family = family or type(template).__name__
        # trace-shaping fields come from the template (signature-equal
        # across the batch by construction); the drive-time knobs —
        # signature-excluded, so they CAN differ — come from the FIRST
        # request: one drive loop serves all lanes, and the template's
        # own values belong to whichever tenant happened to build it
        self.param = template.param.replace(
            **{k: getattr(self.params[0], k) for k in DRIVE_KEYS})
        self.dtype = template.dtype
        self.n = len(self.params)
        self._metrics = template._metrics
        self._lane_arity = len(template.initial_state())
        self._time_index = self._lane_arity - (3 if self._metrics else 2)
        self._n_fields = self._time_index
        # only clauses THIS batch can express are consumed — a clause
        # aimed past the lane count (or at a field the family lacks)
        # stays armed for the batch it targets
        self._lane_faults = _fi.take_lane_faults(
            n_lanes=self.n, fields=_field_names(self._n_fields))
        t0 = time.perf_counter()
        self._chunk_fn = jax.jit(self._build_chunk())
        _tm.emit("build", family=f"fleet.{self.family}", lanes=self.n,
                 trace_wall_s=round(time.perf_counter() - t0, 3))

    def rebind(self, params, sids) -> None:
        """Point this compiled batch at a NEW same-signature request set
        — the scheduler's warm path. The vmapped chunk is lane-COUNT-
        and trace-specific, never lane-VALUE-specific: initial states
        are rebuilt from the new requests' init fields, the compiled
        program is reused untouched (zero retrace). Drive knobs re-derive
        from the new first request; lane-fault clauses re-arm for the
        new batch like a fresh build would."""
        from .queue import DRIVE_KEYS

        if len(params) != self.n:
            raise ValueError(
                f"rebind needs {self.n} scenarios (got {len(params)}) — "
                "a different lane count is a different compiled batch")
        self.params = list(params)
        self.sids = list(sids)
        self.param = self.template.param.replace(
            **{k: getattr(self.params[0], k) for k in DRIVE_KEYS})
        self._lane_faults = _fi.take_lane_faults(
            n_lanes=self.n, fields=_field_names(self._n_fields))

    # -- retry-protocol surface (models/_driver._PallasRetry) ----------
    @property
    def _backend(self):
        return self.template._backend

    @_backend.setter
    def _backend(self, value):
        self.template._backend = value

    def _uses_pallas(self) -> bool:
        return self.template._uses_pallas()

    def _dist(self) -> bool:
        return hasattr(self.template, "_chunk_sm")

    # -- the batched chunk ---------------------------------------------
    def _build_chunk(self, backend: str | None = None):
        tpl = self.template
        if self._dist():
            # the dist chunk is one traced shard_map program with no
            # per-backend rebuild path (models/ns2d_dist.run contract):
            # vmap it as-is; the retry hook returns None there
            inner = tpl._chunk_sm
        else:
            inner = tpl._build_chunk(
                backend if backend is not None else tpl._backend)
        vchunk = jax.vmap(inner)
        ti, mi = self._time_index, (
            self._lane_arity - 1 if self._metrics else None)
        n_fields = self._n_fields

        def fleet_chunk(*state):
            lanes = state[:self._lane_arity]
            active = state[self._lane_arity]
            new = vchunk(*lanes)
            # freeze retired lanes bitwise: a lane that diverged in an
            # earlier chunk keeps its diagnostic-bearing state untouched
            out = tuple(
                jnp.where(active.reshape((-1,) + (1,) * (x.ndim - 1)),
                          x, old)
                for x, old in zip(new, lanes))
            t = out[ti]
            ok = jnp.isfinite(t)
            if mi is not None:
                # the in-band sentinel (PR 3): latched per lane inside
                # the vmapped chunk, read at the boundary like solo runs
                ok = jnp.logical_and(ok, out[mi][:, _tm.M_BAD] < 0)
            else:
                # telemetry off: no sentinel rides the chunk — the fleet
                # wrapper's own per-lane finiteness reductions stand in
                # (one cheap pass per field per chunk, fleet-only ops:
                # the solo chunk program is untouched)
                for f in out[:n_fields]:
                    fin = jnp.all(jnp.isfinite(f),
                                  axis=tuple(range(1, f.ndim)))
                    ok = jnp.logical_and(ok, fin)
            active = jnp.logical_and(active, ok)
            t_drive = jnp.min(jnp.where(active, t, jnp.inf))
            nt_drive = jnp.max(out[ti + 1])
            return (*out, active, t_drive, nt_drive)

        return fleet_chunk

    # -- drive API ------------------------------------------------------
    def initial_state(self) -> tuple:
        lanes = [lane_state(self.template, p) for p in self.params]
        stacked = tuple(jnp.stack(leaves) for leaves in zip(*lanes))
        names = _field_names(self._n_fields)
        for field, lane, value in self._lane_faults:
            # take_lane_faults only hands back clauses this batch can
            # express, so every one applies
            i = names.index(field)
            stacked = (stacked[:i]
                       + (stacked[i].at[lane].set(value),)
                       + stacked[i + 1:])
        active = jnp.ones((self.n,), bool)
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32
        return stacked + (active, jnp.asarray(0.0, time_dtype),
                          jnp.asarray(0, jnp.int32))

    def run(self, progress: bool = False):
        """Drive the batch to te through models/_driver.drive_chunks —
        the solo drive loop, unchanged: transient retry and the
        pallas->jnp fallback/restore operate per BATCH (all lanes share
        the program), divergence is per-LANE masking inside the chunk
        (the loop-level RingRecovery stays a solo feature — a fleet
        rollback would rewind healthy batchmates to recover one lane).
        Returns the final fleet state; read it with `results()`."""
        from ..models._driver import drive_chunks, pallas_retry
        from ..utils import flags as _flags
        from ..utils.progress import Progress

        te = self.param.te
        bar = Progress(te, enabled=progress and not _flags.verbose())
        state = self.initial_state()
        rec = (FleetRecorder(self.family, self.sids)
               if self._metrics else None)

        def on_state(s):
            if rec is not None:
                rec.update(self, s)

        # t_drive sits right past the lanes-plus-active block; nt_drive
        # rides one slot later (the drive loop's ETA contract)
        time_index = self._lane_arity + 1
        if self._dist():
            # no per-backend rebuild path for the shard_map chunk, and
            # no rank-local transient retry under multi-process (the
            # models/ns2d_dist.run convention)
            retry = lambda: None  # noqa: E731 - the dist no-retry hook
            budget = 0 if jax.process_count() > 1 else 1
        else:
            retry = pallas_retry(
                self, "fleet chunk",
                restore_after=self.param.tpu_retry_replenish)
            budget = 1
        return drive_chunks(
            state, self._chunk_fn, te, time_index, bar, retry,
            on_state=on_state, lookahead=self.param.tpu_lookahead,
            replenish_after=self.param.tpu_retry_replenish,
            recover=None, transient_budget=budget)

    def results(self, state) -> list[dict]:
        """Per-scenario results from a final fleet state: one dict per
        lane {sid, t, nt, diverged, fields} — `fields` in the template's
        own layout (dist lanes hold stacked shard blocks, exactly what
        the solo solver publishes)."""
        active = np.asarray(state[self._lane_arity])
        t = np.asarray(state[self._time_index])
        nt = np.asarray(state[self._time_index + 1])
        out = []
        for i, sid in enumerate(self.sids):
            fields = tuple(np.asarray(leaf[i])
                           for leaf in state[:self._n_fields])
            out.append({
                "sid": sid,
                "t": float(t[i]),
                "nt": int(nt[i]),
                "diverged": not bool(active[i]),
                "fields": fields,
            })
        return out


class FleetRecorder:
    """Per-lane telemetry at each host sync: one ChunkRecorder per
    scenario (chunk records tagged with the scenario id; each lane's
    divergence record fires once, from its own sentinel). A retired or
    finished lane whose step counter stopped advancing emits no further
    chunk records — a frozen lane is visible as silence after its
    divergence record, not as a stream of zero-step rows."""

    def __init__(self, family: str, sids, nt0: int = 0):
        self._recs = [_tm.ChunkRecorder(family, nt0, scenario=sid)
                      for sid in sids]
        self._nts = [nt0] * len(sids)

    def update(self, batched: BatchedSolver, state) -> None:
        if not _tm.enabled():
            return
        ti = batched._time_index
        t = np.asarray(state[ti])
        nt = np.asarray(state[ti + 1])
        m = np.asarray(state[batched._lane_arity - 1])  # metrics (N, 7)
        for i, rec in enumerate(self._recs):
            if int(nt[i]) == self._nts[i]:
                continue
            self._nts[i] = int(nt[i])
            rec.update(float(t[i]), int(nt[i]), m[i])
