"""Batched multi-tenant driver: N same-bucket scenarios through ONE
vmapped chunk program.

The solvers already expose everything a batch needs — `_build_chunk()` /
`_chunk_sm` (the traced chunk), `initial_state()` (the chunk-arity state
tuple) — so the batched driver is a thin functional wrapper: stack N
per-lane state tuples on a leading scenario axis, vmap the chunk over
it, and drive the result through `models/_driver.drive_chunks` exactly
like a solo run (same retry protocol, same progress/telemetry hook
points). jax batches the chunk's `lax.while_loop`s per lane (a lane
whose own cond is false passes through by `select` — bitwise identity),
so per-lane dt/CFL/residual trajectories are each lane's OWN: the jnp
and dist chunks batch bitwise-equal to solo runs, the fused kernels at
the repo's ulp contract (fma re-association under the batched grid —
the quarters-layout precedent; test-pinned in tests/test_fleet.py).

Fleet v2 additions (ISSUE 14):

- PER-LANE te: with `te_carry` the end time rides the batched state as
  an (N,) vector and the inner chunk takes it as a traced trailing
  argument (`_build_chunk(te_arg=True)` in the single-device families),
  so mixed end times share one compile and each lane's while-cond stops
  exactly where its solo twin would — batch-of-N-mixed-te == N solo at
  the ulp contract, test-pinned. te_carry off (the default) is the
  byte-identical PR 9 trace (CONTRACTS.json hashes unchanged); mixed-te
  DIST buckets are split per te by the scheduler instead (the shard_map
  chunk still bakes te).
- CONTINUOUS LANE SWAP: `swap_lane(state, lane, param, sid)` splices a
  fresh scenario into a finished or diverged lane's slot host-side —
  the compiled chunk is untouched (zero retrace per (signature,
  lanes)), the new lane starts at t=0 in its slot and tracks its solo
  twin bitwise on the jnp paths. `harvest(state, lane)` reads one
  lane's result without draining the batch.
- FLEET-OVER-MESH: `mesh=` (a device list) shards the scenario axis
  across a mesh axis via NamedSharding — the middle mode between vmap
  (one device) and whole-mesh pjit: a v5e-8 serves 8 lanes in true
  parallel with zero collectives between lanes (the traced program
  carries no cross-lane ops except the scalar t_drive reduction;
  commcheck's zero-resharding ban pins it).
- CLASS TEMPLATES: a template exposing `lane_state(param)` /
  `crop_lane(fields, param)` / `_time_index` (fleet/shapeclass.
  ClassSolver) supplies per-lane state with the grid extents as data —
  the shape-class chunk rides this same wrapper unchanged.

Diverged-lane isolation (the PR 3 sentinel put to work): the fleet
wrapper appends a per-lane `active` mask plus two drive scalars to the
stacked state. After each vmapped chunk, a lane whose in-band sentinel
fired (or, without telemetry, whose loop time / fields went non-finite)
is retired: `active` drops, and every later chunk passes its state
through bitwise (`where(active, new, old)`) — the blown-up scenario
freezes AT its divergence chunk holding the diagnostic-bearing state,
keeps its emitted divergence record, and its batchmates continue
untouched. The drive loop reads `t_drive = min over active (and, under
te_carry, unfinished) lanes` (+inf once none remain), so a dead lane
never blocks — and never spins — the fleet. Ring rollback-recovery
stays a solo-run feature: a fleet-level rollback would rewind HEALTHY
batchmates to recover one lane, the opposite of the isolation contract,
so the batch driver does not arm it (requests carrying tpu_recover_ring
are still served; the knob is recorded as inert for the batch).

Per-lane fault injection (`nan|inf@lane<K>:<field>`, utils/faultinject):
consumed at batch build, applied host-side to the stacked INITIAL state
— the compiled chunk is byte-identical to the uninjected batch, so the
isolation proof runs on the production program.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import faultinject as _fi
from ..utils import telemetry as _tm


def lane_state(template, param) -> tuple:
    """One scenario's initial chunk state from the bucket's template
    solver: the template's geometry/arity with the request's init values.
    Exact — every family initializes its fields as constant fills (the
    reference's init_arrays), so `full_like` reproduces precisely what a
    solver built from `param` would hold. A template with its own
    `lane_state` hook (the shape-class ClassSolver) builds the lane
    itself — per-lane geometry scalars included."""
    hook = getattr(template, "lane_state", None)
    if hook is not None:
        return hook(param)
    fields, tail = _split_state(template, template.initial_state())
    names = _field_names(len(fields))
    inits = {"u": param.u_init, "v": param.v_init, "w": param.w_init,
             "p": param.p_init}
    fresh = tuple(jnp.full_like(x, inits[n])
                  for n, x in zip(names, fields))
    # t/nt restart at zero per scenario; the metrics vector (when it
    # rides) re-arms its sentinel
    time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    out = fresh + (jnp.asarray(0.0, time_dtype), jnp.asarray(0, jnp.int32))
    if template._metrics:
        out = out + (_tm.metrics_init(),)
    return out


def _field_names(n_fields: int) -> tuple:
    return ("u", "v", "p") if n_fields == 3 else ("u", "v", "w", "p")


def _split_state(template, state):
    """(field leaves, trailing scalars) of one lane state: the state
    convention is (fields..., t, nt[, metrics])."""
    n_tail = 3 if template._metrics else 2
    return state[:len(state) - n_tail], state[len(state) - n_tail:]


class BatchedSolver:
    """N same-signature scenarios as one drive_chunks-compatible solver.

    State layout: (stacked lane leaves...[, te], active, t_drive,
    nt_drive) where the lane leaves follow the template's own chunk
    arity with a leading scenario axis, `te` is the (N,) per-lane end
    time (present only under te_carry), `active` is the (N,)
    lane-liveness mask and the two drive scalars are what the host loop
    reads (`time_index` = the t_drive slot). Exposes the retry-protocol
    surface (`_backend`/`_uses_pallas`/`_build_chunk`/`_chunk_fn`) by
    delegating to the template, so `models/_driver.pallas_retry`
    recovers a batched pallas failure with the same jnp-fallback/restore
    protocol as a solo run — one fallback covers all N lanes (they share
    the program)."""

    def __init__(self, template, params, sids, family: str = "",
                 te_carry=None, mesh=None):
        if not params:
            raise ValueError("BatchedSolver needs at least one scenario")
        from .queue import DRIVE_KEYS

        self.template = template
        self.params = list(params)
        self.sids = list(sids)
        self.family = family or type(template).__name__
        # trace-shaping fields come from the template (signature-equal
        # across the batch by construction); the drive-time knobs —
        # signature-excluded, so they CAN differ — come from the FIRST
        # request: one drive loop serves all lanes, and the template's
        # own values belong to whichever tenant happened to build it
        self.param = template.param.replace(
            **{k: getattr(self.params[0], k) for k in DRIVE_KEYS})
        self.dtype = template.dtype
        self.n = len(self.params)
        self._metrics = template._metrics
        self._lane_arity = len(template.initial_state())
        self._time_index = getattr(
            template, "_time_index",
            self._lane_arity - (3 if self._metrics else 2))
        self._n_fields = getattr(template, "_n_fields", self._time_index)
        lane_tes = {float(p.te) for p in self.params}
        tpl_te = float(template.param.te)
        # te needs carrying when the lanes disagree with each other OR
        # with the end time baked into the template's own trace (te is
        # signature-excluded since serving v2, so a cached template may
        # have been built under another tenant's te)
        mixed_te = len(lane_tes) > 1 or lane_tes != {tpl_te}
        # a class template's chunk takes te unconditionally (its carry
        # is inherently per-lane); solver templates opt in per batch
        self._te_carry = bool(getattr(template, "_te_always", False)
                              or (mixed_te if te_carry is None
                                  else te_carry))
        if mixed_te and not self._te_carry:
            raise ValueError(
                "per-lane te off-template needs te_carry (the dist "
                "chunk bakes te — the scheduler splits such buckets "
                "per te)")
        if self._te_carry and self._dist():
            raise ValueError(
                "te_carry is a single-device-chunk feature (the "
                "shard_map chunk bakes te; dist buckets split per te)")
        self._te_index = self._lane_arity if self._te_carry else None
        self._active_index = self._lane_arity + (
            1 if self._te_carry else 0)
        self._mesh = list(mesh) if mesh else None
        if self._mesh and self.n % len(self._mesh) != 0:
            raise ValueError(
                f"fleet-over-mesh needs lanes ({self.n}) divisible by "
                f"devices ({len(self._mesh)})")
        # only clauses THIS batch can express are consumed — a clause
        # aimed past the lane count (or at a field the family lacks)
        # stays armed for the batch it targets
        self._lane_faults = _fi.take_lane_faults(
            n_lanes=self.n, fields=_field_names(self._n_fields))
        t0 = time.perf_counter()
        self._chunk_fn = self._jit(self._build_chunk())
        _tm.emit("build", family=f"fleet.{self.family}", lanes=self.n,
                 te_carry=self._te_carry,
                 mesh=len(self._mesh) if self._mesh else 0,
                 trace_wall_s=round(time.perf_counter() - t0, 3))

    def rebind(self, params, sids) -> None:
        """Point this compiled batch at a NEW same-signature request set
        — the scheduler's warm path. The vmapped chunk is lane-COUNT-
        and trace-specific, never lane-VALUE-specific: initial states
        are rebuilt from the new requests' init fields, the compiled
        program is reused untouched (zero retrace). Drive knobs re-derive
        from the new first request; lane-fault clauses re-arm for the
        new batch like a fresh build would."""
        from .queue import DRIVE_KEYS

        if len(params) != self.n:
            raise ValueError(
                f"rebind needs {self.n} scenarios (got {len(params)}) — "
                "a different lane count is a different compiled batch")
        if (not self._te_carry
                and {float(p.te) for p in params}
                != {float(self.template.param.te)}):
            raise ValueError(
                "this batch was compiled without the per-lane te carry; "
                "a request set off the template's baked te is a "
                "different compiled batch")
        self.params = list(params)
        self.sids = list(sids)
        self.param = self.template.param.replace(
            **{k: getattr(self.params[0], k) for k in DRIVE_KEYS})
        self._lane_faults = _fi.take_lane_faults(
            n_lanes=self.n, fields=_field_names(self._n_fields))

    # -- retry-protocol surface (models/_driver._PallasRetry) ----------
    @property
    def _backend(self):
        return self.template._backend

    @_backend.setter
    def _backend(self, value):
        self.template._backend = value

    def _uses_pallas(self) -> bool:
        return self.template._uses_pallas()

    def _dist(self) -> bool:
        return hasattr(self.template, "_chunk_sm")

    # -- the batched chunk ---------------------------------------------
    def _jit(self, fn):
        """jit the fleet chunk — plain on one device; under `mesh`, the
        scenario axis is sharded across the mesh's `lanes` axis via
        NamedSharding (lane leaves P("lanes"), drive scalars
        replicated). The traced program is the identical vmapped chunk
        (shardings live at the jit boundary, so the jaxpr census stays
        collective-free — the commcheck zero-resharding contract); the
        partitioner then runs n/devices lanes per chip with no
        cross-lane communication beyond the scalar t_drive reduction."""
        if not self._mesh:
            return jax.jit(fn)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(self._mesh), ("lanes",))

        def spec(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] == self.n:
                return NamedSharding(mesh, PartitionSpec("lanes"))
            return NamedSharding(mesh, PartitionSpec())

        shardings = tuple(spec(x) for x in self.initial_state())
        return jax.jit(fn, in_shardings=shardings,
                       out_shardings=shardings)

    def _build_chunk(self, backend: str | None = None):
        tpl = self.template
        if self._dist():
            # the dist chunk is one traced shard_map program with no
            # per-backend rebuild path (models/ns2d_dist.run contract):
            # vmap it as-is; the retry hook returns None there
            inner = tpl._chunk_sm
        elif self._te_carry:
            # per-lane te: the inner chunk takes te as a traced trailing
            # argument (models/ns2d._build_chunk te_arg contract)
            inner = tpl._build_chunk(
                backend if backend is not None else tpl._backend,
                te_arg=True)
        else:
            inner = tpl._build_chunk(
                backend if backend is not None else tpl._backend)
        vchunk = jax.vmap(inner)
        ti, mi = self._time_index, (
            self._lane_arity - 1 if self._metrics else None)
        n_fields = self._n_fields

        def lane_ok(out, t):
            ok = jnp.isfinite(t)
            if mi is not None:
                # the in-band sentinel (PR 3): latched per lane inside
                # the vmapped chunk, read at the boundary like solo runs
                ok = jnp.logical_and(ok, out[mi][:, _tm.M_BAD] < 0)
            else:
                # telemetry off: no sentinel rides the chunk — the fleet
                # wrapper's own per-lane finiteness reductions stand in
                # (one cheap pass per field per chunk, fleet-only ops:
                # the solo chunk program is untouched)
                for f in out[:n_fields]:
                    fin = jnp.all(jnp.isfinite(f),
                                  axis=tuple(range(1, f.ndim)))
                    ok = jnp.logical_and(ok, fin)
            return ok

        if self._te_carry:
            def fleet_chunk(*state):
                lanes = state[:self._lane_arity]
                te = state[self._lane_arity]
                active = state[self._lane_arity + 1]
                new = vchunk(*lanes, te)
                out = tuple(
                    jnp.where(active.reshape(
                        (-1,) + (1,) * (x.ndim - 1)), x, old)
                    for x, old in zip(new, lanes))
                t = out[ti]
                active = jnp.logical_and(active, lane_ok(out, t))
                # a lane past its OWN te is finished: exclude it from
                # the drive minimum (its frozen t would otherwise hold
                # t_drive below a longer lane's te forever)
                running = jnp.logical_and(active, t <= te)
                t_drive = jnp.min(jnp.where(running, t, jnp.inf))
                nt_drive = jnp.max(out[ti + 1])
                return (*out, te, active, t_drive, nt_drive)
        else:
            def fleet_chunk(*state):
                lanes = state[:self._lane_arity]
                active = state[self._lane_arity]
                new = vchunk(*lanes)
                # freeze retired lanes bitwise: a lane that diverged in
                # an earlier chunk keeps its diagnostic-bearing state
                out = tuple(
                    jnp.where(active.reshape(
                        (-1,) + (1,) * (x.ndim - 1)), x, old)
                    for x, old in zip(new, lanes))
                t = out[ti]
                active = jnp.logical_and(active, lane_ok(out, t))
                t_drive = jnp.min(jnp.where(active, t, jnp.inf))
                nt_drive = jnp.max(out[ti + 1])
                return (*out, active, t_drive, nt_drive)

        return fleet_chunk

    # -- drive API ------------------------------------------------------
    def initial_state(self) -> tuple:
        lanes = [lane_state(self.template, p) for p in self.params]
        stacked = tuple(jnp.stack(leaves) for leaves in zip(*lanes))
        names = _field_names(self._n_fields)
        for field, lane, value in self._lane_faults:
            # take_lane_faults only hands back clauses this batch can
            # express, so every one applies
            i = names.index(field)
            stacked = (stacked[:i]
                       + (stacked[i].at[lane].set(value),)
                       + stacked[i + 1:])
        active = jnp.ones((self.n,), bool)
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32
        if self._te_carry:
            te = jnp.asarray([float(p.te) for p in self.params],
                             time_dtype)
            stacked = stacked + (te,)
        return stacked + (active, jnp.asarray(0.0, time_dtype),
                          jnp.asarray(0, jnp.int32))

    def drive_te(self) -> float:
        """The end time the HOST loop drives to: the max lane te (every
        lane's own while-cond stops it at its own te first)."""
        return max(float(p.te) for p in self.params)

    def lane_done(self, state) -> np.ndarray:
        """(N,) host bools: lane finished (past its own te) OR retired
        (diverged) — the continuous-batching swap predicate."""
        active = np.asarray(state[self._active_index])
        t = np.asarray(state[self._time_index])
        if self._te_carry:
            te = np.asarray(state[self._te_index])
        else:
            te = float(self.param.te)
        return np.logical_or(~active, t > te)

    def swap_lane(self, state, lane: int, param, sid: str) -> tuple:
        """CONTINUOUS BATCHING: splice a fresh scenario into lane
        `lane`'s slot — host-side state surgery on the stacked leaves,
        the compiled chunk untouched (zero retrace). The new lane starts
        at t=0 under its own te and advances bitwise like a solo run
        from the next chunk dispatch. The caller harvests the outgoing
        lane's result FIRST (`harvest`)."""
        if not (0 <= lane < self.n):
            raise ValueError(f"lane {lane} out of range 0..{self.n - 1}")
        if (not self._te_carry
                and float(param.te) != float(self.param.te)):
            raise ValueError(
                "swapping in a different te needs a te_carry batch")
        fresh = lane_state(self.template, param)
        out = list(state)
        for i, leaf in enumerate(fresh):
            out[i] = out[i].at[lane].set(leaf)
        if self._te_carry:
            out[self._te_index] = out[self._te_index].at[lane].set(
                float(param.te))
        out[self._active_index] = \
            out[self._active_index].at[lane].set(True)
        # the drive scalars refresh at the next chunk boundary; reset
        # t_drive so the host loop cannot terminate on a stale minimum
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32
        out[self._active_index + 1] = jnp.asarray(0.0, time_dtype)
        self.params[lane] = param
        self.sids[lane] = sid
        _tm.emit("swap", family=f"fleet.{self.family}", lane=lane,
                 scenario=sid)
        return tuple(out)

    def park_lane(self, state, lane: int) -> dict:
        """QoS PREEMPTION (fleet/autopilot.py): lift lane `lane`'s full
        per-lane carry off the device — every stacked leaf below the
        batch scalars (fields, per-lane t/nt, the te slot when carried)
        at the current chunk boundary — so a higher-priority tenant can
        take the slot NOW and the victim resumes later via `resume_lane`
        from exactly this state, bitwise (chunk advances are per-lane
        independent, so park/resume at boundaries never perturbs the
        victim's own step sequence or its batchmates'). Returns
        {sid, param, leaves}; the caller persists `leaves` through
        utils/checkpoint.save_parked_lane."""
        if not (0 <= lane < self.n):
            raise ValueError(f"lane {lane} out of range 0..{self.n - 1}")
        leaves = [np.asarray(leaf[lane])
                  for leaf in state[:self._active_index]]
        return {"sid": self.sids[lane], "param": self.params[lane],
                "leaves": leaves}

    def resume_lane(self, state, lane: int, leaves, param,
                    sid: str) -> tuple:
        """Splice a parked lane's carry back into slot `lane` — the
        inverse of `park_lane`, same host-side surgery as `swap_lane`
        except the state comes from the park file instead of a fresh
        `lane_state`, so the lane continues mid-flight from the boundary
        it was evicted at."""
        if not (0 <= lane < self.n):
            raise ValueError(f"lane {lane} out of range 0..{self.n - 1}")
        if len(leaves) != self._active_index:
            raise ValueError(
                f"parked lane carries {len(leaves)} leaves; this batch "
                f"expects {self._active_index} (a different te-carry or "
                "family shape is a different bucket)")
        out = list(state)
        for i, leaf in enumerate(leaves):
            out[i] = out[i].at[lane].set(jnp.asarray(leaf))
        out[self._active_index] = \
            out[self._active_index].at[lane].set(True)
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 \
            else jnp.float32
        out[self._active_index + 1] = jnp.asarray(0.0, time_dtype)
        self.params[lane] = param
        self.sids[lane] = sid
        _tm.emit("swap", family=f"fleet.{self.family}", lane=lane,
                 scenario=sid, resumed=True)
        return tuple(out)

    def run(self, progress: bool = False):
        """Drive the batch to te through models/_driver.drive_chunks —
        the solo drive loop, unchanged: transient retry and the
        pallas->jnp fallback/restore operate per BATCH (all lanes share
        the program), divergence is per-LANE masking inside the chunk
        (the loop-level RingRecovery stays a solo feature — a fleet
        rollback would rewind healthy batchmates to recover one lane).
        Returns the final fleet state; read it with `results()`."""
        from ..models._driver import drive_chunks, pallas_retry
        from ..utils import flags as _flags
        from ..utils.progress import Progress

        te = self.drive_te()
        bar = Progress(te, enabled=progress and not _flags.verbose())
        state = self.initial_state()
        rec = (FleetRecorder(self.family, self.sids)
               if self._metrics else None)

        def on_state(s):
            if rec is not None:
                rec.update(self, s)

        # t_drive sits right past the lanes(+te)-plus-active block;
        # nt_drive rides one slot later (the drive loop's ETA contract)
        time_index = self._active_index + 1
        if self._dist():
            # no per-backend rebuild path for the shard_map chunk, and
            # no rank-local transient retry under multi-process (the
            # models/ns2d_dist.run convention)
            retry = lambda: None  # noqa: E731 - the dist no-retry hook
            budget = 0 if jax.process_count() > 1 else 1
        else:
            retry = pallas_retry(
                self, "fleet chunk",
                restore_after=self.param.tpu_retry_replenish)
            budget = 1
        return drive_chunks(
            state, self._chunk_fn, te, time_index, bar, retry,
            on_state=on_state, lookahead=self.param.tpu_lookahead,
            replenish_after=self.param.tpu_retry_replenish,
            recover=None, transient_budget=budget)

    def harvest(self, state, lane: int) -> dict:
        """One lane's result from a fleet state (the continuous-batching
        read-out; results() maps it over every lane)."""
        active = np.asarray(state[self._active_index])
        t = np.asarray(state[self._time_index])
        nt = np.asarray(state[self._time_index + 1])
        fields = tuple(np.asarray(leaf[lane])
                       for leaf in state[:self._n_fields])
        crop = getattr(self.template, "crop_lane", None)
        if crop is not None:
            fields = crop(fields, self.params[lane])
        return {
            "sid": self.sids[lane],
            "t": float(t[lane]),
            "nt": int(nt[lane]),
            "diverged": not bool(active[lane]),
            "fields": fields,
            # the harvest clock: when this lane's result left the device
            # plane — the request trace's `done` boundary (the scheduler
            # maps it onto utils/tracing marks; the continuous path's
            # completion ordering rides the same stamp)
            "served_ts": time.time(),
        }

    def results(self, state) -> list[dict]:
        """Per-scenario results from a final fleet state: one dict per
        lane {sid, t, nt, diverged, fields} — `fields` in the template's
        own layout (dist lanes hold stacked shard blocks, exactly what
        the solo solver publishes; class lanes are cropped back to their
        request's reference layout via the template's crop hook)."""
        return [self.harvest(state, i) for i in range(self.n)]


class FleetRecorder:
    """Per-lane telemetry at each host sync: one ChunkRecorder per
    scenario (chunk records tagged with the scenario id; each lane's
    divergence record fires once, from its own sentinel). A retired or
    finished lane whose step counter stopped advancing emits no further
    chunk records — a frozen lane is visible as silence after its
    divergence record, not as a stream of zero-step rows. `rearm(lane,
    sid)` re-points one slot at a swapped-in scenario (continuous
    batching)."""

    def __init__(self, family: str, sids, nt0: int = 0):
        self._family = family
        self._recs = [_tm.ChunkRecorder(family, nt0, scenario=sid)
                      for sid in sids]
        self._nts = [nt0] * len(sids)

    def rearm(self, lane: int, sid: str) -> None:
        self._recs[lane] = _tm.ChunkRecorder(self._family, 0,
                                             scenario=sid)
        self._nts[lane] = 0

    def update(self, batched: BatchedSolver, state) -> None:
        if not _tm.enabled():
            return
        ti = batched._time_index
        t = np.asarray(state[ti])
        nt = np.asarray(state[ti + 1])
        m = np.asarray(state[batched._lane_arity - 1])  # metrics (N, 7)
        for i, rec in enumerate(self._recs):
            if int(nt[i]) == self._nts[i]:
                continue
            self._nts[i] = int(nt[i])
            rec.update(float(t[i]), int(nt[i]), m[i])
