"""Fleet scheduler: queue -> buckets -> batched/sequential execution ->
per-scenario results + a fleet summary artifact.

The serving front of the scenario fleet (ROADMAP item 3): accept a queue
of `.par`-equivalent requests, group them into shared-trace buckets
(fleet/queue.py), pick the execution mode per bucket via the `tpu_fleet`
knob (utils/dispatch.resolve_fleet — every decision recorded like
`tpu_overlap`), and reuse compiled programs aggressively:

- in-process: ONE template solver per knob signature (`_TEMPLATES`) —
  the second batch of a bucket, and every later same-signature request,
  pays zero retrace;
- cross-process: `utils/xlacache.enable()` is armed by the scheduler
  (not just the CLI path), so a warm disk cache turns the per-bucket
  compile into a load on every serving process.

Execution modes (see resolve_fleet for the auto policy):
  vmap   fleet/batch.BatchedSolver — one vmapped chunk advances every
         lane; diverged lanes freeze, batchmates continue
  mesh   fleet-over-mesh (fleet v2): the vmapped chunk's scenario axis
         sharded across a device-mesh axis via NamedSharding — N lanes
         in true parallel on N chips, zero collectives between lanes
         (the commcheck zero-resharding ban is the contract)
  class  shape-class batching (fleet/shapeclass.py): eligible
         mixed-GRID requests pad-and-mask into one power-of-two class
         program whose grid extents are per-lane data — a thousand
         slightly-different grids compile a handful of programs
  pjit   whole-mesh per scenario, sequential, template reused (the
         dist-bucket mode: the existing solver IS the pjit-across-mesh
         program; lanes run through solver.run() under scenario_scope)
  solo   the historical path — a fresh solver per request (the
         fleet-smoke drift oracle)

Continuous batching (fleet v2): with a lane-pool size set (`lanes=`,
the daemon's max_lanes), a bucket larger than the pool runs as a
CONTINUOUS batch — a finished or diverged lane is swapped for a queued
scenario host-side (`BatchedSolver.swap_lane`; zero retrace per
(signature, lanes)) instead of draining the whole batch, and per-lane
te rides the chunk carry so mixed end times share the compile.

Every run emits the fleet summary through the telemetry plane: one
`fleet` record {n_scenarios, buckets: [per-bucket mode/compile-vs-run
walls], scenarios_per_s, divergence_census}, per-bucket spans, and a
`fleet_scenarios_per_s` metric record — `tools/telemetry_report.py
--merge` folds the summary into BENCH/MULTICHIP artifacts as
`fleet_summary`, `tools/check_artifact.py` lints it, and
`tools/bench_trend.py` gates the throughput higher-is-better.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from ..utils import telemetry as _tm
from ..utils import tracing as _tr
from . import queue as _q
from .batch import BatchedSolver, lane_state, _field_names, _split_state

# in-process executable caches above the on-disk xlacache:
# _TEMPLATES: knob signature -> (template solver, dist) — the one traced
# solo program per bucket; _BATCHES: (signature, lane count) -> the
# compiled BatchedSolver, so a warm same-shape batch REBINDS to new
# requests and pays zero retrace (a fresh jax.jit per batch would
# recompile the vmapped chunk every run — the serving rate would be
# compile-bound, BENCH_r07's round-14 finding)
_TEMPLATES: dict[str, tuple] = {}
_BATCHES: dict[tuple, object] = {}


def reset_templates() -> None:
    """Drop the in-process executable caches (tests)."""
    _TEMPLATES.clear()
    _BATCHES.clear()


def _drop_batches(sig: str) -> None:
    """Invalidate cached batches of one signature (their inner chunk
    wraps a template program that just changed — e.g. a contamination
    heal re-traced it)."""
    for key in [k for k in _BATCHES if k[0] == sig]:
        del _BATCHES[key]


@dataclasses.dataclass
class ScenarioResult:
    sid: str
    bucket: str
    mode: str
    family: str
    t: float
    nt: int
    diverged: bool
    fields: tuple
    # scheduling failed for this request's whole bucket (isolate mode:
    # the daemon's per-bucket degradation — see FleetScheduler.run)
    failed: bool = False
    error: str = ""


@dataclasses.dataclass
class FleetResult:
    scenarios: list
    summary: dict

    def by_sid(self, sid: str) -> ScenarioResult:
        for s in self.scenarios:
            if s.sid == sid:
                return s
        raise KeyError(sid)


def _make_comm(param, family: str):
    """The CLI's mesh resolution (pampi_tpu.cli._make_comm): None for a
    single-device bucket, a CartComm otherwise."""
    from ..cli import _make_comm as cli_make_comm

    return cli_make_comm(param, 2 if family == "ns2d" else 3)


def _is_dist(param) -> bool:
    """The _make_comm decision WITHOUT constructing the mesh (no comm
    build, no config banner) — run() resolves the fleet mode per bucket
    before any template exists; the template build constructs the real
    CartComm exactly once. Shares cli.mesh_is_single so the mode
    decision can never diverge from the comm the build constructs."""
    from ..cli import mesh_is_single

    return not mesh_is_single(param)


def _build_solver(param, family: str, comm):
    if family == "ns2d":
        if comm is None:
            from ..models.ns2d import NS2DSolver

            return NS2DSolver(param)
        from ..models.ns2d_dist import NS2DDistSolver

        return NS2DDistSolver(param, comm)
    if comm is None:
        from ..models.ns3d import NS3DSolver

        return NS3DSolver(param)
    from ..models.ns3d_dist import NS3DDistSolver

    return NS3DDistSolver(param, comm)


def _template(sig: str, param, family: str):
    """Build (or fetch) the bucket's template solver — the one traced
    program every lane of the signature rides. Returns
    (solver, dist, build_wall_s) with build_wall_s None on a cache hit."""
    hit = _TEMPLATES.get(sig)
    if hit is not None:
        return hit[0], hit[1], None
    t0 = time.perf_counter()
    comm = _make_comm(param, family)
    solver = _build_solver(param, family, comm)
    wall = time.perf_counter() - t0
    _TEMPLATES[sig] = (solver, comm is not None)
    return solver, comm is not None, wall


def _clear_contamination(solver) -> bool:
    """Tenant ISOLATION: a previous run's divergence recovery
    (cumulative `_dt_scale` clamp) or pallas->jnp fallback (`_backend`)
    must not leak into the next tenant's program — reset the knobs and
    re-trace when either drifted, so the next lane runs the program a
    fresh solver would have built. Returns whether a re-trace happened.
    Class templates (fleet/shapeclass.ClassSolver/Class3DSolver) carry
    the same `_backend`/`_rebuild_chunk` surface since the fused class
    chunk landed (serving v3) and heal the same way."""
    if not hasattr(solver, "_rebuild_chunk"):
        return False
    if (getattr(solver, "_dt_scale", 1.0) != 1.0
            or getattr(solver, "_backend", "auto") != "auto"):
        solver._dt_scale = 1.0
        solver._backend = "auto"
        solver._rebuild_chunk()
        return True
    return False


def _reset_lane(solver, param) -> None:
    """Point the template solver's state at one scenario's initial
    conditions (constant fills — the lane_state contract) and ITS drive
    knobs for the sequential pjit path."""
    _clear_contamination(solver)
    # the request's own drive-time knobs (trace-shaping fields are
    # signature-equal across the bucket, so only these can differ)
    solver.param = solver.param.replace(
        **{k: getattr(param, k) for k in _q.DRIVE_KEYS})
    if float(solver.param.te) != float(param.te):
        # te left the bucket signature (per-lane since fleet v2) but the
        # SOLO chunk still bakes it: a pjit lane with a different end
        # time re-traces the template against its own te (compile cost
        # per distinct te in a pjit bucket — correctness over reuse; the
        # vmap/class paths carry te per lane instead)
        solver.param = solver.param.replace(te=param.te)
        import jax as _jax

        solver._chunk_fn = _jax.jit(
            solver._build_chunk(backend=solver._backend))
    state = lane_state(solver, param)
    fields, _tail = _split_state(solver, state)
    for name, value in zip(_field_names(len(fields)), fields):
        setattr(solver, name, value)
    solver.t = 0.0
    solver.nt = 0


def _split_by_te(key, reqs):
    """Per-te sub-buckets of one DIST bucket (insertion-ordered): the
    shard_map chunk bakes te, so a mixed-te dist bucket runs as one
    compiled batch per distinct te (single-device buckets carry te per
    lane instead — fleet/batch.BatchedSolver te_carry)."""
    groups: dict[float, list] = {}
    for req in reqs:
        groups.setdefault(float(req.param.te), []).append(req)
    return [
        # keyed by the te VALUE unconditionally — te is signature-
        # excluded, so the dist template cache must map (sig, te) ->
        # its baked-te solver: a later run's different-te bucket would
        # otherwise hit a stale-te template
        (dataclasses.replace(key, sig=f"{key.sig}-te{te!r}"), greqs)
        for te, greqs in groups.items()
    ]


def _solo_result(solver, sid, label, mode, family) -> ScenarioResult:
    n_fields = len(_split_state(solver, solver.initial_state())[0])
    fields = tuple(np.asarray(getattr(solver, n))
                   for n in _field_names(n_fields))
    diverged = not np.isfinite(solver.t) or not all(
        np.isfinite(f).all() for f in fields)
    return ScenarioResult(sid=sid, bucket=label, mode=mode, family=family,
                          t=float(solver.t), nt=int(solver.nt),
                          diverged=bool(diverged), fields=fields)


class FleetScheduler:
    """Batched multi-tenant serving: submit requests, run the fleet.

    One scheduler instance is one serving session: its template cache
    persists across `run()` calls (repeated same-bucket batches reuse
    compiled programs), and construction arms the persistent XLA disk
    cache so the same holds across processes."""

    def __init__(self, requests=None, classes: str = "off",
                 lanes: int = 0, isolate: bool = False):
        from ..utils import xlacache

        if classes not in ("on", "off", "auto"):
            raise ValueError(
                f"classes must be on|off|auto, got {classes!r}")
        if lanes < 0:
            raise ValueError(f"lanes must be >= 0, got {lanes}")
        # isolate=True (the daemon): a bucket whose build/execution
        # raises degrades to FAILED ScenarioResults + a warning record
        # and the run continues with the other buckets — one tenant's
        # unschedulable knob combo must not take down its poll-mates.
        # False (the default) keeps loud errors for programmatic use.
        self.isolate = isolate
        xlacache.enable()
        self.requests: list[_q.ScenarioRequest] = list(requests or [])
        # shape-class batching: "on"/"auto" coalesce eligible mixed-GRID
        # requests into padded class buckets (the serving daemon's
        # default); "off" keeps the PR 9 exact-shape bucketing — the
        # scheduler-construction default, so existing callers and the
        # drift oracles see unchanged routing
        self.classes = classes
        # continuous-batching pool size: a bucket larger than this runs
        # with lane swap-in instead of one all-lanes batch (0 = off)
        self.lanes = lanes
        # serving accounting (the daemon's status plane): per-class/
        # bucket compile counts and swap totals for THIS scheduler
        self.compile_census: dict[str, int] = {}
        self.swap_census: dict[str, int] = {}
        # QoS preemption hooks (fleet/autopilot.py wires them when the
        # autopilot runs with tenant priorities; None — the default, and
        # the policy-off daemon — keeps _serve_continuous byte-identical
        # to the hookless loop):
        #   priority_of(sid) -> int   lower = more important
        #   park_store                autopilot.ParkStore (parked-lane
        #                             manifests, keyed by bucket sig)
        #   feed(key) -> [requests]   chunk-boundary arrivals for the
        #                             bucket (the mid-run swap-in plane,
        #                             now reachable from run())
        self.priority_of = None
        self.park_store = None
        self.feed = None
        # isolate mode turns ANY bucket failure into failed results —
        # but a RankDeadError is capacity loss, not a tenant's bad
        # config: with a death consumer armed (the autopilot) it must
        # surface so the heal plane can shrink and requeue. False (the
        # default) keeps the historical funnel byte-identical.
        self.raise_rank_death = False

    def submit(self, request: _q.ScenarioRequest) -> None:
        self.requests.append(request)

    def submit_param(self, sid: str, param) -> None:
        self.submit(_q.ScenarioRequest(sid=sid, param=param))

    # -- execution ------------------------------------------------------
    def run(self, progress: bool = False) -> FleetResult:
        if not self.requests:
            raise ValueError("fleet queue is empty")
        batch, self.requests = self.requests, []  # run() drains the queue
        buckets = _q.bucket(batch, classes=self.classes in ("on", "auto"))
        scenarios: list[ScenarioResult] = []
        bucket_rows: list[dict] = []
        run_wall_total = 0.0
        for key, reqs in buckets.items():
            try:
                rows_results = self._serve_bucket(key, reqs, progress)
            except Exception as exc:  # lint: allow(broad-except) — per-bucket isolation (isolate mode): any mode-resolution/build/execution failure degrades to failed results, re-raised verbatim otherwise
                if not self.isolate:
                    raise
                if self.raise_rank_death:
                    from ..parallel.coordinator import RankDeadError

                    if isinstance(exc, RankDeadError):
                        raise
                _tm.emit("warning", component="fleet.scheduler",
                         reason="bucket_failed", bucket=key.label,
                         error=str(exc),
                         scenarios=[r.sid for r in reqs])
                row = {"bucket": key.label, "family": key.family,
                       "grid": list(key.grid), "mode": "failed",
                       "lanes": len(reqs), "template_cached": False,
                       "compile_wall_s": 0.0, "run_wall_s": 0.0,
                       "failed": True, "error": str(exc)}
                rows_results = [(row, [
                    ScenarioResult(
                        sid=r.sid, bucket=key.label, mode="failed",
                        family=key.family, t=0.0, nt=0,
                        diverged=False, fields=(), failed=True,
                        error=str(exc))
                    for r in reqs])]
            for row, results in rows_results:
                bucket_rows.append(row)
                run_wall_total += row["run_wall_s"]
                scenarios += results
        diverged = [s.sid for s in scenarios if s.diverged]
        failed = [s.sid for s in scenarios if s.failed]
        per_s = (round((len(scenarios) - len(failed)) / run_wall_total,
                       4)
                 if run_wall_total > 0 else None)
        summary = {
            "n_scenarios": len(scenarios),
            "buckets": bucket_rows,
            "scenarios_per_s": per_s,
            "divergence_census": {
                "diverged": len(diverged),
                "scenarios": diverged,
            },
        }
        if failed:
            # isolate mode only: buckets that could not be scheduled
            # (pure addition — legacy summaries never carry the key)
            summary["failures"] = {"failed": len(failed),
                                   "scenarios": failed}
        _tm.emit("fleet", **summary)
        _tm.emit("metric", metric="fleet_scenarios_per_s", value=per_s,
                 unit="scenarios/s", backend=jax.default_backend())
        return FleetResult(scenarios=scenarios, summary=summary)

    def _serve_bucket(self, key, reqs, progress: bool) -> list:
        """Mode resolution + execution of ONE bucket (te sub-groups
        included). Returns [(bucket row, results), ...] — the unit
        run()'s per-bucket isolation wraps."""
        from ..utils import dispatch as _dispatch

        if key.sig.startswith("cls"):
            # the class chunk is its own (vmap-shaped) program; the
            # decision is recorded per bucket like every mode
            mode = "class"
            _dispatch.record(
                f"fleet_{key.label}",
                f"class (padded {'x'.join(map(str, key.grid))}, "
                f"{len(reqs)} lanes)")
            groups = [(key, reqs)]
        else:
            # mode needs the mesh answer before any build: decide it
            # without constructing (the template build makes the real
            # comm). Dist buckets SPLIT per te: te left the bucket
            # signature (per-lane since fleet v2) but the shard_map
            # chunk still bakes it. The lane count the mode is resolved
            # on is the EFFECTIVE batch size — the continuous pool when
            # one is armed — so a mesh divisibility decision matches
            # the batch that will actually be built.
            rep = reqs[0].param
            dist = _is_dist(rep)
            n_eff = (min(self.lanes, len(reqs)) if self.lanes > 0
                     else len(reqs))
            mode = _dispatch.resolve_fleet(
                rep, n_eff, dist, f"fleet_{key.label}")
            groups = ([(key, reqs)] if not dist
                      else _split_by_te(key, reqs))
        out = []
        for gkey, greqs in groups:
            with _tm.span(f"fleet.bucket.{gkey.label}", mode=mode,
                          lanes=len(greqs)):
                out.append(self._run_bucket(gkey, greqs, mode,
                                            progress))
        return out

    def _run_bucket(self, key, reqs, mode: str, progress: bool):
        family = key.family
        label = key.label
        cached = False
        # trace boundary: bucket execution starts here — queue_wait ends
        # for every lane in the bucket (swapped-in continuous lanes are
        # re-stamped at their swap, latest-wins)
        for req in reqs:
            _tr.mark(req.trace, "exec_start")
            _tr.note(req.trace, mode=mode)
        if mode == "solo":
            build_wall = 0.0
            t0 = time.perf_counter()
            results = []
            for req in reqs:
                b0 = time.perf_counter()
                _tr.mark(req.trace, "exec_start")  # per-req solo build
                solver = _build_solver(
                    req.param, family, _make_comm(req.param, family))
                build_wall += time.perf_counter() - b0
                _tr.mark(req.trace, "run_start")
                with _tm.scenario_scope(req.sid):
                    solver.run(progress=progress)
                _tr.mark(req.trace, "done")
                results.append(_solo_result(
                    solver, req.sid, label, mode, family))
            run_wall = time.perf_counter() - t0 - build_wall
        elif mode == "pjit":
            template, cached, build_wall = self._warm_template(key, reqs)
            for req in reqs:
                _tr.mark(req.trace, "run_start")
            t0 = time.perf_counter()
            results = []
            for req in reqs:
                _reset_lane(template, req.param)
                with _tm.scenario_scope(req.sid):
                    template.run(progress=progress)
                _tr.mark(req.trace, "done")
                results.append(_solo_result(
                    template, req.sid, label, mode, family))
            run_wall = time.perf_counter() - t0
        else:  # vmap | mesh | class — the batched paths
            template, cached_tpl, wall = self._bucket_template(
                key, reqs, mode)
            build_wall = 0.0 if wall is None else wall
            # heal BEFORE building: a template left dirty by an earlier
            # bucket (recovery dt clamp, pallas fallback) would be baked
            # into the batched trace and serve every lane a wrong program
            if _clear_contamination(template):
                _drop_batches(key.sig)  # cached batches wrapped the old trace
            pool = (min(self.lanes, len(reqs)) if self.lanes > 0
                    else len(reqs))
            continuous = pool < len(reqs)
            batched, bcached, bwall = self._batch_for(
                key, reqs[:pool], mode, template, family,
                continuous=continuous)
            build_wall += bwall
            cached = bcached
            # the pool's compile phase ends here; lanes beyond the pool
            # are re-stamped when they swap in (_serve_continuous)
            for req in reqs[:pool]:
                _tr.mark(req.trace, "run_start")
            t0 = time.perf_counter()
            if continuous:
                from ..utils import dispatch as _dispatch

                _dispatch.record(
                    f"fleet_cont_{label}",
                    f"continuous ({pool}-lane pool, {len(reqs)} "
                    "scenarios, swap-in on finish/divergence)")
                rows, swaps = self._serve_continuous(
                    batched, reqs[pool:], bucket=key,
                    feed=((lambda: self.feed(key))
                          if self.feed is not None else None))
                self.swap_census[label] = \
                    self.swap_census.get(label, 0) + swaps
            else:
                final = batched.run(progress=progress)
                rows, swaps = batched.results(final), 0
            run_wall = time.perf_counter() - t0
            # ...and heal AFTER: a pallas fallback during THIS batch
            # writes through to the cached template's _backend — later
            # buckets must not silently inherit the jnp path (and the
            # cached batch itself wraps the now-stale program)
            if _clear_contamination(template):
                _drop_batches(key.sig)
            # the harvest clock is each lane's `done` trace boundary
            traces = {r.sid: r.trace for r in reqs}
            for r in rows:
                _tr.mark(traces.get(r["sid"]), "done",
                         ts=r.get("served_ts"))
            results = [
                ScenarioResult(sid=r["sid"], bucket=label, mode=mode,
                               family=family, t=r["t"], nt=r["nt"],
                               diverged=r["diverged"], fields=r["fields"])
                for r in rows
            ]
        row = {
            "bucket": label,
            "family": family,
            "grid": list(key.grid),
            "mode": mode,
            "lanes": len(reqs),
            "template_cached": cached,
            "compile_wall_s": round(build_wall, 3),
            "run_wall_s": round(run_wall, 4),
        }
        if mode in ("vmap", "mesh", "class") and self.lanes > 0:
            row["swaps"] = swaps
        return row, results

    def _bucket_template(self, key, reqs, mode):
        """(template, cache_hit, build_wall) for a batched bucket —
        the solver template for vmap/mesh, the ClassSolver for class
        buckets (both live in the same signature-keyed cache)."""
        if mode != "class":
            solver, _dist_flag, wall = _template(
                key.sig, reqs[0].param, key.family)
            return solver, wall is None, wall
        hit = _TEMPLATES.get(key.sig)
        if hit is not None:
            return hit[0], True, None
        from .shapeclass import Class3DSolver, ClassSolver

        t0 = time.perf_counter()
        grid = key.grid
        if key.family == "ns3d":
            # 3-D class rungs (serving v3): grid is (imax, jmax, kmax)
            template = Class3DSolver(reqs[0].param, ic=grid[0],
                                     jc=grid[1], kc=grid[2])
        else:
            template = ClassSolver(reqs[0].param, ic=grid[0], jc=grid[1])
        _TEMPLATES[key.sig] = (template, False)
        return template, False, time.perf_counter() - t0

    def _batch_for(self, key, reqs, mode, template, family,
                   continuous: bool = False):
        """(BatchedSolver, cache_hit, compile_wall): fetch-or-build the
        compiled batch for this (signature, lane count, mode) — the
        zero-retrace warm path. Continuous pools always carry te (the
        swap-in queue's end times are unknown at compile time)."""
        if hasattr(template, "_chunk_sm"):
            # dist FIRST: te is baked in the shard_map chunk and the
            # bucket is pre-split per te, so even a continuous pool
            # runs without the carry (swap-ins share the group's te)
            te_carry = False
        elif continuous or mode == "class":
            # the swap-in queue's end times are unknown at compile time
            te_carry = True
        else:
            tes = {float(r.param.te) for r in reqs}
            te_carry = (len(tes) > 1
                        or tes != {float(template.param.te)})
        mesh = list(jax.devices()) if mode == "mesh" else None
        bkey = (key.sig, len(reqs), mode, te_carry)
        batched = _BATCHES.get(bkey)
        if batched is not None:
            batched.rebind([r.param for r in reqs],
                           [r.sid for r in reqs])
            return batched, True, 0.0
        c0 = time.perf_counter()
        batched = BatchedSolver(
            template, [r.param for r in reqs], [r.sid for r in reqs],
            family=family, te_carry=te_carry, mesh=mesh)
        # jax.jit is lazy — and on this jax the AOT lower().compile()
        # path does NOT populate the jit dispatch cache — so warm by
        # CALLING the batched chunk once and discarding the result (the
        # loop is functional; one throwaway chunk of device work is
        # noise next to the compile it keeps out of the serving rate
        # bench_trend gates). Scalar-readback fence, the repo timing
        # convention.
        out = batched._chunk_fn(*batched.initial_state())
        float(out[batched._active_index + 1])
        _BATCHES[bkey] = batched
        label = key.label
        self.compile_census[label] = self.compile_census.get(label, 0) + 1
        return batched, False, time.perf_counter() - c0

    def _serve_continuous(self, batched, pending, feed=None,
                          bucket=None):
        """CONTINUOUS BATCHING: drive the compiled pool chunk-by-chunk,
        harvesting each lane the moment it finishes (its own te) or
        diverges (retired by the in-band sentinel / finiteness mask) and
        swapping a queued scenario into the freed slot — zero retrace,
        the batch never drains to serve an arrival. `feed()`, when
        given, is polled at every chunk boundary for newly-arrived
        same-bucket requests (the daemon's mid-run swap-in plane).
        Returns (results in completion order, swap count).

        QoS preemption (fleet/autopilot.py, armed only when both
        `self.park_store` and `self.priority_of` are set — the default
        None/None keeps this loop byte-identical to the hookless build):
        when a strictly higher-priority request is waiting and no slot
        is free, the WORST-priority active lane is parked — its full
        per-lane carry persisted through a parked-lane manifest
        (utils/checkpoint.save_parked_lane) — and the slot handed over;
        parked lanes resume bitwise into freed slots once the pending
        queue drains (new arrivals first: parked tenants are by
        construction the lowest priority in the bucket).

        Fault handling: transient UNAVAILABLE device faults get the
        same-chunk retry the drive_chunks protocol gives every other
        mode (inputs unchanged — the loop is functional; budget 1,
        refilled after 8 clean chunks). The pallas->jnp fallback is NOT
        armed here — the continuous paths are jnp/class programs today;
        a genuine kernel fault propagates loudly."""
        import numpy as np

        from ..models._driver import _is_transient_device_fault

        from .batch import FleetRecorder

        pending = list(pending)
        rec = (FleetRecorder(batched.family, batched.sids)
               if batched._metrics else None)
        state = batched.initial_state()
        harvested = [False] * batched.n
        out = []
        swaps = 0
        transient_budget = 1
        clean = 0
        preempt_on = (self.park_store is not None
                      and self.priority_of is not None
                      and bucket is not None)
        while True:
            # fill freed slots first: a lane harvested last boundary (or
            # freed while the queue was empty) takes the next arrival
            for lane in range(batched.n):
                if harvested[lane] and pending:
                    req = pending.pop(0)
                    # the swapped-in lane's queue_wait ends NOW (the
                    # pool is already compiled, so compile is ~0)
                    _tr.mark(req.trace, "exec_start")
                    _tr.mark(req.trace, "run_start")
                    state = batched.swap_lane(
                        state, lane, req.param, req.sid)
                    if rec is not None:
                        rec.rearm(lane, req.sid)
                    harvested[lane] = False
                    swaps += 1
            if preempt_on and not pending:
                # queue drained: resume parked victims into free slots
                for lane in range(batched.n):
                    if not harvested[lane]:
                        continue
                    entry = self.park_store.pop(bucket.sig)
                    if entry is None:
                        break
                    _tm.emit("autoscale", decision="resume",
                             sid=entry.sid, lane=lane,
                             bucket=bucket.label, manifest=entry.path)
                    state = batched.resume_lane(
                        state, lane, entry.load(), entry.param,
                        entry.sid)
                    if rec is not None:
                        rec.rearm(lane, entry.sid)
                    harvested[lane] = False
                    swaps += 1
            if preempt_on and pending and not any(harvested):
                # no free slot + someone waiting: does the best pending
                # request strictly outrank the worst active lane?
                best = min(range(len(pending)),
                           key=lambda i: self.priority_of(
                               pending[i].sid))
                active = [ln for ln in range(batched.n)
                          if not harvested[ln]]
                worst = max(active,
                            key=lambda ln: self.priority_of(
                                batched.sids[ln]))
                if (self.priority_of(pending[best].sid)
                        < self.priority_of(batched.sids[worst])):
                    payload = batched.park_lane(state, worst)
                    mpath = self.park_store.park(
                        bucket.sig, payload["sid"], payload["param"],
                        payload["leaves"])
                    _tm.emit("autoscale", decision="preempt",
                             victim=payload["sid"], lane=worst,
                             by=pending[best].sid, bucket=bucket.label,
                             manifest=mpath)
                    req = pending.pop(best)
                    _tr.mark(req.trace, "exec_start")
                    _tr.mark(req.trace, "run_start")
                    state = batched.swap_lane(
                        state, worst, req.param, req.sid)
                    if rec is not None:
                        rec.rearm(worst, req.sid)
                    swaps += 1
            if all(harvested) and not pending:
                extra = feed() if feed is not None else []
                if not extra:
                    break
                pending.extend(extra)
                continue
            try:
                state = batched._chunk_fn(*state)
                clean += 1
                if clean >= 8:
                    transient_budget = 1
            except Exception as exc:  # lint: allow(broad-except) — the transient-retry funnel, same classification as drive_chunks
                if not _is_transient_device_fault(exc) \
                        or transient_budget <= 0:
                    raise
                transient_budget -= 1
                clean = 0
                _tm.emit("retry", fault="transient",
                         budget_left=transient_budget,
                         where="fleet.continuous")
                continue  # state unchanged — re-dispatch the chunk
            if rec is not None:
                rec.update(batched, state)
            if feed is not None:
                pending.extend(feed())
            done = batched.lane_done(state)
            for lane in np.nonzero(done)[0]:
                lane = int(lane)
                if harvested[lane]:
                    continue
                res = batched.harvest(state, lane)  # stamps served_ts
                out.append(res)
                harvested[lane] = True
        return out, swaps

    def elastic_restore(self, path: str, param, family: str = "ns2d",
                        devices=None):
        """The autoscaling primitive (ROADMAP item 4): resume an ELASTIC
        checkpoint (utils/checkpoint.save_elastic) on however many chips
        this scheduler currently has — a dist run saved on 8 chips
        shrinks onto 4 (or 1) because the manifest holds the
        mesh-independent global fields and `set_global_fields` reshards
        them onto whatever NamedSharding the freshly-built solver uses.
        `devices` limits the target (None = every local device); a
        single device builds the plain solver. Returns the restored
        solver, ready to drive (`solver.run()`); the caller typically
        lowers `te`-remaining work back into the queue as a pjit bucket.
        """
        import jax

        from ..utils import checkpoint as _ckpt
        from ..utils import dispatch as _dispatch

        devs = list(devices if devices is not None else jax.devices())
        ndims = 2 if family == "ns2d" else 3
        comm = None
        if len(devs) > 1:
            from ..parallel.comm import CartComm

            extents = ((param.jmax, param.imax) if ndims == 2
                       else (param.kmax, param.jmax, param.imax))
            comm = CartComm(ndims=ndims, devices=devs, extents=extents,
                            tiers=param.tpu_mesh_tiers)
        solver = _build_solver(param, family, comm)
        with _tm.span(f"fleet.elastic_restore.{family}",
                      devices=len(devs)):
            # load_elastic also restores the fault LEDGER when the
            # manifest carries one (utils/checkpoint._restore_ledger):
            # the restored solver keeps a pre-death pallas-broken
            # verdict, dt clamp and spent budget — the policy hook the
            # dead-rank shrink path (shrink_resume) rides
            _ckpt.load_elastic(path, solver)
        _dispatch.record(
            f"elastic_restore_{family}",
            f"{len(devs)} device(s), mesh "
            f"{list(comm.dims) if comm is not None else [1]}")
        return solver

    def _warm_template(self, key, reqs):
        """Fetch/build the bucket template AND, on a COLD build, force
        its chunk compile (jax.jit is lazy — without this the cold XLA
        compile lands in the first tenant's run wall; a cached template
        already compiled during its earlier batch). Warming is one
        discarded CALL of the chunk — on this jax the AOT
        lower().compile() path does not populate the jit dispatch cache,
        so an executed chunk is the only warm-up that sticks. Returns
        (template, cache_hit, compile_wall_s)."""
        template, _dist, wall = _template(key.sig, reqs[0].param,
                                          key.family)
        if wall is None:
            return template, True, 0.0
        c0 = time.perf_counter()
        chunk = getattr(template, "_chunk_sm", None) or template._chunk_fn
        state = template.initial_state()
        out = chunk(*state)
        # scalar-readback fence on the carried loop time (the repo
        # timing convention; t sits 2-or-3 slots from the end)
        float(out[len(state) - (3 if template._metrics else 2)])
        return template, False, wall + time.perf_counter() - c0


def shrink_resume(path, param, family: str = "ns2d", devices=None,
                  dead=None, epoch=None, scheduler=None):
    """Dead-rank SHRINK-TO-SURVIVORS resume (ROADMAP item 4 follow-on,
    PR 12): the structured recovery for a `RankDeadError` — rebuild the
    runtime on however much capacity survived (`devices`; None = every
    device this process can still see), restore the newest agreed
    elastic checkpoint generation via `elastic_restore` (NamedSharding
    reshard onto the shrunk mesh + rank-symmetric fault-ledger restore),
    and hand back a solver ready to `run()` the remaining te at degraded
    capacity. The restored trajectory is bitwise-identical to a clean
    run launched on the shrunk mesh from the same generation — the
    elastic-reshard contract, now the survival contract.

    `dead`/`epoch` (from the RankDeadError) ride into the telemetry
    `shrink` record so the flight recorder names what was lost; the
    scheduler argument reuses a serving session's template/xla caches
    (None builds a throwaway one)."""
    import jax

    sched = scheduler if scheduler is not None else FleetScheduler()
    devs = list(devices if devices is not None else jax.devices())
    solver = sched.elastic_restore(path, param, family=family,
                                   devices=devs)
    _tm.emit("shrink", family=family, path=path, survivors=len(devs),
             generation=getattr(solver, "_elastic_generation", None),
             dead=(sorted(int(r) for r in dead) if dead else None),
             epoch=epoch, t=float(solver.t), nt=int(solver.nt))
    return solver


def run_fleet(requests, progress: bool = False) -> FleetResult:
    """One-shot convenience: schedule + run a request list."""
    return FleetScheduler(requests).run(progress=progress)
