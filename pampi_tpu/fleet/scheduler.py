"""Fleet scheduler: queue -> buckets -> batched/sequential execution ->
per-scenario results + a fleet summary artifact.

The serving front of the scenario fleet (ROADMAP item 3): accept a queue
of `.par`-equivalent requests, group them into shared-trace buckets
(fleet/queue.py), pick the execution mode per bucket via the `tpu_fleet`
knob (utils/dispatch.resolve_fleet — every decision recorded like
`tpu_overlap`), and reuse compiled programs aggressively:

- in-process: ONE template solver per knob signature (`_TEMPLATES`) —
  the second batch of a bucket, and every later same-signature request,
  pays zero retrace;
- cross-process: `utils/xlacache.enable()` is armed by the scheduler
  (not just the CLI path), so a warm disk cache turns the per-bucket
  compile into a load on every serving process.

Execution modes (see resolve_fleet for the auto policy):
  vmap   fleet/batch.BatchedSolver — one vmapped chunk advances every
         lane; diverged lanes freeze, batchmates continue
  pjit   whole-mesh per scenario, sequential, template reused (the
         dist-bucket mode: the existing solver IS the pjit-across-mesh
         program; lanes run through solver.run() under scenario_scope)
  solo   the historical path — a fresh solver per request (the
         fleet-smoke drift oracle)

Every run emits the fleet summary through the telemetry plane: one
`fleet` record {n_scenarios, buckets: [per-bucket mode/compile-vs-run
walls], scenarios_per_s, divergence_census}, per-bucket spans, and a
`fleet_scenarios_per_s` metric record — `tools/telemetry_report.py
--merge` folds the summary into BENCH/MULTICHIP artifacts as
`fleet_summary`, `tools/check_artifact.py` lints it, and
`tools/bench_trend.py` gates the throughput higher-is-better.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from ..utils import telemetry as _tm
from . import queue as _q
from .batch import BatchedSolver, lane_state, _field_names, _split_state

# in-process executable caches above the on-disk xlacache:
# _TEMPLATES: knob signature -> (template solver, dist) — the one traced
# solo program per bucket; _BATCHES: (signature, lane count) -> the
# compiled BatchedSolver, so a warm same-shape batch REBINDS to new
# requests and pays zero retrace (a fresh jax.jit per batch would
# recompile the vmapped chunk every run — the serving rate would be
# compile-bound, BENCH_r07's round-14 finding)
_TEMPLATES: dict[str, tuple] = {}
_BATCHES: dict[tuple, object] = {}


def reset_templates() -> None:
    """Drop the in-process executable caches (tests)."""
    _TEMPLATES.clear()
    _BATCHES.clear()


def _drop_batches(sig: str) -> None:
    """Invalidate cached batches of one signature (their inner chunk
    wraps a template program that just changed — e.g. a contamination
    heal re-traced it)."""
    for key in [k for k in _BATCHES if k[0] == sig]:
        del _BATCHES[key]


@dataclasses.dataclass
class ScenarioResult:
    sid: str
    bucket: str
    mode: str
    family: str
    t: float
    nt: int
    diverged: bool
    fields: tuple


@dataclasses.dataclass
class FleetResult:
    scenarios: list
    summary: dict

    def by_sid(self, sid: str) -> ScenarioResult:
        for s in self.scenarios:
            if s.sid == sid:
                return s
        raise KeyError(sid)


def _make_comm(param, family: str):
    """The CLI's mesh resolution (pampi_tpu.cli._make_comm): None for a
    single-device bucket, a CartComm otherwise."""
    from ..cli import _make_comm as cli_make_comm

    return cli_make_comm(param, 2 if family == "ns2d" else 3)


def _is_dist(param) -> bool:
    """The _make_comm decision WITHOUT constructing the mesh (no comm
    build, no config banner) — run() resolves the fleet mode per bucket
    before any template exists; the template build constructs the real
    CartComm exactly once. Shares cli.mesh_is_single so the mode
    decision can never diverge from the comm the build constructs."""
    from ..cli import mesh_is_single

    return not mesh_is_single(param)


def _build_solver(param, family: str, comm):
    if family == "ns2d":
        if comm is None:
            from ..models.ns2d import NS2DSolver

            return NS2DSolver(param)
        from ..models.ns2d_dist import NS2DDistSolver

        return NS2DDistSolver(param, comm)
    if comm is None:
        from ..models.ns3d import NS3DSolver

        return NS3DSolver(param)
    from ..models.ns3d_dist import NS3DDistSolver

    return NS3DDistSolver(param, comm)


def _template(sig: str, param, family: str):
    """Build (or fetch) the bucket's template solver — the one traced
    program every lane of the signature rides. Returns
    (solver, dist, build_wall_s) with build_wall_s None on a cache hit."""
    hit = _TEMPLATES.get(sig)
    if hit is not None:
        return hit[0], hit[1], None
    t0 = time.perf_counter()
    comm = _make_comm(param, family)
    solver = _build_solver(param, family, comm)
    wall = time.perf_counter() - t0
    _TEMPLATES[sig] = (solver, comm is not None)
    return solver, comm is not None, wall


def _clear_contamination(solver) -> bool:
    """Tenant ISOLATION: a previous run's divergence recovery
    (cumulative `_dt_scale` clamp) or pallas->jnp fallback (`_backend`)
    must not leak into the next tenant's program — reset the knobs and
    re-trace when either drifted, so the next lane runs the program a
    fresh solver would have built. Returns whether a re-trace happened."""
    if (getattr(solver, "_dt_scale", 1.0) != 1.0
            or getattr(solver, "_backend", "auto") != "auto"):
        solver._dt_scale = 1.0
        solver._backend = "auto"
        solver._rebuild_chunk()
        return True
    return False


def _reset_lane(solver, param) -> None:
    """Point the template solver's state at one scenario's initial
    conditions (constant fills — the lane_state contract) and ITS drive
    knobs for the sequential pjit path."""
    _clear_contamination(solver)
    # the request's own drive-time knobs (trace-shaping fields are
    # signature-equal across the bucket, so only these can differ)
    solver.param = solver.param.replace(
        **{k: getattr(param, k) for k in _q.DRIVE_KEYS})
    state = lane_state(solver, param)
    fields, _tail = _split_state(solver, state)
    for name, value in zip(_field_names(len(fields)), fields):
        setattr(solver, name, value)
    solver.t = 0.0
    solver.nt = 0


def _solo_result(solver, sid, label, mode, family) -> ScenarioResult:
    n_fields = len(_split_state(solver, solver.initial_state())[0])
    fields = tuple(np.asarray(getattr(solver, n))
                   for n in _field_names(n_fields))
    diverged = not np.isfinite(solver.t) or not all(
        np.isfinite(f).all() for f in fields)
    return ScenarioResult(sid=sid, bucket=label, mode=mode, family=family,
                          t=float(solver.t), nt=int(solver.nt),
                          diverged=bool(diverged), fields=fields)


class FleetScheduler:
    """Batched multi-tenant serving: submit requests, run the fleet.

    One scheduler instance is one serving session: its template cache
    persists across `run()` calls (repeated same-bucket batches reuse
    compiled programs), and construction arms the persistent XLA disk
    cache so the same holds across processes."""

    def __init__(self, requests=None):
        from ..utils import xlacache

        xlacache.enable()
        self.requests: list[_q.ScenarioRequest] = list(requests or [])

    def submit(self, request: _q.ScenarioRequest) -> None:
        self.requests.append(request)

    def submit_param(self, sid: str, param) -> None:
        self.submit(_q.ScenarioRequest(sid=sid, param=param))

    # -- execution ------------------------------------------------------
    def run(self, progress: bool = False) -> FleetResult:
        from ..utils import dispatch as _dispatch

        if not self.requests:
            raise ValueError("fleet queue is empty")
        batch, self.requests = self.requests, []  # run() drains the queue
        buckets = _q.bucket(batch)
        scenarios: list[ScenarioResult] = []
        bucket_rows: list[dict] = []
        run_wall_total = 0.0
        for key, reqs in buckets.items():
            rep = reqs[0].param
            # mode needs the mesh answer before any build: decide it
            # without constructing (the template build makes the real comm)
            dist = _is_dist(rep)
            mode = _dispatch.resolve_fleet(
                rep, len(reqs), dist, f"fleet_{key.label}")
            with _tm.span(f"fleet.bucket.{key.label}", mode=mode,
                          lanes=len(reqs)):
                row, results = self._run_bucket(
                    key, reqs, mode, progress)
            bucket_rows.append(row)
            run_wall_total += row["run_wall_s"]
            scenarios += results
        diverged = [s.sid for s in scenarios if s.diverged]
        per_s = (round(len(scenarios) / run_wall_total, 4)
                 if run_wall_total > 0 else None)
        summary = {
            "n_scenarios": len(scenarios),
            "buckets": bucket_rows,
            "scenarios_per_s": per_s,
            "divergence_census": {
                "diverged": len(diverged),
                "scenarios": diverged,
            },
        }
        _tm.emit("fleet", **summary)
        _tm.emit("metric", metric="fleet_scenarios_per_s", value=per_s,
                 unit="scenarios/s", backend=jax.default_backend())
        return FleetResult(scenarios=scenarios, summary=summary)

    def _run_bucket(self, key, reqs, mode: str, progress: bool):
        family = key.family
        label = key.label
        cached = False
        if mode == "solo":
            build_wall = 0.0
            t0 = time.perf_counter()
            results = []
            for req in reqs:
                b0 = time.perf_counter()
                solver = _build_solver(
                    req.param, family, _make_comm(req.param, family))
                build_wall += time.perf_counter() - b0
                with _tm.scenario_scope(req.sid):
                    solver.run(progress=progress)
                results.append(_solo_result(
                    solver, req.sid, label, mode, family))
            run_wall = time.perf_counter() - t0 - build_wall
        elif mode == "pjit":
            template, cached, build_wall = self._warm_template(key, reqs)
            t0 = time.perf_counter()
            results = []
            for req in reqs:
                _reset_lane(template, req.param)
                with _tm.scenario_scope(req.sid):
                    template.run(progress=progress)
                results.append(_solo_result(
                    template, req.sid, label, mode, family))
            run_wall = time.perf_counter() - t0
        else:  # vmap
            # the bare template only: the vmap path never executes the
            # solo chunk, so warming it would be a wasted compile
            template, _dist, wall = _template(key.sig, reqs[0].param,
                                              family)
            build_wall = 0.0 if wall is None else wall
            # heal BEFORE building: a template left dirty by an earlier
            # bucket (recovery dt clamp, pallas fallback) would be baked
            # into the batched trace and serve every lane a wrong program
            if _clear_contamination(template):
                _drop_batches(key.sig)  # cached batches wrapped the old trace
            bkey = (key.sig, len(reqs))
            batched = _BATCHES.get(bkey)
            cached = batched is not None
            if cached:
                # warm path: same compiled vmapped program, new requests
                batched.rebind([r.param for r in reqs],
                               [r.sid for r in reqs])
            else:
                c0 = time.perf_counter()
                batched = BatchedSolver(
                    template, [r.param for r in reqs],
                    [r.sid for r in reqs], family=family)
                # jax.jit is lazy — and on this jax the AOT
                # lower().compile() path does NOT populate the jit
                # dispatch cache — so warm by CALLING the batched chunk
                # once and discarding the result (the loop is
                # functional; one throwaway chunk of device work is
                # noise next to the compile it keeps out of the serving
                # rate bench_trend gates). Scalar-readback fence, the
                # repo timing convention.
                out = batched._chunk_fn(*batched.initial_state())
                float(out[batched._lane_arity + 1])
                build_wall += time.perf_counter() - c0
                _BATCHES[bkey] = batched
            t0 = time.perf_counter()
            final = batched.run(progress=progress)
            run_wall = time.perf_counter() - t0
            # ...and heal AFTER: a pallas fallback during THIS batch
            # writes through to the cached template's _backend — later
            # buckets must not silently inherit the jnp path (and the
            # cached batch itself wraps the now-stale program)
            if _clear_contamination(template):
                _drop_batches(key.sig)
            results = [
                ScenarioResult(sid=r["sid"], bucket=label, mode=mode,
                               family=family, t=r["t"], nt=r["nt"],
                               diverged=r["diverged"], fields=r["fields"])
                for r in batched.results(final)
            ]
        row = {
            "bucket": label,
            "family": family,
            "grid": list(key.grid),
            "mode": mode,
            "lanes": len(reqs),
            "template_cached": cached,
            "compile_wall_s": round(build_wall, 3),
            "run_wall_s": round(run_wall, 4),
        }
        return row, results

    def elastic_restore(self, path: str, param, family: str = "ns2d",
                        devices=None):
        """The autoscaling primitive (ROADMAP item 4): resume an ELASTIC
        checkpoint (utils/checkpoint.save_elastic) on however many chips
        this scheduler currently has — a dist run saved on 8 chips
        shrinks onto 4 (or 1) because the manifest holds the
        mesh-independent global fields and `set_global_fields` reshards
        them onto whatever NamedSharding the freshly-built solver uses.
        `devices` limits the target (None = every local device); a
        single device builds the plain solver. Returns the restored
        solver, ready to drive (`solver.run()`); the caller typically
        lowers `te`-remaining work back into the queue as a pjit bucket.
        """
        import jax

        from ..utils import checkpoint as _ckpt
        from ..utils import dispatch as _dispatch

        devs = list(devices if devices is not None else jax.devices())
        ndims = 2 if family == "ns2d" else 3
        comm = None
        if len(devs) > 1:
            from ..parallel.comm import CartComm

            extents = ((param.jmax, param.imax) if ndims == 2
                       else (param.kmax, param.jmax, param.imax))
            comm = CartComm(ndims=ndims, devices=devs, extents=extents,
                            tiers=param.tpu_mesh_tiers)
        solver = _build_solver(param, family, comm)
        with _tm.span(f"fleet.elastic_restore.{family}",
                      devices=len(devs)):
            # load_elastic also restores the fault LEDGER when the
            # manifest carries one (utils/checkpoint._restore_ledger):
            # the restored solver keeps a pre-death pallas-broken
            # verdict, dt clamp and spent budget — the policy hook the
            # dead-rank shrink path (shrink_resume) rides
            _ckpt.load_elastic(path, solver)
        _dispatch.record(
            f"elastic_restore_{family}",
            f"{len(devs)} device(s), mesh "
            f"{list(comm.dims) if comm is not None else [1]}")
        return solver

    def _warm_template(self, key, reqs):
        """Fetch/build the bucket template AND, on a COLD build, force
        its chunk compile (jax.jit is lazy — without this the cold XLA
        compile lands in the first tenant's run wall; a cached template
        already compiled during its earlier batch). Warming is one
        discarded CALL of the chunk — on this jax the AOT
        lower().compile() path does not populate the jit dispatch cache,
        so an executed chunk is the only warm-up that sticks. Returns
        (template, cache_hit, compile_wall_s)."""
        template, _dist, wall = _template(key.sig, reqs[0].param,
                                          key.family)
        if wall is None:
            return template, True, 0.0
        c0 = time.perf_counter()
        chunk = getattr(template, "_chunk_sm", None) or template._chunk_fn
        state = template.initial_state()
        out = chunk(*state)
        # scalar-readback fence on the carried loop time (the repo
        # timing convention; t sits 2-or-3 slots from the end)
        float(out[len(state) - (3 if template._metrics else 2)])
        return template, False, wall + time.perf_counter() - c0


def shrink_resume(path, param, family: str = "ns2d", devices=None,
                  dead=None, epoch=None, scheduler=None):
    """Dead-rank SHRINK-TO-SURVIVORS resume (ROADMAP item 4 follow-on,
    PR 12): the structured recovery for a `RankDeadError` — rebuild the
    runtime on however much capacity survived (`devices`; None = every
    device this process can still see), restore the newest agreed
    elastic checkpoint generation via `elastic_restore` (NamedSharding
    reshard onto the shrunk mesh + rank-symmetric fault-ledger restore),
    and hand back a solver ready to `run()` the remaining te at degraded
    capacity. The restored trajectory is bitwise-identical to a clean
    run launched on the shrunk mesh from the same generation — the
    elastic-reshard contract, now the survival contract.

    `dead`/`epoch` (from the RankDeadError) ride into the telemetry
    `shrink` record so the flight recorder names what was lost; the
    scheduler argument reuses a serving session's template/xla caches
    (None builds a throwaway one)."""
    import jax

    sched = scheduler if scheduler is not None else FleetScheduler()
    devs = list(devices if devices is not None else jax.devices())
    solver = sched.elastic_restore(path, param, family=family,
                                   devices=devs)
    _tm.emit("shrink", family=family, path=path, survivors=len(devs),
             generation=getattr(solver, "_elastic_generation", None),
             dead=(sorted(int(r) for r in dead) if dead else None),
             epoch=epoch, t=float(solver.t), nt=int(solver.nt))
    return solver


def run_fleet(requests, progress: bool = False) -> FleetResult:
    """One-shot convenience: schedule + run a request list."""
    return FleetScheduler(requests).run(progress=progress)
