"""Tenant SLO accounting for the serving daemon: per-tenant latency
targets, a sliding-window error budget, and burn-rate alerting.

The model is the classic SRE error budget: a tenant's target says "p95
latency under T ms", which budgets 5% of requests (BUDGET) to exceed T.
The tracker keeps a sliding window (window_s seconds) of per-request
outcomes and reports, per tenant:

    burn_rate = (violations / requests) / BUDGET

- burn 1.0 = spending the budget exactly as fast as it refills (at the
  p95 target boundary);
- burn > 1.0 = on track to exhaust it (20.0 = every request violating);
- burn 0.0 = no violations in the window.

Targets come from a spec string (`--slo "default=250,alice=100"` on
tools/serve.py, or ServeConfig.slo): `default` applies to any tenant
without an explicit entry; tenants without a target (no default either)
are observed into histograms but carry no SLO accounting.

The daemon calls `observe()` per served request and `poll()` per status
poll; `poll()` emits one `slo` telemetry record per tenant (schema v9)
and returns the `status.json` block. Burn beyond `burn_alert` raises a
`warning` record (component="slo") — EDGE-triggered: one warning when a
tenant's burn crosses the threshold, re-armed when it drops back under,
so a sustained burn doesn't spam a warning per poll.

Window memory is bounded by construction: entries older than window_s
are pruned on every observe/poll, so a soak holds at most one window of
(ts, violated) pairs per tenant.
"""

from __future__ import annotations

import collections

from ..utils import telemetry as _tm

# the error budget a p95 target implies: 5% of requests may exceed it
BUDGET = 0.05


def parse_slo_spec(spec: str | None) -> dict[str, float]:
    """`"default=250,alice=100"` -> {"default": 250.0, "alice": 100.0}.
    Empty/None -> {} (SLO plane off). Raises ValueError on a malformed
    entry — a mistyped SLO flag must fail loudly, not silently untrack
    a tenant."""
    out: dict[str, float] = {}
    if not spec:
        return out
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad SLO entry {part!r} "
                             "(want tenant=target_ms)")
        tenant, _, val = part.partition("=")
        tenant = tenant.strip()
        try:
            target = float(val)
        except ValueError:
            raise ValueError(f"bad SLO target {val!r} for tenant "
                             f"{tenant!r} (want a number, ms)")
        if not tenant or target <= 0:
            raise ValueError(f"bad SLO entry {part!r} "
                             "(tenant non-empty, target > 0)")
        out[tenant] = target
    return out


class SloTracker:
    """Sliding-window error-budget accounting per tenant."""

    def __init__(self, targets: dict[str, float],
                 window_s: float = 60.0, burn_alert: float = 2.0):
        self.targets = dict(targets)
        self.window_s = float(window_s)
        self.burn_alert = float(burn_alert)
        # tenant -> deque[(ts, violated)] spanning at most window_s
        self._window: dict[str, collections.deque] = {}
        # tenant -> lifetime violation count (the stop-record metric)
        self.violations_total: dict[str, int] = {}
        self._alerting: set[str] = set()

    def target_for(self, tenant: str) -> float | None:
        return self.targets.get(tenant, self.targets.get("default"))

    def _prune(self, tenant: str, now: float) -> None:
        win = self._window.get(tenant)
        if not win:
            return
        edge = now - self.window_s
        # inclusive window: an entry AT the edge still counts, so a
        # window_s-old outcome leaves exactly when now - ts > window_s
        while win and win[0][0] < edge:
            win.popleft()

    def observe(self, tenant: str, latency_ms: float, now: float) -> bool:
        """Record one served request; returns whether it violated the
        tenant's target (False when the tenant has no target)."""
        target = self.target_for(tenant)
        if target is None:
            return False
        violated = float(latency_ms) > target
        self._window.setdefault(
            tenant, collections.deque()).append((now, violated))
        self._prune(tenant, now)
        if violated:
            self.violations_total[tenant] = \
                self.violations_total.get(tenant, 0) + 1
        return violated

    def burn_snapshot(self, now: float) -> dict[str, float]:
        """Every tracked tenant's current burn rate — the autopilot's
        per-poll policy input (fleet/autopilot.py). Tenants with no
        windowed requests are omitted (their burn is undefined, not
        zero)."""
        out: dict[str, float] = {}
        for tenant in sorted(self._window):
            burn = self.burn_rate(tenant, now)
            if burn is not None:
                out[tenant] = burn
        return out

    def inject_synthetic(self, tenant: str, count: int, now: float,
                         factor: float = 10.0) -> int:
        """TEST-ONLY synthetic burn — the payload of the PAMPI_FAULTS
        `burst@poll<N>:<tenant>*<count>` clause (utils/faultinject.py):
        `count` violating observations at `factor`x the tenant's target
        land in the sliding window, so the hysteresis plane gets
        deterministic fuel without timing a real overload. Returns the
        number injected (0 when the tenant carries no target — a burst
        aimed at an untracked tenant is inert, same as a real slow
        request would be)."""
        target = self.target_for(tenant)
        if target is None:
            return 0
        for _ in range(int(count)):
            self.observe(tenant, target * factor, now)
        return int(count)

    def burn_rate(self, tenant: str, now: float) -> float | None:
        """The window's budget-burn rate; None when the tenant has no
        target or no windowed requests."""
        if self.target_for(tenant) is None:
            return None
        self._prune(tenant, now)
        win = self._window.get(tenant)
        if not win:
            return None
        bad = sum(1 for _, v in win if v)
        return round((bad / len(win)) / BUDGET, 4)

    def poll(self, now: float) -> dict:
        """Per-poll reporting: emits one `slo` record per tracked tenant
        (+ edge-triggered `warning` on burn > burn_alert) and returns
        the status.json block."""
        block: dict[str, dict] = {}
        for tenant in sorted(self._window):
            target = self.target_for(tenant)
            if target is None:
                continue
            self._prune(tenant, now)
            win = self._window.get(tenant) or ()
            n = len(win)
            bad = sum(1 for _, v in win if v)
            burn = round((bad / n) / BUDGET, 4) if n else 0.0
            row = {"target_ms": target, "window_s": self.window_s,
                   "requests": n, "violations": bad,
                   "violations_total": self.violations_total.get(
                       tenant, 0),
                   "burn_rate": burn}
            block[tenant] = row
            _tm.emit("slo", tenant=tenant, **row)
            if burn > self.burn_alert:
                if tenant not in self._alerting:
                    self._alerting.add(tenant)
                    _tm.emit("warning", component="slo",
                             reason=f"tenant {tenant} error-budget burn "
                                    f"{burn:.2f}x exceeds alert "
                                    f"threshold {self.burn_alert:.2f}x",
                             tenant=tenant, burn_rate=burn,
                             target_ms=target)
            else:
                self._alerting.discard(tenant)
        return block

    def total_violations(self) -> int:
        return sum(self.violations_total.values())
