"""Persistent fleet serving daemon: a long-lived process over
FleetScheduler with a file-queue request plane (ROADMAP item 2's
"persistent front" — tools/serve.py is the CLI).

Request plane (filesystem — works everywhere the repo does, survives
restarts, and needs no socket policy): tenants drop `.par` files into
the watched QUEUE directory; the daemon polls it, ADMITS requests
(global queue cap + per-tenant quota — over-quota files stay in place
and retry next poll), moves accepted files to `accepted/`, PARKS
malformed or fleet-ineligible files to `parked/` with a structured
`warning` telemetry record (one tenant's bad config must never kill the
daemon — the hardened `queue.load_queue(on_error=)` path), and serves
the accepted set through the scheduler: shape-class batching coalesces
mixed grids into shared compiles, the continuous lane pool swaps queued
scenarios into finished/diverged lanes, and the warm template/batch
caches (+ utils/xlacache across restarts) make zero-retrace the common
case.

Naming convention: `<tenant>__<scenario>.par` attributes the request to
a tenant for quota accounting and the per-tenant status table; files
without the `__` separator belong to tenant "default".

Status endpoint: a JSON file rewritten atomically at every poll and
after every bucket — uptime, served/parked/deferred counts, queue
depth (+max), per-tenant table, per-class compile counts, swap count,
latency percentiles, scenarios/s — the live view a load-test watches.

Observability (serving v4): latency percentiles come from a BOUNDED
log-bucket histogram (utils/metrics.Registry, per serving session — the
old unbounded `latencies_ms` list grew one float per request forever),
labeled per tenant and per class; the registry is snapshotted into a
`metrics` telemetry record each poll and rendered as a Prometheus-style
text file (`metrics.prom`) next to status.json. Every accepted request
mints a trace id (utils/tracing) whose parented stage records
(queue_wait/compile/execute/emit) decompose its end-to-end latency —
tools/telemetry_report.py renders the waterfall. Tenant SLO targets
(ServeConfig.slo, `"default=250,alice=100"`) arm fleet/slo.SloTracker:
sliding-window error-budget burn per tenant as `slo` records + a
status.json block, burn alerts via `warning` records, and
fleet_class_p95_ms / slo_violations metric records into bench_trend's
gate at stop.
Autopilot (serving v5, ISSUE 19): with `ServeConfig.autopilot` (or the
base .par's `tpu_autopilot`) on, fleet/autopilot.py threads a policy
loop through this poll cycle — self-healing `shrink_resume` on rank
death, hysteresis-banded elastic lane scaling, priority-weighted
admission + parked-lane preemption, and an explicit degradation ladder
— every decision an `autoscale` record (schema v9). Off (the default)
constructs nothing: the daemon is byte-identical to the policy-less
build, test-pinned. Independent of the knob, admission now ages
deferred files (most-deferred first, `starving` records past
defer_alert_polls) and status.json carries a bounded `parked_census`
(+ the `parked_max` retention knob).
Shutdown: a `STOP` file in the queue directory (or `max_polls` for
smokes/CI); the daemon finishes the in-flight poll, writes the final
status and telemetry (`serving` stop record + the
fleet_p50_latency_ms / fleet_queue_depth_max metric records the
bench_trend gate consumes), and exits 0.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

from ..utils import metrics as _mx
from ..utils import telemetry as _tm
from ..utils import tracing as _tr
from . import queue as _q
from .scheduler import FleetScheduler
from .slo import SloTracker, parse_slo_spec

STOP_FILE = "STOP"


@dataclasses.dataclass
class ServeConfig:
    """Daemon knobs (tools/serve.py maps CLI flags onto these)."""

    queue_dir: str
    status_path: str = ""       # default <queue_dir>/status.json
    results_dir: str = ""       # default <queue_dir>/results
    poll_s: float = 0.5         # queue-scan cadence
    max_lanes: int = 4          # continuous-batch pool size per bucket
    max_queue: int = 64         # admission: max accepted-and-unserved
    tenant_quota: int = 8       # admission: per-tenant pending cap
    classes: str = "on"         # shape-class batching (the serving
    #                             default; "off" = exact-shape buckets)
    max_polls: int = 0          # 0 = run until the STOP file appears
    slo: str = ""               # tenant SLO targets, fleet/slo.
    #                             parse_slo_spec ("default=250,alice=100"
    #                             = p95 latency targets in ms; empty =
    #                             SLO plane off)
    slo_window_s: float = 60.0  # sliding error-budget window
    slo_burn_alert: float = 2.0  # burn-rate warning threshold
    autopilot: str = ""         # policy loop (fleet/autopilot.py):
    #                             "off"/"" = no Autopilot — the daemon
    #                             is byte-identical to the policy-less
    #                             build (test-pinned); "on[:k=v,...]"
    #                             arms heal/scale/preempt/degrade.
    #                             Empty falls back to the base .par's
    #                             tpu_autopilot knob.
    priorities: str = ""        # tenant priority classes for the QoS
    #                             plane ("zoe=high,bob=low,default=
    #                             normal"; empty = flat — weighted
    #                             admission and preemption both off)
    parked_max: int = 0         # parked/ retention: keep at most this
    #                             many parked malformed files (0 =
    #                             unbounded); beyond it the OLDEST are
    #                             deleted with a warning record — the
    #                             bounded-census knob (status.json
    #                             `parked_census` reports count +
    #                             oldest age either way)
    defer_alert_polls: int = 5  # an `admission` action="starving"
    #                             record once a request has deferred
    #                             more than this many polls (its aging
    #                             boost is already active — see scan)


def tenant_of(sid: str) -> str:
    return sid.split("__", 1)[0] if "__" in sid else "default"


def _percentile(values, q: float):
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return round(vs[idx], 3)


class FleetDaemon:
    """One serving session: poll -> admit -> serve -> publish status."""

    def __init__(self, config: ServeConfig, base=None):
        cfg = config
        self.cfg = cfg
        self.base = base
        self.status_path = cfg.status_path or os.path.join(
            cfg.queue_dir, "status.json")
        self.results_dir = cfg.results_dir or os.path.join(
            cfg.queue_dir, "results")
        self.parked_dir = os.path.join(cfg.queue_dir, "parked")
        self.accepted_dir = os.path.join(cfg.queue_dir, "accepted")
        for d in (cfg.queue_dir, self.results_dir, self.parked_dir,
                  self.accepted_dir):
            os.makedirs(d, exist_ok=True)
        self.sched = FleetScheduler(classes=cfg.classes,
                                    lanes=cfg.max_lanes, isolate=True)
        self.t0 = time.time()
        self.polls = 0
        self.served = 0
        self.diverged = 0
        self.failed = 0
        self.parked = 0
        self.deferred = 0
        self.swaps = 0
        self.queue_depth = 0
        self.queue_depth_max = 0
        # latency population: a BOUNDED log-bucket histogram per label
        # set (overall / tenant / class) — O(#buckets) memory over any
        # soak length, where the old `latencies_ms` list grew forever.
        # The registry is per serving SESSION: two daemons in one
        # process must not share a latency population.
        self.metrics = _mx.Registry()
        self.metrics_path = os.path.join(
            os.path.dirname(self.status_path) or ".", "metrics.prom")
        self.slo = SloTracker(parse_slo_spec(cfg.slo),
                              window_s=cfg.slo_window_s,
                              burn_alert=cfg.slo_burn_alert)
        self._slo_block: dict = {}
        self.per_tenant: dict[str, dict] = {}
        self.scenarios_per_s = None
        self._accept_ts: dict[str, float] = {}
        self._trace_ids: dict[str, str | None] = {}
        self._pending_by_tenant: dict[str, int] = {}
        # admission-starvation fix (ISSUE 19): consecutive deferral
        # count per queue FILE -> the aging boost in scan()'s sort;
        # _starving de-dupes the one-shot starving record per file
        self._defer_polls: dict[str, int] = {}
        self._starving: set[str] = set()
        self.shed = 0
        # the policy plane: config wins, else the base .par's knob;
        # "off" builds NOTHING — the daemon stays byte-identical to the
        # policy-less build (test-pinned; fleet/autopilot.py docstring)
        mode = cfg.autopilot or (getattr(base, "tpu_autopilot", "")
                                 if base is not None else "") or "off"
        self.autopilot = None
        if mode != "off":
            from .autopilot import Autopilot

            self.autopilot = Autopilot(self, mode)
            self.sched.raise_rank_death = True
        _tm.emit("serving", event="start", queue_dir=cfg.queue_dir,
                 max_lanes=cfg.max_lanes, max_queue=cfg.max_queue,
                 tenant_quota=cfg.tenant_quota, classes=cfg.classes)
        self.write_status()

    # -- intake ---------------------------------------------------------
    def _park(self, path: str, exc) -> None:
        """The hardened malformed-.par path: move the file aside and
        record a structured warning — the daemon outlives the tenant's
        typo (fleet/queue.load_queue on_error contract)."""
        dest = os.path.join(self.parked_dir, os.path.basename(path))
        try:
            os.replace(path, dest)
        except OSError:
            dest = None
        self.parked += 1
        self._defer_polls.pop(os.path.basename(path), None)
        self._starving.discard(os.path.basename(path))
        _tm.emit("warning", component="fleet.serve", reason="parked",
                 path=path, parked_to=dest, error=str(exc))
        _tm.emit("admission", action="park", path=path,
                 tenant=tenant_of(os.path.splitext(
                     os.path.basename(path))[0]),
                 error=str(exc))
        self._retain_parked()

    def _retain_parked(self) -> None:
        """parked/ retention (ISSUE 19): with parked_max > 0, keep only
        the newest parked_max files — the oldest are deleted with a
        warning record, so a misconfigured tenant spraying malformed
        .par files cannot fill the queue dir's disk. 0 (the default)
        keeps the historical unbounded behavior; either way the census
        rides status.json."""
        cap = self.cfg.parked_max
        if cap <= 0:
            return
        entries = sorted(
            (os.path.getmtime(p), p)
            for p in (os.path.join(self.parked_dir, f)
                      for f in os.listdir(self.parked_dir))
            if os.path.isfile(p))
        for _mt, victim in entries[:-cap] if len(entries) > cap else ():
            try:
                os.remove(victim)
            except OSError:
                continue
            _tm.emit("warning", component="fleet.serve",
                     reason="parked_evicted", path=victim,
                     parked_max=cap)

    def _parked_census(self) -> dict:
        """The bounded parked/ view for status.json: count + oldest age
        (None when empty) + the retention cap — an operator sees the
        malformed backlog without listing the directory."""
        now = time.time()
        oldest = None
        count = 0
        for f in os.listdir(self.parked_dir):
            p = os.path.join(self.parked_dir, f)
            if not os.path.isfile(p):
                continue
            count += 1
            try:
                age = now - os.path.getmtime(p)
            except OSError:
                continue
            if oldest is None or age > oldest:
                oldest = age
        return {"count": count,
                "oldest_age_s": (round(oldest, 3)
                                 if oldest is not None else None),
                "max": self.cfg.parked_max}

    def scan(self) -> list:
        """One admission pass over the queue directory. Returns the
        newly accepted requests; over-quota/over-cap files are left in
        place (deferred — they retry next poll), malformed files are
        parked."""
        files = sorted(
            os.path.join(self.cfg.queue_dir, f)
            for f in os.listdir(self.cfg.queue_dir)
            if f.endswith(".par")
            and os.path.isfile(os.path.join(self.cfg.queue_dir, f)))
        if self._defer_polls:
            # starvation fix: a deferred file's retry outranks newer
            # arrivals — most-deferred first, name-order tiebreak. With
            # zero deferrals outstanding (every key popped on accept/
            # park) this IS the historical sorted order.
            files.sort(key=lambda p: (
                -self._defer_polls.get(os.path.basename(p), 0), p))
        self.queue_depth = len(files)
        self.queue_depth_max = max(self.queue_depth_max,
                                   self.queue_depth)
        accepted: list[_q.ScenarioRequest] = []
        deferred_now = 0
        for path in files:
            fname = os.path.basename(path)
            sid = os.path.splitext(fname)[0]
            tenant = tenant_of(sid)
            if self.autopilot is not None \
                    and self.autopilot.should_shed(tenant):
                # rung 3: lowest-priority tenants are refused outright
                # (an explicit, recorded degradation — not a deferral)
                self._shed(path, sid, tenant)
                continue
            # _pending_by_tenant already counts this scan's accepts
            # (incremented on each accept below)
            if sum(self._pending_by_tenant.values()) \
                    >= self.cfg.max_queue:
                deferred_now += 1
                _tm.emit("admission", action="defer", sid=sid,
                         tenant=tenant, reason="queue_cap",
                         queue_depth=self.queue_depth,
                         deferrals=self._note_defer(fname, sid, tenant,
                                                    "queue_cap"))
                continue
            quota = (self.autopilot.quota_for(tenant)
                     if self.autopilot is not None
                     else self.cfg.tenant_quota)
            if self._pending_by_tenant.get(tenant, 0) >= quota:
                deferred_now += 1
                _tm.emit("admission", action="defer", sid=sid,
                         tenant=tenant, reason="tenant_quota",
                         deferrals=self._note_defer(fname, sid, tenant,
                                                    "tenant_quota"))
                continue
            reqs = _q.load_queue([path], self.base,
                                 on_error=self._park)
            if not reqs:
                continue  # parked
            req = reqs[0]
            # admission is the trace root: the minted id threads
            # queue -> scheduler -> batch and back (None when telemetry
            # is off — every downstream mark no-ops)
            trace = _tr.mint(sid, tenant=tenant)
            req = _q.ScenarioRequest(sid=sid, param=req.param,
                                     trace=trace)
            if self.autopilot is not None:
                # rung-2 degradation: cap the pressure-solve budget
                req = self.autopilot.admit(req)
            self._trace_ids[sid] = req.trace
            os.replace(path, os.path.join(self.accepted_dir, fname))
            self._defer_polls.pop(fname, None)
            self._starving.discard(fname)
            self._accept_ts[sid] = time.time()
            self._pending_by_tenant[tenant] = \
                self._pending_by_tenant.get(tenant, 0) + 1
            accepted.append(req)
            _tm.emit("admission", action="accept", sid=sid,
                     tenant=tenant, queue_depth=self.queue_depth)
        self.deferred += deferred_now
        return accepted

    def _note_defer(self, fname: str, sid: str, tenant: str,
                    reason: str) -> int:
        """Count a deferral for the aging boost; past defer_alert_polls
        the file earns ONE `admission` action="starving" record (cleared
        when it finally admits — a later starvation re-alerts)."""
        n = self._defer_polls.get(fname, 0) + 1
        self._defer_polls[fname] = n
        if (n > self.cfg.defer_alert_polls
                and fname not in self._starving):
            self._starving.add(fname)
            _tm.emit("admission", action="starving", sid=sid,
                     tenant=tenant, reason=reason, deferrals=n,
                     boost_active=True)
        return n

    def _shed(self, path: str, sid: str, tenant: str) -> None:
        """Rung-3 admission shedding: the request is refused NOW with a
        structured failure result (the tenant sees a decision, not a
        silent stall) and the queue file removed."""
        self.shed += 1
        self.failed += 1
        self._defer_polls.pop(os.path.basename(path), None)
        self._starving.discard(os.path.basename(path))
        self.metrics.counter("fleet_shed_total", tenant=tenant).inc()
        _tm.emit("admission", action="shed", sid=sid, tenant=tenant,
                 rung=self.autopilot.rung)
        with open(os.path.join(self.results_dir,
                               f"{sid}.json"), "w") as fh:
            json.dump({"sid": sid, "tenant": tenant, "failed": True,
                       "shed": True,
                       "error": "shed: degraded fleet is refusing "
                                "lowest-priority admissions"}, fh)
        try:
            os.remove(path)
        except OSError:
            pass

    # -- serving --------------------------------------------------------
    def serve(self, requests) -> None:
        for req in requests:
            self.sched.submit(req)
        t0 = time.perf_counter()
        try:
            result = self.sched.run()
        except Exception as exc:  # lint: allow(broad-except) — serving isolation: one tenant's bad knob combo (e.g. a forced-mesh bucket with indivisible lanes) must degrade to failed requests, never kill the daemon serving every other tenant
            if self.autopilot is not None:
                from ..parallel.coordinator import RankDeadError

                if isinstance(exc, RankDeadError):
                    # self-healing (fleet/autopilot.py): the death
                    # becomes shrink_resume onto survivor capacity and
                    # the poll's requests go BACK in the queue — they
                    # retry next poll on the healed fleet instead of
                    # failing to the tenants
                    self.autopilot.heal(exc)
                    self._requeue(requests)
                    return
            self._fail_batch(requests, exc)
            return
        wall = time.perf_counter() - t0
        now = time.time()
        for sc in result.scenarios:
            tenant = tenant_of(sc.sid)
            self._pending_by_tenant[tenant] = max(
                0, self._pending_by_tenant.get(tenant, 0) - 1)
            t_acc = self._accept_ts.pop(sc.sid, None)
            trace = self._trace_ids.pop(sc.sid, None)
            if getattr(sc, "failed", False):
                # per-bucket isolation (scheduler isolate mode): the
                # bucket could not be scheduled — a failed result, a
                # failure file, and the daemon keeps serving
                self.failed += 1
                self.metrics.counter("fleet_failed_total",
                                     tenant=tenant).inc()
                _tm.emit("admission", action="fail", sid=sc.sid,
                         tenant=tenant, error=sc.error)
                with open(os.path.join(self.results_dir,
                                       f"{sc.sid}.json"), "w") as fh:
                    json.dump({"sid": sc.sid, "tenant": tenant,
                               "failed": True, "error": sc.error}, fh)
                _tr.finish(trace, status="failed")
                continue
            latency_ms = (round((now - t_acc) * 1e3, 3)
                          if t_acc is not None else None)
            if latency_ms is not None:
                self.metrics.histogram(
                    "fleet_request_latency_ms").observe(latency_ms)
                self.metrics.histogram(
                    "fleet_request_latency_ms",
                    tenant=tenant).observe(latency_ms)
                self.metrics.histogram(
                    "fleet_class_latency_ms",
                    klass=sc.bucket,
                    family=sc.family).observe(latency_ms)
                self.slo.observe(tenant, latency_ms, now)
                _tm.emit("latency", scenario=sc.sid, tenant=tenant,
                         ms=latency_ms, bucket=sc.bucket, mode=sc.mode)
            row = self.per_tenant.setdefault(
                tenant, {"served": 0, "diverged": 0})
            row["served"] += 1
            self.served += 1
            self.metrics.counter("fleet_served_total",
                                 tenant=tenant).inc()
            if sc.diverged:
                row["diverged"] += 1
                self.diverged += 1
                self.metrics.counter("fleet_diverged_total",
                                     tenant=tenant).inc()
            with open(os.path.join(self.results_dir,
                                   f"{sc.sid}.json"), "w") as fh:
                json.dump({"sid": sc.sid, "tenant": tenant,
                           "bucket": sc.bucket, "mode": sc.mode,
                           "t": sc.t, "nt": sc.nt,
                           "diverged": sc.diverged,
                           "latency_ms": latency_ms}, fh)
            # the result file is the request's emit boundary: the trace
            # flushes its parented stage records here
            _tr.mark(trace, "emit_end")
            _tr.finish(trace)
        self.swaps = sum(self.sched.swap_census.values())
        self.scenarios_per_s = (round(len(result.scenarios) / wall, 4)
                                if wall > 0 else None)

    def _requeue(self, requests) -> None:
        """Put a poll's accepted-but-unserved requests back in the
        queue (the heal path): accounting released, accepted/ files
        moved home, traces finished as requeued — next poll re-admits
        them onto the healed fleet."""
        for req in requests:
            tenant = tenant_of(req.sid)
            self._pending_by_tenant[tenant] = max(
                0, self._pending_by_tenant.get(tenant, 0) - 1)
            self._accept_ts.pop(req.sid, None)
            _tr.finish(self._trace_ids.pop(req.sid, None),
                       status="requeued")
            src = os.path.join(self.accepted_dir, f"{req.sid}.par")
            dst = os.path.join(self.cfg.queue_dir, f"{req.sid}.par")
            try:
                os.replace(src, dst)
            except OSError:
                continue  # already gone: the request is simply dropped
            _tm.emit("admission", action="requeue", sid=req.sid,
                     tenant=tenant, reason="heal")

    def _fail_batch(self, requests, exc) -> None:
        """Scheduling failed for this poll's accepted set: release the
        pending accounting, write per-scenario error results, and keep
        serving — the structured-degradation twin of `_park` for
        requests that parsed fine but could not be scheduled."""
        self.failed += len(requests)
        _tm.emit("warning", component="fleet.serve",
                 reason="schedule_failed", error=str(exc),
                 scenarios=[r.sid for r in requests])
        for req in requests:
            tenant = tenant_of(req.sid)
            self._pending_by_tenant[tenant] = max(
                0, self._pending_by_tenant.get(tenant, 0) - 1)
            self._accept_ts.pop(req.sid, None)
            self.metrics.counter("fleet_failed_total",
                                 tenant=tenant).inc()
            _tr.finish(self._trace_ids.pop(req.sid, None),
                       status="failed")
            _tm.emit("admission", action="fail", sid=req.sid,
                     tenant=tenant, error=str(exc))
            with open(os.path.join(self.results_dir,
                                   f"{req.sid}.json"), "w") as fh:
                json.dump({"sid": req.sid, "tenant": tenant,
                           "failed": True, "error": str(exc)}, fh)

    # -- status endpoint ------------------------------------------------
    def status(self) -> dict:
        # percentiles off the bounded histogram: nearest-rank at bucket
        # resolution (< ~4.5% of the exact sorted-list value,
        # test-pinned); `max` is exact (the histogram tracks it aside)
        hist = self.metrics.histogram("fleet_request_latency_ms")
        st = {
            "uptime_s": round(time.time() - self.t0, 3),
            "polls": self.polls,
            "served": self.served,
            "diverged": self.diverged,
            "failed": self.failed,
            "parked": self.parked,
            "deferred": self.deferred,
            "swaps": self.swaps,
            "queue_depth": self.queue_depth,
            "queue_depth_max": self.queue_depth_max,
            "active_lanes": self.cfg.max_lanes,
            "per_tenant": self.per_tenant,
            "classes": dict(self.sched.compile_census),
            "latency_ms": {
                "p50": hist.quantile(0.5),
                "p95": hist.quantile(0.95),
                "max": (round(hist.vmax, 3)
                        if hist.vmax is not None else None),
            },
            "scenarios_per_s": self.scenarios_per_s,
            "updated": round(time.time(), 3),
        }
        st["parked_census"] = self._parked_census()
        if self.shed:
            st["shed"] = self.shed
        if self.slo.targets:
            st["slo"] = self._slo_block
        if self.autopilot is not None:
            st["autopilot"] = self.autopilot.status_block()
        return st

    def write_status(self) -> dict:
        st = self.status()
        tmp = self.status_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(st, fh, indent=1)
        os.replace(tmp, self.status_path)  # atomic: readers never tear
        # the scrape surface rides along: the registry as Prometheus
        # text, atomically, next to status.json
        self.metrics.write_prometheus(self.metrics_path)
        return st

    # -- the daemon loop ------------------------------------------------
    def should_stop(self) -> bool:
        return os.path.exists(os.path.join(self.cfg.queue_dir,
                                           STOP_FILE))

    def poll_once(self) -> dict:
        self.polls += 1
        if self.autopilot is not None:
            # daemon-plane fault clauses (dead/burst/slow_lane@poll)
            # land BEFORE the scan: a heal reshapes capacity for this
            # poll's admissions, a burst is visible to this poll's tick
            self.autopilot.pre_poll(time.time())
        accepted = self.scan()
        if accepted:
            self.serve(accepted)
        self.metrics.gauge("fleet_queue_depth").set(self.queue_depth)
        self.metrics.gauge("fleet_active_lanes").set(self.cfg.max_lanes)
        if self.slo.targets:
            # per-tenant slo records + edge-triggered burn warnings;
            # the returned block rides the status endpoint
            self._slo_block = self.slo.poll(time.time())
        if self.autopilot is not None:
            # observe→decide→act, exactly one autoscale record; the
            # status write below publishes the post-decision state
            self.autopilot.tick(time.time())
        st = self.write_status()
        # one cumulative registry snapshot per poll — the `metrics`
        # record plane telemetry_report.metrics_summary folds
        self.metrics.emit_snapshot(event="poll", poll=self.polls)
        _tm.emit("serving", event="poll", poll=self.polls,
                 accepted=len(accepted), served=self.served,
                 queue_depth=self.queue_depth)
        return st

    def stop(self) -> dict:
        """Final status + the trend-gated serving metrics."""
        st = self.write_status()
        p50 = st["latency_ms"]["p50"]
        import jax

        backend = jax.default_backend()
        if p50 is not None:
            _tm.emit("metric", metric="fleet_p50_latency_ms", value=p50,
                     unit="ms", backend=backend)
        _tm.emit("metric", metric="fleet_queue_depth_max",
                 value=self.queue_depth_max, unit="requests",
                 backend=backend)
        # the SLO gate metrics (bench_trend NAME_DIRECTIONS, both
        # lower-is-better): the WORST per-class p95 — one headline per
        # artifact, so the gate watches the tail class, not an average —
        # and the lifetime violation count
        class_p95 = [h.quantile(0.95)
                     for h in self.metrics.histograms(
                         "fleet_class_latency_ms") if h.n]
        if class_p95:
            _tm.emit("metric", metric="fleet_class_p95_ms",
                     value=round(max(class_p95), 3), unit="ms",
                     backend=backend)
        if self.slo.targets:
            _tm.emit("metric", metric="slo_violations",
                     value=self.slo.total_violations(),
                     unit="requests", backend=backend)
        if self.autopilot is not None:
            # autoscale_flaps / autoscale_time_to_recover_ms /
            # autoscale_transitions — the policy plane's own gate series
            self.autopilot.emit_stop_metrics(backend)
        self.metrics.emit_snapshot(event="stop")
        _tm.emit("serving", event="stop",
                 # the daemon's own percentiles ride the stop record so
                 # the merged serving_summary reports the SAME numbers
                 # as the status endpoint (one percentile definition)
                 p50_latency_ms=p50,
                 max_latency_ms=st["latency_ms"]["max"],
                 **{k: st[k] for k in (
                     "polls", "served", "diverged", "failed", "parked",
                     "deferred", "swaps", "queue_depth_max",
                     "scenarios_per_s")})
        return st

    def run(self) -> int:
        """Serve until the STOP file appears (or max_polls). Returns 0
        on a clean shutdown."""
        try:
            while True:
                if self.should_stop():
                    break
                self.poll_once()
                if (self.cfg.max_polls
                        and self.polls >= self.cfg.max_polls):
                    break
                time.sleep(self.cfg.poll_s)
        finally:
            self.stop()
        return 0
