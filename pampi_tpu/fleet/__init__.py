"""Scenario-fleet serving: batched multi-tenant runs as a first-class
workload (ROADMAP item 2 — serving v2).

The north star's "millions of users" is not one 4096² run — it is
thousands of concurrent small/medium scenarios (parameter sweeps,
per-user `.par` configs, ensembles). This package turns the solo-run
machinery into a serving stack:

  queue.py      request intake + shared-trace bucketing (what may share
                one compiled program); per-lane te and the hardened
                load_queue error path
  shapeclass.py shape-class batching: power-of-two padded rungs whose
                grid extents are per-lane DATA — mixed grids share one
                compile, dead pad cells masked out of every reduction
  batch.py      the vmapped batched driver: N lanes through one chunk,
                diverged lanes frozen by the in-band sentinel, per-lane
                te carried, continuous lane swap, fleet-over-mesh
                NamedSharding
  scheduler.py  the serving front: buckets -> execution mode
                (`tpu_fleet` knob) -> compiled-program reuse -> fleet
                summary artifact; the continuous-batching pool
  serve.py      the persistent daemon: file-queue request plane,
                admission control, per-tenant quotas, live status
                endpoint (tools/serve.py is the CLI)
  slo.py        tenant SLO accounting: per-tenant latency targets, the
                sliding-window error budget, burn-rate alerting (the
                `slo` record plane + the status.json block)
  autopilot.py  the self-healing elastic control plane (ISSUE 19): a
                policy loop in the daemon's poll cycle consuming the
                SLO/queue/latency signals to drive shrink_resume,
                elastic lane scaling, QoS preemption and the explicit
                degradation ladder (`tpu_autopilot`; every decision an
                `autoscale` record)

See README "Fleet serving" for the request format, the bucketing policy
and the knob table.
"""

from .batch import BatchedSolver, FleetRecorder, lane_state
from .queue import (
    BucketKey,
    ScenarioRequest,
    bucket,
    bucket_key,
    class_bucket_key,
    family_of,
    knob_signature,
    load_queue,
    signature_hash,
)
from .scheduler import (
    FleetResult,
    FleetScheduler,
    ScenarioResult,
    reset_templates,
    run_fleet,
    shrink_resume,
)
from .autopilot import (
    Autopilot,
    AutopilotConfig,
    ParkStore,
    parse_autopilot_spec,
    parse_priority_spec,
)
from .serve import FleetDaemon, ServeConfig
from .slo import SloTracker, parse_slo_spec

__all__ = [
    "BatchedSolver", "FleetRecorder", "lane_state",
    "BucketKey", "ScenarioRequest", "bucket", "bucket_key",
    "class_bucket_key", "family_of", "knob_signature", "load_queue",
    "signature_hash",
    "FleetResult", "FleetScheduler", "ScenarioResult", "reset_templates",
    "run_fleet", "shrink_resume",
    "FleetDaemon", "ServeConfig",
    "SloTracker", "parse_slo_spec",
    "Autopilot", "AutopilotConfig", "ParkStore",
    "parse_autopilot_spec", "parse_priority_spec",
]
