"""CLI driver: `python -m pampi_tpu <configFile.par>`.

Parity with the reference's L6 driver convention (`./exe-<TAG> <file.par>`,
assignment-6/src/main.c:21-110): parse argv -> read .par -> echo config ->
run solver -> write outputs -> print walltime. Dispatch on the `name` key:
  poisson           -> 2-D Poisson red-black SOR      (assignment-4)
  dcavity / canal   -> NS-2D time-stepper             (assignment-5)
  canal_obstacle    -> NS-2D canal + flag-masked obstacles (ops/obstacle.py)
  dcavity3d/canal3d -> NS-3D time-stepper             (assignment-6)
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = sys.argv if argv is None else argv
    if len(argv) < 2:
        print(f"Usage: {argv[0]} <configFile>  |  {argv[0]} <N> <iter>")
        return 0
    if argv[1] == "--halo-test":
        # halo-exchange debug dump (≙ assignment-6 test.c rank-id checker)
        from .parallel.halo_debug import main as halo_main

        return halo_main(argv)
    if argv[1].isdigit():
        # DMVM mode (≙ assignment-3a/3b CLI: ./exe <N> <iter>); under a
        # PAMPI_COORDINATOR launch the ring spans every process's devices
        from .models.dmvm import main as dmvm_main
        from .parallel import multihost

        with multihost.session():
            return dmvm_main(argv)
    return _run(argv)


def mesh_is_single(param) -> bool:
    """Whether the tpu_mesh key resolves to the single-device path — the
    ONE statement of that policy, shared by `_make_comm` (which builds
    the CartComm otherwise) and the fleet scheduler's per-bucket mode
    decision (`fleet/scheduler._is_dist` must never diverge from the
    comm the template build actually constructs)."""
    import jax

    if len(jax.devices()) == 1:
        return True
    if param.tpu_mesh == "auto":
        return False
    return all(int(t) == 1 for t in param.tpu_mesh.split("x"))


def _make_comm(param, ndims: int):
    """Resolve the tpu_mesh key to a CartComm, or None for single-device
    (the ≙ of ENABLE_MPI=false: same solver API, one process, comm.c:470-488)."""
    import jax

    dims = (
        None
        if param.tpu_mesh == "auto"
        else tuple(int(t) for t in param.tpu_mesh.split("x"))
    )
    if mesh_is_single(param):
        if jax.process_count() > 1:
            # every rank would run the full serial solver and race on the
            # output files; a 1-cell mesh makes no sense distributed
            raise ValueError(
                "tpu_mesh 1 under a multi-process launch: drop the "
                "PAMPI_COORDINATOR env (run single-process) or widen tpu_mesh"
            )
        return None
    from .parallel.comm import CartComm

    # grid extents in mesh-axis order make `auto` prefer factorizations the
    # grid actually divides (e.g. canal.par 200x50 on 8 devices -> (2,4))
    extents = (
        (param.jmax, param.imax) if ndims == 2
        else (param.kmax, param.jmax, param.imax)
    )
    comm = CartComm(ndims=ndims, dims=dims, extents=extents,
                    tiers=param.tpu_mesh_tiers)
    comm.print_config()
    return comm


def _try_build(build):
    """Config errors (bad mesh shape, indivisible grid) get a clean one-line
    report; solver-internal errors keep their traceback."""
    try:
        return build()
    except ValueError as exc:
        print(f"Error: {exc}", file=sys.stderr)
        return None


def _run(argv) -> int:

    from .utils.params import Parameter, read_parameter, print_parameter

    param = read_parameter(argv[1], Parameter())

    # commInit before anything touches devices: under a PAMPI_COORDINATOR
    # launch this joins the process group and makes jax.devices() global;
    # single-process runs no-op (≙ the ENABLE_MPI=false build)
    from .parallel import multihost

    # the whole body runs inside the commInit/commFinalize bracket so a
    # failure anywhere (cache setup, config echo, solver) still shuts the
    # process group down instead of leaving peer ranks blocked
    with multihost.session():
        from .utils import xlacache

        xlacache.enable()  # recompiles of unchanged programs become disk loads

        if param.tpu_dtype == "float64":
            import jax

            jax.config.update("jax_enable_x64", True)
        from .utils import flags as _flags

        _flags.set_default("PAMPI_DTYPE", param.tpu_dtype)

        from .utils import profiling as prof
        from .utils import telemetry

        print_parameter(param)
        prof.init()
        telemetry.start_run(
            tool="cli", config=argv[1], problem=param.name,
            grid=[param.kmax, param.jmax, param.imax],
            solver=param.tpu_solver, dtype=param.tpu_dtype,
        )
        try:
            return _dispatch(param, prof)
        finally:
            # always stop an open XProf trace and print the region table, even
            # when the solver or a writer raises — that's the run worth
            # profiling. telemetry.finalize after prof.finalize: the region
            # table is still populated (only reset() clears it) and lands in
            # the JSONL finalize record; both are idempotent vs their atexit
            # hooks
            prof.finalize()
            telemetry.finalize()


def _resume_after_death(param, exc, is3d: bool):
    """The driver's dead-rank policy (`tpu_dead_resume`): on a
    RankDeadError, restore the newest agreed elastic generation onto
    whatever capacity THIS process still owns and finish the run
    degraded (fleet/scheduler.shrink_resume). Returns the completed
    survivor solver, or None when resume is off / not armed / this
    process cannot stand alone — then the structured error plus the
    operator walkthrough is the output, and the caller exits 3.

    Under a real multi-process launch every surviving process lands
    here; an in-place process-group shrink would need a re-elected
    coordinator and dense re-ranking, so the cross-process story is the
    printed relaunch (survivor count + tpu_restart) — the single-process
    shape (one host owning local devices, and the lockstep proof path)
    resumes in-process."""
    import jax

    print(f"Error: {exc}", file=sys.stderr)
    armed = (param.tpu_dead_resume and param.tpu_checkpoint
             and param.tpu_ckpt_elastic
             and os.path.exists(param.tpu_checkpoint))
    if not armed:
        print(
            "dead-rank resume not armed (needs tpu_dead_resume 1 + "
            "tpu_ckpt_elastic 1 + an existing tpu_checkpoint manifest); "
            "resume manually via tpu_restart on the survivor set",
            file=sys.stderr,
        )
        return None
    if jax.process_count() > 1:
        n_alive = (len(exc.survivors) if exc.survivors is not None
                   else jax.process_count() - max(1, len(exc.ranks)))
        print(
            "dead-rank resume across processes is operator-driven: "
            f"relaunch with {n_alive} process(es) on the surviving "
            f"hosts, adding `tpu_restart {param.tpu_checkpoint}` — the "
            "elastic manifest reshards onto the shrunk mesh and the "
            "fault ledger restores the fleet's protocol state",
            file=sys.stderr,
        )
        return None
    from .fleet.scheduler import shrink_resume

    family = "ns3d" if is3d else "ns2d"
    try:
        solver = shrink_resume(param.tpu_checkpoint, param,
                               family=family, dead=exc.ranks,
                               epoch=exc.epoch)
    except (OSError, ValueError, KeyError) as err:
        print(f"Error: dead-rank resume from {param.tpu_checkpoint} "
              f"failed: {err}", file=sys.stderr)
        return None
    print(f"Resumed on the survivor set from {param.tpu_checkpoint} "
          f"(generation {getattr(solver, '_elastic_generation', '?')}) "
          f"at t={solver.t:.4f}; finishing at degraded capacity")
    solver.run()
    return solver


def _dispatch(param, prof) -> int:
    from .utils.timing import get_timestamp

    if param.tpu_solver not in ("sor", "mg", "fft", "sor_lex", "sor_rba",
                                "auto"):
        print(
            "Error: tpu_solver must be auto|sor|mg|fft|sor_lex|sor_rba, "
            f"got {param.tpu_solver!r}",
            file=sys.stderr,
        )
        return 1

    from .utils.params import is_3d_config

    ns3d = is_3d_config(param)
    if param.tpu_solver == "sor_rba" and not param.name.startswith("poisson"):
        # the assignment-4 separable-ω oracle; NS pressure solves don't
        # have it (sor_lex IS available on NS-2D — the capped-trajectory
        # ordering oracle, tools/northstar.py match4096)
        print(
            "Error: tpu_solver sor_rba is a Poisson-only oracle mode; "
            "NS problems take sor|sor_lex|mg|fft",
            file=sys.stderr,
        )
        return 1
    if param.tpu_solver == "sor_lex" and ns3d:
        print(
            "Error: tpu_solver sor_lex is 2-D only (Poisson and NS-2D); "
            "NS-3D takes sor|mg|fft",
            file=sys.stderr,
        )
        return 1

    if param.tpu_chunk < 0 or param.tpu_lookahead < 0:
        print(
            "Error: tpu_chunk and tpu_lookahead must be >= 0 "
            f"(got {param.tpu_chunk}, {param.tpu_lookahead})",
            file=sys.stderr,
        )
        return 1

    if (param.tpu_recover_ring < 0 or param.tpu_recover_max < 1
            or not 0.0 < param.tpu_recover_dt_scale <= 1.0
            or param.tpu_retry_replenish < 0):
        print(
            "Error: recovery knobs out of range — need tpu_recover_ring "
            ">= 0, tpu_recover_max >= 1, 0 < tpu_recover_dt_scale <= 1, "
            "tpu_retry_replenish >= 0 (got "
            f"{param.tpu_recover_ring}, {param.tpu_recover_max}, "
            f"{param.tpu_recover_dt_scale}, {param.tpu_retry_replenish})",
            file=sys.stderr,
        )
        return 1

    if param.tpu_coord not in ("auto", "on", "off") \
            or param.tpu_ckpt_elastic not in (0, 1):
        print(
            "Error: tpu_coord must be auto|on|off and tpu_ckpt_elastic "
            f"0|1 (got {param.tpu_coord!r}, {param.tpu_ckpt_elastic})",
            file=sys.stderr,
        )
        return 1

    if param.tpu_coord_timeout < 0 or param.tpu_dead_resume not in (0, 1):
        print(
            "Error: tpu_coord_timeout must be >= 0 (seconds; 0 disables "
            "the boundary watchdog) and tpu_dead_resume 0|1 (got "
            f"{param.tpu_coord_timeout}, {param.tpu_dead_resume})",
            file=sys.stderr,
        )
        return 1

    from .utils import faultinject as _fi

    if _fi.enabled():
        # fault injection is the recovery layer's TEST plane — loud when it
        # leaks into a real run (utils/faultinject.py)
        print(
            "WARNING: PAMPI_FAULTS is set — deterministic fault injection "
            "armed (test-only; unset it for production runs)",
            file=sys.stderr,
        )

    if param.tpu_sor_layout not in ("auto", "checkerboard", "quarters",
                                    "octants"):
        print(
            "Error: tpu_sor_layout must be auto|checkerboard|quarters"
            f"|octants, got {param.tpu_sor_layout!r}",
            file=sys.stderr,
        )
        return 1

    if param.obstacles.strip() and param.name.startswith("poisson"):
        # refuse rather than silently simulate an empty box
        print(
            "Error: the obstacles key is supported for NS problems only",
            file=sys.stderr,
        )
        return 1

    if param.name.startswith("poisson"):
        from .models.poisson import PoissonSolver

        def build():
            comm = _make_comm(param, ndims=2)
            if comm is None:
                return PoissonSolver(param, problem=2)
            from .models.poisson_dist import DistPoissonSolver

            return DistPoissonSolver(param, comm, problem=2)

        solver = _try_build(build)
        if solver is None:
            return 1
        start = get_timestamp()
        with prof.region("solve"):
            it, res = solver.solve()
        end = get_timestamp()
        # parity: solver prints "%d " (no newline), main appends Walltime
        print(f"{it} ", end="")
        with prof.region("writeResult"):
            solver.write_result("p.dat")
        print("Walltime %.2fs" % (end - start))
    elif param.name in ("dcavity", "canal", "canal_obstacle", "dcavity3d",
                        "canal3d"):
        from .utils.params import is_3d_config

        is3d = is_3d_config(param)
        if is3d and param.tpu_vtk not in ("ascii", "binary", "sharded"):
            # validate before the run, not in the writer after hours of solve
            print(
                f"Error: tpu_vtk must be ascii|binary|sharded, "
                f"got {param.tpu_vtk!r}",
                file=sys.stderr,
            )
            return 1

        def build():
            if is3d:
                comm = _make_comm(param, ndims=3)
                if comm is None:
                    from .models.ns3d import NS3DSolver

                    return NS3DSolver(param)
                from .models.ns3d_dist import NS3DDistSolver

                return NS3DDistSolver(param, comm)
            comm = _make_comm(param, ndims=2)
            if comm is None:
                from .models.ns2d import NS2DSolver

                return NS2DSolver(param)
            from .models.ns2d_dist import NS2DDistSolver

            return NS2DDistSolver(param, comm)

        solver = _try_build(build)
        if solver is None:
            return 1
        if is3d:
            from .utils import flags as _flags

            if _flags.verbose():
                # ≙ A6 main.c's VERBOSE-gated printConfig(solver)
                from .utils.params import print_solver_config

                print_solver_config(param, solver.grid, solver.dt_bound)
        from .utils import checkpoint as ckpt

        on_sync = None
        if param.tpu_restart:
            try:
                # either format: legacy .npz or elastic manifest (sniffed)
                ckpt.load_any(param.tpu_restart, solver)
            except (OSError, ValueError, KeyError) as exc:
                # config-class error: same one-line convention as _try_build
                print(f"Error: cannot restart from {param.tpu_restart}: {exc}",
                      file=sys.stderr)
                return 1
            print(f"Restarted from {param.tpu_restart} at t={solver.t:.4f}")
        if param.tpu_checkpoint:
            from .parallel.coordinator import coord_armed

            # an armed coordinator owns the checkpoint cadence itself
            # (the agreed ckpt vote at chunk boundaries — models/_driver.
            # coord_ckpt_cadence); wiring the counter-based writer too
            # would double-write every cadence point
            if not coord_armed(param):
                on_sync = ckpt.periodic_writer(
                    param.tpu_checkpoint, param.tpu_ckpt_every,
                    save=ckpt.writer_for(param),
                )
        start = get_timestamp()
        from .parallel.coordinator import RankDeadError

        try:
            with prof.region("timeloop"):
                solver.run(on_sync=on_sync)
        except RankDeadError as exc:
            # a peer stopped answering the boundary allgather: the
            # watchdog + membership round turned the wedge into this
            # structured, fleet-symmetric verdict. Shrink to the
            # survivors when the run armed the elastic resume path.
            solver = _resume_after_death(param, exc, is3d)
            if solver is None:
                return 3
        end = get_timestamp()
        print("Solution took %.2fs" % (end - start))
        if param.tpu_checkpoint:
            ckpt.writer_for(param)(param.tpu_checkpoint, solver)
        with prof.region("writeResult"):
            if is3d:
                if param.tpu_vtk == "sharded":
                    if hasattr(solver, "write_result_sharded"):
                        solver.write_result_sharded()
                    else:  # single device: binary writer = same bytes
                        solver.write_result(fmt="binary")
                else:
                    solver.write_result(fmt=param.tpu_vtk)
            else:
                solver.write_result("pressure.dat", "velocity.dat")
    else:
        print(f"Unknown problem name: {param.name}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
