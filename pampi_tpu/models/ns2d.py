"""NS-2D incompressible Navier-Stokes time-stepper (lid-driven cavity, canal).

Capability parity with /root/reference/assignment-5/sequential — the full
pipeline of SURVEY.md §3.5: computeTimestep → setBoundaryConditions →
setSpecialBoundaryCondition → computeFG → computeRHS → (nt%100==0)
normalizePressure → solve → adaptUV, advancing t += dt while t <= te
(main.c:43-60).

TPU-first design:
- One timestep is a single traced function; the pressure solve inside it is
  the same red-black `lax.while_loop` used by the Poisson model (equivalence
  policy documented there — the reference's lexicographic SOR trajectory is
  matched at the converged-residual level, not sweep-by-sweep).
- The time loop itself runs ON DEVICE in chunks of `chunk` steps (a
  `lax.while_loop` whose cond is `t <= te && k < chunk`), so the host syncs
  once per chunk — not once per step — and XLA overlaps everything else.
  Progress is reported at chunk granularity (progress.c parity).
- tau > 0 (adaptive CFL) vs constant-dt is a trace-time branch, like the
  reference's `if (tau > 0)` (main.c:44).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import ns2d as ops
from ..utils import faultinject as _fi
from ..utils import flags as _flags
from ..utils import telemetry as _tm
from ._driver import clamped_dt
from ..utils.datio import write_pressure, write_velocity
from ..utils.params import Parameter, validate_obstacle_layout
from ..utils.precision import resolve_dtype
from ..utils.progress import Progress


def make_pressure_solve(imax, jmax, dx, dy, omega, eps, itermax, dtype,
                        backend: str = "auto", n_inner: int = 1,
                        solver: str = "sor", layout: str = "auto",
                        stall_rtol=None, flat: bool = False,
                        mg_fused: str = "off"):
    """Pressure-Poisson solve loop (solve, solver.c:140-191): carry
    (p, res, it); res = Σr²/(imax·jmax) vs eps²; Neumann ghost copy per sweep.

    Layout: `layout` goes straight to make_rb_loop's standard dispatch
    (auto -> quarters when eligible, checkerboard otherwise). Measured
    (v5e, 4096² dcavity, itermax=100, chained-step differencing, round 3):
    quarters 22.2-22.5 ms/step vs checkerboard 36.9-39.6 — quarters wins
    1.7× at the step level too. Round 2 had measured quarters LOSING (68 vs
    39 ms/step) and pinned NS-2D auto to checkerboard; that loss predated
    the staged single-transpose packing — the pack+unpad roundtrip now
    measures 0.94 ms at 4096².

    solver="sor" (default, the reference's algorithm): identical semantics to
    the Poisson convergence loop, so it IS that loop — `make_solver_fn`
    dispatches to the fused Pallas kernel on TPU (f32/bf16), converting to
    the padded layout once per pressure solve, not per sweep.
    solver="mg": geometric multigrid V-cycles (ops/multigrid.py), same
    stopping contract, `it` counts cycles.
    solver="fft": direct DCT-diagonalization solve (ops/dctpoisson.py) —
    exact in one application, `it` reports 1."""
    if solver == "mg":
        from ..ops.multigrid import make_mg_solve_2d

        return make_mg_solve_2d(imax, jmax, dx, dy, eps, itermax, dtype,
                                stall_rtol=stall_rtol, backend=backend,
                                fused=mg_fused)
    if solver == "fft":
        from ..ops.dctpoisson import make_dct_solve_2d

        return make_dct_solve_2d(imax, jmax, dx, dy, dtype)
    if solver == "sor_lex":
        # the reference's LEXICOGRAPHIC solve (assignment-5/sequential/src/
        # solver.c:159-176) as an oracle mode: on itermax-capped configs the
        # capped trajectory depends on the sweep ORDERING, so C-vs-framework
        # field comparisons at fixed step count need this path, not rb
        # (tools/northstar.py match4096). Always the jnp scan program
        # (ops/sor.lex_sweep), f64-capable, never pallas.
        from .poisson import make_solver_fn

        return make_solver_fn(imax, jmax, dx, dy, omega, eps, itermax,
                              dtype, backend="jnp", method="lex")
    if solver != "sor":
        raise ValueError(
            f"NS pressure solve supports sor|sor_lex|mg|fft, got {solver!r} "
            "(sor_rba is a Poisson-only oracle mode)"
        )
    from .poisson import make_solver_fn

    return make_solver_fn(imax, jmax, dx, dy, omega, eps, itermax, dtype,
                          backend=backend, n_inner=n_inner,
                          layout=layout, flat=flat)


class NS2DSolver:
    """Driver-facing NS-2D solver (≙ the Solver struct + main loop)."""

    CHUNK = 64  # device steps per host sync

    def __init__(self, param: Parameter, dtype=None):
        from ..utils.dispatch import resolve_solver

        param = resolve_solver(param, obstacles=bool(param.obstacles.strip()))
        if dtype is None:
            dtype = resolve_dtype(param.tpu_dtype,
                                  record_key="ns2d_dtype")
        self.param = param
        self.dtype = dtype
        self.imax, self.jmax = param.imax, param.jmax
        self.dx = param.xlength / param.imax
        self.dy = param.ylength / param.jmax
        shape = (param.jmax + 2, param.imax + 2)
        self.u = jnp.full(shape, param.u_init, dtype)
        self.v = jnp.full(shape, param.v_init, dtype)
        self.p = jnp.full(shape, param.p_init, dtype)
        inv_sqr_sum = 1.0 / (self.dx * self.dx) + 1.0 / (self.dy * self.dy)
        self.dt_bound = 0.5 * param.re / inv_sqr_sum
        self.t = 0.0
        self.nt = 0
        self._backend = "auto"
        self._fused = False  # set by _build_chunk (fused-phase dispatch)
        self._dt_scale = 1.0  # recovery dt clamp (models/_driver.clamped_dt)
        # flag-field obstacles (ops/obstacle.py): static geometry -> static
        # masks baked into the traced step as constants (branch-free)
        if param.obstacles.strip():
            if param.tpu_solver in ("fft", "sor_lex"):
                raise ValueError(
                    f"tpu_solver {param.tpu_solver} cannot solve obstacle "
                    "flag fields (fft: non-constant coefficients; sor_lex: "
                    "the lex oracle has no eps-coefficient form); use sor "
                    "or mg"
                )
            validate_obstacle_layout(param.tpu_sor_layout)
            from ..ops import obstacle as obst

            fluid = obst.build_fluid(
                param.imax, param.jmax, self.dx, self.dy, param.obstacles
            )
            self.masks = obst.make_masks(fluid, self.dx, self.dy, param.omg, dtype)
        else:
            self.masks = None
        t0 = time.perf_counter()
        # fault-injection generation for this build (utils/faultinject.py):
        # taken HERE and in _rebuild_chunk only, never inside _build_chunk —
        # the pallas->jnp fallback rebuild must keep the failing chunk's
        # armed corruption instead of silently spending a fresh generation
        self._field_faults = _fi.take_field_faults()
        self._chunk_fn = jax.jit(self._build_chunk())
        from ..utils import dispatch as _dispatch

        _tm.emit("build", family="ns2d", grid=[self.jmax, self.imax],
                 trace_wall_s=round(time.perf_counter() - t0, 3),
                 phases=_dispatch.last("ns2d_phases"))

    def _uses_pallas(self) -> bool:
        """Whether the current chunk contains ANY pallas kernel — the
        pressure solve's (the uniform solver, the flag-masked solver, and
        mg's fine-level smoother all go through the same backend probe;
        jnp-dispatched dtypes/backends never do; fft and the always-jnp
        sor_lex oracle contain no solve kernel) or the fused step-phase
        pair, so the runtime retry protocol (models/_driver.pallas_retry)
        covers the fused chunk too."""
        if self._fused:
            return True
        if self.param.tpu_solver in ("fft", "sor_lex"):
            return False
        from .poisson import _use_pallas

        return _use_pallas(self._backend, self.dtype)

    def _make_solve(self, backend: str):
        """The pressure-solve closure for one backend — shared by the jnp
        step chain and the fused-phase chunk (the fused kernels replace the
        non-solve phases only; the solve dispatch is unchanged)."""
        param = self.param
        dx, dy = self.dx, self.dy
        dtype = self.dtype
        masks = self.masks
        if masks is None:
            solve = make_pressure_solve(
                param.imax,
                param.jmax,
                dx,
                dy,
                param.omg,
                param.eps,
                param.itermax,
                dtype,
                backend=backend,
                n_inner=param.tpu_sor_inner,
                solver=param.tpu_solver,
                layout=param.tpu_sor_layout,
                stall_rtol=param.tpu_mg_stall_rtol,
                flat=bool(param.tpu_flat_solve),
                mg_fused=param.tpu_mg_fused,
            )
        elif param.tpu_solver == "mg":
            # obstacle-capable multigrid: rediscretized eps-coefficient
            # operator per level (ops/multigrid.make_obstacle_mg_solve_2d) —
            # the O(1)-cycles option fft cannot provide here
            from ..ops.multigrid import make_obstacle_mg_solve_2d

            solve = make_obstacle_mg_solve_2d(
                param.imax, param.jmax, dx, dy, param.eps, param.itermax,
                masks, dtype,
                stall_rtol=param.tpu_mg_stall_rtol, backend=backend,
                fused=param.tpu_mg_fused,
            )
        else:
            from ..ops import obstacle as obst

            solve = obst.make_obstacle_solver_fn(
                param.imax, param.jmax, dx, dy, param.eps, param.itermax,
                masks, dtype, backend=backend,
                n_inner=param.tpu_sor_inner,
            )
        return solve

    # -- one full timestep, traced ------------------------------------
    def _build_presolve(self):
        """The pre-solve phase chain (dt → wall BCs → special BC → obstacle
        BC → F/G predictor → obstacle F/G mask → Poisson rhs) as a
        standalone traced function (u, v) -> (u, v, f, g, rhs, dt).
        _build_step composes it with the solve/projection phases; the
        solve/non-solve decomposition tools (bench.py, tools/northstar.py)
        call it to derive a representative rhs for timing the step's own
        solve closure — one wiring, no hand-copies to drift."""
        param = self.param
        dx, dy = self.dx, self.dy
        dtype = self.dtype
        masks = self.masks
        adaptive = param.tau > 0.0
        problem = param.name
        dt_scale = self._dt_scale  # 1.0 = identity (recovery rebuilds clamp)

        def presolve(u, v):
            if adaptive:
                dt = ops.compute_timestep(u, v, self.dt_bound, dx, dy, param.tau)
            else:
                dt = jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            u, v = ops.set_boundary_conditions(
                u, v, param.bcLeft, param.bcRight, param.bcBottom, param.bcTop
            )
            if problem == "dcavity":
                u = ops.set_special_bc_dcavity(u)
            elif problem in ("canal", "canal_obstacle"):
                u = ops.set_special_bc_canal(u, dy, param.ylength, dtype)
            if masks is not None:
                from ..ops.obstacle import (
                    apply_obstacle_velocity_bc,
                    mask_fg,
                )

                u, v = apply_obstacle_velocity_bc(u, v, masks)
            f, g = ops.compute_fg(
                u, v, dt, param.re, param.gx, param.gy, param.gamma, dx, dy
            )
            if masks is not None:
                f, g = mask_fg(f, g, u, v, masks)
            rhs = ops.compute_rhs(f, g, dt, dx, dy)
            return u, v, f, g, rhs, dt

        return presolve

    def time_solve_ms(self, reps: int = 6) -> float:
        """Best-of-`reps` wall time (ms) of the step's OWN solve closure on
        the first step's rhs. The solve/non-solve decomposition tools
        (bench.py, tools/northstar.py) both call this, so BENCH_*.json and
        the northstar artifact always time the identical protocol: rhs via
        _build_presolve, jit once, warm with a scalar readback fence,
        best-of-reps perf_counter."""
        import time

        *_, rhs, _dt = jax.jit(self._build_presolve())(self.u, self.v)
        fold = getattr(self, "_folded_solve", None)
        if fold is not None:
            # the folded chunk runs its solve ENTIRELY in the padded layout
            # (models/poisson.make_padded_solver_fn) — time that program,
            # not the conversion-wrapped _make_solve the step no longer uses
            solve_fn, pad = fold
            solve = jax.jit(solve_fn)
            p_in, rhs_in = pad(self.p), pad(rhs)
        else:
            solve = jax.jit(self._make_solve(self._backend))
            p_in, rhs_in = self.p, rhs
        _p, res, _it = solve(p_in, rhs_in)
        float(res)  # compile + warm-up; scalar readback is the fence
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            _p, res, _it = solve(p_in, rhs_in)
            float(res)
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    def _build_step(self, backend: str = "auto", instrumented: bool = False):
        """One traced timestep (the jnp phase chain — the parity oracle and
        CPU path; _build_fused_chunk is the TPU production composition).
        instrumented=True returns the SAME pipeline with the pressure
        solve's discarded outputs exposed — (u, v, p, t, nt, res, it, dt) —
        so measurement tools (tools/northstar.py, tools/perf_obstacle_mg.py)
        can sample solver iteration counts without hand-copying the step
        wiring (which would silently diverge when this pipeline changes)."""
        param = self.param
        dx, dy = self.dx, self.dy
        dtype = self.dtype
        masks = self.masks
        solve = self._make_solve(backend)
        presolve = self._build_presolve()
        faults = getattr(self, "_field_faults", ())

        def step(u, v, p, t, nt):
            u, v, p = _fi.apply_field_faults(faults, nt, u=u, v=v, p=p)
            u, v, f, g, rhs, dt = presolve(u, v)
            if masks is None:
                p = lax.cond(nt % 100 == 0, ops.normalize_pressure, lambda q: q, p)
            else:
                from ..ops.obstacle import normalize_pressure_fluid

                p = lax.cond(
                    nt % 100 == 0,
                    lambda q: normalize_pressure_fluid(q, masks),
                    lambda q: q,
                    p,
                )
            p, res, it = solve(p, rhs)
            if masks is None:
                u, v = ops.adapt_uv(u, v, f, g, p, dt, dx, dy)
            else:
                from ..ops.obstacle import adapt_uv_obstacle

                u, v = adapt_uv_obstacle(u, v, f, g, p, dt, dx, dy, masks)
            # t accumulates in high precision regardless of the field dtype
            # (bfloat16 would stall t once ulp/2 > dt and never reach te)
            time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            t_next = t + dt.astype(time_dtype)
            if _flags.verbose():
                # ≙ -DVERBOSE "TIME %f , TIMESTEP %f" printed AFTER t += dt
                # (A5 main.c:52-57)
                jax.debug.print("TIME {} , TIMESTEP {}", t_next, dt)
            if instrumented:
                return u, v, p, t_next, nt + 1, res, it, dt
            return u, v, p, t_next, nt + 1

        return step

    def _build_fused_chunk(self, backend: str, metrics: bool = False,
                           te_arg: bool = False, kfuse: int = 1):
        """The fused-phase chunk: the non-solve step phases run as the two
        Pallas kernels of ops/ns2d_fused.py (BCs+FG+RHS before the solve,
        adaptUV+CFL-max after), the loop carries u/v in the kernels' padded
        layout plus the running (umax, vmax) scalars, and the timestep is
        pure scalar math (ops/ns2d.cfl_dt). Returns None when the fused
        path is not dispatched (knob off, jnp backend, no TPU, probe/VMEM
        failure) — the caller falls back to the jnp chunk.

        metrics=True (PAMPI_TELEMETRY set at build time) additionally
        threads the in-band telemetry vector through the chunk: the solve's
        res/it and dt join the already-carried CFL maxima as f32 scalars,
        plus the non-finite sentinel (utils/telemetry.sentinel_update) —
        read out only at the chunk boundary where the host already syncs.
        metrics=False takes the exact pre-telemetry trace (jaxpr identity,
        tests/test_telemetry.py)."""
        from ..ops.ns2d_fused import probe_fused_2d
        from ..utils.dispatch import record, resolve_fuse_phases

        # reset BEFORE any early return: the pallas-retry rebuild
        # (backend="jnp") exits at the gate below and must not leave a
        # stale folded solve for time_solve_ms to time
        self._folded_solve = None
        param = self.param
        if not resolve_fuse_phases(
            param, backend, self.dtype, probe_fused_2d, "ns2d_phases",
        ):
            return None
        from ..ops import ns2d_fused as nf

        dx, dy = self.dx, self.dy
        dtype = self.dtype
        masks = self.masks

        # p-layout fold (the ROADMAP post-fusion knob): when the pressure
        # solve resolves to the checkerboard tblock kernel, run it DIRECTLY
        # on the fused kernels' padded layout — p and rhs stay padded across
        # the whole chunk and the per-step layout passes around the solve
        # (unpad rhs, re-pad rhs, pad/unpad p) vanish. The quarters layout
        # keeps explicit conversions (its stacked data layout cannot be
        # shared with the phase kernels; it remains the measured-best solve
        # at 4096², so auto-even grids are untouched).
        solve_pad = br_fold = None

        def ckb_solve_home():
            if param.tpu_sor_layout == "checkerboard":
                return True
            if param.tpu_sor_layout == "quarters":
                return False
            # auto: ask the solver's OWN layout resolution (including its
            # quarters-VMEM-infeasible fallback to checkerboard) instead of
            # re-deriving the policy here; called lazily, only when the
            # other fold preconditions already hold (the probe builds a
            # throwaway quarters kernel)
            from .poisson import _try_quarters

            return _try_quarters(
                param.imax, param.jmax, dx, dy, param.omg, dtype,
                param.tpu_sor_inner, "auto",
            ) is None

        from .poisson import _use_pallas

        if (masks is None and param.tpu_solver == "sor"
                and (param.tpu_fuse_phases == "on"
                     or _use_pallas(backend, dtype))
                and ckb_solve_home()):
            from .poisson import make_padded_solver_fn

            try:
                solve_pad, br_fold, h_fold = make_padded_solver_fn(
                    param.imax, param.jmax, dx, dy, param.omg, param.eps,
                    param.itermax, dtype, n_inner=param.tpu_sor_inner,
                    flat=bool(param.tpu_flat_solve),
                )
                if (br_fold, h_fold) != nf.fused_layout_2d(
                        param.jmax, param.imax, dtype, block_rows=br_fold):
                    solve_pad = br_fold = None  # halo mismatch: no shared layout
            except ValueError:  # tblock unavailable/VMEM-infeasible
                solve_pad = br_fold = None

        def build_step(block_rows):
            return nf.make_fused_step_2d(
                param, param.jmax, param.imax, dx, dy, dtype,
                fluid=None if masks is None else masks.fluid,
                block_rows=block_rows,
            )

        try:
            pre, post, pad, unpad, _h = build_step(br_fold)
        except ValueError as exc:  # VMEM-infeasible geometry
            if br_fold is None:
                record("ns2d_phases", f"jnp ({exc})")
                return None
            # the solve's block_rows didn't fit the phase kernels' larger
            # VMEM budget: give up the fold, keep the fusion (PR 1 default
            # geometry) rather than dropping the whole step to the jnp chain
            solve_pad = br_fold = None
            try:
                pre, post, pad, unpad, _h = build_step(None)
            except ValueError as exc2:
                record("ns2d_phases", f"jnp ({exc2})")
                return None
        # recorded only now: the fold is live only if the phase kernels
        # themselves built (a VMEM failure above falls back to the jnp
        # chain, where no padded layout exists at all)
        record("ns2d_p_layout",
               "folded (solve shares the fused padded layout)"
               if solve_pad is not None else "explicit pad/unpad")
        solve = self._make_solve(backend) if solve_pad is None else solve_pad
        if solve_pad is not None:
            # time_solve_ms must time THIS padded-layout solve, not the
            # conversion-wrapped _make_solve the folded step no longer runs
            self._folded_solve = (solve_pad, pad)
        adaptive = param.tau > 0.0
        dt_scale = self._dt_scale  # 1.0 = identity (recovery rebuilds clamp)
        faults = getattr(self, "_field_faults", ())
        te_static = param.te
        chunk = param.tpu_chunk or self.CHUNK
        offs = jnp.zeros((2,), jnp.int32)
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        if masks is not None:
            from ..ops.obstacle import normalize_pressure_fluid

            def normalize(q):
                return normalize_pressure_fluid(q, masks)
        else:
            normalize = ops.normalize_pressure

        folded = solve_pad is not None
        if folded:
            # normalize on the padded carry: the conversion pair runs only
            # inside the every-100-steps cond branch
            def norm_carry(q):
                return pad(normalize(unpad(q)))
        else:
            norm_carry = normalize

        def step(up, vp, p, t, nt, umax, vmax):
            # `p` is the padded carry when folded, the plain array otherwise
            up, vp, p = _fi.apply_field_faults(faults, nt, u=up, v=vp, p=p)
            if adaptive:
                dt = ops.cfl_dt(umax, vmax, self.dt_bound, dx, dy, param.tau)
            else:
                dt = jnp.asarray(param.dt, dtype)
            dt = clamped_dt(dt, dt_scale)
            dt11 = jnp.full((1, 1), dt, dtype)
            up, vp, fp, gp, rhsp = pre(offs, dt11, up, vp)
            p = lax.cond(nt % 100 == 0, norm_carry, lambda q: q, p)
            if folded:
                p, _res, _it = solve(p, rhsp)
                p_post = p
            else:
                p, _res, _it = solve(p, unpad(rhsp))
                p_post = pad(p)
            up, vp, umax, vmax = post(offs, dt11, up, vp, fp, gp, p_post)
            t_next = t + dt.astype(time_dtype)
            if _flags.verbose():
                jax.debug.print("TIME {} , TIMESTEP {}", t_next, dt)
            if metrics:
                return (up, vp, p, t_next, nt + 1, umax, vmax,
                        _res, _it, dt)
            return up, vp, p, t_next, nt + 1, umax, vmax

        def chunk_fn(u, v, p, t, nt, *te_in):
            # te_arg builds take the end time as a TRACED trailing arg
            # (the fleet's per-lane te carry); the default closes over
            # the baked constant — the byte-identical historical trace
            te = te_in[0] if te_in else te_static
            up, vp = pad(u), pad(v)
            if folded:
                p = pad(p)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))

            def cond(c):
                return jnp.logical_and(c[3] <= te, c[7] < chunk)

            if kfuse > 1:
                # K-step fused trips (ISSUE 17): one scan advances K
                # gated steps — past te the frozen branch is an identity
                # on the carry, so nt/t stay exact at the boundary
                def kblock(c, _):
                    def live(c):
                        return step(*c)

                    return lax.cond(c[3] <= te, live, lambda c: c, c), None

                def body(c):
                    up, vp, p, t, nt, umax, vmax, k = c
                    (up, vp, p, t, nt, umax, vmax), _ = lax.scan(
                        kblock, (up, vp, p, t, nt, umax, vmax), None,
                        length=kfuse)
                    return up, vp, p, t, nt, umax, vmax, k + kfuse
            else:
                def body(c):
                    up, vp, p, t, nt, umax, vmax, k = c
                    up, vp, p, t, nt, umax, vmax = step(
                        up, vp, p, t, nt, umax, vmax
                    )
                    return up, vp, p, t, nt, umax, vmax, k + 1

            up, vp, p, t, nt, _um, _vm, _k = lax.while_loop(
                cond, body,
                (up, vp, p, t, nt, umax, vmax, jnp.asarray(0, jnp.int32)),
            )
            return unpad(up), unpad(vp), unpad(p) if folded else p, t, nt

        def chunk_fn_metrics(u, v, p, t, nt, m, *te_in):
            # the telemetry twin: same loop, the f32 metrics scalars ride
            # the carry and pack into the in-band vector at the boundary
            te = te_in[0] if te_in else te_static
            up, vp = pad(u), pad(v)
            if folded:
                p = pad(p)
            umax = jnp.max(jnp.abs(u))
            vmax = jnp.max(jnp.abs(v))

            def cond(c):
                return jnp.logical_and(c[3] <= te, c[7] < chunk)

            if kfuse > 1:
                # metrics_step runs PER STEP inside the live branch (the
                # POST-step nt, exactly the historical placement), so the
                # divergence sentinel keeps step resolution across the
                # K-block
                def kblock(c, _):
                    def live(c):
                        up, vp, p, t, nt, umax, vmax, res, it, dtv, bad = c
                        (up, vp, p, t, nt, umax, vmax,
                         res, it, dtv) = step(up, vp, p, t, nt, umax, vmax)
                        res, it, dtv, _um, _vm, bad = _tm.metrics_step(
                            bad, nt, res, it, dtv, umax, vmax)
                        return (up, vp, p, t, nt, umax, vmax,
                                res, it, dtv, bad)

                    return lax.cond(c[3] <= te, live, lambda c: c, c), None

                def body(c):
                    up, vp, p, t, nt, umax, vmax, k, res, it, dtv, bad = c
                    (up, vp, p, t, nt, umax, vmax,
                     res, it, dtv, bad), _ = lax.scan(
                        kblock,
                        (up, vp, p, t, nt, umax, vmax, res, it, dtv, bad),
                        None, length=kfuse)
                    return (up, vp, p, t, nt, umax, vmax, k + kfuse,
                            res, it, dtv, bad)
            else:
                def body(c):
                    up, vp, p, t, nt, umax, vmax, k, res, it, dtv, bad = c
                    up, vp, p, t, nt, umax, vmax, res, it, dtv = step(
                        up, vp, p, t, nt, umax, vmax
                    )
                    # maxima stay native-dtype in the carry (the CFL
                    # scalars); metrics_step's f32 copies feed only the
                    # sentinel
                    res, it, dtv, _um, _vm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv, umax, vmax)
                    return (up, vp, p, t, nt, umax, vmax, k + 1,
                            res, it, dtv, bad)

            (up, vp, p, t, nt, umax, vmax, _k,
             res, it, dtv, bad) = lax.while_loop(
                cond, body,
                (up, vp, p, t, nt, umax, vmax, jnp.asarray(0, jnp.int32),
                 m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT], m[_tm.M_BAD]),
            )
            m = _tm.metrics_pack(res, it, dtv, umax, vmax, 0.0, bad)
            return (unpad(up), unpad(vp), unpad(p) if folded else p,
                    t, nt, m)

        return chunk_fn_metrics if metrics else chunk_fn

    def _build_chunk(self, backend: str = "auto", te_arg: bool = False):
        # telemetry is a trace-time decision, like utils/flags.py: unset
        # means the chunk below is byte-identical to the uninstrumented
        # program (asserted by tests/test_telemetry.py). Field-fault
        # injection (PAMPI_FAULTS nan/inf clauses) follows the same
        # contract via self._field_faults — set by __init__/_rebuild_chunk,
        # NOT taken here (the pallas fallback rebuild reuses the armed
        # generation; only a recovery rebuild advances it).
        # te_arg=True (the fleet's per-lane te carry) makes the end time a
        # TRACED trailing argument of the chunk instead of a baked
        # constant; the default is the byte-identical historical trace.
        metrics = _tm.enabled()
        self._metrics = metrics
        from ..utils.dispatch import resolve_chunk_fuse

        chunk = self.param.tpu_chunk or self.CHUNK
        kfuse = resolve_chunk_fuse(self.param, "ns2d_chunk_fuse", chunk)
        fused = self._build_fused_chunk(backend, metrics=metrics,
                                        te_arg=te_arg, kfuse=kfuse)
        self._fused = fused is not None
        if fused is not None:
            return fused
        step = self._build_step(backend, instrumented=metrics)
        te_static = self.param.te

        def chunk_fn(u, v, p, t, nt, *te_in):
            te = te_in[0] if te_in else te_static

            def cond(c):
                _, _, _, t, _, k = c
                return jnp.logical_and(t <= te, k < chunk)

            if kfuse > 1:
                # K-step fused trips (ISSUE 17): one scan advances K
                # gated steps (frozen identity past te) per while trip
                def kblock(c, _):
                    def live(c):
                        return step(*c)

                    return lax.cond(c[3] <= te, live, lambda c: c, c), None

                def body(c):
                    u, v, p, t, nt, k = c
                    (u, v, p, t, nt), _ = lax.scan(
                        kblock, (u, v, p, t, nt), None, length=kfuse)
                    return u, v, p, t, nt, k + kfuse
            else:
                def body(c):
                    u, v, p, t, nt, k = c
                    u, v, p, t, nt = step(u, v, p, t, nt)
                    return u, v, p, t, nt, k + 1

            u, v, p, t, nt, _ = lax.while_loop(
                cond, body, (u, v, p, t, nt, jnp.asarray(0, jnp.int32))
            )
            return u, v, p, t, nt

        def chunk_fn_metrics(u, v, p, t, nt, m, *te_in):
            # the telemetry twin of chunk_fn: the instrumented step exposes
            # the solve's discarded res/it plus dt; |u|/|v| maxima are the
            # two extra fused reductions this path did not already carry
            te = te_in[0] if te_in else te_static

            def cond(c):
                return jnp.logical_and(c[3] <= te, c[5] < chunk)

            if kfuse > 1:
                # per-step metrics_step with the POST-step nt inside the
                # live branch — divergence keeps step resolution in the
                # K-block
                def kblock(c, _):
                    def live(c):
                        u, v, p, t, nt, res, it, dtv, um, vm, bad = c
                        u, v, p, t, nt, res, it, dtv = step(u, v, p, t, nt)
                        res, it, dtv, um, vm, bad = _tm.metrics_step(
                            bad, nt, res, it, dtv,
                            ops.max_element(u), ops.max_element(v))
                        return u, v, p, t, nt, res, it, dtv, um, vm, bad

                    return lax.cond(c[3] <= te, live, lambda c: c, c), None

                def body(c):
                    u, v, p, t, nt, k, res, it, dtv, um, vm, bad = c
                    (u, v, p, t, nt, res, it, dtv, um, vm, bad), _ = \
                        lax.scan(
                            kblock,
                            (u, v, p, t, nt, res, it, dtv, um, vm, bad),
                            None, length=kfuse)
                    return (u, v, p, t, nt, k + kfuse,
                            res, it, dtv, um, vm, bad)
            else:
                def body(c):
                    u, v, p, t, nt, k, res, it, dtv, um, vm, bad = c
                    u, v, p, t, nt, res, it, dtv = step(u, v, p, t, nt)
                    res, it, dtv, um, vm, bad = _tm.metrics_step(
                        bad, nt, res, it, dtv,
                        ops.max_element(u), ops.max_element(v))
                    return u, v, p, t, nt, k + 1, res, it, dtv, um, vm, bad

            (u, v, p, t, nt, _k, res, it, dtv, um, vm, bad) = lax.while_loop(
                cond, body,
                (u, v, p, t, nt, jnp.asarray(0, jnp.int32),
                 m[_tm.M_RES], m[_tm.M_IT], m[_tm.M_DT],
                 m[_tm.M_UMAX], m[_tm.M_VMAX], m[_tm.M_BAD]),
            )
            return u, v, p, t, nt, _tm.metrics_pack(
                res, it, dtv, um, vm, 0.0, bad)

        return chunk_fn_metrics if metrics else chunk_fn

    # -- driver API ----------------------------------------------------
    def _rebuild_chunk(self):
        """Re-trace the chunk against the solver's CURRENT attributes
        (backend, recovery dt clamp) — the rollback-recovery rebuild hook
        (models/_driver.RingRecovery). Advances the fault-injection
        generation: single-charge corruption clauses are spent, so the
        recovered run re-drives clean."""
        self._field_faults = _fi.take_field_faults()
        self._chunk_fn = jax.jit(self._build_chunk(backend=self._backend))
        return self._chunk_fn

    def initial_state(self) -> tuple:
        """The chunk-call state tuple matching the built chunk's arity —
        (u, v, p, t, nt), plus the in-band telemetry metrics vector when
        PAMPI_TELEMETRY was set at build time. The measurement tools
        (bench.py, tools/northstar.py) call the chunk with this instead of
        hand-building the tuple, so the telemetry arity cannot drift."""
        time_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        state = (self.u, self.v, self.p,
                 jnp.asarray(self.t, time_dtype),
                 jnp.asarray(self.nt, jnp.int32))
        if getattr(self, "_metrics", False):
            state = state + (_tm.metrics_init(),)
        return state

    # -- elastic-checkpoint contract (utils/checkpoint.save_elastic) ---
    def global_shape(self) -> tuple:
        return (self.jmax + 2, self.imax + 2)

    def global_fields(self) -> dict:
        """Reference-layout global fields: single-device fields ARE the
        global layout (interior + ghost ring)."""
        return {f: np.asarray(getattr(self, f)) for f in ("u", "v", "p")}

    def set_global_fields(self, fields: dict) -> None:
        for f, arr in fields.items():
            cur = getattr(self, f)
            setattr(self, f, jnp.asarray(arr, cur.dtype))

    def run(self, progress: bool = True, on_sync=None) -> None:
        """Advance from t to te. `on_sync(self)` fires at each host sync
        (every CHUNK device steps) — the checkpoint hook point. Loop +
        retry/rollback protocol live in models/_driver.py."""
        from ._driver import (
            coord_ckpt_cadence,
            drive_chunks,
            make_recovery,
            pallas_retry,
        )

        bar = Progress(self.param.te, enabled=progress and not _flags.verbose())
        state = self.initial_state()
        rec = _tm.ChunkRecorder("ns2d", self.nt) if self._metrics else None
        recover = make_recovery(self, "ns2d", time_index=3, recorder=rec)

        def publish(s):
            self.u, self.v, self.p = s[0], s[1], s[2]
            self.t, self.nt = float(s[3]), int(s[4])

        def on_state(s):
            if rec is not None:
                rec.update(float(s[3]), int(s[4]), s[5])
            if recover is not None:
                recover.capture(s)
            if on_sync is not None:
                publish(s)
                on_sync(self)

        if recover is not None:
            recover.capture(state)  # first-chunk divergence is recoverable
        from ..parallel.coordinator import make_coordinator
        from ..utils import xprof as _xprof

        # single-device default is the uncoordinated historical loop;
        # tpu_coord on forces the 1-rank protocol path (seam identity)
        coord = make_coordinator(self.param, "ns2d")
        ckpt_every, on_ckpt = coord_ckpt_cadence(self, coord, publish)
        nt0 = self.nt
        with _xprof.capture("ns2d", steps=lambda: self.nt - nt0):
            state = drive_chunks(
                state, self._chunk_fn, self.param.te, 3, bar,
                pallas_retry(
                    self, "pressure solve",
                    restore_after=self.param.tpu_retry_replenish,
                ),
                on_state, lookahead=self.param.tpu_lookahead,
                replenish_after=self.param.tpu_retry_replenish,
                recover=recover, coordinator=coord,
                ckpt_every=ckpt_every, on_ckpt=on_ckpt, family="ns2d",
                ledger=getattr(self, "_fault_ledger", None))
            publish(state)

    def write_result(
        self, pressure_path: str = "pressure.dat", velocity_path: str = "velocity.dat"
    ) -> None:
        write_pressure(np.asarray(self.p), self.dx, self.dy, pressure_path)
        write_velocity(
            np.asarray(self.u), np.asarray(self.v), self.dx, self.dy, velocity_path
        )
